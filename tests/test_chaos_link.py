"""chaos.link: the shared link-shaping layer, in isolation.

Determinism is the load-bearing property: the campaign's replay
guarantee rests on a (seed, schedule) pair producing identical shaping
decisions — so these tests pin the per-edge RNG streams, every fault
class (loss/dup/reorder/bandwidth/partitions), the accounting counters,
and the transport's legacy ``link_delays`` knob now riding the same
hook.
"""

import pytest

from hbbft_tpu.chaos.link import (
    LinkShaper,
    NetShape,
    PRESETS,
    ShapedLink,
    preset_shape,
)


def _decisions(shaper, n=200, edge=("a", "b"), now=0.0, nbytes=100):
    return [shaper.shape_frame(edge[0], edge[1], now, nbytes=nbytes)
            for _ in range(n)]


def test_same_seed_same_decisions_different_seed_differs():
    shape = NetShape(default=ShapedLink(delay_s=0.01, jitter_s=0.02,
                                        loss=0.1, dup=0.1))
    a = _decisions(LinkShaper(shape, seed=7))
    b = _decisions(LinkShaper(shape, seed=7))
    c = _decisions(LinkShaper(shape, seed=8))
    assert a == b
    assert a != c


def test_edges_draw_independent_streams():
    """One edge's draw count must not perturb another's (each edge owns
    a derived RNG, like the transport's backoff streams)."""
    shape = NetShape(default=ShapedLink(jitter_s=0.5))
    s1 = LinkShaper(shape, seed=3)
    s2 = LinkShaper(shape, seed=3)
    # interleave a foreign edge's draws on s1 only
    seq1 = []
    for i in range(50):
        seq1.append(s1.shape_frame(0, 1, 0.0))
        s1.shape_frame(2, 3, 0.0)
    seq2 = [s2.shape_frame(0, 1, 0.0) for _ in range(50)]
    assert seq1 == seq2


def test_unshaped_edge_returns_none_and_counts_nothing():
    shaper = LinkShaper(NetShape(edges={(0, 1): ShapedLink()}))
    assert shaper.shape_frame(1, 0, 0.0) is None
    assert shaper.stats()["shaped"] == 0
    assert shaper.shape_frame(0, 1, 0.0) == [0.0]
    assert shaper.stats()["shaped"] == 1


def test_loss_drops_and_counts():
    shaper = LinkShaper(NetShape(default=ShapedLink(loss=1.0)), seed=1)
    assert shaper.shape_frame(0, 1, 0.0) == []
    assert shaper.stats()["dropped"] == 1


def test_duplication_emits_extra_copies():
    shaper = LinkShaper(NetShape(default=ShapedLink(dup=1.0,
                                                    delay_s=0.01)),
                        seed=1)
    delays = shaper.shape_frame(0, 1, 0.0)
    assert len(delays) == 2
    assert shaper.stats()["duplicated"] == 1
    # copies are not byte-simultaneous
    assert delays[0] != delays[1]


def test_bandwidth_cap_serializes_per_edge():
    # 8000 bps → a 100-byte frame takes 0.1 s on the wire; back-to-back
    # frames queue behind each other, and the queue drains with time
    link = ShapedLink(bandwidth_bps=8000.0)
    assert link.needs_size
    shaper = LinkShaper(NetShape(default=link))
    d1 = shaper.shape_frame(0, 1, 0.0, nbytes=100)
    d2 = shaper.shape_frame(0, 1, 0.0, nbytes=100)
    assert d1 == [pytest.approx(0.1)]
    assert d2 == [pytest.approx(0.2)]
    # another edge has its own queue
    assert shaper.shape_frame(0, 2, 0.0, nbytes=100) == [
        pytest.approx(0.1)]
    # after the queue clears, delay resets
    assert shaper.shape_frame(0, 1, 10.0, nbytes=100) == [
        pytest.approx(0.1)]


def test_backlog_s_reads_the_bandwidth_queue():
    """backlog_s is the congestion signal the transport's VID shed path
    reads: seconds of bulk already committed to the edge, draining with
    time, zero for unshaped/idle edges."""
    shaper = LinkShaper(NetShape(default=ShapedLink(bandwidth_bps=8000.0)))
    assert shaper.backlog_s(0, 1, 0.0) == 0.0  # untouched edge
    shaper.shape_frame(0, 1, 0.0, nbytes=100)  # 0.1 s on the wire
    shaper.shape_frame(0, 1, 0.0, nbytes=100)
    assert shaper.backlog_s(0, 1, 0.0) == pytest.approx(0.2)
    assert shaper.backlog_s(0, 1, 0.15) == pytest.approx(0.05)  # drains
    assert shaper.backlog_s(0, 1, 5.0) == 0.0   # fully drained
    assert shaper.backlog_s(1, 0, 0.0) == 0.0   # other direction idle


def test_partition_hold_delivers_at_heal_and_counts():
    link = ShapedLink(partitions=((1.0, 3.0),))
    shaper = LinkShaper(NetShape(default=link))
    assert shaper.shape_frame(0, 1, 0.5) == [0.0]      # before window
    held = shaper.shape_frame(0, 1, 1.5)               # inside window
    assert held == [pytest.approx(1.5)]                # due at the heal
    assert shaper.shape_frame(0, 1, 3.0) == [0.0]      # healed
    assert shaper.stats()["partition_holds"] == 1
    assert shaper.stats()["dropped"] == 0


def test_partition_drop_mode_loses_frames():
    link = ShapedLink(partitions=((1.0, 3.0),), partition_mode="drop")
    shaper = LinkShaper(NetShape(default=link))
    assert shaper.shape_frame(0, 1, 2.0) == []
    assert shaper.stats()["dropped"] == 1


def test_scaled_rescales_every_time_constant():
    link = ShapedLink(delay_s=1.0, jitter_s=2.0, reorder_spread_s=4.0,
                      bandwidth_bps=8000.0, partitions=((10.0, 20.0),))
    s = link.scaled(0.001)
    assert s.delay_s == pytest.approx(0.001)
    assert s.jitter_s == pytest.approx(0.002)
    assert s.reorder_spread_s == pytest.approx(0.004)
    assert s.partitions == ((pytest.approx(0.01), pytest.approx(0.02)),)
    # a frame's transmission time scales with the clock: 8·n/bps' = k·8·n/bps
    assert 8.0 * 100 / s.bandwidth_bps == pytest.approx(
        0.001 * 8.0 * 100 / link.bandwidth_bps)
    # probabilities are NOT time constants
    lossy = ShapedLink(loss=0.25, dup=0.5).scaled(0.001)
    assert lossy.loss == 0.25 and lossy.dup == 0.5


def test_presets_cover_every_name_and_reject_unknown():
    for name in PRESETS:
        shape = preset_shape(name, 4)
        if name != "none":
            assert (shape.default is not None or shape.edges), name
    with pytest.raises(ValueError, match="unknown chaos preset"):
        preset_shape("nope", 4)
    # the partition preset isolates node n-1 in BOTH directions
    shape = preset_shape("partition-10s", 4)
    assert shape.policy_for(3, 0).partitions
    assert shape.policy_for(0, 3).partitions
    assert not shape.policy_for(0, 1).partitions


def test_transport_link_delays_ride_the_shared_hook():
    """The legacy per-peer constant-delay knob is now sugar for a
    constant-delay ShapedLink on this node's egress edges."""
    from hbbft_tpu.net.transport import Transport

    t = Transport(0, b"cid", link_delays={1: 0.02, 2: 0.05})
    assert t.shaper is not None
    assert t.shaper.policy_for(0, 1).delay_s == pytest.approx(0.02)
    assert t.shaper.policy_for(0, 2).delay_s == pytest.approx(0.05)
    assert t.shaper.policy_for(0, 3) is None
    # shaping counters live on the node's registry (hbbft_chaos_*)
    text = t.stats.registry.render_prometheus()
    assert "hbbft_chaos_frames_dropped_total" in text
    # both knobs at once is a config conflict, refused loudly (before
    # the shared hook, link_delays always applied — never drop one)
    with pytest.raises(ValueError, match="mutually exclusive"):
        Transport(0, b"cid", link_delays={1: 0.02},
                  shaper=LinkShaper(NetShape()))

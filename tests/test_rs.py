"""Reed–Solomon erasure coding tests (host oracle + device path parity)."""

import numpy as np
import pytest

from hbbft_tpu.ops import rs


def test_systematic():
    coder = rs.ReedSolomon(4, 2)
    data = np.arange(4 * 10, dtype=np.uint8).reshape(4, 10)
    shards = coder.encode_np(data)
    assert shards.shape == (6, 10)
    assert np.array_equal(shards[:4], data)
    assert coder.verify_np(shards)


def test_verify_detects_corruption():
    coder = rs.ReedSolomon(4, 2)
    data = np.random.RandomState(0).randint(0, 256, (4, 8)).astype(np.uint8)
    shards = coder.encode_np(data)
    shards[5, 3] ^= 1
    assert not coder.verify_np(shards)


@pytest.mark.parametrize("data_n,parity_n", [(2, 2), (4, 2), (6, 8), (22, 42)])
def test_reconstruct_any_erasures(data_n, parity_n):
    rng = np.random.RandomState(data_n * 100 + parity_n)
    coder = rs.ReedSolomon(data_n, parity_n)
    data = rng.randint(0, 256, (data_n, 17)).astype(np.uint8)
    shards = coder.encode_np(data)
    full = [bytes(s) for s in shards]
    for _ in range(5):
        lost = rng.choice(coder.total_shards, parity_n, replace=False)
        holed = [None if i in lost else full[i] for i in range(coder.total_shards)]
        rec = coder.reconstruct_np(holed)
        assert rec == full


def test_reconstruct_too_few_raises():
    coder = rs.ReedSolomon(4, 2)
    data = np.zeros((4, 4), dtype=np.uint8)
    shards = [bytes(s) for s in coder.encode_np(data)]
    holed = [None, None, None] + shards[3:]
    with pytest.raises(ValueError):
        coder.reconstruct_np(holed)


def test_trivial_coding():
    coder = rs.ReedSolomon(4, 0)
    data = np.arange(16, dtype=np.uint8).reshape(4, 4)
    assert np.array_equal(coder.encode_np(data), data)


def test_encode_jax_matches_host():
    import jax
    import jax.numpy as jnp

    coder = rs.ReedSolomon(5, 4)
    rng = np.random.RandomState(7)
    # batched over two leading axes (instance × node)
    data = rng.randint(0, 256, (3, 2, 5, 24)).astype(np.uint8)
    out = jax.jit(coder.encode_jax)(jnp.asarray(data))
    assert out.shape == (3, 2, 9, 24)
    for i in range(3):
        for j in range(2):
            assert np.array_equal(np.asarray(out[i, j]), coder.encode_np(data[i, j]))


def test_reconstruct_jax_matches_host():
    import jax.numpy as jnp

    coder = rs.ReedSolomon(4, 3)
    rng = np.random.RandomState(8)
    data = rng.randint(0, 256, (4, 12)).astype(np.uint8)
    shards = coder.encode_np(data)
    use = (1, 3, 5, 6)
    survivors = shards[list(use)]  # (4, 12)
    rec = coder.reconstruct_jax(jnp.asarray(survivors[None]), use)
    assert np.array_equal(np.asarray(rec[0]), data)


def test_for_n_f():
    coder = rs.for_n_f(4, 1)
    assert coder.data_shards == 2 and coder.parity_shards == 2
    assert rs.for_n_f(4, 1) is coder  # cached


def test_rs16_reconstruct_np_optional_api():
    """ReedSolomon16.reconstruct_np — the object-mode Broadcast decode
    contract (round 5: previously missing; object mode at N > 256 had no
    erasure reconstruction)."""
    import random

    from hbbft_tpu.ops.rs import ReedSolomon16

    rng = random.Random(5)
    k, par = 10, 6
    coder = ReedSolomon16(k, par)
    data = np.array(
        [[rng.randrange(256) for _ in range(8)] for _ in range(k)],
        dtype=np.uint8,
    )
    full = coder.encode_np(data)
    shards = [bytes(s) for s in full]
    # erase par shards (incl. data rows)
    lost = [0, 3, 7, 11, 13, 15]
    holed = [None if i in lost else shards[i] for i in range(k + par)]
    out = coder.reconstruct_np(holed)
    assert out == shards
    # too few shards raises
    import pytest as _pytest

    holed2 = [s if i < k - 1 else None for i, s in enumerate(shards)]
    with _pytest.raises(ValueError):
        coder.reconstruct_np(holed2)

"""C++ full-scheme BLS12-381 oracle vs the Python host implementation.

Byte-exact parity (same algorithms, constants generated from the Python
derivation): curve ops, hash-to-curve, sign/verify, Lagrange combination,
and TPKE — the §2.2 ground-truth obligation for the device crypto.

The host side runs under ``bls12_381.pure_python()`` — without it the host
API would itself dispatch to the native oracle and every assertion would
compare the C++ code to itself.
"""

import random

import pytest

from hbbft_tpu.crypto import bls12_381 as H
from hbbft_tpu.crypto.tc import Ciphertext, SecretKeySet
from hbbft_tpu.native import get_oracle


@pytest.fixture(autouse=True)
def _host_is_pure_python():
    with H.pure_python():
        yield


@pytest.fixture(scope="module")
def oracle():
    return get_oracle()


@pytest.fixture(scope="module")
def keyset():
    rng = random.Random(1)
    sks = SecretKeySet.random(2, rng)
    return rng, sks, sks.public_keys()


def test_g1_g2_ops_byte_parity(oracle):
    rng = random.Random(7)
    for _ in range(3):
        k1, k2 = rng.randrange(1, H.R), rng.randrange(1, H.R)
        p1, p2 = H.g1_mul(H.G1_GEN, k1), H.g1_mul(H.G1_GEN, k2)
        assert oracle.bls_g1_add(H.g1_to_bytes(p1), H.g1_to_bytes(p2)) == \
            H.g1_to_bytes(H.g1_add(p1, p2))
        assert oracle.bls_g1_mul(H.g1_to_bytes(p1), k2) == \
            H.g1_to_bytes(H.g1_mul(p1, k2))
        q1, q2 = H.g2_mul(H.G2_GEN, k1), H.g2_mul(H.G2_GEN, k2)
        assert oracle.bls_g2_add(H.g2_to_bytes(q1), H.g2_to_bytes(q2)) == \
            H.g2_to_bytes(H.g2_add(q1, q2))
        assert oracle.bls_g2_mul(H.g2_to_bytes(q1), k2) == \
            H.g2_to_bytes(H.g2_mul(q1, k2))
    # infinity handling
    inf1, inf2 = H.g1_to_bytes(None), H.g2_to_bytes(None)
    assert oracle.bls_g1_add(inf1, H.g1_to_bytes(p1)) == H.g1_to_bytes(p1)
    assert oracle.bls_g1_mul(H.g1_to_bytes(p1), 0) == inf1
    assert oracle.bls_g2_mul(H.g2_to_bytes(q1), H.R) == inf2


def test_hash_to_curve_byte_parity(oracle):
    for msg in [b"", b"a", b"hello world", bytes(range(200)), b"\x00" * 64]:
        assert oracle.bls_hash_g1(msg) == H.g1_to_bytes(H.hash_g1(msg))
        assert oracle.bls_hash_g2(msg) == H.g2_to_bytes(H.hash_g2(msg))


def test_pairing_check_outcomes_agree(oracle):
    rng = random.Random(11)
    k = rng.randrange(1, H.R)
    p = H.g1_mul(H.G1_GEN, k)
    h = H.hash_g2(b"pairing doc")
    sig = H.g2_mul(h, k)
    good = [(H.g1_neg(H.G1_GEN), sig), (p, h)]
    bad = [(H.g1_neg(H.G1_GEN), sig), (H.g1_mul(p, 2), h)]
    for pairs, expect in [(good, True), (bad, False)]:
        assert H.pairing_check(pairs) is expect
        enc = [(H.g1_to_bytes(a), H.g2_to_bytes(b)) for a, b in pairs]
        assert oracle.bls_pairing_check(enc) is expect


def test_sign_verify_combine_byte_parity(oracle, keyset):
    rng, sks, pks = keyset
    msg = b"native oracle parity"
    sig_bytes = {}
    for i in range(5):
        sk = sks.secret_key_share(i)
        s = oracle.bls_sign(msg, sk.scalar)
        assert s == sk.sign(msg).to_bytes()
        assert oracle.bls_verify(pks.public_key_share(i).to_bytes(), msg, s)
        # wrong pk rejects
        assert not oracle.bls_verify(
            pks.public_key_share((i + 1) % 5).to_bytes(), msg, s
        )
        sig_bytes[i] = s
    subset = {i: sig_bytes[i] for i in (0, 2, 4)}
    comb = oracle.bls_combine_g2(subset)
    expect = pks.combine_signatures(
        {i: sks.secret_key_share(i).sign(msg) for i in (0, 2, 4)}
    )
    assert comb == expect.to_bytes()
    assert oracle.bls_verify(pks.public_key().to_bytes(), msg, comb)


def test_tpke_byte_parity(oracle, keyset):
    rng, sks, pks = keyset
    msg = b"the quick brown transaction"
    r = rng.randrange(1, H.R)
    # same r → identical ciphertext as the host path
    ct_host = pks.public_key().encrypt(msg, random.Random(0))
    # replicate: host encrypt consumes rng.randrange(1, R); replay it
    replay = random.Random(0)
    r_host = replay.randrange(1, H.R)
    u, v, w = oracle.bls_tpke_encrypt(pks.public_key().to_bytes(), msg, r_host)
    assert u == H.g1_to_bytes(ct_host.u)
    assert v == ct_host.v
    assert w == H.g2_to_bytes(ct_host.w)
    assert oracle.bls_tpke_verify(u, v, w)
    # bit-flip → CCA check fails
    bad_v = bytes([v[0] ^ 1]) + v[1:]
    assert not oracle.bls_tpke_verify(u, bad_v, w)

    # decryption shares + combine, against the host decrypt
    shares = {}
    for i in (1, 2, 3):
        d = oracle.bls_tpke_decrypt_share(u, sks.secret_key_share(i).scalar)
        host_share = sks.secret_key_share(i).decrypt_share(ct_host, check=False)
        assert d == host_share.to_bytes()
        shares[i] = d
    out = oracle.bls_tpke_combine(shares, v)
    assert out == msg

"""Performance plane (`obs/perf.py`).

The always-on sampler contract: the core is clock-free (every window
takes ``now`` from the caller), the first sample only primes, each
window folds counter deltas into per-segment stats and per-layer
utilization, the retained history is a bounded ring, every
``snapshot_every``-th sample is journaled, and ``segment_means`` is the
shared read path of the bench pump lines, frozen profiles, and the
watchtower's perf-drift sentinel.
"""

import json

import pytest

from hbbft_tpu.obs.metrics import Registry
from hbbft_tpu.obs.perf import (
    ALL_LAYERS,
    DEFAULT_ERASURE_REF_MBPS,
    PUMP_SEGMENTS,
    PerfPlane,
    segment_means,
)


def _plane(**kwargs):
    reg = Registry()
    seg_h = reg.histogram("hbbft_pump_segment_seconds", "",
                          labelnames=("segment",))
    ph_h = reg.histogram("hbbft_phase_duration_seconds", "",
                         labelnames=("phase",))
    ers = reg.counter("hbbft_rbc_erasure_bytes_total", "")
    sent = reg.counter("hbbft_net_bytes_sent_total", "")
    return PerfPlane(reg, 0, **kwargs), seg_h, ph_h, ers, sent


def test_priming_sample_then_window_folds_layer_utilization():
    plane, seg_h, ph_h, ers, _sent = _plane()
    assert plane.sample(10.0) is None  # priming: nothing to delta
    assert plane.registry.get("hbbft_perf_headroom").value() == -1
    assert plane.headroom() is None
    assert plane.utilization() == {}
    assert plane.summary()["headroom"] is None

    # one 1 s window: 0.3 s pump (msg), 0.1 s recv, 0.05 s flush,
    # 0.2 s crypto, 30 MB erasure (= 0.1 of the 300 MB/s reference)
    for _ in range(60):
        seg_h.labels(segment="msg").observe(0.005)
    seg_h.labels(segment="recv").observe(0.1)
    seg_h.labels(segment="flush").observe(0.05)
    ph_h.labels(phase="decrypt_share").observe(0.2)
    ers.inc(30e6)
    w = plane.sample(11.0)
    assert w is not None and w["wall_s"] == 1.0
    assert abs(w["layers"]["pump"] - 0.3) < 1e-6
    assert abs(w["layers"]["recv"] - 0.1) < 1e-6
    assert abs(w["layers"]["egress"] - 0.05) < 1e-6
    assert abs(w["layers"]["crypto"] - 0.2) < 1e-6
    assert abs(w["layers"]["erasure"]
               - 30e6 / (DEFAULT_ERASURE_REF_MBPS * 1e6)) < 1e-9
    seg = w["segments"]["msg"]
    assert seg["events"] == 60
    assert abs(seg["mean_s"] - 0.005) < 1e-6
    # headroom is 1 minus the WORST of the layer and whole-process
    # CPU fractions, floored at 0
    worst = max(max(w["layers"].values()), w["cpu_frac"])
    assert abs(w["headroom"] - max(0.0, 1.0 - worst)) < 1e-12
    assert plane.headroom() == w["headroom"]
    assert plane.summary()["util"]["pump"] == round(w["layers"]["pump"], 4)
    # the model's own exposition follows each window
    reg = plane.registry
    assert reg.get("hbbft_perf_headroom").value() == w["headroom"]
    assert reg.get("hbbft_perf_util").value(layer="pump") \
        == w["layers"]["pump"]
    assert reg.get("hbbft_perf_util").value(layer="cpu") == w["cpu_frac"]
    assert reg.get("hbbft_perf_samples_total").total() == 1


def test_maybe_sample_is_rate_limited_and_ring_bounded():
    plane, seg_h, *_ = _plane(interval_s=1.0, ring=5)
    assert plane.maybe_sample(0.0) is None   # priming
    assert plane.maybe_sample(0.5) is None   # inside the interval
    assert plane._prev is not None
    for i in range(1, 20):
        seg_h.labels(segment="msg").observe(0.001)
        plane.maybe_sample(float(i))
    assert plane.samples == 19
    assert len(plane.windows) == 5           # bounded ring
    with pytest.raises(ValueError):
        PerfPlane(Registry(), 0, interval_s=0.0)


def test_every_nth_sample_is_journaled_via_record():
    recorded = []
    plane, seg_h, *_ = _plane(snapshot_every=3,
                              record=lambda **kw: recorded.append(kw))
    plane.sample(0.0)
    for i in range(1, 8):
        seg_h.labels(segment="msg").observe(0.002)
        plane.sample(float(i))
    assert len(recorded) == 2  # windows 3 and 6
    assert recorded[0]["window_s"] == 1.0
    assert 0.0 <= recorded[0]["headroom"] <= 1.0
    doc = json.loads(recorded[0]["doc"])
    assert set(doc) == {"layers", "segments"}
    assert set(doc["layers"]) == set(ALL_LAYERS)
    assert doc["segments"]["msg"]["events"] == 1


def test_pump_cpu_and_offload_stats_fold_into_windows():
    cpu = [0.0]
    stats = [(0, 0)]
    plane, *_ = _plane(pump_cpu_fn=lambda: cpu[0],
                       pump_stats_fn=lambda: stats[0])
    plane.sample(0.0)
    cpu[0] = 0.4
    stats[0] = (10, 3)
    w = plane.sample(1.0)
    assert abs(w["pump_cpu_frac"] - 0.4) < 1e-9
    assert w["pump_iters"] == 10
    assert abs(w["offload_frac"] - 0.3) < 1e-9


def test_perf_doc_flame_tree_aggregates_the_ring():
    plane, seg_h, *_ = _plane()
    plane.sample(0.0)
    seg_h.labels(segment="msg").observe(0.2)
    seg_h.labels(segment="recv").observe(0.1)
    plane.sample(1.0)
    doc = plane.perf_doc()
    assert doc["windows"] == 1 and doc["samples"] == 1
    flame = doc["flame"]
    assert flame["name"] == "node0"
    by_name = {c["name"]: c for c in flame["children"]}
    assert set(by_name) == set(ALL_LAYERS)
    assert abs(by_name["pump"]["value"] - 0.2) < 1e-6
    assert [c["name"] for c in by_name["pump"]["children"]] == ["msg"]
    assert abs(by_name["recv"]["value"] - 0.1) < 1e-6
    assert by_name["crypto"]["value"] == 0.0
    assert doc["series"] == list(plane.windows)
    assert doc["headroom"] == plane.headroom()


def test_segment_means_folds_and_deltas_scrapes():
    prev = {
        "hbbft_pump_segment_seconds_sum":
            [({"segment": "msg"}, 1.0), ({"segment": "input"}, 0.5)],
        "hbbft_pump_segment_seconds_count":
            [({"segment": "msg"}, 100.0), ({"segment": "input"}, 10.0)],
    }
    cur = {
        "hbbft_pump_segment_seconds_sum":
            [({"segment": "msg"}, 2.0), ({"segment": "input"}, 0.5)],
        "hbbft_pump_segment_seconds_count":
            [({"segment": "msg"}, 200.0), ({"segment": "input"}, 10.0)],
    }
    full = segment_means(cur)
    assert full["msg"] == {"mean_s": 0.01, "busy_s": 2.0, "events": 200.0}
    assert full["input"]["events"] == 10.0

    d = segment_means(cur, prev)
    assert d["msg"] == {"mean_s": 0.01, "busy_s": 1.0, "events": 100.0}
    assert "input" not in d  # zero events in the delta window

    # duplicate label rows (a multi-node fold) accumulate per segment
    twice = {k: v + v for k, v in cur.items()}
    assert segment_means(twice)["msg"]["events"] == 400.0
    assert segment_means({}) == {}


def test_runtime_folds_batch_msgs_into_msg_segment():
    """The batch-handle transport delivers peer traffic as ``"msgs"``
    pump events; their dispatch time must fold into the ``msg``
    segment (one observation per iteration) — otherwise the dominant
    hot path is invisible to the perf plane, the frozen profile, and
    the drift sentinel."""
    from hbbft_tpu.net.cluster import ClusterConfig, build_algo, \
        generate_infos
    from hbbft_tpu.net.runtime import NodeRuntime

    cfg = ClusterConfig(n=4, seed=5)
    infos = generate_infos(cfg)
    rt = NodeRuntime(build_algo(cfg, infos, 0), cfg.cluster_id)
    child = rt.registry.get(
        "hbbft_pump_segment_seconds").labels(segment="msg")
    junk = b"\x00perf-junk"  # undecodable: strikes the peer, no raise
    rt.pump_process([("msgs", (1, [junk, junk])), ("msg", (1, junk))],
                    depth=1)
    assert child.count == 1 and child.sum > 0.0
    rt.pump_process([("msgs", (2, [junk]))], depth=1)
    assert child.count == 2


def test_pump_segment_taxonomy_is_the_histogram_contract():
    # the sampler's segment list must cover the pump's attribution
    # taxonomy (runtime.py's histogram help string); queue_wait is
    # latency (not busy time) and recv/flush are their own layers
    assert set(PUMP_SEGMENTS) == {"msg", "input", "hello", "startup",
                                  "guard", "shed", "deferred"}
    assert "queue_wait" not in PUMP_SEGMENTS
    assert "recv" not in PUMP_SEGMENTS and "flush" not in PUMP_SEGMENTS

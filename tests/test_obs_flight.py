"""Flight recorder: on-disk journal format, rotation, bounded retention,
torn-tail tolerance, and failure accounting.

The durability contract under test: every append is framed + CRC'd +
flushed; a crash mid-record leaves a torn tail the reader skips loudly
(counted, never raises — fuzz-tested against EVERY truncation offset);
disk errors count ``hbbft_obs_flight_write_failures_total`` instead of
silently dropping events.
"""

import json
import os

from hbbft_tpu.obs.flight import (
    DEFAULT,
    FlightCommit,
    FlightHello,
    FlightMsg,
    FlightNote,
    FlightRecorder,
    find_journal_dirs,
    read_journal,
    read_segment_bytes,
    record_as_dict,
    target_covers,
    target_str,
)
from hbbft_tpu.obs.metrics import Registry
from hbbft_tpu.protocols.broadcast import ReadyMsg
from hbbft_tpu.traits import Target


def _segment_files(d):
    return sorted(n for n in os.listdir(d) if n.endswith(".fjl"))


def test_recorder_writes_readable_journal(tmp_path):
    d = str(tmp_path / "node-0")
    rec = FlightRecorder(d, node="0", flavor="virtualnet", clock=None)
    rec.record_msg("in", "1", ReadyMsg(b"\x07" * 32))
    rec.record_msg("out", "all", ReadyMsg(b"\x07" * 32))
    rec.record_commit(0, 3, 0, b"\xab" * 32)
    rec.record_fault("2", "MultipleReadys")
    rec.close()

    j = read_journal(d)
    assert j.node == "0" and j.flavor == "virtualnet"
    assert j.torn_tails == 0 and j.incarnations == [1]
    kinds = [type(r).__name__ for _inc, r in j.records]
    assert kinds == ["FlightHello", "FlightNote", "FlightMsg",
                     "FlightMsg", "FlightCommit", "FlightFault",
                     "FlightNote"]
    # the message payload is the real wire encoding (auditable)
    msgs = [r for _i, r in j.records if isinstance(r, FlightMsg)]
    from hbbft_tpu.protocols import wire

    assert wire.decode_message(msgs[0].payload) == ReadyMsg(b"\x07" * 32)
    assert msgs[0].direction == "in" and msgs[0].peer == "1"
    assert msgs[1].peer == "all"
    # logical clock: timestamps == record sequence numbers
    seqs = [r.seq for _i, r in j.records]
    assert seqs == sorted(seqs) and len(set(seqs)) == len(seqs)


def test_segment_rotation_and_bounded_retention(tmp_path):
    d = str(tmp_path / "j")
    rec = FlightRecorder(d, node="0", clock=None,
                         max_segment_bytes=256, max_segments=4)
    for i in range(200):
        rec.record_commit(0, i, i, bytes([i % 256]) * 32)
    rec.close()
    segs = _segment_files(d)
    # rotated AND bounded
    assert 1 < len(segs) <= 4
    assert int(rec.registry.get(
        "hbbft_obs_flight_rotations_total").value()) > 1
    assert rec.registry.get("hbbft_obs_flight_segments").value() <= 4
    # the retained tail still reads back cleanly, newest commits last
    j = read_journal(d)
    commits = [r for _i, r in j.records if isinstance(r, FlightCommit)]
    assert commits[-1].epoch == 199
    # every retained segment self-describes
    with open(os.path.join(d, segs[0]), "rb") as fh:
        recs, torn = read_segment_bytes(fh.read())
    assert isinstance(recs[0], FlightHello) and not torn


def test_restart_bumps_incarnation_and_notes_it(tmp_path):
    d = str(tmp_path / "j")
    rec1 = FlightRecorder(d, node="0", clock=None)
    rec1.record_commit(0, 0, 0, b"\x01" * 32)
    rec1.close()
    rec2 = FlightRecorder(d, node="0", clock=None)  # same dir: restart
    rec2.record_commit(0, 0, 0, b"\x01" * 32)
    rec2.close()
    j = read_journal(d)
    assert j.incarnations == [1, 2] and j.starts == 2
    notes = [r.kind for _i, r in j.records if isinstance(r, FlightNote)]
    assert notes == ["start", "stop", "restart", "stop"]


def test_torn_tail_fuzz_every_truncation_offset(tmp_path):
    """CI satellite: a journal cut at ANY byte offset yields a clean
    prefix of records, a counted torn tail, and never an exception."""
    d = str(tmp_path / "j")
    rec = FlightRecorder(d, node="0", clock=None)
    for i in range(6):
        rec.record_msg("in", "1", ReadyMsg(bytes([i]) * 32))
    rec.close()
    seg = os.path.join(d, _segment_files(d)[0])
    with open(seg, "rb") as fh:
        data = fh.read()
    full, torn = read_segment_bytes(data)
    assert not torn and len(full) == 9  # hello + start + 6 msgs + stop
    # exact record boundaries (a cut there looks like a clean shorter
    # segment — indistinguishable by design; every OTHER cut is torn)
    import struct

    boundaries = {0}
    pos = 0
    while pos < len(data):
        (length,) = struct.unpack_from(">I", data, pos)
        pos += 8 + length
        boundaries.add(pos)
    torn_counter = DEFAULT.get("hbbft_obs_flight_torn_tails_total")
    before = torn_counter.value()
    torn_seen = 0
    for cut in range(len(data)):
        recs, was_torn = read_segment_bytes(data[:cut])
        assert len(recs) <= len(full)
        assert recs == full[: len(recs)]
        assert was_torn == (cut not in boundaries), cut
        torn_seen += 1 if was_torn else 0
    assert torn_seen > 0
    assert torn_counter.value() == before + torn_seen
    # corrupting a CRC mid-file tears there, keeping the prefix
    corrupt = bytearray(data)
    corrupt[len(data) // 2] ^= 0xFF
    recs, was_torn = read_segment_bytes(bytes(corrupt))
    assert was_torn and recs == full[: len(recs)]


def test_near_cap_record_reads_back_not_torn(tmp_path):
    """A legally-journaled message near wire.MAX_MESSAGE_BYTES embeds a
    blob above wire.MAX_BLOB_BYTES; the reader must lift the per-blob
    cap to the record's own CRC-validated length instead of misreporting
    the segment as torn."""
    from hbbft_tpu.obs.flight import FlightMsg
    from hbbft_tpu.protocols import wire

    d = str(tmp_path / "j")
    rec = FlightRecorder(d, node="0", clock=None,
                         max_segment_bytes=64 * 2**20)
    big = FlightMsg(1, 1.0, "in", "1", 0, 0, "Huge",
                    b"\x5a" * (wire.MAX_BLOB_BYTES + 64))
    rec._append(big)
    rec.record_commit(0, 0, 0, b"\x01" * 32)  # a record AFTER the big one
    rec.close()
    j = read_journal(d)
    assert j.torn_tails == 0
    kinds = [type(r).__name__ for _i, r in j.records]
    assert "FlightMsg" in kinds and "FlightCommit" in kinds
    got = [r for _i, r in j.records if isinstance(r, FlightMsg)][0]
    assert got == big


def test_write_failures_are_counted_not_raised(tmp_path):
    d = str(tmp_path / "j")
    reg = Registry()
    rec = FlightRecorder(d, node="0", clock=None, registry=reg)
    rec._fh.close()  # simulate the disk yanking the handle away
    rec.record_commit(0, 0, 0, b"\x01" * 32)  # must not raise
    rec.record_fault("1", "MultipleEchos")
    assert reg.get("hbbft_obs_flight_write_failures_total").value() >= 2
    # the in-memory tail still has the records (the /flight endpoint
    # keeps working even when the disk does not)
    assert any(type(t).__name__ == "FlightCommit" for t in rec.tail)


def test_tail_jsonl_summarizes_payloads(tmp_path):
    rec = FlightRecorder(str(tmp_path / "j"), node="0", clock=None)
    rec.record_msg("in", "1", ReadyMsg(b"\x07" * 32))
    lines = [json.loads(l) for l in rec.tail_jsonl().splitlines()]
    rec.close()
    msg = [l for l in lines if l["type"] == "FlightMsg"][0]
    # payload never inlined into JSON — digest + size instead
    assert "payload" not in msg
    assert msg["payload_bytes"] > 0 and len(msg["payload_sha3"]) == 16
    assert msg["mtype"] == "ReadyMsg"
    d = record_as_dict(FlightCommit(1, 1.0, 0, 0, 0, b"\xab" * 32))
    assert d["digest_sha3"] and d["digest_bytes"] == 32


def test_target_descriptors_round_trip_coverage():
    assert target_str(Target.all()) == "all"
    assert target_covers("all", "3")
    t = target_str(Target.nodes([2, 0]))
    assert t == "nodes:0,2"
    assert target_covers(t, "2") and not target_covers(t, "1")
    t = target_str(Target.all_except([1]))
    assert t == "all_except:1"
    assert target_covers(t, "0") and not target_covers(t, "1")


def test_find_journal_dirs_layouts(tmp_path):
    # flat: the dir itself is a journal
    flat = str(tmp_path / "flat")
    FlightRecorder(flat, node="0", clock=None).close()
    assert find_journal_dirs(flat) == [flat]
    # parent layout: root/node-*/
    root = str(tmp_path / "root")
    for n in range(3):
        FlightRecorder(os.path.join(root, f"node-{n}"), node=str(n),
                       clock=None).close()
    dirs = find_journal_dirs(root)
    assert [os.path.basename(d) for d in dirs] == [
        "node-0", "node-1", "node-2"]
    assert find_journal_dirs(str(tmp_path / "missing")) == []

"""Per-tx causal tracing (obs.trace) + critical path (obs.critpath).

The acceptance scenarios:

- trace-context basics: ids derive from tx bytes alone, hop counters
  follow the stage chain, packed tid blobs round-trip;
- ``FlightTrace`` rides the wire registry (tag 0x95) byte-exactly;
- two identical-seed VirtualNet runs (cost model on, TPKE on) produce
  **byte-identical** critpath reports, reconstruct every committed tx,
  and every reconstruction's components sum exactly to its total;
- a real 4-node socket cluster reconstructs ≥ 99 % of committed txs
  end-to-end, the p50 decomposition sums to within 10 % of the
  client-measured submit→commit p50, the always-on
  ``hbbft_pump_segment_seconds`` histogram and the ``/trace`` endpoint
  serve, and ``obs.top --json`` snapshots the same cluster.
"""

import asyncio
import contextlib
import io
import json
import random

import pytest

from hbbft_tpu.obs import critpath
from hbbft_tpu.obs.trace import (
    STAGE_HOPS,
    TRACE_ID_BYTES,
    FlightTrace,
    TraceContext,
    iter_tids,
    pack_tids,
    tid_of_digest,
    trace_id,
)
from hbbft_tpu.protocols import wire
from hbbft_tpu.protocols.dynamic_honey_badger import DynamicHoneyBadger
from hbbft_tpu.protocols.honey_badger import EncryptionSchedule
from hbbft_tpu.protocols.queueing_honey_badger import (
    QueueingHoneyBadger,
    TxInput,
)
from hbbft_tpu.sim import NetBuilder
from hbbft_tpu.sim.trace import CostModel

# ---------------------------------------------------------------------------
# trace-context unit behavior
# ---------------------------------------------------------------------------


def test_trace_id_derives_from_tx_bytes_alone():
    assert trace_id(b"tx-1") == trace_id(b"tx-1")
    assert trace_id(b"tx-1") != trace_id(b"tx-2")
    assert len(trace_id(b"tx-1")) == TRACE_ID_BYTES
    # client side derives the same id from the sha3 digest prefix it
    # already tracks per submitted tx
    import hashlib

    digest = hashlib.sha3_256(b"tx-1").digest()
    assert tid_of_digest(digest) == trace_id(b"tx-1")


def test_pack_iter_tids_roundtrip_and_truncation():
    tids = [trace_id(b"a"), trace_id(b"b"), trace_id(b"c")]
    blob = pack_tids(tids)
    assert list(iter_tids(blob)) == tids
    # a torn trailing partial id is dropped, never yielded short
    assert list(iter_tids(blob + b"\x01\x02")) == tids
    assert list(iter_tids(b"")) == []


def test_stage_hops_monotone_along_the_causal_chain():
    # submit (client) → ingress (node) → queued (pump) → commit →
    # commit_seen (client): hop counts never decrease along the chain
    chain = ("submit", "ingress", "queued", "commit", "commit_seen")
    hops = [STAGE_HOPS[s] for s in chain]
    assert hops == sorted(hops)
    ctx = TraceContext(trace_id(b"x"), 0)
    assert ctx.next().hop == 1 and ctx.next().tid == ctx.tid


def test_flight_trace_wire_roundtrip():
    rec = FlightTrace(seq=7, t=1.25, stage="commit", era=2, epoch=9,
                      hop=3, detail="0",
                      tids=pack_tids([trace_id(b"a"), trace_id(b"b")]))
    enc = wire.encode_message(rec)
    dec = wire.decode_message(enc)
    assert dec == rec
    assert list(iter_tids(dec.tids)) == [trace_id(b"a"), trace_id(b"b")]


# ---------------------------------------------------------------------------
# sim: byte-identical reports, exact reconstruction + component sums
# ---------------------------------------------------------------------------


def _recorded_sim_run(infos, root, n=4, txs=8):
    net = (
        NetBuilder(list(range(n)))
        .cost_model(CostModel())
        .flight(root)
        .using_step(
            lambda nid: QueueingHoneyBadger(
                DynamicHoneyBadger(
                    infos[nid], infos[nid].secret_key(),
                    rng=random.Random(100 + nid),
                    encryption_schedule=EncryptionSchedule.always(),
                ),
                batch_size=4, rng=random.Random(200 + nid),
            )
        )
    )
    for i in range(txs):
        net.send_input(i % n, TxInput(b"cp-tx-%d" % i))
    net.run_to_quiescence()
    net.close_observers()
    return net


@pytest.fixture(scope="module")
def sim_reports(shared_netinfo, tmp_path_factory):
    """The SAME deterministic schedule recorded twice, independently,
    each reduced to its critpath report."""
    infos = shared_netinfo(4, 13)
    reports = []
    for tag in ("a", "b"):
        root = str(tmp_path_factory.mktemp(f"critpath-{tag}"))
        _recorded_sim_run(infos, root)
        dirs = sorted(critpath.find_journal_dirs(root))
        assert len(dirs) == 4
        reports.append(critpath.build_report(dirs))
    return reports


def test_identical_seed_runs_yield_byte_identical_reports(sim_reports):
    a, b = sim_reports
    assert json.dumps(a, sort_keys=True) == json.dumps(b, sort_keys=True)


def test_sim_reconstructs_every_committed_tx(sim_reports):
    rep = sim_reports[0]
    assert rep["txs_committed"] >= 8
    assert rep["txs_reconstructed"] == rep["txs_committed"]
    assert rep["reconstructed_fraction"] == 1.0
    # unmatched evidence is COUNTED, and a clean sim run has none
    um = rep["unmatched"]
    assert um["no_ingress"] == 0 and um["no_commit"] == 0
    assert um["unaligned_processes"] == []


def test_components_sum_exactly_to_each_total(sim_reports):
    rep = sim_reports[0]
    assert rep["waterfalls"], rep
    for row in rep["waterfalls"]:
        total = sum(row["components"].values())
        assert abs(total - row["total_s"]) < 1e-6, row
        assert all(v >= 0 for v in row["components"].values()), row
    # the percentile rows report one tx's OWN decomposition
    for p in ("p50", "p99"):
        doc = rep[p]
        assert abs(sum(doc["components"].values())
                   - doc["total_s"]) < 1e-6
        assert doc["dominant"] in critpath.COMPONENTS
        # an encrypted sim epoch spends real time in protocol phases
    assert rep["p50"]["total_s"] > 0


def test_clock_offsets_report_bounds_not_point_estimates(sim_reports):
    rep = sim_reports[0]
    for node, doc in rep["clock_offsets"].items():
        assert "bound_s" in doc, node
        # every aligned process carries a finite, nonnegative bound
        assert doc["bound_s"] is not None and doc["bound_s"] >= 0
    assert rep["anchor"] in rep["clock_offsets"]
    assert rep["clock_offsets"][rep["anchor"]]["offset_s"] == 0.0


def test_critpath_cli_renders_and_exits_zero(sim_reports, shared_netinfo,
                                             tmp_path):
    infos = shared_netinfo(4, 13)
    root = str(tmp_path / "cli")
    _recorded_sim_run(infos, root)
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        rc = critpath.main([root])
    out = buf.getvalue()
    assert rc == 0
    assert "critpath: 4 journals" in out and "p50:" in out
    # --json emits the full deterministic document
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        rc = critpath.main([root, "--json"])
    doc = json.loads(buf.getvalue())
    assert rc == 0 and doc["reconstructed_fraction"] == 1.0


def test_critpath_cli_exits_2_without_journals(tmp_path):
    import sys

    buf = io.StringIO()
    with contextlib.redirect_stderr(buf):
        rc = critpath.main([str(tmp_path / "nothing-here")])
    assert rc == 2


# ---------------------------------------------------------------------------
# socket acceptance: end-to-end reconstruction on a real 4-node cluster
# ---------------------------------------------------------------------------

SOCKET_TIMEOUT_S = 90


def test_socket_cluster_end_to_end_critical_path(tmp_path):
    """The tentpole acceptance run: a real 4-node cluster with client
    trace journaling, ≥ 99 % end-to-end reconstruction, p50 component
    sum within 10 % of the client-measured submit→commit p50 — plus the
    live surfaces riding the same boot: the always-on
    ``hbbft_pump_segment_seconds`` histogram, ``/trace``, and
    ``obs.top --json``."""
    import os

    from hbbft_tpu.net.cluster import ClusterConfig, LocalCluster
    from hbbft_tpu.obs.http import http_get

    flight_root = str(tmp_path / "flight")

    async def scenario():
        cfg = ClusterConfig(n=4, seed=23, batch_size=6,
                            flight_dir=flight_root)
        cluster = LocalCluster(cfg)
        await cluster.start()
        try:
            client = await cluster.client(
                0, trace_dir=os.path.join(flight_root, "client-0"))
            txs = [b"cpsock-%03d" % i for i in range(24)]
            for tx in txs:
                assert await client.submit(tx) == 0
            for tx in txs:
                await client.wait_committed(tx, timeout_s=30)
            pct = client.latency_percentiles()
            host, port = cluster.metrics_addrs[0]
            metrics = await asyncio.to_thread(http_get, host, port,
                                              "/metrics")
            trace_tail = await asyncio.to_thread(http_get, host, port,
                                                 "/trace")
            from hbbft_tpu.obs import top

            targets = ",".join(
                f"{h}:{p}" for h, p in
                dict(cluster.metrics_addrs).values())

            def run_top():
                # worker thread: obs endpoints are served by THIS
                # event loop, so a blocking poll here would deadlock
                buf = io.StringIO()
                with contextlib.redirect_stdout(buf):
                    rc = top.main(["--targets", targets, "--json"])
                return rc, buf.getvalue()

            rc, top_out = await asyncio.to_thread(run_top)
            return pct, metrics, trace_tail, rc, top_out
        finally:
            await cluster.stop()

    pct, metrics, trace_tail, top_rc, top_out = asyncio.run(
        asyncio.wait_for(scenario(), SOCKET_TIMEOUT_S))

    # satellite: the pump-segment histogram is always on (no env gate)
    assert "hbbft_pump_segment_seconds_bucket" in metrics
    assert 'segment="queue_wait"' in metrics
    assert 'segment="flush"' in metrics
    # the /trace endpoint serves the causal stages live, tids in hex
    trace_lines = [json.loads(l) for l in trace_tail.splitlines() if l]
    assert any(d["stage"] == "ingress" for d in trace_lines)
    assert any(d["stage"] == "commit" for d in trace_lines)
    assert all(d["type"] == "FlightTrace" for d in trace_lines)
    assert all(
        all(len(t) == 2 * TRACE_ID_BYTES for t in d["tids"])
        for d in trace_lines)
    # satellite: obs.top one-shot JSON over the live cluster
    assert top_rc == 0
    top_doc = json.loads(top_out)
    assert len(top_doc["nodes"]) == 4
    assert all(n["up"] for n in top_doc["nodes"])
    assert all("mesh_collectives" in n and "load" in n
               for n in top_doc["nodes"])

    # offline: merge all journals (4 nodes + 1 client) into the report
    dirs = sorted(critpath.find_journal_dirs(flight_root))
    assert len(dirs) == 5, dirs
    rep = critpath.build_report(dirs)
    assert rep["clients"] == ["client"]
    # ≥ 99 % of committed txs reconstruct end to end
    assert rep["reconstructed_fraction"] >= 0.99, rep["unmatched"]
    # every reconstructed tx has the full client→client chain
    assert rep["unmatched"]["no_commit_seen"] == 0, rep["unmatched"]
    # the p50 decomposition sums to the p50 total exactly, and that
    # total agrees with the CLIENT-measured submit→commit p50 within
    # 10 % (different clocks, same two events)
    p50 = rep["p50"]
    assert abs(sum(p50["components"].values()) - p50["total_s"]) < 1e-6
    measured = pct["p50_s"]
    assert measured > 0
    assert abs(p50["total_s"] - measured) <= 0.10 * max(
        measured, p50["total_s"]) + 2e-3, (p50["total_s"], measured)
    # a real-socket run spends most of its budget outside the client
    # wire hop; the dominant edge must be a protocol-side component
    assert p50["dominant"] in critpath.COMPONENTS

"""Chaos campaign runner — the acceptance scenarios.

Tier 1 keeps the fast pieces: a 6-cell all-clean smoke over every
link-shaping preset, the equivocator-under-loss auto-triage (correct
faulty node + first divergent epoch), byte-identical replay from a
reported spec, one socket churn cell, and the CLI.  The full ≥100-cell
sweep is marked ``slow``.
"""

import json
import os
import subprocess
import sys

import pytest

from hbbft_tpu.chaos.campaign import (
    ADVERSARIES,
    CellSpec,
    SIM_SCALES,
    full_grid,
    main as campaign_main,
    replay_matches,
    run_campaign,
    run_cell,
    run_churn_cell,
    smoke_grid,
)
from hbbft_tpu.chaos.link import PRESETS

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_smoke_grid_all_clean_and_committing(tmp_path):
    """The tier-1 campaign smoke: six seeded cells spanning every preset
    must commit batches on every correct node and audit clean."""
    specs = smoke_grid()
    assert {s.shape for s in specs} == set(PRESETS)
    report = run_campaign(specs, str(tmp_path))
    assert report["cells"] == len(specs)
    assert report["verdicts"] == {"clean": len(specs)}, report["triage"]
    assert report["errors"] == 0
    assert report["stalled_cells"] == 0
    assert report["triage"] == []
    # shaping really happened: delays, at least one drop (lossy), a dup
    # (dup-reorder) and a partition hold crossed the campaign
    frames = report["frames"]
    assert frames["delayed"] > 0 and frames["duplicated"] > 0
    assert frames["partition_holds"] > 0
    # report schema: the trajectory/--compare surface
    assert report["metric"] == "chaos_campaign"
    assert report["unit"] == "clean_fraction" and report["value"] == 1.0
    assert report["epoch_virtual_s_p50"] > 0
    for d in report["cells_detail"]:
        assert d["batches_min"] >= 1
        assert d["spec"] == CellSpec.from_dict(d["spec"]).as_dict()


def test_equivocator_under_loss_is_triaged_to_node_and_epoch(tmp_path):
    """Acceptance: the intentionally-faulty cell (equivocator under
    loss) is auto-triaged to the correct faulty node and the first
    divergent epoch, with the replay spec attached."""
    spec = CellSpec(shape="lossy-1pct", adversary="equivocate", seed=0,
                    crank_limit=60_000)
    assert spec.faulty == (3,)
    report = run_campaign([spec], str(tmp_path), verify_nonclean=False)
    assert report["verdicts"] == {"fault": 1}
    (entry,) = report["triage"]
    assert entry["faulty_nodes"] == ["3"]
    assert entry["first_divergent_epoch"] is not None
    era, epoch = entry["first_divergent_epoch"]
    assert era == 0 and epoch >= 0
    assert any(k.startswith("Multiple") for k in entry["kinds"])
    # the replay block IS a loadable CellSpec
    assert CellSpec.from_dict(entry["replay"]["spec"]) == spec


def test_cell_replays_byte_identically(tmp_path):
    """Acceptance: a cell re-run from its reported seed + spec produces
    a byte-identical merged audit timeline; a different seed does not."""
    spec = CellSpec(shape="lossy-1pct", adversary="reorder", seed=1)
    d1, _res = run_cell(spec, str(tmp_path / "a"))
    assert replay_matches(spec, d1["timeline_digest"],
                          str(tmp_path / "b"))
    d3, _res = run_cell(CellSpec(shape="lossy-1pct", adversary="reorder",
                                 seed=2), str(tmp_path / "c"))
    assert d3["timeline_digest"] != d1["timeline_digest"]


def test_mitm_delay_budget_sweeps_with_seed():
    """Satellite: MitmDelayAdversary's budget comes from the scenario
    seed when unset, while the no-arg default stays 200."""
    from hbbft_tpu.sim.adversary import MitmDelayAdversary

    assert MitmDelayAdversary(target=0).max_delay == 200
    budgets = {MitmDelayAdversary(target=0, max_delay=None,
                                  seed=s).max_delay for s in range(8)}
    assert len(budgets) > 1
    assert all(50 <= b <= 500 for b in budgets)
    # deterministic per seed
    assert (MitmDelayAdversary(target=0, max_delay=None, seed=3).max_delay
            == MitmDelayAdversary(target=0, max_delay=None,
                                  seed=3).max_delay)


def test_churn_cell_restarts_and_audits_clean(tmp_path):
    """Kill/restart storm over a real in-process socket cluster: the
    restarted nodes catch up, the incident audits clean, and the
    restarts are visible as journal incarnations."""
    detail, res = run_churn_cell(
        CellSpec(kind="churn", seed=0, restarts=1), str(tmp_path))
    assert detail["verdict"] == "clean", res.as_dict()
    assert detail["batches_min"] >= 2
    assert sum(detail["restarts"].values()) >= 1
    assert detail["common_prefix_len"] >= 1


def test_vote_storm_rotates_eras_under_partition(tmp_path):
    """ROADMAP item 4's named next step: a vote-storm cell under the
    timed-partition preset drives REAL remove/re-add DKG rotations
    mid-partition — every chain crosses the era boundaries and the
    era-aware auditor returns clean."""
    spec = CellSpec(shape="partition-10s", adversary="vote-storm", n=4,
                    seed=0, time_scale=SIM_SCALES["partition-10s"],
                    crank_limit=60_000)
    detail, res = run_cell(spec, str(tmp_path))
    assert detail["verdict"] == "clean", res.as_dict()
    assert detail["eras_rotated"] >= 1, \
        "the storm never won a vote — no DKG rotation happened"
    assert detail["batches_min"] >= 1
    # the partition actually held traffic while eras rotated
    assert detail["shaping"]["partition_holds"] > 0


def test_socket_cell_pipelined_wan(tmp_path):
    """Satellite: a WAN-shaped REAL socket cluster at pipeline_depth=2
    commits under chaos and audits clean (the campaign's socket kind)."""
    from hbbft_tpu.chaos.campaign import run_socket_cell

    detail, _res = run_socket_cell(
        CellSpec(kind="socket", shape="wan-100ms", adversary="null",
                 n=4, seed=0, pipeline_depth=2), str(tmp_path))
    assert detail["verdict"] == "clean"
    assert detail["batches_min"] >= 1
    assert detail["pipeline_depth"] == 2


def test_future_spam_cell_bounded_counted_attributed(tmp_path):
    """Overload defense, sim kind: window-edge protocol spam from the
    faulty node — the victims keep committing, every future buffer
    stays under its cap, the per-sender budgets count the flood, and
    the audit attributes the overload to the spammer."""
    spec = CellSpec(shape="none", adversary="future-spam", n=4, seed=0,
                    crank_limit=60_000)
    assert spec.faulty == (3,)
    detail, res = run_cell(spec, str(tmp_path))
    assert detail["verdict"] == "clean", res.as_dict()
    assert detail["batches_min"] >= 1
    g = detail["guard"]
    if g["aba_future_cap"]:
        # peaks record PRE-eviction (falsifiable witness): cap + the
        # one just-inserted entry is the legal ceiling
        assert g["aba_future_peak"] <= g["aba_future_cap"] + 1
    assert g["hb_future_drops"] > 0
    assert detail["overload_attributed_to"] == ["3"]
    assert res.overload_incidents[0]["kinds"]["FutureEpochFlood"] > 0


def test_flood_cell_keeps_committing(tmp_path):
    """Sim kind: max-rate valid-frame spam amplification — duplicates
    are protocol no-ops, the queues absorb the burst, liveness holds."""
    spec = CellSpec(shape="none", adversary="flood", n=4, seed=0,
                    crank_limit=60_000)
    detail, res = run_cell(spec, str(tmp_path))
    assert detail["verdict"] == "clean", res.as_dict()
    assert detail["batches_min"] >= 1


def test_socket_garbage_stream_cell(tmp_path):
    """Overload defense, socket kind: a raw-socket injector claiming
    validator 3's identity streams framing-valid decode-invalid bytes
    at node 0.  The cluster keeps committing, the guard counts every
    strike and disconnects the stream with backoff, the live-sampled
    buffer gauges stay under their caps, and the audit attributes the
    incident to the claimed peer."""
    from hbbft_tpu.chaos.campaign import run_socket_cell

    detail, res = run_socket_cell(
        CellSpec(kind="socket", shape="none", adversary="garbage-stream",
                 n=4, seed=0, pipeline_depth=2), str(tmp_path))
    assert detail["verdict"] == "clean", res.as_dict()
    assert detail["batches_min"] >= 1
    g = detail["guard"]
    assert g["decode_strikes"] > 0
    assert g["disconnects"] >= 1
    assert g["injector"]["frames_sent"] > 0
    peaks, caps = g["gauge_peaks"], g["gauge_caps"]
    assert peaks["senderq_buffered"] <= caps["senderq_buffered"]
    assert peaks["inflight_frames"] <= caps["inflight_frames"]
    assert "3" in detail["overload_attributed_to"]


@pytest.mark.slow
def test_socket_valid_frame_flood_cell(tmp_path):
    """Socket kind, valid-frame flood: MSG_BATCH frames of well-formed
    EpochStarted spam — only the byte budget and in-flight caps can
    engage, and they must (counted throttles, then a disconnect)."""
    from hbbft_tpu.chaos.campaign import run_socket_cell

    detail, res = run_socket_cell(
        CellSpec(kind="socket", shape="none", adversary="flood",
                 n=4, seed=0, pipeline_depth=2), str(tmp_path))
    assert detail["verdict"] == "clean", res.as_dict()
    assert detail["batches_min"] >= 1
    g = detail["guard"]
    assert g["throttles"] > 0 or g["disconnects"] >= 1
    assert g["gauge_peaks"]["inflight_frames"] <= \
        g["gauge_caps"]["inflight_frames"]
    assert "3" in detail["overload_attributed_to"]


def test_campaign_cli_smoke(tmp_path):
    out = tmp_path / "report.json"
    rc = campaign_main(["--grid", "smoke", "--max-cells", "2",
                        "--out", str(out)])
    assert rc == 0
    doc = json.loads(out.read_text())
    assert doc["cells"] == 2 and doc["verdicts"] == {"clean": 2}
    # ephemeral journals are not advertised in the report
    assert all("journal" not in d for d in doc["cells_detail"])


def test_campaign_module_entry_point(tmp_path):
    """The literal ``python -m hbbft_tpu.chaos.campaign`` invocation."""
    out = tmp_path / "report.json"
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, "-m", "hbbft_tpu.chaos.campaign",
         "--grid", "smoke", "--max-cells", "1", "--out", str(out)],
        capture_output=True, text=True, env=env, cwd=REPO, timeout=240,
    )
    assert proc.returncode == 0, proc.stderr
    doc = json.loads(out.read_text())
    assert doc["metric"] == "chaos_campaign" and doc["cells"] == 1


def test_replay_cli_verifies_byte_identity(tmp_path, capsys):
    spec = CellSpec(shape="dup-reorder", adversary="equivocate", seed=1,
                    crank_limit=60_000)
    rc = campaign_main(["--replay", json.dumps(spec.as_dict()),
                        "--journal-root", str(tmp_path / "j")])
    assert rc == 0  # non-clean verdict, but byte-identical replay
    doc = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    # --journal-root given → the advertised journal survives the run
    assert os.path.isdir(doc["journal"])
    # without --journal-root the temp journals are deleted on exit, so
    # the path must not be advertised at all (no dangling forensics)
    rc = campaign_main(["--replay", json.dumps(spec.as_dict())])
    assert rc == 0
    doc = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert "journal" not in doc


def test_eclipse_does_not_heal_while_shaped_traffic_in_flight():
    """A shaped lull is not quiescence: with every in-flight message in
    the shaper's held set and an empty live queue, the eclipse must NOT
    take its early heal — only true quiescence (or heal_crank) ends it."""
    import heapq

    from hbbft_tpu.sim.adversary import EclipseAdversary
    from hbbft_tpu.sim.virtual_net import NetworkMessage, VirtualNet

    adv = EclipseAdversary(victim=0, heal_crank=100)
    net = VirtualNet({}, adversary=adv)
    assert adv.filter_message(net, NetworkMessage(0, 1, b"x")) is None
    assert adv.pending() == 1
    net._held_seq += 1
    heapq.heappush(net._held, (5.0, net._held_seq,
                               NetworkMessage(1, 2, b"y", at=5.0)))
    adv.pre_crank(net)  # queue empty BUT shaped traffic in flight
    assert not adv.healed
    net._held.clear()
    adv.pre_crank(net)  # true quiescence → early heal, backlog released
    assert adv.healed and adv.pending() == 0
    assert len(net.queue) == 1


def test_compare_gate_reads_clean_fraction():
    """The report line gates through bench.py --compare: a clean-fraction
    drop beyond threshold is a regression, a rise is not."""
    sys.path.insert(0, REPO)
    from bench import compare_bench

    old = {"metric": "chaos_campaign", "value": 0.95,
           "unit": "clean_fraction"}
    worse = compare_bench(old, dict(old, value=0.70))
    assert not worse["ok"] and "value" in worse["regressions"]
    better = compare_bench(old, dict(old, value=1.0))
    assert better["ok"]


@pytest.mark.slow
def test_full_sweep_meets_acceptance(tmp_path):
    """One invocation: ≥ 100 seeded cells over ≥ 4 shaping policies and
    ≥ 4 adversaries, every cell audited, every equivocator triaged to
    the correct faulty node, and every non-clean correct-node verdict
    (if any) reproduced byte-identically."""
    specs = full_grid(seeds=[0, 1], churn_cells=2)
    assert len(specs) >= 100
    report = run_campaign(specs, str(tmp_path))
    assert report["cells"] == len(specs)
    assert report["errors"] == 0
    assert len([p for p in report["policies"] if p != "none"]) >= 4
    assert len(report["adversaries"]) >= 4
    assert sum(report["verdicts"].values()) == report["cells"]
    equivocate_cells = [s for s in specs if s.adversary == "equivocate"]
    fault_triage = [t for t in report["triage"]
                    if t["verdict"] == "fault"]
    assert len(fault_triage) == len(equivocate_cells)
    for entry in fault_triage:
        spec = CellSpec.from_dict(entry["replay"]["spec"])
        assert entry["faulty_nodes"] == [str(spec.n - 1)]
        assert entry["first_divergent_epoch"] is not None
    # any non-clean verdict from a correct-node cell must have been
    # reproduced byte-identically from its reported seed
    for entry in report["triage"]:
        if "reproduced" in entry:
            assert entry["reproduced"] is True, entry

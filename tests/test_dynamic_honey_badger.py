"""DynamicHoneyBadger tests (reference: ``tests/dynamic_honey_badger.rs`` /
``net_dynamic_hb.rs``): add a validator via JoinPlan + DKG, remove one,
change the encryption schedule — mid-run, through consensus."""

import random

import pytest

from hbbft_tpu.crypto import tc
from hbbft_tpu.netinfo import NetworkInfo
from hbbft_tpu.protocols.dynamic_honey_badger import (
    Change,
    ChangeInput,
    ChangeState,
    DhbBatch,
    DynamicHoneyBadger,
    UserInput,
)
from hbbft_tpu.protocols.honey_badger import EncryptionSchedule
from hbbft_tpu.sim import NetBuilder, NullAdversary


def make_network(n, seed=31, schedule=None):
    rng = random.Random(seed)
    infos = NetworkInfo.generate_map(list(range(n)), rng)
    sec_keys = {nid: infos[nid].secret_key() for nid in infos}
    net = NetBuilder(list(range(n))).using_step(
        lambda nid: DynamicHoneyBadger(
            infos[nid],
            sec_keys[nid],
            rng=random.Random(5000 + nid),
            encryption_schedule=schedule or EncryptionSchedule.never(),
        )
    )
    return net


def batches_of(node):
    return [o for o in node.outputs if isinstance(o, DhbBatch)]


def drive_epoch(net, payload_fn, validators=None):
    ids = validators if validators is not None else net.node_ids()
    for nid in ids:
        net.send_input(nid, UserInput(payload_fn(nid)))
    net.run_to_quiescence()


def test_plain_epochs_without_changes():
    net = make_network(4)
    drive_epoch(net, lambda nid: f"user-{nid}".encode())
    for nid in net.node_ids():
        bs = batches_of(net.nodes[nid])
        assert len(bs) == 1
        assert bs[0].era == 0 and bs[0].change.state == "none"
    ref = batches_of(net.nodes[0])
    assert all(batches_of(net.nodes[nid]) == ref for nid in net.node_ids())


def test_remove_validator_rotates_era():
    net = make_network(4)
    # everyone votes to remove node 3, then proposes (committing the votes)
    for nid in net.node_ids():
        net.send_input(nid, ChangeInput(
            Change.node_change({
                k: net.nodes[nid].algorithm.netinfo.public_key(k)
                for k in (0, 1, 2)
            })
        ))
    drive_epoch(net, lambda nid: b"payload")
    # drive until era rotates everywhere (DKG runs through batches)
    for _ in range(6):
        if all(net.nodes[nid].algorithm.era == 1 for nid in net.node_ids()):
            break
        drive_epoch(net, lambda nid: b"more")
    for nid in net.node_ids():
        algo = net.nodes[nid].algorithm
        assert algo.era == 1, f"node {nid} stuck in era {algo.era}"
        assert sorted(algo.netinfo.all_ids()) == [0, 1, 2]
    # removed node is no longer a validator; the rest are
    assert not net.nodes[3].algorithm.is_validator()
    assert all(net.nodes[nid].algorithm.is_validator() for nid in (0, 1, 2))
    # a Complete batch was reported with the change
    completes = [
        b for b in batches_of(net.nodes[0]) if b.change.state == "complete"
    ]
    assert completes and completes[0].change.change.kind == "nodes"
    # consensus still works in the new era among 0,1,2
    drive_epoch(net, lambda nid: f"era1-{nid}".encode(), validators=[0, 1, 2])
    era1 = [b for b in batches_of(net.nodes[0]) if b.era == 1 and b.contributions]
    assert era1, "no era-1 batch committed"
    for nid in (1, 2, 3):
        got = [b for b in batches_of(net.nodes[nid]) if b.era == 1 and b.contributions]
        assert got == era1  # node 3 still observes identically


def test_add_validator_via_join_plan():
    net = make_network(4)
    rng = random.Random(99)
    # candidate node 4 with a fresh plain keypair
    cand_sk = tc.SecretKey.random(rng)
    cand_pk = cand_sk.public_key()
    plan = net.nodes[0].algorithm.join_plan()
    from hbbft_tpu.sim.virtual_net import Node

    cand_algo = DynamicHoneyBadger.from_join_plan(
        4, cand_sk, plan, rng=random.Random(5004)
    )
    net.nodes[4] = Node(node_id=4, algorithm=cand_algo)
    assert not cand_algo.is_validator()
    # validators vote to add node 4
    for nid in (0, 1, 2, 3):
        algo = net.nodes[nid].algorithm
        net.send_input(nid, ChangeInput(
            Change.node_change(
                {**algo.netinfo.public_key_map(), 4: cand_pk}
            )
        ))
    drive_epoch(net, lambda nid: b"x", validators=[0, 1, 2, 3])
    for _ in range(8):
        if all(
            net.nodes[nid].algorithm.era == 1 for nid in net.node_ids()
        ):
            break
        drive_epoch(net, lambda nid: b"y", validators=[0, 1, 2, 3])
    for nid in net.node_ids():
        algo = net.nodes[nid].algorithm
        assert algo.era == 1, f"node {nid} stuck in era {algo.era}"
        assert sorted(algo.netinfo.all_ids()) == [0, 1, 2, 3, 4]
    # the candidate became a real validator with a working key share
    assert net.nodes[4].algorithm.is_validator()
    # and can now contribute to consensus
    drive_epoch(net, lambda nid: f"from-{nid}".encode())
    era1 = [
        b for b in batches_of(net.nodes[0]) if b.era == 1 and b.contributions
    ]
    assert era1
    contribs = era1[0].contributions_map()
    ref = [b for b in batches_of(net.nodes[4]) if b.era == 1 and b.contributions]
    assert ref == era1


def test_encryption_schedule_change():
    net = make_network(4, schedule=EncryptionSchedule.never())
    es = EncryptionSchedule.every_nth_epoch(2)
    for nid in net.node_ids():
        net.send_input(nid, ChangeInput(Change.encryption_schedule(es)))
    drive_epoch(net, lambda nid: b"z")
    for _ in range(4):
        if all(net.nodes[nid].algorithm.era == 1 for nid in net.node_ids()):
            break
        drive_epoch(net, lambda nid: b"w")
    for nid in net.node_ids():
        algo = net.nodes[nid].algorithm
        assert algo.era == 1
        assert algo.encryption_schedule.kind == "nth"
        assert algo.is_validator()  # same keys, new era

"""Property-based network-dimension coverage + determinism.

Reference: ``tests/net/proptest.rs :: NetworkDimension`` — (n, f) pairs
with f ≤ ⌊(n−1)/3⌋ sampled by proptest; and the determinism discipline of
SURVEY §5 ("race detection"): same seed ⇒ bit-identical full message trace.
"""

import random

import pytest

pytest.importorskip("hypothesis")
from hypothesis import given, settings, strategies as st  # noqa: E402

from hbbft_tpu.netinfo import NetworkInfo
from hbbft_tpu.protocols import wire
from hbbft_tpu.protocols.binary_agreement import BinaryAgreement
from hbbft_tpu.protocols.broadcast import Broadcast
from hbbft_tpu.protocols.honey_badger import (
    Batch,
    EncryptionSchedule,
    HoneyBadger,
)
from hbbft_tpu.protocols.subset import Contribution, Done, Subset
from hbbft_tpu.sim import NetBuilder, RandomAdversary, ReorderingAdversary

_INFO_CACHE = {}


def infos_for(n, seed=21):
    key = (n, seed)
    if key not in _INFO_CACHE:
        _INFO_CACHE[key] = NetworkInfo.generate_map(
            list(range(n)), random.Random(seed)
        )
    return _INFO_CACHE[key]


def network_dimension():
    """(n, f) with 1 ≤ n ≤ 10 and f ≤ ⌊(n−1)/3⌋, like the reference's
    proptest ``NetworkDimension`` strategy."""
    return st.integers(min_value=1, max_value=10).flatmap(
        lambda n: st.tuples(
            st.just(n), st.integers(min_value=0, max_value=(n - 1) // 3)
        )
    )


@settings(max_examples=12, deadline=None)
@given(dim=network_dimension(), seed=st.integers(0, 2**16))
def test_broadcast_any_dimension(dim, seed):
    n, f = dim
    infos = infos_for(n)
    net = (
        NetBuilder(list(range(n)))
        .num_faulty(f)
        .adversary(ReorderingAdversary(seed=seed))
        .using_step(lambda nid: Broadcast(infos[nid], n - 1))
    )
    # proposer is the last id — never in the first-f faulty set unless n
    # small; faulty here means adversary-routed, not silent
    net.send_input(n - 1, b"dim value")
    net.run_to_quiescence()
    correct = [nid for nid in net.node_ids() if not net.nodes[nid].is_faulty]
    decided = [
        tuple(net.nodes[nid].outputs) for nid in correct if net.nodes[nid].outputs
    ]
    assert len(set(decided)) <= 1
    if n - 1 not in [nid for nid in net.node_ids() if net.nodes[nid].is_faulty]:
        assert all(d == (b"dim value",) for d in decided)
        assert len(decided) == len(correct)


@settings(max_examples=8, deadline=None)
@given(dim=network_dimension(), seed=st.integers(0, 2**16))
def test_binary_agreement_any_dimension(dim, seed):
    n, f = dim
    infos = infos_for(n)
    rng = random.Random(seed)
    net = (
        NetBuilder(list(range(n)))
        .adversary(ReorderingAdversary(seed=seed))
        .crank_limit(500_000)
        .using_step(lambda nid: BinaryAgreement(infos[nid], b"dim", 0))
    )
    inputs = {nid: rng.random() < 0.5 for nid in range(n)}
    for nid, b in inputs.items():
        net.send_input(nid, b)
    net.run_to_quiescence()
    decisions = {
        net.nodes[nid].outputs[0]
        for nid in net.node_ids()
        if net.nodes[nid].outputs
    }
    assert len(decisions) == 1
    if len(set(inputs.values())) == 1:
        assert decisions == set(inputs.values())


@settings(max_examples=5, deadline=None)
@given(dim=network_dimension(), seed=st.integers(0, 2**16))
def test_subset_any_dimension(dim, seed):
    n, f = dim
    infos = infos_for(n)
    net = (
        NetBuilder(list(range(n)))
        .adversary(ReorderingAdversary(seed=seed))
        .crank_limit(1_000_000)
        .using_step(lambda nid: Subset(infos[nid], session_id=b"dim-acs"))
    )
    for nid in range(n):
        net.send_input(nid, b"contrib-%d" % nid)
    net.run_to_quiescence()
    per_node = []
    for nid in net.node_ids():
        contribs = {
            (o.proposer_id, o.value)
            for o in net.nodes[nid].outputs
            if isinstance(o, Contribution)
        }
        assert any(isinstance(o, Done) for o in net.nodes[nid].outputs), nid
        per_node.append(frozenset(contribs))
    assert len(set(per_node)) == 1  # same accepted set everywhere
    assert len(per_node[0]) >= n - f


def _run_traced_hb(n, seed):
    """Run one HB epoch recording the full canonical message trace."""
    infos = infos_for(n)
    net = (
        NetBuilder(list(range(n)))
        .adversary(RandomAdversary(seed=seed))
        .using_step(
            lambda nid: HoneyBadger.builder(infos[nid])
            .session_id(b"det")
            .encryption_schedule(EncryptionSchedule.always())
            .rng(random.Random(seed * 1000 + nid))
            .build()
        )
    )
    for nid in net.node_ids():
        net.send_input(nid, b"det-contrib-%d" % nid)
    trace = []
    while net.queue:
        m = net.crank()
        if m is not None:
            trace.append(
                (m.sender, m.to, wire.encode_message(m.payload))
            )
    batches = {
        nid: [o for o in net.nodes[nid].outputs if isinstance(o, Batch)]
        for nid in net.node_ids()
    }
    return trace, batches


def test_same_seed_identical_full_trace():
    """Determinism is the race detector (SURVEY §5): two runs from one seed
    must produce byte-identical message traces and outputs."""
    t1, b1 = _run_traced_hb(4, seed=5)
    t2, b2 = _run_traced_hb(4, seed=5)
    assert t1 == t2
    assert b1 == b2
    assert len(t1) > 100
    # and a different seed takes a different path (sanity that the trace
    # comparison is not vacuous)
    t3, _ = _run_traced_hb(4, seed=6)
    assert t3 != t1

"""GF(2^8) field tests: axioms, table consistency, bit-plane lowering."""

import numpy as np
import pytest

from hbbft_tpu.ops import gf256


def test_exp_log_roundtrip():
    for a in range(1, 256):
        assert gf256.GF_EXP[gf256.GF_LOG[a]] == a


def test_mul_axioms():
    rng = np.random.RandomState(1)
    a = rng.randint(0, 256, 200).astype(np.uint8)
    b = rng.randint(0, 256, 200).astype(np.uint8)
    c = rng.randint(0, 256, 200).astype(np.uint8)
    # commutative, distributive over XOR
    assert np.array_equal(gf256.gf_mul(a, b), gf256.gf_mul(b, a))
    assert np.array_equal(
        gf256.gf_mul(a, b ^ c), gf256.gf_mul(a, b) ^ gf256.gf_mul(a, c)
    )
    # identity and zero
    assert np.array_equal(gf256.gf_mul(a, np.uint8(1)), a)
    assert np.all(gf256.gf_mul(a, np.uint8(0)) == 0)


def test_mul_matches_carryless_reference():
    def slow_mul(a, b):
        r = 0
        while b:
            if b & 1:
                r ^= a
            b >>= 1
            a <<= 1
            if a & 0x100:
                a ^= gf256.GF_POLY
        return r

    rng = np.random.RandomState(2)
    for _ in range(300):
        a, b = int(rng.randint(256)), int(rng.randint(256))
        assert int(gf256.gf_mul(a, b)) == slow_mul(a, b)


def test_inverse():
    a = np.arange(1, 256, dtype=np.uint8)
    assert np.all(gf256.gf_mul(a, gf256.gf_inv(a)) == 1)
    with pytest.raises(ZeroDivisionError):
        gf256.gf_inv(0)


def test_matrix_inverse():
    rng = np.random.RandomState(3)
    for n in (1, 2, 5, 16):
        while True:
            M = rng.randint(0, 256, (n, n)).astype(np.uint8)
            try:
                Minv = gf256.gf_inv_matrix_np(M)
                break
            except np.linalg.LinAlgError:
                continue
        assert np.array_equal(
            gf256.gf_matmul_np(M, Minv), np.eye(n, dtype=np.uint8)
        )


def test_bitplane_matches_table_matmul():
    import jax.numpy as jnp

    rng = np.random.RandomState(4)
    r, k, B = 6, 4, 33
    M = rng.randint(0, 256, (r, k)).astype(np.uint8)
    D = rng.randint(0, 256, (k, B)).astype(np.uint8)
    expected = gf256.gf_matmul_np(M, D)  # (r, B)

    bitmat = gf256.gf_matrix_to_bits(M)
    out = gf256.gf_apply_bitmatrix(jnp.asarray(D.T), jnp.asarray(bitmat))  # (B, r)
    assert np.array_equal(np.asarray(out).T, expected)


def test_bitplane_batched():
    import jax
    import jax.numpy as jnp

    rng = np.random.RandomState(5)
    r, k, B = 3, 5, 16
    M = rng.randint(0, 256, (r, k)).astype(np.uint8)
    D = rng.randint(0, 256, (7, 2, B, k)).astype(np.uint8)
    bitmat = jnp.asarray(gf256.gf_matrix_to_bits(M))
    out = jax.jit(lambda d: gf256.gf_apply_bitmatrix(d, bitmat))(jnp.asarray(D))
    assert out.shape == (7, 2, B, r)
    for i in range(7):
        for j in range(2):
            expected = gf256.gf_matmul_np(M, D[i, j].T).T
            assert np.array_equal(np.asarray(out[i, j]), expected)


def test_gf_mul_jnp_matches_host():
    import jax.numpy as jnp

    rng = np.random.RandomState(6)
    a = rng.randint(0, 256, 500).astype(np.uint8)
    b = rng.randint(0, 256, 500).astype(np.uint8)
    out = gf256.gf_mul_jnp(jnp.asarray(a), jnp.asarray(b))
    assert np.array_equal(np.asarray(out), gf256.gf_mul(a, b))

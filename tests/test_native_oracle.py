"""C++ oracle vs Python/numpy host implementations (bit-exactness)."""

import hashlib

import numpy as np
import pytest

from hbbft_tpu.ops import gf256, rs

oracle = pytest.importorskip("hbbft_tpu.native").get_oracle()


def test_gf_mul_matches():
    rng = np.random.RandomState(0)
    a = rng.randint(0, 256, 1000).astype(np.uint8)
    b = rng.randint(0, 256, 1000).astype(np.uint8)
    assert np.array_equal(oracle.gf_mul(a, b), gf256.gf_mul(a, b))


def test_gf_matmul_matches():
    rng = np.random.RandomState(1)
    A = rng.randint(0, 256, (7, 5)).astype(np.uint8)
    B = rng.randint(0, 256, (5, 11)).astype(np.uint8)
    assert np.array_equal(oracle.gf_matmul(A, B), gf256.gf_matmul_np(A, B))


def test_gf_invert_matches():
    rng = np.random.RandomState(2)
    M = rng.randint(0, 256, (6, 6)).astype(np.uint8)
    try:
        expected = gf256.gf_inv_matrix_np(M)
    except np.linalg.LinAlgError:
        pytest.skip("singular sample")
    assert np.array_equal(oracle.gf_invert(M), expected)


def test_rs_matrix_matches():
    coder = rs.ReedSolomon(4, 3)
    assert np.array_equal(oracle.rs_matrix(4, 7), coder.matrix)


def test_rs_encode_matches():
    rng = np.random.RandomState(3)
    coder = rs.ReedSolomon(5, 4)
    data = rng.randint(0, 256, (5, 13)).astype(np.uint8)
    assert np.array_equal(oracle.rs_encode(data, 9), coder.encode_np(data))


def test_rs_reconstruct_matches():
    rng = np.random.RandomState(4)
    coder = rs.ReedSolomon(4, 4)
    data = rng.randint(0, 256, (4, 9)).astype(np.uint8)
    full = [bytes(s) for s in coder.encode_np(data)]
    holed = [None, full[1], None, full[3], full[4], None, full[6], None]
    assert oracle.rs_reconstruct(4, holed) == coder.reconstruct_np(holed)


def test_sha3_matches_hashlib():
    for msg in [b"", b"abc", b"x" * 135, b"y" * 136, b"z" * 1000]:
        assert oracle.sha3_256(msg) == hashlib.sha3_256(msg).digest()


def test_sha3_batch():
    rng = np.random.RandomState(5)
    msgs = rng.randint(0, 256, (6, 50)).astype(np.uint8)
    out = oracle.sha3_256_batch(msgs)
    for i in range(6):
        assert out[i].tobytes() == hashlib.sha3_256(msgs[i].tobytes()).digest()


def test_keccak_permutation_vs_jnp():
    import jax.numpy as jnp

    from hbbft_tpu.ops import keccak

    rng = np.random.RandomState(6)
    state = rng.randint(0, 2**63, 25).astype(np.uint64)
    expected = oracle.keccak_f1600(state)
    hi = jnp.asarray((state >> np.uint64(32)).astype(np.uint32))
    lo = jnp.asarray((state & np.uint64(0xFFFFFFFF)).astype(np.uint32))
    ohi, olo = keccak.keccak_f1600(hi, lo)
    got = (np.asarray(ohi).astype(np.uint64) << np.uint64(32)) | np.asarray(
        olo
    ).astype(np.uint64)
    assert np.array_equal(got, expected)

"""The Pallas lazy-field kernel (ops/pallas_fp.py) vs the fp381 host truth.

Runs the Pallas INTERPRETER on the CPU backend — the same kernel code path
that compiles via Mosaic on a real chip (where it was verified too; see the
module docstring's measured numbers)."""

import random

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from hbbft_tpu.ops import fp381 as F  # noqa: E402
from hbbft_tpu.ops.pallas_fp import fp_mul_lazy_pallas  # noqa: E402


def test_pallas_mul_matches_host():
    rng = random.Random(11)
    R = 128
    xs = [rng.randrange(F.P) for _ in range(R)]
    ys = [rng.randrange(F.P) for _ in range(R)]
    a = jnp.asarray(F.ints_to_limbs_batch(xs).T.copy())
    b = jnp.asarray(F.ints_to_limbs_batch(ys).T.copy())
    out = np.asarray(fp_mul_lazy_pallas(a, b, interpret=True))
    got = F.limbs_to_ints_batch(out.T)
    for i in range(R):
        assert got[i] % F.P == xs[i] * ys[i] % F.P, i
    # lazy digit invariant: every digit in [0, 2^13]
    assert out.min() >= 0 and out.max() <= (1 << F.LIMB_BITS)


def test_pallas_mul_matches_fp381_lazy_digits_semantics():
    # same VALUES as fp381.fp_mul_lazy (both are valid lazy encodings;
    # compare the represented residues, not raw digits)
    rng = random.Random(12)
    R = 64
    xs = [rng.randrange(F.P) for _ in range(R)]
    ys = [rng.randrange(F.P) for _ in range(R)]
    rows = F.ints_to_limbs_batch(xs)
    rows_b = F.ints_to_limbs_batch(ys)
    ref = F.limbs_to_ints_batch(
        np.asarray(F.fp_mul_lazy(jnp.asarray(rows), jnp.asarray(rows_b)))
    )
    out = np.asarray(
        fp_mul_lazy_pallas(
            jnp.asarray(rows.T.copy()), jnp.asarray(rows_b.T.copy()),
            interpret=True,
        )
    )
    got = F.limbs_to_ints_batch(out.T)
    for i in range(R):
        assert got[i] % F.P == ref[i] % F.P, i

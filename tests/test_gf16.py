"""GF(2^16) field / RS coder / large-N batched RBC.

Networks above 256 nodes exceed GF(2^8) (the reference's erasure crate caps
total shards at 256); these cover the GF(2^16) replacement and the
full-delivery large-N simulator path built on it.
"""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from hbbft_tpu.ops import gf16
from hbbft_tpu.ops.rs import ReedSolomon16, for_n_f


def test_field_axioms_and_tables():
    rng = np.random.default_rng(0)
    a = rng.integers(0, 1 << 16, size=200, dtype=np.uint16)
    b = rng.integers(1, 1 << 16, size=200, dtype=np.uint16)
    c = rng.integers(0, 1 << 16, size=200, dtype=np.uint16)
    assert (gf16.gf_mul(a, np.ones_like(b)) == a).all()
    assert (gf16.gf_mul(a, np.zeros_like(b)) == 0).all()
    assert (gf16.gf_mul(gf16.gf_mul(a, b), gf16.gf_inv(b)) == a).all()
    # distributivity over xor
    assert (
        gf16.gf_mul(a, b ^ c) == (gf16.gf_mul(a, b) ^ gf16.gf_mul(a, c))
    ).all()


def test_vandermonde_matches_gf_pow():
    V = gf16.vandermonde(33, 9)
    for r in (0, 1, 2, 17, 32):
        for c in (0, 1, 5, 8):
            assert V[r, c] == gf16.gf_pow(r, c), (r, c)


def test_rs16_encode_reconstruct_roundtrip():
    rs = ReedSolomon16(5, 4)
    rng = np.random.default_rng(1)
    data = rng.integers(0, 256, size=(5, 10), dtype=np.uint8)
    shards = rs.encode_np(data)
    np.testing.assert_array_equal(shards[:5], data)
    # reconstruct from a parity-heavy survivor set
    use = (1, 4, 5, 7, 8)
    rec = rs.reconstruct_data_np(shards[list(use)], use)
    np.testing.assert_array_equal(rec, data)
    # device encode == host encode
    dev = jax.jit(rs.encode_jax)(jnp.asarray(data[None]))
    np.testing.assert_array_equal(np.asarray(dev[0]), shards)


def test_for_n_f_picks_field_by_size():
    assert for_n_f(256, 85).__class__.__name__ == "ReedSolomon"
    assert for_n_f(300, 99).__class__.__name__ == "ReedSolomon16"


def test_large_rbc_full_delivery_and_tamper():
    from hbbft_tpu.parallel.rbc import BatchedRbc, frame_values, unframe_value

    n = 300  # > 256 → GF(2^16) large path
    f = (n - 1) // 3
    rbc = BatchedRbc(n, f)
    assert rbc.large
    values = [b"big-%d" % p for p in range(n)]
    data = frame_values(values, rbc.k)
    out = rbc.run(jnp.asarray(data))
    assert out["delivered"].all()
    assert list(out["data_receivers"]) == [0]
    for p in (0, 1, 137, n - 1):
        assert unframe_value(out["data"][0, p]) == values[p]

    # value_tamper: corrupt proposer 5's shard to node 2 in flight — the
    # god-view verify rejects that echo; n-1 remain, still delivered
    vt = np.zeros((n, n, data.shape[-1]), dtype=np.uint8)
    vt[5, 2, 0] = 0xFF
    out2 = rbc.run(jnp.asarray(data), value_tamper=jnp.asarray(vt))
    assert out2["delivered"].all()
    assert out2["echo_count"][0, 5] == n - 1
    assert unframe_value(out2["data"][0, 5]) == values[5]

    # masks are explicitly unsupported at this scale
    with pytest.raises(NotImplementedError):
        rbc.run(jnp.asarray(data), value_mask=jnp.ones((n, n), bool))


def test_large_acs_agreement():
    from hbbft_tpu.parallel.acs import BatchedAcs
    from hbbft_tpu.parallel.rbc import unframe_value

    n = 300
    acs = BatchedAcs(n, (n - 1) // 3)
    values = [b"v%d" % p for p in range(n)]
    out = acs.run(values)
    acc = out["accepted"]
    assert (acc == acc[0]).all() and acc[0].all()
    assert unframe_value(out["data"][0, 42]) == values[42]

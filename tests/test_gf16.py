"""GF(2^16) field / RS coder / large-N batched RBC.

Networks above 256 nodes exceed GF(2^8) (the reference's erasure crate caps
total shards at 256); these cover the GF(2^16) replacement and the
full-delivery large-N simulator path built on it.
"""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from hbbft_tpu.ops import gf16
from hbbft_tpu.ops.rs import ReedSolomon16, for_n_f


def test_field_axioms_and_tables():
    rng = np.random.default_rng(0)
    a = rng.integers(0, 1 << 16, size=200, dtype=np.uint16)
    b = rng.integers(1, 1 << 16, size=200, dtype=np.uint16)
    c = rng.integers(0, 1 << 16, size=200, dtype=np.uint16)
    assert (gf16.gf_mul(a, np.ones_like(b)) == a).all()
    assert (gf16.gf_mul(a, np.zeros_like(b)) == 0).all()
    assert (gf16.gf_mul(gf16.gf_mul(a, b), gf16.gf_inv(b)) == a).all()
    # distributivity over xor
    assert (
        gf16.gf_mul(a, b ^ c) == (gf16.gf_mul(a, b) ^ gf16.gf_mul(a, c))
    ).all()


def test_vandermonde_matches_gf_pow():
    V = gf16.vandermonde(33, 9)
    for r in (0, 1, 2, 17, 32):
        for c in (0, 1, 5, 8):
            assert V[r, c] == gf16.gf_pow(r, c), (r, c)


def test_rs16_encode_reconstruct_roundtrip():
    rs = ReedSolomon16(5, 4)
    rng = np.random.default_rng(1)
    data = rng.integers(0, 256, size=(5, 10), dtype=np.uint8)
    shards = rs.encode_np(data)
    np.testing.assert_array_equal(shards[:5], data)
    # reconstruct from a parity-heavy survivor set
    use = (1, 4, 5, 7, 8)
    rec = rs.reconstruct_data_np(shards[list(use)], use)
    np.testing.assert_array_equal(rec, data)
    # device encode == host encode
    dev = jax.jit(rs.encode_jax)(jnp.asarray(data[None]))
    np.testing.assert_array_equal(np.asarray(dev[0]), shards)


def test_for_n_f_picks_field_by_size():
    assert for_n_f(256, 85).__class__.__name__ == "ReedSolomon"
    assert for_n_f(300, 99).__class__.__name__ == "ReedSolomon16"


def test_large_rbc_full_delivery_and_tamper():
    from hbbft_tpu.parallel.rbc import BatchedRbc, frame_values, unframe_value

    n = 300  # > 256 → GF(2^16) large path
    f = (n - 1) // 3
    rbc = BatchedRbc(n, f)
    assert rbc.large
    values = [b"big-%d" % p for p in range(n)]
    data = frame_values(values, rbc.k)
    out = rbc.run(jnp.asarray(data))
    assert out["delivered"].all()
    assert list(out["data_receivers"]) == [0]
    for p in (0, 1, 137, n - 1):
        assert unframe_value(out["data"][0, p]) == values[p]

    # value_tamper: corrupt proposer 5's shard to node 2 in flight — the
    # god-view verify rejects that echo; n-1 remain, still delivered
    vt = np.zeros((n, n, data.shape[-1]), dtype=np.uint8)
    vt[5, 2, 0] = 0xFF
    out2 = rbc.run(jnp.asarray(data), value_tamper=jnp.asarray(vt))
    assert out2["delivered"].all()
    assert out2["echo_count"][0, 5] == n - 1
    assert unframe_value(out2["data"][0, 5]) == values[5]

    # masks at this scale take the GF(2^16) masked path (separate test)


def test_large_acs_agreement():
    from hbbft_tpu.parallel.acs import BatchedAcs
    from hbbft_tpu.parallel.rbc import unframe_value

    n = 300
    acs = BatchedAcs(n, (n - 1) // 3)
    values = [b"v%d" % p for p in range(n)]
    out = acs.run(values)
    acc = out["accepted"]
    assert (acc == acc[0]).all() and acc[0].all()
    assert unframe_value(out["data"][0, 42]) == values[42]


def test_device_field_ops_match_host():
    """gf16 device mul/inv and batched Gauss–Jordan vs the host tables."""
    rng = np.random.default_rng(7)
    a = rng.integers(0, 1 << 16, size=500, dtype=np.uint16)
    b = rng.integers(0, 1 << 16, size=500, dtype=np.uint16)
    got = np.asarray(gf16.gf_mul_jnp(jnp.asarray(a), jnp.asarray(b)))
    assert np.array_equal(got, gf16.gf_mul(a, b))
    nz = a[a != 0]
    got_inv = np.asarray(gf16.gf_inv_jnp(jnp.asarray(nz)))
    assert np.array_equal(got_inv, gf16.gf_inv(nz))

    k = 6
    M = rng.integers(0, 1 << 16, size=(5, k, k), dtype=np.uint16)
    M[4] = 0  # singular member of the batch
    inv_dev, ok = (np.asarray(x) for x in gf16.gf_inv_matrix_jnp(M))
    assert not ok[4]
    for i in range(4):
        if not ok[i]:
            continue
        want = gf16.gf_inv_matrix_np(M[i])
        assert np.array_equal(inv_dev[i], want), i
    assert ok[:4].any()  # random 6×6 over GF(2^16): singulars are rare

    bits = np.asarray(gf16.gf_matrix_to_bits_jnp(jnp.asarray(M[:2])))
    for i in range(2):
        assert np.array_equal(bits[i], gf16.gf_matrix_to_bits(M[i]))


def test_large_rbc_masked_adversarial():
    """Masked adversarial RBC beyond the GF(2^8) boundary: survivor-set
    dependent decode with the GF(2^16) device Gauss–Jordan.

    Proposer 1 commits an inconsistent codeword (parity row k+3 corrupted
    pre-commit).  Receiver 5's echo set is cut so its first-k survivor set
    leans on that row: it must reconstruct garbage, fail the root re-check,
    and flag the proposer, while a full-echo receiver delivers — the same
    deliver/fault split the small-N masked path and the object-mode oracle
    exhibit (reference: ``Broadcast::compute_output`` re-encode check).
    """
    from hbbft_tpu.parallel.rbc import BatchedRbc, frame_values, unframe_value

    n, f = 272, 90
    rbc = BatchedRbc(n, f)
    assert rbc.large
    k = rbc.k
    P = 2
    values = [bytes([40 + p]) * 33 for p in range(P)]
    data = jnp.asarray(frame_values(values, k))

    tam = np.zeros((P, n, data.shape[-1]), dtype=np.uint8)
    tam[1, k + 3, 0] = 0xA5
    echo_mask = np.ones((n, n, P), dtype=bool)
    echo_mask[0:4, 5, :] = False  # receiver 5 loses data sources 0..3
    receivers = jnp.asarray([0, 5])

    shards, root, proofs, pmask = rbc.propose(
        data, codeword_tamper=jnp.asarray(tam)
    )
    out = rbc.run_from_proposal(
        shards, root, proofs, pmask,
        echo_mask=jnp.asarray(echo_mask), receivers=receivers,
    )
    d = np.asarray(out["delivered"])
    fl = np.asarray(out["fault"])
    assert d[0].all()  # receiver 0: full echoes → delivers both
    assert unframe_value(np.asarray(out["data"][0, 1])) == values[1]
    assert d[1, 0] and not d[1, 1]  # receiver 5: p0 ok, p1 poisoned
    assert fl[1, 1] and not fl[1, 0]
    assert unframe_value(np.asarray(out["data"][1, 0])) == values[0]

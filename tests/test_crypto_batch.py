"""Randomized-linear-combination batch share verification (device MSM path).

Cross-checks hbbft_tpu.crypto.batch against per-share host verification:
valid batches accept, any single corrupted share rejects.
"""

import random

import pytest

pytest.importorskip("jax")

from hbbft_tpu.crypto.batch import (
    batch_verify_dec_shares,
    batch_verify_sig_shares,
)
from hbbft_tpu.crypto.tc import SecretKeySet


@pytest.fixture(scope="module")
def keys():
    rng = random.Random(5)
    sks = SecretKeySet.random(2, rng)
    return rng, sks, sks.public_keys()


def test_sig_share_batch_accepts_valid_and_rejects_bad(keys):
    rng, sks, pks = keys
    msg = b"round 3 coin"
    pairs = [
        (pks.public_key_share(i), sks.secret_key_share(i).sign(msg))
        for i in range(6)
    ]
    # host per-share ground truth
    for pk, s in pairs:
        assert pk.verify(s, msg)
    assert batch_verify_sig_shares(pairs, msg, rng) is True
    # swap one share to another node's: each individual is valid BLS but
    # not for that pk — the batch must reject
    bad = pairs[:2] + [(pairs[2][0], pairs[3][1])] + pairs[3:]
    assert batch_verify_sig_shares(bad, msg, rng) is False
    assert batch_verify_sig_shares([], msg, rng) is True


def test_dec_share_batch_accepts_valid_and_rejects_bad(keys):
    rng, sks, pks = keys
    ct = pks.public_key().encrypt(b"secret payload", rng)
    pairs = [
        (pks.public_key_share(i), sks.secret_key_share(i).decrypt_share(ct))
        for i in range(5)
    ]
    for pk, d in pairs:
        assert pk.verify_decryption_share(d, ct)
    assert batch_verify_dec_shares(pairs, ct, rng) is True
    bad = pairs[:1] + [(pairs[1][0], pairs[2][1])] + pairs[2:]
    assert batch_verify_dec_shares(bad, ct, rng) is False


def test_batch_tpke_decrypt_host_and_device_paths(keys):
    from hbbft_tpu.crypto import batch as BT

    rng, sks, pks = keys
    pk = pks.public_key()
    msgs = [b"m%d" % i * (i + 1) for i in range(4)]
    cts = [pk.encrypt(m, rng) for m in msgs]
    shares = [(i, sks.secret_key_share(i)) for i in range(pks.threshold() + 2)]

    assert BT.batch_tpke_decrypt(pks, cts, shares) == msgs  # host path
    old = BT.DEVICE_DECRYPT_MIN_BATCH
    try:
        BT.DEVICE_DECRYPT_MIN_BATCH = 1  # force the device ladder path
        assert BT.batch_tpke_decrypt(pks, cts, shares) == msgs
        assert BT.batch_tpke_decrypt(pks, [], shares) == []
    finally:
        BT.DEVICE_DECRYPT_MIN_BATCH = old

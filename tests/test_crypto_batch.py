"""Randomized-linear-combination batch share verification (device MSM path).

Cross-checks hbbft_tpu.crypto.batch against per-share host verification:
valid batches accept, any single corrupted share rejects.
"""

import random

import pytest

pytest.importorskip("jax")

from hbbft_tpu.crypto.batch import (
    batch_verify_dec_shares,
    batch_verify_sig_shares,
)
from hbbft_tpu.crypto.tc import SecretKeySet


@pytest.fixture(scope="module")
def keys():
    rng = random.Random(5)
    sks = SecretKeySet.random(2, rng)
    return rng, sks, sks.public_keys()


def test_sig_share_batch_accepts_valid_and_rejects_bad(keys):
    rng, sks, pks = keys
    msg = b"round 3 coin"
    pairs = [
        (pks.public_key_share(i), sks.secret_key_share(i).sign(msg))
        for i in range(6)
    ]
    # host per-share ground truth
    for pk, s in pairs:
        assert pk.verify(s, msg)
    assert batch_verify_sig_shares(pairs, msg, rng) is True
    # swap one share to another node's: each individual is valid BLS but
    # not for that pk — the batch must reject
    bad = pairs[:2] + [(pairs[2][0], pairs[3][1])] + pairs[3:]
    assert batch_verify_sig_shares(bad, msg, rng) is False
    assert batch_verify_sig_shares([], msg, rng) is True


def test_dec_share_batch_accepts_valid_and_rejects_bad(keys):
    rng, sks, pks = keys
    ct = pks.public_key().encrypt(b"secret payload", rng)
    pairs = [
        (pks.public_key_share(i), sks.secret_key_share(i).decrypt_share(ct))
        for i in range(5)
    ]
    for pk, d in pairs:
        assert pk.verify_decryption_share(d, ct)
    assert batch_verify_dec_shares(pairs, ct, rng) is True
    bad = pairs[:1] + [(pairs[1][0], pairs[2][1])] + pairs[2:]
    assert batch_verify_dec_shares(bad, ct, rng) is False


def test_batch_tpke_decrypt_host_and_device_paths(keys):
    from hbbft_tpu.crypto import batch as BT

    rng, sks, pks = keys
    pk = pks.public_key()
    msgs = [b"m%d" % i * (i + 1) for i in range(4)]
    cts = [pk.encrypt(m, rng) for m in msgs]
    shares = [(i, sks.secret_key_share(i)) for i in range(pks.threshold() + 2)]

    assert BT.batch_tpke_decrypt(pks, cts, shares) == msgs  # host path
    old = BT.DEVICE_DECRYPT_MIN_BATCH
    try:
        BT.DEVICE_DECRYPT_MIN_BATCH = 1  # force the device ladder path
        assert BT.batch_tpke_decrypt(pks, cts, shares) == msgs
        assert BT.batch_tpke_decrypt(pks, [], shares) == []
    finally:
        BT.DEVICE_DECRYPT_MIN_BATCH = old


def test_g2_mul_batch_matches_host(keys):
    """The GLS ψ²-split device G2 ladder (the W-ladder of the split
    encrypt) against the host ground truth, over full-range scalars
    including the split edges."""
    from hbbft_tpu.crypto import batch as BT
    from hbbft_tpu.crypto import bls12_381 as c

    pts = [c.hash_g2(b"g2mb-0"), c.hash_g2(b"g2mb-1")]
    scalars = [c.R - 1, c.LAMBDA_G2 + 3]
    out = BT._CACHE.g2_mul_batch(pts, scalars)
    for p, s, o in zip(pts, scalars, out):
        assert c.g2_eq(o, c.g2_mul(p, s))
    # infinity base rides through index-aligned
    out = BT._CACHE.g2_mul_batch([None, pts[0]], [5, 7])
    assert out[0] is None
    assert c.g2_eq(out[1], c.g2_mul(pts[0], 7))


def test_tpke_encrypt_device_path_matches_native(keys, monkeypatch):
    """Tentpole cross-path equality: the SPLIT device encrypt (G1/G2
    ladders as device MSMs, hash-to-G2 in the native batch call) must be
    BYTE-IDENTICAL to the one-call native encrypt on randomized inputs —
    same rng draw order, so the same scalars."""
    import random

    from hbbft_tpu.crypto import tc

    rng, sks, pks = keys
    pk = pks.public_key()
    msgs = [
        bytes(rng.getrandbits(8) for _ in range(ln)) for ln in (0, 1, 33)
    ][:2]  # 2 msgs: the ladders reuse the suite's g1@8 jit key

    nat_cts = tc.tpke_encrypt_batch(
        pk, msgs, random.Random(2024), backend="native"
    )
    dev_cts = tc.tpke_encrypt_batch(
        pk, msgs, random.Random(2024), backend="device"
    )
    assert [a.to_bytes() for a in nat_cts] == [
        b.to_bytes() for b in dev_cts
    ]
    # and the env knob routes the same way
    monkeypatch.setenv("HBBFT_ENCRYPT_BACKEND", "device")
    env_cts = tc.tpke_encrypt_batch(pk, msgs, random.Random(2024))
    assert [a.to_bytes() for a in env_cts] == [
        b.to_bytes() for b in dev_cts
    ]
    # the ciphertexts are REAL: they decrypt under the threshold key
    shares = [(i, sks.secret_key_share(i)) for i in range(pks.threshold() + 1)]
    from hbbft_tpu.crypto import batch as BT

    assert BT.batch_tpke_decrypt(pks, dev_cts, shares) == msgs


def test_tpke_encrypt_device_chunk_pipeline(keys):
    """The chunked overlap structure (dispatch all G1 ladders, then per
    chunk hash + dispatch G2 while later chunks hash) must not change a
    single byte vs the unchunked path."""
    import random

    from hbbft_tpu.crypto import batch as BT
    from hbbft_tpu.crypto import tc

    rng, sks, pks = keys
    pk = pks.public_key()
    msgs = [b"chunk-%d" % i * (i + 1) for i in range(4)]
    nat_cts = tc.tpke_encrypt_batch(
        pk, msgs, random.Random(77), backend="native"
    )
    old = BT.DEVICE_ENCRYPT_CHUNK
    try:
        BT.DEVICE_ENCRYPT_CHUNK = 2  # 4 msgs → 2 chunks in flight
        dev_cts = tc.tpke_encrypt_batch(
            pk, msgs, random.Random(77), backend="device"
        )
    finally:
        BT.DEVICE_ENCRYPT_CHUNK = old
    assert [a.to_bytes() for a in nat_cts] == [
        b.to_bytes() for b in dev_cts
    ]


def test_batch_tpke_check_decrypt_fused(keys):
    """The fused native parse+decrypt (one C call doing the full
    Ciphertext.from_bytes wire checks then the master-scalar decrypt)
    matches the per-item path byte-for-byte and rejects exactly what
    from_bytes rejects."""
    from hbbft_tpu.crypto import batch as BT
    from hbbft_tpu.crypto import bls12_381 as c
    from hbbft_tpu.crypto import tc

    rng, sks, pks = keys
    pk = pks.public_key()
    msgs = [b"fused%d" % i * (i + 1) for i in range(5)] + [b""]
    cts = tc.tpke_encrypt_batch(pk, msgs, rng)
    payloads = [ct.to_bytes() for ct in cts]
    shares = [(i, sks.secret_key_share(i)) for i in range(pks.threshold() + 2)]

    assert BT.batch_tpke_check_decrypt(pks, payloads, shares) == msgs
    assert BT.batch_tpke_check_decrypt(pks, [], shares) == []

    # U with an infinity flag decrypts identically on both paths
    p_inf = tc.Ciphertext(None, b"payload", cts[0].w).to_bytes()
    assert BT.batch_tpke_check_decrypt(pks, [p_inf], shares) == \
        BT.batch_tpke_decrypt(
            pks, [tc.Ciphertext.from_bytes(p_inf)], shares
        )

    def rejects(payload):
        with pytest.raises(ValueError):
            BT.batch_tpke_check_decrypt(
                pks, [payloads[0], payload], shares
            )

    bad_u = bytearray(payloads[1]); bad_u[5] ^= 1          # off-curve U
    rejects(bytes(bad_u))
    bad_w = bytearray(payloads[1]); bad_w[97 + 5] ^= 1     # off-curve W
    rejects(bytes(bad_w))
    nc = bytearray(payloads[1])                            # non-canonical x
    nc[1:49] = c.P.to_bytes(48, "big")
    rejects(bytes(nc))
    bad_flag = bytearray(payloads[1]); bad_flag[0] = 7     # bad flag byte
    rejects(bytes(bad_flag))
    rejects(payloads[1][:100])                             # truncated

    # a non-subgroup but on-curve U must be rejected (the attack the
    # subgroup check exists for); build one by skipping cofactor clearing
    import hashlib

    ctr = 0
    while True:
        x = int.from_bytes(
            hashlib.sha3_256(b"nonsub%d" % ctr).digest() * 2, "big"
        ) % c.P
        ctr += 1
        y2 = (pow(x, 3, c.P) + 4) % c.P
        y = pow(y2, (c.P + 1) // 4, c.P)
        if (y * y) % c.P != y2:
            continue
        pt = (x, y, 1)
        if not c.g1_in_subgroup(pt):
            break
    evil = bytearray(payloads[1])
    evil[:97] = c.g1_to_bytes(pt)
    rejects(bytes(evil))

    # mixed exact / non-exact framing: a payload with trailing bytes (which
    # from_bytes tolerates by truncation) must decrypt via the straggler
    # path WITHOUT pushing the exact ones off the fused native path
    trailing = payloads[2] + b"\xEE"
    mixed = [payloads[0], trailing, payloads[1]]
    expect = [
        BT.batch_tpke_decrypt(
            pks, [tc.Ciphertext.from_bytes(p)], shares
        )[0]
        for p in mixed
    ]
    assert BT.batch_tpke_check_decrypt(pks, mixed, shares) == expect
    assert expect[1] == msgs[2]  # the trailing byte is outside vlen


def test_fused_decrypt_mutation_parity(keys):
    """Property sweep of the crypto wire boundary: for randomly mutated
    ciphertext payloads, the fused native path and the per-item Python
    path must agree EXACTLY — same plaintexts when accepted, rejection
    (ValueError) on the same inputs.  Guards the duplicated accept-set
    logic (flag/canonical/on-curve/subgroup/framing) against drift."""
    pytest.importorskip("hypothesis")
    from hypothesis import HealthCheck, given, settings
    from hypothesis import strategies as st

    from hbbft_tpu.crypto import batch as BT
    from hbbft_tpu.crypto import tc

    rng, sks, pks = keys
    pk = pks.public_key()
    base = [
        ct.to_bytes()
        for ct in tc.tpke_encrypt_batch(
            pk, [b"mut-%d" % i * (i + 1) for i in range(4)], rng
        )
    ]
    shares = [(i, sks.secret_key_share(i)) for i in range(pks.threshold() + 1)]

    def per_item(payloads):
        cts = [tc.Ciphertext.from_bytes(p) for p in payloads]
        return BT.batch_tpke_decrypt(pks, cts, shares)

    @settings(
        max_examples=25,
        deadline=None,
        suppress_health_check=[HealthCheck.too_slow],
    )
    @given(st.data())
    def sweep(data):
        payloads = []
        for i, b in enumerate(base):
            mode = data.draw(
                st.sampled_from(["keep", "flip", "trunc", "vlen"]),
                label=f"mode{i}",
            )
            p = bytearray(b)
            if mode == "flip":
                pos = data.draw(
                    st.integers(0, len(p) - 1), label=f"pos{i}"
                )
                p[pos] ^= 1 << data.draw(
                    st.integers(0, 7), label=f"bit{i}"
                )
            elif mode == "trunc":
                cut = data.draw(
                    st.integers(0, len(p) - 1), label=f"cut{i}"
                )
                p = p[:cut]
            elif mode == "vlen":
                delta = data.draw(
                    st.integers(-3, 3), label=f"d{i}"
                )
                v = max(0, int.from_bytes(p[290:294], "big") + delta)
                p[290:294] = v.to_bytes(4, "big")
            payloads.append(bytes(p))

        try:
            want = per_item(payloads)
            raised = None
        except (ValueError, IndexError) as e:
            want, raised = None, type(e)
        if raised is None:
            assert BT.batch_tpke_check_decrypt(pks, payloads, shares) == want
        else:
            with pytest.raises(raised):
                BT.batch_tpke_check_decrypt(pks, payloads, shares)

    sweep()

"""Epoch-phase tracing over a full 4-node QHB epoch (object mode).

The acceptance-shaped assertions: every phase the protocol must exercise
is present exactly once per epoch, spans are strictly ordered along the
protocol's causal chain, the epoch span covers all of them, and the JSONL
export round-trips.  Also covers the ``wire_size`` silent-zero fix."""

import json
import random

import pytest

from hbbft_tpu.obs.metrics import DEFAULT, Registry
from hbbft_tpu.obs.spans import PHASE_ORDER, SpanTracer, classify, phase_group
from hbbft_tpu.protocols.dynamic_honey_badger import DynamicHoneyBadger
from hbbft_tpu.protocols.honey_badger import EncryptionSchedule
from hbbft_tpu.protocols.queueing_honey_badger import (
    QhbBatch,
    QueueingHoneyBadger,
    TxInput,
)
from hbbft_tpu.sim import NetBuilder, NullAdversary


@pytest.fixture(scope="module")
def qhb_traced_run(shared_netinfo):
    """One 4-node QHB run with TPKE encryption and a SpanTracer per node,
    driven to quiescence — shared by the span-shape tests."""
    n = 4
    infos = shared_netinfo(4, 13)
    net = NetBuilder(list(range(n))).adversary(NullAdversary()).observe(
        lambda nid: SpanTracer(Registry(), node=nid)
    ).using_step(
        lambda nid: QueueingHoneyBadger(
            DynamicHoneyBadger(
                infos[nid], infos[nid].secret_key(),
                rng=random.Random(100 + nid),
                encryption_schedule=EncryptionSchedule.always(),
            ),
            batch_size=4, rng=random.Random(200 + nid),
        )
    )
    for i in range(8):
        net.send_input(i % n, TxInput(b"span-tx-%d" % i))
    net.run_to_quiescence()
    return net


# phases a fault-free encrypted epoch MUST contain (aba_coin only appears
# when a round survives to the every-third random-coin epoch; aba_term is
# delivery-order dependent)
REQUIRED = (
    "rbc_value", "rbc_echo", "rbc_ready",
    "aba_bval", "aba_aux", "aba_conf",
    "decrypt_share", "decrypt_combine", "epoch",
)


def test_every_phase_present_exactly_once_per_epoch(qhb_traced_run):
    net = qhb_traced_run
    for nid in net.node_ids():
        tracer = net.observers[nid]
        assert tracer.epochs_finalized >= 2
        epochs = sorted({(s.era, s.epoch) for s in tracer.finished})
        for era, epoch in epochs:
            spans = tracer.spans_for(era, epoch)
            names = [(s.name, s.round) for s in spans]
            # exactly one span per (phase, round)
            assert len(names) == len(set(names)), names
            present = {s.name for s in spans}
            for phase in REQUIRED:
                assert phase in present, (nid, era, epoch, present)
            # and nothing outside the documented phase vocabulary
            assert present <= set(PHASE_ORDER)


def test_spans_strictly_ordered_along_the_causal_chain(qhb_traced_run):
    net = qhb_traced_run
    tracer = net.observers[0]
    for era, epoch in sorted({(s.era, s.epoch) for s in tracer.finished}):
        spans = {(s.name, s.round): s
                 for s in tracer.spans_for(era, epoch)}

        def start(name, rnd=None):
            return spans[(name, rnd)].t_start

        # a Value strictly precedes the Echos it triggers, which strictly
        # precede the Readys, which precede round-0 BVal voting, …
        assert start("rbc_value") < start("rbc_echo") < start("rbc_ready")
        assert start("rbc_ready") < start("aba_bval", 0)
        assert start("aba_bval", 0) < start("aba_aux", 0)
        assert start("aba_aux", 0) < start("aba_conf", 0)
        assert start("aba_conf", 0) < start("decrypt_share")
        # the combine stretch starts where the last share landed
        assert spans[("decrypt_share", None)].t_end <= start(
            "decrypt_combine")
        # the epoch span covers everything
        ep = spans[("epoch", None)]
        for key, s in spans.items():
            if key[0] == "epoch":
                continue
            assert ep.t_start <= s.t_start and s.t_end <= ep.t_end, key
        # the finished deque is start-ordered within the epoch
        ordered = tracer.spans_for(era, epoch)
        assert all(a.t_start <= b.t_start
                   for a, b in zip(ordered, ordered[1:]))


def test_phase_durations_feed_registry_and_export_round_trips(
        qhb_traced_run):
    net = qhb_traced_run
    tracer = net.observers[1]
    reg = tracer.registry
    assert reg.get("hbbft_node_epochs_total").value() == (
        tracer.epochs_finalized
    )
    hist = reg.get("hbbft_phase_duration_seconds")
    counts = {labels["phase"]: child.count
              for labels, child in hist.series()}
    for phase in ("rbc_echo", "aba_conf", "decrypt_share"):
        assert counts[phase] == tracer.epochs_finalized
    # JSONL export parses back into the span dicts, in order
    lines = [json.loads(l) for l in
             tracer.export_jsonl().splitlines()]
    assert len(lines) == len(tracer.finished)
    for line, span in zip(lines, tracer.finished):
        assert line["name"] == span.name
        assert line["era"] == span.era and line["epoch"] == span.epoch
        assert line["duration_s"] == pytest.approx(span.duration_s,
                                                   abs=1e-5)
    # phase grouping used by bench.py --net and obs.top
    assert phase_group("rbc_echo") == "rbc"
    assert phase_group("aba_coin") == "coin"
    assert phase_group("aba_bval") == "aba"
    assert phase_group("decrypt_combine") == "decrypt"
    assert phase_group("dkg_rotation") == "dkg"


def test_classify_ignores_control_and_unknown_messages():
    from hbbft_tpu.protocols.sender_queue import AlgoMessage, EpochStarted

    assert classify(EpochStarted((0, 3))) is None
    assert classify(b"raw bytes") is None
    assert classify(AlgoMessage(msg=b"not a protocol message")) is None


def test_classify_unwraps_the_full_qhb_wrapper_chain():
    from hbbft_tpu.protocols.binary_agreement import BValMsg, CoinMsg
    from hbbft_tpu.protocols.broadcast import EchoHashMsg, ReadyMsg
    from hbbft_tpu.protocols.dynamic_honey_badger import HbWrap, KeyGenWrap
    from hbbft_tpu.protocols.honey_badger import SubsetWrap
    from hbbft_tpu.protocols.sender_queue import AlgoMessage
    from hbbft_tpu.protocols.subset import AgreementWrap, BroadcastWrap

    msg = AlgoMessage(HbWrap(2, SubsetWrap(5, BroadcastWrap(
        1, ReadyMsg(b"\0" * 32)))))
    assert classify(msg) == (2, 5, "rbc_ready", None)
    msg = HbWrap(0, SubsetWrap(1, AgreementWrap(2, BValMsg(3, True))))
    assert classify(msg) == (0, 1, "aba_bval", 3)
    assert classify(SubsetWrap(4, BroadcastWrap(0, EchoHashMsg(b"r")))) \
        == (0, 4, "rbc_echo", None)
    assert classify(KeyGenWrap(7, object())) == (7, 0, "dkg_rotation",
                                                 None)
    # CoinMsg carries the ABA round in its own epoch field
    msg = SubsetWrap(0, AgreementWrap(1, CoinMsg(2, object())))
    assert classify(msg) == (0, 0, "aba_coin", 2)


def test_dkg_rotation_span_emitted_on_era_change(shared_netinfo):
    """Drive an object-mode remove-validator DKG through VirtualNet with
    tracers attached: the era rotation must produce exactly one
    ``dkg_rotation`` span per era, covering the signed Part/Ack traffic
    and ending at the Complete batch."""
    from hbbft_tpu.protocols.dynamic_honey_badger import (
        Change, ChangeInput, UserInput,
    )

    infos = shared_netinfo(4, 31)
    net = NetBuilder(list(range(4))).observe(
        lambda nid: SpanTracer(node=nid)
    ).using_step(
        lambda nid: DynamicHoneyBadger(
            infos[nid], infos[nid].secret_key(),
            rng=random.Random(5000 + nid),
            encryption_schedule=EncryptionSchedule.never(),
        )
    )
    for nid in net.node_ids():
        net.send_input(nid, ChangeInput(Change.node_change({
            k: net.nodes[nid].algorithm.netinfo.public_key(k)
            for k in (0, 1, 2)
        })))
    for round_ in range(8):
        for nid in net.node_ids():
            net.send_input(nid, UserInput(b"dkg-%d" % round_))
        net.run_to_quiescence()
        if all(net.nodes[nid].algorithm.era == 1
               for nid in net.node_ids()):
            break
    assert all(net.nodes[nid].algorithm.era == 1
               for nid in net.node_ids())
    for nid in net.node_ids():
        tracer = net.observers[nid]
        dkg = [s for s in tracer.finished if s.name == "dkg_rotation"]
        assert len(dkg) == 1, (nid, dkg)
        span = dkg[0]
        assert span.era == 0 and span.count > 0
        assert span.t_end > span.t_start
        # it reached the registry histogram too
        hist = tracer.registry.get("hbbft_phase_duration_seconds")
        counts = {labels["phase"]: child.count
                  for labels, child in hist.series()}
        assert counts["dkg_rotation"] == 1


def test_open_epoch_state_is_bounded_and_finalized_epochs_stay_closed():
    """A Byzantine peer minting arbitrary (era, epoch) keys must not grow
    tracer state without bound, and a straggler message for an already-
    finalized epoch must not re-open it (it could never finalize again)."""
    from hbbft_tpu.protocols.broadcast import ReadyMsg
    from hbbft_tpu.protocols.honey_badger import Batch, SubsetWrap
    from hbbft_tpu.protocols.subset import BroadcastWrap
    from hbbft_tpu.traits import Step

    tracer = SpanTracer(node=0, max_open_epochs=16)
    for epoch in range(500):
        tracer.on_message(1, SubsetWrap(epoch, BroadcastWrap(
            0, ReadyMsg(b"\0" * 32))))
    assert len(tracer._open) <= 16
    evicted = tracer.registry.get(
        "hbbft_phase_open_epochs_evicted_total")
    assert evicted.value() == 500 - 16
    # the genuine in-progress trace (the LOWEST open key) survives a
    # flood of attacker-minted future keys: epoch 0's aggregation is
    # still there with its message counted
    assert (0, 0) in tracer._open
    assert tracer._open[(0, 0)][("rbc_ready", None)].count == 1
    # same bound for per-era DKG state
    from hbbft_tpu.protocols.dynamic_honey_badger import KeyGenWrap

    for era in range(100):
        tracer.on_message(1, KeyGenWrap(era, object()))
    assert len(tracer._dkg_open) <= 8
    assert 0 in tracer._dkg_open  # lowest (genuine) era kept
    # finalize epoch 499, then a straggler for it arrives late
    tracer.on_step(Step(output=[Batch(epoch=499, contributions=())]))
    assert (0, 499) not in tracer._open
    tracer.on_message(1, SubsetWrap(499, BroadcastWrap(
        0, ReadyMsg(b"\0" * 32))))
    assert (0, 499) not in tracer._open  # not re-opened
    assert tracer.epochs_finalized == 1


def test_reconnects_view_survives_label_cardinality_overflow():
    """Past the metric's label cap, overflowed peers share one series —
    the dict view must still report exact per-peer counts and only apply
    deltas to the shared series (no clobbering)."""
    from hbbft_tpu.net.transport import _LabeledCounterView
    from hbbft_tpu.obs.metrics import OVERFLOW

    reg = Registry()
    counter = reg.counter("hbbft_net_reconnects_total", "r",
                          labelnames=("peer",), max_label_sets=2)
    view = _LabeledCounterView(counter)
    for peer in range(5):
        for _ in range(peer + 1):
            view[peer] = view.get(peer, 0) + 1
    # dict semantics exact for every peer, capped or not
    assert dict(view.items()) == {p: p + 1 for p in range(5)}
    assert view[4] == 5 and 4 in view and len(view) == 5
    series = {labels["peer"]: child.get()
              for labels, child in counter.series()}
    # the two real series are exact; the overflow series aggregates the
    # rest instead of holding only the last write
    assert series["0"] == 1 and series["1"] == 2
    assert series[OVERFLOW] == 3 + 4 + 5


def test_wire_size_failure_is_counted_and_logged_once(caplog):
    import logging

    from hbbft_tpu.sim.trace import wire_size

    class Unencodable:
        pass

    counter = DEFAULT.counter(
        "hbbft_sim_wire_size_failures_total", "", labelnames=("type",))
    before = counter.value(type="Unencodable")
    with caplog.at_level(logging.WARNING, logger="hbbft_tpu.sim"):
        assert wire_size(Unencodable()) == 0
        assert wire_size(Unencodable()) == 0
    after = counter.value(type="Unencodable")
    assert after == before + 2
    warnings = [r for r in caplog.records
                if "wire_size" in r.getMessage()]
    assert len(warnings) <= 1  # logged at most once per type path
    # a real protocol message still encodes with a positive size
    from hbbft_tpu.protocols.broadcast import ReadyMsg

    assert wire_size(ReadyMsg(b"\0" * 32)) > 0


# ---------------------------------------------------------------------------
# epoch pipelining (pipeline_depth >= 2): overlapping epochs stay separate
# ---------------------------------------------------------------------------


def test_pipelined_epochs_finalize_one_span_set_each():
    """The pipeline_depth >= 2 message shape: epoch e+1's RBC/ABA traffic
    interleaves with epoch e's before EITHER commits.  Each commit must
    finalize exactly one span set, every phase attributed to the epoch
    its messages named — and a straggler for a finalized epoch must not
    re-open it."""
    from hbbft_tpu.protocols.binary_agreement import AuxMsg, BValMsg
    from hbbft_tpu.protocols.broadcast import ReadyMsg
    from hbbft_tpu.protocols.dynamic_honey_badger import HbWrap
    from hbbft_tpu.protocols.honey_badger import Batch, SubsetWrap
    from hbbft_tpu.protocols.subset import AgreementWrap, BroadcastWrap
    from hbbft_tpu.traits import Step

    def rbc(epoch):
        return HbWrap(0, SubsetWrap(epoch, BroadcastWrap(
            0, ReadyMsg(b"\0" * 32))))

    def aba(epoch, msg):
        return HbWrap(0, SubsetWrap(epoch, AgreementWrap(0, msg)))

    tracer = SpanTracer(Registry(), node=0)
    # interleaved: epoch 0 and epoch 1 both in flight
    tracer.on_message(1, rbc(0), t=0.0)
    tracer.on_message(1, rbc(1), t=1.0)
    tracer.on_message(2, aba(0, BValMsg(0, True)), t=2.0)
    tracer.on_message(2, aba(1, BValMsg(0, True)), t=3.0)
    tracer.on_message(3, aba(0, AuxMsg(0, True)), t=4.0)
    tracer.on_message(3, aba(1, AuxMsg(0, False)), t=5.0)
    # epoch 0 commits first; epoch 1 is STILL OPEN and keeps receiving
    tracer.on_step(Step(output=[Batch(0, ())]), t=6.0)
    tracer.on_message(2, aba(1, AuxMsg(1, True)), t=7.0)
    tracer.on_step(Step(output=[Batch(1, ())]), t=8.0)

    assert tracer.epochs_finalized == 2
    s0 = tracer.spans_for(0, 0)
    s1 = tracer.spans_for(0, 1)
    # exactly one span set per epoch, one epoch-span each
    assert sum(1 for s in s0 if s.name == "epoch") == 1
    assert sum(1 for s in s1 if s.name == "epoch") == 1
    # epoch 0's spans cover ONLY its own timestamps (0, 2, 4, commit 6)
    names0 = {(s.name, s.round): s for s in s0}
    assert set(names0) == {("rbc_ready", None), ("aba_bval", 0),
                           ("aba_aux", 0), ("epoch", None)}
    assert names0[("epoch", None)].t_start == 0.0
    assert names0[("epoch", None)].t_end == 6.0
    assert names0[("aba_bval", 0)].t_start == 2.0
    assert all(s.t_end <= 6.0 for s in s0)
    # epoch 1's spans cover only its own (1, 3, 5, 7, commit 8) — the
    # post-commit-of-epoch-0 Aux at t=7 landed in round 1 of epoch 1
    names1 = {(s.name, s.round): s for s in s1}
    assert set(names1) == {("rbc_ready", None), ("aba_bval", 0),
                           ("aba_aux", 0), ("aba_aux", 1),
                           ("epoch", None)}
    assert names1[("epoch", None)].t_start == 1.0
    assert names1[("epoch", None)].t_end == 8.0
    assert names1[("aba_aux", 1)].t_start == 7.0
    # a straggler for a FINALIZED epoch never re-opens it
    before = len(tracer.finished)
    tracer.on_message(1, rbc(0), t=9.0)
    tracer.on_step(Step(), t=9.5)
    assert len(tracer.finished) == before
    assert tracer.epochs_finalized == 2

"""net/: localhost QHB clusters over real sockets.

Tier 1 keeps exactly one fast smoke (4 in-process nodes on ephemeral
ports, a few epochs, hard timeout — typically a couple of seconds).  The
multi-process soak, the kill/restart catch-up e2e, and the two-run
determinism comparison are marked ``slow``.
"""

import asyncio
import subprocess
import time

import pytest

from hbbft_tpu.net.client import ClusterClient
from hbbft_tpu.net.cluster import (
    ClusterConfig,
    LocalCluster,
    assert_status_chains_consistent,
    build_runtime,
    find_free_base_port,
    generate_infos,
    shutdown_procs,
    spawn_node,
)

SMOKE_TIMEOUT_S = 60  # hard cap; the smoke body typically runs in ~2 s


def test_four_node_smoke(tmp_path):
    """4-node QHB cluster over real TCP commits client transactions with
    identical ledgers — the one socket test in the fast tier.  Runs with
    the flight recorder on: afterwards the journals must audit to a
    clean verdict and cross-check the live /status chain head."""
    flight_root = str(tmp_path / "flight")

    async def scenario():
        cfg = ClusterConfig(n=4, seed=21, batch_size=6,
                            flight_dir=flight_root)
        cluster = LocalCluster(cfg)
        await cluster.start()
        try:
            client = await cluster.client(0)
            # an oversized tx is rejected at admission (never proposed)
            assert await client.submit(b"\x00" * (256 * 1024 + 1)) == 3
            txs = [b"smoke-%02d" % i for i in range(18)]
            for tx in txs:
                assert await client.submit(tx) == 0
            for tx in txs:
                await client.wait_committed(tx, timeout_s=30)
            await cluster.wait_epochs(2, timeout_s=30)
            # identical batches on all nodes (ledger digest chain)
            prefix = cluster.common_digest_prefix()
            assert len(prefix) >= 2
            # latency was measured end to end
            pct = client.latency_percentiles()
            assert pct["count"] == len(txs) and pct["p50_s"] > 0
            # a status document is servable over the same socket
            doc = await client.status()
            assert doc["committed_txs"] >= len(txs)
            assert doc["peers_connected"] == 3
            assert doc["decode_failures"] == 0
            # chain head + total length are exposed for the auditor
            assert doc["chain_head"] == doc["ledger"]
            assert doc["chain_len"] == doc["batches"]
            assert doc["flight"]["records"] > 0
            assert doc["flight"]["write_failures"] == 0
            # the /flight endpoint serves the journal tail
            from hbbft_tpu.obs.http import http_get

            host, port = cluster.metrics_addrs[0]
            tail = await asyncio.to_thread(http_get, host, port,
                                           "/flight")
            assert any('"FlightCommit"' in l
                       for l in tail.splitlines())
            return doc
        finally:
            await cluster.stop()

    doc = asyncio.run(asyncio.wait_for(scenario(), SMOKE_TIMEOUT_S))
    # offline forensics over the journals the run left behind
    from hbbft_tpu.obs.audit import cross_check_status, run_audit

    res, journals = run_audit([flight_root])
    assert len(journals) == 4 and res.torn_tails == 0
    cross_check_status(res, doc)
    assert res.verdict == "clean", res.as_dict()
    heads = {c["head"] for c in res.chains.values()}
    assert heads == {doc["chain_head"]}


async def _poll_status(addr, cluster_id, deadline_s=60.0, client_id="poll"):
    """Connect (retrying while the node boots) and fetch one status doc."""
    t_end = time.monotonic() + deadline_s
    last = None
    while time.monotonic() < t_end:
        client = ClusterClient(addr, cluster_id, client_id=client_id)
        try:
            await client.connect()
            doc = await client.status()
            await client.close()
            return doc
        except (OSError, asyncio.TimeoutError, ConnectionError) as exc:
            last = exc
            await client.close()
            await asyncio.sleep(0.3)
    raise TimeoutError(f"no status from {addr}: {last!r}")


def _assert_chains_consistent(docs):
    assert assert_status_chains_consistent(docs) > 0


@pytest.mark.slow
def test_multiprocess_cluster_kill_restart_e2e(tmp_path):
    """The acceptance scenario: a 4-process localhost cluster commits ≥ 20
    epochs of client transactions with identical batches everywhere; one
    node is SIGKILLed mid-run, restarted from scratch, and catches up via
    the SenderQueue replay path while the cluster keeps committing.
    Every node journals to a flight recorder; afterwards the merged
    journals must audit to a CLEAN verdict — the SIGKILL shows up as a
    restart incarnation (and possibly a torn tail), never as a false
    divergence across the replay/catch-up path."""
    flight_root = str(tmp_path / "flight")
    cfg = ClusterConfig(n=4, seed=31, batch_size=4,
                        base_port=find_free_base_port(4),
                        heartbeat_s=0.3, dead_after_s=2.0,
                        flight_dir=flight_root)
    procs = {
        i: spawn_node(cfg, i, stdout=subprocess.DEVNULL,
                      stderr=subprocess.STDOUT)
        for i in range(4)
    }

    async def pump(client, tag, count, start=0):
        txs = [b"%s-%04d" % (tag, i) for i in range(start, start + count)]
        for tx in txs:
            assert await client.submit(tx) == 0
        for tx in txs:
            await client.wait_committed(tx, timeout_s=120)
        return txs

    async def scenario():
        client = None
        for _ in range(200):
            try:
                c = ClusterClient(cfg.addr(0), cfg.cluster_id)
                await c.connect()
                client = c
                break
            except (OSError, asyncio.TimeoutError):
                await asyncio.sleep(0.5)
        assert client is not None, "node 0 never came up"

        # phase 1: load until every node reports ≥ 8 batches
        batch = 0
        while True:
            await pump(client, b"p1", 12, start=batch * 12)
            batch += 1
            docs = [await _poll_status(cfg.addr(i), cfg.cluster_id)
                    for i in range(4)]
            if min(d["batches"] for d in docs) >= 8:
                break
            assert batch < 20

        # kill node 3 hard, keep the load coming (3 of 4 make progress)
        procs[3].kill()
        procs[3].wait(timeout=10)
        await pump(client, b"p2", 24)

        # restart node 3 from scratch at (0, 0)
        procs[3] = spawn_node(cfg, 3, stdout=subprocess.DEVNULL,
                              stderr=subprocess.STDOUT)
        await pump(client, b"p3", 24)

        # drive past 20 epochs and wait for the restarted node to catch up
        target = None
        for _ in range(40):
            docs = [await _poll_status(cfg.addr(i), cfg.cluster_id)
                    for i in range(4)]
            target = max(d["batches"] for d in docs)
            if target >= 20 and min(d["batches"] for d in docs) >= 20:
                break
            await pump(client, b"p4", 8, start=_ * 8)
        assert min(d["batches"] for d in docs) >= 20, (
            f"catch-up stalled: {[d['batches'] for d in docs]}"
        )
        # identical batches on all nodes wherever the chains overlap
        _assert_chains_consistent(docs)
        # the restarted node really did rebuild pre-kill history: its chain
        # reaches back before the kill point and matches node 0's
        assert docs[3]["batches"] >= 20
        assert docs[3]["digest_chain_offset"] < 8 or (
            docs[3]["digest_chain"][0] == docs[0]["digest_chain"][
                docs[3]["digest_chain_offset"]
                - docs[0]["digest_chain_offset"]]
        )
        await client.close()

    try:
        asyncio.run(asyncio.wait_for(scenario(), 600))
    finally:
        shutdown_procs(procs.values())

    # forensic audit over the whole incident: the restarted node's
    # journal holds two incarnations whose replayed chain prefix must
    # match everyone byte for byte — a clean verdict, no false fork
    from hbbft_tpu.obs.audit import run_audit

    res, journals = run_audit([flight_root])
    assert len(journals) == 4
    assert res.restarts[repr(3)] >= 1  # the SIGKILL is visible
    assert res.verdict == "clean", res.as_dict()
    assert not res.self_conflicts and not res.equivocations
    heads = {}
    for node, chain in res.chains.items():
        heads.setdefault(chain["commits"][min(chain["commits"])][0],
                         []).append(node)
    # everyone folded the same batch 0 (full agreement is the clean
    # verdict above; this pins the replay reached all the way back)
    assert len(heads) == 1


@pytest.mark.slow
def test_same_seed_same_schedule_and_batches():
    """Determinism satellite: two runs of the 4-node localhost cluster
    with the same seed produce (a) identical seeded reconnect schedules
    for the late-starting peer and (b) identical committed transaction
    sequences per epoch.

    Every node receives every transaction before consensus starts and
    ``batch_size`` covers them all, so each proposal is the full set and
    the committed per-epoch tx sequence is schedule-independent — which is
    exactly what must come out identical; proposer attribution inside a
    batch legitimately varies with socket timing."""

    TXS = [b"det-%02d" % i for i in range(12)]

    async def one_run():
        cfg = ClusterConfig(n=4, seed=77, batch_size=len(TXS),
                            heartbeat_s=0.2, dead_after_s=2.0)
        infos = generate_infos(cfg)
        runtimes = [build_runtime(cfg, infos, nid) for nid in range(4)]
        addrs = {}
        # nodes 0..2 listen; node 3 is late so its peers draw real
        # backoff schedules
        for nid in (0, 1, 2):
            addrs[nid] = await runtimes[nid].start("127.0.0.1", 0)
        import socket as socket_mod

        s = socket_mod.socket()
        s.bind(("127.0.0.1", 0))
        addrs[3] = ("127.0.0.1", s.getsockname()[1])
        s.close()
        for nid in (0, 1, 2):
            runtimes[nid].connect(addrs)
        await asyncio.sleep(0.4)  # let reconnect schedules accumulate
        schedules = {
            nid: list(
                runtimes[nid].transport.stats.backoff_delays.get(3, [])
            )
            for nid in (0, 1, 2)
        }
        await runtimes[3].start(*addrs[3])
        runtimes[3].connect(addrs)
        # all txs to all nodes BEFORE consensus can start committing
        for rt in runtimes:
            for tx in TXS:
                rt.submit_tx(tx)

        async def all_done():
            while any(rt.committed_txs < len(TXS) for rt in runtimes):
                await asyncio.sleep(0.02)

        await asyncio.wait_for(all_done(), 60)
        epochs = [
            [(b.era, b.epoch, tuple(b.all_txs())) for b in rt.batches]
            for rt in runtimes
        ]
        for rt in runtimes:
            await rt.stop()
        return schedules, epochs

    async def scenario():
        sched1, epochs1 = await one_run()
        sched2, epochs2 = await one_run()
        # (a) identical reconnect schedule prefixes, and non-trivial ones
        for nid in (0, 1, 2):
            k = min(len(sched1[nid]), len(sched2[nid]))
            assert k >= 1, f"node {nid} never drew a backoff delay"
            assert sched1[nid][:k] == sched2[nid][:k]
        # (b) within each run all nodes agree; across runs the committed
        # tx sequences match epoch for epoch
        for run in (epochs1, epochs2):
            for per_node in run[1:]:
                assert per_node[: len(run[0])] == run[0][: len(per_node)]
        k = min(len(epochs1[0]), len(epochs2[0]))
        assert k >= 1
        assert epochs1[0][:k] == epochs2[0][:k]
        # everything committed exactly once in both runs
        for run in (epochs1, epochs2):
            flat = [tx for _e, _p, txs in run[0] for tx in txs]
            assert sorted(flat) == sorted(set(flat))
            assert set(flat) == set(TXS)

    asyncio.run(asyncio.wait_for(scenario(), 300))


# ---------------------------------------------------------------------------
# multi-process --join (the PR-8 membership lifecycle as an OS process)


def test_join_cli_parses_and_builds_command():
    """Tier-1 wiring check: ``--join`` relaxes the node-id range and the
    command builder emits the full flag set."""
    from hbbft_tpu.net.cluster import join_command, main as cluster_main

    cfg = ClusterConfig(n=4, seed=7, base_port=25000, batch_size=4)
    cmd = join_command(cfg, 4)
    assert "--join" in cmd and "--node-id" in cmd
    assert cmd[cmd.index("--node-id") + 1] == "4"
    # without --join, an out-of-range node id is still an argparse error
    with pytest.raises(SystemExit):
        cluster_main(["--nodes", "4", "--node-id", "4",
                      "--base-port", "25000"])


def test_join_cli_runs_the_join_flow(monkeypatch):
    """``--join`` routes main() into run_join_node (not run_node)."""
    import hbbft_tpu.net.cluster as cluster_mod

    called = {}

    def fake_run(coro):
        called["coro"] = coro.cr_code.co_name
        coro.close()

    monkeypatch.setattr(cluster_mod.asyncio, "run", fake_run)
    cluster_mod.main(["--nodes", "4", "--node-id", "5",
                      "--base-port", "25000", "--join"])
    assert called["coro"] == "run_join_node"
    cluster_mod.main(["--nodes", "4", "--node-id", "0",
                      "--base-port", "25000"])
    assert called["coro"] == "run_node"


def test_join_cli_process_joins_live_cluster(tmp_path):
    """The full multi-process --join flow: an in-process 4-node cluster
    votes node 4 in (DKG rotation), then a FRESH OS PROCESS runs
    ``python -m hbbft_tpu.net.cluster --join --node-id 4`` — it
    state-syncs the era-boundary snapshot from the live donors,
    activates share-complete, and commits with the cluster."""
    cfg = ClusterConfig(n=4, seed=29, batch_size=4,
                        base_port=find_free_base_port(6),
                        heartbeat_s=0.3, dead_after_s=2.0,
                        flight_dir=str(tmp_path / "flight"))
    cluster = LocalCluster(cfg)
    proc = None

    async def scenario():
        nonlocal proc
        await cluster.start()
        try:
            await _join_body()
        finally:
            await cluster.stop()

    async def _join_body():
        nonlocal proc
        client = await cluster.client(0)
        for i in range(8):
            assert await client.submit(b"pre-%02d" % i) == 0
        # vote node 4 in and wait for every donor to serve the
        # era-boundary snapshot of the completed rotation
        cluster.vote_to_add(4)
        min_era = max(rt.current_key()[0] for rt in cluster.runtimes) + 1
        await cluster.wait_snapshot(min_era, timeout_s=120)
        proc = spawn_node(cfg, 4, join=True,
                          stdout=subprocess.DEVNULL,
                          stderr=subprocess.STDOUT)
        # keep traffic flowing while the joiner boots + state-syncs
        deadline = time.monotonic() + 180
        wave = 0
        joined_doc = None
        while time.monotonic() < deadline:
            txs = [b"post-%02d-%02d" % (wave, i) for i in range(4)]
            wave += 1
            for tx in txs:
                await client.submit(tx)
            for tx in txs:
                await client.wait_committed(tx, timeout_s=120)
            assert proc.poll() is None, "joiner process died"
            try:
                jc = ClusterClient(cfg.addr(4), cfg.cluster_id,
                                   client_id="probe")
                await jc.connect()
                doc = await jc.status()
                await jc.close()
                if doc["batches"] >= 1:
                    joined_doc = doc
                    break
            except (OSError, asyncio.TimeoutError, ConnectionError):
                await asyncio.sleep(0.5)
        assert joined_doc is not None, "joiner never committed a batch"
        assert joined_doc["era"] >= min_era
        # the joiner's chain must agree with a donor's wherever the
        # retained tails overlap
        d0 = await client.status()
        assert_status_chains_consistent([d0, joined_doc])

    try:
        asyncio.run(asyncio.wait_for(scenario(), 300))
    finally:
        if proc is not None:
            shutdown_procs([proc])

"""Erasure-backend equivalence + zero-copy hot-path regressions.

The MB-scale ingestion work split the RS hot path into three engines
(HBBFT_ERASURE_BACKEND = native / numpy / jax) that MUST stay
byte-identical — the Merkle root commits to the exact parity bytes, so a
single differing byte forks consensus between nodes running different
backends.  These tests pin:

  * encode byte-equality across all loadable backends, over shard sizes
    64 B → 64 KB including odd lengths, for every shipped (n, f) shape;
  * reconstruction from every f-sized erasure pattern (bounded
    deterministic sample at n = 16 where C(16,5) = 4368);
  * the proposer encode→commit path staying copy-free (one immutable
    snapshot shared by the Merkle tree and every per-peer proof).
"""

import itertools

import numpy as np
import pytest

from hbbft_tpu.ops import rs
from hbbft_tpu.ops.merkle import MerkleTree
from hbbft_tpu.protocols import wire
from hbbft_tpu.protocols.broadcast import _encode_value, _unframe_value

# (n, f) → (data, parity) = (n − 2f, 2f)
SHAPES = [(4, 1), (7, 2), (10, 3), (16, 5)]

# shard byte lengths: tiny, odd, unaligned, tile-boundary, large
SHARD_LENS = [64, 63, 65, 1024, 4097, 32768, 65536]


def _backends():
    """Backends loadable in this environment (numpy always; native when
    the oracle builds; jax when importable)."""
    out = ["numpy"]
    try:
        from hbbft_tpu.native.oracle import get_oracle

        get_oracle()
        out.append("native")
    except Exception:
        pass
    try:
        import jax  # noqa: F401

        out.append("jax")
    except Exception:
        pass
    return out


BACKENDS = _backends()


def _rng(seed):
    return np.random.default_rng(seed)


def _encode_with(monkeypatch, backend, coder, data):
    monkeypatch.setenv("HBBFT_ERASURE_BACKEND", backend)
    return coder.encode_np(data)


@pytest.mark.parametrize("n,f", SHAPES)
def test_encode_byte_equality_across_backends(monkeypatch, n, f):
    coder = rs.ReedSolomon(n - 2 * f, 2 * f)
    # jax re-traces per distinct shape — keep its sweep to a subset
    lens_by_backend = {"jax": [64, 1024]}
    for B in SHARD_LENS:
        data = _rng(1000 * n + B).integers(
            0, 256, size=(coder.data_shards, B), dtype=np.uint8
        )
        ref = _encode_with(monkeypatch, "numpy", coder, data)
        assert ref.shape == (coder.total_shards, B)
        # systematic: data rows pass through untouched
        assert np.array_equal(ref[: coder.data_shards], data)
        for backend in BACKENDS:
            if backend == "numpy":
                continue
            if B not in lens_by_backend.get(backend, SHARD_LENS):
                continue
            got = _encode_with(monkeypatch, backend, coder, data)
            assert np.array_equal(got, ref), (
                f"backend {backend} diverges at n={n} f={f} B={B}"
            )


@pytest.mark.parametrize("n,f", SHAPES)
def test_reconstruct_every_erasure_pattern(monkeypatch, n, f):
    monkeypatch.setenv("HBBFT_ERASURE_BACKEND", "numpy")
    coder = rs.ReedSolomon(n - 2 * f, 2 * f)
    B = 64
    data = _rng(7 * n).integers(
        0, 256, size=(coder.data_shards, B), dtype=np.uint8
    )
    full = [bytes(row) for row in coder.encode_np(data)]
    patterns = itertools.combinations(range(n), f)
    if n >= 16:
        # C(16,5) = 4368 — deterministic stride sample keeps tier-1 fast
        patterns = list(patterns)[::37]
    for erased in patterns:
        shards = [
            None if i in erased else full[i] for i in range(n)
        ]
        got = coder.reconstruct_np(shards)
        assert got == full, f"pattern {erased} reconstructed wrong"


@pytest.mark.parametrize("backend", BACKENDS)
def test_reconstruct_backend_equality(monkeypatch, backend):
    """Decode-side matrices run through the same backend dispatch."""
    monkeypatch.setenv("HBBFT_ERASURE_BACKEND", backend)
    coder = rs.ReedSolomon(5, 4)  # n=9, f=2
    B = 1026
    data = _rng(42).integers(0, 256, size=(5, B), dtype=np.uint8)
    full = [bytes(row) for row in coder.encode_np(data)]
    shards = [None, full[1], None, full[3], full[4], full[5], None, full[7], full[8]]
    assert coder.reconstruct_np(shards) == full


def test_backend_switch_validation(monkeypatch):
    monkeypatch.setenv("HBBFT_ERASURE_BACKEND", "bogus")
    with pytest.raises(ValueError):
        rs.resolve_backend()
    monkeypatch.delenv("HBBFT_ERASURE_BACKEND")
    assert rs.resolve_backend() in ("native", "numpy")


def test_stats_counters_advance(monkeypatch):
    monkeypatch.setenv("HBBFT_ERASURE_BACKEND", "numpy")
    before = rs.stats_snapshot()["numpy"]
    coder = rs.ReedSolomon(2, 2)
    coder.encode_np(np.zeros((2, 128), dtype=np.uint8))
    after = rs.stats_snapshot()["numpy"]
    assert after["calls"] == before["calls"] + 1
    assert after["bytes"] == before["bytes"] + 2 * 128


# ---------------------------------------------------------------------------
# Zero-copy proposer hot path
# ---------------------------------------------------------------------------


def test_encode_value_zero_copy():
    """encode→commit shares ONE immutable snapshot: no per-leaf copies,
    every proof value a memoryview slice of the same buffer."""
    coder = rs.for_n_f(4, 1)
    value = bytes(range(256)) * 128  # 32 KB
    shards, leaves = _encode_value(coder, value)
    tree = MerkleTree.from_shards(shards, leaves)
    assert tree.leaf_copies == 0
    bufs = {mv.obj for mv in tree.values}
    assert len(bufs) == 1, "leaves must share one snapshot buffer"
    buf = next(iter(bufs))
    assert isinstance(buf, bytes)
    for i in range(coder.total_shards):
        p = tree.proof(i)
        assert isinstance(p.value, memoryview)
        assert p.value.obj is buf
        assert p.validate(coder.total_shards)
    # decode side: unframe recovers the value from the data rows
    k = coder.data_shards
    assert _unframe_value(b"".join(bytes(v) for v in leaves[:k])) == value


def test_memoryview_proof_wire_roundtrip():
    """Proof values as memoryviews must encode on the wire identically to
    their bytes() conversion, and hash/eq-match the bytes form (replay
    dedup and MultipleValues detection compare Proof objects)."""
    from hbbft_tpu.ops.merkle import Proof
    from hbbft_tpu.protocols.broadcast import EchoMsg, ValueMsg

    coder = rs.for_n_f(4, 1)
    shards, leaves = _encode_value(coder, b"x" * 5000)
    tree = MerkleTree.from_shards(shards, leaves)
    for cls in (ValueMsg, EchoMsg):
        p = tree.proof(2)
        enc = wire.encode_message(cls(p))
        pb = Proof(
            value=bytes(p.value), index=p.index,
            root_hash=p.root_hash, path=p.path,
        )
        assert enc == wire.encode_message(cls(pb))
        dec = wire.decode_message(enc)
        assert dec.proof == p and dec.proof == pb
        assert hash(p) == hash(pb)


def test_encode_value_matches_legacy_frame():
    """The in-place framed encode must produce byte-identical shards to
    the legacy _frame_value → encode_np pipeline."""
    from hbbft_tpu.protocols.broadcast import _frame_value

    for n, f in SHAPES:
        coder = rs.ReedSolomon(n - 2 * f, 2 * f)
        for vlen in (0, 1, 100, 4097):
            value = bytes(_rng(vlen + n).integers(0, 256, vlen, dtype=np.uint8))
            legacy = coder.encode_np(_frame_value(value, coder.data_shards))
            shards, leaves = _encode_value(coder, value)
            assert np.array_equal(shards, legacy)
            assert all(
                bytes(mv) == bytes(row) for mv, row in zip(leaves, legacy)
            )


def test_rs16_encode_into_matches_encode_np():
    """GF(2^16) coder (n > 256 networks) honors the same in-place
    contract — Broadcast._encode_value calls encode_into on ANY coder."""
    coder = rs.ReedSolomon16(3, 2)
    data = _rng(99).integers(0, 256, size=(3, 64), dtype=np.uint8)
    ref = coder.encode_np(data)
    shards = np.zeros((5, 64), dtype=np.uint8)
    shards[:3] = data
    coder.encode_into(shards)
    assert np.array_equal(shards, ref)


# ---------------------------------------------------------------------------
# Decode-side pattern caches (receiver reconstruct hot path)
# ---------------------------------------------------------------------------


def test_lru_bound_and_recency():
    """The _Lru backing every per-coder compiled-artifact cache: bounded,
    and ``get`` refreshes recency so hot erasure patterns survive."""
    lru = rs._Lru(maxsize=3)
    for i in range(5):
        lru.put(i, i * 10)
    assert len(lru) == 3
    assert 0 not in lru and 1 not in lru and 4 in lru
    assert lru.get(2) == 20  # refresh 2 → 3 becomes the eviction victim
    lru.put(5, 50)
    assert 2 in lru and 3 not in lru


@pytest.mark.parametrize("backend", BACKENDS)
def test_rs16_reconstruct_backend_equality(monkeypatch, backend):
    """GF(2^16) reconstruct_data_np byte-identical across backends (the
    native SIMD kernel is GF(2^8)-only, so ``native`` must route to the
    numpy schedule path without diverging)."""
    monkeypatch.setenv("HBBFT_ERASURE_BACKEND", "numpy")
    coder = rs.ReedSolomon16(5, 4)  # the n=9-style shape of the GF(2^8) test
    B = 1026
    data = _rng(46).integers(0, 256, size=(5, B), dtype=np.uint8)
    full = coder.encode_np(data)

    monkeypatch.setenv("HBBFT_ERASURE_BACKEND", backend)
    use = (1, 3, 4, 6, 8)  # mixed data + parity survivors
    per_backend = rs.ReedSolomon16(5, 4)
    got = per_backend.reconstruct_data_np(full[list(use)], use)
    np.testing.assert_array_equal(got, data)
    # second call exercises the cache-hit path — still identical
    np.testing.assert_array_equal(
        per_backend.reconstruct_data_np(full[list(use)], use), data
    )


def test_rs16_reconstruct_above_schedule_col_bound(monkeypatch):
    """Decode matrices wider than _SCHED_MAX_COLS skip the XOR-schedule
    compile (quadratic CSE) and use the cached table matmul — results
    must be identical either way."""
    monkeypatch.setenv("HBBFT_ERASURE_BACKEND", "numpy")
    k = rs._SCHED_MAX_COLS + 16
    coder = rs.ReedSolomon16(k, 20)
    data = _rng(58).integers(0, 256, size=(k, 64), dtype=np.uint8)
    full = coder.encode_np(data)
    # drop the first 20 data rows → survivors = rest of data + all parity
    use = tuple(range(20, k + 20))
    got = coder.reconstruct_data_np(full[list(use)], use)
    np.testing.assert_array_equal(got, data)
    assert len(coder._sched_cache) == 0  # the wide matrix never compiled


def test_rs16_decode_caches_populate_hit_and_count(monkeypatch):
    monkeypatch.setenv("HBBFT_ERASURE_BACKEND", "numpy")
    coder = rs.ReedSolomon16(4, 3)
    data = _rng(9).integers(0, 256, size=(4, 64), dtype=np.uint8)
    full = coder.encode_np(data)
    use = (0, 2, 4, 6)
    before = rs.stats_snapshot()["numpy"]
    out1 = coder.reconstruct_data_np(full[list(use)], use)
    assert len(coder._decode_cache) == 1
    assert len(coder._sched_cache) == 1
    out2 = coder.reconstruct_data_np(full[list(use)], use)
    assert len(coder._decode_cache) == 1  # hit — no second inversion entry
    after = rs.stats_snapshot()["numpy"]
    assert after["calls"] == before["calls"] + 2  # decode stats still advance
    assert after["bytes"] == before["bytes"] + 2 * data.size
    np.testing.assert_array_equal(out1, out2)
    np.testing.assert_array_equal(out1, data)


def test_gf256_reconstruct_data_np_matches_full_reconstruct(monkeypatch):
    """The new GF(2^8) reconstruct_data_np (pattern-cached inversion +
    apply) agrees with the long-standing reconstruct_np on the same
    survivor set."""
    monkeypatch.setenv("HBBFT_ERASURE_BACKEND", "numpy")
    coder = rs.ReedSolomon(2, 2)  # N=4 f=1 — the rbc-mb1 bench shape
    data = _rng(12).integers(0, 256, size=(2, 256), dtype=np.uint8)
    full = coder.encode_np(data)
    use = (2, 3)  # worst case: all-parity survivors
    got = coder.reconstruct_data_np(full[list(use)], use)
    np.testing.assert_array_equal(got, data)
    shards = [None, None, bytes(full[2]), bytes(full[3])]
    assert coder.reconstruct_np(shards) == [bytes(r) for r in full]
    assert len(coder._decode_cache) == 1  # both calls share one pattern

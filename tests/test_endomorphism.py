"""The ψ/φ endomorphism fast paths (round-5 crypto accelerations).

Covers the pieces the batched TPKE encrypt, common-coin batch, and
hash-to-curve rely on: the ψ eigenvalue, Budroni–Pintore cofactor clearing,
GLS/GLV scalar decompositions (exercised through the native mul paths), and
the batch C entry points' equivalence to their per-item forms.

Reference roles: ``threshold_crypto``'s encrypt/hash/sign internals
(SURVEY §2.2 row 2; §3.1 marks TPKE encrypt HOT).
"""

import random

import pytest

from hbbft_tpu.crypto import bls12_381 as H
from hbbft_tpu.crypto import tc
from hbbft_tpu.native import get_oracle


@pytest.fixture(scope="module")
def oracle():
    return get_oracle()


def test_psi_eigenvalue_on_generator():
    # ψ acts as [p] ≡ [X] (mod r) on G2 (pure-Python ladder, no native)
    with H.pure_python():
        lhs = H.g2_psi(H.G2_GEN)
        rhs = H.g2_mul(H.G2_GEN, H.X % H.R)
        assert H.g2_eq(lhs, rhs)


def test_psi2_eigenvalue_and_split_bounds():
    """ψ² — the GLS split of the device G2 ladders (split TPKE encrypt):
    a pure Fp coordinate scaling acting as [X²] on G2, with both split
    halves inside the lazy ladder's < 2^128 soundness regime."""
    with H.pure_python():
        p = H.g2_mul(H.G2_GEN, 987654321)
        assert H.g2_eq(H.g2_psi2(p), H.g2_mul(p, H.LAMBDA_G2))
        # ψ² == ψ∘ψ (the scaling constants really are the ψ norms)
        assert H.g2_eq(H.g2_psi2(p), H.g2_psi(H.g2_psi(p)))
    # every split s = a + b·λ₂ stays below the 2^128 ladder bound
    assert 0 < H.LAMBDA_G2 < 1 << 128
    assert (H.R - 1) // H.LAMBDA_G2 < 1 << 128
    assert H.LAMBDA_G2 == H.LAMBDA_G1 + 1  # X² vs X²−1, both eigenvalues


def test_hash_g2_batch_matches_per_item(oracle):
    """The native batched hash-to-G2 (the host half of the split device
    encrypt) is byte-identical to per-item ``bls_hash_g2``."""
    msgs = [b"", b"a", b"HBBFT-TPKE" + bytes(range(97)), b"x" * 300]
    batch = oracle.bls_hash_g2_batch(msgs)
    assert batch == [oracle.bls_hash_g2(m) for m in msgs]
    assert oracle.bls_hash_g2_batch([]) == []


def test_psi_is_additive():
    with H.pure_python():
        rng = random.Random(3)
        p = H.g2_mul(H.G2_GEN, rng.randrange(1, H.R))
        q = H.g2_mul(H.G2_GEN, rng.randrange(1, H.R))
        assert H.g2_eq(
            H.g2_psi(H.g2_add(p, q)), H.g2_add(H.g2_psi(p), H.g2_psi(q))
        )


def _raw_twist_point(data: bytes):
    """A pre-clearing E'(Fp2) point from the hash candidates (NOT in G2)."""
    ctr = 0
    while True:
        x = H._hash_fp2(data, ctr)
        rhs = H.fp2_add(H.fp2_mul(H.fp2_sqr(x), x), H._B2)
        y = H.fp2_sqrt(rhs)
        if y is not None and y != H.FP2_ZERO:
            return (x, y, H.FP2_ONE)
        ctr += 1


def test_bp_clearing_lands_in_subgroup():
    with H.pure_python():
        for i in range(3):
            p = _raw_twist_point(b"bp-%d" % i)
            q = H.g2_clear_cofactor(p)
            assert q is not None
            assert H.g2_is_on_curve(q)
            # order r: [r]Q = ∞
            assert H.g2_mul(q, H.R, mod_r=False) is None


def test_bp_on_subgroup_point_is_heff_scalar():
    # For P already in G2, ψ = [X], so the BP map is multiplication by
    # 4x² − 2x − 1 (mod r) — an independent algebraic cross-check.
    with H.pure_python():
        p = H.g2_mul(H.G2_GEN, 0xDEADBEEF)
        heff_mod_r = (4 * H.X * H.X - 2 * H.X - 1) % H.R
        assert H.g2_eq(
            H.g2_clear_cofactor(p), H.g2_mul(p, heff_mod_r)
        )


def test_native_gls_mul_matches_python(oracle):
    # bls_sign = hash + GLS mul; compare against the pure-Python ladder
    rng = random.Random(9)
    for i in range(3):
        sk = rng.randrange(1, H.R)
        msg = b"gls-%d" % i
        h_bytes = oracle.bls_hash_g2(msg)
        with H.pure_python():
            h = H.g2_from_bytes(h_bytes)
            expect = H.g2_to_bytes(H.g2_mul(h, sk, mod_r=False))
        assert oracle.bls_sign(msg, sk) == expect


def test_native_glv_mask_batch_matches_python(oracle):
    rng = random.Random(10)
    s = rng.randrange(1, H.R)
    us, expect = [], []
    for _ in range(4):
        k = rng.randrange(1, H.R)
        with H.pure_python():
            u = H.g1_mul(H.G1_GEN, k)
            expect.append(H.g1_to_bytes(H.g1_mul(u, s)))
            us.append(H.g1_to_bytes(u))
    assert oracle.bls_tpke_mask_batch(s, us) == expect


def test_encrypt_batch_equals_per_item():
    rng = random.Random(4)
    sks = tc.SecretKeySet.random(2, rng)
    pk = sks.public_keys().public_key()
    msgs = [b"tx-%d" % i * (i + 1) for i in range(5)] + [b""]
    a, b = random.Random(77), random.Random(77)
    per_item = [pk.encrypt(m, a) for m in msgs]
    batch = tc.tpke_encrypt_batch(pk, msgs, b)
    for x, y in zip(per_item, batch):
        assert x.to_bytes() == y.to_bytes()
    for ct in batch:
        assert ct.verify()


def test_encrypt_batch_decrypts():
    rng = random.Random(6)
    sks = tc.SecretKeySet.random(2, rng)
    pks = sks.public_keys()
    msgs = [b"payload-%d" % i for i in range(4)]
    cts = tc.tpke_encrypt_batch(pks.public_key(), msgs, rng)
    from hbbft_tpu.crypto.batch import batch_tpke_decrypt

    shares = [(i, sks.secret_key_share(i)) for i in range(3)]
    assert batch_tpke_decrypt(pks, cts, shares) == msgs


def test_coin_batch_equals_coin_for():
    from hbbft_tpu.netinfo import NetworkInfo
    from hbbft_tpu.parallel.aba import coin_for, coins_for_epoch

    rng = random.Random(13)
    ids = list(range(5))
    netmap = NetworkInfo.generate_map(ids, rng=rng)
    for epoch in (2, 5, 8):
        batch = coins_for_epoch(netmap, b"s", ids, epoch)
        assert batch == [coin_for(netmap, b"s", p, epoch) for p in ids]


def test_hash_outputs_have_order_r(oracle):
    # both clearings (G1 h_eff = 1−x, G2 Budroni–Pintore) must land in the
    # r-order subgroups — on-curve alone is not enough (fault_log docs)
    for i in range(3):
        g1 = H.g1_from_bytes(oracle.bls_hash_g1(b"o1-%d" % i))
        g2 = H.g2_from_bytes(oracle.bls_hash_g2(b"o2-%d" % i))
        assert g1 is not None and g2 is not None
        # g1_from_bytes/g2_from_bytes already subgroup-check; make the
        # assertion explicit and independent anyway
        with H.pure_python():
            assert H.g1_add(H.g1_mul(g1, H.R - 1), g1) is None
            assert H.g2_add(H.g2_mul(g2, H.R - 1, mod_r=False), g2) is None


def test_subgroup_check_soundness_gcds():
    # the gcd facts the eigenvalue subgroup tests rest on (see
    # bls12_381.g1_in_subgroup / g2_in_subgroup docstrings)
    import math

    lam = H.LAMBDA_G1
    k = (lam * lam + lam + 1) // H.R
    assert (lam * lam + lam + 1) % H.R == 0
    assert math.gcd(H.H1, k) == 1
    assert H.P - H.X == H.H1 * H.R  # p − x = h₁·r (char. eq. route)
    assert math.gcd(H.H1, H.H2) == 1
    assert H.H1 % H.R != 0 and H.H2 % H.R != 0


def test_subgroup_checks_accept_and_reject(oracle):
    rng = random.Random(21)
    # members accepted (native + pure python agree)
    for _ in range(2):
        k = rng.randrange(1, H.R)
        p1 = H.g1_mul(H.G1_GEN, k)
        p2 = H.g2_mul(H.G2_GEN, k)
        assert oracle.bls_g1_in_subgroup(H.g1_to_bytes(p1))
        assert oracle.bls_g2_in_subgroup(H.g2_to_bytes(p2))
        with H.pure_python():
            assert H.g1_in_subgroup(p1)
            assert H.g2_in_subgroup(p2)
    # a pre-clearing twist point has cofactor torsion → rejected
    raw2 = _raw_twist_point(b"not-in-g2")
    with H.pure_python():
        raw2a = H.g2_affine(raw2)
        assert not H.g2_in_subgroup(raw2a)
        assert H.g2_is_on_curve(raw2a)  # on-curve but outside G2
    assert not oracle.bls_g2_in_subgroup(H.g2_to_bytes(raw2))
    import pytest as _pytest

    with _pytest.raises(ValueError, match="subgroup"):
        H.g2_from_bytes(H.g2_to_bytes(raw2))
    # same for G1: raw hash candidate before clearing
    import hashlib

    ctr = 0
    while True:
        h0 = hashlib.sha3_256(b"H1G-raw0" + ctr.to_bytes(4, "big")).digest()
        x = int.from_bytes(h0, "big") % H.P
        rhs = (x * x % H.P * x + H.B1) % H.P
        y = H.fp_sqrt(rhs)
        if y:
            raw1 = (x, y, 1)
            break
        ctr += 1
    # raw1 is on E(Fp) but (w.h.p.) not in the r-order subgroup
    with H.pure_python():
        in_g1 = H.g1_in_subgroup(raw1)
    assert oracle.bls_g1_in_subgroup(H.g1_to_bytes(raw1)) == in_g1
    if not in_g1:
        with _pytest.raises(ValueError, match="subgroup"):
            H.g1_from_bytes(H.g1_to_bytes(raw1))

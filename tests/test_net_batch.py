"""Batch-handle transport path: the grouping must be invisible.

The perf PR moved receive-side delivery from one pump event per frame to
one pump event per socket chunk (``on_peer_batch`` →
``SenderQueue.handle_message_batch`` → one merged absorb), optionally
with framing/decode offloaded to per-peer ingress worker threads.  All
of it is pure batching — these tests pin the contract that NOTHING
observable changes:

- sans-I/O: a 4-node network run with per-message ``handle_message``
  and one run with consecutive messages grouped through
  ``handle_message_batch`` produce byte-identical batch sequences AND
  byte-identical outbound message streams;
- over sockets: a cluster on the batch path and one forced onto the
  legacy per-message path commit identical ledgers;
- the worker path keeps cross-node consistency, and worker-thread parse
  failures (torn frames, decode garbage) attribute strikes to exactly
  the peer that sent the bytes.
"""

import asyncio
import random
from typing import Any, Dict, List, Tuple

import pytest

from hbbft_tpu.netinfo import NetworkInfo
from hbbft_tpu.protocols.dynamic_honey_badger import DynamicHoneyBadger
from hbbft_tpu.protocols.honey_badger import EncryptionSchedule
from hbbft_tpu.protocols.queueing_honey_badger import (
    QhbBatch,
    QueueingHoneyBadger,
    TxInput,
)
from hbbft_tpu.protocols.sender_queue import SenderQueue

N = 4
SMOKE_TIMEOUT_S = 90


def make_node(infos, nid) -> SenderQueue:
    dhb = DynamicHoneyBadger(
        infos[nid], infos[nid].secret_key(),
        rng=random.Random(7000 + nid),
        encryption_schedule=EncryptionSchedule.never(),
    )
    return SenderQueue(QueueingHoneyBadger(
        dhb, batch_size=4, rng=random.Random(8000 + nid)
    ))


class GroupingPump:
    """Deterministic FIFO pump that can deliver per-message or grouped.

    In grouped mode, maximal runs of consecutive queue entries with the
    same (sender, dest) go through ``handle_message_batch`` as ONE call
    — exactly what the transport's chunk batching does to a peer's
    frames — and the outputs/outbound stream are recorded identically
    either way so the two modes can be diffed byte for byte.
    """

    def __init__(self, nodes: Dict[int, SenderQueue], grouped: bool):
        self.nodes = nodes
        self.grouped = grouped
        self.queue: List[Tuple[int, int, Any]] = []
        self.outputs: Dict[int, List] = {nid: [] for nid in nodes}
        self.sent: Dict[int, List] = {nid: [] for nid in nodes}

    def absorb(self, nid: int, step) -> None:
        self.outputs[nid].extend(
            o for o in step.output if isinstance(o, QhbBatch))
        all_ids = sorted(self.nodes.keys())
        for tm in step.messages:
            for dest in tm.target.resolve(all_ids, nid):
                self.sent[nid].append((dest, repr(tm.message)))
                self.queue.append((nid, dest, tm.message))

    def run(self) -> None:
        while self.queue:
            sender, dest, msg = self.queue.pop(0)
            if not self.grouped:
                self.absorb(dest, self.nodes[dest].handle_message(
                    sender, msg))
                continue
            batch = [msg]
            while (self.queue and self.queue[0][0] == sender
                    and self.queue[0][1] == dest):
                batch.append(self.queue.pop(0)[2])
            self.absorb(dest, self.nodes[dest].handle_message_batch(
                sender, batch))


def _drive(grouped: bool):
    infos = NetworkInfo.generate_map(list(range(N)), random.Random(11))
    nodes = {nid: make_node(infos, nid) for nid in range(N)}
    pump = GroupingPump(nodes, grouped)
    for e in range(6):
        for nid in range(N):
            pump.absorb(nid, nodes[nid].handle_input(
                TxInput(b"tx-%d-%d" % (e, nid))))
        pump.run()
    ledgers = {
        nid: [(b.era, b.epoch, tuple(b.all_txs()))
              for b in pump.outputs[nid]]
        for nid in range(N)
    }
    return ledgers, pump.sent


def test_handle_message_batch_is_invisible():
    """Grouped delivery = per-message delivery: byte-identical batch
    sequences on every node (same seeds, same inputs ⇒ the ledger
    comparison is exact, not prefix-based).  The outbound streams are
    NOT compared globally — a merged Step legitimately defers fan-out
    relative to per-message interleaving; per-delivery equivalence is
    pinned separately below."""
    ledgers_a, _sent_a = _drive(grouped=False)
    ledgers_b, _sent_b = _drive(grouped=True)
    assert ledgers_a == ledgers_b
    assert all(len(l) >= 4 for l in ledgers_a.values())


def test_handle_message_batch_on_error_isolates_bad_message():
    """A message the wrapped handler rejects mid-batch is routed to
    ``on_error`` and the REST of the batch still lands — the runtime's
    strike accounting depends on this (one bad frame must not void its
    chunk-mates)."""
    infos = NetworkInfo.generate_map(list(range(N)), random.Random(11))
    a, b = make_node(infos, 0), make_node(infos, 1)
    step = a.handle_input(TxInput(b"seed-tx"))
    msgs = [tm.message for tm in step.messages
            if 1 in tm.target.resolve(list(range(N)), 0)]
    assert msgs, "no unicast/broadcast traffic to node 1?"
    poison = object()  # not an AlgoMessage/EpochStarted: TypeErrors
    errors = []
    step_b = b.handle_message_batch(
        0, [msgs[0], poison] + msgs[1:],
        on_error=lambda m, exc: errors.append((m, exc)))
    assert len(errors) == 1 and errors[0][0] is poison
    # every good message was still handled: byte-identical wire output
    # vs per-message delivery on a fresh same-seed node
    from hbbft_tpu.protocols.wire import encode_message

    b2 = make_node(infos, 1)
    ref = [encode_message(tm.message)
           for m in msgs for tm in b2.handle_message(0, m).messages]
    assert [encode_message(tm.message)
            for tm in step_b.messages] == ref
    # and without on_error the poison raises
    with pytest.raises(TypeError):
        make_node(infos, 1).handle_message_batch(0, [poison])


def _cluster_ledger(cfg_kwargs, txs, *, legacy=False):
    """Run a LocalCluster to ≥3 epochs, return the common digest-chain
    prefix across its nodes (the consistency assert is internal)."""
    from hbbft_tpu.net.cluster import ClusterConfig, LocalCluster

    async def scenario():
        cfg = ClusterConfig(n=4, seed=33, batch_size=6, **cfg_kwargs)
        cluster = LocalCluster(cfg)
        await cluster.start()
        try:
            if legacy:
                # sever the batch callback: _recv_chunk falls back to
                # the original one-pump-event-per-frame delivery
                for rt in cluster.runtimes:
                    rt.transport.on_peer_batch = None
            client = await cluster.client(0)
            for tx in txs:
                assert await client.submit(tx) == 0
            for tx in txs:
                await client.wait_committed(tx, timeout_s=45)
            await cluster.wait_epochs(3, timeout_s=45)
            prefix = cluster.common_digest_prefix()
            assert len(prefix) >= 3
            for rt in cluster.runtimes:
                assert rt.decode_failures == 0
            return prefix
        finally:
            await cluster.stop()

    return asyncio.run(asyncio.wait_for(scenario(), SMOKE_TIMEOUT_S))


def test_batch_path_ledger_matches_per_message_path():
    """Same seed, same txs: the chunk-batched receive path and the
    legacy per-message path commit byte-identical ledgers."""
    txs = [b"batch-eq-%02d" % i for i in range(12)]
    batched = _cluster_ledger({}, txs, legacy=False)
    legacy = _cluster_ledger({}, txs, legacy=True)
    n = min(len(batched), len(legacy))
    assert n >= 3
    # one run may sample an extra committed epoch before stop; the
    # common prefix is the determinism claim
    assert batched[:n] == legacy[:n]


def test_ingress_worker_cluster_consistency():
    """The worker-thread ingress path keeps every node on one ledger
    (cross-node byte-identity; the internal consistency assert of
    common_digest_prefix is the claim) and strikes nobody."""
    txs = [b"worker-%02d" % i for i in range(12)]
    prefix = _cluster_ledger({"ingress_workers": True}, txs)
    assert len(prefix) >= 3


class _FakeIngress:
    def __init__(self):
        self.admitted = []

    def frame_admitted(self, peer_id, n):
        self.admitted.append((peer_id, n))


class _FakeStats:
    def __init__(self):
        self.frames = 0
        self.bytes = 0

    def frame_recv_batch(self, nframes, nbytes):
        self.frames += nframes
        self.bytes += nbytes


class _FakeTransport:
    def __init__(self):
        from hbbft_tpu.net.framing import DEFAULT_MAX_FRAME

        self.max_frame = DEFAULT_MAX_FRAME
        self.ingress = _FakeIngress()
        self.stats = _FakeStats()
        self.cost_model = None
        self.trace = None
        self.batches = []
        self.on_peer_batch = (
            lambda peer, items: self.batches.append((peer, items)))


class _FakeProto:
    def __init__(self, loop):
        self.loop = loop
        self.failures = []

    def _fail(self, exc):
        self.failures.append(exc)


def _worker_fuzz_case(chunks):
    """Feed ``chunks`` to one PeerIngressWorker under a live loop;
    return (transport, proto) after the worker has gone quiet."""
    from hbbft_tpu.net.ingress import PeerIngressWorker

    async def scenario():
        loop = asyncio.get_running_loop()
        t = _FakeTransport()
        proto = _FakeProto(loop)
        worker = PeerIngressWorker(t, "peer-X", writer=None,
                                   session=b"\x00" * 8)
        worker.bind(proto)
        try:
            for chunk in chunks:
                worker.feed(chunk)
            for _ in range(200):  # drain: callbacks land via the loop
                await asyncio.sleep(0.01)
                if not worker.backlog_over() and (
                        t.batches or proto.failures):
                    break
            await asyncio.sleep(0.05)
        finally:
            worker.stop()
        return t, proto

    return asyncio.run(asyncio.wait_for(scenario(), 30))


def test_ingress_worker_decodes_and_attributes_garbage():
    """Well-framed chunks decode off-thread into (payload, msg) pairs;
    payloads that frame correctly but decode to garbage surface as
    (payload, None) — the runtime's strike path — all attributed to the
    feeding peer.  Torn/corrupt FRAMING kills the connection via
    proto._fail with a FrameError, exactly like the inline path."""
    from hbbft_tpu.net import framing

    good = framing.encode_frame(framing.MSG, b"not-a-real-message")
    t, proto = _worker_fuzz_case([good])
    assert not proto.failures
    assert len(t.batches) == 1
    peer, items = t.batches[0]
    assert peer == "peer-X"
    # framed fine, decoded to garbage: delivered as (payload, None) so
    # the runtime strikes THIS peer
    assert items == [(b"not-a-real-message", None)]
    assert t.ingress.admitted == [("peer-X", 1)]
    assert t.stats.frames == 1

    # a torn frame (length prefix promising more than ever arrives) is
    # fine — the decoder waits — but a corrupted length prefix blowing
    # past the frame cap is a FrameError, marshalled back to the loop
    frame = bytearray(framing.encode_frame(framing.MSG, b"payload"))
    frame[0] = 0xFF  # ~4 GiB announced length
    t, proto = _worker_fuzz_case([bytes(frame)])
    assert not t.batches
    assert len(proto.failures) == 1
    assert isinstance(proto.failures[0], framing.FrameError)


def test_ingress_worker_split_frames_reassemble():
    """A frame torn across arbitrary chunk boundaries reassembles into
    the same delivery as one contiguous chunk — the worker owns the
    decoder state just like the loop did."""
    from hbbft_tpu.net import framing

    payload = b"x" * 300
    frame = framing.encode_frame(framing.MSG, payload)
    t, proto = _worker_fuzz_case(
        [frame[:7], frame[7:8], frame[8:150], frame[150:]])
    assert not proto.failures
    assert [it for _p, b in t.batches for it in b] == [(payload, None)]

"""Property tests for the masked batched (array-mode) protocol paths.

Reference analog: ``tests/net/proptest.rs :: NetworkDimension`` — the
reference sweeps (n, f) network shapes with seeded randomness; here the
swept space is (n, delivery-drop patterns, tamper patterns), and the
assertions are:

- **RBC**: verdict-for-verdict equality (delivered / fault / decoded bytes)
  between ``BatchedRbc`` under random masks and the object-mode
  ``Broadcast`` oracle delivering exactly the mask-allowed edges.
- **ABA**: the agreement/validity/termination invariants under random
  partial-delivery masks (self-delivery forced), plus masked == full-
  delivery path equality on all-ones masks over random estimates.
  (Exact object-mode equality under arbitrary masks is NOT asserted: the
  bulk-synchronous Aux tie-break diverges by design — see
  ``parallel/aba.py``'s documented divergence note.)
"""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

hyp = pytest.importorskip("hypothesis")
from hypothesis import HealthCheck, given, settings  # noqa: E402
from hypothesis import strategies as st  # noqa: E402

from hbbft_tpu.parallel.aba import BatchedAba  # noqa: E402
from hbbft_tpu.parallel.rbc import BatchedRbc  # noqa: E402

from test_parallel_rbc import run_both, run_object_rbc  # noqa: E402

_SETTINGS = dict(
    max_examples=8,
    deadline=None,
    suppress_health_check=[HealthCheck.too_slow],
)


@st.composite
def rbc_scenario(draw):
    n = draw(st.integers(min_value=4, max_value=8))
    seed = draw(st.integers(min_value=0, max_value=2**31 - 1))
    drop = draw(st.sampled_from([0.05, 0.2, 0.4]))
    value_drop = draw(st.sampled_from([0.0, 0.2]))
    return n, seed, drop, value_drop


@given(rbc_scenario())
@settings(**_SETTINGS)
def test_rbc_masked_equals_object_oracle(case):
    n, seed, drop, value_drop = case
    rng = np.random.default_rng(seed)
    P = n
    values = [bytes(rng.integers(0, 256, size=3 * p + 1, dtype=np.uint8))
              for p in range(P)]
    vm = rng.random((P, n)) >= value_drop
    em = rng.random((n, n, P)) >= drop
    rm = rng.random((n, n, P)) >= drop
    for i in range(n):
        em[i, i, :] = True
        rm[i, i, :] = True
        vm[i, i] = True  # proposer keeps its own Value

    rbc, data, out = run_both(n, values, vm, em, rm)
    delivered_o, outputs_o, fault_o = run_object_rbc(n, values, vm, em, rm)

    np.testing.assert_array_equal(out["delivered"], delivered_o)
    np.testing.assert_array_equal(out["fault"], fault_o)
    from hbbft_tpu.parallel.rbc import unframe_value

    row_of = {int(r): i for i, r in enumerate(out["data_receivers"])}
    for (j, p), v in outputs_o.items():
        got = unframe_value(out["data"][row_of[j], p])
        assert got == v, (j, p)


@st.composite
def aba_scenario(draw):
    n = draw(st.integers(min_value=4, max_value=8))
    seed = draw(st.integers(min_value=0, max_value=2**31 - 1))
    drop = draw(st.sampled_from([0.0, 0.1, 0.25]))
    return n, seed, drop


@given(aba_scenario())
@settings(**_SETTINGS)
def test_aba_masked_invariants(case):
    """Agreement, validity, and termination under random delivery drops."""
    n, seed, drop = case
    f = (n - 1) // 3
    rng = np.random.default_rng(seed)
    aba = BatchedAba(n, f)
    est0 = rng.random((n, n)) < 0.5
    st_ = aba.init_state(jnp.asarray(est0))
    step = jax.jit(aba.epoch_step)
    for e in range(30):
        coins = jnp.asarray(rng.random((n,)) < 0.5)
        masks = {}
        if drop > 0.0:
            for name in ("bval_mask", "aux_mask", "conf_mask"):
                m = rng.random((n, n, n)) >= drop
                masks[name] = jnp.asarray(m)
        st_ = step(st_, coins, **masks)
        if bool(np.asarray(st_["decided"]).all()):
            break
    decided = np.asarray(st_["decided"])
    decision = np.asarray(st_["decision"])
    # termination is only guaranteed with eventual delivery: re-run final
    # epochs with full delivery until everyone decides
    extra = 0
    while not decided.all() and extra < 12:
        coins = jnp.asarray(rng.random((n,)) < 0.5)
        st_ = step(st_, coins)
        decided = np.asarray(st_["decided"])
        decision = np.asarray(st_["decision"])
        extra += 1
    assert decided.all(), "no termination after full-delivery epochs"
    # agreement: per instance, all nodes decide the same value
    for p in range(n):
        assert (decision[:, p] == decision[0, p]).all(), p
        # validity: the decision was some node's input estimate
        assert decision[0, p] in set(est0[:, p].tolist()), p


@given(st.integers(min_value=0, max_value=2**31 - 1))
@settings(**_SETTINGS)
def test_aba_allones_masks_equal_full_delivery(seed):
    n, f = 8, 2
    rng = np.random.default_rng(seed)
    aba = BatchedAba(n, f)
    est0 = jnp.asarray(rng.random((n, n)) < 0.5)
    st_m = aba.init_state(est0)
    st_f = aba.init_state(est0)
    step = jax.jit(aba.epoch_step)
    ones = jnp.ones((n, n, n), dtype=bool)
    for e in range(9):
        coins = jnp.asarray(rng.random((n,)) < 0.5)
        st_m = step(st_m, coins, bval_mask=ones, aux_mask=ones,
                    conf_mask=ones)
        st_f = step(st_f, coins)
        for k in ("est", "decided", "decision"):
            np.testing.assert_array_equal(
                np.asarray(st_m[k]), np.asarray(st_f[k]), err_msg=f"{k}@{e}"
            )
        if bool(np.asarray(st_f["decided"]).all()):
            break


@given(
    st.integers(min_value=0, max_value=2**31 - 1),
    st.integers(min_value=257, max_value=300),
)
@settings(max_examples=4, deadline=None,
          suppress_health_check=[HealthCheck.too_slow])
def test_gf16_reconstruct_roundtrip_random_erasures(seed, n):
    """GF(2^16) coder (the >256-node field) under random erasure patterns."""
    from hbbft_tpu.ops.rs import ReedSolomon16

    rng = np.random.default_rng(seed)
    f = (n - 1) // 3
    k = n - 2 * f
    coder = ReedSolomon16(k, n - k)
    data = rng.integers(0, 256, size=(k, 6), dtype=np.uint8)
    shards = coder.encode_np(data)
    # keep a random k-subset of survivor rows, erase the rest
    keep = tuple(sorted(int(i) for i in rng.permutation(n)[:k]))
    survivors = np.stack([shards[i] for i in keep])
    got = coder.reconstruct_data_np(survivors, keep)
    np.testing.assert_array_equal(got, data)


@given(
    st.integers(min_value=0, max_value=2**31 - 1),
    st.sampled_from([0.05, 0.15, 0.3]),
)
@settings(max_examples=2, deadline=None,  # the 264-node object oracle costs
          # ~25 s/example; two keep the default suite within budget
          suppress_health_check=[HealthCheck.too_slow])
def test_large_n_masked_rbc_equals_object_oracle(seed, drop):
    """The GF(2^16) masked path (N > 256) under RANDOM delivery schedules,
    verdict-for-verdict against the object-mode oracle — round-4 Weak #6:
    the field switch beyond the reference's 256-shard limit was previously
    exercised by fixed examples only.

    Fixed (n, P, receivers) keep one compiled executable across examples;
    the proposer count is small and the decode is ``receivers``-bounded —
    exactly how callers bound the O(N³) masked cost at this scale.
    """
    import random as pyrandom

    from hbbft_tpu.parallel.rbc import unframe_value

    n, P = 264, 2
    f = (n - 1) // 3
    rng = np.random.default_rng(seed)
    vals_rng = pyrandom.Random(seed)
    values = [
        bytes(vals_rng.randrange(256) for _ in range(9 + 5 * p))
        for p in range(P)
    ]
    vm = np.ones((P, n), dtype=bool)
    em = rng.random((n, n, P)) >= drop
    rm = rng.random((n, n, P)) >= drop
    for i in range(n):
        em[i, i, :] = True
        rm[i, i, :] = True
    receivers = np.array([0, 5], dtype=np.int32)

    rbc = BatchedRbc(n, f)
    assert rbc.large  # the GF(2^16) regime
    from hbbft_tpu.parallel.rbc import frame_values

    data = frame_values(values, rbc.k)
    out = jax.jit(rbc.run, static_argnames=())(
        jnp.asarray(data),
        value_mask=jnp.asarray(vm),
        echo_mask=jnp.asarray(em),
        ready_mask=jnp.asarray(rm),
        receivers=jnp.asarray(receivers),
    )
    delivered = np.asarray(out["delivered"])
    fault = np.asarray(out["fault"])
    datr = np.asarray(out["data"])

    delivered_o, outputs_o, fault_o = run_object_rbc(n, values, vm, em, rm)

    # the decode ran only for `receivers`; counting verdicts are global
    for row, j in enumerate(receivers):
        assert (delivered[row] == delivered_o[j]).all(), (seed, j)
        assert (fault[row] == fault_o[j]).all(), (seed, j)
        for p in range(P):
            if delivered_o[j][p]:
                got = unframe_value(datr[row, p])
                assert got == outputs_o[(j, p)], (seed, j, p)

"""net/framing.py: frame round-trips, size caps, cut streams, hello."""

import struct

import pytest

from hbbft_tpu.net import framing
from hbbft_tpu.net.framing import (
    FrameDecoder,
    FrameError,
    Hello,
    ROLE_CLIENT,
    ROLE_NODE,
)


def test_frame_roundtrip_all_kinds():
    dec = FrameDecoder()
    payloads = {
        framing.HELLO: b"h" * 40,
        framing.MSG: b"\x70" + b"\x00" * 16,
        framing.PING: struct.pack(">Q", 7),
        framing.TX: b"some transaction",
        framing.STATUS_REQ: b"",
    }
    stream = b"".join(
        framing.encode_frame(k, p) for k, p in payloads.items()
    )
    frames = dec.feed(stream)
    assert frames == list(payloads.items())
    assert dec.pending() == 0


def test_decoder_byte_by_byte():
    """Feeding one byte at a time never yields a partial frame."""
    frames_in = [
        (framing.MSG, b"alpha"),
        (framing.PING, b"\x00" * 8),
        (framing.TX, b""),
    ]
    stream = b"".join(framing.encode_frame(k, p) for k, p in frames_in)
    dec = FrameDecoder()
    out = []
    for i in range(len(stream)):
        out.extend(dec.feed(stream[i : i + 1]))
    assert out == frames_in
    assert dec.pending() == 0


def test_cut_stream_stays_pending():
    """A mid-frame cut yields nothing — no partial frames, no exception."""
    frame = framing.encode_frame(framing.MSG, b"payload-bytes")
    for cut in range(len(frame)):
        dec = FrameDecoder()
        assert dec.feed(frame[:cut]) == []
        assert dec.pending() == cut
        # the remainder completes it
        assert dec.feed(frame[cut:]) == [(framing.MSG, b"payload-bytes")]


def test_oversize_claim_rejected_before_buffering():
    dec = FrameDecoder(max_frame=1024)
    hostile = struct.pack(">I", 2**31) + b"\x02"
    with pytest.raises(FrameError, match="exceeds cap"):
        dec.feed(hostile)


def test_zero_length_frame_rejected():
    with pytest.raises(FrameError, match="zero-length"):
        FrameDecoder().feed(struct.pack(">I", 0))


def test_encode_frame_cap():
    with pytest.raises(FrameError, match="exceeds cap"):
        framing.encode_frame(framing.MSG, b"x" * 100, max_frame=50)


def test_hello_roundtrip():
    for nid in (3, "node-a", "client-7"):
        for role in (ROLE_NODE, ROLE_CLIENT):
            h = Hello(node_id=nid, role=role, cluster_id=b"cl/1",
                      era=2, epoch=17)
            assert framing.decode_hello(framing.encode_hello(h)) == h
            assert h.key == (2, 17)


def test_hello_version_mismatch_is_loud():
    h = Hello(node_id=0, role=ROLE_NODE, cluster_id=b"c", era=0, epoch=0)
    enc = bytearray(framing.encode_hello(h))
    enc[4:8] = struct.pack(">I", framing.PROTOCOL_VERSION + 1)
    with pytest.raises(FrameError, match="version mismatch"):
        framing.decode_hello(bytes(enc))


def test_hello_bad_magic_and_cuts():
    h = Hello(node_id="n", role=ROLE_NODE, cluster_id=b"cluster",
              era=1, epoch=5)
    enc = framing.encode_hello(h)
    with pytest.raises(FrameError, match="magic"):
        framing.decode_hello(b"XXXX" + enc[4:])
    # every truncation is a FrameError, never a crash or a partial Hello
    for cut in range(len(enc)):
        with pytest.raises(FrameError):
            framing.decode_hello(enc[:cut])
    with pytest.raises(FrameError, match="trailing"):
        framing.decode_hello(enc + b"\x00")

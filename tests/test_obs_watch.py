"""Live health plane — the watchtower acceptance scenarios.

- the incremental auditor's verdict document is BYTE-identical to the
  batch CLI core over the same journals (clean and equivocating runs);
- the adversary zoo detects online with exactly ONE classified incident
  each, no duplicates across poll ticks: equivocator (streaming-audit
  ``equivocation``), flood (``overload`` attribution from the window-edge
  spam drill), spoof (``overload`` with ``claimed_identities`` from
  guard ``auth_fail`` notes), crash (``target_down`` scrape hysteresis);
- a clean 4-node run raises ZERO false alarms over many ticks;
- the SLO rule engine: parsing, hysteresis engage/clear, per-episode
  re-alarm, bounded scrape fan-out with per-target failure accounting;
- a real socket cluster scraped end-to-end (``/status`` + ``/metrics``
  + ``/health``), with an injected spoof journal flipping the served
  ``/health`` document — the tier-1 smoke.
"""

import asyncio
import json
import os
import random

import pytest

from hbbft_tpu.obs import audit
from hbbft_tpu.obs.audit_stream import (
    IncrementalAuditor,
    JournalTailer,
    extract_incidents,
)
from hbbft_tpu.obs.flight import FlightRecorder
from hbbft_tpu.obs.metrics import Registry
from hbbft_tpu.obs.watch import (
    DEFAULT_SLOS,
    Ring,
    SloRule,
    Watchtower,
    normalize_perf_profile,
    parse_slo_rule,
)
from hbbft_tpu.protocols.dynamic_honey_badger import DynamicHoneyBadger
from hbbft_tpu.protocols.honey_badger import EncryptionSchedule
from hbbft_tpu.protocols.queueing_honey_badger import (
    QhbBatch,
    QueueingHoneyBadger,
    TxInput,
)
from hbbft_tpu.sim import NetBuilder, NullAdversary
from hbbft_tpu.sim.adversary import (
    EquivocatingAdversary,
    FutureEpochSpamAdversary,
)


# ===========================================================================
# Recorded sim runs (module-scoped: one keygen + one run per adversary)
# ===========================================================================


def _run_recorded(infos, root, adversary=None, faulty=(), txs=8,
                  max_cranks=60_000):
    """Crank-bounded recorded QHB run (see test_obs_audit for why the
    bound: a Byzantine proposer's queue never drains)."""
    n = len(infos)
    builder = NetBuilder(list(range(n))).adversary(
        adversary or NullAdversary()).faulty(list(faulty)).flight(root)
    net = builder.using_step(
        lambda nid: QueueingHoneyBadger(
            DynamicHoneyBadger(
                infos[nid], infos[nid].secret_key(),
                rng=random.Random(100 + nid),
                encryption_schedule=EncryptionSchedule.never(),
            ),
            batch_size=4, rng=random.Random(200 + nid),
        )
    )
    for i in range(txs):
        net.send_input(i % n, TxInput(b"watch-tx-%d" % i))
    while net.queue and net.cranks < max_cranks:
        net.crank()
    net.close_observers()
    return net


@pytest.fixture(scope="module")
def clean_root(shared_netinfo, tmp_path_factory):
    root = str(tmp_path_factory.mktemp("watch-clean"))
    net = _run_recorded(shared_netinfo(4, 13), root)
    assert sum(1 for o in net.nodes[0].outputs
               if isinstance(o, QhbBatch)) >= 2
    return root


@pytest.fixture(scope="module")
def equiv_root(shared_netinfo, tmp_path_factory):
    root = str(tmp_path_factory.mktemp("watch-equiv"))
    _run_recorded(shared_netinfo(4, 13), root,
                  adversary=EquivocatingAdversary(), faulty=[3])
    return root


@pytest.fixture(scope="module")
def flood_root(shared_netinfo, tmp_path_factory):
    """The window-edge spam drill — the flood shape that leaves journal
    evidence (counted future-epoch flood faults naming the spammer)."""
    root = str(tmp_path_factory.mktemp("watch-flood"))
    _run_recorded(shared_netinfo(4, 13), root,
                  adversary=FutureEpochSpamAdversary(spammer=3, seed=7),
                  faulty=[3])
    return root


def _snap(chain_len, mempool_frac=0.1):
    """A minimal healthy scrape snapshot for the scripted drivers."""
    return {
        "status": {"chain_len": chain_len},
        "metrics": {},
        "health": {
            "status": "ok",
            "headroom": {"mempool": {"used": 1, "cap": 10,
                                     "frac": mempool_frac}},
        },
    }


def _targets(n=4):
    return [("127.0.0.1", 9000 + i) for i in range(n)]


def _names(n=4):
    return [f"127.0.0.1:{9000 + i}" for i in range(n)]


# ===========================================================================
# Byte-identical streaming/batch parity
# ===========================================================================


@pytest.mark.parametrize("fixture", ["clean_root", "equiv_root"])
def test_incremental_verdict_byte_identical_to_batch(fixture, request):
    """The regression gate of the refactor: the tailer-fed incremental
    auditor and the batch CLI core produce byte-identical result
    documents over the same journal bytes."""
    root = request.getfixturevalue(fixture)
    res_batch, _journals = audit.run_audit([root])
    tailer = JournalTailer([root], IncrementalAuditor())
    tailer.finalize()
    res_inc = tailer.result()
    assert (json.dumps(res_inc.as_dict(), sort_keys=True)
            == json.dumps(res_batch.as_dict(), sort_keys=True))
    assert res_inc.verdict == res_batch.verdict


# ===========================================================================
# Adversary zoo: exactly ONE classified incident each
# ===========================================================================


def _tick_journal_only(root, ticks=6):
    """Drive a watchtower over a finished journal with empty scrape
    snapshots: every incident must come from the streaming audit, and
    repeated polls over the same evidence must never duplicate."""
    tower = Watchtower([], journal_roots=[root])
    try:
        per_tick = [tower.tick(float(i), snaps={}) for i in range(ticks)]
        tower.tailer.finalize()
        final = extract_incidents(tower.tailer.result())
        for fi in final:
            tower._raise_incident(float(ticks), fi["kind"],
                                  fi["severity"], fi["subject"],
                                  fi["detail"], [])
        return tower, per_tick
    finally:
        tower.close()


def test_equivocator_exactly_one_incident(equiv_root):
    tower, per_tick = _tick_journal_only(equiv_root)
    incs = list(tower.incidents)
    assert len(incs) == 1  # one faulty node == one incident, ever
    inc = incs[0]
    assert inc["kind"] == "equivocation" and inc["severity"] == "fault"
    assert inc["subject"] == "3"
    # it surfaced on the FIRST tick (online, not at finalize)
    assert len(per_tick[0]) == 1 and not any(per_tick[1:])
    doc = tower.health_doc()
    assert doc["status"] == "fault"
    assert doc["audit"]["verdict"] == "fault"


def test_flood_exactly_one_incident(flood_root):
    tower, _per_tick = _tick_journal_only(flood_root)
    incs = list(tower.incidents)
    assert len(incs) == 1
    inc = incs[0]
    assert inc["kind"] == "overload" and inc["subject"] == "3"
    assert inc["severity"] == "info"  # absorbed overload never alarms
    # ...and absorbed overload is not a fault: the verdict stays clean
    assert tower.health_doc()["audit"]["verdict"] == "clean"


def test_spoof_exactly_one_incident(tmp_path):
    """Identity spoofing evidence is the guard's ``auth_fail`` note
    (the authenticated transport's attribution: attacker endpoint +
    claimed identity).  Many notes, one incident."""
    root = str(tmp_path / "spoof")
    rec = FlightRecorder(os.path.join(root, "0"), "0",
                         clock=lambda: 1.0)
    for _ in range(3):
        rec.note("guard",
                 "kind=auth_fail peer='10.0.0.9:555' claimed=2")
    rec.close()
    tower, per_tick = _tick_journal_only(root)
    incs = list(tower.incidents)
    assert len(incs) == 1
    assert incs[0]["kind"] == "overload"
    assert incs[0]["subject"] == "'10.0.0.9:555'"  # attacker, not victim
    assert len(per_tick[0]) == 1 and not any(per_tick[1:])
    over = tower.tailer.result().overload_incidents
    assert over[0]["claimed_identities"] == ["2"]


def test_crash_target_down_exactly_one_incident():
    """Crash-stop detection is the scrape path: the implicit
    ``target_up>=1`` rule engages after ``engage_ticks`` consecutive
    missed scrapes and raises exactly one ``target_down``."""
    tower = Watchtower(_targets(), engage_ticks=2, clear_ticks=2)
    try:
        names = _names()
        up = {n: _snap(5) for n in names}
        for i in range(2):
            assert tower.tick(float(i), snaps=up) == []
        down = dict(up)
        down[names[3]] = None  # node 3 crashes
        raised = []
        for i in range(2, 8):
            raised.extend(tower.tick(float(i), snaps=down))
        assert len(raised) == 1
        assert raised[0]["kind"] == "target_down"
        assert raised[0]["subject"] == names[3]
        assert list(tower.incidents) == raised
        doc = tower.health_doc()
        assert doc["status"] == "warn"
        assert doc["targets_up"] == 3
        assert {a["subject"] for a in doc["active_alerts"]} \
            == {names[3]}
    finally:
        tower.close()


def test_clean_run_zero_false_alarms(clean_root):
    """A healthy cluster + a clean journal over many ticks: no
    incidents of any kind, status ok, verdict clean."""
    tower = Watchtower(_targets(), journal_roots=[clean_root])
    try:
        names = _names()
        for i in range(12):
            snaps = {n: _snap(5 + i) for n in names}
            assert tower.tick(float(i), snaps=snaps) == []
        tower.tailer.finalize()
        assert extract_incidents(tower.tailer.result()) == []
        doc = tower.health_doc()
        assert doc["status"] == "ok"
        assert not doc["incidents"] and not doc["active_alerts"]
        assert doc["audit"]["verdict"] == "clean"
        assert doc["audit"]["records"] > 0  # it actually read evidence
    finally:
        tower.close()


# ===========================================================================
# SLO rules, hysteresis, bounded scraping
# ===========================================================================


def test_slo_rule_parsing():
    r = parse_slo_rule("epoch_lag<=6")
    assert r == SloRule("epoch_lag", "<=", 6.0)
    assert r.breached(7.0) and not r.breached(6.0)
    f = parse_slo_rule("epochs_per_s>=0.5")
    assert f.breached(0.4) and not f.breached(0.5)
    assert f.text == "epochs_per_s>=0.5"
    for bad in ("nope", "x==1", "<=3", "lag<=abc"):
        with pytest.raises(ValueError):
            parse_slo_rule(bad)


def test_ring_is_bounded_and_rates():
    ring = Ring(maxlen=4)
    assert ring.last is None and ring.rate() is None
    for i in range(10):
        ring.push(float(i), float(2 * i))
    assert ring.last == 18.0
    assert len(ring._buf) == 4  # bounded: old samples evicted
    assert ring.rate() == pytest.approx(2.0)


def test_straggler_hysteresis_one_incident_per_episode():
    """A held breach alarms once; a flap never alarms; a NEW episode
    after a full clear alarms again."""
    tower = Watchtower(_targets(), engage_ticks=2, clear_ticks=2)
    try:
        names = _names()

        def snaps(lagging):
            out = {n: _snap(20) for n in names}
            if lagging:
                out[names[3]] = _snap(4)  # lag 16 > default ceiling 6
            return out

        t = iter(range(100))
        # one-tick flap: below engage_ticks, no alarm
        assert tower.tick(float(next(t)), snaps=snaps(True)) == []
        assert tower.tick(float(next(t)), snaps=snaps(False)) == []
        # held breach: alarms exactly once, then stays silent
        raised = []
        for _ in range(5):
            raised.extend(tower.tick(float(next(t)), snaps=snaps(True)))
        assert [i["kind"] for i in raised] == ["straggler"]
        assert raised[0]["subject"] == names[3]
        assert tower.health_doc()["status"] == "warn"
        # full clear, then a new episode: alarms exactly once more
        for _ in range(3):
            tower.tick(float(next(t)), snaps=snaps(False))
        assert tower.health_doc()["status"] == "ok"
        raised2 = []
        for _ in range(4):
            raised2.extend(tower.tick(float(next(t)),
                                      snaps=snaps(True)))
        assert [i["kind"] for i in raised2] == ["straggler"]
        assert len(tower.incidents) == 2
    finally:
        tower.close()


def test_normalize_perf_profile_accepts_frozen_doc_and_flat_forms():
    frozen = {"segments": {"msg": {"mean_s": 0.001},
                           "bogus": {"mean_s": "nan?"},
                           "zero": {"mean_s": 0.0}},
              "epochs_per_s": 12.0}
    assert normalize_perf_profile(frozen) == {"msg": 0.001}
    flat = {"msg": 0.002, "input": "junk", "neg": -1.0}
    assert normalize_perf_profile(flat) == {"msg": 0.002}
    assert normalize_perf_profile(None) == {}
    assert normalize_perf_profile([1, 2]) == {}


def _perf_snaps_factory(names, per_tick_events=50):
    """Scripted scrapes whose pump-segment counters advance by
    ``mean_s * events`` per tick — cumulative, like a real /metrics."""
    cum = {n: [0.0, 0.0] for n in names}

    def snaps(mean_by_name, events=per_tick_events):
        out = {}
        for n in names:
            mean = mean_by_name.get(n, 0.001)
            cum[n][0] += mean * events
            cum[n][1] += events
            s = _snap(20)
            s["metrics"] = {
                "hbbft_pump_segment_seconds_sum":
                    [({"segment": "msg"}, cum[n][0])],
                "hbbft_pump_segment_seconds_count":
                    [({"segment": "msg"}, float(cum[n][1]))],
            }
            out[n] = s
        return out

    return snaps


def test_perf_sentinel_one_incident_per_episode_zero_false_alarms():
    """The perf-drift sentinel: live per-window segment means compared
    against the frozen same-host profile through the standard SLO
    hysteresis — a held 3x slowdown on one node alarms exactly once as
    ``perf_regression``, clean scrapes at the profile never alarm, and
    a second slowdown episode after a full clear alarms exactly once
    more."""
    names = _names(2)
    snaps = _perf_snaps_factory(names)
    tower = Watchtower(_targets(2),
                       perf_profile={"segments":
                                     {"msg": {"mean_s": 0.001}}},
                       perf_ratio=2.0, perf_min_events=10,
                       engage_ticks=2, clear_ticks=2)
    try:
        t = iter(range(100))
        # clean: live means at the profile — zero alarms over many
        # ticks (the first scrape only primes the per-target delta)
        for _ in range(6):
            assert tower.tick(float(next(t)), snaps=snaps({})) == []
        # node 1's msg segment goes 3x the frozen mean, held
        slow = {names[1]: 0.003}
        raised = []
        for _ in range(5):
            raised.extend(tower.tick(float(next(t)), snaps=snaps(slow)))
        assert [(i["kind"], i["subject"]) for i in raised] \
            == [("perf_regression", names[1])]
        # recovery clears the episode; a NEW slowdown alarms once more
        for _ in range(3):
            assert tower.tick(float(next(t)), snaps=snaps({})) == []
        raised2 = []
        for _ in range(4):
            raised2.extend(tower.tick(float(next(t)),
                                      snaps=snaps(slow)))
        assert [i["kind"] for i in raised2] == ["perf_regression"]
        assert len([i for i in tower.incidents
                    if i["kind"] == "perf_regression"]) == 2
    finally:
        tower.close()


def test_perf_sentinel_ignores_low_event_windows_and_unarmed_tower():
    # below perf_min_events the drifted window is noise, not evidence
    names = _names(2)
    snaps = _perf_snaps_factory(names)
    tower = Watchtower(_targets(2),
                       perf_profile={"msg": 0.001},
                       perf_ratio=2.0, perf_min_events=10,
                       engage_ticks=2, clear_ticks=2)
    try:
        slow = {names[0]: 0.005}
        for i in range(5):
            assert tower.tick(float(i),
                              snaps=snaps(slow, events=5)) == []
    finally:
        tower.close()

    # no profile → the rule is never armed, drifted scrapes are ignored
    snaps2 = _perf_snaps_factory(names)
    bare = Watchtower(_targets(2), engage_ticks=2, clear_ticks=2)
    try:
        assert not any(r.signal == "perf_drift_ratio"
                       for r in bare.rules)
        for i in range(5):
            assert bare.tick(float(i),
                             snaps=snaps2({names[0]: 0.05})) == []
    finally:
        bare.close()


def test_custom_cluster_slo_floor():
    """A cluster-scoped rule (epochs/s floor) over the ring-derived
    head rate."""
    tower = Watchtower(_targets(2), slos=DEFAULT_SLOS
                       + ("epochs_per_s>=1.0",),
                       engage_ticks=2, clear_ticks=2)
    try:
        names = _names(2)
        raised = []
        for i in range(6):  # head frozen at 5 → rate 0 < 1.0 floor
            raised.extend(tower.tick(float(i),
                                     snaps={n: _snap(5) for n in names}))
        assert [i["kind"] for i in raised] == ["slo_epochs_per_s"]
        assert raised[0]["subject"] == "cluster"
    finally:
        tower.close()


def test_degrade_activity_rule_is_per_node():
    """``degrade_active<=0`` alarms on exactly the degraded node."""
    tower = Watchtower(_targets(2), slos=("degrade_active<=0",),
                       engage_ticks=2, clear_ticks=2)
    try:
        names = _names(2)
        raised = []
        for i in range(4):
            snaps = {n: _snap(5) for n in names}
            snaps[names[1]]["status"]["degraded"] = {"active": True,
                                                     "level": 2}
            raised.extend(tower.tick(float(i), snaps=snaps))
        assert [(i["kind"], i["subject"]) for i in raised] \
            == [("slo_degrade_active", names[1])]
    finally:
        tower.close()


def test_scrape_fanout_is_bounded_and_failures_counted():
    """The satellite contract: concurrency-capped pool, per-target
    failure accounting, and a dead target never raises."""
    calls = []

    def fetch(host, port, timeout_s):
        calls.append((host, port, timeout_s))
        if port == 9001:
            return None           # down target
        if port == 9002:
            raise OSError("boom")  # misbehaving fetch: counted, not raised
        return _snap(3)

    reg = Registry()
    tower = Watchtower(_targets(3), scrape_workers=2,
                       scrape_timeout_s=0.5, fetch=fetch, registry=reg)
    try:
        assert tower._pool._max_workers == 2  # capped below target count
        snaps = tower.scrape()
        assert len(calls) == 3 and all(c[2] == 0.5 for c in calls)
        assert snaps["127.0.0.1:9000"] is not None
        assert snaps["127.0.0.1:9001"] is None
        assert snaps["127.0.0.1:9002"] is None
        fails = {labels["target"]: child.get()
                 for labels, child in tower._c_scrape_fail.series()
                 if child.get()}
        assert fails == {"127.0.0.1:9001": 1.0, "127.0.0.1:9002": 1.0}
        assert tower._g_targets_up.value() == 1
    finally:
        tower.close()


# ===========================================================================
# Socket-cluster smoke (tier 1: one real scrape + incident end-to-end)
# ===========================================================================


def test_socket_cluster_watchtower_smoke(tmp_path):
    """A real 4-node TCP cluster scraped by a live watchtower: all
    targets up, zero alarms while healthy — then an injected spoof
    journal (guard ``auth_fail`` evidence) raises exactly one incident
    and flips the served ``/health`` document."""
    from hbbft_tpu.net.cluster import ClusterConfig, LocalCluster
    from hbbft_tpu.obs.http import http_get
    from hbbft_tpu.obs.watch import _serve_health

    flight_root = str(tmp_path / "flight")

    async def scenario():
        cfg = ClusterConfig(n=4, seed=21, batch_size=4,
                            flight_dir=flight_root)
        cluster = LocalCluster(cfg)
        await cluster.start()
        tower = Watchtower(
            [cluster.metrics_addrs[nid] for nid in range(4)],
            journal_roots=[flight_root], scrape_timeout_s=2.0)
        try:
            client = await cluster.client(0)
            for i in range(6):
                assert await client.submit(b"watch-smoke-%d" % i) == 0
            await cluster.wait_epochs(1, timeout_s=30)
            new = await asyncio.to_thread(tower.tick, 0.0)
            assert new == []  # healthy cluster: no incidents
            doc = tower.health_doc()
            assert doc["targets_up"] == 4
            assert doc["status"] == "ok"
            # real signals flowed out of the scraped surfaces
            lags = [v for k, v in doc["signals"].items()
                    if k.startswith("epoch_lag@")]
            assert len(lags) == 4
            assert any(k.startswith("mempool_frac@")
                       for k in doc["signals"])
            # inject spoof evidence next to the cluster's journals
            rec = FlightRecorder(os.path.join(flight_root, "intruder"),
                                 "intruder", clock=lambda: 1.0)
            rec.note("guard",
                     "kind=auth_fail peer='6.6.6.6:666' claimed=0")
            rec.close()
            raised = await asyncio.to_thread(tower.tick, 1.0)
            assert [i["kind"] for i in raised] == ["overload"]
            assert raised[0]["subject"] == "'6.6.6.6:666'"
            # second tick over the same evidence: no duplicate
            assert await asyncio.to_thread(tower.tick, 2.0) == []
            # the aggregated document is served over HTTP
            addr = _serve_health(tower, "127.0.0.1", 0)
            host, port = addr
            served = json.loads(await asyncio.to_thread(
                http_get, host, port, "/health"))
            assert served["targets_up"] == 4
            assert [i["kind"] for i in served["incidents"]] \
                == ["overload"]
            metrics_text = await asyncio.to_thread(
                http_get, host, port, "/metrics")
            assert "hbbft_health_ticks_total 3" in metrics_text
            assert "hbbft_health_incidents_total" in metrics_text
        finally:
            tower.close()
            await cluster.stop()

    asyncio.run(scenario())


def test_watch_cli_iterations_and_journal_out(tmp_path, clean_root):
    """The ``python -m hbbft_tpu.obs.watch`` surface: bounded
    iterations, journal tailing, and HealthIncident records landing in
    the watchtower's own journal (kept OUTSIDE the audited roots)."""
    out_dir = str(tmp_path / "watch-journal")
    from hbbft_tpu.obs import watch as watch_mod

    rc = watch_mod.main([
        "--targets", "", "--nodes", "0",
        "--journals", clean_root,
        "--iterations", "2", "--interval", "0.01",
        "--journal-out", out_dir, "--json",
    ])
    assert rc == 0
    # a clean journal produced no incident records, but the watchtower's
    # own journal exists and is well-formed (hello + no incidents)
    from hbbft_tpu.obs.flight import read_journal

    j = read_journal(os.path.join(out_dir))
    assert j.node == "watchtower"
    kinds = [type(rec).__name__ for _inc, rec in j.records]
    assert "HealthIncident" not in kinds

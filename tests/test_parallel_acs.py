"""Batched ABA / ACS / HoneyBadger-epoch vs properties and object mode.

The batched pipeline must (a) satisfy agreement/validity/termination on
its own, and (b) commit the same batch as the object-mode HoneyBadger on
the same inputs (happy path and crashed-proposer cases).
"""

import random

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from hbbft_tpu.netinfo import NetworkInfo
from hbbft_tpu.parallel.aba import BatchedAba
from hbbft_tpu.parallel.acs import BatchedAcs, BatchedHoneyBadgerEpoch
from hbbft_tpu.parallel.rbc import unframe_value
from hbbft_tpu.protocols.honey_badger import (
    Batch,
    EncryptionSchedule,
    HoneyBadger,
)
from hbbft_tpu.sim import NetBuilder, NullAdversary

_INFO_CACHE = {}


def infos_for(n, seed=13):
    key = (n, seed)
    if key not in _INFO_CACHE:
        _INFO_CACHE[key] = NetworkInfo.generate_map(
            list(range(n)), random.Random(seed)
        )
    return _INFO_CACHE[key]


def run_aba(n, f, est0, coins, max_epochs=12):
    aba = BatchedAba(n, f)
    st = aba.init_state(jnp.asarray(est0))
    step = jax.jit(aba.epoch_step)
    for e in range(max_epochs):
        st = step(st, jnp.asarray(coins(e)))
        if bool(np.asarray(st["decided"]).all()):
            break
    return {k: np.asarray(v) for k, v in st.items()}


@pytest.mark.parametrize("n,f", [(4, 1), (7, 2), (10, 3)])
def test_batched_aba_validity_and_agreement(n, f):
    P = n
    # unanimous true: epoch-0 fixed coin true → immediate decision
    st = run_aba(n, f, np.ones((n, P), bool), lambda e: np.zeros(P, bool))
    assert st["decided"].all() and st["decision"].all() and st["epoch"] == 1
    # unanimous false: decides false on the epoch-1 fixed coin
    st = run_aba(n, f, np.zeros((n, P), bool), lambda e: np.zeros(P, bool))
    assert st["decided"].all() and not st["decision"].any()
    # mixed inputs: agreement per instance, termination
    rng = np.random.default_rng(n)
    st = run_aba(
        n, f, rng.random((n, P)) < 0.5, lambda e: rng.random(P) < 0.5
    )
    assert st["decided"].all()
    for p in range(P):
        assert len(set(st["decision"][:, p])) == 1


def test_batched_acs_happy_path_and_agreement():
    n, f = 7, 2
    acs = BatchedAcs(n, f)
    values = [b"v%d" % p * (p + 1) for p in range(n)]
    out = acs.run(values)
    acc = out["accepted"]
    assert (acc == acc[0]).all()
    assert acc[0].all()
    for p in range(n):
        assert unframe_value(out["data"][0, p]) == values[p]


def test_batched_acs_excludes_crashed_proposers():
    n, f = 7, 2
    acs = BatchedAcs(n, f)
    values = [b"v%d" % p for p in range(n)]
    vm = np.ones((n, n), bool)
    vm[0, :] = False  # proposer 0 crashes before sending Values
    # (the proposer's own Value is always self-delivered, so excluding 4
    # others leaves 4 < n−f = 5 echoes)
    vm[5, 3:] = False
    out = acs.run(values, value_mask=jnp.asarray(vm))
    acc = out["accepted"]
    assert (acc == acc[0]).all()
    assert not acc[0][0] and not acc[0][5]
    assert acc[0].sum() >= n - f
    for p in np.flatnonzero(acc[0]):
        assert unframe_value(out["data"][0, p]) == values[p]


@pytest.mark.parametrize("encrypt", [True, False], ids=["tpke", "plain"])
def test_batched_hb_epoch_matches_object_mode(encrypt):
    n = 4
    infos = infos_for(n)
    contribs = {i: f"contribution-{i}".encode() for i in range(n)}

    # batched epoch
    hb = BatchedHoneyBadgerEpoch(infos, session_id=b"hb-test")
    batch_b, detail = hb.run(contribs, random.Random(7), encrypt=encrypt)
    acc = detail["accepted"]
    assert (acc == acc[0]).all()

    # object mode, same contributions
    sched = (
        EncryptionSchedule.always() if encrypt else EncryptionSchedule.never()
    )
    net = NetBuilder(list(range(n))).adversary(NullAdversary()).using_step(
        lambda nid: HoneyBadger.builder(infos[nid])
        .session_id(b"hb-test")
        .encryption_schedule(sched)
        .rng(random.Random(1000 + nid))
        .build()
    )
    for nid in net.node_ids():
        net.send_input(nid, contribs[nid])
    net.run_to_quiescence()
    object_batches = [
        [o for o in net.nodes[nid].outputs if isinstance(o, Batch)]
        for nid in net.node_ids()
    ]
    assert all(len(b) == 1 for b in object_batches)
    assert batch_b == object_batches[0][0].contributions_map()


def test_aba_fast_path_matches_masked_path():
    """Maskless ABA epochs must evolve identically to all-ones-mask epochs."""
    n, f, P = 7, 2, 6
    aba = BatchedAba(n, f)
    rng = np.random.default_rng(8)
    st_f = aba.init_state(jnp.asarray(rng.random((n, P)) < 0.5))
    st_m = {k: v for k, v in st_f.items()}
    ones = jnp.ones((n, n, P), dtype=bool)
    step = jax.jit(aba.epoch_step)
    for e in range(6):
        coins = jnp.asarray(rng.random(P) < 0.5)
        st_f = step(st_f, coins)
        st_m = step(st_m, coins, bval_mask=ones, aux_mask=ones,
                    conf_mask=ones)
        for k in ("est", "decided", "decision"):
            np.testing.assert_array_equal(
                np.asarray(st_f[k]), np.asarray(st_m[k]), err_msg=f"{k}@{e}"
            )
        if bool(np.asarray(st_f["decided"]).all()):
            break
    assert bool(np.asarray(st_f["decided"]).all())


def test_batched_qhb_drains_queue_commit_once():
    """Multi-epoch transaction queueing over batched epochs: every injected
    tx commits exactly once, leftovers re-propose, queues drain."""
    import random

    from hbbft_tpu.netinfo import NetworkInfo
    from hbbft_tpu.parallel.qhb import BatchedQueueingHoneyBadger

    rng = random.Random(41)
    n = 4
    infos = NetworkInfo.generate_map(list(range(n)), rng)
    qhb = BatchedQueueingHoneyBadger(infos, batch_size=3, session_id=b"qhb-t")
    txs = [b"tx-%02d" % i for i in range(20)]
    for i, tx in enumerate(txs):
        qhb.push(i % n, tx)

    epochs = qhb.run_to_empty(rng)
    assert epochs >= 2  # batch_size 3 × 4 nodes < 20 txs → several epochs
    assert sorted(qhb.committed) == sorted(txs)  # exactly once each
    assert qhb.pending() == 0


def test_batched_epoch_deterministic():
    """Same seeds ⇒ bit-identical batched HB epoch results (the batched
    analog of the object-mode same-seed ⇒ identical-trace test)."""
    import random

    from hbbft_tpu.netinfo import NetworkInfo
    from hbbft_tpu.parallel.acs import BatchedHoneyBadgerEpoch

    infos = NetworkInfo.generate_map(list(range(4)), random.Random(11))
    contribs = {i: b"det-%d" % i * 3 for i in range(4)}
    hb = BatchedHoneyBadgerEpoch(infos, session_id=b"det")

    b1, d1 = hb.run(contribs, random.Random(5), encrypt=True)
    b2, d2 = hb.run(contribs, random.Random(5), encrypt=True)
    assert b1 == b2 == contribs
    for k in ("accepted", "delivered", "data"):
        np.testing.assert_array_equal(np.asarray(d1[k]), np.asarray(d2[k]))


def test_batched_qhb_pipelined_epochs_commit_once():
    """Epoch-axis overlap (§2.3 PP): the pipelined driver — epoch e+1's
    TPKE encrypt on a worker thread while epoch e's ACS runs — commits
    every injected transaction exactly once, like the sequential driver."""
    import random

    from hbbft_tpu.parallel.qhb import BatchedQueueingHoneyBadger

    n = 4
    infos = infos_for(n)
    qhb = BatchedQueueingHoneyBadger(
        infos, batch_size=6, session_id=b"pipelined-qhb"
    )
    txs = [b"ptx-%03d" % i for i in range(36)]
    rng = random.Random(71)
    for i, tx in enumerate(txs):
        qhb.push(qhb.ids[i % n], tx)

    total = 0
    epochs = 0
    while qhb.pending() > 0 and epochs < 16:
        total += qhb.run_epochs_pipelined(rng, 2)
        epochs += 2
    assert qhb.pending() == 0, "queue not drained"
    assert sorted(qhb.committed) == sorted(txs)      # exactly once each
    assert total == len(txs)


@pytest.mark.parametrize("encrypt", [True, False], ids=["tpke", "plain"])
def test_compact_epoch_equals_full(encrypt):
    """compact=True (device-side ACS reduction) must produce the identical
    Batch to the full-detail mode."""
    import random

    n = 4
    infos = infos_for(n)
    contribs = {i: bytes([65 + i]) * (4 + i) for i in range(n)}
    full = BatchedHoneyBadgerEpoch(infos, session_id=b"compact-cmp")
    b_full, d_full = full.run(dict(contribs), random.Random(9),
                              encrypt=encrypt)
    comp = BatchedHoneyBadgerEpoch(infos, session_id=b"compact-cmp",
                                   compact=True)
    b_comp, d_comp = comp.run(dict(contribs), random.Random(9),
                              encrypt=encrypt)
    assert b_comp == b_full == contribs
    assert d_comp["epochs"] == d_full["epochs"]
    np.testing.assert_array_equal(
        d_comp["accepted_row"], d_full["accepted"][0]
    )


def test_compact_epoch_equals_full_under_masks():
    """The compact path's receiver→row mapping and argmax-deliverer
    selection on PER-RECEIVER data rows (the masked, non-shared-row case)."""
    import random

    import jax.numpy as jnp_

    n = 4
    infos = infos_for(n)
    contribs = {i: b"masked-%d" % i * (i + 2) for i in range(n)}
    rng = np.random.default_rng(12)
    em = ~(rng.random((n, n, n)) < 0.25)
    for i in range(n):
        em[i, i, :] = True
    kw = dict(echo_mask=jnp_.asarray(em))

    full = BatchedHoneyBadgerEpoch(infos, session_id=b"mask-cmp")
    b_f, _ = full.run(dict(contribs), random.Random(5), **kw)
    comp = BatchedHoneyBadgerEpoch(infos, session_id=b"mask-cmp",
                                   compact=True)
    b_c, _ = comp.run(dict(contribs), random.Random(5), **kw)
    assert b_c == b_f

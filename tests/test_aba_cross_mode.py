"""Cross-mode ABA verdict equality on the round-aligned schedule class.

Round-4 verdict (Weak #5): the batched (bulk-synchronous) ABA deliberately
diverges from object mode under *arbitrary* delivery schedules (the Aux
tie-break when both values enter ``bin_values`` in one sub-round is
arrival-order-dependent in object mode and fixed to True-preference in
array mode).  This suite closes the gap by pinning down the schedule class
where the two coincide and asserting VERDICT equality on it, keeping the
invariant suite (test_parallel_property) for arbitrary masks.

The class — **round-aligned, True-first delivery**: all messages generated
in communication round t are delivered before any message of round t+1,
and within a round every BVal(True) is delivered before any BVal(False)
(everything else in any order).  The array epoch models exactly this round
structure: its relay fixpoint records each value's *crossing round* and
the Aux choice follows object mode's first-crossing rule, with the
same-round tie resolved True-first — which the within-round BVal order
realizes on the object side.  The hypothesis sweep asserts the DECISIONS
agree verdict-for-verdict — the property the protocol stack (Subset)
consumes.

Reference analog: ``tests/binary_agreement.rs`` drives input mixes through
schedules; coin values are the real threshold-signature coins in both
modes (same session nonce ⇒ bit-identical, see ``parallel/aba.coin_for``).
"""

import random

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

hyp = pytest.importorskip("hypothesis")
from hypothesis import HealthCheck, given, settings  # noqa: E402
from hypothesis import strategies as st  # noqa: E402

from hbbft_tpu.netinfo import NetworkInfo  # noqa: E402
from hbbft_tpu.parallel.aba import BatchedAba, coin_for  # noqa: E402
from hbbft_tpu.protocols.binary_agreement import BinaryAgreement  # noqa: E402

_INFOS = {}


def infos_for(n):
    if n not in _INFOS:
        _INFOS[n] = NetworkInfo.generate_map(
            list(range(n)), random.Random(97 + n)
        )
    return _INFOS[n]


SESSION = b"aba-cross-mode"


def run_object_round_aligned(n, est_col, shuffle_seed, max_rounds=64):
    """One object-mode ABA instance (proposer 0) driven round-aligned with
    the True-first tie order: every message of round t delivered before
    round t+1; within a round, BVal(True) before BVal(False), the rest in
    seeded-random order."""
    from hbbft_tpu.protocols.binary_agreement import BValMsg

    infos = infos_for(n)
    nodes = {
        i: BinaryAgreement(infos[i], SESSION, 0) for i in range(n)
    }
    rng = random.Random(shuffle_seed)
    ids = list(range(n))

    def expand(src, step):
        out = []
        for tm in step.messages:
            for dest in tm.target.resolve(ids, src):
                out.append((src, dest, tm.message))
        return out

    def tie_order(item):
        m = item[2]
        if isinstance(m, BValMsg):
            return 0 if m.value else 1
        return 2

    queue = []
    for i in ids:
        queue += expand(i, nodes[i].handle_input(bool(est_col[i])))
    rounds = 0
    while queue:
        if rounds >= max_rounds:
            raise RuntimeError("round-aligned ABA did not quiesce")
        rng.shuffle(queue)
        queue.sort(key=tie_order)  # stable: random within each class
        nxt = []
        for src, dest, m in queue:
            nxt += expand(dest, nodes[dest].handle_message(src, m))
        queue = nxt
        rounds += 1
    return {i: nodes[i].decision for i in ids}


def run_array_full_delivery(n, est_col, max_epochs=24):
    f = (n - 1) // 3
    aba = BatchedAba(n, f)
    infos = infos_for(n)
    est = jnp.asarray(
        np.broadcast_to(np.asarray(est_col, bool)[:, None], (n, 1))
    )
    st_ = aba.init_state(est)
    step = jax.jit(aba.epoch_step)
    for e in range(max_epochs):
        coins = jnp.asarray(
            np.array([coin_for(infos, SESSION, 0, e)], dtype=bool)
        )
        st_ = step(st_, coins)
        if bool(np.asarray(jnp.all(st_["decided"]))):
            break
    decided = np.asarray(st_["decided"])[:, 0]
    decision = np.asarray(st_["decision"])[:, 0]
    assert decided.all(), "array ABA did not terminate"
    return {i: bool(decision[i]) for i in range(n)}


@st.composite
def cross_mode_case(draw):
    n = draw(st.integers(min_value=4, max_value=7))
    bits = draw(st.integers(min_value=0, max_value=(1 << n) - 1))
    shuffle_seed = draw(st.integers(min_value=0, max_value=2**31 - 1))
    est = [(bits >> i) & 1 == 1 for i in range(n)]
    return n, est, shuffle_seed


@given(cross_mode_case())
@settings(max_examples=12, deadline=None,
          suppress_health_check=[HealthCheck.too_slow])
def test_round_aligned_object_equals_array_decisions(case):
    n, est, shuffle_seed = case
    obj = run_object_round_aligned(n, est, shuffle_seed)
    arr = run_array_full_delivery(n, est)
    assert None not in obj.values(), "object ABA did not terminate"
    assert obj == arr, (est, obj, arr)


def test_unanimous_inputs_decide_immediately_both_modes():
    for n, val in [(4, True), (7, False)]:
        est = [val] * n
        obj = run_object_round_aligned(n, est, shuffle_seed=1)
        arr = run_array_full_delivery(n, est)
        assert set(obj.values()) == {val}
        assert obj == arr

"""Membership lifecycle: snapshot state-sync join, bounded storage.

Covers the PR-8 tentpole end to end:

- ``JoinSnapshot`` image codec + DKG-transcript share derivation (a
  joiner that never saw the DKG decrypts its rows and regenerates the
  exact public key set — or refuses a tampered transcript loudly);
- the chunked transfer protocol: manifests, CRC'd chunks, NACKs, donor
  failover with resume (a donor killed mid-transfer costs a retry, not a
  restart) and multi-donor manifest confirmation;
- the live 4-node socket cluster join: DHB vote → DKG rotation →
  state-sync → activation — identical post-join ledgers, a clean
  forensic audit across the era boundary (with a concurrent
  crash-restart), and commits within 10 epochs of activation;
- restart-beyond-retention recovery through the same path;
- bounded storage: replay-log byte caps and flight-journal checkpoint
  truncation keep disk/memory under the configured ceilings, counted
  and visible in ``/status``.
"""

import asyncio
import hashlib
import os
import random
import zlib

import pytest

from hbbft_tpu.crypto import tc
from hbbft_tpu.net import framing
from hbbft_tpu.net.statesync import (
    SnapshotStore,
    StateSyncClient,
    StateSyncError,
    SyncChunk,
    SyncChunkReq,
    SyncManifest,
    SyncManifestReq,
    SyncNack,
)
from hbbft_tpu.obs.metrics import Registry
from hbbft_tpu.protocols.dynamic_honey_badger import (
    SignedKeyGenMsg,
    _keygen_payload,
    ser_ack,
    ser_part,
)
from hbbft_tpu.protocols.sync_key_gen import SyncKeyGen
from hbbft_tpu.snapshot import (
    JoinSnapshot,
    decode_join_snapshot,
    derive_secret_share,
    encode_join_snapshot,
)

CLUSTER_ID = b"statesync-test"


# ===========================================================================
# Unit: image codec + share derivation
# ===========================================================================


def _manual_dkg(n_old: int = 4, joiner_id: int = 9):
    """A committed DKG transcript among ``n_old`` present validators plus
    one absent candidate, exactly as DHB would commit it: every Part and
    every Ack signed by its sender, in deterministic order."""
    rng = random.Random(42)
    ids = list(range(n_old)) + [joiner_id]
    sks = {i: tc.SecretKey.random(rng) for i in ids}
    pub = {i: sks[i].public_key() for i in ids}
    threshold = (len(ids) - 1) // 3
    kgs = {
        i: SyncKeyGen(i, sks[i], pub, threshold, random.Random(100 + i))
        for i in range(n_old)
    }
    era = 0

    def signed(sender: int, kind: str, payload: bytes) -> SignedKeyGenMsg:
        return SignedKeyGenMsg(
            era, sender, kind, payload,
            sks[sender].sign(_keygen_payload(era, sender, kind, payload)),
        )

    transcript = []
    for dealer in range(n_old):
        part = kgs[dealer].generate_part()
        transcript.append(signed(dealer, "part", ser_part(part)))
        acks = []
        for i in range(n_old):
            outcome = kgs[i].handle_part(dealer, part)
            assert outcome.fault is None
            if outcome.ack is not None:
                acks.append((i, outcome.ack))
        for i, ack in acks:
            transcript.append(signed(i, "ack", ser_ack(ack)))
            for j in range(n_old):
                assert kgs[j].handle_ack(i, ack).fault is None
    assert all(kg.is_ready() for kg in kgs.values())
    pks0, share0 = kgs[0].generate()
    snap = JoinSnapshot(
        era=era + 1,
        pub_key_set_bytes=pks0.commitment.to_bytes(),
        pub_keys=tuple(sorted(
            ((i, pk.to_bytes()) for i, pk in pub.items()),
            key=lambda kv: repr(kv[0]))),
        encryption_schedule=("never", 0, 0),
        transcript=tuple(transcript),
        chain_head=hashlib.sha3_256(b"boundary").digest(),
        chain_len=7,
    )
    return snap, sks, pks0, share0, ids


def test_join_snapshot_roundtrip():
    snap, _sks, _pks, _share, _ids = _manual_dkg()
    image = encode_join_snapshot(snap)
    back = decode_join_snapshot(image)
    assert back == snap
    with pytest.raises(ValueError):
        decode_join_snapshot(image[:-1])
    with pytest.raises(ValueError):
        decode_join_snapshot(b"XX" + image)


def test_share_derivation_from_transcript():
    """The absent candidate replays the committed transcript, decrypts
    its rows, and signs with a share that COMBINES with a validator's —
    the cryptographic proof it joined the same key set."""
    snap, sks, pks, share0, ids = _manual_dkg()
    joiner = ids[-1]
    share_j = derive_secret_share(snap, joiner, sks[joiner])
    assert share_j is not None
    msg = b"joined-era-1"
    # joiner is the last index in the sorted id order
    j_index = sorted(ids).index(joiner)
    sigs = {0: share0.sign(msg), j_index: share_j.sign(msg)}
    combined = pks.combine_signatures(sigs)
    assert pks.public_key().verify(combined, msg)


def test_share_derivation_rejects_tampering():
    snap, sks, _pks, _share, ids = _manual_dkg()
    joiner = ids[-1]
    # a donor claiming a different public key set than the transcript
    # produces must be refused
    bad = JoinSnapshot(
        era=snap.era,
        pub_key_set_bytes=b"\x00" * len(snap.pub_key_set_bytes),
        pub_keys=snap.pub_keys,
        encryption_schedule=snap.encryption_schedule,
        transcript=snap.transcript,
        chain_head=snap.chain_head,
        chain_len=snap.chain_len,
    )
    with pytest.raises(ValueError, match="different public key set"):
        derive_secret_share(bad, joiner, sks[joiner])
    # a truncated transcript (DKG cannot complete) is refused too
    stub = JoinSnapshot(
        era=snap.era,
        pub_key_set_bytes=snap.pub_key_set_bytes,
        pub_keys=snap.pub_keys,
        encryption_schedule=snap.encryption_schedule,
        transcript=snap.transcript[:1],
        chain_head=snap.chain_head,
        chain_len=snap.chain_len,
    )
    with pytest.raises(ValueError, match="does not complete"):
        derive_secret_share(stub, joiner, sks[joiner])


def test_snapshot_store_serving():
    snap, _sks, _pks, _share, _ids = _manual_dkg()
    store = SnapshotStore(Registry(), chunk_bytes=1024)
    assert isinstance(store.handle(SyncManifestReq()), SyncNack)
    store.publish(snap)
    m = store.handle(SyncManifestReq())
    assert isinstance(m, SyncManifest)
    assert m.era == snap.era and m.chain_len == snap.chain_len
    chunks = []
    for i in range(m.n_chunks):
        ck = store.handle(SyncChunkReq(m.image_sha3, i))
        assert isinstance(ck, SyncChunk) and ck.index == i
        assert zlib.crc32(ck.data) == ck.crc
        chunks.append(ck.data)
    image = b"".join(chunks)
    assert len(image) == m.image_len
    assert hashlib.sha3_256(image).digest() == m.image_sha3
    assert decode_join_snapshot(image) == snap
    # nacks: wrong image, bad index
    assert isinstance(store.handle(SyncChunkReq(b"\x00" * 32, 0)),
                      SyncNack)
    assert isinstance(store.handle(SyncChunkReq(m.image_sha3,
                                                m.n_chunks)), SyncNack)


# ===========================================================================
# Transfer: failover + resume against scripted donors
# ===========================================================================


class _FakeDonor:
    """A minimal donor speaking HELLO + SYNC, optionally dying after
    serving ``die_after_chunks`` chunks (socket closed mid-transfer)."""

    def __init__(self, store: SnapshotStore, die_after_chunks=None):
        self.store = store
        self.die_after_chunks = die_after_chunks
        self.chunks_served = 0
        self.server = None
        self.addr = None

    async def start(self):
        self.server = await asyncio.start_server(self._serve,
                                                 host="127.0.0.1", port=0)
        self.addr = self.server.sockets[0].getsockname()[:2]
        return self.addr

    async def stop(self):
        self.server.close()
        await self.server.wait_closed()

    async def _serve(self, reader, writer):
        from hbbft_tpu.protocols import wire

        try:
            kind, payload = await framing.read_one_frame(reader)
            assert kind == framing.HELLO
            hello = framing.decode_hello(payload)
            reply = framing.Hello(node_id=0, role=framing.ROLE_NODE,
                                  cluster_id=hello.cluster_id,
                                  era=0, epoch=0)
            writer.write(framing.encode_frame(
                framing.HELLO, framing.encode_hello(reply)))
            await writer.drain()
            while True:
                kind, payload = await framing.read_one_frame(reader)
                if kind != framing.SYNC:
                    continue
                msg = wire.decode_message(payload)
                if isinstance(msg, SyncChunkReq):
                    if (self.die_after_chunks is not None
                            and self.chunks_served
                            >= self.die_after_chunks):
                        writer.close()
                        return
                    self.chunks_served += 1
                writer.write(framing.encode_frame(
                    framing.SYNC,
                    wire.encode_message(self.store.handle(msg))))
                await writer.drain()
        except (asyncio.IncompleteReadError, ConnectionError, OSError):
            return


def test_transfer_failover_resumes_on_second_donor():
    snap, _sks, _pks, _share, _ids = _manual_dkg()
    store = SnapshotStore(Registry(), chunk_bytes=2048)
    store.publish(snap)
    assert store.manifest.n_chunks >= 3, "image too small for the test"

    async def run():
        flaky = _FakeDonor(store, die_after_chunks=1)
        solid = _FakeDonor(store)
        a1 = await flaky.start()
        a2 = await solid.start()
        reg = Registry()
        client = StateSyncClient(
            [a1, a2], CLUSTER_ID, request_timeout_s=1.0,
            connect_timeout_s=1.0, min_manifest_confirm=2,
            backoff_base_s=0.05, registry=reg,
        )
        got = await client.fetch()
        await flaky.stop()
        await solid.stop()
        return got, reg, flaky, solid

    got, reg, flaky, solid = asyncio.run(run())
    assert got == snap
    text = reg.render_prometheus()
    assert "hbbft_sync_donor_failovers_total" in text
    # the flaky donor died mid-transfer; the solid one finished the image
    assert flaky.chunks_served == 1
    assert solid.chunks_served >= store.manifest.n_chunks - 1


def test_transfer_abandons_loudly_when_all_donors_die():
    snap, _sks, _pks, _share, _ids = _manual_dkg()
    store = SnapshotStore(Registry(), chunk_bytes=2048)
    store.publish(snap)

    async def run():
        d = _FakeDonor(store, die_after_chunks=0)
        addr = await d.start()
        reg = Registry()
        client = StateSyncClient(
            [addr], CLUSTER_ID, request_timeout_s=0.5,
            connect_timeout_s=0.5, max_donor_cycles=2,
            backoff_base_s=0.01, registry=reg,
        )
        with pytest.raises(StateSyncError, match="abandoned"):
            await client.fetch()
        await d.stop()
        return reg

    reg = asyncio.run(run())
    assert reg.get("hbbft_sync_transfers_abandoned_total").value() >= 1


def test_transfer_restarts_when_snapshot_rotates_mid_fetch():
    """Donors that rotate to a NEWER snapshot mid-transfer (old image →
    'unknown image' NACKs everywhere) make the client refresh manifests
    and restart on the new image instead of abandoning."""
    snap_old, _s, _p, _sh, _ids = _manual_dkg()
    snap_new = JoinSnapshot(
        era=snap_old.era + 1,
        pub_key_set_bytes=snap_old.pub_key_set_bytes,
        pub_keys=snap_old.pub_keys,
        encryption_schedule=snap_old.encryption_schedule,
        transcript=(),
        chain_head=hashlib.sha3_256(b"newer boundary").digest(),
        chain_len=snap_old.chain_len + 5,
    )
    store = SnapshotStore(Registry(), chunk_bytes=2048)
    store.publish(snap_old)
    old_sha = store.manifest.image_sha3

    class _RotatingDonor(_FakeDonor):
        """Publishes the newer snapshot after serving one chunk."""

        async def _serve(self, reader, writer):
            self._orig_handle = self.store.handle

            def handle(msg):
                if (isinstance(msg, SyncChunkReq)
                        and msg.image_sha3 == old_sha
                        and self.chunks_served >= 1):
                    self.store.publish(snap_new)
                return self._orig_handle(msg)

            self.store.handle = handle
            try:
                await super()._serve(reader, writer)
            finally:
                self.store.handle = self._orig_handle

    async def run():
        d = _RotatingDonor(store)
        addr = await d.start()
        reg = Registry()
        client = StateSyncClient(
            [addr], CLUSTER_ID, request_timeout_s=1.0,
            connect_timeout_s=1.0, backoff_base_s=0.01,
            max_donor_cycles=2, registry=reg,
        )
        got = await client.fetch()
        await d.stop()
        return got, reg

    got, reg = asyncio.run(run())
    assert got == snap_new, "client should land on the NEW snapshot"
    assert reg.get("hbbft_sync_transfers_abandoned_total").value() == 0


def test_manifest_quorum_required():
    """One donor alone cannot satisfy min_manifest_confirm=2."""
    snap, _sks, _pks, _share, _ids = _manual_dkg()
    store = SnapshotStore(Registry(), chunk_bytes=2048)
    store.publish(snap)

    async def run():
        d = _FakeDonor(store)
        addr = await d.start()
        client = StateSyncClient([addr], CLUSTER_ID,
                                 request_timeout_s=1.0,
                                 min_manifest_confirm=2)
        with pytest.raises(StateSyncError, match="agree"):
            await client.fetch()
        await d.stop()

    asyncio.run(run())


# ===========================================================================
# Live cluster: the membership lifecycle end-to-end
# ===========================================================================


async def _pump_wave(cluster, client, wave: int, count: int):
    txs = [b"join-%02d-%04d" % (wave, i) for i in range(count)]
    for tx in txs:
        status = await client.submit(tx)
        assert status == 0, f"tx rejected with {status}"
    for tx in txs:
        await client.wait_committed(tx, timeout_s=60)


def test_join_from_snapshot_live_cluster(tmp_path):
    """The acceptance incident: a validator with NO history joins a live
    committing 4-node socket cluster via DHB vote + DKG rotation +
    snapshot state-sync, with one concurrent crash-restart — identical
    ledgers, commits within 10 epochs of activation, clean audit across
    the era boundary (state-sync boundary corroborated)."""
    from hbbft_tpu.net.cluster import (
        ClusterConfig,
        LocalCluster,
        find_free_base_port,
    )
    from hbbft_tpu.obs.audit import run_audit

    flight_root = str(tmp_path / "flight")
    cfg = ClusterConfig(
        n=4, seed=3, batch_size=4,
        base_port=find_free_base_port(6),
        heartbeat_s=0.2, dead_after_s=1.5,
        flight_dir=flight_root,
    )

    async def scenario():
        cluster = LocalCluster(cfg)
        await cluster.start()
        try:
            client = await cluster.client(0)
            await _pump_wave(cluster, client, 0, cfg.batch_size * 2)
            # the join vote: every validator votes node 4 in; the DKG
            # rotation's boundary snapshot becomes fetchable everywhere
            cluster.vote_to_add(4)
            await cluster.wait_snapshot(min_era=1, timeout_s=60)
            # concurrent crash-restart while the join is in flight
            await cluster.restart_node(1)
            joiner = await cluster.activate_from_snapshot(
                4, donors=[0, 2, 3], min_manifest_confirm=2)
            activation_key = joiner.current_key()
            # traffic keeps flowing; the joiner must commit
            await _pump_wave(cluster, client, 1, cfg.batch_size * 2)

            async def joiner_commits():
                while not joiner.batches:
                    await asyncio.sleep(0.02)

            await asyncio.wait_for(joiner_commits(), 60)
            first = joiner.batches[0]
            assert first.era >= activation_key[0]
            assert (first.era, first.epoch) <= (
                activation_key[0], activation_key[1] + 10
            ), "joiner's first commit is not within 10 epochs"
            # every node, joiner included, agrees wherever chains
            # overlap — wait until even the restarted node's rebuilt
            # chain reaches past the joiner's boundary
            boundary = joiner.digest_chain_offset

            async def chains_overlap():
                while min(rt.chain_len for rt in cluster.runtimes) \
                        <= boundary:
                    await asyncio.sleep(0.02)

            await asyncio.wait_for(chains_overlap(), 60)
            prefix = cluster.common_digest_prefix()
            assert prefix, "joiner's chain never overlapped the donors'"
            assert joiner.digest_chain_offset >= 1, \
                "joiner should start mid-chain (snapshot boundary)"
            assert joiner.sq.algo.dhb.is_validator(), \
                "transcript replay should make the joiner a validator"
            assert joiner.sq.algo.dhb.netinfo.secret_key_share() \
                is not None
            docs = [rt.status_doc() for rt in cluster.runtimes]
            from hbbft_tpu.net.cluster import (
                assert_status_chains_consistent,
            )

            assert assert_status_chains_consistent(docs) > 0
        finally:
            await cluster.stop()

    asyncio.run(asyncio.wait_for(scenario(), 180))
    res, _journals = run_audit([flight_root])
    assert res.verdict == "clean", res.as_dict()
    # the audit saw the era boundary AND the state-sync join, and
    # corroborated the joiner's claimed boundary against a donor journal
    assert res.restarts.get("1", 0) == 1
    joins = [j for j in res.sync_joins if j["node"] == "4"]
    assert joins and joins[0]["verified_against"] is not None
    assert not res.sync_mismatches


def test_resync_after_retention_gap(tmp_path):
    """A validator whose outage outlived replay retention recovers via
    the SAME snapshot path (checkpoint rotation → state-sync), instead
    of wedging on replay_gaps."""
    from hbbft_tpu.net.cluster import (
        ClusterConfig,
        LocalCluster,
        find_free_base_port,
    )
    from hbbft_tpu.obs.audit import run_audit

    flight_root = str(tmp_path / "flight")
    cfg = ClusterConfig(
        n=4, seed=11, batch_size=4,
        base_port=find_free_base_port(5),
        heartbeat_s=0.2, dead_after_s=1.5,
        replay_retain_epochs=4,          # tiny: outages outlive it fast
        flight_dir=flight_root,
    )

    async def scenario():
        cluster = LocalCluster(cfg)
        await cluster.start()
        try:
            client = await cluster.client(0)
            await _pump_wave(cluster, client, 0, cfg.batch_size)
            # node 3 goes dark; the cluster outruns its replay retention
            await cluster.runtimes[3].stop()
            for wave in range(1, 4):
                await _pump_wave(cluster, client, wave,
                                 cfg.batch_size * 2)
            survivors = cluster.runtimes[:3]
            assert min(len(rt.batches) for rt in survivors) > \
                cfg.replay_retain_epochs
            # checkpoint rotation: a node-change vote to the CURRENT key
            # map runs a fresh DKG and rotates the era, re-arming
            # snapshot joins with a transcript node 3 can derive its new
            # share from
            cluster.runtimes = survivors
            cluster.vote_to_readd()
            await cluster.wait_snapshot(min_era=1, timeout_s=60)
            rejoined = await cluster.activate_from_snapshot(
                3, donors=[0, 1, 2], min_manifest_confirm=2)
            await _pump_wave(cluster, client, 9, cfg.batch_size * 2)

            async def caught_up():
                while not rejoined.batches:
                    await asyncio.sleep(0.02)

            await asyncio.wait_for(caught_up(), 60)
            assert rejoined.sq.algo.dhb.is_validator()
            assert rejoined.sq.algo.dhb.netinfo.secret_key_share() \
                is not None
            assert cluster.common_digest_prefix() is not None
            # the recovery replayed ZERO pre-boundary history
            assert rejoined.digest_chain_offset > 0
        finally:
            await cluster.stop()

    asyncio.run(asyncio.wait_for(scenario(), 180))
    res, _journals = run_audit([flight_root])
    assert res.verdict == "clean", res.as_dict()
    joins = [j for j in res.sync_joins if j["node"] == "3"]
    assert joins and not res.sync_mismatches


def test_bounded_storage_regression(tmp_path):
    """Replay logs and flight journals stay under their configured caps
    over a long-ish run, with truncations counted and visible in
    /status."""
    from hbbft_tpu.net.cluster import (
        ClusterConfig,
        LocalCluster,
        find_free_base_port,
    )

    flight_root = str(tmp_path / "flight")
    seg_bytes = 64 * 1024
    cfg = ClusterConfig(
        n=4, seed=5, batch_size=4,
        base_port=find_free_base_port(4),
        heartbeat_s=0.2, dead_after_s=1.5,
        replay_retain_epochs=256,        # epochs alone would not bound it
        replay_retain_bytes=16 * 1024,   # the byte cap must
        flight_dir=flight_root,
        flight_max_segment_bytes=seg_bytes,
        flight_max_segments=64,
        flight_retain_batches=8,         # checkpoint truncation
    )

    async def scenario():
        cluster = LocalCluster(cfg)
        await cluster.start()
        try:
            client = await cluster.client(0)
            for wave in range(6):
                await _pump_wave(cluster, client, wave,
                                 cfg.batch_size * 2)
            docs = [rt.status_doc() for rt in cluster.runtimes]
            for rt, doc in zip(cluster.runtimes, docs):
                # replay log honors the per-peer byte cap (+1 entry of
                # slack: the cap is enforced at the per-iteration prune)
                for peer, used in rt._replay_bytes.items():
                    assert used <= cfg.replay_retain_bytes + 4096, (
                        peer, used)
                assert "replay_truncations" in doc
                assert "replay_log_bytes" in doc
                assert doc["flight"]["truncations"] >= 0
            total_trunc = sum(
                doc["replay_truncations"] for doc in docs)
            assert total_trunc > 0, \
                "the byte cap never triggered — grow the run"
            flight_trunc = sum(
                doc["flight"]["truncations"] for doc in docs)
            assert flight_trunc > 0, \
                "checkpoint truncation never triggered"
        finally:
            await cluster.stop()

    asyncio.run(asyncio.wait_for(scenario(), 180))
    # on-disk bound: segments per node ≤ cap, each ≤ segment bytes + one
    # oversized record of slack
    for node_dir in os.listdir(flight_root):
        d = os.path.join(flight_root, node_dir)
        segs = os.listdir(d)
        assert len(segs) <= 64
        total = sum(os.path.getsize(os.path.join(d, s)) for s in segs)
        assert total <= 64 * (seg_bytes + 8192)


# ===========================================================================
# Audit: boundary verification
# ===========================================================================


def test_audit_flags_contradicted_sync_boundary(tmp_path):
    """A joiner claiming a boundary digest nobody committed is a fork."""
    from hbbft_tpu.obs.audit import audit
    from hbbft_tpu.obs.flight import (
        FlightObserver,
        FlightRecorder,
        read_journal,
    )
    from hbbft_tpu.traits import Step

    honest_head = hashlib.sha3_256(b"honest").digest()
    # donor journal: commits at indices 0 and 1
    donor_dir = str(tmp_path / "donor")
    rec = FlightRecorder(donor_dir, node="0", flavor="runtime")
    rec.record_commit(0, 0, 0, honest_head)
    rec.record_commit(0, 1, 1, hashlib.sha3_256(b"next").digest())
    rec.close()
    # joiner journal: claims it joined at index 1 with a DIFFERENT head
    joiner_dir = str(tmp_path / "joiner")
    rec2 = FlightRecorder(joiner_dir, node="9", flavor="runtime")
    lying_head = hashlib.sha3_256(b"lies").digest()
    rec2.note("statesync", f"index=1 head={lying_head.hex()}")
    rec2.record_commit(1, 0, 1, hashlib.sha3_256(b"whatever").digest())
    rec2.close()
    res = audit([read_journal(donor_dir), read_journal(joiner_dir)])
    assert res.sync_mismatches
    assert res.verdict == "fork"
    # and the honest version of the same claim is corroborated
    joiner2 = str(tmp_path / "joiner2")
    rec3 = FlightRecorder(joiner2, node="9", flavor="runtime")
    rec3.note("statesync", f"index=1 head={honest_head.hex()}")
    rec3.close()
    res2 = audit([read_journal(donor_dir), read_journal(joiner2)])
    assert not res2.sync_mismatches
    assert [j for j in res2.sync_joins
            if j["verified_against"] == "0"]

"""Live exposition: /metrics /status /spans on a committing cluster, and
the obs.top pipeline against those endpoints.

One 4-node in-process cluster (real sockets, ephemeral ports) commits
client transactions; every node must serve valid Prometheus text with the
consensus counters moving, the JSON status document, and parseable span
JSONL — then ``obs.top``'s poll/aggregate/render path runs against the
same endpoints."""

import asyncio
import json

from hbbft_tpu.net.cluster import ClusterConfig, LocalCluster
from hbbft_tpu.obs import top
from hbbft_tpu.obs.http import http_get
from hbbft_tpu.obs.metrics import parse_prometheus_text

TIMEOUT_S = 90


def test_top_util_and_ctrl_cells_and_snapshot_doc():
    """The perf-plane columns are pure functions of /status: util% is
    ``100·(1 − headroom)``, ctrl is the signed effective level, and
    both degrade to "-" on nodes without the respective plane."""
    status = {
        "perf": {"headroom": 0.25, "util": {"pump": 0.75}},
        "degraded": {"level": 0, "boost": 1, "batch_size": 64,
                     "base_batch_size": 32},
    }
    assert top.util_cell(status) == ("75", 75.0)
    cell, doc = top.ctrl_summary(status)
    assert cell == "-1"  # raised one boost level
    assert doc == {"level": 0, "boost": 1, "effective": -1,
                   "batch_size": 64, "base_batch_size": 32}

    degraded = {"perf": {"headroom": 0.0},
                "degraded": {"level": 2, "boost": 0, "batch_size": 8,
                             "base_batch_size": 32}}
    assert top.util_cell(degraded) == ("100", 100.0)
    assert top.ctrl_summary(degraded)[0] == "+2"
    at_base = {"headroom": 1.0,  # top-level fallback, sampler primed
               "degraded": {"level": 1, "boost": 1, "batch_size": 32,
                            "base_batch_size": 32}}
    assert top.util_cell(at_base) == ("0", 0.0)
    assert top.ctrl_summary(at_base)[0] == "0"
    # no perf plane / no controller: "-" cells, None docs
    assert top.util_cell({}) == ("-", None)
    assert top.ctrl_summary({"degraded": None}) == ("-", None)

    # the render table and --json doc carry the same cells
    snap = {"status": dict(status, node=0, era=0, epoch=3,
                           chain_len=3, batches=3, mempool=0,
                           peers_connected=3, committed_txs=9,
                           faults_observed=0, decode_failures=0,
                           replay_gaps=0),
            "metrics": {}, "health": {"status": "ok"}}
    frame = top.render([("127.0.0.1", 9100)], [None], [snap], 1.0)
    header = next(l for l in frame.splitlines() if "util%" in l)
    assert "ctrl" in header
    doc = top.snapshot_doc([("127.0.0.1", 9100)], [snap])
    node = doc["nodes"][0]
    assert node["util_pct"] == 75.0
    assert node["ctrl"]["effective"] == -1
    assert node["perf"]["headroom"] == 0.25


def test_cluster_obs_endpoints_and_top():
    async def scenario():
        cfg = ClusterConfig(n=4, seed=23, batch_size=6)
        cluster = LocalCluster(cfg)
        await cluster.start()
        try:
            client = await cluster.client(0)
            txs = [b"obs-http-%02d" % i for i in range(12)]
            for tx in txs:
                assert await client.submit(tx) == 0
            for tx in txs:
                await client.wait_committed(tx, timeout_s=30)
            loop = asyncio.get_running_loop()

            def get(nid, path):
                host, port = cluster.metrics_addrs[nid]
                return http_get(host, port, path)

            for nid in range(4):
                rt = cluster.runtimes[nid]
                text = await loop.run_in_executor(None, get, nid,
                                                  "/metrics")
                parsed = parse_prometheus_text(text)  # valid exposition
                assert parsed["hbbft_node_epochs_total"][0][1] >= 2
                assert parsed["hbbft_node_committed_txs_total"][0][1] \
                    == len(txs)
                assert parsed["hbbft_node_peers_connected"][0][1] == 3
                # replay/catch-up health is scrapeable per peer
                assert len(parsed["hbbft_node_peer_epoch"]) == 3
                assert len(parsed["hbbft_node_replay_log_entries"]) >= 1
                # transport + mempool counters migrated onto the registry
                assert parsed["hbbft_net_frames_sent_total"][0][1] > 0
                acks = {labels["status"]: v for labels, v in
                        parsed["hbbft_node_mempool_acks_total"]}
                assert acks["accepted"] >= (len(txs) if nid == 0 else 0)
                # attribute views agree with the registry
                assert rt.transport.stats.frames_sent == int(
                    parsed["hbbft_net_frames_sent_total"][0][1])

                status = json.loads(await loop.run_in_executor(
                    None, get, nid, "/status"))
                ref = rt.status_doc()
                for key in ("node", "era", "ledger", "committed_txs",
                            "replay_gaps", "decode_failures"):
                    assert status[key] == ref[key], key
                assert status["committed_txs"] == len(txs)
                assert status["obs_addr"] == list(
                    cluster.metrics_addrs[nid])

                spans = await loop.run_in_executor(None, get, nid,
                                                   "/spans")
                lines = [json.loads(l) for l in spans.splitlines()]
                assert lines, "no spans served"
                names = {l["name"] for l in lines}
                assert {"rbc_value", "rbc_echo", "rbc_ready",
                        "epoch"} <= names
                # per-epoch span durations are consistent with the epoch
                for l in lines:
                    assert l["t_start"] <= l["t_end"]

            # unknown path → 404, not a hang or a crash
            host, port = cluster.metrics_addrs[0]
            try:
                await loop.run_in_executor(
                    None, lambda: http_get(host, port, "/nope"))
                assert False, "expected an HTTP error"
            except (OSError, ValueError):
                pass

            # -- obs.top against the live endpoints -----------------------
            targets = [cluster.metrics_addrs[n] for n in range(4)]
            snaps = await loop.run_in_executor(
                None, lambda: [top.poll_target(h, p) for h, p in targets]
            )
            assert all(s is not None for s in snaps)
            pq = top.phase_quantiles(snaps)
            assert "rbc_echo" in pq and pq["rbc_echo"][0] >= 0
            frame = top.render(targets, [None] * 4, snaps, 1.0)
            assert "phase" in frame and "rbc_echo" in frame
            assert "DOWN" not in frame
            # a dead target renders as DOWN instead of raising
            dead = await loop.run_in_executor(
                None, lambda: top.poll_target("127.0.0.1", 9))
            assert dead is None
            frame2 = top.render(
                targets[:1] + [("127.0.0.1", 9)],
                [None, None], [snaps[0], None], 1.0)
            assert "DOWN" in frame2
        finally:
            await cluster.stop()

    asyncio.run(asyncio.wait_for(scenario(), TIMEOUT_S))

"""Authenticated transport: signed hellos, spoof refusal, era grace.

The protocol-v3 handshake contract (`net/framing.py` +
`net/transport.py`): every node-role hello is CHALLENGEd, the dialer
must sign the transcript with the claimed validator's per-era key, and
every refusal is counted under exactly one
``hbbft_guard_auth_failures_total`` reason WITHOUT allocating any
per-peer state — a spoofer must never touch the impersonated
validator's budgets, strikes, or backoff gates.
"""

import asyncio
import random

import pytest

from hbbft_tpu.crypto import tc
from hbbft_tpu.net import framing
from hbbft_tpu.net.transport import EraKeyRing, Transport

CLUSTER = b"auth-cl"


def _secrets(n, salt=0):
    return {
        i: tc.SecretKey.random(random.Random(9000 + salt * 100 + i))
        for i in range(n)
    }


def _make_auth(our_id, secrets, era_ref, ring, cluster_id=CLUSTER):
    """(auth_sign, auth_verify) callbacks over a mutable ``[era]`` box
    and an :class:`EraKeyRing` — NodeRuntime's wiring without the
    protocol stack (same verdict ladder, incl. the lenient era-mismatch
    fallback for honest-but-behind peers with still-valid keys)."""

    def sign(cid, nonce, session):
        era = era_ref[0]
        t = framing.auth_transcript(cid, nonce, session, our_id,
                                    framing.ROLE_NODE, era)
        return era, secrets[our_id].sign(t).to_bytes()

    def verify(node_id, role, era, sig_bytes, nonce, session):
        try:
            sig = tc.Signature.from_bytes(bytes(sig_bytes))
        except (ValueError, IndexError):
            return "bad_sig"
        t = framing.auth_transcript(cluster_id, nonce, session,
                                    node_id, role, int(era))
        candidates = ring.lookup(node_id)
        if not candidates:
            return "unknown_key"
        era_matched = False
        for cand_era, key, stale in candidates:
            if cand_era != era:
                continue
            era_matched = True
            if key.verify(sig, t):
                return "stale" if stale else "ok"
        if not era_matched:
            for cand_era, key, stale in candidates:
                if not stale and key.verify(sig, t):
                    return "stale"
        return "bad_sig"

    return sign, verify


def _ring_over(state, grace_s=30.0, clock=None):
    return EraKeyRing(
        lambda: (state["era"], {i: sk.public_key()
                                for i, sk in state["keys"].items()}),
        grace_s=grace_s,
        **({"clock": clock} if clock is not None else {}),
    )


# ===========================================================================
# EraKeyRing unit
# ===========================================================================


def test_era_keyring_grace_window_and_single_prev():
    ks0, ks1 = _secrets(1, salt=0)[0], _secrets(1, salt=1)[0]
    ks2 = _secrets(1, salt=2)[0]
    clock = [0.0]
    state = {"era": 0, "keys": {7: ks0}}
    ring = _ring_over(state, grace_s=10.0, clock=lambda: clock[0])

    cands = ring.lookup(7)
    assert [(e, s) for e, _k, s in cands] == [(0, False)]
    assert ring.lookup("nobody") == []

    # rotation: previous era admissible within grace, flagged stale
    state["era"], state["keys"] = 1, {7: ks1}
    cands = ring.lookup(7)
    assert [(e, s) for e, _k, s in cands] == [(1, False), (0, True)]

    # grace expiry on the clock
    clock[0] = 11.0
    assert [(e, s) for e, _k, s in ring.lookup(7)] == [(1, False)]

    # exactly ONE previous era retained: a second rotation evicts era 1
    state["era"], state["keys"] = 2, {7: ks2}
    clock[0] = 12.0
    cands = ring.lookup(7)
    assert [(e, s) for e, _k, s in cands] == [(2, False), (1, True)]


# ===========================================================================
# Authenticated transport end to end
# ===========================================================================


def test_authenticated_transports_connect_and_heartbeat():
    """Two auth-wired transports handshake, exchange messages, and run
    session-bound heartbeats without a single auth failure."""

    async def scenario():
        secrets = _secrets(2)
        state = {"era": 0, "keys": secrets}
        got_a, got_b = [], []
        ts = []
        for our, sink in ((0, got_a), (1, got_b)):
            sign, verify = _make_auth(our, secrets, [0],
                                      _ring_over(state))
            ts.append(Transport(
                our, CLUSTER, heartbeat_s=0.05,
                on_peer_message=lambda pid, d, s=sink: s.append(d),
                auth_sign=sign, auth_verify=verify))
        ta, tb = ts
        await ta.listen()
        await tb.listen()
        ta.add_peer(1, tb.addr)
        tb.add_peer(0, ta.addr)
        ta.send(1, b"ping-payload")
        tb.send(0, b"pong-payload")
        for _ in range(400):
            if got_a and got_b:
                break
            await asyncio.sleep(0.01)
        assert got_a == [b"pong-payload"]
        assert got_b == [b"ping-payload"]
        # both acceptors verified a signed hello
        assert ta.ingress._c_auth_ok.total() >= 1
        assert tb.ingress._c_auth_ok.total() >= 1
        # several session-bound heartbeats round-trip cleanly
        await asyncio.sleep(0.3)
        for t in (ta, tb):
            doc = t.ingress.as_dict()
            assert doc["auth_failures"]["session"] == 0
            assert sum(doc["auth_failures"].values()) == 0
        await ta.stop()
        await tb.stop()

    asyncio.run(asyncio.wait_for(scenario(), 20))


def test_prev_era_key_accepted_within_grace_counted_stale():
    """A dialer still signing with the PREVIOUS era's key during a
    rotation connects (grace window) and is counted stale — not refused
    into a retry storm."""

    async def scenario():
        old = _secrets(2, salt=0)
        new = dict(old)
        new[0] = _secrets(1, salt=5)[0]  # node 0 re-keyed
        state = {"era": 0, "keys": old}
        ring_b = _ring_over(state, grace_s=30.0)
        ring_b.lookup(0)  # prime the ring on era 0
        state["era"], state["keys"] = 1, new  # rotation lands on B

        sign_a, _ = _make_auth(0, old, [0], _ring_over(
            {"era": 0, "keys": old}))
        _, verify_b = _make_auth(1, new, [1], ring_b)
        got = []
        ta = Transport(0, CLUSTER, auth_sign=sign_a)
        tb = Transport(1, CLUSTER,
                       on_peer_message=lambda pid, d: got.append(d),
                       auth_verify=verify_b)
        await ta.listen()
        await tb.listen()
        ta.add_peer(1, tb.addr)
        tb.add_peer(0, ta.addr)  # peer must be known for accept
        ta.send(1, b"old-era-hello")
        for _ in range(400):
            if got:
                break
            await asyncio.sleep(0.01)
        assert got == [b"old-era-hello"]
        assert tb.ingress._c_auth_stale.total() == 1
        assert sum(tb.ingress.as_dict()["auth_failures"].values()) == 0
        await ta.stop()
        await tb.stop()

    asyncio.run(asyncio.wait_for(scenario(), 20))


# ===========================================================================
# Handshake fuzz storm — every refusal counted, zero retained state
# ===========================================================================


def _node_hello_frame(node_id, era=0):
    hello = framing.Hello(node_id=node_id, role=framing.ROLE_NODE,
                          cluster_id=CLUSTER, era=era, epoch=0)
    return framing.encode_frame(framing.HELLO,
                                framing.encode_hello(hello),
                                framing.DEFAULT_MAX_FRAME)


async def _read_challenge(reader):
    kind, payload = await asyncio.wait_for(
        framing.read_one_frame(reader, framing.DEFAULT_MAX_FRAME), 3.0)
    assert kind == framing.CHALLENGE
    return framing.decode_challenge(payload)


async def _expect_refusal(reader):
    """After a bad answer the acceptor must close WITHOUT a hello
    reply; a HELLO here means the spoof was accepted."""
    try:
        kind, _ = await asyncio.wait_for(
            framing.read_one_frame(reader, framing.DEFAULT_MAX_FRAME),
            3.0)
    except (asyncio.IncompleteReadError, framing.FrameError,
            ConnectionError, OSError):
        return
    assert kind != framing.HELLO, "spoofed handshake was ACCEPTED"


def test_handshake_fuzz_storm_counted_and_stateless():
    """Truncated / bit-flipped / replayed-nonce / wrong-era /
    signature-stripped / AUTH-less hellos: each refused loudly, each
    counted under one reason, and the guard's per-peer map stays EMPTY
    afterwards — refused handshakes allocate nothing."""

    async def scenario():
        secrets = _secrets(2)
        state = {"era": 0, "keys": secrets}
        _, verify = _make_auth(0, secrets, [0], _ring_over(state))
        t = Transport(0, CLUSTER, dead_after_s=1.0, auth_verify=verify)
        await t.listen()
        rng = random.Random(1234)

        def transcript(nonce, session, node_id=1, era=0):
            return framing.auth_transcript(CLUSTER, nonce, session,
                                           node_id, framing.ROLE_NODE,
                                           era)

        async def probe(answer):
            """hello → challenge → ``answer(nonce, session)`` frame
            bytes (or b"" to just hang up) → expect refusal."""
            reader, writer = await asyncio.open_connection(*t.addr)
            try:
                writer.write(_node_hello_frame(1))
                await writer.drain()
                nonce, session = await _read_challenge(reader)
                frame = answer(nonce, session)
                if frame:
                    writer.write(frame)
                    await writer.drain()
                    await _expect_refusal(reader)
            finally:
                writer.close()

        def auth_frame(era, sig):
            return framing.encode_frame(
                framing.AUTH, framing.encode_auth(era, sig),
                framing.DEFAULT_MAX_FRAME)

        # 1. garbage where the signature belongs
        await probe(lambda n, s: auth_frame(
            0, bytes(rng.randrange(256) for _ in range(96))))
        # 2. bit-flipped valid signature
        def flipped(nonce, session):
            sig = bytearray(
                secrets[1].sign(transcript(nonce, session)).to_bytes())
            sig[3] ^= 0x40
            return auth_frame(0, bytes(sig))
        await probe(flipped)
        # 3. replayed nonce: a signature over a DIFFERENT challenge
        stale = secrets[1].sign(
            transcript(b"\x01" * framing.NONCE_LEN,
                       b"\x02" * framing.SESSION_LEN)).to_bytes()
        await probe(lambda n, s: auth_frame(0, stale))
        # 4. wrong era claim signed with the WRONG key
        wrong = tc.SecretKey.random(random.Random(4242))
        await probe(lambda n, s: auth_frame(
            5, wrong.sign(transcript(n, s, era=5)).to_bytes()))
        # 5. signature stripped (empty blob still decodes as AUTH)
        await probe(lambda n, s: auth_frame(0, b""))
        # 6. no AUTH at all: a protocol frame where the proof belongs
        await probe(lambda n, s: framing.encode_frame(
            framing.MSG, b"inject-before-auth",
            framing.DEFAULT_MAX_FRAME))
        # 7. unknown id, properly signed by a key the ring never held
        async def probe_unknown():
            reader, writer = await asyncio.open_connection(*t.addr)
            try:
                writer.write(_node_hello_frame(99))
                await writer.drain()
                nonce, session = await _read_challenge(reader)
                sig = wrong.sign(
                    transcript(nonce, session, node_id=99)).to_bytes()
                writer.write(auth_frame(0, sig))
                await writer.drain()
                await _expect_refusal(reader)
            finally:
                writer.close()
        await probe_unknown()
        # 8. truncated AUTH frame: length prefix promises more bytes
        async def probe_truncated():
            reader, writer = await asyncio.open_connection(*t.addr)
            try:
                writer.write(_node_hello_frame(1))
                await writer.drain()
                await _read_challenge(reader)
                whole = auth_frame(0, b"\x00" * 96)
                writer.write(whole[: len(whole) // 2])
                await writer.drain()
            finally:
                writer.close()
            await asyncio.sleep(0.1)
        await probe_truncated()

        # drain the refusal paths, then audit the ledger
        await asyncio.sleep(0.3)
        doc = t.ingress.as_dict()
        fails = doc["auth_failures"]
        assert fails["bad_sig"] >= 5     # probes 1,2,3,4,5
        assert fails["no_auth"] == 1     # probe 6
        assert fails["unknown_key"] == 1  # probe 7
        assert fails["malformed"] >= 1   # probe 8
        assert sum(fails.values()) == 8  # one per probe, no doubles
        assert doc["auth_ok"] == 0
        # the spoof-proof core: NOTHING was allocated or charged
        assert doc["peers"] == {}
        assert t._senders == {}
        assert t._half_open == 0
        await t.stop()

    asyncio.run(asyncio.wait_for(scenario(), 30))


def test_mid_handshake_kill_is_one_counted_refusal():
    """A dialer that dies between CHALLENGE and AUTH costs exactly one
    counted refusal and no state."""

    async def scenario():
        secrets = _secrets(2)
        state = {"era": 0, "keys": secrets}
        _, verify = _make_auth(0, secrets, [0], _ring_over(state))
        t = Transport(0, CLUSTER, dead_after_s=0.4, auth_verify=verify)
        await t.listen()
        reader, writer = await asyncio.open_connection(*t.addr)
        writer.write(_node_hello_frame(1))
        await writer.drain()
        await _read_challenge(reader)
        writer.close()  # die mid-handshake
        await asyncio.sleep(0.8)
        doc = t.ingress.as_dict()
        assert sum(doc["auth_failures"].values()) == 1
        assert doc["auth_failures"]["malformed"] \
            + doc["auth_failures"]["timeout"] == 1
        assert doc["peers"] == {}
        assert t._half_open == 0
        await t.stop()

    asyncio.run(asyncio.wait_for(scenario(), 20))


def test_hijacked_stream_wrong_session_ping_torn_down():
    """An attacker who completes the handshake (compromised key) still
    cannot ride heartbeats with a forged session id: the first PING
    carrying the wrong session is refused, counted, and the stream is
    torn down."""

    async def scenario():
        import struct

        secrets = _secrets(2)
        state = {"era": 0, "keys": secrets}
        _, verify = _make_auth(0, secrets, [0], _ring_over(state))

        # a throwaway listener so peer resolution has an address for
        # the "compromised validator" the attacker dials in as
        async def _ignore(reader, writer):
            await asyncio.sleep(10)

        park = await asyncio.start_server(_ignore, "127.0.0.1", 0)
        park_addr = park.sockets[0].getsockname()[:2]
        t = Transport(0, CLUSTER, auth_verify=verify,
                      peer_resolver=lambda nid: park_addr)
        await t.listen()

        reader, writer = await asyncio.open_connection(*t.addr)
        writer.write(_node_hello_frame(1))
        await writer.drain()
        nonce, session = await _read_challenge(reader)
        tr = framing.auth_transcript(CLUSTER, nonce, session, 1,
                                     framing.ROLE_NODE, 0)
        writer.write(framing.encode_frame(
            framing.AUTH,
            framing.encode_auth(0, secrets[1].sign(tr).to_bytes()),
            framing.DEFAULT_MAX_FRAME))
        await writer.drain()
        kind, _ = await asyncio.wait_for(
            framing.read_one_frame(reader, framing.DEFAULT_MAX_FRAME),
            3.0)
        assert kind == framing.HELLO  # genuine key: accepted
        # now heartbeat with a FORGED session id
        bogus = bytes(framing.SESSION_LEN) + struct.pack(">Q", 1)
        assert bogus[:framing.SESSION_LEN] != session
        writer.write(framing.encode_frame(
            framing.PING, bogus, framing.DEFAULT_MAX_FRAME))
        await writer.drain()
        for _ in range(200):
            if t.ingress.as_dict()["auth_failures"]["session"]:
                break
            await asyncio.sleep(0.01)
        assert t.ingress.as_dict()["auth_failures"]["session"] == 1
        writer.close()
        park.close()
        await t.stop()

    asyncio.run(asyncio.wait_for(scenario(), 20))


def test_half_open_budget_refuses_over_cap():
    """Stalled half-open handshakes hold a bounded number of slots;
    connections past the cap are refused and counted, not queued."""

    async def scenario():
        secrets = _secrets(2)
        state = {"era": 0, "keys": secrets}
        _, verify = _make_auth(0, secrets, [0], _ring_over(state))
        t = Transport(0, CLUSTER, dead_after_s=1.5, auth_verify=verify,
                      max_half_open=1)
        await t.listen()
        # slot holder: connects, sends nothing
        _r1, w1 = await asyncio.open_connection(*t.addr)
        await asyncio.sleep(0.1)
        # over cap: refused before its hello is even read
        r2, w2 = await asyncio.open_connection(*t.addr)
        w2.write(_node_hello_frame(1))
        await w2.drain()
        await _expect_refusal(r2)
        for _ in range(200):
            if t.ingress.as_dict()["auth_failures"]["half_open"]:
                break
            await asyncio.sleep(0.01)
        assert t.ingress.as_dict()["auth_failures"]["half_open"] >= 1
        w1.close()
        w2.close()
        await t.stop()

    asyncio.run(asyncio.wait_for(scenario(), 20))


# ===========================================================================
# Rotation-era grace, end to end (vote_to_readd DKG rotation)
# ===========================================================================


@pytest.mark.slow
def test_restart_across_rotation_reconnects_via_stale_grace():
    """Regression for the rotation-era edge: a node restarted from
    scratch AFTER a vote_to_readd DKG rotation signs its hellos with
    era 0 while the live peers are at era 1 — the acceptors must admit
    it under the era-grace path (counted
    ``hbbft_guard_auth_stale_era_total``), never refuse it into a
    backoff storm, and the cluster must keep committing."""
    from hbbft_tpu.net.cluster import (
        ClusterConfig, LocalCluster, find_free_base_port,
    )

    async def scenario():
        cfg = ClusterConfig(n=4, seed=11, batch_size=4,
                            base_port=find_free_base_port(4),
                            heartbeat_s=0.2, dead_after_s=2.0)
        cluster = LocalCluster(cfg)
        await cluster.start()
        try:
            client = await cluster.client(0)
            await client.submit(b"pre-rotation")
            await client.wait_committed(b"pre-rotation", timeout_s=60)
            cluster.vote_to_readd()
            await cluster.wait_snapshot(min_era=1, timeout_s=120)
            # node 3 dies and restarts from genesis: era 0 signatures
            await cluster.restart_node(3)
            await client.submit(b"post-rotation")
            await client.wait_committed(b"post-rotation", timeout_s=60)
            await cluster.wait_epochs(min_batches=1, timeout_s=60)
            stale = sum(
                rt.transport.ingress._c_auth_stale.total()
                for rt in cluster.runtimes)
            assert stale >= 1, ("restarted node's era-0 handshakes "
                                "should land on the grace path")
            fails = {}
            for rt in cluster.runtimes:
                for k, v in (rt.transport.ingress.as_dict()
                             ["auth_failures"].items()):
                    fails[k] = fails.get(k, 0) + v
            # refusal reasons that would indicate the grace path broke
            assert fails["bad_sig"] == 0 and fails["unknown_key"] == 0
        finally:
            await cluster.stop()

    asyncio.run(asyncio.wait_for(scenario(), 300))

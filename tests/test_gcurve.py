"""Device (limbed, batched) BLS12-381 vs the pure-Python host oracle.

Exactness is asserted point-for-point: the device field is canonical, so a
single wrong carry anywhere shows up as inequality.
"""

import random

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from hbbft_tpu.crypto import bls12_381 as H
from hbbft_tpu.ops import fp381 as F
from hbbft_tpu.ops import gcurve as G


def test_fp_ops_exact_incl_edge_cases():
    rng = np.random.default_rng(0)
    edge = [0, 1, H.P - 1, H.P - 2, (1 << 377) - 1, (1 << 377),
            H.P - (1 << 377), 2, (1 << 389) % H.P, ((1 << 390) - 1) % H.P]
    a_vals = [int.from_bytes(rng.bytes(48), "big") % H.P for _ in range(24)] + edge
    b_vals = [int.from_bytes(rng.bytes(48), "big") % H.P for _ in range(24)] + list(reversed(edge))
    n = len(a_vals)
    A = jnp.asarray(np.stack([F.int_to_limbs(v) for v in a_vals]))
    B = jnp.asarray(np.stack([F.int_to_limbs(v) for v in b_vals]))
    add = jax.jit(F.fp_add)(A, B)
    sub = jax.jit(F.fp_sub)(A, B)
    mul = jax.jit(F.fp_mul)(A, B)
    for i in range(n):
        assert F.limbs_to_int(np.asarray(add[i])) == (a_vals[i] + b_vals[i]) % H.P
        assert F.limbs_to_int(np.asarray(sub[i])) == (a_vals[i] - b_vals[i]) % H.P
        assert F.limbs_to_int(np.asarray(mul[i])) == (a_vals[i] * b_vals[i]) % H.P


def test_fp2_ops_exact():
    rng = np.random.default_rng(1)
    vals = [
        ((int.from_bytes(rng.bytes(48), "big") % H.P,
          int.from_bytes(rng.bytes(48), "big") % H.P),
         (int.from_bytes(rng.bytes(48), "big") % H.P,
          int.from_bytes(rng.bytes(48), "big") % H.P))
        for _ in range(16)
    ]
    A = (jnp.asarray(np.stack([F.int_to_limbs(a[0]) for a, _ in vals])),
         jnp.asarray(np.stack([F.int_to_limbs(a[1]) for a, _ in vals])))
    B = (jnp.asarray(np.stack([F.int_to_limbs(b[0]) for _, b in vals])),
         jnp.asarray(np.stack([F.int_to_limbs(b[1]) for _, b in vals])))
    mul = jax.jit(F.fp2_mul)(A, B)
    sqr = jax.jit(F.fp2_sqr)(A)
    for i, (a, b) in enumerate(vals):
        assert F.limbs_to_fp2((np.asarray(mul[0][i]), np.asarray(mul[1][i]))) == H.fp2_mul(a, b)
        assert F.limbs_to_fp2((np.asarray(sqr[0][i]), np.asarray(sqr[1][i]))) == H.fp2_sqr(a)


@pytest.fixture(scope="module")
def g1_batch():
    rng = random.Random(7)
    B = 6
    pts_h = [H.g1_mul(H.G1_GEN, rng.randrange(1, H.R)) for _ in range(B)]
    scals = [0, 1, 2, H.R - 1] + [rng.randrange(0, H.R) for _ in range(B - 4)]
    pts = tuple(jnp.asarray(c) for c in G.g1_to_device(pts_h))
    bits = jnp.asarray(G.scalars_to_bits(scals))
    return pts_h, scals, pts, bits


@pytest.mark.slow
def test_g1_add_complete_cases(g1_batch):
    pts_h, _, pts, _ = g1_batch
    B = len(pts_h)
    add_fn = jax.jit(lambda p, q: G.point_add(G.FP_OPS, p, q))
    cases = {
        "P+Q": [pts_h[(i + 1) % B] for i in range(B)],
        "P+P": pts_h,
        "P+negP": [H.g1_neg(p) for p in pts_h],
        "P+inf": [None] * B,
    }
    for name, qh in cases.items():
        q = tuple(jnp.asarray(c) for c in G.g1_to_device(qh))
        r = add_fn(pts, q)
        for i in range(B):
            got = G.g1_from_device(tuple(np.asarray(c[i]) for c in r))
            assert H.g1_eq(got, H.g1_add(pts_h[i], qh[i])), (name, i)
    # inf + P (batched infinity as first operand)
    inf = tuple(jnp.asarray(c) for c in G.g1_to_device([None] * B))
    r = add_fn(inf, pts)
    for i in range(B):
        got = G.g1_from_device(tuple(np.asarray(c[i]) for c in r))
        assert H.g1_eq(got, pts_h[i])


def _g1_msm_case(nbits, scalar_pairs):
    """MSM parity vs host at a given ladder width: exercises the add/double
    step and the device tree-add; ladder length only changes the unroll."""
    rng = random.Random(13)
    fn = jax.jit(lambda p, b: G.msm(G.FP_OPS, p, b))
    base = [H.g1_mul(H.G1_GEN, rng.randrange(1, H.R)) for _ in range(2)]
    pts = tuple(jnp.asarray(c) for c in G.g1_to_device(base))
    for s0, s1 in scalar_pairs:
        bits = jnp.asarray(G.scalars_to_bits([s0, s1], nbits=nbits))
        m = fn(pts, bits)
        expect = H.g1_add(H.g1_mul(base[0], s0), H.g1_mul(base[1], s1))
        assert H.g1_eq(G.g1_from_device(tuple(np.asarray(c) for c in m)), expect)


@pytest.mark.slow
def test_g1_msm_ladder_and_tree():
    """64-bit ladder (same per-step machinery as full width; compile is
    minutes shorter).  Tier 1 keeps ``test_lazy_g1_msm_packed_path`` as
    the G1 MSM representative — the packed path is what production
    dispatch uses, and this unpacked ladder's 144 s compile is all
    redundant machinery on top of it."""
    rng = random.Random(13)
    _g1_msm_case(64, [
        (0, rng.randrange(1, 1 << 64)),
        (1, (1 << 64) - 1),
        (rng.randrange(1, 1 << 64), rng.randrange(1, 1 << 64)),
    ])


@pytest.mark.slow
def test_g1_msm_ladder_full_width():
    rng = random.Random(13)
    _g1_msm_case(G.R_BITS, [
        (0, rng.randrange(1, H.R)),
        (1, H.R - 1),
        (rng.randrange(1, H.R), rng.randrange(1, H.R)),
    ])


def _g2_msm_case(nbits, s0, s1):
    rng = random.Random(17)
    base = [H.g2_mul(H.G2_GEN, rng.randrange(1, H.R)) for _ in range(2)]
    pts = tuple(tuple(jnp.asarray(x) for x in c) for c in G.g2_to_device(base))
    bits = jnp.asarray(G.scalars_to_bits([s0, s1], nbits=nbits))
    m = jax.jit(lambda p, b: G.msm(G.FP2_OPS, p, b))(pts, bits)
    expect = H.g2_add(H.g2_mul(base[0], s0), H.g2_mul(base[1], s1))
    assert H.g2_eq(
        G.g2_from_device(tuple(tuple(np.asarray(x) for x in c) for c in m)),
        expect,
    )


@pytest.mark.slow
def test_g2_msm_ladder_and_tree():
    """Slow-gated: the 13-bit-field Fp2 ladder body alone compiles for
    minutes on the CPU backend (the persistent cache does not load there).
    Default coverage of the 13-bit field's COMPONENTS: test_fp2_ops_exact
    (Fp2 ops), test_lazy_g1_msm_packed_path (lazy field + bitwise ladder +
    packed I/O), MXU-field G2 ladder (tests/test_fp381_mxu.py — G2 point
    formulas).  The exact lazy-Fp2×G2×bitwise COMPOSITION — the production
    path for G2 MSM batches > MXU_MAX_BATCH — is only exercised under
    --slow (here and test_lazy_g2_msm_packed_path); run --slow before
    touching fp381's lazy Fp2 ops or the ladder."""
    rng = random.Random(17)
    _g2_msm_case(64, rng.randrange(1, 1 << 64), (1 << 64) - 1)


@pytest.mark.slow
def test_g2_msm_ladder_full_width():
    rng = random.Random(17)
    _g2_msm_case(G.R_BITS,
                 rng.randrange(1, H.R), H.R - 1)


def test_lazy_g1_msm_packed_path():
    """The PRODUCTION large-batch path — scalar_mul_lazy over the 13-bit
    LAZY field with int16/uint8 packed I/O — at a small batch, forced via
    HBBFT_FIELD_BACKEND=lazy on a fresh cache (the auto heuristic would
    pick the MXU field at this size)."""
    import os

    from hbbft_tpu.crypto import batch as CB
    from hbbft_tpu.crypto import bls12_381 as c

    rng = random.Random(41)
    pts = [c.g1_mul(c.G1_GEN, rng.randrange(1, c.R)) for _ in range(3)]
    pts.append(None)
    sc = [rng.randrange(1, 1 << 128) for _ in range(3)] + [7]
    cache = CB._MsmCache()
    old = os.environ.get("HBBFT_FIELD_BACKEND")
    old_max = CB.MXU_MAX_BATCH
    os.environ["HBBFT_FIELD_BACKEND"] = "lazy"
    CB.MXU_MAX_BATCH = 0  # also forces the BITWISE (large-batch) ladder
    try:
        got = cache._msm("g1", pts, sc)
    finally:
        CB.MXU_MAX_BATCH = old_max
        if old is None:
            os.environ.pop("HBBFT_FIELD_BACKEND", None)
        else:
            os.environ["HBBFT_FIELD_BACKEND"] = old
    expect = None
    for p, s in zip(pts, sc):
        expect = c.g1_add(expect, c.g1_mul(p, s))
    assert c.g1_eq(got, expect)


@pytest.mark.slow
def test_lazy_g2_msm_packed_path():
    """--slow: the exact production composition for LARGE G2 MSM batches —
    lazy 13-bit Fp2 field × bitwise ladder × packed int16/uint8 I/O."""
    import os

    from hbbft_tpu.crypto import batch as CB
    from hbbft_tpu.crypto import bls12_381 as c

    rng = random.Random(59)
    pts = [c.g2_mul(c.G2_GEN, rng.randrange(1, c.R)) for _ in range(3)]
    sc = [rng.randrange(1, 1 << 128) for _ in range(3)]
    cache = CB._MsmCache()
    old = os.environ.get("HBBFT_FIELD_BACKEND")
    old_max = CB.MXU_MAX_BATCH
    os.environ["HBBFT_FIELD_BACKEND"] = "lazy"
    CB.MXU_MAX_BATCH = 0
    try:
        got = cache._msm("g2", pts, sc)
    finally:
        CB.MXU_MAX_BATCH = old_max
        if old is None:
            os.environ.pop("HBBFT_FIELD_BACKEND", None)
        else:
            os.environ["HBBFT_FIELD_BACKEND"] = old
    expect = None
    for p, s in zip(pts, sc):
        expect = c.g2_add(expect, c.g2_mul(p, s))
    assert c.g2_eq(got, expect)

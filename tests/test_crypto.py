"""BLS12-381 + threshold-crypto tests.

Covers the curve layer (parameters, bilinearity, hash-to-curve), plain keys,
threshold signatures (share/combine round-trip — the reference's
``tests/threshold_sign.rs`` analog), TPKE, and the DKG polynomial substrate.
"""

import random

import pytest

from hbbft_tpu.crypto import bls12_381 as c
from hbbft_tpu.crypto import tc


def test_parameters_derived_from_x():
    # p and r follow from the BLS12 family formulas — transcription guard.
    assert c.P % 2 == 1 and c.R % 2 == 1
    assert (c.P**4 - c.P**2 + 1) % c.R == 0
    assert ((c.X - 1) ** 2 * c.R // 3 + c.X) == c.P


def test_generators():
    assert c.g1_is_on_curve(c.G1_GEN)
    assert c.g2_is_on_curve(c.G2_GEN)
    assert c._g1_mul_nat(c.G1_GEN, c.R) is None
    assert c.g2_mul(c.G2_GEN, c.R, mod_r=False) is None


def test_group_ops():
    p2 = c.g1_add(c.G1_GEN, c.G1_GEN)
    assert c.g1_eq(p2, c.g1_double(c.G1_GEN))
    assert c.g1_eq(c.g1_mul(c.G1_GEN, 5), c.g1_add(p2, c.g1_add(p2, c.G1_GEN)))
    assert c.g1_add(c.G1_GEN, c.g1_neg(c.G1_GEN)) is None
    q2 = c.g2_add(c.G2_GEN, c.G2_GEN)
    assert c.g2_eq(q2, c.g2_double(c.G2_GEN))
    assert c.g2_add(c.G2_GEN, c.g2_neg(c.G2_GEN)) is None


def test_pairing_bilinear():
    e = c.pairing(c.G1_GEN, c.G2_GEN)
    assert e != c.FP12_ONE
    lhs = c.pairing(c.g1_mul(c.G1_GEN, 6), c.g2_mul(c.G2_GEN, 5))
    assert lhs == c.fp12_pow(e, 30)
    # product check
    assert c.pairing_check(
        [(c.g1_neg(c.g1_mul(c.G1_GEN, 30)), c.G2_GEN),
         (c.g1_mul(c.G1_GEN, 6), c.g2_mul(c.G2_GEN, 5))]
    )


def test_hash_g2_subgroup_and_determinism():
    h1 = c.hash_g2(b"doc")
    h2 = c.hash_g2(b"doc")
    assert c.g2_eq(h1, h2)
    assert not c.g2_eq(h1, c.hash_g2(b"doc2"))
    assert c.g2_mul(h1, c.R, mod_r=False) is None


def test_point_serialization_roundtrip():
    pt = c.g1_mul(c.G1_GEN, 12345)
    assert c.g1_eq(c.g1_from_bytes(c.g1_to_bytes(pt)), pt)
    qt = c.g2_mul(c.G2_GEN, 54321)
    assert c.g2_eq(c.g2_from_bytes(c.g2_to_bytes(qt)), qt)
    assert c.g1_from_bytes(c.g1_to_bytes(None)) is None
    with pytest.raises(ValueError):
        c.g1_from_bytes(b"\x00" + bytes(96))


def test_infinity_encoding_is_strict_cross_implementation(rng):
    """The ONLY valid infinity encoding is the 0x40 flag followed by
    all-zero bytes: both the Python deserializers and the native checked
    wire readers must reject every malleable variant (nonzero trailing
    bytes after the flag), and accept the canonical one — the accept sets
    may not diverge (ADVICE r5 #1)."""
    # Python side: canonical accepted, every mutated trailing byte rejected
    assert c.g1_from_bytes(b"\x40" + bytes(96)) is None
    assert c.g2_from_bytes(b"\x40" + bytes(192)) is None
    for pos in (1, 48, 96):
        bad = bytearray(b"\x40" + bytes(96))
        bad[pos] = 0x5A
        with pytest.raises(ValueError, match="infinity"):
            c.g1_from_bytes(bytes(bad))
    for pos in (1, 97, 192):
        bad = bytearray(b"\x40" + bytes(192))
        bad[pos] = 0x5A
        with pytest.raises(ValueError, match="infinity"):
            c.g2_from_bytes(bytes(bad))
    # truncated infinity frames are rejected too (the native readers
    # consume a fixed 97/193-byte frame; Python must not accept less)
    with pytest.raises(ValueError, match="infinity"):
        c.g1_from_bytes(b"\x40")
    with pytest.raises(ValueError, match="infinity"):
        c.g2_from_bytes(b"\x40" + bytes(10))

    # native side: the checked wire readers (reached through the fused
    # check+decrypt entry point) must reject exactly the same encodings —
    # on rejection the batch falls back to the per-item Python parse,
    # which raises; agreement is what this asserts
    from hbbft_tpu.crypto import batch as BT

    sks = tc.SecretKeySet.random(1, rng)
    pks = sks.public_keys()
    ct = pks.public_key().encrypt(b"strict", rng)
    shares = [(i, sks.secret_key_share(i)) for i in range(2)]
    inf_u = tc.Ciphertext(None, b"strict", ct.w).to_bytes()
    # canonical infinity-U decrypts on the native path
    assert BT.batch_tpke_check_decrypt(pks, [inf_u], shares) is not None
    for pos in (5, 50, 96):  # inside U's zero region
        bad = bytearray(inf_u)
        bad[pos] = 0x5A
        with pytest.raises(ValueError, match="infinity"):
            BT.batch_tpke_check_decrypt(pks, [bytes(bad)], shares)
    inf_w = tc.Ciphertext(ct.u, b"strict", None).to_bytes()
    assert BT.batch_tpke_check_decrypt(pks, [inf_w], shares) is not None
    for pos in (97 + 5, 97 + 100, 97 + 192):  # inside W's zero region
        bad = bytearray(inf_w)
        bad[pos] = 0x5A
        with pytest.raises(ValueError, match="infinity"):
            BT.batch_tpke_check_decrypt(pks, [bytes(bad)], shares)


def test_plain_sign_verify(rng):
    sk = tc.SecretKey.random(rng)
    pk = sk.public_key()
    sig = sk.sign(b"hello")
    assert pk.verify(sig, b"hello")
    assert not pk.verify(sig, b"other")
    sk2 = tc.SecretKey.random(rng)
    assert not sk2.public_key().verify(sig, b"hello")


def test_plain_encrypt_decrypt(rng):
    sk = tc.SecretKey.random(rng)
    pk = sk.public_key()
    msg = b"attack at dawn" * 5
    ct = pk.encrypt(msg, rng)
    assert ct.verify()
    assert sk.decrypt(ct) == msg
    # tampered ciphertext fails CCA check
    bad = tc.Ciphertext(ct.u, ct.v[:-1] + bytes([ct.v[-1] ^ 1]), ct.w)
    assert not bad.verify()
    assert sk.decrypt(bad) is None


@pytest.mark.parametrize("t,n", [(1, 4), (2, 7)])
def test_threshold_signature_roundtrip(t, n, rng):
    sks = tc.SecretKeySet.random(t, rng)
    pks = sks.public_keys()
    msg = b"common coin doc"
    shares = {i: sks.secret_key_share(i).sign(msg) for i in range(n)}
    # each share verifies under its public key share
    for i in range(n):
        assert pks.verify_signature_share(i, shares[i], msg)
        assert not pks.verify_signature_share((i + 1) % n, shares[i], msg)
    # any t+1 subset combines to the same valid master signature
    sig_a = pks.combine_signatures({i: shares[i] for i in range(t + 1)})
    sig_b = pks.combine_signatures({i: shares[i] for i in range(n - t - 1, n)})
    assert sig_a == sig_b
    assert pks.verify_signature(sig_a, msg)
    # and equals the master-key signature (interpolation correctness)
    master = tc.SecretKey(sks.poly.evaluate(0))
    assert master.sign(msg) == sig_a


def test_threshold_signature_too_few_shares(rng):
    sks = tc.SecretKeySet.random(2, rng)
    pks = sks.public_keys()
    shares = {i: sks.secret_key_share(i).sign(b"m") for i in range(2)}
    with pytest.raises(ValueError):
        pks.combine_signatures(shares)


def test_tpke_roundtrip(rng):
    t, n = 1, 4
    sks = tc.SecretKeySet.random(t, rng)
    pks = sks.public_keys()
    msg = b"contribution bytes: " + bytes(range(100))
    ct = pks.public_key().encrypt(msg, rng)
    assert ct.verify()
    dshares = {}
    for i in range(n):
        sh = sks.secret_key_share(i).decrypt_share(ct)
        assert sh is not None
        assert pks.public_key_share(i).verify_decryption_share(sh, ct)
        dshares[i] = sh
    # bad share is detected
    bad = tc.DecryptionShare(c.g1_mul(c.G1_GEN, 99))
    assert not pks.public_key_share(0).verify_decryption_share(bad, ct)
    # any t+1 shares decrypt
    assert pks.decrypt({0: dshares[0], 3: dshares[3]}, ct) == msg
    assert pks.decrypt(dshares, ct) == msg


def test_ciphertext_serialization(rng):
    sks = tc.SecretKeySet.random(1, rng)
    ct = sks.public_keys().public_key().encrypt(b"payload", rng)
    ct2 = tc.Ciphertext.from_bytes(ct.to_bytes())
    assert ct == ct2 and ct2.verify()


def test_poly_interpolate(rng):
    poly = tc.Poly.random(3, rng)
    pts = [(x, poly.evaluate(x)) for x in (1, 5, 7, 11)]
    rec = tc.Poly.interpolate(pts)
    assert rec.coeffs == poly.coeffs


def test_commitment_evaluate(rng):
    poly = tc.Poly.random(2, rng)
    com = poly.commitment()
    for x in (0, 1, 9):
        assert c.g1_eq(com.evaluate(x), c.g1_mul(c.G1_GEN, poly.evaluate(x)))


def test_bivar_poly_symmetry_and_rows(rng):
    t = 2
    bp = tc.BivarPoly.random(t, rng)
    assert bp.evaluate(3, 8) == bp.evaluate(8, 3)
    row2 = bp.row(2)
    assert row2.evaluate(5) == bp.evaluate(2, 5)
    com = bp.commitment()
    # commitment row matches row's own commitment
    assert com.row(2) == row2.commitment()
    assert c.g1_eq(com.evaluate(2, 5), c.g1_mul(c.G1_GEN, bp.evaluate(2, 5)))


def test_dkg_style_aggregation(rng):
    """Sum of dealer bivariate polys behaves like one threshold key set."""
    t, n = 1, 4
    dealers = [tc.BivarPoly.random(t, rng) for _ in range(3)]
    # node i's secret share = Σ_d f_d(i+1, 0)
    shares = [
        tc.SecretKeyShare(
            sum(d.evaluate(i + 1, 0) for d in dealers) % tc.R
        )
        for i in range(n)
    ]
    # public commitment = Σ_d commit_d.row(0)
    com = dealers[0].commitment().row(0)
    for d in dealers[1:]:
        com = com + d.commitment().row(0)
    pks = tc.PublicKeySet(com)
    msg = b"post-dkg doc"
    sig_shares = {i: shares[i].sign(msg) for i in range(t + 1)}
    sig = pks.combine_signatures(sig_shares)
    assert pks.verify_signature(sig, msg)

"""Adversarial coverage (SURVEY §4 parity gaps).

- A genuinely tampering + injecting RandomAdversary over broadcast and ABA
  with faulty nodes: correct nodes must keep agreement/termination.
- The MITM delay-schedule ABA attack (reference:
  ``tests/binary_agreement_mitm.rs``): the threshold coin still terminates.
- One end-to-end fault per reachable FaultKind: crafted Byzantine messages
  delivered through the protocols' public handle_message, asserting the
  exact evidence recorded.
"""

import random

import pytest

from hbbft_tpu.fault_log import FaultKind
from hbbft_tpu.netinfo import NetworkInfo
from hbbft_tpu.protocols.binary_agreement import (
    AuxMsg,
    BValMsg,
    ConfMsg,
    TermMsg,
    BOTH,
    BinaryAgreement,
)
from hbbft_tpu.protocols.broadcast import Broadcast, ReadyMsg, ValueMsg
from hbbft_tpu.protocols.honey_badger import EncryptionSchedule, HoneyBadger
from hbbft_tpu.protocols.subset import BroadcastWrap, Subset
from hbbft_tpu.protocols.threshold_decrypt import DecryptionMessage, ThresholdDecrypt
from hbbft_tpu.protocols.threshold_sign import ThresholdSign, ThresholdSignMessage
from hbbft_tpu.crypto import tc
from hbbft_tpu.ops.merkle import MerkleTree
from hbbft_tpu.sim import MitmDelayAdversary, NetBuilder, RandomAdversary

_INFO_CACHE = {}


def infos_for(n, seed=7):
    key = (n, seed)
    if key not in _INFO_CACHE:
        _INFO_CACHE[key] = NetworkInfo.generate_map(
            list(range(n)), random.Random(seed)
        )
    return _INFO_CACHE[key]


# ---------------------------------------------------------------------------
# tampering/injecting RandomAdversary end-to-end
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_broadcast_survives_tampering_injecting_adversary(seed):
    n, f = 7, 2
    infos = infos_for(n)
    net = (
        NetBuilder(list(range(n)))
        .num_faulty(f)
        .adversary(RandomAdversary(seed=seed))
        .using_step(lambda nid: Broadcast(infos[nid], 3))
    )
    net.send_input(3, b"tamper-proof value")
    net.run_to_quiescence()
    correct = net.correct_ids()
    outs = [tuple(net.nodes[nid].outputs) for nid in correct]
    decided = [o for o in outs if o]
    # agreement among deciders, and the honest proposer's value wins
    assert len(set(decided)) <= 1
    assert all(o == (b"tamper-proof value",) for o in decided)
    # tampering targets only faulty senders, so every correct node decides
    assert len(decided) == len(correct)


@pytest.mark.parametrize("seed", [0, 3])
def test_aba_survives_tampering_injecting_adversary(seed):
    n, f = 7, 2
    infos = infos_for(n)
    net = (
        NetBuilder(list(range(n)))
        .num_faulty(f)
        .adversary(RandomAdversary(seed=seed))
        .crank_limit(200_000)
        .using_step(lambda nid: BinaryAgreement(infos[nid], b"adv", 0))
    )
    for nid in range(n):
        net.send_input(nid, nid % 2 == 0)
    net.run_to_quiescence()
    correct = net.correct_ids()
    decisions = {net.nodes[nid].outputs[0] for nid in correct if net.nodes[nid].outputs}
    assert len(decisions) == 1  # agreement
    for nid in correct:
        assert net.nodes[nid].algorithm.terminated()


def test_aba_terminates_under_mitm_delay_attack():
    """Reference ``tests/binary_agreement_mitm.rs``: delaying one node's
    view must not stall the threshold-coin epochs."""
    n = 4
    infos = infos_for(n)
    net = (
        NetBuilder(list(range(n)))
        .adversary(MitmDelayAdversary(target=0, max_delay=150, seed=1))
        .crank_limit(500_000)
        .using_step(lambda nid: BinaryAgreement(infos[nid], b"mitm", 0))
    )
    # split inputs — the hard case for schedule attacks
    for nid in range(n):
        net.send_input(nid, nid % 2 == 0)
    net.run_to_quiescence()
    decisions = {
        net.nodes[nid].outputs[0]
        for nid in net.node_ids()
        if net.nodes[nid].outputs
    }
    assert len(decisions) == 1
    for nid in net.node_ids():
        assert net.nodes[nid].algorithm.terminated(), nid


# ---------------------------------------------------------------------------
# FaultKind end-to-end coverage: each reachable kind produced by a crafted
# Byzantine message through the public API
# ---------------------------------------------------------------------------


def _faults(step):
    return {f.kind for f in step.fault_log}


@pytest.fixture()
def bc_net():
    infos = infos_for(4)
    nodes = {nid: Broadcast(infos[nid], 0) for nid in range(4)}
    return infos, nodes


def test_fault_broadcast_kinds(bc_net):
    infos, nodes = bc_net
    proposer = Broadcast(infos[0], 0)
    step = proposer.handle_input(b"value")
    # deliver node 1 its real Value first (the step also carries the
    # proposer's own Echo broadcast — filter to ValueMsg)
    (v1,) = [
        tm.message for tm in step.messages
        if isinstance(tm.message, ValueMsg) and tm.target.contains(1)
    ]
    assert _faults(nodes[1].handle_message(0, v1)) == set()

    # InvalidProof: corrupted shard in a Value to node 2
    (v2,) = [
        tm.message for tm in step.messages
        if isinstance(tm.message, ValueMsg) and tm.target.contains(2)
    ]
    import dataclasses

    bad_proof = dataclasses.replace(
        v2.proof, value=bytes([v2.proof.value[0] ^ 1]) + v2.proof.value[1:]
    )
    assert FaultKind.InvalidProof in _faults(
        nodes[2].handle_message(0, ValueMsg(bad_proof))
    )
    # MultipleValues: a second, different Value to node 1
    assert FaultKind.MultipleValues in _faults(
        nodes[1].handle_message(0, ValueMsg(bad_proof))
    )
    # NotAProposer: Value from a non-proposer
    assert FaultKind.NotAProposer in _faults(
        nodes[3].handle_message(2, v1)
    )
    # UnknownSender
    assert FaultKind.UnknownSender in _faults(
        nodes[3].handle_message(99, v1)
    )
    # MultipleEchos: echo twice with different proofs
    tree = MerkleTree([b"a", b"b", b"c", b"d"])
    from hbbft_tpu.protocols.broadcast import EchoMsg

    e = EchoMsg(tree.proof(2))
    nodes[3].handle_message(2, e)
    e2 = EchoMsg(MerkleTree([b"a", b"b", b"c", b"e"]).proof(2))
    assert FaultKind.MultipleEchos in _faults(nodes[3].handle_message(2, e2))
    # MultipleReadys
    nodes[3].handle_message(1, ReadyMsg(b"\x01" * 32))
    assert FaultKind.MultipleReadys in _faults(
        nodes[3].handle_message(1, ReadyMsg(b"\x02" * 32))
    )


def test_fault_binary_agreement_kinds():
    infos = infos_for(4)
    ba = BinaryAgreement(infos[1], b"faults", 0)
    ba.handle_input(True)
    ba.handle_message(2, BValMsg(0, True))
    # same-value BVal/Aux repeats are BENIGN by design (Term substitutes
    # for them, so repeats are indistinguishable from honest reordering)
    assert _faults(ba.handle_message(2, BValMsg(0, True))) == set()
    ba.handle_message(2, AuxMsg(0, True))
    assert _faults(ba.handle_message(2, AuxMsg(0, True))) == set()
    ba.handle_message(2, ConfMsg(0, BOTH))
    # replays are benign; a CONFLICTING Conf is the faultable abuse
    assert FaultKind.MultipleConf in _faults(
        ba.handle_message(2, ConfMsg(0, frozenset([True])))
    )
    ba.handle_message(2, TermMsg(True))
    assert FaultKind.MultipleTerm in _faults(
        ba.handle_message(2, TermMsg(False))
    )
    assert FaultKind.AgreementEpochMismatch in _faults(
        ba.handle_message(3, BValMsg(10_000, True))
    )


def test_fault_threshold_sign_kinds():
    infos = infos_for(4)
    ts = ThresholdSign(infos[0], optimistic=False)
    ts.set_document(b"doc")
    # InvalidSignatureShare: share from the wrong key
    wrong = infos[1].secret_key_share().sign(b"other doc")
    assert FaultKind.InvalidSignatureShare in _faults(
        ts.handle_message(1, ThresholdSignMessage(wrong))
    )
    good = infos[2].secret_key_share().sign(b"doc")
    ts.handle_message(2, ThresholdSignMessage(good))
    other = infos[3].secret_key_share().sign(b"doc")
    assert FaultKind.MultipleSignatureShares in _faults(
        ts.handle_message(2, ThresholdSignMessage(other))
    )
    # pessimistic fallback in the optimistic path: a garbage share must be
    # evicted and faulted once combination fails
    ts2 = ThresholdSign(infos[0], optimistic=True)
    ts2.set_document(b"doc")
    bad = infos[1].secret_key_share().sign(b"not the doc")
    ts2.handle_message(1, ThresholdSignMessage(bad))
    step = ts2.handle_message(2, ThresholdSignMessage(good))
    acc = _faults(step)
    st = ts2.handle_message(
        3, ThresholdSignMessage(infos[3].secret_key_share().sign(b"doc"))
    )
    acc |= _faults(st)
    assert FaultKind.InvalidSignatureShare in acc


def test_fault_threshold_decrypt_kinds():
    rng = random.Random(3)
    infos = infos_for(4)
    pks = infos[0].public_key_set()
    ct = pks.public_key().encrypt(b"secret", rng)
    td = ThresholdDecrypt(infos[0])
    td.set_ciphertext(ct)  # also contributes node 0's own share
    # InvalidDecryptionShare: share for a DIFFERENT ciphertext; the
    # optimistic combiner defers verification until t+1 shares are in hand,
    # then evicts+faults the liar
    ct2 = pks.public_key().encrypt(b"other", rng)
    bad = infos[1].secret_key_share().decrypt_share(ct2, check=False)
    td2 = ThresholdDecrypt(infos[3])
    td2.set_ciphertext(ct)  # own share counts: bad share hits t+1 at once
    assert FaultKind.InvalidDecryptionShare in _faults(
        td2.handle_message(1, DecryptionMessage(bad))
    )
    # MultipleDecryptionShares: conflicting shares buffered before the
    # ciphertext is known
    td3 = ThresholdDecrypt(infos[2])
    good = infos[1].secret_key_share().decrypt_share(ct, check=False)
    td3.handle_message(1, DecryptionMessage(good))
    assert FaultKind.MultipleDecryptionShares in _faults(
        td3.handle_message(1, DecryptionMessage(bad))
    )


def test_fault_subset_and_honey_badger_kinds():
    rng = random.Random(9)
    infos = infos_for(4)
    sub = Subset(infos[1], session_id=b"s")
    assert FaultKind.InvalidSubsetMessage in _faults(
        sub.handle_message(2, BroadcastWrap(99, ReadyMsg(b"\x00" * 32)))
    )

    hb = (
        HoneyBadger.builder(infos[1])
        .session_id(b"hb-faults")
        .encryption_schedule(EncryptionSchedule.always())
        .rng(random.Random(1))
        .build()
    )
    # UnexpectedHbMessage: far-future epoch
    from hbbft_tpu.protocols.honey_badger import SubsetWrap

    assert FaultKind.UnexpectedHbMessage in _faults(
        hb.handle_message(
            2, SubsetWrap(10_000, BroadcastWrap(0, ReadyMsg(b"\x00" * 32)))
        )
    )

"""Byzantine overload defense: ingress budgets, backlog caps, shedding.

The tentpole's acceptance surface, unit-level: every network-fed buffer
is budgeted, evictable, and observable —

- :class:`~hbbft_tpu.net.transport.IngressBudget` token bucket,
  in-flight frame cap, strike ladder and disconnect backoff;
- wire-decode fuzz under a sustained garbage-frame flood (counted,
  bounded state, guard strikes escalate to a disconnect);
- SenderQueue backlog front-chop at the per-peer cap, counted, with the
  statesync-shaped catch-up still working from the retained tail;
- BinaryAgreement future-buffer eviction (the spammer's own entries,
  epoch priority) and the HoneyBadger / DHB per-sender flood budgets;
- mempool fair admission: a hog cannot starve an under-share client;
- the forensic auditor attributing an overload incident to the
  offending peer from journaled guard events.
"""

import random

import pytest

from hbbft_tpu.fault_log import FaultKind
from hbbft_tpu.net.client import Mempool
from hbbft_tpu.net.transport import IngressBudget
from hbbft_tpu.obs.metrics import Registry


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self) -> float:
        return self.t


# ---------------------------------------------------------------------------
# IngressBudget


def test_token_bucket_throttles_then_recovers():
    clock = FakeClock()
    b = IngressBudget(Registry(), bytes_per_s=1000, burst_bytes=1000,
                      throttle_strikes=1000, clock=clock)
    assert b.charge("p", 600) == 0.0
    delay = b.charge("p", 600)          # burst exhausted → pause
    assert delay > 0
    assert int(b._c_throttles.total()) == 1
    assert float(b._c_throttle_s.total()) == pytest.approx(delay)
    clock.t += 5.0                       # bucket refills
    assert b.charge("p", 600) == 0.0
    assert not b.kill_pending("p")


def test_strike_ladder_disconnects_with_exponential_backoff():
    clock = FakeClock()
    events = []
    b = IngressBudget(Registry(), bytes_per_s=100, burst_bytes=100,
                      throttle_strikes=3, backoff_s=2.0, clock=clock)
    b.on_event = lambda kind, peer, detail: events.append((kind, peer))
    for _ in range(10):
        b.charge("p", 500)
        if b.kill_pending("p"):
            break
    else:
        pytest.fail("strike ladder never tripped")
    assert int(b._c_disconnects.total()) == 1
    assert ("disconnect", "p") in events
    # backoff window armed: hellos rejected until it expires
    assert b.in_backoff("p")
    assert int(b._c_hello_rejects.total()) == 1
    clock.t += 2.1
    assert not b.in_backoff("p")
    # a second trip doubles the backoff
    for _ in range(10):
        b.charge("p", 500)
        if b.kill_pending("p"):
            break
    assert b.in_backoff("p")
    clock.t += 2.1                       # 2s window would have expired
    assert b.in_backoff("p")             # but this one is 4s
    clock.t += 2.0
    assert not b.in_backoff("p")
    # an unrelated peer is never affected
    assert not b.in_backoff("q")


def test_backlog_aftershocks_do_not_inflate_backoff_or_kill_successor():
    """After a disconnect, the pump keeps draining frames the OLD
    connection already admitted — those aftershock strikes must not
    re-count the incident, double the backoff, or leave a stale kill
    mark that tears down the peer's next legitimate connection."""
    clock = FakeClock()
    b = IngressBudget(Registry(), decode_strikes=4, backoff_s=2.0,
                      clock=clock)
    for _ in range(4):
        b.decode_strike("p")
    assert b.kill_pending("p")            # the recv loop tears down
    assert int(b._c_disconnects.total()) == 1
    # the pump drains the backlog: 8 more garbage frames = 2 more trips
    for _ in range(8):
        b.decode_strike("p")
    assert int(b._c_disconnects.total()) == 1   # not re-counted
    clock.t += 2.1                        # window (still 2 s) expires
    assert not b.in_backoff("p")
    # the honest owner of the identity reconnects: hello accept clears
    # the stale kill mark, so its first chunk is NOT torn down
    b.connection_accepted("p")
    assert not b.kill_pending("p")
    assert b.charge("p", 100) == 0.0


def test_decode_strikes_trip_disconnect():
    b = IngressBudget(Registry(), decode_strikes=4, clock=FakeClock())
    for _ in range(3):
        b.decode_strike("p")
    assert not b.kill_pending("p")
    b.decode_strike("p")
    assert b.kill_pending("p")
    assert int(b._c_decode_strikes.total()) == 4
    assert int(b._c_disconnects.total()) == 1


def test_inflight_cap_counts_and_retires():
    clock = FakeClock()
    b = IngressBudget(Registry(), bytes_per_s=1e9, burst_bytes=1e9,
                      max_inflight_frames=4, throttle_strikes=1000,
                      clock=clock)
    b.track_inflight = True
    for _ in range(6):
        b.frame_admitted("p")
    assert b.peer_doc()["'p'"]["inflight"] == 6
    assert b.charge("p", 1) > 0          # over the in-flight cap
    for _ in range(6):
        b.frame_done("p")
    assert b.peer_doc()["'p'"]["inflight"] == 0
    clock.t += 1.0
    assert b.charge("p", 1) == 0.0


# ---------------------------------------------------------------------------
# Runtime decode-fuzz flood (framing-valid, decode-invalid)


@pytest.fixture(scope="module")
def guard_runtime(request):
    """One NodeRuntime (no sockets started) for the fuzz tests."""
    from hbbft_tpu.net.cluster import ClusterConfig, build_algo, \
        generate_infos

    cfg = ClusterConfig(n=4, seed=3)
    infos = generate_infos(cfg)
    from hbbft_tpu.net.runtime import NodeRuntime

    rt = NodeRuntime(build_algo(cfg, infos, 0), cfg.cluster_id,
                     ingress_kwargs={"decode_strikes": 256})
    return rt


def test_decode_fuzz_flood_is_counted_and_bounded(guard_runtime):
    """A sustained garbage-frame flood: every frame counted, the decode
    memo stays bounded, no protocol state grows, and the guard's strike
    ladder marks the peer for disconnect."""
    rt = guard_runtime
    rng = random.Random(0xF100D)
    n_frames = 600
    for i in range(n_frames):
        kind = i % 3
        if kind == 0:                     # undecodable bytes
            payload = bytes(rng.randrange(256)
                            for _ in range(rng.randrange(1, 128)))
        elif kind == 1:                   # torn/empty frames
            payload = b""
        else:                             # decodes, protocol-rejected
            from hbbft_tpu.protocols import wire
            from hbbft_tpu.protocols.broadcast import ReadyMsg

            payload = wire.encode_message(ReadyMsg(bytes(32)))
        rt._process_peer_message(1, payload)
    assert rt.decode_failures == n_frames
    assert int(rt.transport.ingress._c_decode_strikes.total()) == n_frames
    # bounded state: the decode memo only caches SUCCESSFUL decodes and
    # clears wholesale at its cap; garbage must not accumulate anywhere
    assert len(rt._decode_cache) <= 4096
    assert rt.sq.buffered == {}
    assert rt._replay == {}
    # 600 garbage frames > the 256-strike ladder: the recv loop would
    # tear this connection down on its next chunk
    assert rt.transport.ingress.kill_pending(1)
    assert int(rt.transport.ingress._c_disconnects.total()) >= 1


def test_guard_state_visible_in_status(guard_runtime):
    doc = guard_runtime.status_doc()
    g = doc["guard"]
    assert g["ingress"]["decode_strikes"] >= 600
    assert g["ingress"]["disconnects"] >= 1
    assert "senderq_evictions" in g and "mempool_sheds" in g


# ---------------------------------------------------------------------------
# SenderQueue backlog cap (the voted-in joiner that never connects)


def test_senderq_backlog_front_chops_at_cap_and_catches_up(
        shared_netinfo):
    """PR-8's named gap: a voted-in joiner that never connects must not
    grow the SenderQueue backlog without bound.  The backlog front-chops
    its lowest-epoch entries at the cap (counted), and a later
    state-sync-shaped announcement (the joiner landing at the current
    key) still releases the retained deliverable tail in order."""
    from hbbft_tpu.protocols.honey_badger import HoneyBadger, SubsetWrap
    from hbbft_tpu.protocols.sender_queue import AlgoMessage, SenderQueue
    from hbbft_tpu.traits import Step, Target

    infos = shared_netinfo(4, 11)
    evicted = []
    sq = SenderQueue(HoneyBadger(infos[0], session_id=b"cap"),
                     buffered_cap=8,
                     on_evict=lambda peer, n: evicted.append((peer, n)))
    # peer 3 never announces: its record stays (0, 0), window 3 — every
    # message beyond epoch 3 buffers
    for epoch in range(10, 40):
        inner = Step()
        inner.send(Target.nodes([3]), SubsetWrap(epoch, b"m%d" % epoch))
        sq._post(inner)
    assert sq.buffered_len(3) == 8                # pinned at the cap
    assert sq.evictions[3] == 30 - 8
    assert sum(n for _p, n in evicted) == 30 - 8
    kept = sorted(k for k, _m in sq.buffered[3])
    assert kept == [(0, e) for e in range(32, 40)]  # newest retained
    # other peers' backlogs are untouched by 3's overflow
    assert sq.buffered_len(0) == 0
    # statesync catch-up shape: the joiner activates at the current era
    # boundary and announces a key near the head — the retained tail is
    # exactly the deliverable window
    step = sq._peer_advanced(3, (0, 36))
    released = [
        tm for tm in step.messages
        if isinstance(tm.message, AlgoMessage)
    ]
    assert released, "retained backlog must flow after the announcement"
    assert sq.buffered_len(3) <= 8


# ---------------------------------------------------------------------------
# Protocol-layer flood budgets


def test_ba_future_eviction_is_per_sender_epoch_priority(shared_netinfo):
    from dataclasses import dataclass

    from hbbft_tpu.protocols.binary_agreement import BinaryAgreement

    @dataclass(frozen=True)
    class FakeFutureMsg:                  # buffered on .epoch alone
        epoch: int
        nonce: int

    infos = shared_netinfo(4, 11)
    ba = BinaryAgreement(infos[0], b"s", 0)
    cap = ba.future_cap_per_sender
    # sender 1 spams far more distinct future messages than the cap
    for i in range(cap + 30):
        step = ba.handle_message(1, FakeFutureMsg(1 + i % 16, i))
        del step
    mine = [m for s, m in ba.future if s == 1]
    assert len(mine) == cap               # pinned at the cap
    assert ba.future_evictions[1] == 30
    # epoch priority: the retained set skews to the LOWEST epochs
    assert max(m.epoch for m in mine) <= 16
    # an honest peer's few future messages are never evicted
    ba.handle_message(2, FakeFutureMsg(2, 99_999))
    assert sum(1 for s, _m in ba.future if s == 2) == 1
    assert 2 not in ba.future_evictions


def test_hb_future_epoch_budget_faults_and_resets(shared_netinfo):
    from hbbft_tpu.protocols.binary_agreement import BValMsg
    from hbbft_tpu.protocols.honey_badger import HoneyBadger, SubsetWrap
    from hbbft_tpu.protocols.subset import AgreementWrap

    infos = shared_netinfo(4, 11)
    hb = HoneyBadger(infos[0], session_id=b"budget")
    hb.future_msg_budget = 5
    msg = SubsetWrap(2, AgreementWrap(0, BValMsg(1, True)))
    for _ in range(5):
        step = hb.handle_message(1, msg)
        assert not step.fault_log
    step = hb.handle_message(1, msg)
    assert [f.kind for f in step.fault_log] == [FaultKind.FutureEpochFlood]
    assert hb.future_drops[1] == 1
    # another sender has its own budget
    assert not hb.handle_message(2, msg).fault_log


def test_dhb_future_era_cap_is_per_sender(shared_netinfo):
    from hbbft_tpu.protocols.dynamic_honey_badger import (
        DynamicHoneyBadger, HbWrap,
    )
    from hbbft_tpu.protocols.honey_badger import SubsetWrap

    infos = shared_netinfo(4, 11)
    dhb = DynamicHoneyBadger(infos[0], infos[0].secret_key(),
                             rng=random.Random(5))
    dhb.future_era_cap_per_sender = 5
    msg = HbWrap(1, SubsetWrap(0, b"x"))
    for _ in range(5):
        assert not dhb.handle_message(1, msg).fault_log
    step = dhb.handle_message(1, msg)
    assert [f.kind for f in step.fault_log] == [FaultKind.FutureEpochFlood]
    assert dhb.future_era_drops[1] == 1
    assert len(dhb.future_era) == 5
    # sender 2's slice is untouched by 1's overflow
    assert not dhb.handle_message(2, msg).fault_log
    assert len(dhb.future_era) == 6


def test_subset_per_sender_message_budget(shared_netinfo):
    from hbbft_tpu.protocols.binary_agreement import BValMsg
    from hbbft_tpu.protocols.subset import AgreementWrap, Subset

    infos = shared_netinfo(4, 11)
    sub = Subset(infos[0], b"flood")
    sub.msg_budget_per_sender = 3
    msg = AgreementWrap(0, BValMsg(1, True))
    for _ in range(3):
        sub.handle_message(1, msg)
    step = sub.handle_message(1, msg)
    assert [f.kind for f in step.fault_log] == [
        FaultKind.SubsetMessageFlood]
    assert sub.flood_drops[1] == 1


# ---------------------------------------------------------------------------
# Mempool fair admission


def test_mempool_hog_cannot_starve_light_client():
    mp = Mempool(capacity=10)
    for i in range(10):
        assert mp.add(b"hog-%02d" % i, client_id="hog") == Mempool.ACCEPTED
    # pool FULL — but the light client is under its fair share, so the
    # hog's OLDEST pending tx is shed (counted) and the newcomer admitted
    assert mp.add(b"light-0", client_id="light") == Mempool.ACCEPTED
    assert mp.sheds == {"hog": 1}
    assert len(mp) == 10
    # the hog itself stays FULL: it is at/over its share
    assert mp.add(b"hog-extra", client_id="hog") == Mempool.FULL
    # fair share at 2 clients is 5: light keeps landing until it
    # reaches it, each admission shedding one of the hog's
    for i in range(1, 5):
        assert mp.add(b"light-%d" % i,
                      client_id="light") == Mempool.ACCEPTED
    assert mp.sheds == {"hog": 5}
    assert mp.add(b"light-5", client_id="light") == Mempool.FULL


def test_mempool_sybil_swarm_cannot_grind_honest_client_to_zero():
    """Client ids are self-declared: a swarm of minted ids must not
    shrink the fair share toward zero and evict an honest bulk
    client's whole allocation — the divisor is clamped."""
    mp = Mempool(capacity=64)
    mp.fair_clients_max = 4                   # share floor = 16
    for i in range(64):
        assert mp.add(b"bulk-%03d" % i,
                      client_id="bulk") == Mempool.ACCEPTED
    for i in range(200):                      # 200 fresh sybil ids
        mp.add(b"sybil-%03d" % i, client_id="sybil-%03d" % i)
    floor = mp.capacity // mp.fair_clients_max
    assert mp._client_counts["bulk"] >= floor
    assert sum(mp.sheds.values()) <= 64 - floor


def test_mempool_byte_hog_is_sheddable_too():
    """Fair share is count AND bytes: a client that filled the byte
    ceiling with a few huge txs must not be unsheddable just because
    its entry count is tiny."""
    mp = Mempool(capacity=10_000, max_pending_bytes=10_000,
                 max_tx_bytes=4_000)
    for i in range(3):
        assert mp.add(bytes([i]) * 3_000,    # 9 000 B in 3 txs
                      client_id="bytehog") == Mempool.ACCEPTED
    small = b"s" * 2_000
    # byte-FULL; the hog's count (3) is far under the count share, but
    # its bytes are over the byte share — one shed admits the newcomer
    assert mp.add(small, client_id="light") == Mempool.ACCEPTED
    assert sum(mp.sheds.values()) == 1
    assert mp._client_counts["bytehog"] == 2


def test_mempool_shed_is_feasibility_checked():
    """Shedding never destroys acked state for a FULL anyway: if one
    shed cannot admit the newcomer (byte pressure vs small victims),
    nothing is shed at all."""
    mp = Mempool(capacity=50, max_pending_bytes=1000)
    for i in range(50):
        assert mp.add(b"%020d" % i,          # 20 B each, 1000 B total
                      client_id="hog") == Mempool.ACCEPTED
    big = b"x" * 500                          # can never fit via 1 shed
    assert mp.add(big, client_id="light") == Mempool.FULL
    assert mp.sheds == {}                     # nothing destroyed
    assert len(mp) == 50
    small = b"y" * 20                         # one shed admits this
    assert mp.add(small, client_id="light") == Mempool.ACCEPTED
    assert sum(mp.sheds.values()) == 1


def test_mempool_single_client_full_is_unchanged():
    mp = Mempool(capacity=4)
    for i in range(4):
        assert mp.add(b"t%d" % i) == Mempool.ACCEPTED
    assert mp.add(b"t4") == Mempool.FULL        # nobody to shed from
    assert mp.sheds == {}
    # committing frees space and the owner bookkeeping follows
    mp.mark_committed([b"t0", b"t1"])
    assert mp.add(b"t4") == Mempool.ACCEPTED
    assert len(mp) == 3


def test_mempool_shed_reaches_protocol_queue(guard_runtime):
    """A shed tx was already handed to the consensus layer at
    admission: shedding must pull it back out of the protocol queue
    too, or rotating client identities could grow the queue without
    bound through the shedding path itself."""
    rt = guard_runtime
    rt.mempool.capacity = 6
    queue = rt.sq.algo.queue
    hog_txs = [b"hog-q-%02d" % i for i in range(6)]
    for tx in hog_txs:
        assert rt.mempool.add(tx, client_id="hog") == Mempool.ACCEPTED
        rt.pump.enqueue("input", rt.make_tx_input(tx))
    # drain the pump events synchronously (no loop running in this
    # test): the inputs land in the protocol queue
    events = [("input", (rt.make_tx_input(tx),)) for tx in hog_txs]
    rt.pump_process(events, depth=1)
    before = len(queue)
    assert before >= len(hog_txs)
    assert rt.mempool.add(b"light-q", client_id="light") \
        == Mempool.ACCEPTED
    # the shed hook enqueued a pump event; process it
    shed_events = []
    while rt.pump._inbox:
        shed_events.append(rt.pump._inbox.popleft())
    # inbox entries are (kind, args, t_enq) since the pump started
    # stamping queue-wait times
    assert any(ev[0] == "shed" for ev in shed_events)
    rt.pump_process([e for e in shed_events if e[0] == "shed"], depth=1)
    assert len(queue) == before - 1
    assert hog_txs[0] not in queue._set


def test_mempool_sheds_dict_is_key_capped():
    mp = Mempool(capacity=4)
    mp._sheds_key_cap = 2
    # rotate hog identities (commit everything between waves so each
    # wave's hog really fills the pool); every shed victim would
    # otherwise mint a fresh dict key forever
    for wave in range(6):
        hog = "hog-%d" % wave
        for i in range(4):
            assert mp.add(b"h%d-%d" % (wave, i),
                          client_id=hog) == Mempool.ACCEPTED
        assert mp.add(b"l%d" % wave,
                      client_id="light-%d" % wave) == Mempool.ACCEPTED
        mp.mark_committed(list(mp._pending.values()))
    assert sum(mp.sheds.values()) == 6
    assert len(mp.sheds) <= 3                  # 2 keys + _overflow_
    assert "_overflow_" in mp.sheds


def test_shed_notification_definitive_and_suppressed_when_riding(
        guard_runtime):
    """The ACK_SHED push is DEFINITIVE: emitted only for a shed tx that
    was still in the protocol queue and NOT riding an open proposal —
    a proposal cannot be recalled, so such a tx may still commit and
    the client must not be told otherwise."""
    from hbbft_tpu.net.client import tx_digest as _digest

    rt = guard_runtime
    qhb = rt.sq.algo
    tx_q = b"shed-unit-queued"
    tx_r = b"shed-unit-riding"
    qhb.queue.extend([tx_q, tx_r])
    qhb._proposed[(99, 99)] = (tx_r,)        # riding an open epoch
    try:
        out = rt.pump_process(
            [("shed", (tx_q,)), ("shed", (tx_r,)),
             ("shed", (b"shed-unit-never-queued",))], depth=1)
    finally:
        qhb._proposed.pop((99, 99), None)
    # only the queued-and-unproposed tx earns the notification; both
    # queued txs still left the queue (consensus-side memory freed)
    assert out.sheds == [_digest(tx_q)]
    assert tx_q not in qhb.queue._set and tx_r not in qhb.queue._set


def test_client_ack_shed_fails_commit_waiters_fast():
    """Client side of the push: a pending ``wait_committed`` raises
    :class:`TxShedError` promptly instead of riding out its timeout."""
    import asyncio

    from hbbft_tpu.net import framing
    from hbbft_tpu.net.client import (ClusterClient, TxShedError,
                                      tx_digest)

    async def scenario():
        c = ClusterClient(("127.0.0.1", 1), b"x")
        digest = tx_digest(b"shed-me")
        fut = asyncio.get_running_loop().create_future()
        c._commits.setdefault(digest, []).append(fut)
        c._submit_times[digest] = 0.0
        c._on_frame(framing.TX_ACK,
                    bytes([framing.ACK_SHED]) + digest)
        with pytest.raises(TxShedError):
            await fut
        assert digest not in c._submit_times

    asyncio.run(scenario())


def test_mempool_shed_metrics_registered():
    reg = Registry()
    mp = Mempool(capacity=2, registry=reg)
    mp.add(b"a", client_id="hog")
    mp.add(b"b", client_id="hog")
    mp.add(b"c", client_id="light")
    assert reg.get("hbbft_guard_mempool_sheds_total").value(
        client="hog") == 1


# ---------------------------------------------------------------------------
# Forensics: guard events → audit attribution


def test_audit_attributes_overload_to_offending_peer(tmp_path):
    from hbbft_tpu.obs.audit import format_report, run_audit
    from hbbft_tpu.obs.flight import FlightRecorder

    for node in ("0", "1"):
        rec = FlightRecorder(str(tmp_path / f"node-{node}"), node=node)
        rec.note("guard", "kind=throttle peer=3 why=bytes_per_s")
        rec.note("guard", "kind=disconnect peer=3 why=decode_garbage "
                          "backoff_s=2.0")
        rec.close()
    res, _journals = run_audit([str(tmp_path)])
    assert res.verdict == "clean"         # defense working ≠ fault
    (incident,) = res.overload_incidents
    assert incident["peer"] == "3"
    assert incident["kinds"] == {"disconnect": 2, "throttle": 2}
    assert incident["witnesses"] == ["0", "1"]
    assert "OVERLOAD: peer 3" in format_report(res)
    assert res.as_dict()["overload_incidents"] == res.overload_incidents


def test_flight_truncation_spans_incarnations(tmp_path):
    """PR-8's named gap: checkpoint truncation must reason about
    segments left by OLDER incarnations, so audits across restarts keep
    the incident window without pinning stale segments forever."""
    from hbbft_tpu.obs.flight import FlightRecorder

    d = str(tmp_path / "node-0")
    rec1 = FlightRecorder(d, node="0", max_segment_bytes=256,
                          max_segments=64)
    for i in range(40):
        rec1.record_commit(0, i, i, bytes([i]) * 32)
    rec1.close()
    rec2 = FlightRecorder(d, node="0", max_segment_bytes=256,
                          max_segments=64)
    assert rec2.incarnation == 2
    indexed = int(rec2._c_prior_indexed.total())
    assert indexed > 1                    # rec1's segments are known
    removed = rec2.truncate_checkpoint(30)
    assert removed > 0                    # old-incarnation segments go
    assert int(rec2._c_truncations.total()) == removed
    # commits ≥ the horizon survive — the incident window is intact
    from hbbft_tpu.obs.flight import FlightCommit, read_journal

    rec2.record_commit(0, 40, 40, bytes([40]) * 32)
    rec2.close()
    j = read_journal(d)
    commits = [r.index for _inc, r in j.records
               if isinstance(r, FlightCommit)]
    assert max(commits) == 40
    assert any(c >= 30 for c in commits if c < 40)
"""Test configuration.

Tests run on a virtual 8-device CPU mesh (no TPU required, deterministic,
fast): the env vars below must be set before jax initializes its backends, so
this module sets them at import time — pytest imports conftest before any
test module imports jax.
"""

import os

# Force CPU with 8 virtual devices even when the session env points JAX at a
# TPU tunnel (JAX_PLATFORMS=axon, registered by a sitecustomize that imports
# jax before any test code runs — so plain env vars are too late and we must
# go through jax.config).  Unit tests must be fast, local, and deterministic;
# the TPU is for bench.py.
xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax

jax.config.update("jax_platforms", "cpu")

import random

import pytest


@pytest.fixture
def rng():
    """Seeded RNG — every test failure reproduces from this seed."""
    return random.Random(0x48425446)  # "HBTF"

"""Test configuration.

Tests run on a virtual 8-device CPU mesh (no TPU required, deterministic,
fast): the env vars below must be set before jax initializes its backends, so
this module sets them at import time — pytest imports conftest before any
test module imports jax.
"""

import os

# Force CPU with 8 virtual devices even when the session env points JAX at a
# TPU tunnel (JAX_PLATFORMS=axon, registered by a sitecustomize that imports
# jax before any test code runs — so plain env vars are too late and we must
# go through jax.config).  Unit tests must be fast, local, and deterministic;
# the TPU is for bench.py.
xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ["JAX_PLATFORMS"] = "cpu"
# Plain bitwise MSM ladders in the suite: the windowed ladder's XLA graph
# costs ~250 s to compile cold on this CPU backend vs ~30 s plain (both
# exact; crypto/batch.py documents the knob).  The windowed FUNCTION stays
# covered by its direct tests (test_fp381_mxu / test_gcurve) and by every
# TPU bench run; only the _MsmCache integration uses plain here.
os.environ.setdefault("HBBFT_PLAIN_LADDER", "1")

import jax

jax.config.update("jax_platforms", "cpu")

from hbbft_tpu.util import enable_compilation_cache

# The big fori_loop ladder graphs cost minutes to compile; persist the
# executables so the suite pays that once per (code, shape), not per run.
enable_compilation_cache()

import random

import pytest


def pytest_addoption(parser):
    parser.addoption(
        "--slow", action="store_true", default=False,
        help="also run tests marked slow (full-width MSM ladders etc.)",
    )


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: long-running test, skipped unless --slow is given"
    )


def pytest_collection_modifyitems(config, items):
    if config.getoption("--slow"):
        return
    skip = pytest.mark.skip(reason="slow test: run with --slow")
    for item in items:
        if "slow" in item.keywords:
            item.add_marker(skip)


@pytest.fixture
def rng():
    """Seeded RNG — every test failure reproduces from this seed."""
    return random.Random(0x48425446)  # "HBTF"

"""Test configuration.

Tests run on a virtual 8-device CPU mesh (no TPU required, deterministic,
fast): the env vars below must be set before jax initializes its backends, so
this module sets them at import time — pytest imports conftest before any
test module imports jax.
"""

import os

# Force CPU with 8 virtual devices even when the session env points JAX at a
# TPU tunnel (JAX_PLATFORMS=axon, registered by a sitecustomize that imports
# jax before any test code runs — so plain env vars are too late and we must
# go through jax.config).  Unit tests must be fast, local, and deterministic;
# the TPU is for bench.py.
xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ["JAX_PLATFORMS"] = "cpu"
# Plain bitwise MSM ladders in the suite: the windowed ladder's XLA graph
# costs ~250 s to compile cold on this CPU backend vs ~30 s plain (both
# exact; crypto/batch.py documents the knob).  The windowed FUNCTION stays
# covered by its direct tests (test_fp381_mxu / test_gcurve) and by every
# TPU bench run; only the _MsmCache integration uses plain here.
os.environ.setdefault("HBBFT_PLAIN_LADDER", "1")

import jax

jax.config.update("jax_platforms", "cpu")

from hbbft_tpu.util import enable_compilation_cache

# The big fori_loop ladder graphs cost minutes to compile; persist the
# executables so the suite pays that once per (code, shape), not per run.
enable_compilation_cache()

import random

import pytest


def pytest_addoption(parser):
    parser.addoption(
        "--slow", action="store_true", default=False,
        help="also run tests marked slow (full-width MSM ladders etc.)",
    )


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: long-running test, skipped unless --slow is given"
    )


def pytest_collection_modifyitems(config, items):
    if config.getoption("--slow"):
        return
    skip = pytest.mark.skip(reason="slow test: run with --slow")
    for item in items:
        if "slow" in item.keywords:
            item.add_marker(skip)


@pytest.fixture
def rng():
    """Seeded RNG — every test failure reproduces from this seed."""
    return random.Random(0x48425446)  # "HBTF"


# ---------------------------------------------------------------------------
# Session-scoped shared keygen / DKG runs
#
# The BLS-heavy DHB/DKG tests were 4 of the suite's 10 slowest: every
# driver instance re-TRACES the full batched-ACS graph for each payload
# shape it meets (the persistent cache stores XLA executables, not Python
# traces), so re-running a DKG rotation per test pays tens of seconds of
# pure tracing each time.  These fixtures run each expensive scenario ONCE
# per session and hand tests the recorded artifacts (batches, rotated
# validator sets, era-1 results) to assert on.  Consumers must treat the
# returned objects as READ-ONLY; a test that needs to drive epochs itself
# builds its own driver.
# ---------------------------------------------------------------------------


@pytest.fixture(scope="session")
def shared_netinfo():
    """Session-scoped ``NetworkInfo.generate_map`` cache: the BLS keygen
    for a given (n, seed) runs once per suite.  The returned maps are
    shared — read-only by contract (drivers copy the dict and never mutate
    the NetworkInfo objects)."""
    from hbbft_tpu.netinfo import NetworkInfo

    cache = {}

    def get(n: int, seed: int):
        if (n, seed) not in cache:
            cache[(n, seed)] = NetworkInfo.generate_map(
                list(range(n)), random.Random(seed)
            )
        return cache[(n, seed)]

    return get


def _run_dkg_scenario(infos, vote, era1_payload):
    """One complete DKG era rotation on a fresh array driver: vote, drive
    epochs until the change completes, then run one era-1 epoch under the
    ROTATED keys.  Returns every artifact the consuming tests assert on."""
    from hbbft_tpu.parallel.dhb import BatchedDynamicHoneyBadger

    dhb = BatchedDynamicHoneyBadger(
        infos, session_id=b"dhb-arr", rng=random.Random(77)
    )
    vote(dhb)
    b0 = dhb.run_epoch(
        {nid: b"e0-%d" % nid for nid in dhb.validators}
    )
    final = (
        b0 if b0.change.state == "complete"
        else dhb.run_until_change_completes()
    )
    era1_validators = sorted(dhb.validators)
    era1_contribs = {nid: era1_payload(nid) for nid in dhb.validators}
    b1 = dhb.run_epoch(era1_contribs)
    join_plan_error = None
    try:
        dhb.join_plan()
    except ValueError as exc:
        join_plan_error = exc
    return {
        "batches": list(dhb.batches),
        "b0": b0,
        "final": final,
        "era": dhb.era,
        "era1_validators": era1_validators,
        "era1_contribs": era1_contribs,
        "era1_batch": b1,
        "join_plan_error": join_plan_error,
    }


@pytest.fixture(scope="session")
def dkg_remove_run(shared_netinfo):
    """Remove-validator rotation at the cross-mode scenario's shape
    (n=4, seed 31, everyone votes node 3 out, epoch-0 payloads
    ``e0-<nid>``) — shared by the rotation test AND the array side of the
    cross-mode equality test."""

    def vote(dhb):
        for voter in range(4):
            dhb.vote_to_remove(voter, 3)

    return _run_dkg_scenario(
        shared_netinfo(4, 31), vote, lambda nid: b"era1-%d" % nid
    )


@pytest.fixture(scope="session")
def dkg_add_run(shared_netinfo):
    """Add-validator rotation (n=4 → 5, seed 5): candidate 4 joins via
    DKG; the era-1 epoch includes its contribution.  The single most
    expensive scenario in the suite — run once, asserted on by the
    add-validator test (and the completion half of the recoverable-missing-
    key test, which now only asserts its DKG *starts*)."""
    from hbbft_tpu.crypto import tc

    new_sk = tc.SecretKey.random(random.Random(99))

    def vote(dhb):
        for voter in range(4):
            dhb.vote_to_add(voter, 4, new_sk.public_key(), secret_key=new_sk)

    return _run_dkg_scenario(
        shared_netinfo(4, 5), vote, lambda nid: b"era1-%d" % nid
    )

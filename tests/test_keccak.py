"""SHA3-256 jnp implementation vs the hashlib host oracle."""

import hashlib

import numpy as np
import pytest

from hbbft_tpu.ops import keccak


@pytest.mark.parametrize("length", [0, 1, 31, 32, 135, 136, 137, 271, 272, 500])
def test_sha3_matches_hashlib(length):
    import jax.numpy as jnp

    rng = np.random.RandomState(length)
    data = rng.randint(0, 256, (3, length)).astype(np.uint8)
    out = np.asarray(keccak.sha3_256(jnp.asarray(data)))
    for i in range(3):
        expected = hashlib.sha3_256(data[i].tobytes()).digest()
        assert out[i].tobytes() == expected


def test_sha3_batched_multi_axis():
    import jax
    import jax.numpy as jnp

    rng = np.random.RandomState(42)
    data = rng.randint(0, 256, (2, 3, 40)).astype(np.uint8)
    out = np.asarray(jax.jit(keccak.sha3_256)(jnp.asarray(data)))
    assert out.shape == (2, 3, 32)
    for i in range(2):
        for j in range(3):
            assert out[i, j].tobytes() == hashlib.sha3_256(data[i, j].tobytes()).digest()


def test_round_constants_known_values():
    # First and last round constants of keccak-f[1600] (FIPS-202 appendix).
    assert keccak.ROUND_CONSTANTS[0] == 0x0000000000000001
    assert keccak.ROUND_CONSTANTS[1] == 0x0000000000008082
    assert keccak.ROUND_CONSTANTS[23] == 0x8000000080008008


@pytest.mark.parametrize("form", ["wide", "compact"])
def test_both_round_forms_bit_exact(form, monkeypatch):
    """Both traced round-body forms (the TPU-tuned unrolled one and the
    compile-cheap compact one — see keccak._keccak_form) must be bit-exact
    against hashlib regardless of which backend auto-selection would pick."""
    import jax.numpy as jnp

    monkeypatch.setenv("HBBFT_KECCAK_FORM", form)
    rng = np.random.RandomState(7)
    data = rng.randint(0, 256, (4, 77)).astype(np.uint8)
    out = np.asarray(keccak.sha3_256(jnp.asarray(data)))
    for i in range(4):
        assert out[i].tobytes() == hashlib.sha3_256(data[i].tobytes()).digest()

"""Chaos on the REAL transport: shaped LocalClusters.

The satellite contract: a client submitting across a timed
partition-and-heal either commits after the heal or fails loudly — a
``wait_committed`` future must never wedge silently.  Plus the preset
plumbing (``ClusterConfig.chaos`` → per-node shaper → ``/metrics``).
"""

import asyncio

import pytest

from hbbft_tpu.chaos.link import LinkShaper, NetShape, ShapedLink
from hbbft_tpu.net.cluster import (
    ClusterConfig,
    LocalCluster,
    build_runtime,
    generate_infos,
)

SCENARIO_TIMEOUT_S = 90


def _partition_shaper(nid: int, n: int, victim: int,
                      window) -> LinkShaper:
    """Hold-mode partition isolating ``victim`` on every crossing edge
    of node ``nid``'s egress during ``window`` (transport clock)."""
    link = ShapedLink(partitions=(window,))
    edges = {}
    if nid == victim:
        edges = {(victim, other): link for other in range(n)
                 if other != victim}
    else:
        edges = {(nid, victim): link}
    return LinkShaper(NetShape(edges=edges), seed=nid)


def test_client_across_partition_and_heal_commits_or_fails_loudly():
    """Transactions submitted to a partitioned node: wait_committed
    FAILS LOUDLY (TimeoutError) while the partition lasts, and the same
    transaction COMMITS once the link heals — no silent wedge.  The
    majority side keeps committing throughout."""

    async def scenario():
        n, victim = 4, 0
        window = (0.0, 2.0)  # victim isolated from transport start
        cfg = ClusterConfig(n=n, seed=51, batch_size=4,
                            heartbeat_s=0.3, dead_after_s=5.0)
        infos = generate_infos(cfg)
        runtimes = [
            build_runtime(cfg, infos, nid,
                          shaper=_partition_shaper(nid, n, victim,
                                                   window))
            for nid in range(n)
        ]
        addrs = {}
        for nid, rt in enumerate(runtimes):
            addrs[nid] = await rt.start(cfg.host, 0)
        for rt in runtimes:
            rt.connect(addrs)
        try:
            from hbbft_tpu.net.client import ClusterClient

            # client on the partitioned node: the client socket is NOT
            # shaped (shaping is consensus egress), so admission works —
            # but the node cannot drive consensus until the heal
            c_victim = ClusterClient(addrs[victim], cfg.cluster_id,
                                     client_id="c-victim")
            await c_victim.connect()
            tx_v = b"partitioned-tx"
            assert await c_victim.submit(tx_v) == 0
            # ... fails loudly while partitioned (future resolved by
            # wait_for's TimeoutError, not a silent wedge)
            with pytest.raises(asyncio.TimeoutError):
                await c_victim.wait_committed(tx_v, timeout_s=0.8)

            # the majority side commits right through the partition
            c_major = ClusterClient(addrs[1], cfg.cluster_id,
                                    client_id="c-major")
            await c_major.connect()
            txs = [b"majority-%02d" % i for i in range(8)]
            for tx in txs:
                assert await c_major.submit(tx) == 0
            for tx in txs:
                await c_major.wait_committed(tx, timeout_s=30)

            # after the heal, the held frames flood through and the
            # victim's transaction commits — the SAME future path that
            # timed out above now resolves
            lat = await c_victim.wait_committed(tx_v, timeout_s=45)
            assert lat >= 0.0
            # every ledger agrees wherever the chains overlap
            tails = [(rt.digest_chain_offset, rt.digest_chain)
                     for rt in runtimes]
            lo = max(off for off, _c in tails)
            hi = min(off + len(c) for off, c in tails)
            assert hi - lo >= 1
            for i in range(lo, hi):
                assert len({c[i - off] for off, c in tails}) == 1
            # the shaping showed up in the victim's metrics
            stats = runtimes[victim].transport.shaper.stats()
            assert stats["partition_holds"] > 0
            await c_victim.close()
            await c_major.close()
        finally:
            for rt in runtimes:
                await rt.stop()

    asyncio.run(asyncio.wait_for(scenario(), SCENARIO_TIMEOUT_S))


def test_cluster_config_chaos_preset_plumbs_to_runtime():
    """ClusterConfig.chaos builds one shaper per node over the preset,
    and LocalCluster serves its counters on /metrics."""

    async def scenario():
        cfg = ClusterConfig(n=4, seed=9, batch_size=4, chaos="wan-100ms")
        shaper = cfg.chaos_shaper_for(0)
        assert shaper.policy_for(0, 1).delay_s == pytest.approx(0.05)
        cluster = LocalCluster(cfg)
        await cluster.start()
        try:
            assert all(rt.transport.shaper is not None
                       for rt in cluster.runtimes)
            client = await cluster.client(0)
            txs = [b"wan-%02d" % i for i in range(4)]
            for tx in txs:
                assert await client.submit(tx) == 0
            for tx in txs:
                await client.wait_committed(tx, timeout_s=45)
            # shaped frames are visible on the node's live metrics
            from hbbft_tpu.obs.http import http_get

            host, port = cluster.metrics_addrs[0]
            text = await asyncio.to_thread(http_get, host, port,
                                           "/metrics")
            for line in text.splitlines():
                if line.startswith("hbbft_chaos_frames_shaped_total"):
                    assert float(line.split()[-1]) > 0
                    break
            else:
                raise AssertionError("hbbft_chaos_frames_shaped_total "
                                     "not exposed")
        finally:
            await cluster.stop()

    asyncio.run(asyncio.wait_for(scenario(), SCENARIO_TIMEOUT_S))


def test_chaos_seed_controls_fault_schedule():
    """Same preset + same seed → the same per-edge fault decisions;
    a different chaos seed diverges (the interactive replay contract of
    examples/cluster.py --chaos)."""
    cfg_a = ClusterConfig(n=4, seed=3, chaos="lossy-1pct")
    cfg_b = ClusterConfig(n=4, seed=3, chaos="lossy-1pct")
    cfg_c = ClusterConfig(n=4, seed=3, chaos="lossy-1pct", chaos_seed=99)

    def draws(cfg):
        shaper = cfg.chaos_shaper_for(2)
        return [shaper.shape_frame(2, 0, 0.0, nbytes=64)
                for _ in range(300)]

    assert draws(cfg_a) == draws(cfg_b)
    assert draws(cfg_a) != draws(cfg_c)

"""Epoch-pipelined runtime: depth > 1 correctness, restart, audit.

The pipelined scheduler (net/scheduler.py + ``pipeline_depth``) must
change THROUGHPUT, never outcomes: identical ledgers across nodes, a
node restarted from scratch still rebuilds the exact chain through the
``SenderQueue.reinit_peer`` rewind, and the forensic auditor still
reaches the right verdict — clean for the restart incident, ``fault``
naming the culprit under an equivocating adversary driven WITH
pipelining engaged.
"""

import asyncio
import random

import pytest

from hbbft_tpu.net.cluster import ClusterConfig, LocalCluster, build_runtime, generate_infos
from hbbft_tpu.protocols.queueing_honey_badger import PipelineInput, QhbBatch

SMOKE_TIMEOUT_S = 90


def _common_prefix(runtimes):
    """Offset-aware agreed digest chain across runtimes (raises on any
    conflict) — the hand-built sibling of LocalCluster.common_digest_prefix."""
    tails = [(rt.digest_chain_offset, rt.digest_chain) for rt in runtimes]
    lo = max(off for off, _c in tails)
    hi = min(off + len(c) for off, c in tails)
    prefix = []
    for i in range(lo, hi):
        vals = {c[i - off] for off, c in tails}
        assert len(vals) == 1, f"ledger fork at batch {i}: {sorted(vals)}"
        prefix.append(tails[0][1][i - tails[0][0]])
    return prefix


def test_pipelined_smoke_and_depth_engages(tmp_path):
    """A depth-3 cluster commits under load with identical ledgers, the
    pipeline actually engages (≥ 2 epochs in flight observed), and the
    flight journals still audit clean."""
    flight_root = str(tmp_path / "flight")

    async def scenario():
        cfg = ClusterConfig(n=4, seed=23, batch_size=4, pipeline_depth=3,
                            flight_dir=flight_root)
        cluster = LocalCluster(cfg)
        await cluster.start()
        max_in_flight = 0
        try:
            client = await cluster.client(0)
            txs = [b"pipe-%03d" % i for i in range(48)]
            for tx in txs:
                assert await client.submit(tx) == 0

            async def watch_depth():
                nonlocal max_in_flight
                while True:
                    for rt in cluster.runtimes:
                        hb = rt._inner_hb()
                        if hb is not None:
                            max_in_flight = max(max_in_flight,
                                                len(hb.epochs))
                    await asyncio.sleep(0.002)

            watcher = asyncio.get_running_loop().create_task(watch_depth())
            try:
                for tx in txs:
                    await client.wait_committed(tx, timeout_s=60)
                await cluster.wait_epochs(3, timeout_s=30)
            finally:
                watcher.cancel()
                with pytest.raises(asyncio.CancelledError):
                    await watcher
            assert len(cluster.common_digest_prefix()) >= 3
            doc = await client.status()
            assert doc["pipeline_depth"] == 3
            assert doc["decode_failures"] == 0
        finally:
            await cluster.stop()
        return max_in_flight

    max_in_flight = asyncio.run(
        asyncio.wait_for(scenario(), SMOKE_TIMEOUT_S))
    # depth-3 under sustained load: at least two epochs were genuinely
    # concurrent at some observed instant
    assert max_in_flight >= 2, max_in_flight
    from hbbft_tpu.obs.audit import run_audit

    res, journals = run_audit([flight_root])
    assert len(journals) == 4
    assert res.verdict == "clean", res.as_dict()


def test_pipelined_restart_rebuilds_identical_ledger(tmp_path):
    """A node torn down mid-run under pipeline_depth=2 and restarted from
    scratch at (0, 0) rebuilds the identical ledger via the
    ``SenderQueue.reinit_peer`` replay rewind, and the whole incident
    audits clean (the restart shows as an incarnation, not a fork)."""
    flight_root = str(tmp_path / "flight")

    async def scenario():
        cfg = ClusterConfig(n=4, seed=42, batch_size=4, pipeline_depth=2,
                            heartbeat_s=0.2, dead_after_s=2.0,
                            flight_dir=flight_root)
        infos = generate_infos(cfg)
        runtimes = [build_runtime(cfg, infos, nid) for nid in range(4)]
        addrs = {}
        for nid, rt in enumerate(runtimes):
            addrs[nid] = await rt.start("127.0.0.1", 0)
        for rt in runtimes:
            rt.connect(addrs)

        seq = 0

        async def load(targets, waves):
            """Submit a wave of txs, wait for every target's mempool to
            drain (all committed), repeat — each wave forces ≥ 1 epoch.
            Transactions only ever enter through nodes 0..2 (the e2e's
            shape): node 3's own contributions stay empty, so its
            restart-from-scratch re-proposals are bytewise identical to
            its first incarnation's and the audit stays clean."""
            nonlocal seq
            for _ in range(waves):
                for _i in range(8):
                    targets[seq % len(targets)].submit_tx(
                        b"rst-%04d" % seq)
                    seq += 1

                async def drained():
                    while any(len(rt.mempool) for rt in targets):
                        await asyncio.sleep(0.02)

                await asyncio.wait_for(drained(), 60)

        async def node3_level():
            while len(runtimes[3].batches) < min(
                len(rt.batches) for rt in runtimes[:3]
            ):
                await asyncio.sleep(0.05)

        # phase 1: everyone commits a shared prefix
        await load(runtimes[:3], 3)
        await asyncio.wait_for(node3_level(), 30)
        pre_kill = len(_common_prefix(runtimes))
        assert pre_kill >= 3

        # tear node 3 down hard (process-death equivalent in-process)
        await runtimes[3].stop()

        # the cluster keeps committing with 3 of 4
        await load(runtimes[:3], 2)

        # restart node 3 from scratch at (0, 0) on its old address
        runtimes[3] = build_runtime(cfg, infos, 3)
        await runtimes[3].start(*addrs[3])
        runtimes[3].connect(addrs)

        await load(runtimes[:3], 2)
        await asyncio.wait_for(node3_level(), 90)

        prefix = _common_prefix(runtimes)
        assert len(prefix) >= pre_kill + 2
        # the restarted node really rebuilt PRE-KILL history: its retained
        # chain starts at offset 0 and matches the agreed prefix
        assert runtimes[3].digest_chain_offset == 0
        assert runtimes[3].digest_chain[: pre_kill] == prefix[: pre_kill]
        for rt in runtimes:
            await rt.stop()

    asyncio.run(asyncio.wait_for(scenario(), 240))

    from hbbft_tpu.obs.audit import run_audit

    res, journals = run_audit([flight_root])
    assert len(journals) == 4
    assert res.restarts[repr(3)] >= 1  # the teardown is visible
    assert res.verdict == "clean", res.as_dict()


def test_equivocating_adversary_audited_under_pipelining(
        shared_netinfo, tmp_path):
    """The sim-side twin: drive a recorded VirtualNet QHB run with
    ``PipelineInput`` keeping 3 epochs proposed-into while node 3
    equivocates — the auditor must still name node 3 with receiver-side
    evidence (pipelining must not blur fault attribution)."""
    from hbbft_tpu.fault_log import equivocation_kinds
    from hbbft_tpu.obs import audit
    from hbbft_tpu.protocols.dynamic_honey_badger import DynamicHoneyBadger
    from hbbft_tpu.protocols.honey_badger import EncryptionSchedule
    from hbbft_tpu.protocols.queueing_honey_badger import (
        QueueingHoneyBadger, TxInput,
    )
    from hbbft_tpu.sim import NetBuilder
    from hbbft_tpu.sim.adversary import EquivocatingAdversary

    infos = shared_netinfo(4, 13)
    root = str(tmp_path / "flight-equiv-pipe")
    net = NetBuilder(list(range(4))).adversary(
        EquivocatingAdversary()).faulty([3]).flight(root).using_step(
        lambda nid: QueueingHoneyBadger(
            DynamicHoneyBadger(
                infos[nid], infos[nid].secret_key(),
                rng=random.Random(100 + nid),
                encryption_schedule=EncryptionSchedule.never(),
            ),
            batch_size=4, rng=random.Random(200 + nid),
        )
    )
    for i in range(12):
        net.send_input(i % 4, TxInput(b"pipe-audit-%d" % i))
    # keep the pipeline topped up on the honest nodes while cranking
    # (the equivocator's queue never drains, so the run is crank-bounded)
    cranks = 0
    while net.queue and net.cranks < 60_000:
        if cranks % 400 == 0:
            for nid in (0, 1, 2):
                net.send_input(nid, PipelineInput(3))
        net.crank()
        cranks += 1
    net.close_observers()
    for nid in (0, 1, 2):
        assert sum(1 for o in net.nodes[nid].outputs
                   if isinstance(o, QhbBatch)) >= 1
    res, _ = audit.run_audit([root])
    assert res.verdict == "fault"
    assert res.equivocations
    assert {e["sender"] for e in res.equivocations} == {"3"}
    assert {e["kind"] for e in res.equivocations} <= {
        k.name for k in equivocation_kinds()
    }

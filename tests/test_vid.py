"""Verifiable information dispersal: disperse/vote/cert + retrieve faults.

The VID subsystem decouples availability from ordering (protocols/vid.py
+ net/retrieve.py): a proposer disperses its contribution as per-node
shards, collects n − f signed availability votes into a retrievability
certificate, and epochs order only the constant-size (root, cert)
commitment — payloads are retrieved lazily post-commit.  These tests pin:

- the sans-I/O disperse → vote → cert round trip (cert verifies, rides
  the VID1 commitment codec, and a tampered transcript fails);
- the retrieve-path fault contracts: a donor shard failing its Merkle
  proof is counted + faulted and reconstruction still succeeds from the
  remaining donors; a retrieve for a never-dispersed root is refused
  LOUDLY (counted + noted, never a fault); a non-codeword dispersal is
  caught deterministically at reconstruction and attributed to the
  proposer; exhausted retrievals surface as failed RetrievedPayloads;
- the ShardStore byte-cap LRU regression (evictions counted, cap held,
  re-put refreshes recency without double-charging);
- the per-peer serve quota (over-budget retrieves dropped + counted);
- one sub-10s 4-node socket smoke: a VID-mode LocalCluster commits
  client transactions end to end, and the ingress-worker variant keeps
  every node on one byte-identical ledger.
"""

import asyncio
import random

from hbbft_tpu.fault_log import FaultKind
from hbbft_tpu.net.retrieve import RetrieveService, ShardStore
from hbbft_tpu.ops.merkle import MerkleTree, Proof
from hbbft_tpu.protocols.vid import (
    Disperser,
    VidCert,
    VidDisperse,
    VidRetrieve,
    VidShard,
    VidVote,
    decode_commitment,
    encode_commitment,
    verify_cert,
)

N, F = 4, 1  # k = n − 2f = 2


def _disperse(infos, payload: bytes, era: int = 0, proposer: int = 0):
    """Run a real dispersal on the proposer; return (root, proofs-by-index,
    disperser, per-dest VidDisperse map)."""
    d = Disperser(ShardStore())
    root, step = d.disperse(era, infos[proposer], payload)
    all_ids = sorted(infos.keys())
    by_dest = {}
    proofs = {}
    for tm in step.messages:
        assert isinstance(tm.message, VidDisperse)
        for dest in tm.target.resolve(all_ids, proposer):
            by_dest[dest] = tm.message
            proofs[tm.message.proof.index] = tm.message.proof
    own = d.store.proof_for(root)
    assert own is not None
    proofs[own[1].index] = own[1]
    return root, proofs, d, by_dest


def _notes():
    seen = []
    return seen, lambda kind, detail: seen.append((kind, detail))


# ---------------------------------------------------------------------------
# Disperse → vote → cert
# ---------------------------------------------------------------------------


def test_disperse_vote_cert_roundtrip(shared_netinfo):
    infos = shared_netinfo(N, 21)
    payload = random.Random(3).randbytes(700)
    root, _proofs, prop, by_dest = _disperse(infos, payload, era=0)
    assert len(by_dest) == N - 1  # one shard per non-proposer node

    # each receiver proof-checks its shard, stores it, and votes
    votes = []
    for nid, msg in sorted(by_dest.items()):
        recv = Disperser(ShardStore())
        step = recv.handle_disperse(infos[nid], 0, msg)
        assert step.fault_log.is_empty()
        assert recv.store.known(root)
        (tm,) = step.messages
        assert isinstance(tm.message, VidVote)
        votes.append((nid, tm.message))
        # re-disperse (excluded proposer re-sampling the same queue):
        # the cached vote is re-SENT so the proposer can still reach a
        # cert, but it is never re-SIGNED
        (again,) = recv.handle_disperse(infos[nid], 0, msg).messages
        assert again.message == tm.message
        assert recv.votes_cast == 1

    # a vote with a garbage signature faults the voter, not the dispersal
    bad = VidVote(0, root, infos[1].secret_key().sign(b"wrong transcript"))
    step, cert = prop.handle_vote(infos[0], 1, bad)
    assert cert is None
    assert [(f.node_id, f.kind) for f in step.fault_log.faults] == [
        (1, FaultKind.VidInvalidVote)
    ]

    # the cert completes at n − f distinct votes (own vote pre-counted)
    cert = None
    for nid, v in votes:
        step, c = prop.handle_vote(infos[0], nid, v)
        assert step.fault_log.is_empty()
        cert = cert or c
    assert isinstance(cert, VidCert)
    assert cert.root == root and cert.total_len == len(payload)
    assert len(cert.votes) >= N - F
    assert verify_cert(cert, infos[0])
    assert prop.certs == 1

    # the commitment codec round-trips; a tampered transcript fails
    assert decode_commitment(encode_commitment(cert)) == cert
    assert decode_commitment(b"plain payload, not a commitment") is None
    tampered = VidCert(cert.era + 1, cert.root, cert.total_len, cert.votes)
    assert not verify_cert(tampered, infos[0])


def test_invalid_disperse_faulted(shared_netinfo):
    """A shard addressed to the wrong index, or carrying a broken proof,
    is the proposer's counted fault — and casts no vote."""
    infos = shared_netinfo(N, 21)
    root, _proofs, _prop, by_dest = _disperse(
        infos, b"misdirected" * 40, era=0)
    msg_for_1 = by_dest[1]
    recv = Disperser(ShardStore())
    # node 2 receives node 1's shard: index mismatch
    step = recv.handle_disperse(infos[2], 0, msg_for_1)
    assert [(f.node_id, f.kind) for f in step.fault_log.faults] == [
        (0, FaultKind.VidInvalidDisperse)
    ]
    assert not step.messages and recv.votes_cast == 0
    assert not recv.store.known(root)


# ---------------------------------------------------------------------------
# Retrieve path faults
# ---------------------------------------------------------------------------


def test_bad_donor_shard_counted_and_recovered(shared_netinfo):
    """A donor shard failing its Merkle proof is counted + faulted, and
    the retrieval still reconstructs from the remaining donors."""
    infos = shared_netinfo(N, 21)
    payload = random.Random(5).randbytes(900)
    root, proofs, _prop, _by_dest = _disperse(infos, payload)
    notes, on_note = _notes()
    svc = RetrieveService(9, ShardStore(), on_note=on_note)
    step = svc.start(root, len(payload), N, F, proposer=0,
                     now=0.0, t_ordered=0.0)
    assert [tm.message for tm in step.messages] == [VidRetrieve(root)]

    good = proofs[1]
    forged = Proof(value=bytes(len(good.value)), index=good.index,
                   root_hash=good.root_hash, path=good.path)
    step = svc.handle_shard(1, VidShard(root, len(payload), forged), 0.1)
    assert svc.shards_bad == 1
    assert [(f.node_id, f.kind) for f in step.fault_log.faults] == [
        (1, FaultKind.VidShardProofInvalid)
    ]
    assert notes and notes[0][0] == "vid_bad_shard"

    # k = 2 honest donors finish the job despite the forgery
    out = []
    for idx in (2, 3):
        step = svc.handle_shard(
            idx, VidShard(root, len(payload), proofs[idx]), 0.2)
        out.extend(step.output)
    (rp,) = out
    assert rp.payload == payload and rp.shards_bad == 1
    assert svc.retrieved == 1 and svc.mismatches == 0
    assert svc.pending_count() == 0

    # a shard for nothing pending is stray, not a fault
    step = svc.handle_shard(2, VidShard(root, len(payload), proofs[2]), 0.3)
    assert svc.stray_shards == 1 and step.fault_log.is_empty()


def test_retrieve_of_unknown_root_refused_loudly(shared_netinfo):
    """A retrieve for a root we never stored is refused LOUDLY — counted
    and noted — but never faulted: a faster peer's early retrieve is
    honest and simply retries."""
    infos = shared_netinfo(N, 21)
    notes, on_note = _notes()
    svc = RetrieveService(0, ShardStore(), on_note=on_note)
    unknown = b"\x07" * 32
    step = svc.handle_retrieve(2, VidRetrieve(unknown), now=0.0)
    assert not step.messages and step.fault_log.is_empty()
    assert svc.refusals == 1 and svc.served == 0
    assert notes == [("vid_refusal", f"peer=2 root={unknown.hex()[:24]}")]

    # once the dispersal lands, the same retrieve serves the shard
    root, proofs, _prop, _by_dest = _disperse(infos, b"late" * 100)
    svc.store.put(root, 400, proofs[0])
    step = svc.handle_retrieve(2, VidRetrieve(root), now=0.0)
    (tm,) = step.messages
    assert isinstance(tm.message, VidShard) and tm.message.root == root
    assert svc.served == 1


def test_serve_quota_drops_counted(shared_netinfo):
    """The per-peer token bucket bounds how hard one peer can milk the
    shard store: over-budget retrieves are dropped + counted, and the
    bucket refills with time."""
    infos = shared_netinfo(N, 21)
    root, proofs, _prop, _by_dest = _disperse(infos, b"q" * 800)
    shard_len = len(proofs[0].value)
    notes, on_note = _notes()
    svc = RetrieveService(
        0, ShardStore(), on_note=on_note,
        serve_bytes_per_s=shard_len, serve_burst_bytes=shard_len)
    svc.store.put(root, 800, proofs[0])
    assert svc.handle_retrieve(2, VidRetrieve(root), now=0.0).messages
    step = svc.handle_retrieve(2, VidRetrieve(root), now=0.0)
    assert not step.messages and svc.quota_drops == 1
    assert any(k == "vid_quota" for k, _ in notes)
    # a different peer has its own bucket; time refills the first
    assert svc.handle_retrieve(3, VidRetrieve(root), now=0.0).messages
    assert svc.handle_retrieve(2, VidRetrieve(root), now=1.5).messages
    assert svc.served == 3


def test_non_codeword_dispersal_attributed_to_proposer():
    """Proof-valid shards whose leaves are NOT an RS codeword reconstruct,
    re-encode, and mismatch the committed root — proposer fault, payload
    resolves to None (deterministically, for every k-subset)."""
    leaves = [bytes([65 + i]) * 20 for i in range(N)]  # not a codeword
    tree = MerkleTree.from_vec(leaves)
    root = tree.root_hash()
    notes, on_note = _notes()
    svc = RetrieveService(9, ShardStore(), on_note=on_note)
    svc.start(root, 10, N, F, proposer=3, now=0.0, t_ordered=0.0)
    svc.handle_shard(0, VidShard(root, 10, tree.proof(0)), 0.1)
    step = svc.handle_shard(1, VidShard(root, 10, tree.proof(1)), 0.2)
    (rp,) = step.output
    assert rp.payload is None
    assert svc.mismatches == 1
    assert [(f.node_id, f.kind) for f in step.fault_log.faults] == [
        (3, FaultKind.VidReconstructMismatch)
    ]
    assert any(k == "vid_mismatch" and "proposer=3" in d for k, d in notes)


def test_retrieval_exhausts_after_max_rounds():
    """No donors at all: retries back off, then the retrieval fails
    loudly with a payload-less RetrievedPayload and a counted failure."""
    notes, on_note = _notes()
    svc = RetrieveService(9, ShardStore(), on_note=on_note,
                          retry_s=0.5, max_rounds=2)
    svc.start(b"\x42" * 32, 64, N, F, proposer=1, now=0.0, t_ordered=0.0)
    step = svc.tick(1.0)  # round 1: retry
    assert [tm.message for tm in step.messages] == [
        VidRetrieve(b"\x42" * 32)]
    assert svc.retries == 1 and not step.output
    step = svc.tick(10.0)  # round 2 = max_rounds: exhausted
    (rp,) = step.output
    assert rp.payload is None and rp.rounds == 2
    assert svc.failures == 1 and svc.pending_count() == 0
    assert any(k == "vid_exhausted" for k, _ in notes)
    assert svc.next_deadline() is None


def test_retrieval_inflight_cap_queues_fifo():
    """Retrieval is background work: only ``max_inflight`` retrievals
    request shards at once; the rest queue FIFO, burn no retry rounds,
    and promote the moment a slot frees."""
    svc = RetrieveService(9, ShardStore(), retry_s=0.5, max_rounds=2,
                          max_inflight=1)
    r1, r2 = b"\x41" * 32, b"\x42" * 32
    step = svc.start(r1, 64, N, F, proposer=1, now=0.0, t_ordered=0.0)
    assert [tm.message for tm in step.messages] == [VidRetrieve(r1)]
    step = svc.start(r2, 64, N, F, proposer=2, now=0.0, t_ordered=0.0)
    assert not step.messages  # queued behind the in-flight window
    assert svc.pending_count() == 2
    assert svc.next_deadline() == 0.5  # only the ACTIVE retrieval ticks
    step = svc.tick(1.0)  # r1 round 1: retried; r2 still mute
    assert [tm.message for tm in step.messages] == [VidRetrieve(r1)]
    step = svc.tick(10.0)  # r1 exhausts → r2 promotes in the same step
    (rp,) = step.output
    assert rp.root == r1 and rp.payload is None
    assert [tm.message for tm in step.messages] == [VidRetrieve(r2)]
    # the queued retrieval burned none of r1's rounds while waiting
    assert svc.pending_count() == 1 and svc.retries == 1


def test_pick_shed_peers_budget_threshold_reuse():
    """The dispersal shed policy: worst congested links first, never
    past the ``f``-peer budget, re-dispersals reuse the root's prior
    set instead of shedding fresh peers."""
    from hbbft_tpu.net.runtime import pick_shed_peers

    backlogs = {0: 0.0, 1: 2.0, 2: 0.6}
    assert pick_shed_peers(backlogs, 0.25, 1) == frozenset({1})
    assert pick_shed_peers(backlogs, 0.25, 2) == frozenset({1, 2})
    # everything under threshold: nothing shed
    assert pick_shed_peers(backlogs, 5.0, 2) == frozenset()
    # a full prior set admits no newcomers even if their link is worse
    # now — the budget bounds DISTINCT peers over the root's lifetime
    assert pick_shed_peers({0: 9.0, 1: 0.0}, 0.25, 1,
                           frozenset({1})) == frozenset({1})
    # room left: extend with the worst eligible newcomer
    assert pick_shed_peers(backlogs, 0.25, 2,
                           frozenset({0})) == frozenset({0, 1})
    # budget 0 (n < 4 has no shed slack) sheds nothing
    assert pick_shed_peers(backlogs, 0.25, 0) == frozenset()


# ---------------------------------------------------------------------------
# ShardStore LRU regression
# ---------------------------------------------------------------------------


def test_shard_store_byte_cap_lru(shared_netinfo):
    infos = shared_netinfo(N, 21)
    entries = []
    for i in range(5):
        root, proofs, _prop, _by_dest = _disperse(
            infos, bytes([i]) * 600, era=i)
        entries.append((root, proofs[0]))
    cost = ShardStore._cost(entries[0][1])
    store = ShardStore(max_bytes=3 * cost)
    for root, proof in entries:
        store.put(root, 600, proof)
    assert store.bytes <= store.max_bytes
    assert store.evictions == 2 and len(store) == 3
    assert not store.known(entries[0][0]) and not store.known(entries[1][0])
    assert store.known(entries[4][0])

    # re-put refreshes recency without double-charging...
    before = store.bytes
    store.put(entries[2][0], 600, entries[2][1])
    assert store.bytes == before
    # ...so the NEXT eviction takes the now-oldest root 3, not root 2
    root5, proofs5, _p, _b = _disperse(infos, b"\xee" * 600, era=9)
    store.put(root5, 600, proofs5[0])
    assert store.evictions == 3
    assert store.known(entries[2][0]) and not store.known(entries[3][0])

    # a tiny cap still keeps the newest root (never evicts to empty)
    tiny = ShardStore(max_bytes=1)
    tiny.put(entries[0][0], 600, entries[0][1])
    assert len(tiny) == 1 and tiny.known(entries[0][0])


# ---------------------------------------------------------------------------
# Socket smoke: VID cluster end to end (tier 1, sub-10s target)
# ---------------------------------------------------------------------------

SMOKE_TIMEOUT_S = 90


def _vid_cluster_run(txs, **cfg_kwargs):
    """Run a 4-node VID-mode LocalCluster until ``txs`` commit; return
    (digest prefix, summed vid status counters)."""
    from hbbft_tpu.net.cluster import ClusterConfig, LocalCluster

    async def scenario():
        cfg = ClusterConfig(n=4, seed=47, batch_size=6, vid=True,
                            **cfg_kwargs)
        cluster = LocalCluster(cfg)
        await cluster.start()
        try:
            client = await cluster.client(0)
            for tx in txs:
                assert await client.submit(tx) == 0
            await client.wait_committed_many(txs, timeout_s=60)
            await cluster.wait_epochs(2, timeout_s=45)
            prefix = cluster.common_digest_prefix()
            assert len(prefix) >= 2
            totals = {}
            for rt in cluster.runtimes:
                assert rt.decode_failures == 0
                doc = rt.status_doc()["vid"]
                assert doc is not None
                for k, v in doc.items():
                    if isinstance(v, int):
                        totals[k] = totals.get(k, 0) + v
            return prefix, totals
        finally:
            await cluster.stop()

    return asyncio.run(asyncio.wait_for(scenario(), SMOKE_TIMEOUT_S))


def test_vid_cluster_socket_smoke():
    """4 real sockets in VID mode: dispersals complete, commitments
    order, payloads retrieve, clients see their transactions — with zero
    Byzantine evidence on an honest network."""
    txs = [b"vid-smoke-%02d" % i for i in range(8)]
    prefix, totals = _vid_cluster_run(txs)
    assert totals["disperse"] > 0 and totals["cert"] > 0
    assert totals["retrieved"] > 0 and totals["shard_served"] > 0
    assert totals["bad_shard"] == 0 and totals["mismatch"] == 0
    assert totals["failure"] == 0
    assert len(prefix) >= 2


def test_vid_ingress_worker_cluster_consistency():
    """Satellite of the ingress-worker enablement: the worker-thread
    decode path must be invisible in VID mode too — every node on ONE
    byte-identical ledger (common_digest_prefix's internal cross-node
    assert is the claim; cross-RUN digests legitimately differ because a
    cert's vote subset is timing-dependent) with the same healthy VID
    counters as the plain smoke."""
    txs = [b"vid-worker-%02d" % i for i in range(8)]
    prefix, totals = _vid_cluster_run(txs, ingress_workers=True)
    assert len(prefix) >= 2
    assert totals["cert"] > 0 and totals["retrieved"] > 0
    assert totals["bad_shard"] == 0 and totals["mismatch"] == 0
    assert totals["failure"] == 0

"""hblint: the static-analysis suite itself, plus the tier-1 repo gate.

Four layers:

- fixture tests — every checker fires on a minimal violating snippet
  (``tests/lint_fixtures/*_bad.py``) and stays quiet on the corrected
  version (``*_good.py``);
- framework semantics — suppression comments (line / file / comment-line
  above), baseline fingerprints (content-anchored: stable under line
  drift, invalidated by editing the anchored line), JSON reporter schema;
- registry invariants — the wire-completeness runtime cross-check over a
  synthetic registry;
- the tier-1 gate — ``python -m hbbft_tpu.lint --json`` over the repo via
  the MODULE ENTRY POINT (so the CLI path stays exercised) must be clean
  with ≤ 10 baselined findings.
"""

import json
import os
import subprocess
import sys
from dataclasses import dataclass

from hbbft_tpu.lint.core import (
    ModuleSource,
    render_baseline,
    run_lint,
)
from hbbft_tpu.lint.asyncio_hazard import AsyncioHazardChecker
from hbbft_tpu.lint.determinism import DeterminismChecker
from hbbft_tpu.lint.fault_accounting import FaultAccountingChecker
from hbbft_tpu.lint.metric_convention import check_metrics
from hbbft_tpu.lint.reporters import render_json
from hbbft_tpu.lint.wire_completeness import WireCompletenessChecker

FIXTURES = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "lint_fixtures")
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def fired(checker, fixture_name):
    mod = ModuleSource(FIXTURES, fixture_name)
    assert mod.parse_error is None
    return [f.rule for f in checker.check_module(mod)]


# ---------------------------------------------------------------------------
# per-checker fixtures: bad fires, good is quiet


def test_determinism_fixture():
    rules = fired(DeterminismChecker(), "det_bad.py")
    assert "det-wall-clock" in rules
    assert rules.count("det-unseeded-random") == 2  # random.random + urandom
    assert rules.count("det-set-iteration") == 2    # loop + genexp sink
    assert fired(DeterminismChecker(), "det_good.py") == []


def test_asyncio_fixture():
    rules = fired(AsyncioHazardChecker(), "async_bad.py")
    assert set(rules) == {
        "async-unawaited-coroutine", "async-fire-and-forget-task",
        "async-blocking-call", "async-lock-across-await",
    }
    assert fired(AsyncioHazardChecker(), "async_good.py") == []


def test_pump_inline_crypto_fixture():
    # the scheduler module must stay crypto-free: direct pairing/share
    # calls bypass the batched executor path the pump exists to provide
    rules = fired(AsyncioHazardChecker(),
                  "hbbft_tpu/net/scheduler_bad.py")
    assert rules.count("pump-inline-crypto") == 3
    assert fired(AsyncioHazardChecker(),
                 "hbbft_tpu/net/scheduler_good.py") == []
    # and the rule scopes to scheduler modules only: the same calls in a
    # generic net module are not its business (async rules still apply)
    assert "pump-inline-crypto" not in fired(
        AsyncioHazardChecker(), "async_bad.py")


def test_fault_accounting_fixture():
    # the drop rule self-scopes to hbbft_tpu/net/ paths, so the fault
    # fixtures live under that relative path inside the fixture root
    rules = fired(FaultAccountingChecker(), "hbbft_tpu/net/fault_bad.py")
    assert set(rules) == {"fault-except-pass", "fault-swallowed-drop"}
    assert fired(FaultAccountingChecker(),
                 "hbbft_tpu/net/fault_good.py") == []


def test_bounded_ingress_fixture():
    from hbbft_tpu.lint.bounded_ingress import BoundedIngressChecker

    rules = fired(BoundedIngressChecker(), "ingress_bad.py")
    # both growth sites fire: the per-sender setdefault().append and
    # the flat log.append
    assert rules == ["bounded-ingress", "bounded-ingress"]
    # capped + counted (or sender-identity-valued) growth stays quiet
    assert fired(BoundedIngressChecker(), "ingress_good.py") == []


def test_wire_ast_fixture():
    chk = WireCompletenessChecker()
    bad = ModuleSource(FIXTURES, "wire_bad.py")
    rules = [f.rule for f in chk.ast_unregistered(bad, registered=set())]
    assert rules == ["wire-unregistered"]
    good = ModuleSource(FIXTURES, "wire_good.py")
    assert chk.ast_unregistered(good, registered={"PlainMsg"}) == []


def test_metric_convention_fixture():
    bad_root = os.path.join(FIXTURES, "metric_bad")
    problems, n, _ = check_metrics(bad_root, check_faults=False)
    msgs = [m for m, _p, _l in problems]
    assert n == 1
    assert any("violates the naming convention" in m for m in msgs)
    assert any("not documented" in m for m in msgs)
    good_root = os.path.join(FIXTURES, "metric_good")
    problems, n, _ = check_metrics(good_root, check_faults=False)
    assert problems == [] and n == 1


# ---------------------------------------------------------------------------
# wire registry invariants over a synthetic registry


def test_wire_registry_invariants():
    @dataclass(frozen=True)
    class GoodM:
        x: int

    @dataclass
    class MutableM:
        x: int

    class UnhashableM:
        __hash__ = None

    chk = WireCompletenessChecker()
    tags = {
        GoodM: (0x01, None),
        MutableM: (0x01, None),      # duplicate tag + not frozen
        UnhashableM: (0x02, None),   # decoder missing + unhashable
    }
    decoders = {0x01: None, 0x07: None}  # 0x07: decoder without encoder
    out = chk.registry_findings(
        tags, decoders, locate=lambda cls: ("x.py", 1, ""))
    rules = sorted(f.rule for f in out)
    assert rules.count("wire-duplicate-tag") == 1
    assert rules.count("wire-missing-codec") == 2  # 0x02 enc-only, 0x07 dec-only
    # MutableM too: dataclass(eq=True, frozen=False) sets __hash__ = None
    assert rules.count("wire-not-hashable") == 2
    # MutableM and UnhashableM (not a dataclass at all) both lack frozen
    assert rules.count("wire-not-frozen") == 2
    # an all-good registry is silent
    assert chk.registry_findings(
        {GoodM: (0x01, None)}, {0x01: None},
        locate=lambda cls: ("x.py", 1, "")) == []


def test_wire_registry_real_repo_is_clean():
    """The live registry: unique tags, codec pairs, frozen+hashable."""
    from hbbft_tpu.protocols import wire

    wire.ensure_registered()
    chk = WireCompletenessChecker()
    out = chk.registry_findings(
        dict(wire._MSG_TAGS), dict(wire._MSG_DECODERS),
        locate=lambda cls: ("x.py", 0, ""))
    assert out == [], [f.message for f in out]


# ---------------------------------------------------------------------------
# framework semantics on a synthetic repo tree


def _write(tmp_path, rel, text):
    path = tmp_path / rel
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(text)
    return path


_VIOLATION = "import time\n\ndef f():\n    return time.time()\n"


def _lint_tmp(tmp_path, **kwargs):
    kwargs.setdefault("checkers", [DeterminismChecker()])
    kwargs.setdefault("baseline_path", None)
    return run_lint(root=str(tmp_path), paths=["hbbft_tpu"], **kwargs)


def test_scope_and_basic_finding(tmp_path):
    _write(tmp_path, "hbbft_tpu/protocols/x.py", _VIOLATION)
    # same violation outside the determinism scope: not flagged
    _write(tmp_path, "hbbft_tpu/net/y.py", _VIOLATION)
    result = _lint_tmp(tmp_path)
    assert [f.path for f in result.findings] == ["hbbft_tpu/protocols/x.py"]
    assert result.findings[0].rule == "det-wall-clock"
    assert result.findings[0].line == 4


def test_chaos_is_in_determinism_and_drop_scopes(tmp_path):
    """chaos/ shaping decisions must come from the seeded RNG (campaign
    replay depends on it), and its drop paths must be accounted."""
    assert "hbbft_tpu/chaos/" in DeterminismChecker.scope
    assert "hbbft_tpu/chaos/" in FaultAccountingChecker.DROP_SCOPE
    _write(tmp_path, "hbbft_tpu/chaos/z.py", _VIOLATION)
    result = _lint_tmp(tmp_path)
    assert [f.rule for f in result.findings] == ["det-wall-clock"]
    _write(tmp_path, "hbbft_tpu/chaos/drop.py",
           "def f(x):\n    try:\n        return x()\n"
           "    except ValueError:\n        return None\n")
    result = run_lint(root=str(tmp_path), paths=["hbbft_tpu/chaos"],
                      checkers=[FaultAccountingChecker()],
                      baseline_path=None)
    assert [f.rule for f in result.findings] == ["fault-swallowed-drop"]


def test_trace_is_in_determinism_scope_and_critpath_in_drop_scope(
        tmp_path):
    """obs/trace.py joined the determinism scope with causal tracing
    (trace ids ride the wire; a clock read there forks identical-seed
    critpath reports), and obs/critpath.py rides the obs/ drop scope
    (unmatched pairs must be counted, never silently discarded)."""
    assert "hbbft_tpu/obs/trace.py" in DeterminismChecker.scope
    assert any("hbbft_tpu/obs/critpath.py".startswith(p)
               for p in FaultAccountingChecker.DROP_SCOPE)
    _write(tmp_path, "hbbft_tpu/obs/trace.py", _VIOLATION)
    result = _lint_tmp(tmp_path)
    assert [f.rule for f in result.findings] == ["det-wall-clock"]
    # the rest of obs/ stays OUT of the determinism scope (runtime
    # journals legitimately stamp wall-clock time)
    _write(tmp_path, "hbbft_tpu/obs/other.py", _VIOLATION)
    result = run_lint(root=str(tmp_path),
                      paths=["hbbft_tpu/obs/other.py"],
                      checkers=[DeterminismChecker()],
                      baseline_path=None)
    assert result.findings == []


def test_pump_and_trace_metric_prefixes_pass_convention():
    from hbbft_tpu.lint.metric_convention import NAME_CONVENTION

    assert NAME_CONVENTION.match("hbbft_pump_segment_seconds")
    assert NAME_CONVENTION.match("hbbft_trace_records_total")
    assert not NAME_CONVENTION.match("hbbft_bogus_prefix_total")


def test_suppression_same_line(tmp_path):
    _write(tmp_path, "hbbft_tpu/protocols/x.py",
           "import time\n\ndef f():\n"
           "    return time.time()  # hblint: disable=det-wall-clock (why)\n")
    result = _lint_tmp(tmp_path)
    assert result.findings == [] and result.suppressed == 1


def test_suppression_comment_line_above(tmp_path):
    _write(tmp_path, "hbbft_tpu/protocols/x.py",
           "import time\n\ndef f():\n"
           "    # hblint: disable=det-wall-clock (justification)\n"
           "    return time.time()\n")
    result = _lint_tmp(tmp_path)
    assert result.findings == [] and result.suppressed == 1


def test_suppression_file_level_and_all(tmp_path):
    _write(tmp_path, "hbbft_tpu/protocols/x.py",
           "# hblint: disable-file=det-wall-clock\n" + _VIOLATION)
    assert _lint_tmp(tmp_path).findings == []
    _write(tmp_path, "hbbft_tpu/protocols/x.py",
           "# hblint: disable-file=all\n" + _VIOLATION)
    assert _lint_tmp(tmp_path).findings == []


def test_suppression_justification_words_are_not_rules(tmp_path):
    """An unparenthesized justification after the rule list must not leak
    tokens (like the word 'all') into the suppression set."""
    _write(tmp_path, "hbbft_tpu/protocols/x.py",
           "import time\n\ndef f():\n"
           "    return time.time()  "
           "# hblint: disable=det-set-iteration all timers are benign\n")
    result = _lint_tmp(tmp_path)
    assert [f.rule for f in result.findings] == ["det-wall-clock"]


def test_suppression_wrong_rule_does_not_apply(tmp_path):
    _write(tmp_path, "hbbft_tpu/protocols/x.py",
           "import time\n\ndef f():\n"
           "    return time.time()  # hblint: disable=det-set-iteration\n")
    result = _lint_tmp(tmp_path)
    assert [f.rule for f in result.findings] == ["det-wall-clock"]


def test_baseline_grandfathers_and_survives_line_drift(tmp_path):
    _write(tmp_path, "hbbft_tpu/protocols/x.py", _VIOLATION)
    first = _lint_tmp(tmp_path)
    assert len(first.findings) == 1
    baseline = tmp_path / "baseline.txt"
    baseline.write_text(render_baseline(first.findings))
    result = _lint_tmp(tmp_path, baseline_path=str(baseline))
    assert result.findings == []
    assert [f.rule for f in result.baselined] == ["det-wall-clock"]
    assert result.stale_baseline == 0
    # unrelated edits shift the line: the content fingerprint still holds
    _write(tmp_path, "hbbft_tpu/protocols/x.py",
           "# a new leading comment\n# another\n" + _VIOLATION)
    result = _lint_tmp(tmp_path, baseline_path=str(baseline))
    assert result.findings == [] and len(result.baselined) == 1
    # editing the anchored line itself invalidates the entry, on purpose
    _write(tmp_path, "hbbft_tpu/protocols/x.py",
           _VIOLATION.replace("return time.time()",
                              "return 1 + time.time()"))
    result = _lint_tmp(tmp_path, baseline_path=str(baseline))
    assert len(result.findings) == 1 and result.stale_baseline == 1


def test_changed_only_includes_untracked_files(tmp_path):
    """--changed-only is the pre-commit path: a brand-new (untracked)
    violating module must still be scanned."""
    git = lambda *a: subprocess.run(  # noqa: E731
        ["git", *a], cwd=tmp_path, capture_output=True, text=True,
        check=True,
        env=dict(os.environ, GIT_AUTHOR_NAME="t", GIT_AUTHOR_EMAIL="t@t",
                 GIT_COMMITTER_NAME="t", GIT_COMMITTER_EMAIL="t@t"),
    )
    git("init", "-q")
    _write(tmp_path, "hbbft_tpu/protocols/clean.py", "X = 1\n")
    git("add", "-A")
    git("commit", "-qm", "seed")
    _write(tmp_path, "hbbft_tpu/protocols/fresh.py", _VIOLATION)  # untracked
    result = _lint_tmp(tmp_path, changed_only="HEAD")
    assert [f.path for f in result.findings] == [
        "hbbft_tpu/protocols/fresh.py"]


def test_write_baseline_refuses_restricted_scan():
    proc = _run_cli("hbbft_tpu/obs", "--write-baseline")
    assert proc.returncode == 2
    assert "full scan" in proc.stderr
    proc = _run_cli("--changed-only", "HEAD", "--write-baseline")
    assert proc.returncode == 2


def test_syntax_error_is_reported_not_fatal(tmp_path):
    _write(tmp_path, "hbbft_tpu/protocols/x.py", "def broken(:\n")
    result = _lint_tmp(tmp_path)
    assert [f.rule for f in result.findings] == ["syntax-error"]


def test_json_reporter_schema(tmp_path):
    _write(tmp_path, "hbbft_tpu/protocols/x.py", _VIOLATION)
    doc = json.loads(render_json(_lint_tmp(tmp_path)))
    assert doc["version"] == 1 and doc["tool"] == "hblint"
    assert set(doc) >= {"checkers", "findings", "baselined", "summary"}
    (f,) = doc["findings"]
    assert set(f) == {"checker", "rule", "path", "line", "message",
                      "fingerprint"}
    assert f["rule"] == "det-wall-clock"
    s = doc["summary"]
    assert set(s) >= {"findings", "baselined", "suppressed",
                      "files_scanned", "stale_baseline", "clean"}
    assert s["findings"] == 1 and s["clean"] is False


# ---------------------------------------------------------------------------
# the tier-1 repo gate, via the module entry point


def _run_cli(*args, timeout=240):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    return subprocess.run(
        [sys.executable, "-m", "hbbft_tpu.lint", *args],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=timeout,
    )


def test_lint_repo_clean():
    """Zero non-baselined findings over the repo, ≤ 10 grandfathered."""
    proc = _run_cli("--json")
    assert proc.returncode == 0, proc.stdout + proc.stderr
    doc = json.loads(proc.stdout)
    assert doc["findings"] == [], doc["findings"]
    assert doc["summary"]["clean"] is True
    assert doc["summary"]["baselined"] <= 10
    # all six checkers ran
    assert set(doc["checkers"]) == {
        "determinism", "asyncio-hazard", "wire-completeness",
        "fault-accounting", "metric-convention", "bounded-ingress",
    }


def test_lint_cli_list_rules():
    proc = _run_cli("--list-rules")
    assert proc.returncode == 0
    for rule in ("det-wall-clock", "async-fire-and-forget-task",
                 "wire-not-hashable", "fault-except-pass",
                 "metric-convention", "bounded-ingress"):
        assert rule in proc.stdout


def test_lint_cli_changed_only():
    """--changed-only HEAD: the fast pre-commit path stays wired."""
    proc = _run_cli("--json", "--changed-only", "HEAD")
    assert proc.returncode == 0, proc.stdout + proc.stderr
    doc = json.loads(proc.stdout)
    assert doc["findings"] == []

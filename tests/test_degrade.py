"""Guard-driven adaptive degradation (`net/degrade.py`).

The controller contract: pressure read as counter deltas per window,
one ladder level per window up under sustained pressure, hysteresis in
the middle band, ``clear_windows`` consecutive quiet windows per level
down, and every lever a pure function of (level, attach-time base) so
recovery restores the EXACT configured values.
"""

import asyncio
import random

import pytest

from hbbft_tpu.net.degrade import DegradationController, attach_runtime
from hbbft_tpu.obs.metrics import Registry


def _controller(**kwargs):
    clock = [0.0]
    count = [0.0]
    applied = []
    defaults = dict(
        sources=[("src", lambda: count[0])],
        apply_level=applied.append,
        registry=Registry(),
        window_s=1.0,
        engage_per_s=5.0,
        clear_per_s=1.0,
        clear_windows=2,
        max_level=2,
        clock=lambda: clock[0],
    )
    defaults.update(kwargs)
    return DegradationController(**defaults), clock, count, applied


def test_shrink_halves_per_level_with_floor():
    shrink = DegradationController.shrink
    assert shrink(64, 0, 8) == 64
    assert shrink(64, 1, 8) == 32
    assert shrink(64, 3, 8) == 8
    assert shrink(64, 6, 8) == 8    # floored, never 1 or 0
    assert shrink(6, 1, 8) == 8     # base below floor: floor wins


def test_ladder_engages_holds_and_clears_with_hysteresis():
    ctl, clock, count, applied = _controller()

    # sub-window tick is a no-op
    clock[0] = 0.5
    count[0] = 100.0
    ctl.tick()
    assert ctl.level == 0 and applied == []

    # sustained pressure: one level per window, capped at max_level
    for i, t in enumerate((1.0, 2.0, 3.0)):
        clock[0] = t
        count[0] += 10.0
        ctl.tick()
    assert ctl.level == 2 and applied == [1, 2]  # third window capped

    # middle band: hold the level AND reset the clean-window count
    clock[0] = 4.0
    count[0] += 3.0  # 3/s: above clear (1/s), below engage (5/s)
    ctl.tick()
    assert ctl.level == 2

    # two clean windows per downward step
    clock[0] = 5.0
    ctl.tick()
    assert ctl.level == 2  # one clean window is not enough
    clock[0] = 6.0
    ctl.tick()
    assert ctl.level == 1
    # hysteresis: the middle band also restarts the count mid-descent
    clock[0] = 7.0
    count[0] += 3.0
    ctl.tick()
    clock[0] = 8.0
    ctl.tick()
    assert ctl.level == 1
    clock[0] = 9.0
    ctl.tick()
    assert ctl.level == 0
    assert applied == [1, 2, 1, 0]
    assert ctl._c_transitions.value(direction="up") == 2
    assert ctl._c_transitions.value(direction="down") == 2

    d = ctl.as_dict()
    assert d["level"] == 0 and d["active"] is False
    assert d["engage_per_s"] == 5.0 and d["max_level"] == 2


def test_rebound_counter_reset_not_negative_pressure():
    """A source counter restarting from zero (runtime re-bind) must not
    produce a negative delta that masks real pressure from the other
    sources."""
    clock = [0.0]
    a, b = [1000.0], [0.0]
    ctl = DegradationController(
        sources=[("a", lambda: a[0]), ("b", lambda: b[0])],
        apply_level=lambda lvl: None, registry=Registry(),
        window_s=1.0, engage_per_s=5.0, clock=lambda: clock[0])
    clock[0] = 1.0
    a[0] = 0.0       # re-bound: would read as -1000/s
    b[0] = 10.0      # real pressure: 10/s
    ctl.tick()
    assert ctl.level == 1


def test_attach_runtime_levers_shrink_and_restore_exactly():
    """attach_runtime wires the real levers: batch size and mempool
    ceilings halve per level (floored), and level 0 restores the exact
    configured bases; /status carries the controller state."""
    from hbbft_tpu.net.cluster import (
        ClusterConfig, build_runtime, generate_infos,
    )

    cfg = ClusterConfig(n=4, seed=21, batch_size=32,
                        max_tx_bytes=64 * 1024)
    rt = build_runtime(cfg, generate_infos(cfg), 0)
    try:
        ctl = rt.degrade
        assert ctl is not None
        algo = rt.sq.algo
        base_batch = algo.batch_size
        base_cap = rt.mempool.capacity
        base_pending = rt.mempool.max_pending_bytes
        assert base_batch == 32

        ctl._set_level(1, "test")
        assert algo.batch_size == 16
        assert rt.mempool.capacity == max(64, base_cap >> 1)
        assert rt.mempool.max_pending_bytes == base_pending >> 1
        assert ctl.batch_size() == 16

        ctl._set_level(3, "test")
        assert algo.batch_size == 8  # min_batch floor

        ctl._set_level(0, "test")
        assert algo.batch_size == base_batch
        assert rt.mempool.capacity == base_cap
        assert rt.mempool.max_pending_bytes == base_pending

        doc = rt.status_doc()
        assert doc["degraded"]["level"] == 0
        assert doc["degraded"]["batch_size"] == base_batch
    finally:
        rt.transport.registry = None  # nothing started; nothing to stop


def test_degrade_opt_out_and_custom_knobs():
    from hbbft_tpu.net.cluster import (
        ClusterConfig, build_runtime, generate_infos,
    )

    cfg = ClusterConfig(n=4, seed=22)
    infos = generate_infos(cfg)
    rt_off = build_runtime(cfg, infos, 0, degrade=False)
    assert rt_off.degrade is None
    assert rt_off.status_doc()["degraded"] is None

    rt_knobs = build_runtime(
        cfg, infos, 1,
        degrade_kwargs=dict(engage_per_s=99.0, max_level=1))
    assert rt_knobs.degrade.engage_per_s == 99.0
    assert rt_knobs.degrade.max_level == 1


def test_grow_doubles_per_boost_with_ceiling():
    grow = DegradationController.grow
    assert grow(32, 0, 256) == 32
    assert grow(32, 1, 256) == 64
    assert grow(32, 3, 256) == 256
    assert grow(32, 5, 256) == 256   # capped, never past the ceiling
    assert grow(300, 1, 256) == 256  # base above ceiling: ceiling wins


def _raise_controller(**kwargs):
    """A controller with the raise arm wired to scripted headroom and
    demand signals (clean guard counters unless the test adds count)."""
    hr = [0.9]
    dem = [5.0]
    defaults = dict(
        max_boost=2, raise_windows=3, raise_headroom=0.6,
        headroom_fn=lambda: hr[0], demand_fn=lambda: dem[0],
    )
    defaults.update(kwargs)
    ctl, clock, count, applied = _controller(**defaults)
    return ctl, clock, count, applied, hr, dem


def test_raise_engages_only_after_raise_windows():
    ctl, clock, count, applied, hr, dem = _raise_controller()

    # two clean slack windows: not enough (raise_windows=3)
    for t in (1.0, 2.0):
        clock[0] = t
        ctl.tick()
        assert ctl.boost == 0 and applied == []
    clock[0] = 3.0
    ctl.tick()
    assert ctl.boost == 1 and applied == [-1]

    # the slack count restarts per boost level: three more windows
    for t in (4.0, 5.0):
        clock[0] = t
        ctl.tick()
        assert ctl.boost == 1
    clock[0] = 6.0
    ctl.tick()
    assert ctl.boost == 2 and applied == [-1, -2]

    # capped at max_boost: further slack windows change nothing
    for t in (7.0, 8.0, 9.0, 10.0):
        clock[0] = t
        ctl.tick()
    assert ctl.boost == 2 and applied == [-1, -2]
    assert ctl._c_ctrl_transitions.value(direction="raise") == 2

    d = ctl.as_dict()
    assert d["boost"] == 2 and d["max_boost"] == 2
    assert d["headroom"] == 0.9


def test_raise_needs_real_headroom_and_real_demand():
    ctl, clock, count, applied, hr, dem = _raise_controller(
        raise_windows=2)

    # headroom below the bar: strain, never slack
    hr[0] = 0.3
    for t in (1.0, 2.0, 3.0):
        clock[0] = t
        ctl.tick()
    assert ctl.boost == 0 and applied == []

    # headroom fine but no demand: quiet, never slack (an idle node
    # has nothing to absorb)
    hr[0] = 0.9
    dem[0] = 0.0
    for t in (4.0, 5.0, 6.0):
        clock[0] = t
        ctl.tick()
    assert ctl.boost == 0 and applied == []

    # a None headroom (perf plane not yet primed) is "no evidence of
    # slack", not slack
    dem[0] = 5.0
    hr[0] = None
    for t in (7.0, 8.0, 9.0):
        clock[0] = t
        ctl.tick()
    assert ctl.boost == 0 and applied == []

    # both real: raise after raise_windows
    hr[0] = 0.9
    clock[0] = 10.0
    ctl.tick()
    clock[0] = 11.0
    ctl.tick()
    assert ctl.boost == 1 and applied == [-1]


def test_raise_arm_disabled_without_headroom_source_or_max_boost():
    # max_boost left at its 0 default: headroom/demand alone never raise
    hr = [0.95]
    ctl, clock, count, applied = _controller(
        headroom_fn=lambda: hr[0], demand_fn=lambda: 50.0,
        raise_windows=1)
    for t in (1.0, 2.0, 3.0, 4.0):
        clock[0] = t
        ctl.tick()
    assert ctl.boost == 0 and applied == []

    # max_boost set but no headroom source: a controller without a perf
    # plane behind it must never infer slack
    ctl, clock, count, applied = _controller(
        max_boost=2, raise_windows=1, demand_fn=lambda: 50.0)
    for t in (1.0, 2.0, 3.0, 4.0):
        clock[0] = t
        ctl.tick()
    assert ctl.boost == 0 and applied == []


def test_abuse_instantly_preempts_raised_level():
    """PR-15 abuse-only rule stands: one abusive window restores the
    exact bases FIRST, then the degradation ladder engages — the raised
    state never coexists with pressure."""
    ctl, clock, count, applied, hr, dem = _raise_controller(
        raise_windows=2)
    clock[0] = 1.0
    ctl.tick()
    clock[0] = 2.0
    ctl.tick()
    assert ctl.boost == 1 and applied == [-1]

    clock[0] = 3.0
    count[0] += 10.0  # 10/s >= engage 5/s
    ctl.tick()
    assert ctl.boost == 0 and ctl.level == 1
    assert applied == [-1, 0, 1]  # restore-to-base precedes the ladder
    assert ctl._c_ctrl_transitions.value(direction="restore") == 1


def test_middle_band_pressure_forfeits_boost_without_degrading():
    ctl, clock, count, applied, hr, dem = _raise_controller(
        raise_windows=2)
    clock[0] = 1.0
    ctl.tick()
    clock[0] = 2.0
    ctl.tick()
    assert ctl.boost == 1

    clock[0] = 3.0
    count[0] += 3.0  # 3/s: above clear (1/s), below engage (5/s)
    ctl.tick()
    assert ctl.boost == 0 and ctl.level == 0
    assert applied == [-1, 0]
    assert ctl._c_ctrl_transitions.value(direction="restore") == 1


def test_quiet_windows_restore_exact_bases_in_one_step():
    ctl, clock, count, applied, hr, dem = _raise_controller(
        raise_windows=2)
    for t in (1.0, 2.0, 3.0, 4.0):
        clock[0] = t
        ctl.tick()
    assert ctl.boost == 2 and applied == [-1, -2]

    dem[0] = 0.0  # demand gone
    clock[0] = 5.0
    ctl.tick()
    assert ctl.boost == 2  # one quiet window is not enough
    clock[0] = 6.0
    ctl.tick()
    # straight to the bases (restore), not a one-level step down
    assert ctl.boost == 0 and applied == [-1, -2, 0]
    assert ctl._c_ctrl_transitions.value(direction="restore") == 1


def test_strain_steps_boost_down_one_level_at_a_time():
    ctl, clock, count, applied, hr, dem = _raise_controller(
        raise_windows=2)
    for t in (1.0, 2.0, 3.0, 4.0):
        clock[0] = t
        ctl.tick()
    assert ctl.boost == 2

    hr[0] = 0.2  # demand stays, headroom gone: strain
    clock[0] = 5.0
    ctl.tick()
    assert ctl.boost == 2
    clock[0] = 6.0
    ctl.tick()
    assert ctl.boost == 1 and applied == [-1, -2, -1]
    clock[0] = 7.0
    ctl.tick()
    clock[0] = 8.0
    ctl.tick()
    assert ctl.boost == 0 and applied == [-1, -2, -1, 0]
    assert ctl._c_ctrl_transitions.value(direction="lower") == 2


def test_attach_runtime_raise_levers_grow_and_restore_exactly():
    """attach_runtime's raise wiring: negative effective levels double
    the real levers toward the attach-time ceilings (default 8x), the
    slack signal is the perf plane's measured headroom, and boost 0
    restores the exact configured bases."""
    from hbbft_tpu.net.cluster import (
        ClusterConfig, build_runtime, generate_infos,
    )

    cfg = ClusterConfig(n=4, seed=24, batch_size=32,
                        max_tx_bytes=64 * 1024)
    rt = build_runtime(cfg, generate_infos(cfg), 0,
                       degrade_kwargs=dict(max_boost=2))
    try:
        ctl = rt.degrade
        assert ctl.max_boost == 2
        assert ctl.headroom_fn == rt.perf.headroom
        algo = rt.sq.algo
        base_batch = algo.batch_size
        base_cap = rt.mempool.capacity
        base_pending = rt.mempool.max_pending_bytes

        ctl._set_boost(1, "raise", "test")
        assert algo.batch_size == base_batch * 2
        assert rt.mempool.capacity == base_cap * 2
        assert rt.mempool.max_pending_bytes == base_pending * 2
        ctl._set_boost(2, "raise", "test")
        assert algo.batch_size == base_batch * 4

        # the default ceiling is 8x the bases: boosts past it are capped
        ctl.max_boost = 5
        ctl._set_boost(5, "raise", "test")
        assert algo.batch_size == base_batch * 8
        assert rt.mempool.capacity == base_cap * 8

        ctl._set_boost(0, "restore", "test")
        assert algo.batch_size == base_batch
        assert rt.mempool.capacity == base_cap
        assert rt.mempool.max_pending_bytes == base_pending

        doc = rt.status_doc()
        assert doc["degraded"]["boost"] == 0
        assert doc["degraded"]["base_batch_size"] == base_batch
        assert doc["degraded"]["max_boost"] == 5
    finally:
        rt.transport.registry = None  # nothing started; nothing to stop


@pytest.mark.slow
def test_flood_shrinks_batch_then_restores_e2e():
    """The acceptance drill: a sustained garbage flood from a
    compromised validator identity drives the victim's ladder up
    (batch size shrinks), the cluster keeps committing throughout, and
    once the flood stops the ladder walks back to level 0 with the
    exact configured batch size restored."""
    from hbbft_tpu.net.cluster import (
        ClusterConfig, LocalCluster, node_secret_key,
    )
    from hbbft_tpu.sim.adversary import GarbageStreamAdversary

    async def scenario():
        cfg = ClusterConfig(
            n=4, seed=31, batch_size=16, max_tx_bytes=64 * 1024,
            # tight guard budgets so the flood registers as pressure
            # within a short run (the campaign's flood-cell idiom)
            ingress_bytes_per_s=64 * 1024,
            ingress_burst_bytes=32 * 1024,
            ingress_decode_strikes=40,
        )
        cluster = LocalCluster(cfg, degrade_kwargs=dict(
            window_s=0.3, engage_per_s=20.0,
            clear_per_s=2.0, clear_windows=2))
        await cluster.start()
        injector = None
        try:
            client = await cluster.client(1)
            await client.submit(b"tx-before-flood")
            await client.wait_committed(b"tx-before-flood", timeout_s=60)

            victim = cluster.runtimes[0]
            base_batch = victim.sq.algo.batch_size
            assert base_batch == 16 and victim.degrade.level == 0

            # compromised validator: correct node id AND its real key,
            # so the flood passes the auth challenge and the pressure
            # drill runs against the post-auth guard layer
            injector = GarbageStreamAdversary(
                seed=5, budget_frames=200_000, frame_bytes=512,
                secret_key=node_secret_key(cfg, cfg.n - 1))
            task = asyncio.ensure_future(injector.run(
                cluster.addrs[0], cfg.cluster_id,
                identity=cfg.n - 1, duration_s=30.0))

            for _ in range(600):  # ≤ 15 s for the ladder to engage
                if victim.degrade.level > 0:
                    break
                await asyncio.sleep(0.025)
            assert victim.degrade.level > 0, "flood never engaged"
            assert victim.sq.algo.batch_size < base_batch

            # degraded, not dead: commits continue under flood
            await client.submit(b"tx-during-flood")
            await client.wait_committed(b"tx-during-flood", timeout_s=60)

            injector.budget_frames = 0  # stop the flood
            await asyncio.wait_for(task, 10.0)

            for _ in range(800):  # ≤ 20 s to walk back down
                if victim.degrade.level == 0:
                    break
                await asyncio.sleep(0.025)
            assert victim.degrade.level == 0, "ladder never cleared"
            assert victim.sq.algo.batch_size == base_batch

            up = victim.degrade._c_transitions.value(direction="up")
            down = victim.degrade._c_transitions.value(direction="down")
            assert up >= 1 and up == down

            await client.submit(b"tx-after-recovery")
            await client.wait_committed(b"tx-after-recovery",
                                        timeout_s=60)
        finally:
            if injector is not None:
                injector.budget_frames = 0
            await cluster.stop()

    asyncio.run(asyncio.wait_for(scenario(), 240))

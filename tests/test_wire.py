"""Wire codec round-trips — every protocol message type.

Two layers: synthetic unit round-trips per type, and a live-traffic fuzz
that runs a real HoneyBadger epoch and round-trips every message the
network actually carries (the reference serializes everything with bincode;
``encode_message`` must too).
"""

import random

import pytest

from hbbft_tpu.netinfo import NetworkInfo
from hbbft_tpu.ops.merkle import MerkleTree
from hbbft_tpu.protocols import wire
from hbbft_tpu.protocols.binary_agreement import (
    BOTH,
    AuxMsg,
    BValMsg,
    ConfMsg,
    CoinMsg,
    TermMsg,
)
from hbbft_tpu.protocols.broadcast import EchoMsg, ReadyMsg, ValueMsg
from hbbft_tpu.protocols.dynamic_honey_badger import (
    DynamicHoneyBadger,
    HbWrap,
    KeyGenWrap,
    SignedKeyGenMsg,
)
from hbbft_tpu.protocols.honey_badger import (
    DecryptionShareWrap,
    EncryptionSchedule,
    HoneyBadger,
    SubsetWrap,
)
from hbbft_tpu.protocols.sender_queue import AlgoMessage, EpochStarted
from hbbft_tpu.protocols.subset import AgreementWrap, BroadcastWrap
from hbbft_tpu.protocols.threshold_decrypt import DecryptionMessage
from hbbft_tpu.protocols.threshold_sign import ThresholdSignMessage
from hbbft_tpu.crypto.tc import SecretKey, SecretKeySet
from hbbft_tpu.sim import NetBuilder, NullAdversary


def rt(msg):
    data = wire.encode_message(msg)
    out = wire.decode_message(data)
    assert out == msg, (msg, out)
    return data


@pytest.fixture(scope="module")
def crypto_bits():
    rng = random.Random(77)
    sks = SecretKeySet.random(1, rng)
    share = sks.secret_key_share(0).sign(b"doc")
    pk = sks.public_keys().public_key()
    ct = pk.encrypt(b"payload", rng)
    dshare = sks.secret_key_share(0).decrypt_share(ct)
    sig = SecretKey(5).sign(b"x")
    return share, dshare, sig


def test_rbc_messages_roundtrip():
    tree = MerkleTree([b"shard-%d" % i for i in range(7)])
    for i in range(7):
        proof = tree.proof(i)
        rt(ValueMsg(proof))
        rt(EchoMsg(proof))
    rt(ReadyMsg(tree.root_hash()))


def test_aba_messages_roundtrip(crypto_bits):
    share, _, _ = crypto_bits
    rt(BValMsg(0, True))
    rt(BValMsg(7, False))
    rt(AuxMsg(2, True))
    rt(ConfMsg(3, BOTH))
    rt(ConfMsg(3, frozenset([True])))
    rt(ConfMsg(4, frozenset()))
    rt(TermMsg(False))
    rt(CoinMsg(5, ThresholdSignMessage(share)))


def test_threshold_messages_roundtrip(crypto_bits):
    share, dshare, _ = crypto_bits
    rt(ThresholdSignMessage(share))
    rt(DecryptionMessage(dshare))


def test_wrapper_messages_roundtrip(crypto_bits):
    share, dshare, sig = crypto_bits
    inner = BValMsg(1, True)
    rt(BroadcastWrap(3, ReadyMsg(b"\x07" * 32)))
    rt(AgreementWrap("node-a", inner))
    rt(SubsetWrap(9, BroadcastWrap(0, ReadyMsg(b"\x01" * 32))))
    rt(DecryptionShareWrap(4, 2, DecryptionMessage(dshare)))
    skg = SignedKeyGenMsg(1, 3, "part", b"\x00\x01\x02", sig)
    rt(KeyGenWrap(1, skg))
    rt(HbWrap(2, SubsetWrap(0, AgreementWrap(1, TermMsg(True)))))
    rt(EpochStarted((3, 11)))
    rt(AlgoMessage(HbWrap(0, SubsetWrap(0, BroadcastWrap(0, ReadyMsg(b"\x02" * 32))))))


def test_unknown_and_corrupt_rejected():
    with pytest.raises(TypeError):
        wire.encode_message(object())
    with pytest.raises(ValueError):
        wire.decode_message(b"\xff\x00")
    good = wire.encode_message(BValMsg(0, True))
    with pytest.raises(ValueError):
        wire.decode_message(good + b"\x00")  # trailing bytes
    with pytest.raises(ValueError):
        wire.decode_message(good[:-1])  # truncated


def test_nesting_bomb_rejected_with_value_error():
    """Deep attacker-crafted wrapper nesting must raise ValueError, not
    blow the Python stack."""
    bomb = (b"\x60" + (0).to_bytes(8, "big")) * 2000 + wire.encode_message(
        TermMsg(True)
    )
    with pytest.raises(ValueError):
        wire.decode_message(bomb)


def test_non_canonical_proof_flag_rejected():
    tree = MerkleTree([b"a", b"b"])
    enc = bytearray(wire.encode_message(ValueMsg(tree.proof(0))))
    assert enc[-1] in (0, 1)
    enc[-1] = 2  # corrupt the sibling-side flag
    with pytest.raises(ValueError):
        wire.decode_message(bytes(enc))


def test_live_honey_badger_traffic_roundtrips():
    """Every message a real N=4 HB epoch puts on the wire must round-trip."""
    n = 4
    infos = NetworkInfo.generate_map(list(range(n)), random.Random(13))
    net = NetBuilder(list(range(n))).adversary(NullAdversary()).using_step(
        lambda nid: HoneyBadger.builder(infos[nid])
        .session_id(b"wire-test")
        .encryption_schedule(EncryptionSchedule.always())
        .rng(random.Random(1000 + nid))
        .build()
    )
    for nid in net.node_ids():
        net.send_input(nid, f"contribution {nid}".encode())
    seen = set()
    count = 0
    while net.queue:
        # round-trip each queued message before delivery
        for m in list(net.queue):
            data = wire.encode_message(m.payload)
            assert wire.decode_message(data) == m.payload
            seen.add(type(m.payload).__name__)
            count += 1
        # deliver everything currently queued, then re-check the new wave
        for _ in range(len(net.queue)):
            net.crank()
    assert count > 100
    assert "SubsetWrap" in seen and "DecryptionShareWrap" in seen


def test_live_dhb_traffic_roundtrips():
    """DHB era messages (HbWrap/KeyGenWrap) round-trip on a live network."""
    n = 4
    rng = random.Random(5)
    infos = NetworkInfo.generate_map(list(range(n)), rng)
    net = NetBuilder(list(range(n))).using_step(
        lambda nid: DynamicHoneyBadger(
            infos[nid],
            infos[nid].secret_key(),
            rng=random.Random(400 + nid),
        )
    )
    from hbbft_tpu.protocols.dynamic_honey_badger import UserInput

    for nid in net.node_ids():
        net.send_input(nid, UserInput(f"tx-{nid}".encode()))
    kinds = set()
    while net.queue:
        for m in list(net.queue):
            data = wire.encode_message(m.payload)
            assert wire.decode_message(data) == m.payload
            kinds.add(type(m.payload).__name__)
        for _ in range(len(net.queue)):
            net.crank()
    assert "HbWrap" in kinds


def _flight_samples():
    from hbbft_tpu.obs.flight import (
        FlightCommit, FlightFault, FlightHello, FlightMsg, FlightNote,
        FlightSpan, HealthIncident, PerfSnapshot,
    )

    return [
        FlightHello("3", "runtime", 2, 1, 0.0),
        FlightMsg(7, 7.0, "in", "2", 0, 3, "ReadyMsg",
                  wire.encode_message(ReadyMsg(b"\x09" * 32))),
        FlightMsg(8, 8.0, "out", "all_except:1", 1, 4, "HbWrap", b""),
        FlightCommit(9, 9.0, 0, 3, 2, b"\xab" * 32),
        FlightFault(10, 10.0, "1", "MultipleReadys", 0, 3),
        FlightSpan(11, 11.0, "aba_bval", 0, 3, 2, 1.5, 2.5, 12),
        FlightSpan(12, 12.0, "epoch", 0, 3, None, 1.0, 3.0, 60),
        FlightNote(13, 13.0, "replay_gap", "peer=3"),
        HealthIncident(15, 15.0, "watchtower", "equivocation", "fault",
                       "3", "equivocation:3:MultipleReadys:slot",
                       "node 3 sent two Ready roots for one RBC slot"),
        PerfSnapshot(16, 16.0, "2", 1.0, 0.42, 0.58,
                     '{"layers": {"pump": 0.42}, "segments": {}}'),
        _trace_sample(),
    ]


def _trace_sample():
    from hbbft_tpu.obs.trace import FlightTrace, pack_tids, trace_id

    return FlightTrace(14, 14.0, "ingress", 0, 3, 1, "0",
                       pack_tids([trace_id(b"tx-a"), trace_id(b"tx-b")]))


def _vid_samples(sig):
    from hbbft_tpu.protocols.vid import (
        VidCert, VidDisperse, VidRetrieve, VidShard, VidVote,
    )

    tree = MerkleTree([b"vid-shard-%d" % i for i in range(4)])
    root = tree.root_hash()
    return [
        VidDisperse(2, root, 4096, tree.proof(1)),
        VidVote(2, root, sig),
        VidCert(2, root, 4096, ((0, sig), (1, sig), (2, sig))),
        VidRetrieve(root),
        VidShard(root, 4096, tree.proof(3)),
    ]


def _sync_samples():
    from hbbft_tpu.net.statesync import (
        SyncChunk, SyncChunkReq, SyncManifest, SyncManifestReq, SyncNack,
    )
    import zlib

    sha = b"\x5a" * 32
    return [
        SyncManifestReq(),
        SyncManifest(2, 17, b"\xcd" * 32, sha, 70_001, 32_768, 3),
        SyncChunkReq(sha, 1),
        SyncChunk(sha, 1, zlib.crc32(b"chunk-bytes"), b"chunk-bytes"),
        SyncNack("no snapshot published yet"),
    ]


def _sample_messages(crypto_bits):
    share, dshare, sig = crypto_bits
    tree = MerkleTree([b"shard-%d" % i for i in range(7)])
    skg = SignedKeyGenMsg(1, 3, "ack", b"\x00\x01\x02", sig)
    return _flight_samples() + _sync_samples() + _vid_samples(sig) + [
        ValueMsg(tree.proof(3)),
        EchoMsg(tree.proof(0)),
        ReadyMsg(tree.root_hash()),
        BValMsg(5, True),
        ConfMsg(3, BOTH),
        CoinMsg(5, ThresholdSignMessage(share)),
        DecryptionShareWrap(4, 2, DecryptionMessage(dshare)),
        KeyGenWrap(1, skg),
        HbWrap(2, SubsetWrap(0, AgreementWrap(1, TermMsg(True)))),
        AlgoMessage(HbWrap(0, SubsetWrap(0, BroadcastWrap(
            0, EchoMsg(tree.proof(1)))))),
        EpochStarted((3, 11)),
    ]


def test_mid_frame_cut_fuzz(crypto_bits):
    """Every mid-frame cut of every message type dies with ValueError —
    loudly, never a wrong decode, never a non-ValueError crash."""
    for msg in _sample_messages(crypto_bits):
        enc = wire.encode_message(msg)
        for cut in range(len(enc)):
            with pytest.raises(ValueError):
                wire.decode_message(enc[:cut])


def test_blob_cap_rejected_before_allocation():
    """A forged length prefix beyond the blob cap raises even though the
    buffer is short — the cap check precedes the truncation check."""
    r = wire.Reader(b"\xff\xff\xff\xff tiny", max_blob=1024)
    with pytest.raises(ValueError, match="exceeds cap"):
        r.blob()
    # a ciphertext message whose inner blob claims 2 GiB
    forged = b"\x31" + b"\x80\x00\x00\x00"
    with pytest.raises(ValueError, match="exceeds cap"):
        wire.decode_message(forged)


def test_message_byte_cap():
    big = wire.encode_message(ReadyMsg(b"\x01" * 32))
    with pytest.raises(ValueError, match="exceeds cap"):
        wire.decode_message(big, max_bytes=len(big) - 1)
    assert wire.decode_message(big, max_bytes=len(big)) == ReadyMsg(
        b"\x01" * 32
    )


def test_truncation_error_is_descriptive():
    with pytest.raises(ValueError, match="truncated: need"):
        wire.Reader(b"\x00\x00").u32()


def test_echo_hash_can_decode_roundtrip():
    from hbbft_tpu.protocols.broadcast import CanDecodeMsg, EchoHashMsg

    tree = MerkleTree([b"shard-%d" % i for i in range(4)])
    rt(EchoHashMsg(tree.root_hash()))
    rt(CanDecodeMsg(tree.root_hash()))


def test_every_registered_type_roundtrips_and_hashes(crypto_bits):
    """Registry-completeness regression (hblint wire-completeness twin):
    every wire-registered message class must have a sample here that (a)
    is a frozen dataclass, (b) hashes — net/runtime.py's replay log dedups
    entries by value, so an unhashable message breaks peer reconnects —
    and (c) round-trips to an equal-and-equal-hash value.  A newly
    registered type without a sample fails the completeness assert."""
    import dataclasses

    share, dshare, sig = crypto_bits
    tree = MerkleTree([b"shard-%d" % i for i in range(7)])
    skg = SignedKeyGenMsg(1, 3, "part", b"\x00\x01\x02", sig)
    from hbbft_tpu.protocols.broadcast import CanDecodeMsg, EchoHashMsg

    samples = [
        ValueMsg(tree.proof(3)),
        EchoMsg(tree.proof(0)),
        ReadyMsg(tree.root_hash()),
        EchoHashMsg(tree.root_hash()),
        CanDecodeMsg(tree.root_hash()),
        BValMsg(5, True),
        AuxMsg(2, False),
        ConfMsg(3, BOTH),
        TermMsg(True),
        CoinMsg(5, ThresholdSignMessage(share)),
        ThresholdSignMessage(share),
        DecryptionMessage(dshare),
        BroadcastWrap(3, ReadyMsg(b"\x07" * 32)),
        AgreementWrap("node-a", BValMsg(1, True)),
        SubsetWrap(9, BroadcastWrap(0, ReadyMsg(b"\x01" * 32))),
        DecryptionShareWrap(4, 2, DecryptionMessage(dshare)),
        HbWrap(2, SubsetWrap(0, AgreementWrap(1, TermMsg(True)))),
        KeyGenWrap(1, skg),
        EpochStarted((3, 11)),
        AlgoMessage(HbWrap(0, SubsetWrap(0, BroadcastWrap(
            0, EchoMsg(tree.proof(1)))))),
    ] + _flight_samples() + _sync_samples() + _vid_samples(sig)
    wire.ensure_registered()
    sampled = {type(m) for m in samples}
    registered = set(wire._MSG_TAGS)
    missing = {c.__name__ for c in registered - sampled}
    assert not missing, (
        f"registered wire types without a round-trip/hash sample: "
        f"{sorted(missing)} — add one to this test"
    )
    for msg in samples:
        cls = type(msg)
        assert dataclasses.is_dataclass(cls) and \
            cls.__dataclass_params__.frozen, cls.__name__
        h = hash(msg)  # raises if any field is unhashable
        decoded = wire.decode_message(wire.encode_message(msg))
        assert decoded == msg, cls.__name__
        assert hash(decoded) == h, cls.__name__

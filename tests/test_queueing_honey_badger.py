"""QHB tests (reference: ``tests/queueing_honey_badger.rs``): all injected
transactions commit exactly once, across random batches; SenderQueue keeps
laggards usable."""

import random

import pytest

from hbbft_tpu.netinfo import NetworkInfo
from hbbft_tpu.protocols.dynamic_honey_badger import DynamicHoneyBadger
from hbbft_tpu.protocols.honey_badger import EncryptionSchedule
from hbbft_tpu.protocols.queueing_honey_badger import (
    QhbBatch,
    QueueingHoneyBadger,
    TransactionQueue,
    TxInput,
)
from hbbft_tpu.protocols.sender_queue import SenderQueue
from hbbft_tpu.sim import NetBuilder, NullAdversary, RandomAdversary


def make_qhb_net(n, batch_size=8, seed=41, wrap_sender_queue=False):
    rng = random.Random(seed)
    infos = NetworkInfo.generate_map(list(range(n)), rng)

    def make(nid):
        dhb = DynamicHoneyBadger(
            infos[nid],
            infos[nid].secret_key(),
            rng=random.Random(7000 + nid),
            encryption_schedule=EncryptionSchedule.never(),
        )
        qhb = QueueingHoneyBadger(
            dhb, batch_size=batch_size, rng=random.Random(8000 + nid)
        )
        return SenderQueue(qhb) if wrap_sender_queue else qhb

    return NetBuilder(list(range(n))).using_step(make)


def committed_txs(node):
    txs = []
    for o in node.outputs:
        if isinstance(o, QhbBatch):
            txs.extend(o.all_txs())
    return txs


def test_transaction_queue_sampling():
    q = TransactionQueue()
    q.extend([bytes([i]) for i in range(20)])
    rng = random.Random(3)
    sample = q.choose(rng, 5)
    assert len(sample) == 5 and len(set(sample)) == 5
    assert q.choose(rng, 50) == [bytes([i]) for i in range(20)]
    q.remove_multiple(sample)
    assert len(q) == 15
    q.extend([bytes([0])])  # duplicate of a removed? no: 0 was maybe sampled
    # duplicates are not re-added if present
    size = len(q)
    q.extend([q._txs[0]])
    assert len(q) == size


def test_all_txs_committed_exactly_once():
    n = 4
    net = make_qhb_net(n, batch_size=6)
    txs = [f"tx-{i:03d}".encode() for i in range(24)]
    # spread txs across nodes
    for i, tx in enumerate(txs):
        net.send_input(i % n, TxInput(tx))
    net.run_to_quiescence()
    for nid in net.node_ids():
        got = committed_txs(net.nodes[nid])
        assert sorted(got) == sorted(set(got)), "tx committed twice"
        assert set(got) == set(txs), f"node {nid} missing txs"
    # all nodes agree on batch sequence
    ref = [o for o in net.nodes[0].outputs if isinstance(o, QhbBatch)]
    for nid in net.node_ids():
        assert [o for o in net.nodes[nid].outputs if isinstance(o, QhbBatch)] == ref
    # queues drained
    for nid in net.node_ids():
        assert len(net.nodes[nid].algorithm.queue) == 0


def test_qhb_random_adversary():
    n = 4
    net = make_qhb_net(n, batch_size=4, seed=43)
    net.adversary = RandomAdversary(seed=17, dup_prob=0.05)
    txs = [f"r-{i}".encode() for i in range(12)]
    for i, tx in enumerate(txs):
        net.send_input(i % n, TxInput(tx))
    net.run_to_quiescence()
    for nid in net.node_ids():
        assert set(committed_txs(net.nodes[nid])) == set(txs)


def test_qhb_with_sender_queue():
    n = 4
    net = make_qhb_net(n, batch_size=6, wrap_sender_queue=True)
    txs = [f"s-{i}".encode() for i in range(12)]
    for i, tx in enumerate(txs):
        net.send_input(i % n, TxInput(tx))
    net.run_to_quiescence()
    for nid in net.node_ids():
        algo = net.nodes[nid].algorithm
        got = committed_txs(net.nodes[nid])
        assert set(got) == set(txs), f"node {nid}"
        assert sorted(got) == sorted(set(got))


def test_sender_queue_registers_observer():
    """An observer not in the validators' netinfo gets messages once it
    announces itself via startup_step (the JoinPlan flow with SenderQueue)."""
    from hbbft_tpu.netinfo import NetworkInfo as NI
    from hbbft_tpu.protocols.dynamic_honey_badger import DynamicHoneyBadger
    from hbbft_tpu.sim.virtual_net import Node

    n = 4
    net = make_qhb_net(n, batch_size=6, seed=47, wrap_sender_queue=True)
    # observer node 9: same netinfo minus a secret key share
    rng = random.Random(9)
    plan_info = net.nodes[0].algorithm.algo.dhb.netinfo
    from hbbft_tpu.crypto import tc

    obs_sk = tc.SecretKey.random(rng)
    obs_dhb = DynamicHoneyBadger(
        NI(
            our_id=9,
            public_keys=plan_info.public_key_map(),
            public_key_set=plan_info.public_key_set(),
            secret_key=obs_sk,
        ),
        obs_sk,
        encryption_schedule=net.nodes[0].algorithm.algo.dhb.encryption_schedule,
    )
    obs = SenderQueue(QueueingHoneyBadger(obs_dhb, batch_size=6))
    net.nodes[9] = Node(node_id=9, algorithm=obs)
    # announce the observer to the validators
    from hbbft_tpu.sim.virtual_net import NetworkMessage

    startup = obs.startup_step()
    for tm in startup.messages:
        for dest in tm.target.resolve(net.node_ids(), 9):
            net.queue.append(NetworkMessage(9, dest, tm.message))
    txs = [f"ob-{i}".encode() for i in range(8)]
    for i, tx in enumerate(txs):
        net.send_input(i % n, TxInput(tx))
    net.run_to_quiescence()
    # the observer followed consensus and committed the same txs
    assert set(committed_txs(net.nodes[9])) == set(txs)

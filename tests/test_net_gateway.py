"""Gateway tier e2e: client termination off the consensus path.

The gateway speaks the node's exact client protocol on both sides, so
everything here runs an UNMODIFIED :class:`ClusterClient` against a
:class:`Gateway` fronting a real 4-node cluster: dedup at the gateway,
commit relay, gateway-pool fair sheds pushed as ``ACK_SHED``,
authenticated node links (a link that fails the node-identity challenge
never carries traffic), and gateway-kill failover — clients reconnect to
a surviving gateway and their in-flight txs still commit exactly once.
"""

import asyncio

import pytest

from hbbft_tpu.net import framing
from hbbft_tpu.net.client import ClusterClient, Mempool, TxShedError, tx_digest
from hbbft_tpu.net.cluster import ClusterConfig, LocalCluster, donor_key_fn
from hbbft_tpu.net.gateway import Gateway, node_verifier

SMOKE_TIMEOUT_S = 120


def test_gateway_end_to_end():
    """Submit through the gateway: admission acks, dedup AT the gateway,
    commit relay back to the client, status document, and the node only
    ever saw the gateway's couple of links — not the client."""

    async def scenario():
        cfg = ClusterConfig(n=4, seed=41, batch_size=6)
        cluster = LocalCluster(cfg)
        await cluster.start()
        try:
            gw = Gateway([cluster.addrs[i] for i in range(4)],
                         cfg.cluster_id, node_links=2)
            await gw.start()
            await gw.wait_links(2, timeout_s=30)

            client = ClusterClient(gw.addr, cfg.cluster_id,
                                   client_id="gw-e2e")
            await client.connect()
            txs = [b"gw-e2e-%02d" % i for i in range(12)]
            assert await client.submit_many(txs) == [0] * len(txs)
            # dedup terminates at the gateway: the duplicate never
            # reaches a node
            fwd_before = int(gw._c_forwarded.total())
            assert await client.submit(txs[0], retry_full=False) == 1
            await client.wait_committed_many(txs, timeout_s=45)
            assert int(gw._c_forwarded.total()) == fwd_before

            doc = gw.status_doc()
            assert doc["submissions"]["accepted"] == len(txs)
            assert doc["submissions"]["duplicate"] == 1
            assert doc["commits_relayed"] >= len(txs)
            assert doc["clients"] == 1
            # obs endpoint: /status + /metrics served like a node's, so
            # obs.top --gateways renders the tier with the same poller
            from hbbft_tpu.obs import top as obs_top
            ohost, oport = await gw.start_obs()
            snap = await asyncio.to_thread(
                obs_top.poll_target, ohost, oport)
            assert snap is not None
            assert snap["status"]["gateway"] == "gw0"
            assert snap["status"]["forwarded"] == len(txs)
            assert obs_top.metric_total(
                snap, "hbbft_gw_forwarded_total") == len(txs)
            sdoc2 = obs_top.snapshot_doc(
                [], [], [(ohost, oport)], [snap])
            assert sdoc2["gateways"][0]["up"]
            assert "gateway" in obs_top.render(
                [], [], [], 0.0, [(ohost, oport)], [snap])
            # a client can ask the gateway itself for status
            sdoc = await client.status()
            assert sdoc["gateway"] == "gw0"
            # the node's view: its client connections are the gateway's
            # links (+ the transient LocalCluster probe), NOT 1-per-client
            ndoc = await (await cluster.client(0)).status()
            assert ndoc["committed_txs"] >= len(txs)
            await client.close()
            await gw.stop()
        finally:
            await cluster.stop()

    asyncio.run(asyncio.wait_for(scenario(), SMOKE_TIMEOUT_S))


def test_gateway_shed_ack_semantics():
    """Fair-share shedding at the GATEWAY pool matches the node's
    client-visible contract: the victim's digest is pushed as ACK_SHED
    and a parked ``wait_committed`` fails fast with TxShedError.  No
    cluster needed — the links point at a dead address, so the pool
    can only fill."""

    async def scenario():
        gw = Gateway([("127.0.0.1", 1)], b"shed-test",
                     gateway_id="gw-shed", node_links=1,
                     redial_backoff_s=5.0,
                     mempool=Mempool(capacity=4))
        await gw.start()
        try:
            hog = ClusterClient(gw.addr, b"shed-test", client_id="hog")
            await hog.connect()
            hog_txs = [b"hog-%d" % i for i in range(4)]
            assert await hog.submit_many(hog_txs) == [0] * 4
            waiter = asyncio.get_running_loop().create_task(
                hog.wait_committed(hog_txs[0], timeout_s=30))
            await asyncio.sleep(0.05)

            other = ClusterClient(gw.addr, b"shed-test",
                                  client_id="other")
            await other.connect()
            # pool full, hog owns all 4: admitting the under-share
            # client sheds the hog's OLDEST — and the push arrives
            assert await other.submit(b"fair-1", retry_full=False) == 0
            with pytest.raises(TxShedError):
                await asyncio.wait_for(waiter, 10)
            assert int(gw._c_sheds.total()) == 1
            assert not gw.mempool.has_pending(tx_digest(hog_txs[0]))
            assert gw.mempool.has_pending(tx_digest(b"fair-1"))
            await hog.close()
            await other.close()
        finally:
            await gw.stop()

    asyncio.run(asyncio.wait_for(scenario(), SMOKE_TIMEOUT_S))


def test_gateway_node_links_authenticated():
    """Northbound trust: with a verifier that refuses everyone, links
    rotate forever (counted failovers) and no tx is ever forwarded;
    with the config-derived key resolver the same gateway connects and
    the challenge transcript pins the real node identity."""

    async def scenario():
        cfg = ClusterConfig(n=4, seed=43, batch_size=6)
        cluster = LocalCluster(cfg)
        await cluster.start()
        addrs = [cluster.addrs[i] for i in range(4)]
        try:
            bad = Gateway(addrs, cfg.cluster_id, gateway_id="gw-bad",
                          node_links=1, redial_backoff_s=0.05,
                          verify_node=lambda *a: False)
            await bad.start()
            with pytest.raises(asyncio.TimeoutError):
                await bad.wait_links(1, timeout_s=1.5)
            assert int(bad._c_link_failovers.total()) >= 2
            assert bad._live_links() == 0
            await bad.stop()

            good = Gateway(addrs, cfg.cluster_id, gateway_id="gw-good",
                           node_links=2,
                           verify_node=node_verifier(donor_key_fn(cfg)))
            await good.start()
            await good.wait_links(2, timeout_s=30)
            client = ClusterClient(good.addr, cfg.cluster_id,
                                   client_id="auth-c")
            await client.connect()
            assert await client.submit(b"authed-tx") == 0
            await client.wait_committed(b"authed-tx", timeout_s=45)
            await client.close()
            await good.stop()
        finally:
            await cluster.stop()

    asyncio.run(asyncio.wait_for(scenario(), SMOKE_TIMEOUT_S))


def test_gateway_kill_failover_clients_reconnect():
    """Kill the gateway a client is on: the client reconnects to a
    surviving gateway of the same tier, resubmits its un-acked txs
    (at-least-once), and node-side dedup makes redelivery exactly-once
    on the ledger."""

    async def scenario():
        cfg = ClusterConfig(n=4, seed=47, batch_size=6)
        cluster = LocalCluster(cfg)
        await cluster.start()
        addrs = [cluster.addrs[i] for i in range(4)]
        try:
            gw_a = Gateway(addrs, cfg.cluster_id, gateway_id="gwA",
                           node_links=2)
            gw_b = Gateway(addrs, cfg.cluster_id, gateway_id="gwB",
                           node_links=2)
            await gw_a.start()
            await gw_b.start()
            await gw_a.wait_links(2, timeout_s=30)
            await gw_b.wait_links(2, timeout_s=30)

            c1 = ClusterClient(gw_a.addr, cfg.cluster_id,
                               client_id="failover-c")
            await c1.connect()
            first = [b"pre-kill-%d" % i for i in range(6)]
            assert await c1.submit_many(first) == [0] * 6
            await c1.wait_committed_many(first, timeout_s=45)

            await gw_a.stop()  # the tier loses a gateway mid-session

            # the client's reconnect policy: dial the next gateway and
            # resubmit anything not yet seen committed
            c2 = ClusterClient(gw_b.addr, cfg.cluster_id,
                               client_id="failover-c")
            await c2.connect()
            again = await c2.submit_many(first + [b"post-kill"])
            # resubmitted txs are already committed cluster-wide: the
            # gateway forwards them, nodes answer DUPLICATE, nothing
            # double-commits; the new tx sails through
            assert again[-1] == 0
            await c2.wait_committed(b"post-kill", timeout_s=45)

            # exactly-once on the ledger: the nodes stayed on ONE chain
            # through the resubmission storm (common_digest_prefix
            # asserts cross-node byte-identity internally), and the
            # duplicates were absorbed at admission, not committed twice
            assert len(cluster.common_digest_prefix()) >= 2
            doc = await (await cluster.client(0)).status()
            assert doc["committed_txs"] >= len(first) + 1
            await c1.close()
            await c2.close()
            await gw_b.stop()
        finally:
            await cluster.stop()

    asyncio.run(asyncio.wait_for(scenario(), SMOKE_TIMEOUT_S))

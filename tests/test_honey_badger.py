"""HoneyBadger epoch tests (reference: ``tests/honey_badger.rs``).

The BASELINE config-1 milestone lives here: N=4 f=1, a 256-tx batch, one
epoch — all correct nodes commit identical batches, with encryption on.
"""

import random

import pytest

from hbbft_tpu.netinfo import NetworkInfo
from hbbft_tpu.protocols.honey_badger import (
    Batch,
    EncryptionSchedule,
    HoneyBadger,
)
from hbbft_tpu.sim import NetBuilder, NullAdversary, RandomAdversary

_INFO_CACHE = {}


def infos_for(n, seed=13):
    key = (n, seed)
    if key not in _INFO_CACHE:
        _INFO_CACHE[key] = NetworkInfo.generate_map(
            list(range(n)), random.Random(seed)
        )
    return _INFO_CACHE[key]


def build_net(n, adversary, schedule=None):
    infos = infos_for(n)
    return NetBuilder(list(range(n))).adversary(adversary).using_step(
        lambda nid: HoneyBadger.builder(infos[nid])
        .session_id(b"hb-test")
        .encryption_schedule(schedule or EncryptionSchedule.always())
        .rng(random.Random(1000 + nid))
        .build()
    )


def batches_of(node):
    return [o for o in node.outputs if isinstance(o, Batch)]


@pytest.mark.parametrize(
    "schedule",
    [EncryptionSchedule.always(), EncryptionSchedule.never()],
    ids=["encrypted", "plain"],
)
def test_one_epoch_identical_batches(schedule):
    n = 4
    net = build_net(n, NullAdversary(), schedule)
    for nid in net.node_ids():
        net.send_input(nid, f"contribution from {nid}".encode())
    net.run_to_quiescence()
    all_batches = [batches_of(net.nodes[nid]) for nid in net.node_ids()]
    assert all(len(b) == 1 for b in all_batches)
    first = all_batches[0][0]
    assert first.epoch == 0
    assert all(b[0] == first for b in all_batches)
    f = (n - 1) // 3
    assert len(first.contributions) >= n - f
    for pid, contrib in first.contributions:
        assert contrib == f"contribution from {pid}".encode()


def test_baseline_config1_n4_f1_256tx_batch():
    """BASELINE.json config #1: N=4 f=1, 256-tx batch, one epoch."""
    n = 4
    txs = [f"tx-{i:04d}".encode() for i in range(256)]
    # each node contributes a quarter of the batch
    per_node = {nid: b"|".join(txs[nid::n]) for nid in range(n)}
    net = build_net(n, NullAdversary())
    for nid in net.node_ids():
        net.send_input(nid, per_node[nid])
    net.run_to_quiescence()
    batches = [batches_of(net.nodes[nid])[0] for nid in net.node_ids()]
    assert len({b.contributions for b in batches}) == 1
    committed = set()
    for pid, contrib in batches[0].contributions:
        committed.update(contrib.split(b"|"))
    f = (n - 1) // 3
    assert len(committed) >= len(txs) * (n - f) // n


def test_multiple_epochs_in_order():
    n = 4
    net = build_net(n, NullAdversary())
    for epoch in range(3):
        for nid in net.node_ids():
            net.send_input(nid, f"e{epoch}-от-{nid}".encode())
        net.run_to_quiescence()
    for nid in net.node_ids():
        bs = batches_of(net.nodes[nid])
        assert [b.epoch for b in bs] == [0, 1, 2]
    ref = batches_of(net.nodes[0])
    for nid in (1, 2, 3):
        assert batches_of(net.nodes[nid]) == ref


def test_random_adversary_epoch():
    n = 4
    net = build_net(n, RandomAdversary(seed=21, dup_prob=0.05))
    for nid in net.node_ids():
        net.send_input(nid, bytes([nid]) * 64)
    net.run_to_quiescence()
    batches = [batches_of(net.nodes[nid]) for nid in net.node_ids()]
    assert all(len(b) == 1 for b in batches)
    assert len({b[0].contributions for b in batches}) == 1


def test_silent_node_excluded_but_epoch_completes():
    n = 4
    net = build_net(n, NullAdversary())
    for nid in (0, 1, 2):  # node 3 proposes nothing
        net.send_input(nid, bytes([nid]))
    net.run_to_quiescence()
    batches = [batches_of(net.nodes[nid]) for nid in net.node_ids()]
    assert all(len(b) == 1 for b in batches)
    contribs = dict(batches[0][0].contributions)
    assert set(contribs.keys()) == {0, 1, 2}


def test_encryption_schedule_every_nth():
    es = EncryptionSchedule.every_nth_epoch(3)
    assert [es.encrypt_on_epoch(e) for e in range(6)] == [
        True, False, False, True, False, False,
    ]
    tt = EncryptionSchedule.tick_tock(2, 1)
    assert [tt.encrypt_on_epoch(e) for e in range(6)] == [
        True, True, False, True, True, False,
    ]

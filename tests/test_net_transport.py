"""net/transport.py: deterministic backoff, reconnect, queue persistence."""

import asyncio
import socket
import struct

import pytest

from hbbft_tpu.net import framing
from hbbft_tpu.net.transport import BackoffPolicy, Transport


def test_backoff_policy_deterministic():
    """Same seed ⇒ identical delay schedule; the transport's reconnect
    trace is a pure function of (seed, our_id, peer_id)."""
    s1 = BackoffPolicy(seed=42).preview("0->1", 12)
    s2 = BackoffPolicy(seed=42).preview("0->1", 12)
    assert s1 == s2
    assert BackoffPolicy(seed=43).preview("0->1", 12) != s1
    assert BackoffPolicy(seed=42).preview("0->2", 12) != s1
    # exponential growth with jitter in [raw·(1−j), raw), capped
    for i, d in enumerate(s1):
        raw = min(2.0, 0.05 * 2.0 ** i)
        assert raw * 0.5 <= d < raw


def test_backoff_stream_continues_across_outages():
    """One rng stream per peer: successive outages continue the sequence
    (attempt growth resets, the draws do not repeat)."""
    policy = BackoffPolicy(seed=7)
    rng = policy.rng_for("a->b")
    seq = [policy.delay(i, rng) for i in range(3)]
    seq += [policy.delay(i, rng) for i in range(3)]  # second outage
    expect_rng = policy.rng_for("a->b")
    expect = [
        policy.delay(a, expect_rng) for a in (0, 1, 2, 0, 1, 2)
    ]
    assert seq == expect
    assert len(set(seq)) == 6  # jitter keeps drawing fresh values


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_transport_reconnect_preserves_queue_and_schedule():
    """Frames queued while the peer is down arrive in order after it comes
    up, and the recorded backoff delays match the seeded schedule."""

    async def scenario():
        fast = BackoffPolicy(seed=5, base=0.01, cap=0.05)
        got = []
        ta = Transport(0, b"cl", backoff=fast)
        await ta.listen()
        port = _free_port()
        ta.add_peer(1, ("127.0.0.1", port))
        payloads = [b"first", b"second", b"third"]
        for p in payloads:
            ta.send(1, p)
        await asyncio.sleep(0.25)  # several failed dials
        delays = list(ta.stats.backoff_delays[1])
        assert len(delays) >= 3
        assert delays == fast.preview("0->1", len(delays))
        assert ta.queued(1) == len(payloads)  # nothing lost while down

        tb = Transport(1, b"cl",
                       on_peer_message=lambda pid, data: got.append(
                           (pid, data)))
        await tb.listen("127.0.0.1", port)
        tb.add_peer(0, ta.addr)
        for _ in range(400):
            if len(got) == len(payloads):
                break
            await asyncio.sleep(0.01)
        assert got == [(0, p) for p in payloads]
        assert ta.stats.frames_sent >= len(payloads)
        assert tb.stats.frames_recv >= len(payloads)
        await ta.stop()
        await tb.stop()

    asyncio.run(asyncio.wait_for(scenario(), 20))


def test_inbound_rejects_garbage_and_wrong_cluster():
    """A garbage hello or a wrong cluster id closes the connection before
    any payload frame is parsed; the transport keeps serving."""

    async def scenario():
        got = []
        t = Transport(0, b"right-cluster",
                      on_peer_message=lambda pid, data: got.append(data))
        await t.listen()

        async def probe(raw: bytes) -> bytes:
            reader, writer = await asyncio.open_connection(*t.addr)
            writer.write(raw)
            await writer.drain()
            data = await asyncio.wait_for(reader.read(4096), 5)
            writer.close()
            return data

        # not a HELLO first
        assert await probe(framing.encode_frame(framing.MSG, b"x")) == b""
        # oversize claimed frame length
        assert await probe(struct.pack(">I", 2 ** 31) + b"\x01") == b""
        # wrong cluster id
        bad = framing.encode_hello(framing.Hello(
            node_id=1, role=framing.ROLE_NODE,
            cluster_id=b"wrong-cluster", era=0, epoch=0))
        assert await probe(framing.encode_frame(framing.HELLO, bad)) == b""
        # node hello from an unknown peer id (no senders configured)
        unknown = framing.encode_hello(framing.Hello(
            node_id=9, role=framing.ROLE_NODE,
            cluster_id=b"right-cluster", era=0, epoch=0))
        assert await probe(
            framing.encode_frame(framing.HELLO, unknown)) == b""
        assert got == []
        await t.stop()

    asyncio.run(asyncio.wait_for(scenario(), 20))


def test_transport_counters_feed_eventlog_and_costmodel():
    """Satellite wiring: real frames land in EventLog.net_events and
    accrue virtual cost under the simulator's CostModel, so sim and net
    runs report comparable numbers."""
    from hbbft_tpu.sim.trace import CostModel, EventLog

    async def scenario():
        recv_log, send_log = EventLog(), EventLog()
        cost = CostModel(bandwidth_bps=1e9, cpu_lag_s=1e-5)
        got = []
        tb = Transport(1, b"cl", trace=recv_log, cost_model=cost,
                       on_peer_message=lambda pid, d: got.append(d))
        await tb.listen()
        ta = Transport(0, b"cl", trace=send_log)
        await ta.listen()
        tb.add_peer(0, ta.addr)
        ta.add_peer(1, tb.addr)
        for i in range(5):
            ta.send(1, b"payload-%d" % i)
        for _ in range(400):
            if len(got) == 5:
                break
            await asyncio.sleep(0.01)
        assert len(got) == 5
        sent = [e for e in send_log.net_events
                if e.direction == "send" and e.kind == "MSG"]
        recvd = [e for e in recv_log.net_events
                 if e.direction == "recv" and e.kind == "MSG"]
        assert len(sent) == 5 and len(recvd) == 5
        assert send_log.net_bytes_by_kind()["MSG"] == sum(
            e.wire_bytes for e in sent
        )
        assert recv_log.net_frames_by_kind()["MSG"] == 5
        assert recv_log.net_total_bytes("recv") > 0
        # every received frame was charged on the synthetic clock (send
        # events — hello replies, PONGs — are recorded but not charged)
        expect = sum(cost.charge(e.wire_bytes)
                     for e in recv_log.net_events
                     if e.direction == "recv")
        assert abs(tb.stats.virtual_cost_s - expect) < 1e-9
        assert tb.stats.virtual_cost_s > 0
        await ta.stop()
        await tb.stop()

    asyncio.run(asyncio.wait_for(scenario(), 20))


def test_mempool_bounds_tx_size_and_count():
    from hbbft_tpu.net.client import Mempool

    mp = Mempool(capacity=2, max_tx_bytes=16)
    assert mp.add(b"x" * 17) == Mempool.REJECTED  # never retried
    assert mp.add(b"a") == Mempool.ACCEPTED
    assert mp.add(b"a") == Mempool.DUPLICATE
    assert mp.add(b"b") == Mempool.ACCEPTED
    assert mp.add(b"c") == Mempool.FULL  # backpressure: retry later
    mp.mark_committed([b"a"])
    assert mp.add(b"c") == Mempool.ACCEPTED
    assert mp.add(b"a") == Mempool.DUPLICATE  # recently committed


def test_replay_prune_survives_era_boundary():
    """Regression: the replay floor must not discard the whole previous
    era the moment a DKG rotation lands — a peer whose outage spans the
    era boundary still needs the old-era tail replayed."""
    import random

    from hbbft_tpu.net.cluster import (
        ClusterConfig, build_runtime, generate_infos,
    )

    cfg = ClusterConfig(n=4, seed=55)
    rt = build_runtime(cfg, generate_infos(cfg), 0)
    retain = rt.replay_retain_epochs
    # replay entries are (key, message, payload-bytes) triples
    entries = [((0, 58), "a", b"a"), ((0, 63), "b", b"b"),
               ((1, 0), "c", b"c")]
    # young era 1: previous era's tail is retained
    rt._replay = {1: list(entries)}
    rt.current_key = lambda: (1, 2)
    rt._prune_replay()
    assert rt._replay[1] == entries
    # once era 1 is `retain` epochs old, the old era (and this era's own
    # stale prefix) goes
    rt._replay = {1: list(entries)}
    rt.current_key = lambda: (1, retain + 6)
    rt._prune_replay()
    assert rt._replay[1] == []
    # same-era pruning unchanged
    rt._replay = {1: [((0, 1), "old", b"o"),
                      ((0, retain + 3), "new", b"n")]}
    rt.current_key = lambda: (0, retain + 5)
    rt._prune_replay()
    assert rt._replay[1] == [((0, retain + 3), "new", b"n")]


def test_client_fails_fast_on_corrupt_stream():
    """A hostile/corrupt frame from the node must fail every pending
    client future immediately, not leak N× full timeouts."""
    from hbbft_tpu.net.client import ClusterClient

    async def scenario():
        async def serve(reader, writer):
            await reader.read(4096)  # client hello
            reply = framing.encode_hello(framing.Hello(
                node_id=0, role=framing.ROLE_NODE,
                cluster_id=b"cl", era=0, epoch=0))
            writer.write(framing.encode_frame(framing.HELLO, reply))
            # then a frame claiming 2 GiB — the client decoder must bail
            writer.write(struct.pack(">I", 2 ** 31) + b"\x07")
            await writer.drain()

        server = await asyncio.start_server(serve, "127.0.0.1", 0)
        addr = server.sockets[0].getsockname()[:2]
        client = ClusterClient(addr, b"cl")
        await client.connect()
        waiter = asyncio.ensure_future(
            client.wait_committed(b"never", timeout_s=30)
        )
        with pytest.raises(ConnectionError):
            await asyncio.wait_for(waiter, 5)
        await client.close()
        server.close()
        await server.wait_closed()

    asyncio.run(asyncio.wait_for(scenario(), 20))


def test_hello_carries_current_epoch_key():
    """Both hello directions surface the peers' (era, epoch) keys."""

    async def scenario():
        hellos = []
        ta = Transport(0, b"cl", hello_key=lambda: (1, 7),
                       on_peer_hello=lambda pid, h, d: hellos.append(
                           ("a", pid, h.key, d)))
        tb = Transport(1, b"cl", hello_key=lambda: (2, 9),
                       on_peer_hello=lambda pid, h, d: hellos.append(
                           ("b", pid, h.key, d)))
        await ta.listen()
        await tb.listen()
        ta.add_peer(1, tb.addr)
        tb.add_peer(0, ta.addr)
        for _ in range(400):
            if len(hellos) >= 4:
                break
            await asyncio.sleep(0.01)
        assert ("a", 1, (2, 9), "dial") in hellos
        assert ("b", 0, (1, 7), "accept") in hellos
        assert ("b", 0, (1, 7), "dial") in hellos
        assert ("a", 1, (2, 9), "accept") in hellos
        await ta.stop()
        await tb.stop()

    asyncio.run(asyncio.wait_for(scenario(), 20))


def test_egress_quantum_round_robin_counted():
    """A backlog deeper than the byte quantum is drained in counted
    rounds (hbbft_guard_egress_stalls_total): the sender yields the
    event loop between quanta instead of monopolizing it, and every
    frame still arrives in order."""

    async def scenario():
        got = []
        tb = Transport(1, b"cl",
                       on_peer_message=lambda pid, d: got.append(d))
        await tb.listen()
        # 4 KiB quantum, 40 × 1 KiB frames → many truncated rounds
        ta = Transport(0, b"cl", egress_quantum_bytes=4096)
        await ta.listen()
        tb.add_peer(0, ta.addr)
        ta.add_peer(1, tb.addr)
        frames = [bytes([i]) * 1024 for i in range(40)]
        for p in frames:
            ta.send(1, p)
        for _ in range(400):
            if len(got) == len(frames):
                break
            await asyncio.sleep(0.01)
        assert got == frames  # all delivered, in order
        stalls = ta.stats._egress_stalls.total()
        assert stalls > 0, "deep backlog must hit the quantum"
        await ta.stop()
        await tb.stop()

    asyncio.run(asyncio.wait_for(scenario(), 20))

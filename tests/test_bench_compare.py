"""``bench.py --compare OLD.json NEW.json`` — the regression gate over
recorded ``BENCH_*.json`` trajectory files."""

import json

import pytest

import bench


def _line(value=20.0, p50=90.0, p99=100.0, wall=41.0, rbc=17.0,
          aba=25.0):
    return {
        "metric": "net_qhb4_localhost",
        "value": value,
        "unit": "epochs/s",
        "p50_latency_ms": p50,
        "p99_latency_ms": p99,
        "phases": {
            "epoch_wall_p50_ms": wall,
            "epoch_wall_p99_ms": wall + 10,
            "rbc": {"attr_p50_ms": rbc},
            "aba": {"attr_p50_ms": aba},
            "coin": {"attr_p50_ms": None},      # absent phase: skipped
            "decrypt": {"attr_p50_ms": None},
        },
    }


def test_improvement_and_noise_pass():
    old = _line()
    new = _line(value=22.0, p50=85.0, wall=39.0)  # better
    report = bench.compare_bench(old, new, threshold=0.15)
    assert report["ok"] and report["regressions"] == []
    # within-threshold noise passes too
    new = _line(value=19.0, p50=95.0)  # ~5% worse: under the gate
    assert bench.compare_bench(old, new, threshold=0.15)["ok"]


def test_throughput_regression_fails():
    report = bench.compare_bench(_line(), _line(value=14.0),
                                 threshold=0.15)
    assert not report["ok"] and report["regressions"] == ["value"]
    check = [c for c in report["checks"] if c["name"] == "value"][0]
    assert check["regressed"] and check["delta_pct"] == -30.0


def test_latency_and_phase_attribution_regressions_fail():
    report = bench.compare_bench(_line(), _line(p99=140.0),
                                 threshold=0.15)
    assert report["regressions"] == ["p99_latency_ms"]
    # per-phase attribution gates at 2x threshold: +25% passes, +60%
    # fails — "a phase silently doubling" is what the gate exists for
    assert bench.compare_bench(_line(), _line(aba=31.0),
                               threshold=0.15)["ok"]
    report = bench.compare_bench(_line(), _line(aba=40.0),
                                 threshold=0.15)
    assert report["regressions"] == ["phases.aba.attr_p50_ms"]


def test_value_direction_respects_unit():
    # a seconds-per-epoch metric regresses UP, not down
    old = {"metric": "m", "value": 4.5, "unit": "s"}
    assert not bench.compare_bench(old, dict(old, value=6.0),
                                   threshold=0.15)["ok"]
    assert bench.compare_bench(old, dict(old, value=3.0),
                               threshold=0.15)["ok"]


def test_cli_exit_codes_and_report_line(tmp_path, capsys):
    old_p = tmp_path / "old.json"
    new_p = tmp_path / "new.json"
    old_p.write_text(json.dumps(_line()))
    new_p.write_text(json.dumps(_line(value=22.0)))
    assert bench.run_compare(str(old_p), str(new_p), 0.15) == 0
    report = json.loads(capsys.readouterr().out.strip())
    assert report["metric"] == "bench_compare" and report["ok"]

    new_p.write_text(json.dumps(_line(value=10.0)))
    with pytest.raises(SystemExit) as exc:
        bench.main(["--compare", str(old_p), str(new_p)])
    assert exc.value.code == 1
    report = json.loads(capsys.readouterr().out.strip())
    assert report["regressions"] == ["value"]


def test_load_bench_json_salvages_truncated_log_lines(tmp_path):
    """A piped log whose final line was cut mid-write must not abort the
    gate — the last COMPLETE object wins."""
    p = tmp_path / "log.json"
    p.write_text("# device: cpu\n" + json.dumps(_line()) + "\n"
                 + '{"metric": "net_clu')
    assert bench.load_bench_json(str(p))["metric"] == "net_qhb4_localhost"


def test_real_recorded_trajectory_files_compare():
    """The shipped BENCH_NET_r01 → r02 trajectory must load and produce
    a verdict (this is the pair the gate exists to watch)."""
    import os

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    old = bench.load_bench_json(os.path.join(root, "BENCH_NET_r01.json"))
    new = bench.load_bench_json(os.path.join(root, "BENCH_NET_r02.json"))
    report = bench.compare_bench(old, new, threshold=0.5)
    names = {c["name"] for c in report["checks"]}
    assert "value" in names and "p50_latency_ms" in names


def test_depth_change_skips_per_epoch_metrics():
    """Epoch-wall / phase-attribution metrics measure a different
    quantity once epochs overlap: a depth-1 → depth-4 comparison must
    gate on throughput and client latency only (pipelining stretches
    every per-epoch wall by design), while an equal-depth comparison
    still gates on them."""
    old = _line()                       # no pipeline_depth key → depth 1
    new = _line(value=40.0, wall=80.0)  # wall doubled, throughput doubled
    new["pipeline_depth"] = 4
    report = bench.compare_bench(old, new, threshold=0.15)
    assert report["ok"] and not report["epoch_metrics_compared"]
    names = {c["name"] for c in report["checks"]}
    assert "phases.epoch_wall_p50_ms" not in names

    same = _line(value=40.0, wall=80.0)  # same depth: wall gate applies
    report = bench.compare_bench(old, same, threshold=0.15)
    assert report["epoch_metrics_compared"]
    assert "phases.epoch_wall_p50_ms" in report["regressions"]


def _sweep_cell(tx_bytes, batch, tx_per_s, mb_per_s):
    return {"tx_bytes": tx_bytes, "batch": batch,
            "tx_per_s": tx_per_s, "mb_per_s": mb_per_s}


def test_ingest_sweep_gates_at_equal_shape_only():
    """Per-shape tx/s + MB/s are higher-better and compared only when
    BOTH recordings ran the same (tx_bytes, batch) cell; added or
    dropped cells are ignored."""
    old = _line()
    old["ingest_sweep"] = [
        _sweep_cell(64, 8, 700.0, 0.043),
        _sweep_cell(65536, 8, 40.0, 2.5),
        _sweep_cell(4096, 256, 900.0, 3.5),   # dropped in new
    ]
    new = _line()
    new["ingest_sweep"] = [
        _sweep_cell(64, 8, 750.0, 0.046),     # improved: ok
        _sweep_cell(65536, 8, 20.0, 1.25),    # halved: regression
        _sweep_cell(64, 4096, 5000.0, 0.3),   # new cell: ignored
    ]
    report = bench.compare_bench(old, new, threshold=0.15)
    assert report["regressions"] == [
        "ingest[65536B x8].tx_per_s", "ingest[65536B x8].mb_per_s",
    ]
    names = {c["name"] for c in report["checks"]}
    assert "ingest[64B x8].tx_per_s" in names
    assert "ingest[64B x8].mb_per_s" in names
    # shapes present on only one side contribute no checks
    assert not any("4096B x256" in n or "64B x4096" in n for n in names)


def test_ingest_sweep_absent_or_empty_is_trivially_ok():
    """r03-era recordings predate the sweep: comparing them against an
    r04 artifact (or vice versa) must not fail on the missing key."""
    old = _line()
    new = _line()
    new["ingest_sweep"] = [_sweep_cell(64, 8, 700.0, 0.043)]
    report = bench.compare_bench(old, new, threshold=0.15)
    assert report["ok"]
    assert not any(c["name"].startswith("ingest[") for c in report["checks"])


def test_mesh_mismatch_skips_value_gate():
    """hb-epoch* records carry mesh_devices; a 1-device vs 8-device
    recording measures different hardware, so the throughput gate only
    applies when both sides ran the same mesh."""
    old = _line()
    old["mesh_devices"] = 1
    new = _line(value=2.0)              # 10x slower — but on 8 devices
    new["mesh_devices"] = 8
    new["mesh_axes"] = "nodes=8"
    report = bench.compare_bench(old, new, threshold=0.15)
    assert report["ok"] and not report["mesh_metrics_compared"]
    names = {c["name"] for c in report["checks"]}
    assert "value" not in names

    equal = _line(value=2.0)            # same (absent → 1-device) mesh
    report = bench.compare_bench(old, equal, threshold=0.15)
    assert report["mesh_metrics_compared"]
    assert "value" in report["regressions"]


def test_multichip_trajectory_gates_per_device_count():
    """MULTICHIP recordings gate epochs/s per n_devices point,
    higher-better; points present on only one side are ignored."""
    def _traj(points):
        return {
            "metric": "multichip_epoch_trajectory",
            "value": points[-1][1],
            "unit": "epochs/s",
            "n_devices": points[-1][0],
            "trajectory": [
                {"n_devices": nd, "epochs_per_s": eps} for nd, eps in points
            ],
        }

    old = _traj([(1, 30.0), (4, 12.0), (8, 11.0)])
    good = _traj([(1, 31.0), (4, 13.0), (8, 12.0), (16, 10.0)])  # 16: new
    report = bench.compare_bench(old, good, threshold=0.15)
    assert report["ok"]
    names = {c["name"] for c in report["checks"]}
    assert "trajectory[4dev].epochs_per_s" in names
    assert not any("16dev" in n for n in names)

    bad = _traj([(1, 30.0), (4, 6.0), (8, 11.0)])  # 4-dev point halved
    report = bench.compare_bench(old, bad, threshold=0.15)
    assert not report["ok"]
    assert "trajectory[4dev].epochs_per_s" in report["regressions"]


def test_pump_segment_means_gate_equal_depth_and_shape_only():
    """The perf plane's per-segment pump costs gate like the phase
    attribution: lower-better mean seconds at 2x threshold, compared
    only at equal pipeline depth and only for segments present in BOTH
    recordings."""
    old = _line()
    old["pump_util"] = {
        "msg": {"mean_s": 0.001, "busy_s": 1.0, "events": 1000},
        "deferred": {"mean_s": 0.004, "busy_s": 0.4, "events": 100},
        "guard": {"mean_s": 0.0002, "busy_s": 0.02, "events": 100},
    }
    new = _line()
    new["pump_util"] = {
        "msg": {"mean_s": 0.0025, "busy_s": 2.5, "events": 1000},
        "deferred": {"mean_s": 0.0042, "busy_s": 0.42, "events": 100},
        "shed": {"mean_s": 0.001, "busy_s": 0.1, "events": 100},
    }
    report = bench.compare_bench(old, new, threshold=0.15)
    # msg 2.5x the old mean fails the 2x-threshold (30%) gate;
    # deferred +5% is noise; guard/shed exist on one side only
    assert report["regressions"] == ["pump[msg].mean_s"]
    check = [c for c in report["checks"]
             if c["name"] == "pump[msg].mean_s"][0]
    assert check["threshold_pct"] == 30.0 and check["delta_pct"] == 150.0
    names = {c["name"] for c in report["checks"]}
    assert "pump[deferred].mean_s" in names
    assert not any("guard" in n or "shed" in n for n in names)

    # a faster segment (lower mean) never regresses
    faster = _line()
    faster["pump_util"] = {
        "msg": {"mean_s": 0.0004, "busy_s": 0.4, "events": 1000}}
    assert bench.compare_bench(old, faster, threshold=0.15)["ok"]

    # a depth change skips the pump gate entirely: per-iteration work
    # legitimately differs once epochs overlap
    deeper = _line(value=40.0)
    deeper["pipeline_depth"] = 4
    deeper["pump_util"] = {
        "msg": {"mean_s": 0.005, "busy_s": 5.0, "events": 1000}}
    report = bench.compare_bench(old, deeper, threshold=0.15)
    assert report["ok"]
    assert not any(c["name"].startswith("pump[")
                   for c in report["checks"])

    # pre-perf-plane recordings (no pump_util key) compare trivially
    assert bench.compare_bench(old, _line(), threshold=0.15)["ok"]
    assert bench.compare_bench(_line(), new, threshold=0.15)["ok"]

"""Merkle tree tests: host proofs, tamper rejection, device/host parity."""

import numpy as np
import pytest

from hbbft_tpu.ops.merkle import MerkleTree, Proof, merkle_build_jax, merkle_verify_jax


@pytest.mark.parametrize("n", [1, 2, 3, 4, 5, 7, 8, 13])
def test_proof_roundtrip(n):
    values = [bytes([i]) * 10 for i in range(n)]
    tree = MerkleTree.from_vec(values)
    for i in range(n):
        proof = tree.proof(i)
        assert proof is not None
        assert proof.validate(n), f"leaf {i}/{n}"
        assert proof.value == values[i]


def test_proof_out_of_range():
    tree = MerkleTree.from_vec([b"a", b"b"])
    assert tree.proof(2) is None
    assert tree.proof(-1) is None


@pytest.mark.parametrize("n", [2, 3, 5, 8])
def test_tampered_proof_rejected(n):
    values = [bytes([i]) * 10 for i in range(n)]
    tree = MerkleTree.from_vec(values)
    p = tree.proof(0)
    # wrong value
    bad = Proof(b"evil" * 3, p.index, p.root_hash, p.path)
    assert not bad.validate(n)
    # wrong index
    bad = Proof(p.value, (p.index + 1) % n, p.root_hash, p.path)
    assert not bad.validate(n)
    # truncated path
    if p.path:
        bad = Proof(p.value, p.index, p.root_hash, p.path[:-1])
        assert not bad.validate(n)
    # tampered sibling
    if p.path:
        sib, left = p.path[0]
        bad_path = ((bytes(32), left),) + p.path[1:]
        bad = Proof(p.value, p.index, p.root_hash, bad_path)
        assert not bad.validate(n)


@pytest.mark.parametrize("n", [1, 2, 3, 5, 8])
def test_device_build_matches_host(n):
    import jax.numpy as jnp

    rng = np.random.RandomState(n)
    leaf_bytes = 24
    leaves_np = rng.randint(0, 256, (n, leaf_bytes)).astype(np.uint8)
    tree = MerkleTree.from_vec([l.tobytes() for l in leaves_np])
    root, proofs, mask = merkle_build_jax(jnp.asarray(leaves_np))
    assert np.asarray(root).tobytes() == tree.root_hash()
    # device proofs verify on device
    ok = merkle_verify_jax(
        jnp.asarray(leaves_np),
        jnp.arange(n),
        jnp.broadcast_to(root, (n, 32)),
        proofs,
        jnp.asarray(mask),
    )
    assert bool(np.all(np.asarray(ok)))
    # and match host path structure
    for i in range(n):
        hp = tree.proof(i)
        dev_sibs = [
            np.asarray(proofs[i, d]).tobytes()
            for d in range(proofs.shape[1])
            if int(mask[i, d])
        ]
        host_sibs = [s for s, _ in hp.path]
        assert dev_sibs == host_sibs


def test_device_verify_rejects_tamper():
    import jax.numpy as jnp

    rng = np.random.RandomState(9)
    leaves_np = rng.randint(0, 256, (5, 16)).astype(np.uint8)
    root, proofs, mask = merkle_build_jax(jnp.asarray(leaves_np))
    bad_leaves = leaves_np.copy()
    bad_leaves[2, 0] ^= 1
    ok = merkle_verify_jax(
        jnp.asarray(bad_leaves),
        jnp.arange(5),
        jnp.broadcast_to(root, (5, 32)),
        proofs,
        jnp.asarray(mask),
    )
    ok = np.asarray(ok)
    assert bool(ok[0]) and bool(ok[1]) and not bool(ok[2])

"""ACS tests (reference: ``tests/subset.rs``): every correct node outputs the
same set of ≥ N−f contributions."""

import random

import pytest

from hbbft_tpu.netinfo import NetworkInfo
from hbbft_tpu.protocols.subset import Contribution, Done, Subset
from hbbft_tpu.sim import (
    NetBuilder,
    NullAdversary,
    RandomAdversary,
    ReorderingAdversary,
)

_INFO_CACHE = {}


def infos_for(n, seed=11):
    key = (n, seed)
    if key not in _INFO_CACHE:
        _INFO_CACHE[key] = NetworkInfo.generate_map(
            list(range(n)), random.Random(seed)
        )
    return _INFO_CACHE[key]


def run_subset(n, inputs, adversary):
    infos = infos_for(n)
    net = NetBuilder(list(range(n))).adversary(adversary).using_step(
        lambda nid: Subset(infos[nid], b"subset-test")
    )
    for nid, v in inputs.items():
        net.send_input(nid, v)
    net.run_to_quiescence()
    return net


def contributions(node):
    return {
        o.proposer_id: o.value for o in node.outputs if isinstance(o, Contribution)
    }


@pytest.mark.parametrize(
    "adv",
    [NullAdversary(), ReorderingAdversary(seed=2), RandomAdversary(seed=3)],
    ids=["null", "reordering", "random"],
)
@pytest.mark.parametrize("n", [1, 4])
def test_all_propose_all_agree(n, adv):
    inputs = {i: f"proposal-{i}".encode() for i in range(n)}
    net = run_subset(n, inputs, adv)
    f = (n - 1) // 3
    sets = []
    for nid in net.node_ids():
        node = net.nodes[nid]
        assert node.algorithm.terminated(), f"node {nid} not done"
        assert isinstance(node.outputs[-1], Done)
        contribs = contributions(node)
        assert len(contribs) >= n - f
        for pid, v in contribs.items():
            assert v == inputs[pid]
        sets.append(tuple(sorted(contribs.items())))
    assert len(set(sets)) == 1, "nodes disagree on the subset"


def test_one_silent_node_subset_excludes_it():
    n = 4
    inputs = {i: f"p{i}".encode() for i in range(n) if i != 3}  # node 3 silent
    net = run_subset(n, inputs, NullAdversary())
    for nid in net.node_ids():
        node = net.nodes[nid]
        assert node.algorithm.terminated()
        contribs = contributions(node)
        assert set(contribs.keys()) == {0, 1, 2}
        assert len(contribs) >= n - 1 - (n - 1) // 3


def test_subset_outputs_identical_across_seeds():
    n = 4
    inputs = {i: bytes([i]) * 30 for i in range(n)}
    reference = None
    for seed in range(3):
        net = run_subset(n, inputs, RandomAdversary(seed=seed))
        this = {
            nid: tuple(sorted(contributions(net.nodes[nid]).items()))
            for nid in net.node_ids()
        }
        vals = set(this.values())
        assert len(vals) == 1
        if reference is None:
            reference = vals


def test_all_at_end_strategy_single_completion_event():
    """AllAtEnd (reference builder knob ``SubsetHandlingStrategy``) releases
    every accepted contribution in the same step as Done — and the decided
    set matches the Incremental run exactly."""
    from hbbft_tpu.protocols.subset import SubsetHandlingStrategy

    n = 4
    infos = infos_for(n)
    inputs = {i: f"proposal-{i}".encode() for i in range(n)}

    def run(strategy):
        net = NetBuilder(list(range(n))).adversary(NullAdversary()).using_step(
            lambda nid: Subset(
                infos[nid], b"subset-strategy", handling_strategy=strategy
            )
        )
        for nid, v in inputs.items():
            net.send_input(nid, v)
        return net

    def crank_watching(net):
        """first-crank-with-a-Contribution and crank-of-Done per node."""
        first_contrib = {}
        done_at = {}
        crank = 0
        while net.queue:
            net.crank()
            crank += 1
            for nid in net.node_ids():
                outs = net.nodes[nid].outputs
                if nid not in first_contrib and any(
                    isinstance(o, Contribution) for o in outs
                ):
                    first_contrib[nid] = crank
                if nid not in done_at and any(
                    isinstance(o, Done) for o in outs
                ):
                    done_at[nid] = crank
        return first_contrib, done_at

    inc = run(SubsetHandlingStrategy.Incremental)
    ate = run(SubsetHandlingStrategy.AllAtEnd)
    fc_a, done_a = crank_watching(ate)
    fc_i, done_i = crank_watching(inc)
    for nid in ate.node_ids():
        node = ate.nodes[nid]
        assert node.algorithm.terminated()
        assert isinstance(node.outputs[-1], Done)
        # AllAtEnd: contributions appear in the same crank as Done
        assert fc_a[nid] == done_a[nid], (nid, fc_a[nid], done_a[nid])
        assert contributions(node) == contributions(inc.nodes[nid])
    # Incremental actually streams: at least one node saw a contribution
    # strictly before its Done
    assert any(fc_i[nid] < done_i[nid] for nid in inc.node_ids())

"""ACS tests (reference: ``tests/subset.rs``): every correct node outputs the
same set of ≥ N−f contributions."""

import random

import pytest

from hbbft_tpu.netinfo import NetworkInfo
from hbbft_tpu.protocols.subset import Contribution, Done, Subset
from hbbft_tpu.sim import (
    NetBuilder,
    NullAdversary,
    RandomAdversary,
    ReorderingAdversary,
)

_INFO_CACHE = {}


def infos_for(n, seed=11):
    key = (n, seed)
    if key not in _INFO_CACHE:
        _INFO_CACHE[key] = NetworkInfo.generate_map(
            list(range(n)), random.Random(seed)
        )
    return _INFO_CACHE[key]


def run_subset(n, inputs, adversary):
    infos = infos_for(n)
    net = NetBuilder(list(range(n))).adversary(adversary).using_step(
        lambda nid: Subset(infos[nid], b"subset-test")
    )
    for nid, v in inputs.items():
        net.send_input(nid, v)
    net.run_to_quiescence()
    return net


def contributions(node):
    return {
        o.proposer_id: o.value for o in node.outputs if isinstance(o, Contribution)
    }


@pytest.mark.parametrize(
    "adv",
    [NullAdversary(), ReorderingAdversary(seed=2), RandomAdversary(seed=3)],
    ids=["null", "reordering", "random"],
)
@pytest.mark.parametrize("n", [1, 4])
def test_all_propose_all_agree(n, adv):
    inputs = {i: f"proposal-{i}".encode() for i in range(n)}
    net = run_subset(n, inputs, adv)
    f = (n - 1) // 3
    sets = []
    for nid in net.node_ids():
        node = net.nodes[nid]
        assert node.algorithm.terminated(), f"node {nid} not done"
        assert isinstance(node.outputs[-1], Done)
        contribs = contributions(node)
        assert len(contribs) >= n - f
        for pid, v in contribs.items():
            assert v == inputs[pid]
        sets.append(tuple(sorted(contribs.items())))
    assert len(set(sets)) == 1, "nodes disagree on the subset"


def test_one_silent_node_subset_excludes_it():
    n = 4
    inputs = {i: f"p{i}".encode() for i in range(n) if i != 3}  # node 3 silent
    net = run_subset(n, inputs, NullAdversary())
    for nid in net.node_ids():
        node = net.nodes[nid]
        assert node.algorithm.terminated()
        contribs = contributions(node)
        assert set(contribs.keys()) == {0, 1, 2}
        assert len(contribs) >= n - 1 - (n - 1) // 3


def test_subset_outputs_identical_across_seeds():
    n = 4
    inputs = {i: bytes([i]) * 30 for i in range(n)}
    reference = None
    for seed in range(3):
        net = run_subset(n, inputs, RandomAdversary(seed=seed))
        this = {
            nid: tuple(sorted(contributions(net.nodes[nid]).items()))
            for nid in net.node_ids()
        }
        vals = set(this.values())
        assert len(vals) == 1
        if reference is None:
            reference = vals

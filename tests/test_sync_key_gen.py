"""DKG tests (reference: ``tests/sync_key_gen.rs``): run the protocol among
n parties in-process, then sign/decrypt with the generated shares."""

import random

import pytest

from hbbft_tpu.crypto import tc
from hbbft_tpu.protocols.sync_key_gen import Ack, Part, SyncKeyGen


def run_dkg(n, t, rng, dealers=None, observer=False):
    ids = list(range(n))
    sec_keys = {i: tc.SecretKey.random(rng) for i in ids}
    pub_keys = {i: sk.public_key() for i, sk in sec_keys.items()}
    nodes = {
        i: SyncKeyGen(i, sec_keys[i], pub_keys, t, random.Random(rng.getrandbits(64)))
        for i in ids
    }
    if observer:
        nodes["obs"] = SyncKeyGen(
            "obs", tc.SecretKey.random(rng), pub_keys, t, rng
        )
    dealers = dealers if dealers is not None else ids
    # deal parts, everyone handles them in the same order, acks likewise
    acks = []
    for d in dealers:
        part = nodes[d].generate_part()
        for nid, node in nodes.items():
            outcome = node.handle_part(d, part)
            assert outcome.fault is None, (nid, d, outcome.fault)
            if outcome.ack is not None:
                acks.append((nid, outcome.ack))
    for acker, ack in acks:
        for nid, node in nodes.items():
            outcome = node.handle_ack(acker, ack)
            assert outcome.fault is None, (nid, acker, outcome.fault)
    return nodes


@pytest.mark.parametrize("n,t", [(4, 1), (7, 2)])
def test_dkg_roundtrip(n, t, rng):
    nodes = run_dkg(n, t, rng)
    assert all(node.is_ready() for node in nodes.values())
    results = {i: nodes[i].generate() for i in range(n)}
    pk_sets = {r[0].to_bytes() for r in results.values()}
    assert len(pk_sets) == 1, "nodes derived different public key sets"
    pks = results[0][0]
    assert pks.threshold() == t
    # threshold signature with the generated shares
    msg = b"post-dkg signing"
    shares = {
        i: results[i][1].sign(msg) for i in range(t + 1)
    }
    sig = pks.combine_signatures(shares)
    assert pks.verify_signature(sig, msg)
    # share indices must line up with PublicKeySet.public_key_share
    for i in range(n):
        assert pks.verify_signature_share(i, results[i][1].sign(msg), msg)
    # TPKE with the generated keys
    ct = pks.public_key().encrypt(b"secret", rng)
    dshares = {i: results[i][1].decrypt_share(ct) for i in (0, n - 1)}
    if t == 1:
        assert pks.decrypt(dshares, ct) == b"secret"


def test_dkg_subset_of_dealers(rng):
    """Only t+1 dealers deal — still ready, same keys on all nodes."""
    n, t = 4, 1
    nodes = run_dkg(n, t, rng, dealers=[1, 3])
    assert all(node.is_ready() for node in nodes.values())
    pk_sets = {nodes[i].generate()[0].to_bytes() for i in range(n)}
    assert len(pk_sets) == 1


def test_dkg_observer_follows(rng):
    """A non-member observer tracks the DKG and derives the public keys."""
    n, t = 4, 1
    nodes = run_dkg(n, t, rng, observer=True)
    obs = nodes["obs"]
    assert obs.is_ready()
    pks_obs, share = obs.generate()
    assert share is None  # observers get no secret share
    assert pks_obs.to_bytes() == nodes[0].generate()[0].to_bytes()


def test_dkg_bad_part_detected(rng):
    n, t = 4, 1
    ids = list(range(n))
    sec_keys = {i: tc.SecretKey.random(rng) for i in ids}
    pub_keys = {i: sk.public_key() for i, sk in sec_keys.items()}
    node0 = SyncKeyGen(0, sec_keys[0], pub_keys, t, rng)
    node1 = SyncKeyGen(1, sec_keys[1], pub_keys, t, rng)
    part = node1.generate_part()
    # tamper: swap two encrypted rows → row check must fail somewhere
    bad = Part(part.commitment, (part.rows[1], part.rows[0]) + part.rows[2:])
    outcome = node0.handle_part(1, bad)
    assert outcome.fault is not None


def test_dkg_not_ready_raises(rng):
    n, t = 4, 1
    ids = list(range(n))
    sec_keys = {i: tc.SecretKey.random(rng) for i in ids}
    pub_keys = {i: sk.public_key() for i, sk in sec_keys.items()}
    node0 = SyncKeyGen(0, sec_keys[0], pub_keys, t, rng)
    with pytest.raises(ValueError):
        node0.generate()

"""ThresholdSign protocol tests (reference: ``tests/threshold_sign.rs``)."""

import random

import pytest

from hbbft_tpu.crypto import tc
from hbbft_tpu.fault_log import FaultKind
from hbbft_tpu.netinfo import NetworkInfo
from hbbft_tpu.protocols.threshold_sign import ThresholdSign, ThresholdSignMessage
from hbbft_tpu.sim import NetBuilder, NullAdversary, RandomAdversary


def run_sign(n, adversary, doc=b"sign me", optimistic=True, rng_seed=1):
    rng = random.Random(rng_seed)
    infos = NetworkInfo.generate_map(list(range(n)), rng)
    net = NetBuilder(list(range(n))).adversary(adversary).using_step(
        lambda nid: ThresholdSign(infos[nid], optimistic=optimistic)
    )
    for nid in net.node_ids():
        net.send_input(nid, doc)
    net.run_to_quiescence()
    return net


@pytest.mark.parametrize("n", [1, 4, 7])
@pytest.mark.parametrize("optimistic", [True, False])
def test_all_nodes_same_signature(n, optimistic):
    net = run_sign(n, NullAdversary(), optimistic=optimistic)
    sigs = [net.nodes[nid].outputs for nid in net.node_ids()]
    assert all(len(s) == 1 for s in sigs)
    assert len({s[0].to_bytes() for s in sigs}) == 1  # unique signature
    assert all(net.nodes[nid].algorithm.terminated() for nid in net.node_ids())


def test_random_schedule():
    net = run_sign(4, RandomAdversary(seed=9, dup_prob=0.1))
    sigs = {net.nodes[nid].outputs[0].to_bytes() for nid in net.node_ids()}
    assert len(sigs) == 1


def test_share_before_document_is_buffered(rng):
    infos = NetworkInfo.generate_map([0, 1, 2, 3], rng)
    ts0 = ThresholdSign(infos[0])
    ts1 = ThresholdSign(infos[1])
    step1 = ts1.handle_input(b"doc")
    share_msg = step1.messages[0].message
    # deliver to ts0 before it knows the document
    assert ts0.handle_message(1, share_msg).output == []
    # now set the document: the buffered share is processed
    ts0.handle_input(b"doc")
    assert len(ts0.shares) >= 2  # own + buffered


def test_invalid_share_is_faulted_and_excluded(rng):
    infos = NetworkInfo.generate_map([0, 1, 2, 3], rng)
    ts0 = ThresholdSign(infos[0])
    ts0.handle_input(b"doc")
    # node 1 sends garbage (a share signed with the wrong key)
    bad = infos[1].secret_key_share()  # valid key...
    wrong = tc.SecretKeyShare(12345)  # ...but sign with junk
    step = ts0.handle_message(1, ThresholdSignMessage(wrong.sign(b"doc")))
    # optimistic: combine of {0,1} fails -> fallback evicts node 1
    assert any(
        f.node_id == 1 and f.kind == FaultKind.InvalidSignatureShare
        for f in step.fault_log
    )
    assert ts0.signature is None
    # two more honest shares arrive -> signature completes
    for nid in (2, 3):
        share = infos[nid].secret_key_share().sign(b"doc")
        step = ts0.handle_message(nid, ThresholdSignMessage(share))
    assert ts0.signature is not None
    assert infos[0].public_key_set().verify_signature(ts0.signature, b"doc")

"""Array-mode DynamicHoneyBadger vs the object-mode state machines.

The batched driver must mirror ``dynamic_honey_badger.rs`` semantics: votes
commit through consensus, a winning node-change starts a DKG whose
Parts/Acks ride contributions, the era-completing batch reports
``Complete``, and the rotated era runs real threshold crypto under the NEW
key set (add and remove scenarios).  Cross-mode: user contributions and
change progression must match the object-mode network driven with the same
inputs.
"""

import random

import pytest

jax = pytest.importorskip("jax")

from hbbft_tpu.crypto import tc
from hbbft_tpu.netinfo import NetworkInfo
from hbbft_tpu.parallel.dhb import BatchedDynamicHoneyBadger
from hbbft_tpu.protocols.dynamic_honey_badger import (
    Change,
    ChangeInput,
    DhbBatch,
    DynamicHoneyBadger,
    UserInput,
)
from hbbft_tpu.protocols.honey_badger import EncryptionSchedule
from hbbft_tpu.sim import NetBuilder, NullAdversary


def god_view(n, seed=31):
    infos = NetworkInfo.generate_map(list(range(n)), random.Random(seed))
    return BatchedDynamicHoneyBadger(
        infos, session_id=b"dhb-arr", rng=random.Random(77)
    )


def test_plain_epochs_no_change():
    dhb = god_view(4)
    for e in range(2):
        contribs = {nid: b"user-%d-%d" % (nid, e) for nid in dhb.validators}
        batch = dhb.run_epoch(contribs)
        assert batch.era == 0 and batch.epoch == e
        assert batch.change.state == "none"
        assert dict(batch.contributions) == contribs


def test_remove_validator_rotates_era_and_new_era_commits(dkg_remove_run):
    run = dkg_remove_run  # ONE shared rotation (conftest session fixture)
    b0 = run["b0"]
    # votes committed in epoch 0; the DKG starts with that batch, so the
    # change is at least InProgress from here on
    assert b0.change.state in ("in_progress", "complete")
    final = run["final"]
    assert final.change.change.kind == "nodes"
    assert sorted(final.change.change.key_map()) == [0, 1, 2]
    assert run["era"] == 1
    assert run["era1_validators"] == [0, 1, 2]
    # era-1 threshold keys are REAL: a full TPKE epoch committed under them
    b1 = run["era1_batch"]
    assert b1.era == 1 and dict(b1.contributions) == run["era1_contribs"]


@pytest.mark.slow
def test_add_validator_via_dkg(dkg_add_run):
    # ONE shared rotation (conftest session fixture) — and this test is
    # its only consumer, so tiering it out drops the fixture's ~106 s
    # too.  The remove rotation + cross-mode equality pair stays tier 1.
    run = dkg_add_run
    final = run["final"]
    assert sorted(final.change.change.key_map()) == [0, 1, 2, 3, 4]
    assert run["era"] == 1
    assert run["era1_validators"] == [0, 1, 2, 3, 4]
    # the joiner is a full validator: era-1 epoch includes its contribution
    assert dict(run["era1_batch"].contributions)[4] == b"era1-4"
    # a JoinPlan would have been available at the boundary semantics-wise;
    # once the era has batches it must refuse
    assert isinstance(run["join_plan_error"], ValueError)


def test_encryption_schedule_change_no_dkg():
    dhb = god_view(4, seed=9)
    for voter in range(4):
        dhb.vote_for_encryption_schedule(
            voter, EncryptionSchedule.every_nth_epoch(2)
        )
    batch = dhb.run_epoch({nid: b"p" for nid in dhb.validators})
    assert batch.change.state == "complete"
    assert batch.change.change.kind == "encryption_schedule"
    assert dhb.era == 1  # rotated without a DKG
    # the committed schedule is installed, drives the epochs, and rides
    # the JoinPlan (mirrors dynamic_honey_badger._try_rotate_era)
    assert (dhb.encryption_schedule.kind, dhb.encryption_schedule.a) == \
        ("nth", 2)
    assert dhb.join_plan().encryption_schedule == ("nth", 2, 0)
    b1 = dhb.run_epoch({nid: b"q" for nid in dhb.validators})
    assert b1.era == 1


@pytest.mark.slow
def test_missing_candidate_key_raises_recoverably(shared_netinfo):
    """A winning add-vote whose candidate key the god view lacks raises,
    but must not half-start the change (change_state stays none, so
    supplying the key afterwards lets the driver proceed to rotation).
    The raise-then-recover sequence must complete — stale state from the
    aborted epoch (winner, key_gens) poisoning the late-keyed DKG is
    exactly what this guards, so the rotation runs to the end."""
    dhb = BatchedDynamicHoneyBadger(
        shared_netinfo(4, 13), session_id=b"dhb-arr", rng=random.Random(77)
    )
    rng = random.Random(1)
    stranger_sk = tc.SecretKey.random(rng)
    for voter in range(4):
        dhb.vote_to_add(voter, 9, stranger_sk.public_key())  # key withheld
    with pytest.raises(ValueError, match="secret keys"):
        dhb.run_epoch({nid: b"x" for nid in dhb.validators})
    assert dhb.change_state.state == "none"  # not wedged half-started
    # recover: hand the god view the candidate's key and keep going — the
    # next epoch re-computes the winner and starts the DKG for real
    dhb.secret_keys[9] = stranger_sk
    b1 = dhb.run_epoch({nid: b"y" for nid in dhb.validators})
    assert b1.change.state == "in_progress"
    assert dhb.key_gens is not None and 9 in dhb.key_gens
    final = dhb.run_until_change_completes()
    assert final.change.state == "complete"
    assert dhb.era == 1 and 9 in dhb.validators


def test_cross_mode_remove_matches_object_network(
    shared_netinfo, dkg_remove_run
):
    """Same inputs, both modes: per-epoch user contributions and the
    change progression must agree (key BYTES differ — each mode's DKG
    draws its own polynomials — so compare key-set membership).  The
    array side is the session-shared ``dkg_remove_run`` (identical inputs:
    n=4 seed=31, everyone removes node 3, epoch-0 payloads ``e0-<nid>``)."""
    n, seed = 4, 31
    infos = shared_netinfo(n, seed)
    sec = {nid: infos[nid].secret_key() for nid in infos}

    # object mode
    net = NetBuilder(list(range(n))).using_step(
        lambda nid: DynamicHoneyBadger(
            infos[nid], sec[nid], rng=random.Random(5000 + nid),
            encryption_schedule=EncryptionSchedule.always(),
        )
    )
    keep = {k: infos[0].public_key(k) for k in (0, 1, 2)}
    for nid in net.node_ids():
        net.send_input(nid, ChangeInput(Change.node_change(dict(keep))))
    payload = lambda nid: b"e0-%d" % nid
    # user payloads commit in epoch 0; afterwards both modes drive the DKG
    # with empty contributions (object mode's auto-pipeline proposes b"")
    for nid in net.node_ids():
        net.send_input(nid, UserInput(payload(nid)))
    net.run_to_quiescence()
    for _ in range(6):
        obj_batches = [
            o for o in net.nodes[0].outputs if isinstance(o, DhbBatch)
        ]
        if any(b.change.state == "complete" for b in obj_batches):
            break
        for nid in net.node_ids():
            if net.nodes[nid].algorithm.is_validator():
                net.send_input(nid, UserInput(b""))
        net.run_to_quiescence()
    obj_batches = [
        o for o in net.nodes[0].outputs if isinstance(o, DhbBatch)
    ]
    assert any(b.change.state == "complete" for b in obj_batches)

    # array mode: same vote, same epoch-0 payloads, then empty epochs —
    # the shared session run (era-0 slice; the fixture's era-1 epoch is
    # outside the object-mode comparison window)
    arr_batches = [b for b in dkg_remove_run["batches"] if b.era == 0]

    # the first Complete batch must carry the same change in both modes
    obj_done = next(b for b in obj_batches if b.change.state == "complete")
    arr_done = next(b for b in arr_batches if b.change.state == "complete")
    assert obj_done.change.change.kind == arr_done.change.change.kind
    assert sorted(obj_done.change.change.key_map()) == \
        sorted(arr_done.change.change.key_map()) == [0, 1, 2]
    # era-0 contributions agree epoch for epoch where both committed:
    # user payloads at epoch 0, empty DKG-pipeline batches afterwards
    obj_map = {
        (b.era, b.epoch): dict(b.contributions)
        for b in obj_batches if b.era == 0
    }
    arr_map = {
        (b.era, b.epoch): dict(b.contributions)
        for b in arr_batches if b.era == 0
    }
    common = sorted(set(obj_map) & set(arr_map))
    assert (0, 0) in common
    for key in common:
        assert obj_map[key] == arr_map[key], key


@pytest.mark.slow
def test_queueing_over_dynamic_membership(shared_netinfo):
    """The composed top-of-stack driver: transactions drain across an era
    boundary while a validator is voted out mid-run; every tx in a
    remaining validator's queue commits exactly once.  (3 txs/node keeps
    the drain loop to the epochs the era-crossing semantics need — each
    extra epoch re-traces the batched-ACS graph for its payload shape.)"""
    from hbbft_tpu.parallel.qhb import BatchedQueueingDynamicHoneyBadger

    infos = shared_netinfo(4, 21)
    q = BatchedQueueingDynamicHoneyBadger(
        infos, batch_size=3, session_id=b"qdhb-t", rng=random.Random(9)
    )
    rng = random.Random(5)
    keepers_txs = set()
    for nid in range(4):
        for j in range(3):
            tx = b"tx|%d|%d|%d" % (nid, j, rng.getrandbits(32))
            q.push(nid, tx)
            if nid != 3:
                keepers_txs.add(tx)
    # one normal epoch, then vote node 3 out and keep draining
    q.run_epoch(random.Random(50))
    for voter in range(4):
        q.vote_to_remove(voter, 3)
    for e in range(12):
        q.run_epoch(random.Random(60 + e))
        if q.dhb.era == 1 and q.pending() == 0:
            break
    assert q.dhb.era == 1
    assert sorted(q.dhb.validators) == [0, 1, 2]
    assert q.pending() == 0
    # every keeper tx committed exactly once; era-0 proposals from node 3
    # may have committed before its removal, never after
    committed = set(q.committed)
    assert keepers_txs <= committed
    assert len(q.committed) == len(committed)
    # the ledger keeps working in era 1
    q.push(0, b"era1-tx")
    q.run_epoch(random.Random(99))
    assert b"era1-tx" in q.committed


def test_vote_majority_property():
    """Hypothesis sweep of the vote rule on the array driver: a change wins
    (and the era rotates) iff a STRICT majority of current validators
    committed a vote for it — the ``votes.rs`` rule."""
    pytest.importorskip("hypothesis")
    from hypothesis import HealthCheck, given, settings
    from hypothesis import strategies as st

    n = 4
    infos = NetworkInfo.generate_map(list(range(n)), random.Random(61))

    @settings(
        max_examples=6, deadline=None,
        suppress_health_check=[HealthCheck.too_slow],
    )
    @given(voters=st.sets(st.integers(0, n - 1)))
    def sweep(voters):
        dhb = BatchedDynamicHoneyBadger(
            infos, session_id=b"prop-%d" % len(voters),
            rng=random.Random(7),
        )
        for v in sorted(voters):
            dhb.vote_to_remove(v, 3)
        b0 = dhb.run_epoch({nid: b"x" for nid in dhb.validators})
        if 2 * len(voters) > n:  # strict majority: the DKG starts
            assert b0.change.state in ("in_progress", "complete")
            if b0.change.state != "complete":
                dhb.run_until_change_completes()
            assert dhb.era == 1 and sorted(dhb.validators) == [0, 1, 2]
        else:
            assert b0.change.state == "none"
            assert dhb.change_state.state == "none" and dhb.era == 0

    sweep()

"""Tracing, cost model, snapshot/resume, builders (SURVEY §5 aux systems)."""

import random

import pytest

from hbbft_tpu.netinfo import NetworkInfo
from hbbft_tpu.protocols.dynamic_honey_badger import DynamicHoneyBadger
from hbbft_tpu.protocols.honey_badger import (
    Batch,
    EncryptionSchedule,
    HoneyBadger,
)
from hbbft_tpu.protocols.queueing_honey_badger import (
    QhbBatch,
    QueueingHoneyBadger,
    TxInput,
)
from hbbft_tpu.sim import CostModel, EventLog, NetBuilder, NullAdversary
from hbbft_tpu.snapshot import load_arrays, restore, save_arrays, snapshot

_INFO_CACHE = {}


def infos_for(n, seed=13):
    key = (n, seed)
    if key not in _INFO_CACHE:
        _INFO_CACHE[key] = NetworkInfo.generate_map(
            list(range(n)), random.Random(seed)
        )
    return _INFO_CACHE[key]


def hb_net(n, trace=None, cost=None):
    infos = infos_for(n)
    b = NetBuilder(list(range(n))).adversary(NullAdversary())
    if trace is not None:
        b = b.trace(trace)
    if cost is not None:
        b = b.cost_model(cost)
    return b.using_step(
        lambda nid: HoneyBadger.builder(infos[nid])
        .session_id(b"obs")
        .encryption_schedule(EncryptionSchedule.always())
        .rng(random.Random(1000 + nid))
        .build()
    )


def test_event_log_records_every_delivery_with_wire_sizes():
    trace = EventLog()
    net = hb_net(4, trace=trace)
    for nid in net.node_ids():
        net.send_input(nid, b"obs-%d" % nid)
    net.run_to_quiescence()
    assert len(trace) == net.messages_delivered > 100
    by_type = trace.messages_by_type()
    assert any(k.startswith("SubsetWrap/") for k in by_type)
    assert any(k.startswith("DecryptionShareWrap/") for k in by_type)
    assert trace.total_bytes() > 0
    # every event has a positive wire size (all protocol messages encode)
    assert all(ev.wire_bytes > 0 for ev in trace.events)


def test_cost_model_virtual_clock_monotone_and_scaled():
    cost = CostModel(bandwidth_bps=1e9, cpu_lag_s=1e-5)
    net = hb_net(4, cost=cost)
    for nid in net.node_ids():
        net.send_input(nid, b"c-%d" % nid)
    net.run_to_quiescence()
    vt_fast = net.virtual_time
    assert vt_fast > 0
    # a 10× slower network must cost strictly more virtual time
    slow = CostModel(bandwidth_bps=1e8, cpu_lag_s=1e-5)
    net2 = hb_net(4, cost=slow)
    for nid in net2.node_ids():
        net2.send_input(nid, b"c-%d" % nid)
    net2.run_to_quiescence()
    assert net2.virtual_time > vt_fast


def test_honey_badger_snapshot_resume_mid_epoch():
    """Snapshot a node mid-protocol; replay the rest of its traffic into
    the restored copy: it must commit the SAME batch as the original."""
    n = 4
    net = hb_net(n)
    for nid in net.node_ids():
        net.send_input(nid, b"snap-%d" % nid)
    for _ in range(40):  # stop mid-epoch
        net.crank()
    frozen = snapshot(net.nodes[2].algorithm)
    # continue the original, recording everything delivered to node 2
    replay = []
    while net.queue:
        m = net.crank()
        if m is not None and m.to == 2:
            replay.append((m.sender, m.payload))
    want = [o for o in net.nodes[2].outputs if isinstance(o, Batch)]
    assert len(want) == 1

    # the thawed copy, fed the same messages, commits the same batch
    thawed = restore(frozen)
    got = []
    for sender, payload in replay:
        step = thawed.handle_message(sender, payload)
        got.extend(o for o in step.output if isinstance(o, Batch))
    assert got == want


def test_qhb_snapshot_roundtrip_preserves_queue_and_provider():
    infos = infos_for(4)
    dhb = DynamicHoneyBadger(infos[1], infos[1].secret_key(),
                             rng=random.Random(5))
    qhb = QueueingHoneyBadger(dhb, batch_size=10, rng=random.Random(6))
    qhb.handle_input(TxInput(b"tx-a"))
    qhb.handle_input(TxInput(b"tx-b"))
    q2 = restore(snapshot(qhb))
    assert q2.dhb.contribution_provider is not None
    assert sorted(q2.queue._txs) == [b"tx-a", b"tx-b"]


def test_batched_state_npz_roundtrip():
    jax = pytest.importorskip("jax")
    import numpy as np

    from hbbft_tpu.parallel.aba import BatchedAba

    aba = BatchedAba(4, 1)
    st = aba.init_state(np.ones((4, 4), bool))
    st = jax.jit(aba.epoch_step)(st, np.zeros(4, bool))
    blob = save_arrays(st)
    back = load_arrays(blob)
    for k in st:
        np.testing.assert_array_equal(back[k], np.asarray(st[k]))


def test_builders_mirror_reference_knobs():
    infos = infos_for(4)
    dhb = (
        DynamicHoneyBadger.builder(infos[0], infos[0].secret_key())
        .era(2)
        .max_future_epochs(7)
        .encryption_schedule(EncryptionSchedule.every_nth_epoch(3))
        .rng(random.Random(9))
        .build()
    )
    assert dhb.era == 2 and dhb.max_future_epochs == 7
    qhb = (
        QueueingHoneyBadger.builder(dhb)
        .batch_size(33)
        .rng(random.Random(10))
        .build()
    )
    assert qhb.batch_size == 33 and qhb.dhb is dhb


def test_batched_epoch_cost_estimate_scales():
    """The analytic bulk-synchronous epoch estimate behaves like the
    hardware model: more nodes / epochs / bytes / lag ⇒ more virtual time."""
    from hbbft_tpu.sim import CostModel

    cm = CostModel(bandwidth_bps=1e9, cpu_lag_s=1e-5)
    base = cm.batched_epoch_estimate(16, 5, 256, aba_epochs=3)
    assert base > 0
    assert cm.batched_epoch_estimate(64, 21, 256, 3) > base
    assert cm.batched_epoch_estimate(16, 5, 256, 9) > base
    assert cm.batched_epoch_estimate(16, 5, 4096, 3) > base
    slow = CostModel(bandwidth_bps=1e6, cpu_lag_s=1e-5)
    assert slow.batched_epoch_estimate(16, 5, 256, 3) > base


def test_batched_dynamic_driver_snapshot_mid_dkg():
    """Array-mode checkpoint/resume (§5): freeze the composed queueing +
    dynamic-membership driver MID-DKG, restore it, and drive both copies
    forward with the same seeds — identical ledgers, eras, and validator
    sets (the jit handles and the queue lock rebuild on restore)."""
    import random

    from hbbft_tpu.netinfo import NetworkInfo
    from hbbft_tpu.parallel.qhb import BatchedQueueingDynamicHoneyBadger

    infos = NetworkInfo.generate_map(list(range(4)), random.Random(41))
    q = BatchedQueueingDynamicHoneyBadger(
        infos, batch_size=2, session_id=b"snap-qdhb", rng=random.Random(3)
    )
    r = random.Random(6)
    for nid in range(3):
        for j in range(3):
            q.push(nid, b"s|%d|%d|%d" % (nid, j, r.getrandbits(32)))
    for voter in range(4):
        q.vote_to_remove(voter, 3)
    q.run_epoch(random.Random(70))  # commits the votes; DKG in flight
    assert q.dhb.change_state.state == "in_progress"

    frozen = snapshot(q)
    q2 = restore(frozen)
    for e in range(8):
        a = q.run_epoch(random.Random(80 + e))
        b = q2.run_epoch(random.Random(80 + e))
        assert a == b, e
        if q.dhb.era == 1 and q.pending() == 0:
            break
    assert q.dhb.era == q2.dhb.era == 1
    assert q.committed == q2.committed
    assert sorted(q.dhb.validators) == sorted(q2.dhb.validators) == [0, 1, 2]
    # the restored copy's rotated keys are REAL too: another epoch commits
    q2.push(0, b"post-restore")
    q2.run_epoch(random.Random(99))
    assert b"post-restore" in q2.committed

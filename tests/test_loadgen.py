"""Open-loop load generator (net/loadgen.py) against in-process nodes.

Tier 1 keeps one short real-socket run: a LocalCluster plus a few
ack-paced clients for ~1.5 s of offered load, asserting the report's
accounting identity (offered = submitted + local drops ≥ shed +
committed) and that commits actually landed.  Saturation behavior is
exercised by the slow-marked bench sweep, not here.
"""

import asyncio

import pytest

from hbbft_tpu.net.cluster import ClusterConfig, LocalCluster
from hbbft_tpu.net.loadgen import LoadGenerator, LoadShape
from hbbft_tpu.obs.metrics import Registry

LOADGEN_TIMEOUT_S = 60


def test_make_wave_unique_and_sized():
    gen = LoadGenerator([("127.0.0.1", 1)], b"x", LoadShape(
        tx_bytes=64, wave_txs=8))
    w0 = gen._make_wave(0, 0)
    w1 = gen._make_wave(1, 0)
    assert len(w0) == 8 and all(len(tx) == 64 for tx in w0)
    assert len({bytes(tx) for tx in w0 + w1}) == 16, "digests must differ"


def test_open_loop_against_local_cluster():
    async def scenario():
        cfg = ClusterConfig(n=4, seed=33, batch_size=8, max_tx_bytes=4096)
        cluster = LocalCluster(cfg)
        await cluster.start()
        try:
            reg = Registry()
            shape = LoadShape(tx_bytes=64, clients=3, wave_txs=4,
                              duration_s=1.5, drain_s=10.0)
            gen = LoadGenerator(
                [cluster.addrs[nid] for nid in range(cfg.n)],
                cfg.cluster_id, shape, registry=reg)
            report = await gen.run()
        finally:
            await cluster.stop()
        return reg, report

    async def capped():
        return await asyncio.wait_for(scenario(), LOADGEN_TIMEOUT_S)

    reg, report = asyncio.run(capped())
    assert report["committed_txs"] > 0
    assert report["tx_per_s"] > 0 and report["mb_per_s"] > 0
    # accounting identity: everything offered was either written to a
    # socket or dropped locally, and nothing committed that wasn't offered
    assert report["offered_txs"] == (
        report["submitted_txs"] + report["local_drops"])
    assert report["committed_txs"] + report["shed_txs"] \
        <= report["offered_txs"]
    # the same numbers are scrapeable from the registry
    by_name = {m.name: m for m in reg.collect()}
    assert int(by_name["hbbft_load_offered_txs_total"].total()) \
        == report["offered_txs"]
    assert int(by_name["hbbft_load_committed_txs_total"].total()) \
        == report["committed_txs"]
    assert report["p50_ms"] > 0


def test_build_schedule_deterministic_bounded_triangular():
    """`--schedule` stages are a pure function of the seed: same seed →
    identical stages (the closed-loop controller bench's replay
    contract), jitter clamped inside [base, peak], directions forming
    the up-then-down triangular ramp."""
    from hbbft_tpu.net.loadgen import build_schedule

    a = build_schedule(7)
    assert a == build_schedule(7)
    assert [s["stage"] for s in a] == list(range(6))
    assert [s["direction"] for s in a] == ["up"] * 3 + ["down"] * 3
    assert all(4 <= s["clients"] <= 32 for s in a)
    assert all(s["waves"] == 2 for s in a)
    # ramp endpoints touch the base, the crest reaches toward the peak
    assert a[0]["clients"] == 4 and a[-1]["clients"] == 4
    assert max(s["clients"] for s in a) >= 24
    # a different seed jitters different points
    assert build_schedule(8) != a

    narrow = build_schedule(3, stages=4, base_clients=2,
                            peak_clients=8, waves_per_client=5)
    assert len(narrow) == 4
    assert all(2 <= s["clients"] <= 8 and s["waves"] == 5
               for s in narrow)

    with pytest.raises(ValueError):
        build_schedule(7, stages=0)
    with pytest.raises(ValueError):
        build_schedule(7, base_clients=8, peak_clients=4)


def test_run_schedule_attaches_ctrl_probe_per_stage(monkeypatch):
    """With a probe wired (`--max-boost`), every stage's summary
    carries the controller scrape taken right after that stage's load —
    the closed-loop evidence BENCH_CTRL records; without one, the
    stages stay probe-free."""
    from hbbft_tpu.net import loadgen

    calls = []

    def fake_run_load(addrs, cluster_id, shape):
        calls.append((shape.clients, shape.burst_waves, shape.salt))
        return {"offered_txs": 10, "committed_txs": 10, "shed_txs": 0,
                "tx_per_s": 100.0, "wall_s": 0.1, "p50_ms": 1.0,
                "p99_ms": 2.0}

    monkeypatch.setattr(loadgen, "run_load", fake_run_load)
    schedule = loadgen.build_schedule(7, stages=3, base_clients=2,
                                      peak_clients=6)
    shape = loadgen.LoadShape(tx_bytes=64, clients=1)
    ticks = iter(range(100))
    probe = lambda: [{"node": 0, "boost": next(ticks)}]  # noqa: E731
    stages = loadgen.run_schedule([("h", 1)], b"cid", shape, schedule,
                                  probe=probe)
    assert [s["ctrl"][0]["boost"] for s in stages] == [0, 1, 2]
    # each stage ran at its scheduled level with a disjoint dedup salt
    assert [c[0] for c in calls] == [s["clients"] for s in schedule]
    assert len({c[2] for c in calls}) == len(schedule)

    bare = loadgen.run_schedule([("h", 1)], b"cid", shape, schedule)
    assert all("ctrl" not in s for s in bare)

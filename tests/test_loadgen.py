"""Open-loop load generator (net/loadgen.py) against in-process nodes.

Tier 1 keeps one short real-socket run: a LocalCluster plus a few
ack-paced clients for ~1.5 s of offered load, asserting the report's
accounting identity (offered = submitted + local drops ≥ shed +
committed) and that commits actually landed.  Saturation behavior is
exercised by the slow-marked bench sweep, not here.
"""

import asyncio

import pytest

from hbbft_tpu.net.cluster import ClusterConfig, LocalCluster
from hbbft_tpu.net.loadgen import LoadGenerator, LoadShape
from hbbft_tpu.obs.metrics import Registry

LOADGEN_TIMEOUT_S = 60


def test_make_wave_unique_and_sized():
    gen = LoadGenerator([("127.0.0.1", 1)], b"x", LoadShape(
        tx_bytes=64, wave_txs=8))
    w0 = gen._make_wave(0, 0)
    w1 = gen._make_wave(1, 0)
    assert len(w0) == 8 and all(len(tx) == 64 for tx in w0)
    assert len({bytes(tx) for tx in w0 + w1}) == 16, "digests must differ"


def test_open_loop_against_local_cluster():
    async def scenario():
        cfg = ClusterConfig(n=4, seed=33, batch_size=8, max_tx_bytes=4096)
        cluster = LocalCluster(cfg)
        await cluster.start()
        try:
            reg = Registry()
            shape = LoadShape(tx_bytes=64, clients=3, wave_txs=4,
                              duration_s=1.5, drain_s=10.0)
            gen = LoadGenerator(
                [cluster.addrs[nid] for nid in range(cfg.n)],
                cfg.cluster_id, shape, registry=reg)
            report = await gen.run()
        finally:
            await cluster.stop()
        return reg, report

    async def capped():
        return await asyncio.wait_for(scenario(), LOADGEN_TIMEOUT_S)

    reg, report = asyncio.run(capped())
    assert report["committed_txs"] > 0
    assert report["tx_per_s"] > 0 and report["mb_per_s"] > 0
    # accounting identity: everything offered was either written to a
    # socket or dropped locally, and nothing committed that wasn't offered
    assert report["offered_txs"] == (
        report["submitted_txs"] + report["local_drops"])
    assert report["committed_txs"] + report["shed_txs"] \
        <= report["offered_txs"]
    # the same numbers are scrapeable from the registry
    by_name = {m.name: m for m in reg.collect()}
    assert int(by_name["hbbft_load_offered_txs_total"].total()) \
        == report["offered_txs"]
    assert int(by_name["hbbft_load_committed_txs_total"].total()) \
        == report["committed_txs"]
    assert report["p50_ms"] > 0

"""MXU-formulated 8-bit-digit field vs the pure-Python host oracle.

Contract under test: for inputs within the lazy invariant (49 int32 digits,
each in [0, 256], arbitrary residue), every op returns digits within the
invariant whose value is ≡ the exact field result (mod p).  Exactness is
checked value-for-value — one wrong f32 rounding or carry anywhere breaks
equality.
"""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from hbbft_tpu.crypto import bls12_381 as H
from hbbft_tpu.ops import fp381_mxu as M


def _rand_digit_arrays(rng, b):
    """Adversarial lazy inputs: uniform digits over the FULL invariant
    [0, 256] (256 inclusive — unreachable from int conversion, reachable
    from rough carries)."""
    return rng.integers(0, 257, size=(b, M.NL)).astype(np.int32)


def _vals(arr):
    return [M.limbs_to_int(row) for row in np.asarray(arr)]


def _check_invariant(arr):
    a = np.asarray(arr)
    assert a.min() >= 0 and a.max() <= 256, (a.min(), a.max())


@pytest.mark.parametrize("op,ref", [
    ("fp_mul", lambda x, y: x * y),
    ("fp_add", lambda x, y: x + y),
    ("fp_sub", lambda x, y: x - y),
])
def test_ops_exact_on_adversarial_lazy_inputs(op, ref):
    rng = np.random.default_rng(hash(op) % 2**32)
    B = 64
    a = _rand_digit_arrays(rng, B)
    b = _rand_digit_arrays(rng, B)
    # mix in structured edges: zero, one, p-1, all-255, all-256
    edges = np.stack([
        M.int_to_limbs(0),
        M.int_to_limbs(1),
        M.int_to_limbs(H.P - 1),
        np.full(M.NL, 255, dtype=np.int32),
        np.full(M.NL, 256, dtype=np.int32),
    ])
    a[:5] = edges
    b[:5] = edges[::-1]
    out = jax.jit(getattr(M, op))(jnp.asarray(a), jnp.asarray(b))
    _check_invariant(out)
    av, bv, ov = _vals(a), _vals(b), _vals(out)
    for i in range(B):
        assert ov[i] % H.P == ref(av[i], bv[i]) % H.P, i


def test_mul_composes_with_itself():
    """Outputs feed back as inputs across a chain of muls (the ladder
    regime): values must track the host product chain exactly."""
    rng = np.random.default_rng(7)
    B = 16
    a = _rand_digit_arrays(rng, B)
    cur = jnp.asarray(a)
    host = [v % H.P for v in _vals(a)]
    sq = jax.jit(M.fp_mul)
    for _ in range(12):
        cur = sq(cur, cur)
        _check_invariant(cur)
        host = [v * v % H.P for v in host]
    got = _vals(cur)
    for i in range(B):
        assert got[i] % H.P == host[i], i


def test_fp2_mul_sqr_exact():
    rng = np.random.default_rng(11)
    B = 16
    ar, ai = _rand_digit_arrays(rng, B), _rand_digit_arrays(rng, B)
    br, bi = _rand_digit_arrays(rng, B), _rand_digit_arrays(rng, B)
    A = (jnp.asarray(ar), jnp.asarray(ai))
    Bp = (jnp.asarray(br), jnp.asarray(bi))
    mul = jax.jit(M.fp2_mul)(A, Bp)
    sqr = jax.jit(M.fp2_sqr)(A)
    for part in (*mul, *sqr):
        _check_invariant(part)
    arv, aiv = _vals(ar), _vals(ai)
    brv, biv = _vals(br), _vals(bi)
    mr, mi = _vals(mul[0]), _vals(mul[1])
    sr, si = _vals(sqr[0]), _vals(sqr[1])
    for i in range(B):
        a2 = (arv[i] % H.P, aiv[i] % H.P)
        b2 = (brv[i] % H.P, biv[i] % H.P)
        em = H.fp2_mul(a2, b2)
        es = H.fp2_sqr(a2)
        assert (mr[i] % H.P, mi[i] % H.P) == em, i
        assert (sr[i] % H.P, si[i] % H.P) == es, i


def test_zero_propagates_digitwise():
    """The explicit-infinity ladder needs exact digit-zero propagation
    through mul (0·x = digit-zero)."""
    rng = np.random.default_rng(13)
    z = jnp.zeros((4, M.NL), dtype=jnp.int32)
    x = jnp.asarray(_rand_digit_arrays(rng, 4))
    out = jax.jit(M.fp_mul)(z, x)
    assert np.asarray(out).max() == 0


def test_g1_lazy_ladder_mxu_ops_matches_host():
    """128-bit explicit-infinity ladder over the MXU field == host G1."""
    import random

    from hbbft_tpu.ops import fp381_mxu as MX
    from hbbft_tpu.ops import gcurve as G

    rng = random.Random(29)
    B = 4
    base = [H.g1_mul(H.G1_GEN, rng.randrange(1, H.R)) for _ in range(B - 1)]
    base.append(None)  # an infinity in the batch
    scalars = [rng.randrange(0, 1 << 64) for _ in range(B - 1)] + [5]
    pts = tuple(jnp.asarray(c) for c in G.g1_to_device(base, rep=MX))
    bits = jnp.asarray(G.scalars_to_bits(scalars, nbits=64))
    base_inf = jnp.asarray(np.array([p is None for p in base]))
    out, inf = jax.jit(
        lambda p, b, i: G.scalar_mul_lazy(G.MXU_FP_OPS, p, b, i)
    )(pts, bits, base_inf)
    inf = np.asarray(inf)
    host_pts = G.g1_from_device_batch(out, rep=MX)
    for i in range(B):
        expect = H.g1_mul(base[i], scalars[i])
        if expect is None:
            assert inf[i], i
        else:
            assert not inf[i], i
            assert H.g1_eq(host_pts[i], expect), i


@pytest.mark.slow
def test_g2_lazy_ladder_mxu_ops_matches_host():
    import random

    from hbbft_tpu.ops import fp381_mxu as MX
    from hbbft_tpu.ops import gcurve as G

    rng = random.Random(31)
    B = 2
    base = [H.g2_mul(H.G2_GEN, rng.randrange(1, H.R)) for _ in range(B)]
    scalars = [rng.randrange(1, 1 << 64) for _ in range(B)]
    pts = tuple(
        tuple(jnp.asarray(x) for x in c) for c in G.g2_to_device(base, rep=MX)
    )
    bits = jnp.asarray(G.scalars_to_bits(scalars, nbits=64))
    base_inf = jnp.asarray(np.zeros(B, dtype=bool))
    out, inf = jax.jit(
        lambda p, b, i: G.scalar_mul_lazy(G.MXU_FP2_OPS, p, b, i)
    )(pts, bits, base_inf)
    assert not np.asarray(inf).any()
    host_pts = G.g2_from_device_batch(out, rep=MX)
    for i in range(B):
        assert H.g2_eq(host_pts[i], H.g2_mul(base[i], scalars[i])), i


@pytest.mark.slow
def test_windowed_ladder_matches_bitwise_and_host():
    """scalar_mul_lazy_window == scalar_mul_lazy == host, G1 MXU ops."""
    import random

    from hbbft_tpu.ops import fp381_mxu as MX
    from hbbft_tpu.ops import gcurve as G

    rng = random.Random(37)
    B = 4
    base = [H.g1_mul(H.G1_GEN, rng.randrange(1, H.R)) for _ in range(B - 1)]
    base.append(None)
    scalars = [rng.randrange(0, 1 << 64) for _ in range(B - 1)] + [9]
    pts = tuple(jnp.asarray(c) for c in G.g1_to_device(base, rep=MX))
    bits = jnp.asarray(G.scalars_to_bits(scalars, nbits=64))
    base_inf = jnp.asarray(np.array([p is None for p in base]))
    out_w, inf_w = jax.jit(
        lambda p, b, i: G.scalar_mul_lazy_window(G.MXU_FP_OPS, p, b, i)
    )(pts, bits, base_inf)
    host_w = G.g1_from_device_batch(out_w, rep=MX)
    inf_w = np.asarray(inf_w)
    for i in range(B):
        expect = H.g1_mul(base[i], scalars[i])
        if expect is None:
            assert inf_w[i], i
        else:
            assert not inf_w[i], i
            assert H.g1_eq(host_w[i], expect), i


def test_squeeze_handles_large_top_digit():
    """Regression: a single appended carry position dropped the carry out
    of digit NL for inputs with digit[NL-1] region values ≥ 2^16 — e.g.
    value 2^400 as one huge digit.  The squeeze must be exact for any
    in-contract input (every limb < 2^31)."""
    rng = np.random.default_rng(17)
    cases = np.zeros((4, M.NL), dtype=np.int64)
    cases[0, M.NL - 1] = 1 << 24          # value 2^408
    cases[1, M.NL - 1] = (1 << 31) - 1    # max limb at the top
    cases[2] = rng.integers(0, 1 << 31, size=M.NL)  # dense max-magnitude
    cases[3, 0] = (1 << 31) - 1
    arr = jnp.asarray(cases.astype(np.int32))
    out = jax.jit(M._squeeze)(arr)
    _check_invariant(out)
    got = _vals(out)
    expect = [M.limbs_to_int(row) % H.P for row in cases]
    for i in range(len(cases)):
        assert got[i] % H.P == expect[i], i

"""Forensic audit over flight journals — the acceptance scenarios.

- a deterministic 4-node VirtualNet run, recorded twice independently,
  audits to byte-identical timelines and a clean verdict (the ``python
  -m hbbft_tpu.obs.audit`` CLI included);
- an equivocating adversary (``sim.adversary.EquivocatingAdversary``)
  yields receiver-side evidence naming the faulty node, keyed to the
  ``Multiple*`` FaultKind family, with the first affected epoch;
- a forked journal reports the FIRST divergent epoch (not a crash), a
  truncated journal reports torn tails and still audits clean;
- commit monotonicity and live-``/status`` cross-checks flip the verdict.
"""

import contextlib
import io
import random

import pytest

from hbbft_tpu.fault_log import equivocation_kinds
from hbbft_tpu.obs import audit
from hbbft_tpu.obs.flight import FlightRecorder, read_journal
from hbbft_tpu.protocols.dynamic_honey_badger import DynamicHoneyBadger
from hbbft_tpu.protocols.honey_badger import EncryptionSchedule
from hbbft_tpu.protocols.queueing_honey_badger import (
    QhbBatch,
    QueueingHoneyBadger,
    TxInput,
)
from hbbft_tpu.sim import NetBuilder, NullAdversary
from hbbft_tpu.sim.adversary import EquivocatingAdversary


def _run_recorded(infos, root, adversary=None, faulty=(), n=4, txs=8,
                  max_cranks=60_000):
    """A recorded QHB run, crank-BOUNDED: an equivocating proposer's own
    txs can never commit, so its queue re-proposes forever and the run
    never goes quiescent — honest Byzantine behavior, not a bug.  A
    fixed crank budget keeps every configuration deterministic AND
    finite (clean runs drain long before the bound)."""
    builder = NetBuilder(list(range(n))).adversary(
        adversary or NullAdversary()).faulty(list(faulty)).flight(root)
    net = builder.using_step(
        lambda nid: QueueingHoneyBadger(
            DynamicHoneyBadger(
                infos[nid], infos[nid].secret_key(),
                rng=random.Random(100 + nid),
                encryption_schedule=EncryptionSchedule.never(),
            ),
            batch_size=4, rng=random.Random(200 + nid),
        )
    )
    for i in range(txs):
        net.send_input(i % n, TxInput(b"audit-tx-%d" % i))
    while net.queue and net.cranks < max_cranks:
        net.crank()
    net.close_observers()
    return net


@pytest.fixture(scope="module")
def clean_runs(shared_netinfo, tmp_path_factory):
    """The SAME deterministic schedule recorded twice, independently."""
    infos = shared_netinfo(4, 13)
    roots = []
    for tag in ("a", "b"):
        root = str(tmp_path_factory.mktemp(f"flight-{tag}"))
        net = _run_recorded(infos, root)
        assert sum(1 for o in net.nodes[0].outputs
                   if isinstance(o, QhbBatch)) >= 2
        roots.append(root)
    return roots


def _cli(args):
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        rc = audit.main(args)
    return rc, buf.getvalue()


def test_clean_run_audits_clean_and_byte_identical(clean_runs):
    """Acceptance: two invocations over independently recorded journals
    → byte-identical timelines, clean verdicts, exit status 0."""
    outs = []
    for root in clean_runs:
        rc, out = _cli([root, "--timeline"])
        assert rc == 0, out
        assert out.endswith("verdict: clean\n")
        assert "-- timeline --" in out and "commit idx=0" in out
        outs.append(out)
    assert outs[0] == outs[1]  # byte-identical
    # all four chains agree and were actually compared
    res, _ = audit.run_audit([clean_runs[0]])
    assert len(res.chains) == 4
    heads = {c["head"] for c in res.chains.values()}
    assert len(heads) == 1
    assert res.torn_tails == 0 and not res.equivocations
    assert res.unmatched_receives == 0


def test_audit_module_entry_point(clean_runs):
    """The literal ``python -m hbbft_tpu.obs.audit`` invocation."""
    import os
    import subprocess
    import sys

    env = dict(os.environ, JAX_PLATFORMS="cpu")
    cwd = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.run(
        [sys.executable, "-m", "hbbft_tpu.obs.audit", clean_runs[0],
         "--json"],
        capture_output=True, text=True, env=env, cwd=cwd, timeout=120,
    )
    assert proc.returncode == 0, proc.stderr
    import json

    doc = json.loads(proc.stdout)
    assert doc["verdict"] == "clean" and len(doc["nodes"]) == 4


def test_equivocating_adversary_is_named_with_first_epoch(
        shared_netinfo, tmp_path):
    """Audit-on-fault satellite: the equivocator's conflicting roots land
    in the receivers' journals; the auditor names the node and the first
    affected epoch, keyed to the Multiple* FaultKind family."""
    infos = shared_netinfo(4, 13)
    root = str(tmp_path / "flight-equiv")
    net = _run_recorded(infos, root, adversary=EquivocatingAdversary(),
                        faulty=[3])
    # consensus survives f=1 equivocation: every correct node commits
    for nid in (0, 1, 2):
        assert sum(1 for o in net.nodes[nid].outputs
                   if isinstance(o, QhbBatch)) >= 1
    res, _ = audit.run_audit([root])
    assert res.verdict == "fault"
    assert res.equivocations
    assert {e["sender"] for e in res.equivocations} == {"3"}
    eq_names = {k.name for k in equivocation_kinds()}
    assert {e["kind"] for e in res.equivocations} <= eq_names
    # first affected epoch = the earliest slot with conflicting values
    assert res.first_affected_epoch == min(
        (e["era"], e["epoch"]) for e in res.equivocations)
    # each piece of evidence shows >= 2 conflicting values with the
    # witnessing receivers attached
    for e in res.equivocations:
        assert len(e["values"]) >= 2
        witnesses = {w for ws in e["values"].values() for w in ws}
        assert witnesses and "3" not in witnesses
    # the report prints the culprit and the epoch, and the CLI exits 1
    rc, out = _cli([root])
    assert rc == 1
    assert "EQUIVOCATION: 3 " in out and "first affected epoch" in out
    assert out.endswith("verdict: fault\n")


def test_truncated_journal_reports_torn_tail_not_crash(
        clean_runs, tmp_path):
    """Chop the newest segment of one node mid-record: the audit still
    completes, counts the torn tail, and the verdict stays clean (the
    tear loses records, it does not forge disagreement)."""
    import os
    import shutil

    root = str(tmp_path / "flight-torn")
    shutil.copytree(clean_runs[0], root)
    node_dir = os.path.join(root, sorted(os.listdir(root))[0])
    seg = sorted(n for n in os.listdir(node_dir)
                 if n.endswith(".fjl"))[-1]
    path = os.path.join(node_dir, seg)
    size = os.path.getsize(path)
    with open(path, "rb+") as fh:
        fh.truncate(size - 11)  # mid-record, past the last boundary
    res, _ = audit.run_audit([root])
    assert res.torn_tails == 1
    assert res.verdict == "clean"
    rc, out = _cli([root])
    assert rc == 0 and "1 torn tails" in out


def test_forked_journals_report_first_divergent_epoch(tmp_path):
    """Two synthetic nodes agree for 3 batches then fork: the auditor
    reports the FIRST divergent epoch with per-node digests and prints
    the surrounding event window."""
    shared = [bytes([i]) * 32 for i in range(3)]
    for node, fork_byte in (("0", 0xAA), ("1", 0xBB)):
        rec = FlightRecorder(str(tmp_path / f"node-{node}"), node=node,
                             clock=None)
        for i, digest in enumerate(shared):
            rec.record_commit(0, i, i, digest)
        rec.record_commit(0, 3, 3, bytes([fork_byte]) * 32)  # fork!
        rec.record_commit(0, 4, 4, bytes([fork_byte + 1]) * 32)
        rec.close()
    res, _ = audit.run_audit([str(tmp_path)])
    assert res.verdict == "fork"
    d = res.first_divergence
    assert d["index"] == 3 and d["era"] == 0 and d["epoch"] == 3
    assert set(d["per_node"]) == {"0", "1"}
    rc, out = _cli([str(tmp_path)])
    assert rc == 1
    assert "FORK: first divergent epoch era=0 epoch=3" in out
    assert "-- event window around divergence --" in out
    assert out.endswith("verdict: fork\n")


def test_restart_replaying_identical_chain_is_clean_but_selffork_is_not(
        tmp_path):
    """The kill-restart shape: incarnation 2 re-commits indices 0..k.
    Identical digests (honest replay) stay clean; a different digest at
    an already-journaled index is a self-fork."""
    d = str(tmp_path / "node-0")
    rec = FlightRecorder(d, node="0", clock=None)
    for i in range(3):
        rec.record_commit(0, i, i, bytes([i]) * 32)
    rec.close()
    rec = FlightRecorder(d, node="0", clock=None)  # restart
    for i in range(4):  # replays 0..2 identically, extends to 3
        rec.record_commit(0, i, i, bytes([i]) * 32)
    rec.close()
    res, _ = audit.run_audit([d])
    assert res.restarts == {"0": 1}
    assert res.verdict == "clean" and not res.monotonicity_violations

    d2 = str(tmp_path / "node-1")
    rec = FlightRecorder(d2, node="1", clock=None)
    rec.record_commit(0, 0, 0, b"\x01" * 32)
    rec.record_commit(0, 1, 1, b"\x02" * 32)
    rec.record_commit(0, 1, 1, b"\x03" * 32)  # same key, new digest
    rec.close()
    res, _ = audit.run_audit([d2])
    assert res.self_conflicts and res.monotonicity_violations
    assert res.verdict == "fork"


def test_status_cross_check(tmp_path):
    d = str(tmp_path / "node-0")
    rec = FlightRecorder(d, node="'0'", clock=None)
    digests = [bytes([i]) * 32 for i in range(4)]
    for i, dig in enumerate(digests):
        rec.record_commit(0, i, i, dig)
    rec.close()
    res, journals = audit.run_audit([d])
    doc = {
        "node": "'0'",
        "chain_len": 4,
        "digest_chain": [dig.hex() for dig in digests[2:]],
        "digest_chain_offset": 2,
    }
    audit.cross_check_status(res, doc)
    assert not res.status_mismatches and res.verdict == "clean"
    # a live node disagreeing with the journal is a fork
    bad = dict(doc, digest_chain=["ff" * 32, digests[3].hex()])
    res2, _ = audit.run_audit([d])
    audit.cross_check_status(res2, bad)
    assert res2.status_mismatches and res2.verdict == "fork"

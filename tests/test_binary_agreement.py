"""Binary agreement tests (reference: ``tests/binary_agreement.rs``).

Agreement: all correct nodes decide the same bit.  Validity: if all correct
nodes input b, the decision is b.  Termination under every adversary.
"""

import random

import pytest

from hbbft_tpu.netinfo import NetworkInfo
from hbbft_tpu.protocols.binary_agreement import BinaryAgreement
from hbbft_tpu.sim import (
    NetBuilder,
    NodeOrderAdversary,
    NullAdversary,
    RandomAdversary,
    ReorderingAdversary,
)

_INFO_CACHE = {}


def infos_for(n, seed=7):
    key = (n, seed)
    if key not in _INFO_CACHE:
        _INFO_CACHE[key] = NetworkInfo.generate_map(
            list(range(n)), random.Random(seed)
        )
    return _INFO_CACHE[key]


def run_ba(n, inputs, adversary):
    infos = infos_for(n)
    net = NetBuilder(list(range(n))).adversary(adversary).using_step(
        lambda nid: BinaryAgreement(infos[nid], b"ba-test", 0)
    )
    for nid, b in inputs.items():
        net.send_input(nid, b)
    net.run_to_quiescence()
    return net


@pytest.mark.parametrize("n", [1, 4, 7])
@pytest.mark.parametrize("value", [True, False])
def test_validity_unanimous(n, value):
    net = run_ba(n, {i: value for i in range(n)}, NullAdversary())
    for nid in net.node_ids():
        assert net.nodes[nid].outputs == [value], f"node {nid}"
        assert net.nodes[nid].algorithm.terminated()


@pytest.mark.parametrize(
    "adv",
    [
        NullAdversary(),
        NodeOrderAdversary(),
        ReorderingAdversary(seed=5),
        RandomAdversary(seed=6, dup_prob=0.1),
    ],
    ids=["null", "node_order", "reordering", "random"],
)
def test_agreement_mixed_inputs(adv):
    n = 4
    inputs = {0: True, 1: False, 2: True, 3: False}
    net = run_ba(n, inputs, adv)
    decisions = {nid: net.nodes[nid].outputs for nid in net.node_ids()}
    assert all(len(d) == 1 for d in decisions.values()), decisions
    assert len({d[0] for d in decisions.values()}) == 1, decisions


def test_agreement_many_seeds_mixed():
    n = 4
    for seed in range(4):
        rng = random.Random(seed + 100)
        inputs = {i: bool(rng.getrandbits(1)) for i in range(n)}
        net = run_ba(n, inputs, RandomAdversary(seed=seed))
        decisions = [net.nodes[nid].outputs for nid in net.node_ids()]
        assert all(len(d) == 1 for d in decisions)
        assert len({d[0] for d in decisions}) == 1
        # validity direction: decision must be someone's input
        assert decisions[0][0] in inputs.values()


def test_single_node_decides_immediately():
    net = run_ba(1, {0: True}, NullAdversary())
    assert net.nodes[0].outputs == [True]

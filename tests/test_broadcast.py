"""Reliable broadcast property tests (reference: ``tests/broadcast.rs``).

All correct nodes must output the proposer's value under every adversary
schedule; a faulty proposer can prevent output but never cause divergence.
"""

import random

import pytest

from hbbft_tpu.netinfo import NetworkInfo
from hbbft_tpu.protocols.broadcast import Broadcast, ReadyMsg, ValueMsg
from hbbft_tpu.sim import (
    NetBuilder,
    NodeOrderAdversary,
    NullAdversary,
    RandomAdversary,
    ReorderingAdversary,
)
from hbbft_tpu.sim.virtual_net import NetworkMessage

# Broadcast needs no keys — build NetworkInfo with dummy key material.


def make_netinfos(n):
    ids = list(range(n))
    pub_keys = {i: object() for i in ids}
    return {
        i: NetworkInfo(our_id=i, public_keys=pub_keys, public_key_set=None)
        for i in ids
    }


def run_broadcast(n, adversary, value=b"the proposed value", proposer=0):
    infos = make_netinfos(n)
    net = (
        NetBuilder(list(range(n)))
        .adversary(adversary)
        .using_step(lambda nid: Broadcast(infos[nid], proposer))
    )
    net.send_input(proposer, value)
    net.run_to_quiescence()
    return net


@pytest.mark.parametrize("n", [1, 2, 3, 4, 7, 10])
@pytest.mark.parametrize(
    "adv",
    [
        NullAdversary(),
        NodeOrderAdversary(),
        ReorderingAdversary(seed=3),
        RandomAdversary(seed=4),
    ],
    ids=["null", "node_order", "reordering", "random"],
)
def test_all_nodes_output_value(n, adv):
    value = b"v" * 50
    net = run_broadcast(n, adv, value)
    for nid in net.node_ids():
        assert net.nodes[nid].outputs == [value], f"node {nid}"
        assert net.nodes[nid].algorithm.terminated()


def test_empty_and_large_values():
    for value in (b"", b"x", bytes(range(256)) * 40):
        net = run_broadcast(4, NullAdversary(), value)
        for nid in net.node_ids():
            assert net.nodes[nid].outputs == [value]


def test_nonzero_proposer():
    net = run_broadcast(4, NullAdversary(), b"hello", proposer=2)
    for nid in net.node_ids():
        assert net.nodes[nid].outputs == [b"hello"]


def test_silent_proposer_no_output():
    infos = make_netinfos(4)
    net = NetBuilder(list(range(4))).using_step(
        lambda nid: Broadcast(infos[nid], 0)
    )
    # nobody inputs anything
    net.run_to_quiescence()
    for nid in net.node_ids():
        assert net.nodes[nid].outputs == []


def test_crashed_proposer_after_value_still_delivers():
    """If the proposer sends all Values then crashes, echo/ready complete."""
    infos = make_netinfos(4)
    net = NetBuilder(list(range(4))).using_step(
        lambda nid: Broadcast(infos[nid], 0)
    )
    net.send_input(0, b"survives crash")
    # drop every subsequent message FROM node 0 (simulated crash)
    net.queue = [m for m in net.queue if m.sender != 0 or isinstance(m.payload, ValueMsg)]

    class DropFromZero(NullAdversary):
        def pick_message(self, net_):
            # drop node-0 messages lazily
            while net_.queue and net_.queue[0].sender == 0 and not isinstance(
                net_.queue[0].payload, ValueMsg
            ):
                net_.queue.pop(0)
            return 0

    net.adversary = DropFromZero()
    net.run_to_quiescence()
    for nid in (1, 2, 3):
        assert net.nodes[nid].outputs == [b"survives crash"], f"node {nid}"


def test_byzantine_proposer_equivocation_no_divergence():
    """A proposer sending two different values: correct nodes never disagree.

    (With n=4, f=1 the echo threshold prevents two roots both reaching
    2f+1 readys.)
    """
    infos = make_netinfos(4)
    net = NetBuilder(list(range(4))).using_step(
        lambda nid: Broadcast(infos[nid], 0)
    )
    # Byzantine proposer: run two separate Broadcast instances for two values
    # and interleave their Value messages to split the honest nodes.
    b_a = Broadcast(infos[0], 0)
    b_b = Broadcast(infos[0], 0)
    step_a = b_a.handle_input(b"value A")
    step_b = b_b.handle_input(b"value B")
    # deliver A's Values to node 1, B's Values to nodes 2,3
    for tm in step_a.messages:
        for dest in tm.target.resolve(net.node_ids(), 0):
            if dest == 1:
                net.queue.append(NetworkMessage(0, dest, tm.message))
    for tm in step_b.messages:
        for dest in tm.target.resolve(net.node_ids(), 0):
            if dest in (2, 3):
                net.queue.append(NetworkMessage(0, dest, tm.message))
    net.run_to_quiescence()
    outputs = [tuple(net.nodes[nid].outputs) for nid in (1, 2, 3)]
    decided = [o for o in outputs if o]
    # no two correct nodes decided different values
    assert len({o for o in decided}) <= 1, outputs


def test_random_adversary_with_duplication_many_seeds():
    for seed in range(5):
        net = run_broadcast(7, RandomAdversary(seed=seed, dup_prob=0.2))
        for nid in net.node_ids():
            assert net.nodes[nid].outputs == [b"the proposed value"]


# -- EchoHash / CanDecode message-reduction optimization ---------------------
# (reference: src/broadcast/message.rs :: Message::{EchoHash, CanDecode})


def test_can_decode_switches_echo_to_hash():
    """A node that received CanDecode(root) from a peer before its own Value
    sends that peer hash-only EchoHash instead of the full shard."""
    from hbbft_tpu.protocols.broadcast import CanDecodeMsg, EchoHashMsg, EchoMsg

    n = 4
    infos = make_netinfos(n)
    proposer = Broadcast(infos[0], 0)
    step = proposer.handle_input(b"shard me" * 5)
    values = {
        next(iter(m.target.ids)): m.message
        for m in step.messages if isinstance(m.message, ValueMsg)
    }

    node1 = Broadcast(infos[1], 0)
    root = values[1].proof.root_hash
    # peer 2 says it can decode; peer 3 says nothing
    s = node1.handle_message(2, CanDecodeMsg(root))
    assert not len(s.fault_log)
    s = node1.handle_message(0, values[1])
    hash_targets = set()
    echo_excepts = None
    for m in s.messages:
        if isinstance(m.message, EchoHashMsg):
            assert m.message.root == root
            hash_targets |= set(m.target.ids)
        elif isinstance(m.message, EchoMsg):
            echo_excepts = set(m.target.ids)  # ALL_EXCEPT the hash peers
    assert hash_targets == {2}
    assert echo_excepts == {2}  # full shards go to everyone else incl. observers


def test_echo_hash_counts_toward_ready_threshold():
    """EchoHash evidence (no shard) still drives the N−f Ready rule."""
    from hbbft_tpu.protocols.broadcast import EchoHashMsg, EchoMsg

    n = 4
    infos = make_netinfos(n)
    proposer = Broadcast(infos[0], 0)
    step = proposer.handle_input(b"payload!" * 3)
    values = {
        next(iter(m.target.ids)): m.message
        for m in step.messages if isinstance(m.message, ValueMsg)
    }

    node1 = Broadcast(infos[1], 0)
    root = values[1].proof.root_hash
    node1.handle_message(0, values[1])          # own echo (1 evidence)
    assert not node1.ready_sent
    node1.handle_message(0, EchoHashMsg(root))  # proposer's hash evidence
    assert not node1.ready_sent
    s = node1.handle_message(2, EchoHashMsg(root))  # third → N−f = 3
    assert node1.ready_sent
    assert any(isinstance(m.message, ReadyMsg) for m in s.messages)


def test_echo_hash_conflict_fault():
    from hbbft_tpu.fault_log import FaultKind
    from hbbft_tpu.protocols.broadcast import EchoHashMsg

    n = 4
    infos = make_netinfos(n)
    proposer = Broadcast(infos[0], 0)
    step = proposer.handle_input(b"conflicted")
    values = {
        next(iter(m.target.ids)): m.message
        for m in step.messages if isinstance(m.message, ValueMsg)
    }
    echo_from_2 = None
    node1 = Broadcast(infos[1], 0)
    node1.handle_message(0, values[1])
    # node 2's full echo would carry its own proof; simulate with the real
    # one by building node 2 and capturing its echo to node 1
    from hbbft_tpu.protocols.broadcast import EchoMsg

    node2 = Broadcast(infos[2], 0)
    s2 = node2.handle_message(0, values[2])
    for m in s2.messages:
        if isinstance(m.message, EchoMsg):
            echo_from_2 = m.message
            break
    assert echo_from_2 is not None
    node1.handle_message(2, echo_from_2)
    # now node 2 "sends" an EchoHash naming a different root → fault
    s = node1.handle_message(2, EchoHashMsg(b"\x99" * 32))
    kinds = [f.kind for f in s.fault_log.faults]
    assert FaultKind.EchoHashConflict in kinds


def test_full_broadcast_still_delivers_with_new_messages():
    """e2e sanity: the optimization messages flow through VirtualNet and the
    value still delivers everywhere (CanDecode fires in the happy path)."""
    n = 7
    net = run_broadcast(n, NullAdversary(), value=b"x" * 300)
    for nid in net.node_ids():
        assert net.nodes[nid].algorithm.output == b"x" * 300

"""Batched array-mode RBC vs the object-mode oracle.

The batched simulator (``hbbft_tpu.parallel.rbc``) must agree with the
object-mode ``Broadcast`` state machines on the same delivered-message set:
same delivered/faulted verdicts at every (receiver, proposer), same values.
The object side here is driven directly (no VirtualNet) so the exact edge
masks used by the batched run can be applied message-for-message.
"""

import random

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from hbbft_tpu.netinfo import NetworkInfo
from hbbft_tpu.parallel.rbc import BatchedRbc, frame_values, unframe_value
from hbbft_tpu.protocols.broadcast import (
    Broadcast,
    EchoMsg,
    ReadyMsg,
    ValueMsg,
)
from hbbft_tpu.traits import Target


def make_netinfos(n):
    ids = list(range(n))
    pub_keys = {i: object() for i in ids}
    return {
        i: NetworkInfo(our_id=i, public_keys=pub_keys, public_key_set=None)
        for i in ids
    }


def run_object_rbc(n, values, value_mask, echo_mask, ready_mask):
    """Drive n×n Broadcast instances delivering only mask-allowed edges.

    Returns (delivered bool (n, P), outputs dict, fault bool (n, P)).
    """
    infos = make_netinfos(n)
    P = len(values)
    inst = {(j, p): Broadcast(infos[j], p) for j in range(n) for p in range(P)}
    queue = []  # (src, dst, proposer, msg)

    def fan_out(src, p, step):
        ids = list(range(n))
        for tm in step.messages:
            for dst in tm.target.resolve(ids, src):
                queue.append((src, dst, p, tm.message))

    for p, v in enumerate(values):
        fan_out(p, p, inst[(p, p)].handle_input(v))

    from hbbft_tpu.protocols.broadcast import EchoHashMsg

    while queue:
        src, dst, p, msg = queue.pop(0)
        if isinstance(msg, ValueMsg) and not value_mask[p][dst]:
            continue
        # EchoHash is the echo of that edge (hash-only form) — same mask
        if isinstance(msg, (EchoMsg, EchoHashMsg)) and not echo_mask[src][dst][p]:
            continue
        if isinstance(msg, ReadyMsg) and not ready_mask[src][dst][p]:
            continue
        fan_out(dst, p, inst[(dst, p)].handle_message(src, msg))

    delivered = np.zeros((n, P), dtype=bool)
    fault = np.zeros((n, P), dtype=bool)
    outputs = {}
    for (j, p), b in inst.items():
        delivered[j, p] = b.decided
        fault[j, p] = b.fault
        if b.output is not None:
            outputs[(j, p)] = b.output
    return delivered, outputs, fault


def run_both(n, values, value_mask, echo_mask, ready_mask, **tamper):
    f = (n - 1) // 3
    rbc = BatchedRbc(n, f)
    data = frame_values(values, rbc.k)
    out = jax.jit(rbc.run)(
        jnp.asarray(data),
        value_mask=jnp.asarray(value_mask),
        echo_mask=jnp.asarray(echo_mask),
        ready_mask=jnp.asarray(ready_mask),
        **{k: jnp.asarray(v) for k, v in tamper.items()},
    )
    return rbc, data, {k: np.asarray(v) for k, v in out.items()}


def all_masks(n, P):
    return (
        np.ones((P, n), dtype=bool),
        np.ones((n, n, P), dtype=bool),
        np.ones((n, n, P), dtype=bool),
    )


@pytest.mark.parametrize("n", [4, 7, 10])
def test_happy_path_matches_object_mode(n):
    rng = random.Random(100 + n)
    values = [bytes(rng.randrange(256) for _ in range(rng.randrange(1, 60)))
              for _ in range(n)]
    vm, em, rm = all_masks(n, n)
    rbc, data, out = run_both(n, values, vm, em, rm)
    delivered_o, outputs_o, fault_o = run_object_rbc(n, values, vm, em, rm)

    assert out["delivered"].all()
    assert not out["fault"].any()
    np.testing.assert_array_equal(out["delivered"], delivered_o)
    np.testing.assert_array_equal(out["fault"], fault_o)
    for j in range(n):
        for p in range(n):
            assert unframe_value(out["data"][j, p]) == values[p] == outputs_o[(j, p)]


def test_echo_drops_match_object_mode():
    """Random echo drops below the disruption threshold: both modes must
    agree exactly on who delivers what."""
    n, P = 7, 7
    f = (n - 1) // 3
    rng = np.random.default_rng(42)
    values = [bytes([p]) * (p + 1) for p in range(P)]
    vm, em, rm = all_masks(n, P)
    # drop ~20% of off-diagonal echo edges (self-delivery always on)
    drop = rng.random((n, n, P)) < 0.2
    for i in range(n):
        drop[i, i, :] = False
    em = em & ~drop

    rbc, data, out = run_both(n, values, vm, em, rm)
    delivered_o, outputs_o, fault_o = run_object_rbc(n, values, vm, em, rm)

    np.testing.assert_array_equal(out["delivered"], delivered_o)
    np.testing.assert_array_equal(out["fault"], fault_o)
    for (j, p), v in outputs_o.items():
        assert unframe_value(out["data"][j, p]) == v
    assert out["delivered"].any()  # the scenario actually delivers something


def test_value_drops_match_object_mode():
    """Proposers whose Value messages are partially dropped."""
    n, P = 7, 7
    values = [bytes([p + 1]) * 9 for p in range(P)]
    vm, em, rm = all_masks(n, P)
    # proposer 0's Values reach only 4 nodes (= n - f - ... still ≥ n-f? no:
    # 4 < n-f=5 → echo count stalls at 4 < 5: nobody sends Ready for p=0)
    vm[0, 4:] = False
    # proposer 1 reaches exactly n - f = 5 nodes → delivers network-wide
    vm[1, 5:] = False

    rbc, data, out = run_both(n, values, vm, em, rm)
    delivered_o, outputs_o, fault_o = run_object_rbc(n, values, vm, em, rm)

    np.testing.assert_array_equal(out["delivered"], delivered_o)
    assert not out["delivered"][:, 0].any()
    assert out["delivered"][:, 1].all()
    for (j, p), v in outputs_o.items():
        assert unframe_value(out["data"][j, p]) == v


def test_ready_amplification_chain_matches_object_mode():
    """A node that misses too many echoes still delivers via f+1 readys —
    and multi-hop amplification under partial ready drops converges the
    same way in both modes."""
    n, P = 7, 1
    f = (n - 1) // 3
    values = [b"amplified"]
    vm, em, rm = all_masks(n, P)
    # node 6 misses all echoes except from 0..k-1 (so it can still decode)
    k = n - 2 * f
    em[k:, 6, 0] = False
    em[6, 6, 0] = True

    rbc, data, out = run_both(n, values, vm, em, rm)
    delivered_o, outputs_o, fault_o = run_object_rbc(n, values, vm, em, rm)
    np.testing.assert_array_equal(out["delivered"], delivered_o)
    assert out["delivered"].all()


def test_inconsistent_codeword_proposer_detected_both_modes():
    """codeword_tamper model: proposer 1 commits a Merkle tree over a
    non-codeword (parity shard 3 corrupted pre-commit).  Reference
    semantics: receivers holding all their data shards deliver (present
    shards are trusted as committed); a receiver whose survivor set leans on
    the corrupted parity reconstructs garbage, fails the root re-check, and
    flags the proposer.  Both modes must agree receiver-for-receiver."""
    n, P = 4, 4
    f = (n - 1) // 3
    values = [b"good0", b"evil!", b"good2", b"good3"]
    vm, em, rm = all_masks(n, P)
    # engineer node 0's survivor set for p=1 to be {1, 3}: no Value (so no
    # own echo) and echo 2→0 dropped
    vm[1, 0] = False
    em[2, 0, 1] = False

    rbc = BatchedRbc(n, f)
    data = frame_values(values, rbc.k)
    ct = np.zeros((P, n, data.shape[-1]), dtype=np.uint8)
    ct[1, 3, 0] = 0x5A  # corrupt proposer 1's parity shard 3 pre-commit

    out = jax.jit(rbc.run)(
        jnp.asarray(data),
        value_mask=jnp.asarray(vm),
        echo_mask=jnp.asarray(em),
        ready_mask=jnp.asarray(rm),
        codeword_tamper=jnp.asarray(ct),
    )
    out = {k: np.asarray(v) for k, v in out.items()}

    # object-mode equivalent: drive proposer 1 with a hand-built bad tree
    from hbbft_tpu.ops.merkle import MerkleTree
    from hbbft_tpu.protocols.broadcast import _frame_value

    infos = make_netinfos(n)
    inst = {(j, p): Broadcast(infos[j], p) for j in range(n) for p in range(P)}
    queue = []

    def fan_out(src, p, step):
        for tm in step.messages:
            for dst in tm.target.resolve(list(range(n)), src):
                queue.append((src, dst, p, tm.message))

    for p, v in enumerate(values):
        if p == 1:
            continue
        fan_out(p, p, inst[(p, p)].handle_input(v))
    # Byzantine proposer 1: encode, corrupt shard 3, commit, send Values
    coder = rbc.coder
    shards = coder.encode_np(_frame_value(values[1], rbc.k))
    shards = shards.copy()
    shards[3, 0] ^= 0x5A
    tree = MerkleTree.from_vec([s.tobytes() for s in shards])
    for i in range(n):
        queue.append((1, i, 1, ValueMsg(tree.proof(i))))

    while queue:
        src, dst, p, msg = queue.pop(0)
        if isinstance(msg, ValueMsg) and not vm[p][dst]:
            continue
        if isinstance(msg, EchoMsg) and not em[src][dst][p]:
            continue
        if isinstance(msg, ReadyMsg) and not rm[src][dst][p]:
            continue
        fan_out(dst, p, inst[(dst, p)].handle_message(src, msg))

    # node 0 flags proposer 1; everyone else delivers the committed value
    assert out["fault"][0, 1] and not out["delivered"][0, 1]
    assert inst[(0, 1)].fault and not inst[(0, 1)].decided
    for j in range(1, n):
        assert out["delivered"][j, 1] and not out["fault"][j, 1]
        assert inst[(j, 1)].decided
        assert unframe_value(out["data"][j, 1]) == values[1] == inst[(j, 1)].output
    for j in range(n):
        for p in (0, 2, 3):
            assert out["delivered"][j, p] and not out["fault"][j, p]
            assert unframe_value(out["data"][j, p]) == values[p] == inst[(j, p)].output


def test_bad_framing_faults_proposer_both_modes():
    """A proposer committing a CONSISTENT codeword whose framing is garbage
    (length prefix larger than the payload): root check passes but unframe
    fails → proposer fault, in both modes."""
    n = 4
    f = (n - 1) // 3
    rbc = BatchedRbc(n, f)
    # craft raw data whose first 4 bytes claim an impossible length
    B = 8
    data = np.zeros((n, rbc.k, B), dtype=np.uint8)
    good = frame_values([b"ok0", b"", b"ok2", b"ok3"], rbc.k)
    data[:, :, : good.shape[-1]] = good
    data[1, 0, :4] = 0xFF  # proposer 1: length prefix 0xFFFFFFFF

    out = jax.jit(rbc.run)(jnp.asarray(data))
    out = {k: np.asarray(v) for k, v in out.items()}
    assert out["delivered"][:, 0].all() and out["delivered"][:, 2:].all()
    assert not out["delivered"][:, 1].any()
    assert out["fault"][:, 1].all()

    # object mode: drive proposer 1 with the same raw (mis-framed) shards
    from hbbft_tpu.ops.merkle import MerkleTree

    infos = make_netinfos(n)
    inst = {j: Broadcast(infos[j], 1) for j in range(n)}
    shards = rbc.coder.encode_np(data[1])
    tree = MerkleTree.from_vec([s.tobytes() for s in shards])
    queue = [(1, i, ValueMsg(tree.proof(i))) for i in range(n)]
    while queue:
        src, dst, msg = queue.pop(0)
        step = inst[dst].handle_message(src, msg)
        for tm in step.messages:
            for d2 in tm.target.resolve(list(range(n)), dst):
                queue.append((dst, d2, tm.message))
    for j in range(n):
        assert inst[j].fault and not inst[j].decided


def test_value_tamper_invalid_proofs_not_delivered():
    """value_tamper model: shards corrupted after commit → proofs invalid →
    victims can't echo; with few enough victims the rest still deliver."""
    n, P = 7, 1
    values = [b"post-commit tamper"]
    rbc = BatchedRbc(n, (n - 1) // 3)
    data = frame_values(values, rbc.k)
    vt = np.zeros((P, n, data.shape[-1]), dtype=np.uint8)
    vt[0, 0, 0] = 0xFF  # node 0's Value shard corrupted in flight

    out = jax.jit(rbc.run)(jnp.asarray(data), value_tamper=jnp.asarray(vt))
    out = {k: np.asarray(v) for k, v in out.items()}
    # echo from node 0 missing (its proof failed) but n-1 ≥ n-f echoes remain
    assert (out["echo_count"][:, 0] == n - 1).all()
    assert out["delivered"].all()
    # no masks → full-delivery fast path: one shared data row for everyone
    assert list(out["data_receivers"]) == [0]
    assert unframe_value(out["data"][0, 0]) == values[0]


def test_full_delivery_fast_path_matches_masked_path():
    """The maskless fast path (shared decode) must agree with the explicit
    all-ones-mask path on verdicts, counts, and values."""
    n = 7
    f = (n - 1) // 3
    rng = random.Random(55)
    values = [bytes(rng.randrange(256) for _ in range(9)) for _ in range(n)]
    rbc = BatchedRbc(n, f)
    data = jnp.asarray(frame_values(values, rbc.k))
    fast = {k: np.asarray(v) for k, v in jax.jit(rbc.run)(data).items()}
    vm, em, rm = all_masks(n, n)
    slow = {
        k: np.asarray(v)
        for k, v in jax.jit(rbc.run)(
            data,
            value_mask=jnp.asarray(vm),
            echo_mask=jnp.asarray(em),
            ready_mask=jnp.asarray(rm),
        ).items()
    }
    for key in ("delivered", "fault", "echo_count", "ready_count"):
        np.testing.assert_array_equal(fast[key], slow[key], err_msg=key)
    for p in range(n):
        assert unframe_value(fast["data"][0, p]) == unframe_value(
            slow["data"][0, p]
        ) == values[p]


def test_large_n_compact_transfers_bit_equal():
    """upload_framed / _fetch_data_compact (the large-N tunnel compaction)
    must be bit-equal to the naive full-frame path, across payload-size
    edges: tiny values, values of very different lengths, and a value
    filling the whole frame (fetch window == k*B)."""
    n = 264
    f = (n - 1) // 3
    rbc = BatchedRbc(n, f)
    kb = rbc.k * 2  # shard_len resolves to 2 for small payloads
    values = [bytes([p % 251 + 1]) * (1 + (p * 37) % 60) for p in range(n)]
    values[0] = b""                      # empty value
    # a value filling the whole frame: fetch window must reach k*B exactly
    values[1] = (bytes(range(256)) * (kb // 256 + 1))[: kb - 4]
    assert len(values[1]) == kb - 4
    # compact upload == naive frame, byte for byte
    np.testing.assert_array_equal(
        np.asarray(rbc.upload_framed(values)), frame_values(values, rbc.k)
    )
    out_naive = rbc._run_large(jnp.asarray(frame_values(values, rbc.k)))
    out_comp = rbc._run_large(rbc.upload_framed(values))
    np.testing.assert_array_equal(out_naive["delivered"], out_comp["delivered"])
    np.testing.assert_array_equal(out_naive["data"], out_comp["data"])
    assert out_comp["delivered"].all()
    for p in (0, 1, 2, 100, n - 1):
        assert unframe_value(out_comp["data"][0, p]) == values[p], p


def test_large_n_compact_fetch_with_bad_framing():
    """A proposer whose committed frame declares an absurd length must not
    widen the compact fetch window, and must fault exactly like the naive
    path (frame_ok false -> not delivered)."""
    n = 264
    f = (n - 1) // 3
    rbc = BatchedRbc(n, f)
    values = [bytes([p % 251 + 1]) * 3 for p in range(n)]
    data = frame_values(values, rbc.k)
    bad = data.copy()
    bad[5, 0, :2] = 255  # length prefix now ~4 GB: frame check must fail
    out = rbc._run_large(jnp.asarray(bad))
    d = np.asarray(out["delivered"])
    fa = np.asarray(out["fault"])
    assert not d[0, 5] and fa[0, 5]
    mask = np.ones(n, dtype=bool); mask[5] = False
    assert d[0, mask].all() and not fa[0, mask].any()
    for p in (0, 4, 6, n - 1):
        assert unframe_value(out["data"][0, p]) == values[p]
    # the fault row comes back ALL-ZERO: a row whose framing failed is
    # only partially inside the compact fetch window, and partial bytes
    # must never be mistakable for real shard data
    assert not np.asarray(out["data"])[0, 5].any()

"""SenderQueue catch-up: the restart path the net runtime relies on.

Regression scenario (satellite of the net-subsystem PR): a peer restarts
from scratch at (era, epoch) = (0, 0) after the others have reached epoch
k.  The others' SenderQueues hold back its far-future messages; the
runtime's replay log re-feeds the already-sent history through
``reinit_peer``; the restarted peer must then receive the backlog *in
epoch order*, released chunk by chunk as it announces ``EpochStarted``
progress, and end up with the identical batch sequence.
"""

import random
from typing import Any, Dict, List, Tuple

from hbbft_tpu.netinfo import NetworkInfo
from hbbft_tpu.protocols.dynamic_honey_badger import DynamicHoneyBadger
from hbbft_tpu.protocols.honey_badger import EncryptionSchedule
from hbbft_tpu.protocols.queueing_honey_badger import (
    QhbBatch,
    QueueingHoneyBadger,
    TxInput,
)
from hbbft_tpu.protocols.sender_queue import (
    AlgoMessage,
    EpochStarted,
    SenderQueue,
    _algo_key,
    message_key,
)

N = 4
DOWN = 3  # the node that is down, then restarts at (0, 0)


def make_node(infos, nid) -> SenderQueue:
    dhb = DynamicHoneyBadger(
        infos[nid], infos[nid].secret_key(),
        rng=random.Random(7000 + nid),
        encryption_schedule=EncryptionSchedule.never(),
    )
    return SenderQueue(QueueingHoneyBadger(
        dhb, batch_size=4, rng=random.Random(8000 + nid)
    ))


class Pump:
    """Deterministic FIFO message pump with a runtime-style replay log.

    Messages to a down node are recorded in ``history[sender]`` exactly as
    the net runtime's per-peer replay log records frames it handed to the
    transport (they were "sent", then lost with the dead process)."""

    def __init__(self, nodes: Dict[int, SenderQueue]):
        self.nodes = nodes
        self.queue: List[Tuple[int, int, Any]] = []
        self.down: set = set()
        self.history: Dict[int, List[Tuple[Tuple[int, int], Any]]] = {}
        # per-sender keys of AlgoMessages delivered to DOWN, in order
        self.delivered_keys: Dict[int, List[Tuple[int, int]]] = {}

    def fan_out(self, sender: int, step) -> None:
        all_ids = sorted(self.nodes.keys())
        for tm in step.messages:
            for dest in tm.target.resolve(all_ids, sender):
                self.queue.append((sender, dest, tm.message))

    def run(self) -> None:
        while self.queue:
            sender, dest, msg = self.queue.pop(0)
            if dest in self.down:
                if isinstance(msg, AlgoMessage):
                    self.history.setdefault(sender, []).append(
                        (message_key(msg.msg), msg.msg)
                    )
                continue
            if dest == DOWN and isinstance(msg, AlgoMessage):
                self.delivered_keys.setdefault(sender, []).append(
                    message_key(msg.msg)
                )
            step = self.nodes[dest].handle_message(sender, msg)
            self.fan_out(dest, step)


def test_restarted_peer_catches_up_in_order():
    infos = NetworkInfo.generate_map(list(range(N)), random.Random(11))
    nodes = {nid: make_node(infos, nid) for nid in range(N)}
    outputs: Dict[int, List[QhbBatch]] = {nid: [] for nid in range(N)}

    pump = Pump(nodes)

    def wrap(nid):
        node = nodes[nid]
        inner = node.handle_message

        def handler(sender, msg):
            step = inner(sender, msg)
            outputs[nid].extend(
                o for o in step.output if isinstance(o, QhbBatch)
            )
            return step

        node.handle_message = handler

    for nid in range(N):
        wrap(nid)

    # phase 1: node DOWN is dead from the start; the others run k epochs
    pump.down = {DOWN}
    for e in range(7):
        for nid in range(N - 1):
            step = nodes[nid].handle_input(
                TxInput(b"tx-%d-%d" % (e, nid))
            )
            outputs[nid].extend(
                o for o in step.output if isinstance(o, QhbBatch)
            )
            pump.fan_out(nid, step)
        pump.run()

    k = _algo_key(nodes[0].algo)[1]
    assert k >= 5, f"live nodes only reached epoch {k}"
    window = nodes[0].algo.dhb.max_future_epochs
    # the exact premise of the catch-up path: with DOWN never announcing,
    # everything beyond (0, window) was held back, the rest was "sent"
    # (recorded in the replay history)
    for nid in range(N - 1):
        held = nodes[nid].buffered.get(DOWN, [])
        assert held, f"node {nid} held nothing back for the dead peer"
        assert all(key > (0, window) for key, _m in held)
        assert any(key <= (0, window) for key, _m in pump.history[nid])

    # phase 2: DOWN restarts from scratch at (0, 0)
    nodes[DOWN] = make_node(infos, DOWN)
    wrap(DOWN)
    pump.down = set()
    for nid in range(N - 1):
        step = nodes[nid].reinit_peer(
            DOWN, (0, 0), pump.history.get(nid, [])
        )
        pump.fan_out(nid, step)
    pump.run()

    # the restarted peer replayed to the same epoch with identical batches
    assert _algo_key(nodes[DOWN].algo) == _algo_key(nodes[0].algo)
    ref = [(b.era, b.epoch, tuple(b.all_txs())) for b in outputs[0]]
    got = [(b.era, b.epoch, tuple(b.all_txs())) for b in outputs[DOWN]]
    assert got == ref and len(ref) >= 5

    # and the backlog arrived in epoch order, per sender: held-back
    # messages were only released as EpochStarted announcements advanced
    for nid in range(N - 1):
        keys = pump.delivered_keys.get(nid, [])
        assert keys, f"no replayed traffic from node {nid}"
        assert keys == sorted(keys), (
            f"out-of-order catch-up from node {nid}: {keys}"
        )


def test_reinit_peer_rewinds_and_rebuffers():
    """Unit shape: reinit_peer re-sends only the deliverable prefix of the
    merged history+buffer backlog, holds the rest, re-announces our key."""
    infos = NetworkInfo.generate_map(list(range(N)), random.Random(13))
    node = make_node(infos, 0)
    window = node.algo.dhb.max_future_epochs
    # pretend peer 1 was known at epoch 9 with two messages buffered
    node.peer_epochs[1] = (0, 9)
    from hbbft_tpu.protocols.dynamic_honey_badger import HbWrap
    from hbbft_tpu.protocols.honey_badger import SubsetWrap

    def fake(epoch):
        return HbWrap(0, SubsetWrap(epoch, None))

    node.buffered[1] = [((0, 14), fake(14)), ((0, 15), fake(15))]
    history = [((0, e), fake(e)) for e in range(6)]

    step = node.reinit_peer(1, (0, 0), history)
    assert node.peer_epochs[1] == (0, 0)
    sent = [tm.message for tm in step.messages]
    algo_sent = [m for m in sent if isinstance(m, AlgoMessage)]
    # deliverable prefix: epochs 0..window
    assert [message_key(m.msg) for m in algo_sent] == [
        (0, e) for e in range(window + 1)
    ]
    # the rest (history tail + old buffer) is held back, in key order
    assert [key for key, _m in node.buffered[1]] == (
        [(0, e) for e in range(window + 1, 6)] + [(0, 14), (0, 15)]
    )
    # and we re-announced our own epoch to the restarted peer
    assert any(isinstance(m, EpochStarted) for m in sent)

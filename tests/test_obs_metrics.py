"""Metrics registry invariants + the static metrics-contract checker.

The registry is the one piece every observability surface trusts, so its
invariants get direct coverage: label cardinality capping, histogram
bucket monotonicity, Prometheus text escaping (round-tripped through the
shipped parser), get-or-create semantics, and quantile estimation."""

import math

import pytest

from hbbft_tpu.obs.metrics import (
    DEFAULT_BUCKETS,
    OVERFLOW,
    Registry,
    escape_help,
    escape_label_value,
    fault_counter,
    histogram_quantile,
    parse_prometheus_text,
)


def test_counter_gauge_basics_and_json():
    r = Registry()
    c = r.counter("hbbft_node_x_total", "x")
    c.inc()
    c.inc(2.5)
    assert c.value() == 3.5
    g = r.gauge("hbbft_node_g", "g")
    g.set(7)
    g.dec(2)
    assert g.value() == 5
    doc = r.as_dict()
    assert doc["hbbft_node_x_total"]["type"] == "counter"
    assert doc["hbbft_node_x_total"]["series"][0]["value"] == 3.5
    assert doc["hbbft_node_g"]["series"][0]["value"] == 5


def test_registration_is_get_or_create_and_kind_conflicts_raise():
    r = Registry()
    a = r.counter("hbbft_node_a_total", "a", labelnames=("k",))
    b = r.counter("hbbft_node_a_total", "ignored", labelnames=("k",))
    assert a is b
    with pytest.raises(ValueError):
        r.gauge("hbbft_node_a_total", "now a gauge?")
    with pytest.raises(ValueError):
        r.counter("hbbft_node_a_total", "other labels", labelnames=("x",))
    with pytest.raises(ValueError):
        r.counter("1bad name", "invalid identifier")


def test_label_cardinality_cap_collapses_into_overflow():
    r = Registry()
    c = r.counter("hbbft_node_peers_total", "p", labelnames=("peer",),
                  max_label_sets=4)
    for i in range(10):
        c.labels(peer=f"p{i}").inc()
    # 4 real series; the 6 overflowing label sets all landed on the
    # sentinel series and were counted as dropped
    series = dict(
        (labels["peer"], child.get()) for labels, child in c.series()
    )
    assert len(series) == 5  # 4 real + the overflow series
    assert series[OVERFLOW] == 6
    assert r.dropped_label_sets == 6
    # total is conserved
    assert sum(series.values()) == 10


def test_histogram_reregistration_with_different_buckets_raises():
    r = Registry()
    r.histogram("hbbft_node_hb_seconds", "h", buckets=(0.01, 0.1))
    with pytest.raises(ValueError):
        r.histogram("hbbft_node_hb_seconds", "h", buckets=(1.0, 10.0))
    # same buckets → same metric back
    h = r.histogram("hbbft_node_hb_seconds", "h", buckets=(0.01, 0.1))
    assert h.buckets == (0.01, 0.1)


def test_unlabeled_metrics_always_expose_a_zero_sample():
    """A scraper must distinguish '0 so far' from 'metric absent': a
    counter that was never incremented still renders a sample line (the
    bug the verify drive caught on a fresh restarted node)."""
    r = Registry()
    r.counter("hbbft_node_replay_gaps_total", "never incremented")
    parsed = parse_prometheus_text(r.render_prometheus())
    assert parsed["hbbft_node_replay_gaps_total"] == [({}, 0.0)]


def test_histogram_buckets_must_be_strictly_increasing():
    r = Registry()
    with pytest.raises(ValueError):
        r.histogram("hbbft_node_h1_seconds", "h", buckets=(1.0, 1.0, 2.0))
    with pytest.raises(ValueError):
        r.histogram("hbbft_node_h2_seconds", "h", buckets=(2.0, 1.0))
    with pytest.raises(ValueError):
        r.histogram("hbbft_node_h3_seconds", "h", buckets=())
    # a trailing +Inf is tolerated (it is implicit)
    h = r.histogram("hbbft_node_h4_seconds", "h",
                    buckets=(0.1, 1.0, math.inf))
    assert h.buckets == (0.1, 1.0)


def test_histogram_observe_render_and_quantile():
    r = Registry()
    h = r.histogram("hbbft_phase_duration_seconds", "p",
                    labelnames=("phase",), buckets=(0.01, 0.1, 1.0))
    for v in (0.005, 0.05, 0.05, 0.5):
        h.labels(phase="rbc_echo").observe(v)
    text = r.render_prometheus()
    parsed = parse_prometheus_text(text)
    buckets = {
        labels["le"]: v
        for labels, v in parsed["hbbft_phase_duration_seconds_bucket"]
    }
    assert buckets["0.01"] == 1 and buckets["0.1"] == 3
    assert buckets["1"] == 4 and buckets["+Inf"] == 4
    assert parsed["hbbft_phase_duration_seconds_count"][0][1] == 4
    q = h.labels(phase="rbc_echo").quantile(0.5)
    assert 0.01 < q <= 0.1


def test_histogram_quantile_interpolation_and_edges():
    cum = [(0.1, 0), (1.0, 10), (math.inf, 10)]
    assert histogram_quantile(cum, 0.5) == pytest.approx(0.55)
    assert histogram_quantile(cum, 1.0) == pytest.approx(1.0)
    # all mass in +Inf reports the highest finite bound
    assert histogram_quantile([(0.1, 0), (math.inf, 5)], 0.5) == 0.1
    assert math.isnan(histogram_quantile([], 0.5))
    assert math.isnan(histogram_quantile([(0.1, 0), (math.inf, 0)], 0.5))


def test_prometheus_text_escaping_round_trips():
    r = Registry()
    c = r.counter("hbbft_node_esc_total", 'help with \\ backslash\nand "',
                  labelnames=("who",))
    tricky = 'a"b\\c\nd'
    c.labels(who=tricky).inc(2)
    text = r.render_prometheus()
    # escaped on the wire…
    assert '\\n' in text and '\\"' in text and "\\\\" in text
    for line in text.splitlines():
        assert "\n" not in line  # no raw newlines inside any sample
    # …and recoverable by the parser
    parsed = parse_prometheus_text(text)
    (labels, value), = parsed["hbbft_node_esc_total"]
    assert labels["who"] == tricky and value == 2
    assert escape_help("a\nb\\") == "a\\nb\\\\"
    assert escape_label_value('x"y') == 'x\\"y'
    # a backslash followed by 'n' must survive the round trip (the
    # sequential-replace unescape bug: '\\' + 'n' is NOT a newline)
    c.labels(who="C:\\new").inc()
    parsed2 = parse_prometheus_text(r.render_prometheus())
    whos = {l["who"] for l, _v in parsed2["hbbft_node_esc_total"]}
    assert whos == {tricky, "C:\\new"}


def test_collect_callbacks_run_before_exposition():
    r = Registry()
    g = r.gauge("hbbft_node_depth", "d")
    state = {"depth": 3}
    r.register_callback(lambda: g.set(state["depth"]))
    assert 'hbbft_node_depth 3' in r.render_prometheus()
    state["depth"] = 9
    assert 'hbbft_node_depth 9' in r.render_prometheus()


def test_fault_counter_preinitializes_every_variant():
    from hbbft_tpu.fault_log import FaultKind

    r = Registry()
    c = fault_counter(r)
    kinds = {labels["kind"] for labels, _ in c.series()}
    assert kinds == {k.name for k in FaultKind}
    # all zero until evidence arrives
    assert c.total() == 0
    text = r.render_prometheus()
    assert 'kind="InvalidProof"' in text


def test_default_buckets_are_valid():
    assert list(DEFAULT_BUCKETS) == sorted(set(DEFAULT_BUCKETS))


def test_tools_check_metrics_passes():
    """The tier-1 contract: every registered metric documented in README,
    convention-clean, and FaultKind fully labeled."""
    import tools_check_metrics

    assert tools_check_metrics.main() == 0

def setup(r):
    return r.counter("hbbft_bogus_thing_total", "bad layer, undocumented")

"""hblint fixture: the corrected async_bad — zero asyncio findings."""

import asyncio


async def worker():
    await asyncio.sleep(0)


async def pump(lock, writer):
    await worker()
    task = asyncio.create_task(worker())
    await asyncio.sleep(0.1)
    async with lock:
        writer.write(b"x")          # write() does not await
    await writer.drain()            # drain outside the lock
    await task

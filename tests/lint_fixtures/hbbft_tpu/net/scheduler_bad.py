"""Fixture: direct threshold crypto inside a net scheduler module."""

from hbbft_tpu.crypto import bls12_381 as bls


class Pump:
    def __init__(self, netinfo, ct):
        self.netinfo = netinfo
        self.ct = ct

    def process(self, pairs, share):
        # BAD: pairing product evaluated directly in the scheduler
        ok = bls.pairing_check(pairs)
        # BAD: per-message share verification bypassing the batched path
        self.netinfo.public_key_set().public_key_share(0).\
            verify_decryption_share(share, self.ct)
        # BAD: inline share generation
        self.netinfo.secret_key_share().decrypt_share(self.ct)
        return ok

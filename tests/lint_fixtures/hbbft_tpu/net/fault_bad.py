"""hblint fixture: both fault-accounting rules fire on this snippet."""


def handle(data):
    return data


def recv_frame(sock):
    try:
        return sock.read()
    except Exception:               # fault-except-pass
        pass


def process(peer, data):
    try:
        handle(data)
    except ValueError:              # fault-swallowed-drop
        return None

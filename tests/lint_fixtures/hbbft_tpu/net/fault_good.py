"""hblint fixture: the corrected fault_bad — zero fault findings."""

import contextlib


def handle(data):
    return data


def recv_frame(sock):
    with contextlib.suppress(ConnectionError):
        return sock.read()
    return None


def process(peer, data, stats):
    try:
        handle(data)
    except ValueError:
        stats.decode_failures += 1  # accounted drop
        return None

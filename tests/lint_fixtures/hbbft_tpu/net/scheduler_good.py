"""Fixture: a scheduler that delegates crypto the sanctioned way."""


class Pump:
    def __init__(self, runtime):
        self.runtime = runtime

    def process(self, events, depth):
        # crypto flows through the protocols' deferred-resolution
        # surface; the scheduler only sequences it
        outcome = self.runtime.pump_process(events, depth)
        while self.runtime.sq.has_deferred():
            self.runtime.sq.resolve_deferred()
        return outcome

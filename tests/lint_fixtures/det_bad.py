"""hblint fixture: every determinism rule fires on this snippet."""

import os
import random
import time


def encode_message(x):
    return bytes([x % 256])


def elect(epoch):
    now = time.time()           # det-wall-clock
    coin = random.random()      # det-unseeded-random
    salt = os.urandom(8)        # det-unseeded-random
    return now, coin, salt


def fan_out(peers):
    ids = {p for p in peers}
    out = b""
    for p in ids:               # det-set-iteration (loop feeds encoder)
        out += encode_message(p)
    return out


def digest_votes(votes):
    seen = set(votes)
    return b"".join(encode_message(v) for v in seen)  # det-set-iteration

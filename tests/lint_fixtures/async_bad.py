"""hblint fixture: every asyncio-hazard rule fires on this snippet."""

import asyncio
import time


async def worker():
    await asyncio.sleep(0)


async def pump(lock, writer):
    worker()                        # async-unawaited-coroutine
    asyncio.create_task(worker())   # async-fire-and-forget-task
    time.sleep(0.1)                 # async-blocking-call
    async with lock:
        writer.write(b"x")
        await writer.drain()        # async-lock-across-await

def setup(r):
    return r.counter("hbbft_node_things_total", "convention-clean")

"""bounded-ingress fixture: network-fed growth with no bounding."""


class LeakyBuffer:
    def __init__(self):
        self.held = {}
        self.log = []

    def handle_message(self, sender_id, msg):
        # grows a per-sender list from network input, never bounded
        self.held.setdefault(sender_id, []).append(msg)

    def on_frame(self, peer_id, payload):
        # grows a flat list from network input, never bounded
        self.log.append((peer_id, payload))

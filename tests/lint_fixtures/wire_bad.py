"""hblint fixture: a message-shaped dataclass with no wire registration."""

from dataclasses import dataclass


@dataclass
class OrphanMsg:                    # wire-unregistered (and mutable)
    x: int

"""bounded-ingress fixture: the same buffers, capped and evicted."""


class BoundedBuffer:
    CAP = 64

    def __init__(self):
        self.held = {}
        self.log = []
        self.evictions = 0
        self.seen_peers = set()

    def handle_message(self, sender_id, msg):
        self.held.setdefault(sender_id, []).append(msg)
        if len(self.held[sender_id]) > self.CAP:
            self.held[sender_id].pop(0)   # counted front-chop at cap
            self.evictions += 1

    def on_frame(self, peer_id, payload):
        self.log.append((peer_id, payload))
        if len(self.log) > self.CAP:
            del self.log[: len(self.log) - self.CAP]
            self.evictions += 1

    def note_peer(self, peer_id, payload):
        # adding just the sender identity is bounded by peer
        # cardinality — exempt without any cap
        self.seen_peers.add(peer_id)

"""hblint fixture: the corrected det_bad — zero determinism findings."""

import os
import random


def encode_message(x):
    return bytes([x % 256])


def elect(epoch, rng):
    # seeded instance randomness is the sanctioned source
    coin = rng.random()
    return epoch, coin


def generate_keypair():
    # key-generation entry point: OS entropy is allowed here
    return os.urandom(32), random.Random(0)


def fan_out(peers):
    ids = {p for p in peers}
    out = b""
    for p in sorted(ids):       # deterministic order
        out += encode_message(p)
    return out


def digest_votes(votes):
    seen = set(votes)
    return b"".join(encode_message(v) for v in sorted(seen))

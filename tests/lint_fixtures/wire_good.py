"""hblint fixture: a frozen, (synthetically) registered message class."""

from dataclasses import dataclass


@dataclass(frozen=True)
class PlainMsg:                     # registered via the test's injection
    x: int

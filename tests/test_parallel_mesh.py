"""Sharded (shard_map) RBC must be bit-identical to the single-device path.

Runs on the 8-virtual-device CPU mesh configured by conftest.py — this is
the test that actually requires all 8 devices.
"""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402
from jax.sharding import Mesh  # noqa: E402

from hbbft_tpu.parallel.mesh import sharded_rbc_run
from hbbft_tpu.parallel.rbc import BatchedRbc, frame_values, unframe_value


@pytest.fixture
def mesh8():
    devs = jax.devices()
    if len(devs) < 8:
        pytest.skip("needs 8 devices (conftest sets the virtual CPU mesh)")
    return Mesh(np.array(devs[:8]), ("nodes",))


def test_sharded_matches_single_device(mesh8):
    n, f = 8, 2
    rbc = BatchedRbc(n, f)
    values = [bytes([p]) * (3 * p + 1) for p in range(n)]
    data = jnp.asarray(frame_values(values, rbc.k))

    # compare against the MASKED single-device path (explicit all-ones
    # masks): the maskless call takes the shared-row fast path, whose
    # result layout differs by design
    ones_vm = jnp.ones((n, n), dtype=bool)
    ones_em = jnp.ones((n, n, n), dtype=bool)
    single = {
        k: np.asarray(v)
        for k, v in jax.jit(rbc.run)(
            data, value_mask=ones_vm, echo_mask=ones_em, ready_mask=ones_em
        ).items()
    }
    sharded = {
        k: np.asarray(v) for k, v in sharded_rbc_run(rbc, mesh8, data).items()
    }

    for key in single:
        np.testing.assert_array_equal(sharded[key], single[key], err_msg=key)
    assert single["delivered"].all()
    for j in range(n):
        for p in range(n):
            assert unframe_value(sharded["data"][j, p]) == values[p]


def test_sharded_matches_single_device_with_masks_and_tamper(mesh8):
    n, f = 8, 2
    rbc = BatchedRbc(n, f)
    values = [bytes([p + 1]) * 10 for p in range(n)]
    data = frame_values(values, rbc.k)
    rng = np.random.default_rng(9)

    em = ~(rng.random((n, n, n)) < 0.15)
    for i in range(n):
        em[i, i, :] = True
    vt = np.zeros((n, n, data.shape[-1]), dtype=np.uint8)
    vt[2, 5, 0] = 0x77  # proposer 2's Value to node 5 corrupted in flight

    kw = dict(
        echo_mask=jnp.asarray(em),
        value_tamper=jnp.asarray(vt),
    )
    single = {
        k: np.asarray(v)
        for k, v in jax.jit(
            lambda d: rbc.run(d, **kw)
        )(jnp.asarray(data)).items()
    }
    sharded = {
        k: np.asarray(v)
        for k, v in sharded_rbc_run(rbc, mesh8, jnp.asarray(data), **kw).items()
    }
    for key in single:
        np.testing.assert_array_equal(sharded[key], single[key], err_msg=key)
    assert sharded["delivered"].any()


def test_sharded_2d_mesh_matches_single_device(mesh8):
    """Hierarchical (hosts × chips — DCN × ICI) mesh: the node axis shards
    over both axes; the Value/Echo fan-out gathers ICI-first.  Results must
    be bit-identical to the 1-axis mesh and the single-device path."""
    devs = jax.devices()[:8]
    mesh2d = Mesh(np.array(devs).reshape(2, 4), ("dcn", "ici"))

    n, f = 8, 2
    rbc = BatchedRbc(n, f)
    values = [bytes([p + 3]) * 7 for p in range(n)]
    data = jnp.asarray(frame_values(values, rbc.k))

    ones_vm = jnp.ones((n, n), dtype=bool)
    ones_em = jnp.ones((n, n, n), dtype=bool)
    single = {
        k: np.asarray(v)
        for k, v in jax.jit(rbc.run)(
            data, value_mask=ones_vm, echo_mask=ones_em, ready_mask=ones_em
        ).items()
    }
    sharded = {
        k: np.asarray(v)
        for k, v in sharded_rbc_run(rbc, mesh2d, data).items()
    }
    for key in single:
        np.testing.assert_array_equal(sharded[key], single[key], err_msg=key)
    assert sharded["delivered"].all()


# ---------------------------------------------------------------------------
# Sharded ABA + full sharded HoneyBadger epoch
# ---------------------------------------------------------------------------


def test_sharded_aba_matches_single_device_full_delivery(mesh8):
    from hbbft_tpu.parallel.aba import BatchedAba
    from hbbft_tpu.parallel.mesh import make_sharded_aba_step

    n, f = 8, 2
    aba = BatchedAba(n, f)
    rng = np.random.default_rng(3)
    est = jnp.asarray(rng.random((n, n)) < 0.5)

    st_s = aba.init_state(est)
    st_m = aba.init_state(est)
    step_s = jax.jit(aba.epoch_step)
    step_m = make_sharded_aba_step(aba, mesh8)
    for e in range(9):
        coins = jnp.asarray(rng.random((n,)) < 0.5)
        st_s = step_s(st_s, coins)
        st_m = step_m(st_m, coins)
        for k in ("est", "decided", "decision"):
            np.testing.assert_array_equal(
                np.asarray(st_m[k]), np.asarray(st_s[k]), err_msg=f"{k}@{e}"
            )
        if bool(np.asarray(st_s["decided"]).all()):
            break
    assert bool(np.asarray(st_s["decided"]).all())


def test_sharded_aba_matches_single_device_masked(mesh8):
    from hbbft_tpu.parallel.aba import BatchedAba
    from hbbft_tpu.parallel.mesh import make_sharded_aba_step

    n, f = 8, 2
    aba = BatchedAba(n, f)
    rng = np.random.default_rng(5)
    est = jnp.asarray(rng.random((n, n)) < 0.5)

    st_s = aba.init_state(est)
    st_m = aba.init_state(est)
    step_s = jax.jit(aba.epoch_step)
    step_m = make_sharded_aba_step(aba, mesh8)
    for e in range(12):
        coins = jnp.asarray(rng.random((n,)) < 0.5)
        # random delivery drops, self-delivery forced inside the step
        bm = jnp.asarray(~(rng.random((n, n, n)) < 0.2))
        am = jnp.asarray(~(rng.random((n, n, n)) < 0.2))
        cm = jnp.asarray(~(rng.random((n, n, n)) < 0.2))
        st_s = step_s(st_s, coins, bval_mask=bm, aux_mask=am, conf_mask=cm)
        st_m = step_m(st_m, coins, bval_mask=bm, aux_mask=am, conf_mask=cm)
        for k in ("est", "decided", "decision"):
            np.testing.assert_array_equal(
                np.asarray(st_m[k]), np.asarray(st_s[k]), err_msg=f"{k}@{e}"
            )


def test_sharded_full_hb_epoch_matches_single_device(mesh8):
    """The complete epoch — RBC fan-out, ABA epochs, TPKE decrypt — on the
    8-device mesh, byte-identical Batch to the single-device array path."""
    import random as pyrandom

    from hbbft_tpu.netinfo import NetworkInfo
    from hbbft_tpu.parallel.acs import BatchedHoneyBadgerEpoch

    n = 8
    rng = pyrandom.Random(11)
    netinfo = NetworkInfo.generate_map(list(range(n)), rng)

    contribs = {i: bytes([i + 1]) * (5 + i) for i in range(n)}
    single = BatchedHoneyBadgerEpoch(netinfo, session_id=b"mesh-cmp")
    batch_s, out_s = single.run(dict(contribs), pyrandom.Random(42))

    sharded = BatchedHoneyBadgerEpoch(netinfo, session_id=b"mesh-cmp",
                                      mesh=mesh8)
    batch_m, out_m = sharded.run(dict(contribs), pyrandom.Random(42))

    assert batch_m == batch_s
    np.testing.assert_array_equal(out_m["accepted"], out_s["accepted"])
    assert out_m["epochs"] == out_s["epochs"]


def test_sharded_msm_matches_single_device_and_host(mesh8):
    """The batch-verify/decrypt MSM ladders row-sharded over the mesh:
    same results as single-device and the host oracle."""
    import random

    from hbbft_tpu.crypto import batch as CB
    from hbbft_tpu.crypto import bls12_381 as c

    rng = random.Random(43)
    B = 8  # pads to 8 = one row per device
    pts = [c.g1_mul(c.G1_GEN, rng.randrange(1, c.R)) for _ in range(B - 1)]
    pts.append(None)
    sc = [rng.randrange(1, 1 << 128) for _ in range(B - 1)] + [11]

    single = CB._MsmCache()._msm("g1", pts, sc)
    sharded = CB._MsmCache(mesh=mesh8)._msm("g1", pts, sc)
    expect = None
    for p, s in zip(pts, sc):
        expect = c.g1_add(expect, c.g1_mul(p, s))
    assert c.g1_eq(single, expect)
    assert c.g1_eq(sharded, expect)


def test_sharded_batch_verify_and_decrypt(mesh8):
    """use_mesh() routes the whole crypto phase (share batch-verify and
    TPKE decrypt) over the mesh; results equal the single-device path."""
    import random

    from hbbft_tpu.crypto import batch as CB
    from hbbft_tpu.crypto.tc import SecretKeySet

    rng = random.Random(47)
    n, f = 8, 2
    sks = SecretKeySet.random(f, rng)
    pks = sks.public_keys()
    msg = b"mesh-coin"
    pairs = [
        (pks.public_key_share(i), sks.secret_key_share(i).sign(msg))
        for i in range(n)
    ]
    ct = pks.public_key().encrypt(b"mesh secret", rng)
    shares = [(i, sks.secret_key_share(i)) for i in range(f + 1)]

    CB.use_mesh(mesh8)
    try:
        assert CB.batch_verify_sig_shares(pairs, msg, rng) is True
        forged = list(pairs)
        forged[3] = (pairs[3][0], sks.secret_key_share(3).sign(b"z"))
        assert CB.batch_verify_sig_shares(forged, msg, rng) is False
        assert CB.batch_tpke_decrypt(pks, [ct], shares) == [b"mesh secret"]
    finally:
        CB.use_mesh(None)


def test_sharded_large_rbc_matches_single_device(mesh8):
    """N > 256 (GF(2^16) scale path): proposer-axis-sharded large-N RBC
    round bit-equal to the single-device ``_run_large`` — the round-5
    removal of the mesh's N ≤ 256 cap."""
    from hbbft_tpu.parallel.mesh import make_sharded_rbc_large_run

    n, f = 264, 87  # smallest large-N shape divisible by the 8 devices
    rbc = BatchedRbc(n, f)
    values = [bytes([i % 251 + 1]) * (3 + i % 5) for i in range(n)]
    data = jnp.asarray(frame_values(values, rbc.k))

    out_single = rbc.run(data)
    out_mesh = make_sharded_rbc_large_run(rbc, mesh8)(data)

    np.testing.assert_array_equal(out_mesh["delivered"],
                                  np.asarray(out_single["delivered"]))
    np.testing.assert_array_equal(out_mesh["root"],
                                  np.asarray(out_single["root"]))
    np.testing.assert_array_equal(out_mesh["data"],
                                  np.asarray(out_single["data"]))
    for p in (0, 131, 263):
        assert unframe_value(out_mesh["data"][0, p]) == values[p]


def test_sharded_large_rbc_codeword_tamper(mesh8):
    """Tamper semantics are identical on the sharded large-N path.

    Under FULL delivery a parity-only codeword corruption still delivers
    (the decode uses the intact data rows and present shards match the
    commitment — same as object mode; inconsistency only surfaces when a
    data shard must be reconstructed from corrupted parity, which the
    masked path's erasure tests cover).  A value_tamper (shards modified
    AFTER the commit) must be rejected."""
    from hbbft_tpu.parallel.mesh import make_sharded_rbc_large_run

    n, f = 264, 87
    rbc = BatchedRbc(n, f)
    values = [b"v%d" % i for i in range(n)]
    data = jnp.asarray(frame_values(values, rbc.k))
    tamper = np.zeros((n, n, data.shape[-1]), dtype=np.uint8)
    tamper[3, rbc.k:, :] = 0x5A  # proposer 3: corrupt all parity shards
    tamper = jnp.asarray(tamper)

    run_mesh = make_sharded_rbc_large_run(rbc, mesh8)
    out_single = rbc.run(data, codeword_tamper=tamper)
    out_mesh = run_mesh(data, codeword_tamper=tamper)
    np.testing.assert_array_equal(out_mesh["delivered"],
                                  np.asarray(out_single["delivered"]))
    np.testing.assert_array_equal(out_mesh["fault"],
                                  np.asarray(out_single["fault"]))
    assert out_mesh["delivered"][0, 3]  # consistent commitment → delivers

    # post-commit tampering of enough shards starves the decode below the
    # N−f echo threshold → not delivered, on both paths identically
    vt = np.zeros((n, n, data.shape[-1]), dtype=np.uint8)
    vt[5, : n - f + 1, :] = 0xA5
    vt = jnp.asarray(vt)
    out_single_vt = rbc.run(data, value_tamper=vt)
    out_mesh_vt = run_mesh(data, value_tamper=vt)
    np.testing.assert_array_equal(out_mesh_vt["delivered"],
                                  np.asarray(out_single_vt["delivered"]))
    assert not out_mesh_vt["delivered"][0, 5]
    assert out_mesh_vt["delivered"][0, 6]


def test_sharded_large_full_hb_epoch_matches_single_device(mesh8):
    """The COMPLETE HoneyBadger epoch at N > 256 on the mesh (sharded
    large-N RBC + sharded ABA + batched TPKE), identical batch to the
    single-device scale path."""
    import random as pyrandom

    from hbbft_tpu.netinfo import NetworkInfo
    from hbbft_tpu.parallel.acs import BatchedHoneyBadgerEpoch

    n = 264
    rng = pyrandom.Random(17)
    netinfo = NetworkInfo.generate_map(list(range(n)), rng)
    contribs = {i: b"tx-%d" % i for i in range(n)}

    single = BatchedHoneyBadgerEpoch(netinfo, session_id=b"mesh-large",
                                     compact=True)
    batch_s, out_s = single.run(dict(contribs), pyrandom.Random(4))

    sharded = BatchedHoneyBadgerEpoch(netinfo, session_id=b"mesh-large",
                                      mesh=mesh8, compact=True)
    batch_m, out_m = sharded.run(dict(contribs), pyrandom.Random(4))

    assert batch_m == batch_s == contribs
    assert out_m["epochs"] == out_s["epochs"]


@pytest.mark.slow  # a sharded-epoch compile + an N=8→9 DKG (~9 min on CPU)
def test_dynamic_membership_on_the_mesh(mesh8):
    """The dynamic driver rides the mesh: era 0 (N=8, sharded) votes a
    node in; era 1 (N=9, which 8 devices no longer divide) falls back to
    the single-device path — the documented rotation behavior — and the
    ledger of batches stays correct throughout."""
    import random

    from hbbft_tpu.crypto import tc
    from hbbft_tpu.netinfo import NetworkInfo
    from hbbft_tpu.parallel.dhb import BatchedDynamicHoneyBadger

    infos = NetworkInfo.generate_map(list(range(8)), random.Random(77))
    dhb = BatchedDynamicHoneyBadger(
        infos, session_id=b"mesh-dhb", rng=random.Random(5), mesh=mesh8
    )
    assert dhb.hb.acs.mesh is mesh8  # era 0 runs sharded
    b0 = dhb.run_epoch({nid: b"m0-%d" % nid for nid in dhb.validators})
    assert dict(b0.contributions) == {
        nid: b"m0-%d" % nid for nid in range(8)
    }
    new_sk = tc.SecretKey.random(random.Random(6))
    for voter in range(8):
        dhb.vote_to_add(voter, 8, new_sk.public_key(), secret_key=new_sk)
    dhb.run_epoch({nid: b"" for nid in dhb.validators})
    dhb.run_until_change_completes()
    assert dhb.era == 1 and sorted(dhb.validators) == list(range(9))
    assert dhb.hb.acs.mesh is None  # 9 % 8 != 0 → single-device fallback
    b1 = dhb.run_epoch({nid: b"m1-%d" % nid for nid in dhb.validators})
    assert dict(b1.contributions) == {
        nid: b"m1-%d" % nid for nid in range(9)
    }


# ---------------------------------------------------------------------------
# Round-6: full-TPKE epochs on the mesh — N=64 tier-1, scale shapes slow
# ---------------------------------------------------------------------------


def _run_encrypted_epoch(n, mesh, seed, compact=False):
    import random as pyrandom

    from hbbft_tpu.netinfo import NetworkInfo
    from hbbft_tpu.parallel.acs import BatchedHoneyBadgerEpoch

    netinfo = NetworkInfo.generate_map(list(range(n)), pyrandom.Random(seed))
    contribs = {i: b"tx-%d|" % i + bytes([i & 0xFF]) * (i % 7) for i in range(n)}
    hb = BatchedHoneyBadgerEpoch(netinfo, session_id=b"mesh-enc-%d" % n,
                                 mesh=mesh, compact=compact)
    payloads = hb.encrypt_phase(dict(contribs), pyrandom.Random(42))
    batch, out = hb.run_from_payloads(payloads, encrypt=True)
    return contribs, payloads, batch, out


def test_sharded_full_encrypted_epoch_n64_matches_single_device(mesh8):
    """The tentpole equality check: one FULL-TPKE epoch at N=64 — TPKE
    encrypt, batched RBC, ABA, coin batch, master-scalar-folded threshold
    decrypt — run once on the virtual 8-device mesh and once single-device,
    with bit-identical ciphertext payloads, detail arrays, and batch."""
    contribs_s, pay_s, batch_s, out_s = _run_encrypted_epoch(64, None, 23)
    contribs_m, pay_m, batch_m, out_m = _run_encrypted_epoch(64, mesh8, 23)

    assert contribs_m == contribs_s
    assert pay_m == pay_s  # ciphertext bytes (encrypt phase) identical
    assert batch_m == batch_s == contribs_s  # decrypted plaintexts identical
    assert out_m["epochs"] == out_s["epochs"]
    for key in ("accepted", "delivered"):
        np.testing.assert_array_equal(
            np.asarray(out_m[key]), np.asarray(out_s[key]), err_msg=key
        )
    # the maskless single-device RBC takes the shared-row fast path, whose
    # data LAYOUT differs by design (see test_sharded_matches_single_device)
    # — so compare per-proposer delivered VALUES, not raw arrays: every
    # delivered ciphertext must unframe to the same encrypt-phase payload
    for out in (out_s, out_m):
        row_of = {
            int(r): i for i, r in enumerate(out["data_receivers"])
        }
        for p in range(64):
            deliverers = np.flatnonzero(out["delivered"][:, p])
            assert deliverers.size > 0
            rows = [row_of[int(d)] for d in deliverers if int(d) in row_of]
            got = unframe_value(out["data"][rows[0], p])
            assert got == pay_s[p], f"proposer {p} payload diverged"


def test_sharded_coin_verify_hook_matches_plain(mesh8):
    """make_sharded_coin_verify — the coin-share batch-verification entry
    the mesh-carrying epoch pins — returns the same verdicts as the plain
    batch_verify_sig_shares, valid and forged."""
    import random

    from hbbft_tpu.crypto.batch import batch_verify_sig_shares
    from hbbft_tpu.crypto.tc import SecretKeySet
    from hbbft_tpu.parallel.mesh import make_sharded_coin_verify

    rng = random.Random(53)
    # n=8/f=2 matches test_sharded_batch_verify_and_decrypt: the ladder
    # cache is keyed by batch size, so this test reuses those compiles
    n, f = 8, 2
    sks = SecretKeySet.random(f, rng)
    pks = sks.public_keys()
    msg = b"round-6 coin"
    pairs = [
        (pks.public_key_share(i), sks.secret_key_share(i).sign(msg))
        for i in range(n)
    ]
    verify = make_sharded_coin_verify(mesh8)
    assert verify(pairs, msg, rng) is True
    assert batch_verify_sig_shares(pairs, msg, rng) is True
    forged = list(pairs)
    forged[7] = (pairs[7][0], sks.secret_key_share(7).sign(b"not it"))
    assert verify(forged, msg, rng) is False
    assert batch_verify_sig_shares(forged, msg, rng) is False


def test_sharded_decrypt_hook_matches_plain(mesh8):
    """make_sharded_decrypt — the epoch's pinned threshold-decrypt entry —
    yields plaintexts byte-identical to batch_tpke_check_decrypt, and
    rejects malformed payloads the same way."""
    import random

    from hbbft_tpu.crypto.batch import batch_tpke_check_decrypt
    from hbbft_tpu.crypto.tc import SecretKeySet
    from hbbft_tpu.parallel.mesh import make_sharded_decrypt

    rng = random.Random(59)
    f = 2  # one ciphertext, f=2 — the shapes the mesh tests already compile
    sks = SecretKeySet.random(f, rng)
    pks = sks.public_keys()
    msgs = [b"payload-0"]
    payloads = [
        pks.public_key().encrypt(m, rng).to_bytes() for m in msgs
    ]
    shares = [(i, sks.secret_key_share(i)) for i in range(f + 1)]

    decrypt = make_sharded_decrypt(mesh8)
    assert decrypt(pks, payloads, shares) == msgs
    assert batch_tpke_check_decrypt(pks, payloads, shares) == msgs
    bad = list(payloads)
    bad[0] = b"\x00" * len(bad[0])
    with pytest.raises(ValueError):
        decrypt(pks, bad, shares)


@pytest.mark.slow  # a full N=4096 encrypted epoch twice on CPU (~minutes)
def test_sharded_full_encrypted_epoch_n4096_matches_single_device(mesh8):
    """The hb-epoch4096 shape: mesh vs single-device full-TPKE epoch at
    N=4096 (compact mode, as the scale drivers run it)."""
    contribs_s, pay_s, batch_s, out_s = _run_encrypted_epoch(
        4096, None, 29, compact=True
    )
    _, pay_m, batch_m, out_m = _run_encrypted_epoch(
        4096, mesh8, 29, compact=True
    )
    assert pay_m == pay_s
    assert batch_m == batch_s == contribs_s
    assert out_m["epochs"] == out_s["epochs"]


@pytest.mark.slow  # the first N=16384 epoch — mesh-only (single would 2x it)
def test_sharded_full_encrypted_epoch_n16384_runs(mesh8):
    """First N=16384 full-TPKE epoch: runs to completion on the mesh and
    commits exactly the proposed contributions (compact mode's
    cross-node agreement checks are the safety net)."""
    contribs, _, batch, out = _run_encrypted_epoch(
        16384, mesh8, 31, compact=True
    )
    assert batch == contribs
    assert out["epochs"] >= 1


def test_dryrun_multichip_quick_smoke(capsys):
    """Tier-1 driver-surface smoke: dryrun_multichip(8, quick=True) must
    emit the MULTICHIP trajectory payload with the sharded path engaged."""
    import importlib
    import json
    import os
    import sys

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if root not in sys.path:
        sys.path.insert(0, root)
    ge = importlib.import_module("__graft_entry__")
    ge.dryrun_multichip(8, quick=True)
    lines = [
        ln for ln in capsys.readouterr().out.splitlines()
        if ln.startswith("{") and "multichip_epoch_trajectory" in ln
    ]
    assert lines, "no MULTICHIP payload line on stdout"
    doc = json.loads(lines[-1])
    assert doc["ok"] is True
    assert doc["n_devices"] > 1
    assert doc["sharded_epoch_engaged"] is True
    assert doc["unit"] == "epochs/s"
    nds = [p["n_devices"] for p in doc["trajectory"]]
    assert nds[0] == 1 and nds[-1] == doc["n_devices"]
    assert all(p["epochs_per_s"] > 0 for p in doc["trajectory"])

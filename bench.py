#!/usr/bin/env python
"""Benchmark harness — measures the TPU kernel path against the host oracle.

Mirrors the role of the reference's ``examples/simulation.rs`` (the only
performance artifact upstream ships): a CLI that times the hot protocol
kernels at the BASELINE.json config shapes and reports throughput.  Upstream
publishes no numbers (see BASELINE.md), so ``vs_baseline`` here is the
measured speedup of the device path over the single-threaded host oracle
(numpy/hashlib) on the same workload — the honest stand-in for "reference
wall-clock" until a runnable reference exists.

Prints exactly ONE JSON line to stdout:
    {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, ...detail}
Per-config detail goes to stderr.

Configs (BASELINE.md):
  hb-epoch  full batched HoneyBadger epoch (TPKE → RBC → ABA → decrypt)
            vs the object-mode simulator (config-1 shape at N=16) — the
            headline metric.
  hb-epoch64 / hb-epoch1024 / hb-epoch4096
            the same full epoch at N=64 / 1024 / 4096 (master-scalar
            decrypt fold); host baseline extrapolated from N=16; all
            hb-epoch* configs shard the whole pipeline over the --mesh /
            HBBFT_EPOCH_MESH device mesh (auto on multi-device hosts)
            and record mesh_devices + per-phase attribution.
  hb-epoch16384
            first-ever N=16384 full-TPKE epoch — explicit-only and
            informational (hours-scale; records completion, not a gate).
  acs1024   BASELINE config 4: full ACS at N=1024 (GF(2^16) coder).
  rbc-round one full batched RBC round (N=64) vs object mode.
  rbc64     N=64 f=21 RBC shard pipeline: RS encode + Merkle build,
            batched over 64 proposer instances (one ACS round's proposals).
  rbc64-reconstruct   RS reconstruct from the worst-case survivor set.
  sha3      batched SHA3-256 digests (Merkle/coin workhorse).
  coin256   BASELINE config 3: randomized-linear-combination batch verify
            of 256 signature shares (device ladders + one pairing check).
  dkg256    DKG hot loop: BivarCommitment.row at t=85 (device GLV ladder
            vs the C++ oracle).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import Optional

import numpy as np


def _frozen_host(metric):
    """(t_host_s, record) from BASELINE_MEASURED.json's ``host_baselines``
    map — the frozen measured denominators for the non-headline configs
    (written once by ``--freeze-baselines``)."""
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "BASELINE_MEASURED.json")
    try:
        with open(path) as fh:
            rec = json.load(fh)["host_baselines"][metric]
        return float(rec["t_host_s"]), rec
    except (KeyError, TypeError, ValueError, OSError):
        return None


def _apply_frozen(out, t_measured):
    """Pin ``vs_baseline`` to the FROZEN host measurement when one is on
    record, so the ratio stops moving every time the live host path gets
    faster (the round-5 pattern: coin256/dkg256/N=16-epoch ratios fell
    purely because the oracle denominator got the same endomorphism
    speedups).  The live host time stays as ``t_host_live_s``."""
    hit = _frozen_host(out["metric"])
    if hit is None:
        return out
    t_host, rec = hit
    if "t_host_s" in out:
        out["t_host_live_s"] = out.pop("t_host_s")
    out["t_host_s"] = round(t_host, 6)
    out["vs_baseline"] = round(t_host / t_measured, 2)
    out["baseline_frozen"] = rec.get("measured_utc", "frozen")
    return out


def _timeit(fn, *, warmup: int = 2, iters: int = 10, min_time: float = 0.2):
    """Median wall-clock seconds per call; fn must block until done."""
    for _ in range(warmup):
        fn()
    times = []
    total = 0.0
    for _ in range(iters):
        t0 = time.perf_counter()
        fn()
        dt = time.perf_counter() - t0
        times.append(dt)
        total += dt
        if total > min_time and len(times) >= 3:
            break
    return float(np.median(times))


def _timeit_best(fn, *, reps: int = 5, **kw):
    """min of ``reps`` independent :func:`_timeit` medians.  On the
    shared-host boxes the bench runs on, scheduler interference only
    ever ADDS time — the smallest repeatable measurement is the closest
    to the true cost.  Used (for BOTH sides of the ratio) by configs
    whose per-call time is small enough that a single median still
    carries the jitter."""
    return min(_timeit(fn, **kw) for _ in range(reps))


def _timeit_device(step, x0, *, target_s: float = 2.0):
    """Seconds per application of ``step`` (an x→x function, same pytree).

    The TPU in this environment sits behind a network tunnel where
    ``block_until_ready`` has been observed to return before compute finishes
    and per-dispatch overhead is large and noisy (~100 ms spikes).  So the
    repetition happens ON DEVICE: one jitted ``fori_loop`` chains ``step``
    n times (each iteration's output feeds the next input, so nothing can be
    hoisted), one launch, one device→host fetch as the fence.  n is grown
    until total time ≥ ``target_s`` so fixed overhead is amortized away, then
    per-step time = (T(n) − T(1)) / (n − 1).
    """
    import jax

    @jax.jit
    def loop(x, n):  # dynamic trip count → compiles exactly once
        return jax.lax.fori_loop(0, n, lambda i, x: step(x), x)

    def fetch(x):
        leaf = jax.tree_util.tree_leaves(x)[0]
        return np.asarray(leaf).ravel()[0]

    def run(n):
        t0 = time.perf_counter()
        fetch(loop(x0, n))
        return time.perf_counter() - t0

    run(1)  # compile + warm
    t1 = min(run(1) for _ in range(3))  # fixed overhead + one step
    n = 4
    while True:
        tn = min(run(n) for _ in range(2))
        if tn >= target_s or n >= 1 << 14:
            return max((tn - t1) / (n - 1), 1e-9)
        n *= 4


def bench_rbc64(n: int = 64, f: int = 21, shard_len: int = 1024,
                instances: int = 64):
    """One ACS round of RBC proposer work: RS encode + Merkle build, all
    proposers batched.  Reference hot loops #3 and #4 (SURVEY §3.5)."""
    import jax
    import jax.numpy as jnp

    from hbbft_tpu.ops.merkle import MerkleTree, merkle_build_jax
    from hbbft_tpu.ops.rs import for_n_f

    rs = for_n_f(n, f)
    k = rs.data_shards
    rng = np.random.default_rng(0)
    data = rng.integers(0, 256, size=(instances, k, shard_len), dtype=np.uint8)

    # --- device path: encode all instances, Merkle-commit all shard sets ---
    @jax.jit
    def pipeline(d):
        shards = rs.encode_jax(d)                       # (I, n, B)
        root, proof, mask = merkle_build_jax(shards)    # (I, 32), ...
        return shards, root, proof

    def step(d):
        # fold all outputs back into the next input so the loop cannot hoist
        shards, root, proof = pipeline(d)
        fold = root[:, None, :1] ^ jnp.sum(proof, dtype=jnp.uint32).astype(jnp.uint8)
        return shards[:, :k, :] ^ fold

    d_dev = jnp.asarray(data)
    out = pipeline(d_dev)
    t_dev = _timeit_device(step, d_dev)

    # --- host oracle: same work, single thread ---
    def host_once():
        for i in range(instances):
            shards = rs.encode_np(data[i])
            MerkleTree([bytes(s) for s in shards])

    t_host = _timeit(host_once, warmup=1, iters=3, min_time=0.1)

    # correctness spot-check device vs host
    shards_dev = np.asarray(out[0][0])
    np.testing.assert_array_equal(shards_dev, rs.encode_np(data[0]))
    root_dev = bytes(np.asarray(out[1][0]))
    assert root_dev == MerkleTree(
        [bytes(s) for s in rs.encode_np(data[0])]).root_hash()

    in_bytes = instances * k * shard_len
    return _apply_frozen({
        "metric": "rbc64_encode_merkle",
        "value": round(in_bytes / t_dev / 1e6, 2),
        "unit": "MB/s",
        "vs_baseline": round(t_host / t_dev, 2),
        "t_device_s": round(t_dev, 6),
        "t_host_s": round(t_host, 6),
        "shape": f"N={n} f={f} I={instances} B={shard_len}",
    }, t_dev)


def bench_rbc64_reconstruct(n: int = 64, f: int = 21, shard_len: int = 1024,
                            instances: int = 64):
    """RS reconstruct from the worst-case survivor set (last data_shards
    rows, i.e. all-parity-heavy), batched over instances."""
    import jax
    import jax.numpy as jnp

    from hbbft_tpu.ops.rs import for_n_f

    rs = for_n_f(n, f)
    k = rs.data_shards
    rng = np.random.default_rng(1)
    data = rng.integers(0, 256, size=(instances, k, shard_len), dtype=np.uint8)
    full = np.stack([rs.encode_np(d) for d in data])    # (I, n, B)
    use = tuple(range(n - k, n))                         # worst case: no data rows
    survivors = full[:, list(use), :]

    @jax.jit
    def recon(s):
        return rs.reconstruct_jax(s, use)

    def step(s):
        # reconstruct is linear algebra: cost is data-independent, so feeding
        # the (garbage after round 1) output back is a valid timing chain
        return recon(s)

    s_dev = jnp.asarray(survivors)
    out = recon(s_dev)
    np.testing.assert_array_equal(np.asarray(out[0]), data[0])
    t_dev = _timeit_device(step, s_dev)

    # Same work as reconstruct_jax: the (data × data) decode matmul only —
    # reconstruct_np would additionally re-encode all n shards, which would
    # unfairly inflate t_host.
    from hbbft_tpu.ops import gf256

    dec = rs._decode_matrix(use)

    def host_once():
        for i in range(instances):
            gf256.gf_matmul_np(dec, survivors[i])

    t_host = _timeit(host_once, warmup=1, iters=3, min_time=0.1)
    out_bytes = instances * k * shard_len
    return _apply_frozen({
        "metric": "rbc64_reconstruct",
        "value": round(out_bytes / t_dev / 1e6, 2),
        "unit": "MB/s",
        "vs_baseline": round(t_host / t_dev, 2),
        "t_device_s": round(t_dev, 6),
        "t_host_s": round(t_host, 6),
        "shape": f"N={n} f={f} I={instances} B={shard_len}",
    }, t_dev)


def bench_sha3(batch: int = 4096, msg_len: int = 136):
    """Batched SHA3-256 — the Merkle/coin digest workhorse."""
    import hashlib

    import jax
    import jax.numpy as jnp

    from hbbft_tpu.ops.keccak import sha3_256

    rng = np.random.default_rng(2)
    msgs = rng.integers(0, 256, size=(batch, msg_len), dtype=np.uint8)

    fn = jax.jit(sha3_256)

    def step(m):
        h = sha3_256(m)                       # (batch, 32)
        fold = jnp.tile(h, (1, (msg_len + 31) // 32))[:, :msg_len]
        return m ^ fold

    m_dev = jnp.asarray(msgs)
    out = fn(m_dev)
    assert bytes(np.asarray(out[0])) == hashlib.sha3_256(msgs[0].tobytes()).digest()
    t_dev = _timeit_device(step, m_dev)

    def host_once():
        for i in range(batch):
            hashlib.sha3_256(msgs[i].tobytes()).digest()

    t_host = _timeit(host_once, warmup=1, iters=3, min_time=0.05)
    return _apply_frozen({
        "metric": "sha3_256_batched",
        "value": round(batch / t_dev, 1),
        "unit": "digests/s",
        "vs_baseline": round(t_host / t_dev, 2),
        "t_device_s": round(t_dev, 6),
        "t_host_s": round(t_host, 6),
        "shape": f"batch={batch} len={msg_len}",
    }, t_dev)


def bench_rbc_round(n: int = 64, f: int = 21, msg_len: int = 512):
    """One FULL batched RBC round — N proposers × N receivers through
    Value/Echo/Ready/decode (the batched simulator's unit of work; BASELINE
    config 2 shape).  Host baseline: the object-mode hot path per receiver —
    N proposer encodes+commits, then per (receiver, proposer) proof checks
    and reconstruct+re-encode+recommit, single-threaded (scaled from a
    sample; the full N² object loop takes minutes)."""
    import jax
    import jax.numpy as jnp

    from hbbft_tpu.ops.merkle import MerkleTree
    from hbbft_tpu.parallel.rbc import BatchedRbc, frame_values

    rbc = BatchedRbc(n, f)
    rng = np.random.default_rng(3)
    values = [rng.integers(0, 256, size=msg_len, dtype=np.uint8).tobytes()
              for _ in range(n)]
    data = frame_values(values, rbc.k)

    d_dev = jnp.asarray(data)
    fn = jax.jit(rbc.run)
    out0 = fn(d_dev)
    assert bool(np.asarray(out0["delivered"]).all())
    # a round is ~1s on device — direct fenced timing is fine (tunnel noise
    # is ~0.1s) and avoids recompiling inside the fori wrapper
    times = []
    for _ in range(5):
        t0 = time.perf_counter()
        out = fn(d_dev)
        np.asarray(out["delivered"])  # hard fence
        times.append(time.perf_counter() - t0)
    t_dev = float(np.median(times))

    # host oracle: one receiver's work for one proposer, × N² (sampled)
    sample = 4
    shards = [rbc.coder.encode_np(data[p]) for p in range(sample)]
    trees = [MerkleTree([bytes(s) for s in sh]) for sh in shards]

    def host_once():
        for p in range(sample):
            proofs = [trees[p].proof(i) for i in range(n)]
            ok = all(pr.validate(n) for pr in proofs)
            sh = [bytes(s) for s in shards[p]]
            full = rbc.coder.reconstruct_np(sh)
            t2 = MerkleTree(full)
            assert ok and t2.root_hash() == trees[p].root_hash()

    t_host_sample = _timeit(host_once, warmup=1, iters=3, min_time=0.1)

    def propose_once(p):
        sh = rbc.coder.encode_np(data[p])
        MerkleTree([bytes(s) for s in sh])

    # full host round: N proposer encodes+commits + N receivers × N proposers
    t_host = t_host_sample / sample * n * n + sum(
        _timeit(lambda p=p: propose_once(p), warmup=0, iters=1, min_time=0.0)
        for p in range(sample)
    ) / sample * n

    # NOT _apply_frozen-wrapped: freeze_baselines deliberately records no
    # rbc_round_batched entry (its host figure derives from sampled
    # device-built commitments), so the live measurement is the baseline
    return {
        "metric": "rbc_round_batched",
        "value": round(1.0 / t_dev, 2),
        "unit": "rounds/s",
        "vs_baseline": round(t_host / t_dev, 2),
        "t_device_s": round(t_dev, 6),
        "t_host_s": round(t_host, 6),
        "shape": f"N={n} f={f} B~{data.shape[-1]}",
    }


def _dkg256_commitment(t: int = 85):
    """The dkg256 config's shared setup (same seed for the bench pass and
    ``--freeze-baselines``, so both time the identical workload)."""
    import random

    from hbbft_tpu.crypto import tc

    rng = random.Random(21)
    print(f"# dkg256: sampling a degree-{t} bivariate poly…", file=sys.stderr)
    return tc.BivarPoly.random(t, rng).commitment()


def bench_dkg256(t: int = 85):
    """DKG hot loop at the N=256 network shape (t = f = 85): a dealer
    commitment's ``row(x)`` check — (t+1)² G1 scalar-muls, done per Part by
    every node (SURVEY §7 "hard part #3").

    The config metric reports the framework's BEST exact path for this
    shape — whatever the production auto-dispatch in
    ``crypto/batch.commitment_row`` actually runs (the ADX/GLV C++ oracle
    below DEVICE_DKG_MIN_BATCH; the device ladder above it; mesh
    row-sharding when one is attached via ``use_mesh``).  The FORCED
    device-ladder time stays as a secondary diagnostic: round 5 reported
    it as the config metric even though the oracle was faster (0.76×,
    BENCH_r05.json), which penalized the framework for having the better
    backend and routing to it."""
    from hbbft_tpu.crypto import batch as BT

    com = _dkg256_commitment(t)
    muls = (t + 1) * (t + 1)

    # the framework's best path: production auto-dispatch, as-is
    BT.commitment_row(com, 3)  # warm (compiles iff it routes to device)
    times = []
    for _ in range(3):
        t0 = time.perf_counter()
        row_best = BT.commitment_row(com, 3)
        times.append(time.perf_counter() - t0)
    t_best = float(np.median(times))
    # label what commitment_row ACTUALLY ran: the host path below the
    # min-batch threshold (Horner-form evaluation whose scalar-muls are
    # by the small node index itself — see tc.BivarCommitment.row and
    # bls12_381.SMALL_SCALAR_BITS), the device ladder above it
    # (row-sharded iff a mesh is routed through crypto.batch.use_mesh —
    # the mesh never changes the dispatch decision, only where ladder
    # rows execute)
    if BT._device_worthwhile(muls):
        best_path = "device+mesh" if BT._CACHE.mesh is not None else "device"
    else:
        best_path = "host-horner"

    # secondary diagnostic: the device ladder, forced
    saved_min = BT.DEVICE_DKG_MIN_BATCH
    BT.DEVICE_DKG_MIN_BATCH = 1
    try:
        BT.commitment_row(com, 3)  # compile/warm
        times = []
        for _ in range(3):
            t0 = time.perf_counter()
            row_dev = BT.commitment_row(com, 3)
            times.append(time.perf_counter() - t0)
        t_dev = float(np.median(times))
    finally:
        BT.DEVICE_DKG_MIN_BATCH = saved_min

    t0 = time.perf_counter()
    row_host = com.row(3)
    t_host = time.perf_counter() - t0
    assert row_dev == row_host and row_best == row_host

    return _apply_frozen({
        "metric": "dkg256_commitment_row",
        "value": round(muls / t_best, 2),
        "unit": "scalar-muls/s",
        "vs_baseline": round(t_host / t_best, 2),
        "best_path": best_path,
        "t_best_s": round(t_best, 6),
        "t_device_s": round(t_dev, 6),  # secondary diagnostic (forced)
        "t_host_s": round(t_host, 6),
        "shape": f"t={t} (N=256 f=85)",
    }, t_best)


def _coin256_setup(n: int = 256, f: int = 85):
    import random

    from hbbft_tpu.crypto.tc import SecretKeySet

    rng = random.Random(99)
    print(f"# coin256: generating {n} key/signature shares…", file=sys.stderr)
    sks = SecretKeySet.random(f, rng)
    pks = sks.public_keys()
    msg = b"coin-epoch-42"
    pairs = [
        (pks.public_key_share(i), sks.secret_key_share(i).sign(msg))
        for i in range(n)
    ]
    return rng, pairs, msg


def _coin256_host(pairs, msg, n: int) -> float:
    """Per-share host pairing verification, sampled — the coin256 host
    denominator (shared with ``--freeze-baselines``)."""
    sample = 4

    def host_once():
        for pk, s in pairs[:sample]:
            assert pk.verify(s, msg)

    return _timeit(host_once, warmup=1, iters=2, min_time=0.0) / sample * n


def bench_coin256(n: int = 256, f: int = 85):
    """BASELINE config 3: common-coin share verification at N=256 —
    randomized-linear-combination batch verify (device G1+G2 ladders + one
    host pairing check) vs per-share host pairing verification (sampled)."""
    from hbbft_tpu.crypto.batch import batch_verify_sig_shares

    rng, pairs, msg = _coin256_setup(n, f)

    # warm (compiles the two ladders)
    assert batch_verify_sig_shares(pairs, msg, rng) is True
    times = []
    for _ in range(3):
        t0 = time.perf_counter()
        ok = batch_verify_sig_shares(pairs, msg, rng)
        times.append(time.perf_counter() - t0)
        assert ok
    t_dev = float(np.median(times))

    t_host = _coin256_host(pairs, msg, n)

    return _apply_frozen({
        "metric": "coin256_share_batch_verify",
        "value": round(n / t_dev, 2),
        "unit": "shares/s",
        "vs_baseline": round(t_host / t_dev, 2),
        "t_device_s": round(t_dev, 6),
        "t_host_s": round(t_host, 6),
        "shape": f"N={n} f={f}",
    }, t_dev)


def _hb_epoch16_setup(n: int = 16, tx_bytes: int = 256):
    import random

    from hbbft_tpu.netinfo import NetworkInfo

    rng = random.Random(17)
    print(f"# hb-epoch: generating keys for N={n}…", file=sys.stderr)
    infos = NetworkInfo.generate_map(list(range(n)), rng)
    contribs = {
        i: bytes(rng.randrange(256) for _ in range(tx_bytes)) for i in range(n)
    }
    return infos, contribs


def _hb_epoch16_host(infos, contribs, n: int) -> float:
    """The object-mode side of the N=16 epoch config (shared with
    ``--freeze-baselines`` so the frozen denominator is the exact same
    measurement the live pass makes)."""
    import random

    from hbbft_tpu.protocols.honey_badger import (
        Batch, EncryptionSchedule, HoneyBadger,
    )
    from hbbft_tpu.sim import NetBuilder, NullAdversary

    def host_once():
        net = NetBuilder(list(range(n))).adversary(NullAdversary()).using_step(
            lambda nid: HoneyBadger.builder(infos[nid])
            .session_id(b"bench")
            .encryption_schedule(EncryptionSchedule.always())
            .rng(random.Random(100 + nid))
            .build()
        )
        for nid in net.node_ids():
            net.send_input(nid, contribs[nid])
        net.run_to_quiescence()
        for nid in net.node_ids():
            batches = [o for o in net.nodes[nid].outputs if isinstance(o, Batch)]
            assert len(batches) == 1

    return _timeit(host_once, warmup=1, iters=2, min_time=0.0)


def bench_hb_epoch(n: int = 16, tx_bytes: int = 256):
    """A FULL batched HoneyBadger epoch (TPKE encrypt → batched RBC round →
    batched ABA epochs → threshold decrypt) vs the object-mode simulator
    running the same epoch message-by-message (BASELINE config-1 shape,
    scaled up to N=16)."""
    import random

    from hbbft_tpu.parallel.acs import BatchedHoneyBadgerEpoch

    infos, contribs = _hb_epoch16_setup(n, tx_bytes)

    hb = BatchedHoneyBadgerEpoch(infos, session_id=b"bench")
    batch0, _ = hb.run(contribs, random.Random(1), encrypt=True)  # warm/compile
    assert batch0 == contribs
    times = []
    for i in range(3):
        t0 = time.perf_counter()
        batch, _ = hb.run(contribs, random.Random(2 + i), encrypt=True)
        times.append(time.perf_counter() - t0)
        assert batch == contribs
    t_dev = float(np.median(times))

    t_host = _hb_epoch16_host(infos, contribs, n)
    return _apply_frozen({
        "metric": "hb_epoch_batched",
        "value": round(1.0 / t_dev, 3),
        "unit": "epochs/s",
        "vs_baseline": round(t_host / t_dev, 2),
        "t_device_s": round(t_dev, 6),
        "t_host_s": round(t_host, 6),
        "shape": f"N={n} tx={tx_bytes}B",
    }, t_dev)


def _epoch_mesh(n: int):
    """The device mesh for the hb-epoch* configs, from ``--mesh`` /
    ``HBBFT_EPOCH_MESH``:

      auto   mesh over ALL visible devices when there is more than one
             (1-axis ``("nodes",)``) — the default, so a multi-chip host
             shards the epoch without any flag;
      none   force the single-device array path;
      K      1-axis mesh over the first K devices;
      AxB    2-axis hierarchical ``("dcn", "ici")`` mesh (hosts × chips)
             over the first A·B devices.

    Returns None (single-device) or a ``jax.sharding.Mesh``; the node
    count must divide over the mesh, otherwise falls back to None with a
    stderr note (the sharded phases shard the node axis evenly)."""
    import jax
    from jax.sharding import Mesh

    spec = os.environ.get("HBBFT_EPOCH_MESH", "auto").strip().lower()
    devs = jax.devices()
    if spec in ("none", "0", "1", ""):
        return None
    if spec == "auto":
        if len(devs) <= 1:
            return None
        shape, axes = (len(devs),), ("nodes",)
    elif "x" in spec:
        a, b = (int(p) for p in spec.split("x", 1))
        shape, axes = (a, b), ("dcn", "ici")
    else:
        shape, axes = (int(spec),), ("nodes",)
    total = int(np.prod(shape))
    if total > len(devs):
        raise ValueError(
            f"HBBFT_EPOCH_MESH={spec!r} wants {total} devices, "
            f"have {len(devs)}")
    if total <= 1:
        return None
    if n % total:
        print(f"# mesh {spec!r}: {total} devices do not divide N={n}; "
              f"falling back to single-device", file=sys.stderr)
        return None
    return Mesh(np.array(devs[:total]).reshape(shape), axes)


def _mesh_fields(mesh):
    """The bench-record fields describing the attached mesh — recorded on
    every hb-epoch* line so ``--compare`` can refuse to gate a sharded
    run against an unsharded one (the equal-pipeline-depth rule's
    sibling: throughput across different device counts measures
    different hardware, not a regression)."""
    if mesh is None:
        return {"mesh_devices": 1}
    return {
        "mesh_devices": int(np.prod(np.asarray(mesh.devices.shape))),
        "mesh_axes": "x".join(
            f"{name}={size}" for name, size in
            zip(mesh.axis_names, mesh.devices.shape)
        ),
    }


def _bench_hb_epoch_large(n: int, tx_bytes: int, iters: int, tag: str):
    """A FULL TPKE HoneyBadger epoch at scale — encryption, batched ACS,
    threshold coins, and master-scalar-folded decryption of all accepted
    ciphertexts, the whole pipeline node-axis-sharded over the
    ``--mesh`` device mesh when one resolves (auto on any multi-device
    host).  Host baseline extrapolated from the N=16 object-mode epoch
    (message count scales ~N³)."""
    import random

    from hbbft_tpu.netinfo import NetworkInfo
    from hbbft_tpu.parallel.acs import BatchedHoneyBadgerEpoch

    rng = random.Random(23)
    print(f"# {tag}: generating keys for N={n}…", file=sys.stderr)
    infos = NetworkInfo.generate_map(list(range(n)), rng)
    contribs = {
        i: bytes(rng.randrange(256) for _ in range(tx_bytes)) for i in range(n)
    }
    mesh = _epoch_mesh(n)
    if mesh is not None:
        print(f"# {tag}: epoch sharded over "
              f"{_mesh_fields(mesh)['mesh_axes']}", file=sys.stderr)
    hb = BatchedHoneyBadgerEpoch(infos, session_id=tag.encode(),
                                 compact=True, mesh=mesh)
    batch0, _ = hb.run(contribs, random.Random(1), encrypt=True)  # compile
    assert batch0 == contribs
    times = []
    phase = {"encrypt": [], "acs": [], "decrypt": []}
    for i in range(iters):
        t0 = time.perf_counter()
        # split the epoch at the phase seams so the record attributes
        # device time: encrypt (host asm or mesh-routed MSM), then
        # run_from_payloads' own timer splits acs vs decrypt
        payloads = hb.encrypt_phase(contribs, random.Random(2 + i))
        t1 = time.perf_counter()
        batch, out = hb.run_from_payloads(
            payloads, encrypt=True, timer=time.perf_counter
        )
        times.append(time.perf_counter() - t0)
        phase["encrypt"].append(t1 - t0)
        phase["acs"].append(out["phase_s"]["acs"])
        phase["decrypt"].append(out["phase_s"]["decrypt"])
        assert batch == contribs
    t_dev = float(np.median(times))

    # Host baseline.  N=64 has a MEASURED object-mode epoch on record
    # (tools_measure_host64.py → BASELINE_MEASURED.json — one full
    # 904.6 s / 1.98M-message run; no extrapolation).  Other N scale from
    # the measured run by the ~N³ message count (flagged `extrapolated`).
    measured = _measured_baseline(n)
    if measured is not None:
        t_host, host_note = measured
        extrapolated = False
    else:
        base = _measured_baseline(64)
        if base is not None:
            # scale the MEASURED N=64 run by message count (~N³) — still
            # an extrapolation for this n, but anchored to a real
            # 1.98M-message measurement instead of the N=16 toy run
            t64, note64 = base
            t_host = t64 * (n / 64) ** 3
            host_note = (f"~N^3-scaled from the measured N=64 host epoch "
                         f"({note64})")
        else:
            # fallback: measure N=16 object mode live, scale ~N³ messages
            small = 16
            s_infos = NetworkInfo.generate_map(
                list(range(small)), random.Random(5)
            )
            s_contribs = {i: contribs[i] for i in range(small)}
            net = NetBuilder(list(range(small))).adversary(
                NullAdversary()
            ).using_step(
                lambda nid: HoneyBadger.builder(s_infos[nid])
                .session_id(tag.encode())
                .encryption_schedule(EncryptionSchedule.always())
                .rng(random.Random(200 + nid))
                .build()
            )
            t0 = time.perf_counter()
            for nid in net.node_ids():
                net.send_input(nid, s_contribs[nid])
            net.run_to_quiescence()
            t_small = time.perf_counter() - t0
            for nid in net.node_ids():
                assert any(
                    isinstance(o, Batch) for o in net.nodes[nid].outputs
                )
            per_msg = t_small / max(net.messages_delivered, 1)
            t_host = per_msg * net.messages_delivered * (n / small) ** 3
            host_note = (f"extrapolated from N={small} object-mode "
                         f"({net.messages_delivered} msgs in {t_small:.2f}s)")
        extrapolated = True

    out = {
        "metric": f"hb_epoch{n}_batched",
        "value": round(1.0 / t_dev, 3),
        "unit": "epochs/s",
        "vs_baseline": round(t_host / t_dev, 1),
        "t_device_s": round(t_dev, 4),
        "phase_s": {
            ph: round(float(np.median(ts)), 4) for ph, ts in phase.items()
        },
        "host_note": host_note,
        "shape": f"N={n} f={(n - 1) // 3} tx={tx_bytes}B",
        **_mesh_fields(mesh),
    }
    if extrapolated:
        out["t_host_est_s"] = round(t_host, 1)
        out["extrapolated"] = True
    else:
        out["t_host_measured_s"] = round(t_host, 1)
    return out


def _measured_baseline(n: int):
    """(t_epoch_s, note) from BASELINE_MEASURED.json for this N, if a
    measured (non-extrapolated) object-mode run is on record."""
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "BASELINE_MEASURED.json")
    if not os.path.exists(path):
        return None
    try:
        with open(path) as fh:
            data = json.load(fh)
        rec = data[f"hb_epoch{n}_host"]
        note = (f"MEASURED object-mode epoch: {rec['t_epoch_s']}s, "
                f"{rec['messages_delivered']} msgs ({rec['measured_utc']}; "
                f"{rec['notes']})")
        return float(rec["t_epoch_s"]), note
    except (KeyError, TypeError, ValueError, OSError):
        # absent/partial/hand-edited record → the extrapolation fallback
        return None


def bench_hb_epoch64():
    """Full TPKE HoneyBadger epoch at N=64 f=21."""
    return _bench_hb_epoch_large(64, 256, iters=3, tag="hb-epoch64")


def bench_hb_epoch1024():
    """Full TPKE HoneyBadger epoch at N=1024 f=341 (BASELINE config 4 with
    real threshold encryption on top of the ACS)."""
    return _bench_hb_epoch_large(1024, 64, iters=2, tag="hb-epoch1024")


def bench_hb_epoch4096():
    """Full TPKE HoneyBadger epoch at the BASELINE config-5 shape
    (N=4096 f=1365).  ~3 min first-run compile and ~40 s per epoch — runs
    LAST in --config all so a driver timeout preserves every other config
    (the emit path marks interrupted runs).  On a multi-chip host the
    whole pipeline runs mesh-sharded (``--mesh``, auto) — the ≥1 epoch/s
    target shape."""
    return _bench_hb_epoch_large(4096, 64, iters=1, tag="hb-epoch4096")


def bench_hb_epoch16384():
    """First-ever N=16384 full-TPKE epoch (f=5461, GF(2^16) coder).

    Explicit-only and informational: the RS16 systematic-matrix
    construction alone is hours of host time on first run (then disk-
    cached, ~180 MB), a single epoch is minutes even mesh-sharded, and
    there is no host baseline at this scale that isn't pure
    extrapolation — the config exists to RECORD that the shape completes
    end-to-end (encrypt → sharded ACS → threshold decrypt), not to gate
    on its throughput.  Never part of ``--config all``."""
    return _bench_hb_epoch_large(16384, 32, iters=1, tag="hb-epoch16384")


def bench_acs1024(n: int = 1024):
    """BASELINE config 4: a full ACS (batched RBC + batched ABA) over
    N=1024 nodes — beyond the reference's reach entirely (its GF(2^8)
    erasure field caps networks at 256 nodes; ours switches to GF(2^16)).
    vs_baseline extrapolates the object-mode per-message cost measured at
    N=16 to the ~N²·per-node message count of an N=1024 epoch."""
    import random

    from hbbft_tpu.parallel.acs import BatchedAcs

    f = (n - 1) // 3
    print(f"# acs1024: building GF(2^16) coder for N={n}…", file=sys.stderr)
    acs = BatchedAcs(n, f)
    values = [b"tx-%d" % p for p in range(n)]
    out = acs.run(values)  # warm + compile
    acc = out["accepted"]
    assert (acc == acc[0]).all() and acc[0].all()
    times = []
    for _ in range(3):
        t0 = time.perf_counter()
        out = acs.run(values)
        times.append(time.perf_counter() - t0)
    t_dev = float(np.median(times))

    # host extrapolation: measure object-mode ACS (Subset) per-message cost
    # at a feasible N, scale by message count ~ N²·const
    from hbbft_tpu.netinfo import NetworkInfo
    from hbbft_tpu.protocols.subset import Subset
    from hbbft_tpu.sim import NetBuilder, NullAdversary

    small = 16
    infos = NetworkInfo.generate_map(list(range(small)), random.Random(3))
    net = NetBuilder(list(range(small))).adversary(NullAdversary()).using_step(
        lambda nid: Subset(infos[nid], session_id=b"acs-bench")
    )
    t0 = time.perf_counter()
    for nid in net.node_ids():
        net.send_input(nid, b"contrib-%d" % nid)
    net.run_to_quiescence()
    t_small = time.perf_counter() - t0
    per_msg = t_small / max(net.messages_delivered, 1)
    est_msgs = net.messages_delivered * (n / small) ** 3  # N proposers × N² fanout
    t_host_est = per_msg * est_msgs

    return {
        "metric": "acs1024_epoch_batched",
        "value": round(1.0 / t_dev, 3),
        "unit": "epochs/s",
        "vs_baseline": round(t_host_est / t_dev, 1),
        "t_device_s": round(t_dev, 4),
        "t_host_est_s": round(t_host_est, 1),
        "host_note": f"extrapolated from N={small} object-mode "
                     f"({net.messages_delivered} msgs in {t_small:.2f}s)",
        "extrapolated": True,
        "shape": f"N={n} f={f}",
    }


def _rbc_mb1_setup(n: int = 4, f: int = 1, value_bytes: int = 2**20):
    from hbbft_tpu.ops.rs import for_n_f

    rng = np.random.default_rng(5)
    return (for_n_f(n, f),
            rng.integers(0, 256, value_bytes, dtype=np.uint8).tobytes())


def _rbc_mb1_legacy_once(coder, value: bytes) -> bytes:
    """The pre-ingestion proposer pipeline, reproduced verbatim: frame →
    per-call GF table-lookup matmul encode → per-shard ``tobytes`` →
    scalar per-leaf SHA3 Merkle build.  This is the frozen
    ``vs_baseline`` denominator for rbc-mb1 — the live code path no
    longer contains it, so it is re-staged here from the old
    ``encode_np``/``MerkleTree.__init__`` bodies.  Returns the root so
    callers can pin new == legacy."""
    from hbbft_tpu.ops import gf256
    from hbbft_tpu.ops.keccak import sha3_256_host
    from hbbft_tpu.protocols.broadcast import _frame_value

    framed = _frame_value(value, coder.data_shards)
    parity = gf256.gf_matmul_np(coder.parity_matrix, framed)
    full = np.concatenate([framed, parity], axis=0)
    digs = [sha3_256_host(s.tobytes()) for s in full]
    while len(digs) > 1:
        digs = [
            sha3_256_host(digs[i] + digs[i + 1])
            if i + 1 < len(digs) else digs[i]
            for i in range(0, len(digs), 2)
        ]
    return digs[0]


def _rbc_mb1_survivors(coder, value: bytes):
    """The reconstruct measurement's shared inputs: full shard set for
    one framed value, worst-case survivor pattern (all-parity-heavy)."""
    from hbbft_tpu.protocols.broadcast import _frame_value

    framed = _frame_value(value, coder.data_shards)
    full = coder.encode_np(framed)
    use = tuple(range(coder.total_shards - coder.data_shards,
                      coder.total_shards))
    return full[list(use)], use


def _rbc_mb1_legacy_reconstruct_once(coder, survivors, use):
    """The pre-cache receiver decode, reproduced verbatim: a fresh
    Gauss–Jordan inversion of the survivor rows on EVERY call, then the
    GF table-lookup matmul — the decode-side twin of
    :func:`_rbc_mb1_legacy_once`, and the frozen ``vs_baseline``
    denominator for rbc_mb1_reconstruct.  Returns the data shards so
    callers can pin new == legacy."""
    from hbbft_tpu.ops import gf256

    dec = gf256.gf_inv_matrix_np(coder.matrix[list(use)])
    return gf256.gf_matmul_np(dec, survivors)


def bench_rbc_mb1(n: int = 4, f: int = 1, value_bytes: int = 2**20):
    """MB-scale RBC hot paths at N=4 (the ingestion PR's headline shape),
    TWO records:

    - ``rbc_mb1_encode_commit`` — proposer side: the live
      ``_encode_value`` → ``MerkleTree.from_shards`` pipeline (cached
      XOR-schedule / SIMD erasure, batched leaf hashing, one snapshot,
      zero per-leaf copies) vs the legacy frame → table-matmul →
      per-shard-copy → scalar-hash pipeline;
    - ``rbc_mb1_reconstruct`` — receiver side: the pattern-cached decode
      (LRU'd Gauss–Jordan inversion + compiled XOR-schedule apply, the
      decode-side gap ROADMAP item 2 named) vs the legacy per-call
      inversion + table matmul.

    Both baselines are frozen by ``--freeze-baselines`` so the ratios
    divide by fixed measurements."""
    from hbbft_tpu.ops.merkle import MerkleTree
    from hbbft_tpu.ops.rs import resolve_backend
    from hbbft_tpu.protocols.broadcast import _encode_value

    coder, value = _rbc_mb1_setup(n, f, value_bytes)

    # correctness pin: both pipelines commit to the same root
    shards, leaves = _encode_value(coder, value)
    assert MerkleTree.from_shards(shards, leaves).root_hash() \
        == _rbc_mb1_legacy_once(coder, value)

    def new_once():
        s, lv = _encode_value(coder, value)
        MerkleTree.from_shards(s, lv)

    t_new = _timeit_best(new_once, warmup=2, iters=5, min_time=0.1)
    t_host = _timeit_best(lambda: _rbc_mb1_legacy_once(coder, value),
                          reps=3, warmup=1, iters=3, min_time=0.1)
    encode_rec = _apply_frozen({
        "metric": "rbc_mb1_encode_commit",
        "value": round(value_bytes / 2**20 / t_new, 2),
        "unit": "MB/s",
        "vs_baseline": round(t_host / t_new, 2),
        "t_new_s": round(t_new, 6),
        "t_host_s": round(t_host, 6),
        "erasure_backend": resolve_backend(),
        "shape": f"N={n} f={f} value={value_bytes}B",
    }, t_new)

    survivors, use = _rbc_mb1_survivors(coder, value)
    # correctness pin: cached decode == legacy per-call decode, bytewise
    got = coder.reconstruct_data_np(survivors, use)
    legacy = _rbc_mb1_legacy_reconstruct_once(coder, survivors, use)
    np.testing.assert_array_equal(got, legacy)

    t_rec = _timeit_best(
        lambda: coder.reconstruct_data_np(survivors, use),
        warmup=2, iters=5, min_time=0.1)
    t_rec_host = _timeit_best(
        lambda: _rbc_mb1_legacy_reconstruct_once(coder, survivors, use),
        reps=3, warmup=1, iters=3, min_time=0.1)
    out_bytes = coder.data_shards * survivors.shape[1]
    recon_rec = _apply_frozen({
        "metric": "rbc_mb1_reconstruct",
        "value": round(out_bytes / 2**20 / t_rec, 2),
        "unit": "MB/s",
        "vs_baseline": round(t_rec_host / t_rec, 2),
        "t_new_s": round(t_rec, 6),
        "t_host_s": round(t_rec_host, 6),
        "erasure_backend": resolve_backend(),
        "shape": f"N={n} f={f} value={value_bytes}B worst-case survivors",
    }, t_rec)
    return [encode_rec, recon_rec]


# Ordered so an interrupted driver run keeps the BASELINE configs: the
# headline epoch (config 1 shape), then configs 2/3/4, then the rest.
CONFIGS = {
    # headline first (the driver parses the first completed config):
    # hb-epoch64 carries the round-5 MEASURED host baseline — a full
    # 904.6 s object-mode epoch vs ~0.9 s batched, no extrapolation
    "hb-epoch64": bench_hb_epoch64,
    "hb-epoch": bench_hb_epoch,
    "rbc64": bench_rbc64,
    "rbc64-reconstruct": bench_rbc64_reconstruct,
    "rbc-mb1": bench_rbc_mb1,
    "coin256": bench_coin256,
    "acs1024": bench_acs1024,
    "hb-epoch1024": bench_hb_epoch1024,
    "rbc-round": bench_rbc_round,
    "sha3": bench_sha3,
    "dkg256": bench_dkg256,
    "hb-epoch4096": bench_hb_epoch4096,
    "hb-epoch16384": bench_hb_epoch16384,
}

# explicit-only configs: runnable via --config NAME but never part of
# --config all (hours-scale informational shapes)
EXPLICIT_ONLY = ("hb-epoch16384",)


def freeze_baselines():
    """Measure the HOST side of the non-headline configs once and record
    them under ``host_baselines`` in BASELINE_MEASURED.json, the way the
    headline froze its 904.6 s object-mode epoch: every ``vs_baseline``
    in the driver artifact must divide by a FIXED measurement, not a
    denominator that gets faster with every oracle improvement (the
    round-5 pattern: coin256 23.4×→6.59× and dkg256 1.37×→0.76× moved
    only because the C++ oracle got the same endomorphism speedups).
    Re-run explicitly to re-base after a hardware change; the bench never
    overwrites these on its own.  Not frozen: rbc-round (its host figure
    derives from sampled device-built commitments) and acs1024 / the
    large hb-epoch configs (extrapolations anchored to the already-frozen
    measured N=64 epoch)."""
    import datetime
    import hashlib

    records = {}

    def rec(metric, t_host, shape, notes):
        records[metric] = {
            "t_host_s": round(float(t_host), 6),
            "shape": shape,
            "notes": notes,
            "measured_utc": datetime.datetime.utcnow().strftime(
                "%Y-%m-%dT%H:%M:%SZ"),
        }
        print(f"# frozen {metric}: t_host={float(t_host):.4f}s",
              file=sys.stderr, flush=True)

    infos, contribs = _hb_epoch16_setup()
    rec("hb_epoch_batched", _hb_epoch16_host(infos, contribs, 16),
        "N=16 tx=256B",
        "object-mode VirtualNet epoch, single CPU core, native oracle")

    _, pairs, msg = _coin256_setup()
    rec("coin256_share_batch_verify", _coin256_host(pairs, msg, 256),
        "N=256 f=85", "per-share host pairing verification (sampled x4)")

    com = _dkg256_commitment()
    times = []
    for _ in range(3):
        t0 = time.perf_counter()
        com.row(3)
        times.append(time.perf_counter() - t0)
    rec("dkg256_commitment_row", float(np.median(times)),
        "t=85 (N=256 f=85)",
        "C++ oracle BivarCommitment.row — 7396 scalar-muls")

    from hbbft_tpu.ops import gf256
    from hbbft_tpu.ops.merkle import MerkleTree
    from hbbft_tpu.ops.rs import for_n_f

    rs_ = for_n_f(64, 21)
    k = rs_.data_shards
    g = np.random.default_rng(0)
    data = g.integers(0, 256, size=(64, k, 1024), dtype=np.uint8)

    def enc_once():
        for i in range(64):
            shards = rs_.encode_np(data[i])
            MerkleTree([bytes(s) for s in shards])

    rec("rbc64_encode_merkle",
        _timeit(enc_once, warmup=1, iters=3, min_time=0.1),
        "N=64 f=21 I=64 B=1024", "single-thread RS encode + Merkle build")

    g = np.random.default_rng(1)
    data = g.integers(0, 256, size=(64, k, 1024), dtype=np.uint8)
    full = np.stack([rs_.encode_np(d) for d in data])
    use = tuple(range(64 - k, 64))
    survivors = full[:, list(use), :]
    dec = rs_._decode_matrix(use)

    def rec_once():
        for i in range(64):
            gf256.gf_matmul_np(dec, survivors[i])

    rec("rbc64_reconstruct",
        _timeit(rec_once, warmup=1, iters=3, min_time=0.1),
        "N=64 f=21 I=64 B=1024",
        "decode matmul only (the same work the bench charges the host)")

    g = np.random.default_rng(2)
    msgs = g.integers(0, 256, size=(4096, 136), dtype=np.uint8)

    def sha_once():
        for i in range(4096):
            hashlib.sha3_256(msgs[i].tobytes()).digest()

    rec("sha3_256_batched",
        _timeit(sha_once, warmup=1, iters=3, min_time=0.05),
        "batch=4096 len=136", "hashlib sha3_256 loop")

    coder, value = _rbc_mb1_setup()
    rec("rbc_mb1_encode_commit",
        _timeit_best(lambda: _rbc_mb1_legacy_once(coder, value),
                     warmup=1, iters=3, min_time=0.1),
        "N=4 f=1 value=1MiB",
        "legacy frame + table-matmul encode + per-shard copy + "
        "scalar-hash Merkle build (pre-ingestion proposer pipeline; "
        "best-of-5 _timeit, same estimator as the live side)")

    survivors, use = _rbc_mb1_survivors(coder, value)
    rec("rbc_mb1_reconstruct",
        _timeit_best(
            lambda: _rbc_mb1_legacy_reconstruct_once(coder, survivors, use),
            warmup=1, iters=3, min_time=0.1),
        "N=4 f=1 value=1MiB worst-case survivors",
        "legacy per-call Gauss-Jordan inversion + table-matmul decode "
        "(pre-cache receiver pipeline; best-of-5 _timeit, same "
        "estimator as the live side)")

    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "BASELINE_MEASURED.json")
    data_j = {}
    if os.path.exists(path):
        with open(path) as fh:
            data_j = json.load(fh)
    data_j.setdefault("host_baselines", {}).update(records)
    with open(path, "w") as fh:
        json.dump(data_j, fh, indent=1)
        fh.write("\n")
    print(json.dumps({
        "metric": "freeze_baselines", "value": len(records),
        "unit": "configs", "vs_baseline": 1.0,
        "frozen": sorted(records),
    }), flush=True)


def sustained4096(epochs: int, n: int = 4096, tx_bytes: int = 64):
    """Sustained multi-epoch N=4096 session (BASELINE config 5's real
    role: examples/simulation.rs runs epoch after epoch, not one).  Prints
    a per-epoch table + drift stats to stderr and ONE summary JSON line;
    not part of --config all (several minutes of wall clock)."""
    import random

    from hbbft_tpu.netinfo import NetworkInfo
    from hbbft_tpu.parallel.acs import BatchedHoneyBadgerEpoch

    rng = random.Random(23)
    print(f"# sustained: generating keys for N={n}…", file=sys.stderr)
    infos = NetworkInfo.generate_map(list(range(n)), rng)
    hb = BatchedHoneyBadgerEpoch(infos, session_id=b"sustained4096",
                                 compact=True)
    contribs = {
        i: bytes(rng.randrange(256) for _ in range(tx_bytes)) for i in range(n)
    }

    # --- encrypt backend: report whichever path is faster ------------------
    # HBBFT_ENCRYPT_BACKEND pins it; otherwise calibrate by timing one
    # encrypt phase per candidate.  The split device path is only a
    # candidate off-CPU (single-chip roofline in crypto/batch.py says the
    # host asm wins; a mesh routed through crypto.batch.use_mesh flips it),
    # so on a plain host the calibration is just the native measurement.
    import jax

    backend = os.environ.get("HBBFT_ENCRYPT_BACKEND") or None
    calib = {}
    if backend is None:
        candidates = ["native"]
        if jax.default_backend() != "cpu":
            candidates.append("device")
        for cand in candidates:
            os.environ["HBBFT_ENCRYPT_BACKEND"] = cand
            try:
                hb.encrypt_phase(contribs, random.Random(7))  # warm/compile
                t0 = time.perf_counter()
                hb.encrypt_phase(contribs, random.Random(7))
                calib[cand] = round(time.perf_counter() - t0, 3)
            finally:
                del os.environ["HBBFT_ENCRYPT_BACKEND"]
        backend = min(calib, key=calib.get)
        print(f"# encrypt calibration: {calib} → {backend}",
              file=sys.stderr, flush=True)
    os.environ["HBBFT_ENCRYPT_BACKEND"] = backend

    enc_times = []

    def encrypt_timed(contribs_, rng_):
        t0 = time.perf_counter()
        out = hb.encrypt_phase(contribs_, rng_)
        enc_times.append(time.perf_counter() - t0)
        return out

    times = []
    interrupted = None

    def emit():
        # one JSON line whatever happened — a driver timeout mid-session
        # must not erase the completed epochs (same contract as the
        # config pass)
        line = {
            "metric": "hb_epoch4096_sustained",
            "value": 0,
            "unit": "epochs/s",
            "vs_baseline": 0,
            "epochs": len(times),
            "epochs_requested": epochs,
            "shape": f"N={n} f={(n - 1) // 3} tx={tx_bytes}B",
        }
        if times:
            warm = times[1:] if len(times) > 1 else times
            line.update({
                "value": round(1.0 / float(np.median(warm)), 4),
                "t_first_s": round(times[0], 2),
                "t_median_warm_s": round(float(np.median(warm)), 2),
                "t_min_s": round(min(times), 2),
                "t_max_s": round(max(times), 2),
                "drift_pct": round(
                    100.0 * (warm[-1] - warm[0]) / warm[0], 1
                ) if len(warm) > 1 else 0.0,
            })
        # per-epoch medians from this bench are PIPELINED (encrypt e+1
        # overlaps epoch e) since commit c6de21f's predecessor round —
        # not comparable to the round-≤4 sequential numbers
        line["pipelined"] = True
        line["encrypt_backend"] = backend
        if calib:
            line["encrypt_calibration_s"] = calib
        if enc_times:
            # wall time of the encrypt phase itself (worker thread) —
            # the tentpole's "encrypt ≤ 1.5 s" criterion reads this
            line["t_encrypt_median_s"] = round(
                float(np.median(enc_times)), 2
            )
        if interrupted is not None:
            line["interrupted"] = interrupted
        print(json.dumps(line), flush=True)

    import signal

    def on_term(signum, frame):
        nonlocal interrupted
        interrupted = signum
        raise SystemExit(128 + signum)

    for sig in (signal.SIGTERM, signal.SIGINT):
        signal.signal(sig, on_term)

    # Epoch-axis pipeline (SURVEY §2.3 PP row): epoch e+1's TPKE encrypt
    # (native: one GIL-released C call; device backend: MSM dispatches
    # interleaved with the native hash batch) runs on a worker thread
    # while epoch e's ACS drives the device — the same overlap the QHB
    # driver uses.  Byte-identical work: encrypt_phase(e) is a pure
    # function of (contribs, seed), so per-epoch results and the
    # batch == contribs assertion are unchanged from the sequential loop.
    from concurrent.futures import ThreadPoolExecutor

    try:
        with ThreadPoolExecutor(max_workers=1) as pool:
            fut = pool.submit(
                encrypt_timed, contribs, random.Random(100)
            )
            for e in range(epochs):
                t0 = time.perf_counter()
                payloads = fut.result()
                if e + 1 < epochs:
                    fut = pool.submit(
                        encrypt_timed, contribs, random.Random(100 + e + 1)
                    )
                batch, _ = hb.run_from_payloads(
                    payloads, encrypt=True, session_suffix=b"/e%d" % e,
                )
                dt = time.perf_counter() - t0
                assert batch == contribs
                times.append(dt)
                print(f"# epoch {e}: {dt:.1f}s ({1.0 / dt:.4f} epochs/s)",
                      file=sys.stderr, flush=True)
    finally:
        emit()


def _net_phase_summary(span_dicts):
    """The per-phase latency breakdown from the nodes' /spans exports.

    Two complementary views per committed epoch:

    - raw per-phase span durations (first→last activity of that phase),
      summarized as p50/p99 per coarse group (rbc / aba / coin / decrypt);
    - a *partition attribution*: the epoch timeline is split at each
      phase's start, so each group's attributed time answers "where did
      this epoch's latency go" and the groups sum to the epoch wall
      (first activity → commit) by construction — ``attr_sum_over_wall``
      is the sanity ratio (1.0 up to float noise); ``raw_sum_over_wall``
      is the overlap-sensitive raw ratio, reported for honesty.
    """
    from hbbft_tpu.net.client import percentile
    from hbbft_tpu.obs.spans import phase_group

    by_epoch = {}
    for s in span_dicts:
        by_epoch.setdefault((s["node"], s["era"], s["epoch"]),
                            []).append(s)

    def pct(vals, p):
        return percentile(sorted(vals), p) if vals else None

    group_durs, attr = {}, {}
    walls, attr_ratios, raw_ratios = [], [], []
    for _key, spans in by_epoch.items():
        epoch = [s for s in spans if s["name"] == "epoch"]
        phases = [s for s in spans
                  if s["name"] not in ("epoch", "dkg_rotation")]
        if not epoch or not phases:
            continue
        wall, t_end = epoch[0]["duration_s"], epoch[0]["t_end"]
        walls.append(wall)
        for s in phases:
            group_durs.setdefault(phase_group(s["name"]),
                                  []).append(s["duration_s"])
        ordered = sorted(phases, key=lambda s: s["t_start"])
        per = {}
        for i, s in enumerate(ordered):
            t1 = (ordered[i + 1]["t_start"] if i + 1 < len(ordered)
                  else t_end)
            g = phase_group(s["name"])
            per[g] = per.get(g, 0.0) + max(t1 - s["t_start"], 0.0)
        for g, v in per.items():
            attr.setdefault(g, []).append(v)
        if wall > 0:
            attr_ratios.append(sum(per.values()) / wall)
            raw_ratios.append(
                sum(s["duration_s"] for s in phases) / wall)

    out = {"epochs_observed": len(walls)}
    for g in ("rbc", "aba", "coin", "decrypt"):
        durs = group_durs.get(g)
        out[g] = {
            "p50_ms": round(pct(durs, 0.50) * 1e3, 3) if durs else None,
            "p99_ms": round(pct(durs, 0.99) * 1e3, 3) if durs else None,
            "spans": len(durs or ()),
            "attr_p50_ms": (round(pct(attr[g], 0.50) * 1e3, 3)
                            if g in attr else None),
        }
    if walls:
        out["epoch_wall_p50_ms"] = round(pct(walls, 0.50) * 1e3, 3)
        out["epoch_wall_p99_ms"] = round(pct(walls, 0.99) * 1e3, 3)
        out["attr_sum_over_wall_p50"] = round(pct(attr_ratios, 0.50), 3)
        out["raw_sum_over_wall_p50"] = round(pct(raw_ratios, 0.50), 3)
    return out


def _net_run_once(epochs_target: int, n: int, batch_size: int,
                  tx_size: int, *, pipeline_depth: int = 1,
                  encrypt: bool = False, link_delays: str = "",
                  inflight: Optional[int] = None,
                  wave_txs: Optional[int] = None,
                  client_nodes: Optional[int] = None,
                  slow_node: int = -1, slow_delay_s: float = 0.0,
                  aba_delay_nodes: str = "", aba_out_delay_s: float = 0.0,
                  vid: bool = False, chaos: str = "",
                  ingress_workers: bool = False,
                  wave_limit_factor: int = 50,
                  watch: bool = False,
                  tag: str = "run"):
    """One localhost cluster measurement: spawn ``n`` node processes,
    pump client transactions until every node committed ``epochs_target``
    epochs, fetch every node's ``/spans`` export, tear down.  Returns the
    raw measurement dict (epochs, wall, latency percentiles, phases,
    transport stats).

    The submit driver keeps ``max(1, pipeline_depth)`` waves of
    transactions in flight: at depth 1 this is exactly the serialized
    submit→wait→repeat loop of the r01/r02 recordings (comparability);
    deeper pipelines need standing load or the measurement would starve
    the very concurrency it benchmarks."""
    import asyncio
    import gc
    import random
    import shutil
    import subprocess
    import tempfile
    from collections import deque

    from hbbft_tpu.net.client import latency_percentiles
    from hbbft_tpu.net.cluster import (
        ClusterConfig, assert_status_chains_consistent, connect_when_up,
        find_free_base_port, shutdown_procs, spawn_node,
    )
    from hbbft_tpu.obs.http import http_get

    # same allocation-heavy/cycle-light shape as the nodes (run_node):
    # stop the driver's gen-0 collector from stealing the shared core
    gc.set_threshold(50_000, 25, 25)
    base = find_free_base_port(2 * n)
    # flight journals (nodes) + client trace journals feed the per-tx
    # critical-path decomposition (obs.critpath) attached to each run
    flight_root = tempfile.mkdtemp(prefix=f"bench-critpath-{tag}-")
    cfg = ClusterConfig(n=n, seed=9, batch_size=batch_size,
                        base_port=base, metrics_base_port=base + n,
                        encrypt=encrypt, pipeline_depth=pipeline_depth,
                        link_delays=link_delays, slow_node=slow_node,
                        slow_delay_s=slow_delay_s,
                        aba_delay_nodes=aba_delay_nodes,
                        aba_out_delay_s=aba_out_delay_s,
                        vid=vid, chaos=chaos, chaos_seed=9,
                        ingress_workers=ingress_workers,
                        flight_dir=flight_root)
    procs = {nid: spawn_node(cfg, nid, stdout=subprocess.DEVNULL,
                             stderr=subprocess.STDOUT)
             for nid in range(n)}
    # --net-watch: the live health plane rides along for the WHOLE
    # measured window — watchtower scraping every node's obs endpoint
    # and the streaming auditor tailing the flight journals — so
    # comparing this run against a plain baseline prices the plane's
    # overhead (the ≤5% epochs/s rule gated by --compare)
    wt = None
    watch_stop = None
    watch_thread = None
    if watch:
        import threading

        from hbbft_tpu.obs.watch import Watchtower

        # bounded in-window cost: journal decode capped per poll (the
        # backlog drains after the timed section) and the full audit
        # derivation runs every 4th tick — the plane stays attached and
        # detecting all run long, it just can't out-spend its ≤5% budget
        # by re-deriving over a hot journal twice a second
        wt = Watchtower([cfg.metrics_addr(nid) for nid in range(n)],
                        journal_roots=[flight_root],
                        scrape_timeout_s=1.0,
                        max_read_bytes=64 * 2**10,
                        derive_ticks=4)
        watch_stop = threading.Event()

        def _watch_loop():
            while not watch_stop.is_set():
                try:
                    wt.tick(time.monotonic())
                except Exception as exc:
                    print(f"# watchtower tick failed: {exc!r}",
                          file=sys.stderr)
                watch_stop.wait(1.0)

        # started inside session() once the nodes answer — scraping
        # half-spawned processes would charge their startup window as
        # target_down incidents against a healthy run
        watch_thread = threading.Thread(
            target=_watch_loop, name="bench-watch", daemon=True)
    # driver policy: depth 1 reproduces the r01/r02 serialized
    # submit→wait→repeat loop exactly; deeper pipelines keep two
    # half-size waves in flight — enough standing load to feed the
    # pipeline without drowning the latency measurement in queue wait
    if inflight is None:
        inflight = 1 if pipeline_depth <= 1 else 2
    if wave_txs is None:
        wave_txs = 4 * batch_size if pipeline_depth <= 1 else 2 * batch_size

    async def session():
        clients = [
            await connect_when_up(
                cfg, nid, client_id=f"bench-{nid}",
                trace_dir=os.path.join(flight_root, f"client-{nid}"))
            for nid in range(n)
        ]
        if watch_thread is not None:
            watch_thread.start()  # nodes are up: the plane attaches now
        rng = random.Random(17)
        t0 = time.monotonic()
        wave = 0
        pending = deque()
        docs = None

        k = client_nodes or n

        def per_client(txs):
            # client_nodes < n starves the last node(s) of transactions:
            # their proposals are empty AND late (they only propose on
            # seeing epoch activity), racing the Subset give-up threshold
            # — the honest trigger for split ABA votes and therefore for
            # genuine threshold-coin rounds (the coin-exercise run)
            groups = [[] for _ in range(n)]
            for i, tx in enumerate(txs):
                groups[i % k].append(tx)
            return groups

        async def submit_wave():
            nonlocal wave
            txs = [
                b"%06d:" % (wave * 100 + i) + rng.randbytes(tx_size - 7)
                for i in range(wave_txs)
            ]
            # batched submits, overlapped across clients: the benchmark
            # must measure the cluster, not a serialized submitter
            statuses = await asyncio.gather(*(
                clients[c].submit_many(group)
                for c, group in enumerate(per_client(txs))
            ))
            if any(s != 0 for group in statuses for s in group):
                raise RuntimeError(f"tx rejected mid-bench: {statuses}")
            wave += 1
            return txs

        async def await_wave(txs):
            await asyncio.gather(*(
                clients[c].wait_committed_many(group, timeout_s=120)
                for c, group in enumerate(per_client(txs))
            ))

        while True:
            while len(pending) < inflight:
                pending.append(await submit_wave())
                # wave_limit_factor > 50: the bandwidth-asym comparison
                # EXPECTS classic mode to crawl at the victim's link
                # while fast nodes churn waves — that is the measured
                # phenomenon, not a stall
                if wave > wave_limit_factor * epochs_target:
                    raise RuntimeError(
                        "cluster failed to reach epoch target")
            await await_wave(pending.popleft())
            # cheap poll: head + batch count only, no digest-chain JSON —
            # and only every 4th wave: the bench must not tax the very
            # nodes it measures with a per-wave status_doc + JSON encode
            if wave % 4 == 0:
                docs = [await c.status(chain_tail=0) for c in clients]
                if min(d["batches"] for d in docs) >= epochs_target:
                    break
        for txs in pending:  # drain: every submitted tx measured
            await await_wave(txs)
        wall = time.monotonic() - t0
        # the full documents (digest chains included) for the
        # cross-node consistency check, outside the timed window
        docs = [await c.status() for c in clients]
        # identical batches everywhere — and the chains must actually
        # overlap, or nothing was compared (status_doc truncates chains).
        # Not a bare assert: the check must survive python -O.
        if assert_status_chains_consistent(docs) == 0:
            raise RuntimeError("no digest-chain overlap to compare")
        lat = latency_percentiles(
            l for c in clients for _d, l in c.latencies
        )
        out = {
            "epochs": min(d["batches"] for d in docs),
            "wall_s": wall,
            "committed_txs": lat["count"],
            "p50_ms": round(lat["p50_s"] * 1e3, 2),
            "p90_ms": round(lat["p90_s"] * 1e3, 2),
            "p99_ms": round(lat["p99_s"] * 1e3, 2),
            "transport": docs[0]["stats"],
        }
        for c in clients:
            await c.close()
        return out

    try:
        net = asyncio.run(session())
        if wt is not None:
            watch_stop.set()
            watch_thread.join(timeout=10.0)
            # the timed section is over: drain whatever backlog the
            # bounded per-tick reads deferred, then seal the audit
            while wt.tailer.poll():
                pass
            wt.tailer.finalize()
            net["watch"] = {
                "ticks": wt.ticks,
                "incidents": sorted(
                    {(i["kind"], i["subject"]) for i in wt.incidents}),
                "audit_verdict": wt.tailer.result().verdict,
                "audit_records": wt.tailer.auditor.records_fed,
                "scrape_failures": int(wt._c_scrape_fail.total()),
            }
            wt.close()
        # every node's epoch-phase spans, while the processes are still up
        span_dicts = []
        for nid in range(n):
            host, mport = cfg.metrics_addr(nid)
            try:
                body = http_get(host, mport, "/spans", timeout_s=5.0)
            except (OSError, ValueError) as exc:
                print(f"# spans fetch from node {nid} failed: {exc!r}",
                      file=sys.stderr)
                continue
            span_dicts.extend(
                json.loads(line) for line in body.splitlines() if line
            )
        net["phases"] = _net_phase_summary(span_dicts)
        # per-segment pump cost over the whole run, summed across the
        # cluster (the perf plane's bench surface): --compare's
        # pump[<segment>].mean_s gate and --freeze-perf-profile both
        # read this
        from hbbft_tpu.obs.metrics import parse_prometheus_text
        from hbbft_tpu.obs.perf import segment_means

        pump = {}
        for nid in range(n):
            host, mport = cfg.metrics_addr(nid)
            try:
                parsed = parse_prometheus_text(
                    http_get(host, mport, "/metrics", timeout_s=5.0))
            except (OSError, ValueError) as exc:
                print(f"# metrics fetch from node {nid} failed: "
                      f"{exc!r}", file=sys.stderr)
                continue
            for seg, m in segment_means(parsed).items():
                acc = pump.setdefault(seg, {"busy_s": 0.0, "events": 0})
                acc["busy_s"] += m["busy_s"]
                acc["events"] += int(m["events"])
        for acc in pump.values():
            acc["mean_s"] = (round(acc["busy_s"] / acc["events"], 9)
                             if acc["events"] else 0.0)
            acc["busy_s"] = round(acc["busy_s"], 6)
        net["pump_util"] = pump
    finally:
        if watch_stop is not None:
            watch_stop.set()
        shutdown_procs(procs.values())
    # journals are fully flushed once the node processes exited: merge
    # them with the client trace journals into the per-tx critical path
    # (components sum exactly to each tx's measured submit→commit wall)
    try:
        from hbbft_tpu.obs import critpath as _critpath

        dirs = _critpath.find_journal_dirs(flight_root)
        if dirs:
            net["critical_path"] = _critpath.build_report(
                sorted(dirs), waterfalls=3)
    except Exception as exc:
        # attribution is best-effort decoration on the measurement:
        # the run's numbers stand even when the journals don't parse
        print(f"# critpath over {flight_root} failed: {exc!r}",
              file=sys.stderr)
        net["critical_path"] = {"error": repr(exc)}
    finally:
        shutil.rmtree(flight_root, ignore_errors=True)
    net["pipeline_depth"] = pipeline_depth
    net["epochs_per_s"] = round(net["epochs"] / net["wall_s"], 3)
    print(f"# net[{tag}] depth={pipeline_depth} encrypt={encrypt} "
          f"link_delays={link_delays!r}: {net['epochs_per_s']} epochs/s, "
          f"p50={net['p50_ms']}ms p99={net['p99_ms']}ms",
          file=sys.stderr, flush=True)
    cp50 = (net.get("critical_path") or {}).get("p50")
    if cp50:
        comps = " ".join(
            f"{k}={v * 1e3:.2f}ms" for k, v in cp50["components"].items()
            if v > 0)
        print(f"# net[{tag}] critpath p50={cp50['total_s'] * 1e3:.2f}ms "
              f"dominant={cp50['dominant']} {comps}",
              file=sys.stderr, flush=True)
    return net


def _coin_gauntlet(sessions: int = 8, n: int = 4):
    """The threshold-coin phase, measured at the protocol's own hard case.

    r02 recorded ``coin: {spans: 0}`` and the satellite assumed a
    span-finalization bug; measurement (this PR) showed the truth is
    sharper: **an honest N=4 cluster never reaches the threshold coin at
    all**.  The Moumen schedule (fixed true/false coins in rounds 0/1)
    terminates every unanimous ABA before round 2, and Subset's
    accept/give-up votes are never genuinely split in an honest run —
    the RBC echo relay equalizes delivery, and the give-up threshold
    (N−f decided ABAs) is gated by the same message rounds everywhere.
    Verified empirically: FIFO, random-reorder and full MITM-delay
    schedules over the QHB stack all produce zero CoinMsgs, while
    split-input bare ABA — the exact shape of
    ``tests/binary_agreement_mitm.rs`` — flips the round-2 threshold
    coin every time.

    So the coin phase is benchmarked where it actually lives: ``sessions``
    split-input 4-node ABA runs (inputs T,F,T,F — the adversarial input
    pattern the coin exists to survive), each flipping a genuine
    BLS-threshold coin (real sign/verify pairings, real shares on the
    simulated wire).  Spans mirror the SpanTracer semantics: per node,
    first→last CoinMsg arrival of each coin round.  Returns (durations_s,
    shares_delivered, rounds).
    """
    import random

    from hbbft_tpu.netinfo import NetworkInfo
    from hbbft_tpu.protocols.binary_agreement import (
        BinaryAgreement, CoinMsg,
    )
    from hbbft_tpu.sim import NetBuilder, NullAdversary

    infos = NetworkInfo.generate_map(list(range(n)), random.Random(9))
    durations, shares, rounds = [], 0, set()
    for s in range(sessions):
        net = NetBuilder(list(range(n))).adversary(
            NullAdversary()
        ).crank_limit(500_000).using_step(
            lambda nid, s=s: BinaryAgreement(
                infos[nid], b"bench-coin/%d" % s, 0
            )
        )
        for nid in range(n):
            net.send_input(nid, nid % 2 == 0)
        agg = {}  # (to, coin_round) -> [t_first, t_last, count]
        orig_crank = net.crank

        def crank():
            m = orig_crank()
            if m is not None:
                x = m.payload
                while hasattr(x, "msg") and not isinstance(x, CoinMsg):
                    x = x.msg
                if isinstance(x, CoinMsg):
                    now = time.perf_counter()
                    a = agg.setdefault((m.to, x.epoch), [now, now, 0])
                    a[1] = now
                    a[2] += 1
            return m

        net.crank = crank
        net.run_to_quiescence()
        decisions = {
            net.nodes[nid].outputs[0]
            for nid in net.node_ids() if net.nodes[nid].outputs
        }
        if len(decisions) != 1:
            raise RuntimeError(f"coin gauntlet session {s} disagreed: "
                               f"{decisions}")
        for (_to, rnd), (t0, t1, cnt) in agg.items():
            durations.append(t1 - t0)
            shares += cnt
            rounds.add(rnd)
    if not durations:
        raise RuntimeError("coin gauntlet flipped no threshold coin")
    return durations, shares, sorted(rounds)


# (tx_bytes, batch_size) cells of the MB-scale ingestion sweep: the tx
# axis spans 64 B → 64 KB and the batch axis 8 → 4096.  The 64 KB shapes
# stop at batch 32: batch × (max_tx_bytes + 16) must fit in half the
# wire blob cap (8 MiB), the same admission-sizing rule NodeRuntime
# enforces at boot.
INGEST_SHAPES = [
    (64, 8), (64, 256), (64, 4096), (4096, 256), (65536, 8), (65536, 32),
]


def _ingest_shape_run(tx_bytes: int, batch_size: int, *, n: int = 4,
                      clients: int = 16, duration_s: float = 5.0,
                      drain_s: float = 12.0, vid: bool = False,
                      ingress_workers: bool = False):
    """One ingestion-sweep cell: boot a throwaway cluster sized for
    (tx_bytes, batch), drive it with the open-loop generator, tear down.
    Unlike ``_net_run_once``'s closed-loop wave driver, offered load here
    is decoupled from commit progress, so the record separates offered /
    shed / committed and reports BOTH tx/s and MB/s."""
    import asyncio
    import subprocess

    from hbbft_tpu.net.cluster import (
        ClusterConfig, connect_when_up, find_free_base_port,
        shutdown_procs, spawn_node,
    )
    from hbbft_tpu.net.loadgen import LoadShape, run_load
    from hbbft_tpu.protocols import wire

    max_tx = max(256, tx_bytes + 64)
    if not vid and batch_size * (max_tx + 16) > wire.MAX_BLOB_BYTES // 2:
        # VID mode is exempt: contributions travel as O(1/n) erasure
        # shards and epochs order constant-size commitments, so MB-scale
        # batches the classic wire-blob admission rule forbids are
        # exactly the shapes the dispersal path exists to carry
        raise ValueError(
            f"ingest shape tx={tx_bytes} batch={batch_size} cannot boot: "
            f"batch × per-tx ceiling exceeds half the wire blob cap")
    base = find_free_base_port(2 * n)
    cfg = ClusterConfig(n=n, seed=9, batch_size=batch_size,
                        max_tx_bytes=max_tx, base_port=base,
                        metrics_base_port=base + n, vid=vid,
                        ingress_workers=ingress_workers)
    procs = [spawn_node(cfg, nid, stdout=subprocess.DEVNULL,
                        stderr=subprocess.STDOUT) for nid in range(n)]
    try:
        async def probe():
            for nid in range(n):
                c = await connect_when_up(cfg, nid,
                                          client_id=f"ingest-probe-{nid}")
                await c.close()

        asyncio.run(probe())
        shape = LoadShape(
            tx_bytes=tx_bytes, clients=clients,
            wave_txs=max(4, min(batch_size, 32)),
            duration_s=duration_s, drain_s=drain_s,
        )
        rep = run_load([cfg.addr(nid) for nid in range(n)],
                       cfg.cluster_id, shape)
    finally:
        shutdown_procs(procs)
    return {
        "tx_bytes": tx_bytes,
        "batch": batch_size,
        "vid": vid,
        "ingress_workers": ingress_workers,
        "clients": clients,
        "offered_txs": rep["offered_txs"],
        "shed_txs": rep["shed_txs"],
        "committed_txs": rep["committed_txs"],
        "committed_mb": rep["committed_mb"],
        "tx_per_s": rep["tx_per_s"],
        "mb_per_s": rep["mb_per_s"],
        "p50_latency_ms": rep["p50_ms"],
        "p99_latency_ms": rep["p99_ms"],
    }


def net_ingest_sweep(shapes=tuple(INGEST_SHAPES)):
    """The full (tx size × batch) open-loop grid for the --net artifact."""
    out = []
    for tx_bytes, batch in shapes:
        print(f"# ingest sweep: tx={tx_bytes}B batch={batch}…",
              file=sys.stderr, flush=True)
        cell = _ingest_shape_run(tx_bytes, batch)
        print(f"#   committed={cell['committed_txs']} "
              f"({cell['tx_per_s']} tx/s, {cell['mb_per_s']} MB/s, "
              f"shed={cell['shed_txs']})", file=sys.stderr, flush=True)
        out.append(cell)
    return out


def net_cluster_bench(epochs_target: int = 20, n: int = 4,
                      batch_size: int = 8, tx_size: int = 64,
                      depths=(1,), crypto_phases: bool = True,
                      ingest_sweep: bool = True,
                      watch: bool = False):
    """Localhost 4-node networked QHB benchmark (`--net`).

    Sweeps ``--pipeline-depth`` values (each a full cluster run of
    ``epochs_target`` epochs), reports the BEST depth as the headline
    epochs/s plus end-to-end p50/p99 submit→commit latency — the
    networked number "The Latency Price of Threshold Cryptosystems" says
    to measure.  The baseline for ``vs_baseline`` is the SAME workload on
    the in-process ``VirtualNet`` simulator (tx/s over wall clock).

    A second measurement (``crypto_phases``) runs the cluster WITH TPKE
    encryption so the threshold-decrypt phase is genuinely exercised and
    its span p50/p99 recorded, and fills the coin phase from the
    :func:`_coin_gauntlet` — the split-input ABA shape that actually
    reaches the threshold coin (an honest N=4 cluster provably never
    does; see the gauntlet docstring).  r02 reported ``spans: 0`` for
    both phases.  One JSON line either way, same contract as the config
    pass.
    """
    import random

    runs = [
        _net_run_once(epochs_target, n, batch_size, tx_size,
                      pipeline_depth=depth, watch=watch,
                      tag=f"depth{depth}")
        for depth in depths
    ]
    best = max(runs, key=lambda r: r["epochs_per_s"])

    crypto = None
    if crypto_phases:
        crypto = _net_run_once(
            max(8, epochs_target // 2), n, batch_size, tx_size,
            pipeline_depth=best["pipeline_depth"], encrypt=True,
            tag="crypto",
        )
        from hbbft_tpu.net.client import percentile

        coin_sessions = 8
        coin_durs, coin_shares, coin_rounds = _coin_gauntlet(
            sessions=coin_sessions, n=n)
        coin_durs.sort()
        crypto["phases"]["coin"] = {
            "p50_ms": round(percentile(coin_durs, 0.50) * 1e3, 3),
            "p99_ms": round(percentile(coin_durs, 0.99) * 1e3, 3),
            "spans": len(coin_durs),
            "attr_p50_ms": None,  # not part of the epoch timeline
            "source": "aba_coin_gauntlet",
        }
        crypto["coin_gauntlet"] = {
            "sessions": coin_sessions,
            "coin_rounds": coin_rounds,
            "shares_delivered": coin_shares,
        }

    # -- simulator baseline: identical workload on VirtualNet ----------------
    from hbbft_tpu.netinfo import NetworkInfo
    from hbbft_tpu.protocols.dynamic_honey_badger import DynamicHoneyBadger
    from hbbft_tpu.protocols.honey_badger import EncryptionSchedule
    from hbbft_tpu.protocols.queueing_honey_badger import (
        QhbBatch, QueueingHoneyBadger, TxInput,
    )
    from hbbft_tpu.sim import NetBuilder

    infos = NetworkInfo.generate_map(list(range(n)), random.Random(9))
    sim = NetBuilder(list(range(n))).using_step(
        lambda nid: QueueingHoneyBadger(
            DynamicHoneyBadger(
                infos[nid], infos[nid].secret_key(),
                rng=random.Random(7000 + nid),
                encryption_schedule=EncryptionSchedule.never(),
            ),
            batch_size=batch_size, rng=random.Random(8000 + nid),
        )
    )
    # identical workload: same tx count AND size (shard/merkle work
    # scales with payload bytes)
    sim_txs = [
        (b"sim-%06d:" % i).ljust(tx_size, b"\x5a")
        for i in range(best["committed_txs"])
    ]
    t0 = time.perf_counter()
    for i, tx in enumerate(sim_txs):
        sim.send_input(i % n, TxInput(tx))
    sim.run_to_quiescence()
    sim_wall = time.perf_counter() - t0
    sim_epochs = sum(
        1 for o in sim.nodes[0].outputs if isinstance(o, QhbBatch)
    )

    net_tx_rate = best["committed_txs"] / best["wall_s"]
    sim_tx_rate = len(sim_txs) / max(sim_wall, 1e-9)
    line = {
        "metric": f"net_qhb{n}_localhost",
        "value": best["epochs_per_s"],
        "unit": "epochs/s",
        # real sockets vs the in-process simulator crank loop on the SAME
        # workload: < 1 is the expected price of actual networking
        "vs_baseline": round(net_tx_rate / sim_tx_rate, 3),
        "shape": f"N={n} f={(n - 1) // 3} batch={batch_size} "
                 f"tx={tx_size}B depth={best['pipeline_depth']}",
        "pipeline_depth": best["pipeline_depth"],
        "pipeline_sweep": [
            {
                "depth": r["pipeline_depth"],
                "epochs_per_s": r["epochs_per_s"],
                "tx_per_s": round(r["committed_txs"] / r["wall_s"], 1),
                "p50_latency_ms": r["p50_ms"],
                "p99_latency_ms": r["p99_ms"],
            }
            for r in runs
        ],
        "epochs": best["epochs"],
        "committed_txs": best["committed_txs"],
        "tx_per_s": round(net_tx_rate, 1),
        "p50_latency_ms": best["p50_ms"],
        "p90_latency_ms": best["p90_ms"],
        "p99_latency_ms": best["p99_ms"],
        "sim_baseline_tx_per_s": round(sim_tx_rate, 1),
        "sim_baseline_epochs": sim_epochs,
        "phases": best["phases"],
        "transport": best["transport"],
        "pump_util": best.get("pump_util"),
    }
    if "watch" in best:
        line["watch"] = best["watch"]
    if ingest_sweep:
        line["ingest_sweep"] = net_ingest_sweep()
    if crypto is not None:
        line["crypto_phases"] = {
            "shape": f"N={n} f={(n - 1) // 3} batch={batch_size} "
                     f"tx={tx_size}B depth={crypto['pipeline_depth']} "
                     f"encrypt=always + coin gauntlet (split-input ABA)",
            "epochs": crypto["epochs"],
            "epochs_per_s": crypto["epochs_per_s"],
            "p50_latency_ms": crypto["p50_ms"],
            "p99_latency_ms": crypto["p99_ms"],
            "phases": crypto["phases"],
            "coin_gauntlet": crypto["coin_gauntlet"],
        }
    print(json.dumps(line), flush=True)


#: MB-scale VID ingest shapes: batch × per-tx ceiling crosses half the
#: wire blob cap (the classic admission rule refuses to even boot these
#: — _ingest_shape_run raises), so only commitment ordering + dispersal
#: can carry them.  Run with ingress workers off and on (satellite: does
#: parallel frame decode move the disperse-path numbers?).
VID_INGEST_SHAPES = [
    (65536, 96, False),
    (65536, 96, True),
]


def vid_dispersal_bench(epochs_target: int = 6, n: int = 4,
                        batch_size: int = 8, tx_size: int = 16384,
                        ingest: bool = True):
    """The verifiable-information-dispersal benchmark (``--vid``).

    The DispersedLedger experiment on one box: the ``bandwidth-asym``
    chaos preset caps ONE node's links at 64 KB/s while the rest run
    unshaped, then the SAME workload runs twice — classic RBC (every
    payload broadcast through the straggler's link) vs VID mode (epochs
    order constant-size (root, cert) commitments; the straggler receives
    an O(1/n) shard and retrieves payloads lazily, off the ordering
    path).  Epochs/s is measured at the SLOWEST node (``min(batches)``
    across the cluster), which is exactly where classic collapses and
    dispersal holds steady.  Both cells run at pipeline_depth=1 with the
    straggler starved of client traffic (``client_nodes = n − 1``) so
    the comparison isolates the availability path.

    ``tx_size`` defaults to 16 KiB: payload bulk has to dominate the
    per-epoch control traffic before the availability path is what the
    shape measures at all — at 4 KiB txs the classic cell is barely
    link-bound and both modes converge on the CPU ceiling.  VID's edge
    comes from two levers classic structurally lacks: dispersal beyond
    the cert's ``n − f`` voters is best-effort (shards bound for the
    straggler's saturated link are SHED, at most ``f`` peers per root),
    and retrieval is background work bounded to a small in-flight window,
    so the straggler's links carry almost nothing but the tiny ordering
    frames.

    One JSON line: headline = VID-mode epochs/s, ``vs_baseline`` = the
    VID/classic speedup (the acceptance gate wants ≥ 2).  ``asym_modes``
    carries both curves; ``vid_ingest`` carries the MB-scale open-loop
    shapes the classic wire-blob admission rule refuses to boot, with
    ingress workers off and on.
    """
    cells = []
    for vid in (False, True):
        tag = "vid" if vid else "classic"
        print(f"# vid bench: bandwidth-asym {tag} run…",
              file=sys.stderr, flush=True)
        r = _net_run_once(
            epochs_target, n, batch_size, tx_size, pipeline_depth=1,
            vid=vid, chaos="bandwidth-asym", client_nodes=n - 1,
            wave_limit_factor=800, tag=f"asym-{tag}")
        committed_mb = r["committed_txs"] * tx_size / 1e6
        cells.append({
            "mode": tag,
            "epochs": r["epochs"],
            "epochs_per_s": r["epochs_per_s"],
            "tx_per_s": round(r["committed_txs"] / r["wall_s"], 1),
            "mb_per_s": round(committed_mb / r["wall_s"], 3),
            "committed_txs": r["committed_txs"],
            "p50_latency_ms": r["p50_ms"],
            "p99_latency_ms": r["p99_ms"],
            "critical_path": {
                k: (r.get("critical_path") or {}).get(k)
                for k in ("mean_components", "p50")
            },
        })
    classic, vid_cell = cells
    speedup = round(
        vid_cell["epochs_per_s"] / max(classic["epochs_per_s"], 1e-9), 3)
    line = {
        "metric": f"vid_dispersal{n}_asym",
        "value": vid_cell["epochs_per_s"],
        "unit": "epochs/s",
        # the acceptance ratio: VID ordering throughput over classic RBC
        # under the same one-straggler 64 KB/s shape (must be ≥ 2)
        "vs_baseline": speedup,
        "speedup_vs_classic": speedup,
        "shape": f"N={n} f={(n - 1) // 3} batch={batch_size} "
                 f"tx={tx_size}B depth=1 chaos=bandwidth-asym",
        "pipeline_depth": 1,
        "asym_modes": cells,
        "classic_epochs_per_s": classic["epochs_per_s"],
    }
    if ingest:
        line["vid_ingest"] = []
        for tx_bytes, batch, workers in VID_INGEST_SHAPES:
            print(f"# vid ingest: tx={tx_bytes}B batch={batch} "
                  f"ingress_workers={workers}…",
                  file=sys.stderr, flush=True)
            cell = _ingest_shape_run(tx_bytes, batch, vid=True,
                                     ingress_workers=workers)
            print(f"#   committed={cell['committed_txs']} "
                  f"({cell['tx_per_s']} tx/s, {cell['mb_per_s']} MB/s, "
                  f"shed={cell['shed_txs']})", file=sys.stderr,
                  flush=True)
            line["vid_ingest"].append(cell)
    print(json.dumps(line), flush=True)


def freeze_perf_profile(epochs_target: int = 10, n: int = 4,
                        batch_size: int = 8, tx_size: int = 64,
                        out_name: str = "PERF_PROFILE.json"):
    """Freeze the same-host per-segment pump cost profile
    (``--freeze-perf-profile``): one short ``--net``-shaped cluster
    run, per-segment mean costs summed across the cluster, written to
    ``PERF_PROFILE.json`` — the baseline the watchtower's perf-drift
    sentinel (``obs.watch --perf-profile``) compares live scrape
    deltas against.  Same-host rule as every frozen number: re-freeze
    after a hardware change, never compare against another box's
    profile."""
    import datetime

    run = _net_run_once(epochs_target, n, batch_size, tx_size,
                        pipeline_depth=1, tag="perf-profile")
    segments = run.get("pump_util") or {}
    line = {
        "metric": "perf_profile",
        "value": len(segments),
        "unit": "segments",
        "vs_baseline": 1.0,
        "shape": f"N={n} f={(n - 1) // 3} batch={batch_size} "
                 f"tx={tx_size}B depth=1",
        "epochs": run["epochs"],
        "epochs_per_s": run["epochs_per_s"],
        "measured_utc": datetime.datetime.utcnow().strftime(
            "%Y-%m-%dT%H:%M:%SZ"),
        "segments": segments,
    }
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        out_name)
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(line, fh, indent=1, sort_keys=True)
        fh.write("\n")
    print(json.dumps(line), flush=True)


# ===========================================================================
# --compare: regression gate over two recorded bench JSON lines
# ===========================================================================


def load_bench_json(path):
    """The LAST JSON object in a bench output file (`BENCH_*.json` files
    hold exactly one; piped logs may prefix `#` detail lines)."""
    last = None
    with open(path, encoding="utf-8") as fh:
        text = fh.read()
    try:
        return json.loads(text)
    except ValueError:
        pass
    for line in text.splitlines():
        line = line.strip()
        if line.startswith("{"):
            try:
                last = json.loads(line)
            except ValueError:
                continue  # truncated/garbled line: salvage the rest
    if last is None:
        raise ValueError(f"{path}: no JSON object found")
    return last


def _lookup(doc, dotted):
    cur = doc
    for part in dotted.split("."):
        if not isinstance(cur, dict) or part not in cur:
            return None
        cur = cur[part]
    return cur if isinstance(cur, (int, float)) else None


def compare_bench(old, new, threshold: float = 0.15,
                  phase_threshold=None):
    """Regression verdict between two bench JSON lines.

    Headline throughput (``value``, higher-better when the unit is a
    rate), client latency p50/p99 and the epoch wall (lower-better) gate
    at ``threshold`` relative change; per-phase attribution deltas
    (rbc/aba/coin/decrypt ``attr_p50_ms``) gate at ``phase_threshold``
    (default 2×threshold — attribution is noisier than the headline, but
    a phase silently doubling is exactly the drift this gate exists to
    catch).  Returns a report dict with ``ok`` False on any regression.
    """
    if phase_threshold is None:
        phase_threshold = 2 * threshold
    checks = []

    def add(name, higher_better, limit):
        o, n = _lookup(old, name), _lookup(new, name)
        if o is None or n is None or o <= 0:
            return  # not comparable (absent / null phase) — skip
        delta = (n - o) / o
        worse = -delta if higher_better else delta
        checks.append({
            "name": name,
            "old": o,
            "new": n,
            "delta_pct": round(100 * delta, 2),
            "threshold_pct": round(100 * limit, 2),
            "regressed": worse > limit,
        })

    unit = str(old.get("unit", ""))
    # Mesh-equality rule (the equal-pipeline-depth rule's sibling): an
    # hb-epoch* record carries mesh_devices, and throughput across
    # different device counts measures different hardware — an 8-chip
    # recording must not read a single-chip rerun as an 87% regression.
    # Unequal meshes skip the headline value gate; records without the
    # field (every non-epoch config) default to 1 == 1 and gate normally.
    meshes_match = old.get("mesh_devices", 1) == new.get("mesh_devices", 1)
    # rates and the chaos campaign's clean fraction are higher-better;
    # latencies/durations below are lower-better
    if meshes_match:
        add("value",
            unit.endswith("/s")
            or unit in ("clean_fraction", "flagged_fraction"),
            threshold)
    for lat in ("p50_latency_ms", "p99_latency_ms"):
        add(lat, False, threshold)
    # Per-EPOCH duration metrics (epoch wall, phase attribution) compare
    # apples to apples only at equal pipeline depth: with depth > 1,
    # epochs overlap, so each epoch's first-activity→commit wall
    # stretches BY DESIGN while throughput and client latency improve.
    # Across a depth change those metrics measure different quantities —
    # skip them and let throughput + end-to-end latency (always
    # comparable) carry the verdict.
    depths_match = old.get("pipeline_depth", 1) == new.get(
        "pipeline_depth", 1)
    if depths_match:
        add("phases.epoch_wall_p50_ms", False, threshold)
        add("phases.epoch_wall_p99_ms", False, threshold)
        for group in ("rbc", "aba", "coin", "decrypt"):
            add(f"phases.{group}.attr_p50_ms", False, phase_threshold)
        # performance plane: per-segment pump mean cost is lower-better,
        # equal-shape rule like the ingest cells (a segment present in
        # only one recording contributes nothing) and equal-depth only
        # like the phase attribution above (a deeper pipeline
        # legitimately changes per-iteration work); gated at
        # phase_threshold — segment means are attribution-grade noisy
        old_pu, new_pu = (old.get("pump_util") or {},
                          new.get("pump_util") or {})
        for seg in sorted(k for k in old_pu if k in new_pu):
            o = (old_pu[seg] or {}).get("mean_s")
            nv = (new_pu[seg] or {}).get("mean_s")
            if not isinstance(o, (int, float)) \
                    or not isinstance(nv, (int, float)) or o <= 0:
                continue
            delta = (nv - o) / o
            checks.append({
                "name": f"pump[{seg}].mean_s",
                "old": o,
                "new": nv,
                "delta_pct": round(100 * delta, 2),
                "threshold_pct": round(100 * phase_threshold, 2),
                "regressed": delta > phase_threshold,
            })
    # ingestion sweep: tx/s and MB/s are higher-better rates gated ONLY
    # at equal (tx_bytes, batch) shape — a recording that adds, drops,
    # or resizes cells contributes nothing to the verdict for the
    # non-matching cells (throughput across different shapes measures
    # different work)
    def sweep_map(doc):
        return {
            (e.get("tx_bytes"), e.get("batch")): e
            for e in doc.get("ingest_sweep", ()) if isinstance(e, dict)
        }

    old_sweep, new_sweep = sweep_map(old), sweep_map(new)
    for key in sorted(k for k in old_sweep if k in new_sweep):
        for fld in ("tx_per_s", "mb_per_s"):
            o, nv = old_sweep[key].get(fld), new_sweep[key].get(fld)
            if not isinstance(o, (int, float)) \
                    or not isinstance(nv, (int, float)) or o <= 0:
                continue
            delta = (nv - o) / o
            checks.append({
                "name": f"ingest[{key[0]}B x{key[1]}].{fld}",
                "old": o,
                "new": nv,
                "delta_pct": round(100 * delta, 2),
                "threshold_pct": round(100 * threshold, 2),
                "regressed": -delta > threshold,
            })
    # BENCH_VID trajectory: the classic-vs-VID speedup under
    # bandwidth-asym is the artifact's reason to exist — it gates
    # higher-better like a rate.  Both per-mode epochs/s curves and the
    # MB-scale vid_ingest cells gate at equal shape only (same-host
    # fresh-baseline rule: compare against a baseline recorded on the
    # same box in the same session, never a checked-in number from other
    # hardware).
    add("speedup_vs_classic", True, threshold)

    def mode_map(doc):
        return {
            e.get("mode"): e
            for e in doc.get("asym_modes", ()) if isinstance(e, dict)
        }

    old_modes, new_modes = mode_map(old), mode_map(new)
    for mode in sorted(k for k in old_modes if k in new_modes):
        for fld in ("epochs_per_s", "tx_per_s"):
            o, nv = old_modes[mode].get(fld), new_modes[mode].get(fld)
            if not isinstance(o, (int, float)) \
                    or not isinstance(nv, (int, float)) or o <= 0:
                continue
            delta = (nv - o) / o
            checks.append({
                "name": f"asym[{mode}].{fld}",
                "old": o,
                "new": nv,
                "delta_pct": round(100 * delta, 2),
                "threshold_pct": round(100 * threshold, 2),
                "regressed": -delta > threshold,
            })

    def vid_ingest_map(doc):
        return {
            (e.get("tx_bytes"), e.get("batch"),
             bool(e.get("ingress_workers"))): e
            for e in doc.get("vid_ingest", ()) if isinstance(e, dict)
        }

    old_vi, new_vi = vid_ingest_map(old), vid_ingest_map(new)
    for key in sorted(k for k in old_vi if k in new_vi):
        for fld in ("tx_per_s", "mb_per_s"):
            o, nv = old_vi[key].get(fld), new_vi[key].get(fld)
            if not isinstance(o, (int, float)) \
                    or not isinstance(nv, (int, float)) or o <= 0:
                continue
            delta = (nv - o) / o
            checks.append({
                "name": (f"vid_ingest[{key[0]}B x{key[1]}"
                         f"{' +workers' if key[2] else ''}].{fld}"),
                "old": o,
                "new": nv,
                "delta_pct": round(100 * delta, 2),
                "threshold_pct": round(100 * threshold, 2),
                "regressed": -delta > threshold,
            })
    # BENCH_OBS trajectory (chaos_online_detection): per-cell detection
    # latency is lower-better, gated only at equal cell name (a grid
    # that adds or drops cells contributes nothing for the non-matching
    # ones); the aggregate flagged_fraction gates higher-better through
    # the headline "value" rule above.  A clean-cell false alarm is an
    # absolute regression: the baseline's count is the ceiling.
    def detect_map(doc):
        return {
            e.get("cell"): e
            for e in doc.get("detection", ()) if isinstance(e, dict)
        }

    old_det, new_det = detect_map(old), detect_map(new)
    for cell in sorted(k for k in old_det if k in new_det):
        o, nv = old_det[cell].get("detect_s"), new_det[cell].get(
            "detect_s")
        if not isinstance(o, (int, float)) \
                or not isinstance(nv, (int, float)) or o <= 0:
            continue
        delta = (nv - o) / o
        checks.append({
            "name": f"detect[{cell}].detect_s",
            "old": o,
            "new": nv,
            "delta_pct": round(100 * delta, 2),
            "threshold_pct": round(100 * threshold, 2),
            "regressed": delta > threshold,
        })
    o_fa, n_fa = (old.get("clean_false_alarms"),
                  new.get("clean_false_alarms"))
    if isinstance(o_fa, int) and isinstance(n_fa, int):
        checks.append({
            "name": "clean_false_alarms",
            "old": o_fa,
            "new": n_fa,
            "delta_pct": round(100.0 * (n_fa - o_fa) / max(o_fa, 1), 2),
            "threshold_pct": 0.0,
            "regressed": n_fa > o_fa,
        })
    # MULTICHIP trajectory (dryrun_multichip's emitted record): per
    # device-count epochs/s is a higher-better rate, gated only at equal
    # n_devices — like the chaos campaign's clean_fraction, dropping a
    # device count from the sweep contributes nothing to the verdict
    def traj_map(doc):
        return {
            e.get("n_devices"): e
            for e in doc.get("trajectory", ()) if isinstance(e, dict)
        }

    old_traj, new_traj = traj_map(old), traj_map(new)
    for nd in sorted(k for k in old_traj if k in new_traj):
        o, nv = (old_traj[nd].get("epochs_per_s"),
                 new_traj[nd].get("epochs_per_s"))
        if not isinstance(o, (int, float)) \
                or not isinstance(nv, (int, float)) or o <= 0:
            continue
        delta = (nv - o) / o
        checks.append({
            "name": f"trajectory[{nd}dev].epochs_per_s",
            "old": o,
            "new": nv,
            "delta_pct": round(100 * delta, 2),
            "threshold_pct": round(100 * threshold, 2),
            "regressed": -delta > threshold,
        })
    regressions = [c["name"] for c in checks if c["regressed"]]
    return {
        "metric": "bench_compare",
        "old_metric": old.get("metric"),
        "new_metric": new.get("metric"),
        "ok": not regressions,
        "regressions": regressions,
        "epoch_metrics_compared": depths_match,
        "mesh_metrics_compared": meshes_match,
        "checks": checks,
    }


def run_compare(old_path, new_path, threshold: float) -> int:
    old = load_bench_json(old_path)
    new = load_bench_json(new_path)
    if old.get("metric") != new.get("metric"):
        print(f"# warning: comparing different metrics "
              f"{old.get('metric')!r} vs {new.get('metric')!r}",
              file=sys.stderr)
    report = compare_bench(old, new, threshold=threshold)
    for c in report["checks"]:
        flag = "REGRESSED" if c["regressed"] else "ok"
        print(f"# {c['name']:<28} {c['old']:>12} -> {c['new']:>12} "
              f"({c['delta_pct']:+.1f}% vs ±{c['threshold_pct']:.0f}%) "
              f"{flag}", file=sys.stderr)
    print(json.dumps(report), flush=True)
    return 0 if report["ok"] else 1


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--config", choices=[*CONFIGS, "all"], default="all")
    ap.add_argument(
        "--sustained", type=int, metavar="EPOCHS", default=0,
        help="run a sustained N=4096 multi-epoch session instead of the "
        "config pass (records per-epoch time + drift)",
    )
    ap.add_argument(
        "--net", type=int, nargs="?", const=20, default=0,
        metavar="EPOCHS",
        help="run the localhost 4-node networked QHB benchmark "
             "(real processes + sockets via hbbft_tpu.net) until every "
             "node commits EPOCHS epochs; reports epochs/s and p50/p99 "
             "client tx latency",
    )
    ap.add_argument(
        "--vid", type=int, nargs="?", const=6, default=0,
        metavar="EPOCHS",
        help="run the verifiable-information-dispersal benchmark: "
             "classic RBC vs VID commitment ordering under the "
             "bandwidth-asym chaos preset (one 64 KB/s straggler), "
             "epochs/s measured at the slowest node, plus the MB-scale "
             "VID ingest shapes the classic wire-blob cap forbids "
             "(the BENCH_VID artifact)",
    )
    ap.add_argument(
        "--vid-no-ingest", action="store_true",
        help="skip --vid's MB-scale open-loop ingest cells",
    )
    ap.add_argument(
        "--pipeline-depth", default="1", metavar="D[,D…]",
        help="--net pipeline depth(s): a comma list runs one full "
             "measurement per depth (e.g. 1,2,4) and the best depth "
             "becomes the headline; per-depth results land in "
             "pipeline_sweep",
    )
    ap.add_argument(
        "--net-no-crypto-phases", action="store_true",
        help="skip --net's second (encrypted + link-shaped) measurement "
             "that exercises the threshold coin/decrypt phases",
    )
    ap.add_argument(
        "--net-no-ingest-sweep", action="store_true",
        help="skip --net's open-loop ingestion sweep (tx 64B→64KB × "
             "batch 8→4096 via net/loadgen; records per-shape tx/s "
             "and MB/s under ingest_sweep)",
    )
    ap.add_argument(
        "--net-watch", action="store_true",
        help="attach the live health plane to --net: a watchtower "
             "scrapes every node's obs endpoint and the streaming "
             "auditor tails the flight journals for the whole measured "
             "window — compare against a plain --net baseline from the "
             "same host/session to price the overhead (≤5%% epochs/s: "
             "--compare --compare-threshold 0.05)",
    )
    ap.add_argument(
        "--mesh", default="", metavar="auto|none|K|AxB",
        help="device mesh for the hb-epoch* configs (sets "
             "HBBFT_EPOCH_MESH): 'auto' shards over all devices when >1 "
             "(default), 'none' forces single-device, 'K' a 1-axis mesh "
             "over K devices, 'AxB' a 2-axis (dcn,ici) hierarchical mesh",
    )
    ap.add_argument(
        "--freeze-baselines", action="store_true",
        help="measure the HOST side of the non-headline configs and "
        "record them in BASELINE_MEASURED.json as the fixed vs_baseline "
        "denominators (host-only; no device work)",
    )
    ap.add_argument(
        "--freeze-perf-profile", type=int, nargs="?", const=10,
        default=0, metavar="EPOCHS",
        help="freeze the same-host per-segment pump cost profile (one "
             "short localhost cluster run) into PERF_PROFILE.json — "
             "the watchtower perf-drift sentinel's baseline "
             "(python -m hbbft_tpu.obs.watch --perf-profile)",
    )
    ap.add_argument(
        "--compare", nargs=2, metavar=("OLD.json", "NEW.json"),
        help="regression gate: compare two recorded bench JSON lines "
             "(epochs/s, latency p50/p99, per-phase attribution) and "
             "exit nonzero if NEW regressed past the threshold",
    )
    ap.add_argument(
        "--compare-threshold", type=float, default=0.15,
        help="relative regression threshold for --compare "
             "(default 0.15 = 15%%; per-phase attribution gates at 2x)",
    )
    args = ap.parse_args(argv)

    if args.compare:
        raise SystemExit(run_compare(args.compare[0], args.compare[1],
                                     args.compare_threshold))

    if args.freeze_baselines:
        freeze_baselines()
        return

    if args.freeze_perf_profile:
        freeze_perf_profile(epochs_target=args.freeze_perf_profile)
        return

    if args.vid:
        vid_dispersal_bench(epochs_target=args.vid,
                            ingest=not args.vid_no_ingest)
        return

    if args.net:
        try:
            depths = tuple(
                int(d) for d in str(args.pipeline_depth).split(",") if d
            )
        except ValueError:
            ap.error(f"--pipeline-depth {args.pipeline_depth!r}: want an "
                     "int or comma list of ints")
        net_cluster_bench(
            epochs_target=args.net, depths=depths or (1,),
            crypto_phases=not args.net_no_crypto_phases,
            ingest_sweep=not args.net_no_ingest_sweep,
            watch=args.net_watch,
        )
        return

    if args.sustained:
        if args.sustained < 2:
            ap.error("--sustained needs >= 2 epochs (epoch 0 is the "
                     "compile epoch; warm stats need at least one more)")
        from hbbft_tpu.util import enable_compilation_cache

        enable_compilation_cache()
        sustained4096(args.sustained)
        return

    if args.mesh:
        os.environ["HBBFT_EPOCH_MESH"] = args.mesh

    names = ([c for c in CONFIGS if c not in EXPLICIT_ONLY]
             if args.config == "all" else [args.config])
    results = []
    failed = []
    emitted = False
    interrupted = None
    error = None

    def emit_line():
        # Exactly ONE JSON line, whatever subset of configs completed.
        # Headline = the FIRST completed config (the full batched HB epoch
        # under --config all); detail rows carry the rest; partial/failed
        # runs are marked so a driver timeout can't masquerade as a full
        # successful pass.
        nonlocal emitted
        if emitted:
            return
        emitted = True
        if not results:
            line = {"metric": "none", "value": 0, "unit": "n/a",
                    "vs_baseline": 0}
        else:
            head = results[0]
            line = {
                "metric": head["metric"],
                "value": head["value"],
                "unit": head["unit"],
                "vs_baseline": head["vs_baseline"],
                "device": head["device"],
                "detail": [
                    dict(
                        {k: r[k]
                         for k in ("metric", "value", "unit", "vs_baseline")},
                        # N³-scaled estimates must not read as measured
                        **({"extrapolated": True}
                           if r.get("extrapolated") else {}),
                    )
                    for r in results
                ],
            }
            # headline consumers assume results[0] is the intended headline
            # config; flag it when that config failed and a different
            # metric/unit took its place
            if names and results[0].get("config_name") != names[0]:
                line["headline_fallback"] = True
            if head.get("extrapolated"):
                line["extrapolated"] = True
        if failed:
            line["configs_failed"] = failed
        if interrupted is not None:
            line["interrupted"] = interrupted
        if error is not None:
            line["error"] = error
        print(json.dumps(line), flush=True)

    def on_term(signum, frame):
        # a driver timeout must not erase the configs that DID finish;
        # no I/O here (buffered streams are not reentrant) — just record
        # and unwind to the finally below; conventional 128+signum exit
        # status so rc-based consumers see the interruption
        nonlocal interrupted
        interrupted = signum
        raise SystemExit(128 + signum)

    import signal

    for sig in (signal.SIGTERM, signal.SIGINT):
        signal.signal(sig, on_term)

    try:
        import jax

        from hbbft_tpu.util import enable_compilation_cache

        enable_compilation_cache()

        device = jax.devices()[0]
        print(f"# device: {device.platform} {device.device_kind}",
              file=sys.stderr)

        for name in names:
            try:
                r = CONFIGS[name]()
            except Exception as exc:  # a broken config must not kill the line
                print(f"# {name} FAILED: {exc!r}", file=sys.stderr)
                failed.append(name)
                continue
            # a config may return several records (rbc-mb1 emits its
            # encode and reconstruct measurements as separate metrics)
            for rec in r if isinstance(r, list) else [r]:
                rec["device"] = device.device_kind
                rec["config_name"] = name
                print(f"# {json.dumps(rec)}", file=sys.stderr)
                results.append(rec)
    except BaseException as exc:
        # a harness/setup crash must be distinguishable from a clean
        # zero-result run in the emitted line; the re-raise keeps the
        # nonzero exit status
        if not isinstance(exc, SystemExit):
            error = repr(exc)
        raise
    finally:
        emit_line()


if __name__ == "__main__":
    main()

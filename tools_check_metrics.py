#!/usr/bin/env python
"""Static metrics-contract check (tier 1 via tests/test_obs_metrics.py).

Thin CLI shim over :mod:`hbbft_tpu.lint.metric_convention` (the checker is
part of the hblint suite — ``python -m hbbft_tpu.lint`` runs it together
with the other checkers).  Kept byte-compatible with the original tool:
same exit codes, same violation messages, same OK line.

Asserts three things about the observability surface so it cannot rot
silently:

1. every metric name registered anywhere in the package (and bench.py)
   follows the naming convention ``hbbft_<layer>_<name>`` with a known
   layer (``net`` | ``node`` | ``phase`` | ``sim`` | ``obs`` | ``chaos`` | ``sync`` | ``guard`` | ``rbc`` | ``load`` | ``mesh``);
2. every registered metric name is documented in README.md's
   Observability section;
3. every :class:`hbbft_tpu.fault_log.FaultKind` variant has a
   pre-initialized ``kind`` label on ``hbbft_node_faults_total`` (so a
   new fault kind cannot ship without appearing — at zero — in every
   node's exposition).

Exit status 0 iff all checks pass; findings go to stdout.
"""

from __future__ import annotations

import os
import sys

from hbbft_tpu.lint.metric_convention import check_metrics, scan_registrations

REPO = os.path.dirname(os.path.abspath(__file__))


def registered_metric_names():
    """(name, file) pairs for every registration in the package + bench."""
    return [(name, path) for name, path, _line in scan_registrations(REPO)]


def main() -> int:
    problems, n_names, n_labels = check_metrics(REPO)
    if problems:
        print("tools_check_metrics: FAIL")
        for message, _path, _line in problems:
            print(f"  - {message}")
        return 1
    print(f"tools_check_metrics: OK — {n_names} metric names, "
          f"{n_labels} fault-kind labels, all documented and "
          f"convention-clean")
    return 0


if __name__ == "__main__":
    sys.exit(main())

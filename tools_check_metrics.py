#!/usr/bin/env python
"""Static metrics-contract check (tier 1 via tests/test_obs_metrics.py).

Asserts three things about the observability surface so it cannot rot
silently:

1. every metric name registered anywhere in the package (and bench.py)
   follows the naming convention ``hbbft_<layer>_<name>`` with a known
   layer (``net`` | ``node`` | ``phase`` | ``sim``);
2. every registered metric name is documented in README.md's
   Observability section;
3. every :class:`hbbft_tpu.fault_log.FaultKind` variant has a
   pre-initialized ``kind`` label on ``hbbft_node_faults_total`` (so a
   new fault kind cannot ship without appearing — at zero — in every
   node's exposition).

Exit status 0 iff all checks pass; findings go to stdout.
"""

from __future__ import annotations

import os
import re
import sys

REPO = os.path.dirname(os.path.abspath(__file__))

NAME_CONVENTION = re.compile(r"^hbbft_(net|node|phase|sim)_[a-z][a-z0-9_]*$")

# a registration is a .counter( / .gauge( / .histogram( call whose first
# argument is a string literal starting with hbbft_ (possibly on the next
# line); DEFAULT.counter(...) in sim/trace.py matches the same shape
_REG_RE = re.compile(
    r"\.(?:counter|gauge|histogram)\(\s*[\r\n]?\s*['\"](hbbft_[A-Za-z0-9_]*)['\"]",
    re.MULTILINE,
)


def registered_metric_names():
    """(name, file) pairs for every registration in the package + bench."""
    roots = []
    pkg = os.path.join(REPO, "hbbft_tpu")
    for dirpath, _dirs, files in os.walk(pkg):
        for fn in files:
            if fn.endswith(".py"):
                roots.append(os.path.join(dirpath, fn))
    roots.append(os.path.join(REPO, "bench.py"))
    out = []
    for path in roots:
        with open(path, encoding="utf-8") as fh:
            src = fh.read()
        for m in _REG_RE.finditer(src):
            out.append((m.group(1), os.path.relpath(path, REPO)))
    return out


def main() -> int:
    problems = []
    regs = registered_metric_names()
    if not regs:
        problems.append("no metric registrations found at all — the "
                        "scanner regex is broken")
    with open(os.path.join(REPO, "README.md"), encoding="utf-8") as fh:
        readme = fh.read()

    seen = {}
    for name, path in regs:
        seen.setdefault(name, set()).add(path)
    for name in sorted(seen):
        where = ", ".join(sorted(seen[name]))
        if not NAME_CONVENTION.match(name):
            problems.append(
                f"{name} ({where}): violates the naming convention "
                f"hbbft_<net|node|phase|sim>_<name>"
            )
        if f"`{name}`" not in readme and name not in readme:
            problems.append(
                f"{name} ({where}): not documented in README.md's "
                f"Observability section"
            )

    # FaultKind coverage: the runtime pre-initializes one label per
    # variant via obs.metrics.fault_counter — verify against the enum
    from hbbft_tpu.fault_log import FaultKind
    from hbbft_tpu.obs.metrics import Registry, fault_counter

    reg = Registry()
    c = fault_counter(reg)
    labeled = {labels["kind"] for labels, _child in c.series()}
    for k in FaultKind:
        if k.name not in labeled:
            problems.append(
                f"FaultKind.{k.name}: no pre-initialized label on "
                f"hbbft_node_faults_total (obs.metrics.fault_counter)"
            )

    if problems:
        print("tools_check_metrics: FAIL")
        for p in problems:
            print(f"  - {p}")
        return 1
    print(f"tools_check_metrics: OK — {len(seen)} metric names, "
          f"{len(labeled)} fault-kind labels, all documented and "
          f"convention-clean")
    return 0


if __name__ == "__main__":
    sys.exit(main())

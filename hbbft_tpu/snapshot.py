"""Checkpoint / resume (SURVEY §5).

The reference has no crash-restart persistence; its closest analogs are
``JoinPlan`` (era-boundary join state, mirrored in
``protocols/dynamic_honey_badger.py``) and the fact that every algorithm is
a serializable value.  This module makes that explicit for both execution
modes:

- object mode: any ``ConsensusProtocol`` is a pure-Python state machine, so
  ``snapshot``/``restore`` pickle it whole (the sans-I/O design means no
  sockets/threads/fds can leak into the image).  Snapshots taken at the
  same crank are byte-identical — a determinism check in itself.
- batched mode: the dense state dicts of :mod:`hbbft_tpu.parallel` are
  plain arrays; ``save_arrays``/``load_arrays`` round-trip them through an
  ``.npz`` — the "per-epoch dense-state snapshot" the survey names as a
  TPU-side win (snapshotting a whole network's epoch is one array dump).
"""

from __future__ import annotations

import io
import pickle
from typing import Any, Dict

import numpy as np


def snapshot(algorithm: Any) -> bytes:
    """Serialize a protocol state machine (HoneyBadger, DHB, QHB, …)."""
    return pickle.dumps(algorithm, protocol=pickle.HIGHEST_PROTOCOL)


def restore(data: bytes) -> Any:
    """Inverse of :func:`snapshot` — returns a live state machine that
    continues exactly where the original stood."""
    return pickle.loads(data)


def save_arrays(state: Dict[str, Any]) -> bytes:
    """Batched-mode state dict (str → array / scalar) → npz bytes."""
    buf = io.BytesIO()
    np.savez(buf, **{k: np.asarray(v) for k, v in state.items()})
    return buf.getvalue()


def load_arrays(data: bytes) -> Dict[str, np.ndarray]:
    with np.load(io.BytesIO(data)) as z:
        return {k: z[k] for k in z.files}

"""Checkpoint / resume / state-sync snapshots (SURVEY §5, ROADMAP item 5).

The reference has no crash-restart persistence; its closest analogs are
``JoinPlan`` (era-boundary join state, mirrored in
``protocols/dynamic_honey_badger.py``) and the fact that every algorithm is
a serializable value.  This module makes that explicit in three forms:

- object mode: any ``ConsensusProtocol`` is a pure-Python state machine, so
  ``snapshot``/``restore`` pickle it whole (the sans-I/O design means no
  sockets/threads/fds can leak into the image).  Snapshots taken at the
  same crank are byte-identical — a determinism check in itself.
- batched mode: the dense state dicts of :mod:`hbbft_tpu.parallel` are
  plain arrays; ``save_arrays``/``load_arrays`` round-trip them through an
  ``.npz`` — the "per-epoch dense-state snapshot" the survey names as a
  TPU-side win (snapshotting a whole network's epoch is one array dump).
- **state-sync mode** (the production join path): a :class:`JoinSnapshot`
  is everything a node with NO history needs to participate from an era
  boundary — the era's :class:`~hbbft_tpu.protocols.dynamic_honey_badger.
  JoinPlan` (validator set, threshold public key set, encryption
  schedule), the consensus-committed **DKG transcript** of the rotation
  that created the era, and the ledger-digest-chain position at the
  boundary ``(chain_head, chain_len)``.  Replaying the transcript through
  the joiner's own :class:`~hbbft_tpu.protocols.sync_key_gen.SyncKeyGen`
  decrypts the rows addressed to it and yields its **secret key share**
  (:func:`derive_secret_share`) — so a brand-new validator is
  share-complete from epoch 0 of the new era with zero epoch replay.
  :mod:`hbbft_tpu.net.statesync` moves these images over the wire.

Trust model: the snapshot is only as good as its source.  The transfer
layer cross-checks the manifest (era, image digest, chain head/length)
across multiple donors before fetching, every transcript signature is
re-verified against the plan's own key map, and the replayed DKG must
regenerate the plan's exact public key set — a donor cannot hand a joiner
a key set the committed DKG did not produce without forging BLS
signatures or breaking the Pedersen commitments.
"""

from __future__ import annotations

import io
import pickle
import random
from dataclasses import dataclass
from typing import Any, Dict, Hashable, Optional, Tuple

import numpy as np

from hbbft_tpu.crypto import tc
from hbbft_tpu.protocols import wire

NodeId = Hashable


def snapshot(algorithm: Any) -> bytes:
    """Serialize a protocol state machine (HoneyBadger, DHB, QHB, …)."""
    return pickle.dumps(algorithm, protocol=pickle.HIGHEST_PROTOCOL)


def restore(data: bytes) -> Any:
    """Inverse of :func:`snapshot` — returns a live state machine that
    continues exactly where the original stood."""
    return pickle.loads(data)


def save_arrays(state: Dict[str, Any]) -> bytes:
    """Batched-mode state dict (str → array / scalar) → npz bytes."""
    buf = io.BytesIO()
    np.savez(buf, **{k: np.asarray(v) for k, v in state.items()})
    return buf.getvalue()


def load_arrays(data: bytes) -> Dict[str, np.ndarray]:
    with np.load(io.BytesIO(data)) as z:
        return {k: z[k] for k in z.files}


# ===========================================================================
# State-sync join snapshots
# ===========================================================================


@dataclass(frozen=True)
class JoinSnapshot:
    """Era-boundary state for a node joining with zero history.

    Captured by a running node the moment a DKG rotation completes (the
    only instant ``DynamicHoneyBadger.join_plan()`` is valid) and served
    over :mod:`hbbft_tpu.net.statesync`.  ``transcript`` is empty for
    encryption-schedule rotations — the era inherits the previous key
    material, so a rejoining config-derived validator falls back to its
    config share (see :func:`derive_secret_share`).
    """

    era: int
    pub_key_set_bytes: bytes
    pub_keys: Tuple[Tuple[NodeId, bytes], ...]
    encryption_schedule: Tuple[str, int, int]
    transcript: Tuple[Any, ...]          # SignedKeyGenMsg, committed order
    chain_head: bytes                    # ledger digest at the boundary
    chain_len: int                       # digest chain length at the boundary

    def plan(self):
        from hbbft_tpu.protocols.dynamic_honey_badger import JoinPlan

        return JoinPlan(
            era=self.era,
            pub_key_set_bytes=self.pub_key_set_bytes,
            pub_keys=self.pub_keys,
            encryption_schedule=self.encryption_schedule,
        )


def capture_join_snapshot(dhb, chain_head: bytes,
                          chain_len: int) -> JoinSnapshot:
    """Package a freshly-rotated DHB's boundary state.  Only valid while
    no epoch of the new era has completed (``join_plan()`` raises
    otherwise)."""
    plan = dhb.join_plan()
    return JoinSnapshot(
        era=plan.era,
        pub_key_set_bytes=plan.pub_key_set_bytes,
        pub_keys=plan.pub_keys,
        encryption_schedule=plan.encryption_schedule,
        transcript=tuple(dhb.last_join_transcript),
        chain_head=bytes(chain_head),
        chain_len=int(chain_len),
    )


def encode_join_snapshot(snap: JoinSnapshot) -> bytes:
    """Canonical image bytes (what the chunked transfer moves)."""
    out = b"HBSNAP1" + wire.u64(snap.era)
    out += wire.blob(snap.pub_key_set_bytes)
    out += wire.u32(len(snap.pub_keys))
    for nid, pk in snap.pub_keys:
        out += wire.node_id(nid) + wire.blob(pk)
    kind, a, b = snap.encryption_schedule
    out += wire.blob(kind.encode()) + wire.u32(a) + wire.u32(b)
    out += wire.u32(len(snap.transcript))
    for skg in snap.transcript:
        out += wire.blob(skg.to_bytes())
    out += wire.blob(snap.chain_head) + wire.u64(snap.chain_len)
    return out


def decode_join_snapshot(data: bytes) -> JoinSnapshot:
    from hbbft_tpu.protocols.dynamic_honey_badger import SignedKeyGenMsg

    r = wire.Reader(data, max_blob=len(data))
    if r.take(7) != b"HBSNAP1":
        raise ValueError("bad join-snapshot magic")
    era = r.u64()
    pks_bytes = r.blob()
    n = r.u32()
    if n > 100_000:
        raise ValueError("absurd validator count")
    pub_keys = tuple((wire.read_node_id(r), r.blob()) for _ in range(n))
    kind = r.blob().decode()
    a, b = r.u32(), r.u32()
    nt = r.u32()
    if nt > 1_000_000:
        raise ValueError("absurd transcript length")
    transcript = tuple(
        SignedKeyGenMsg.read(wire.Reader(r.blob())) for _ in range(nt)
    )
    head = r.blob()
    if len(head) != 32:
        raise ValueError("bad chain head length")
    chain_len = r.u64()
    if not r.done():
        raise ValueError("trailing bytes after join snapshot")
    return JoinSnapshot(era, pks_bytes, pub_keys, (kind, a, b),
                        transcript, head, chain_len)


def derive_secret_share(
    snap: JoinSnapshot,
    our_id: NodeId,
    secret_key: tc.SecretKey,
    config_netinfo: Any = None,
) -> Optional[tc.SecretKeyShare]:
    """This node's threshold secret key share for ``snap.era``.

    With a DKG transcript: replay every committed, signature-valid
    key-gen message through a fresh ``SyncKeyGen`` (decrypting the rows
    encrypted to ``secret_key``), demand the regenerated public key set
    match the plan byte-for-byte, and return the derived share.  Without
    one (encryption-schedule rotations inherit the old keys): fall back
    to ``config_netinfo``'s share when its public key set matches the
    plan.  Returns ``None`` when no share can be derived — the node
    joins as an observer, exactly the reference JoinPlan semantics.

    CPU-heavy (BLS decryption + commitment checks): call it from sync
    code, never from an event-loop coroutine.
    """
    plan = snap.plan()
    if not snap.transcript:
        if config_netinfo is not None and (
            config_netinfo.public_key_set().commitment.to_bytes()
            == snap.pub_key_set_bytes
        ):
            return config_netinfo.secret_key_share()
        return None
    from hbbft_tpu.protocols.dynamic_honey_badger import de_ack, de_part
    from hbbft_tpu.protocols.sync_key_gen import SyncKeyGen

    keys = plan.key_map()
    threshold = (len(keys) - 1) // 3
    kg = SyncKeyGen(our_id, secret_key, keys, threshold, random.Random(0))
    dkg_era = snap.era - 1
    for skg in snap.transcript:
        if skg.era != dkg_era:
            continue
        pk = keys.get(skg.sender)
        if pk is None or not pk.verify(skg.sig, skg.signed_payload()):
            # a removed validator's committed message, or donor tampering:
            # the validators' SyncKeyGen rejected it without mutating, so
            # skipping reproduces their state
            continue
        try:
            if skg.kind == "part":
                kg.handle_part(skg.sender, de_part(skg.payload))
            elif skg.kind == "ack":
                kg.handle_ack(skg.sender, de_ack(skg.payload))
        except ValueError:
            continue
    if not kg.is_ready():
        raise ValueError(
            "join-snapshot DKG transcript does not complete — stale or "
            "tampered snapshot"
        )
    pub_key_set, share = kg.generate()
    if pub_key_set.commitment.to_bytes() != snap.pub_key_set_bytes:
        raise ValueError(
            "replayed DKG transcript yields a different public key set "
            "than the join plan claims — tampered snapshot"
        )
    return share


def build_joiner(
    snap: JoinSnapshot,
    our_id: NodeId,
    secret_key: tc.SecretKey,
    *,
    batch_size: int = 8,
    rng_seed: int = 0,
    config_netinfo: Any = None,
):
    """A ``SenderQueue(QHB(DHB))`` stack activated at ``snap``'s era
    boundary — the standard node stack, built from a snapshot instead of
    genesis config.  Returns the wrapped stack; the caller hosts it (a
    ``NodeRuntime`` with ``ledger_seed=(snap.chain_head, snap.chain_len)``
    continues the digest chain from the boundary)."""
    from hbbft_tpu.protocols.dynamic_honey_badger import DynamicHoneyBadger
    from hbbft_tpu.protocols.queueing_honey_badger import (
        QueueingHoneyBadger,
    )
    from hbbft_tpu.protocols.sender_queue import SenderQueue

    share = derive_secret_share(snap, our_id, secret_key,
                                config_netinfo=config_netinfo)
    dhb = DynamicHoneyBadger.from_join_plan(
        our_id, secret_key, snap.plan(),
        rng=random.Random(rng_seed), secret_key_share=share,
    )
    qhb = QueueingHoneyBadger(
        dhb, batch_size=batch_size, rng=random.Random(rng_seed + 1)
    )
    return SenderQueue(qhb)

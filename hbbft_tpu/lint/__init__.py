"""hblint — AST-based static analysis for the hbbft-tpu codebase.

Dependency-free (stdlib ``ast`` only, plus an import of the package under
analysis for the registry cross-checks).  Five checkers guard the
invariants no unit test can pin down exhaustively:

==================  =====================================================
checker             guards
==================  =====================================================
determinism         consensus core free of wall-clock / global RNG / set
                    iteration order leaking into encoding or fan-out
asyncio-hazard      net/obs event loop: no lost coroutines or tasks, no
                    blocking calls, no locks held across network awaits
wire-completeness   every protocol message registered, uniquely tagged,
                    decodable, frozen and hashable
fault-accounting    every drop path counted; no silent except: pass
metric-convention   metric naming + README docs + FaultKind labels
==================  =====================================================

CLI: ``python -m hbbft_tpu.lint [--json] [--changed-only GITREF] …`` —
runs as a tier-1 test over the repo (``tests/test_lint.py``).
Programmatic: :func:`run_lint` returns a :class:`LintResult`.

Suppress one finding with ``# hblint: disable=<rule>  (justification)``
on the flagged line, a whole file with ``# hblint: disable-file=<rule>``;
grandfather deliberate findings in ``hbbft_tpu/lint/baseline.txt``
(``--write-baseline`` regenerates, then edit the justifications).
"""

from hbbft_tpu.lint.core import (  # noqa: F401
    Checker,
    Finding,
    LintResult,
    ModuleSource,
    Project,
    all_checkers,
    register,
    rule_table,
    run_lint,
)
from hbbft_tpu.lint.reporters import render_json, render_text  # noqa: F401

__all__ = [
    "Checker", "Finding", "LintResult", "ModuleSource", "Project",
    "all_checkers", "register", "rule_table", "run_lint",
    "render_json", "render_text",
]

"""hblint output: human text and machine JSON.

The JSON document is the stable CI surface (``python -m hbbft_tpu.lint
--json``)::

    {
      "version": 1,
      "tool": "hblint",
      "checkers": ["determinism", ...],
      "findings": [
        {"checker": ..., "rule": ..., "path": ..., "line": ...,
         "message": ..., "fingerprint": ...},
        ...
      ],
      "summary": {"findings": N, "baselined": B, "suppressed": S,
                  "files_scanned": F, "stale_baseline": T, "clean": bool}
    }

``findings`` holds only actionable (non-suppressed, non-baselined)
entries, most problems first is not implied — order is (path, line, rule).
"""

from __future__ import annotations

import json

from hbbft_tpu.lint.core import LintResult

JSON_VERSION = 1


def render_text(result: LintResult, verbose_baseline: bool = False) -> str:
    lines = []
    for f in result.findings:
        lines.append(f"{f.location()}: [{f.rule}] {f.message}")
    if verbose_baseline:
        for f in result.baselined:
            lines.append(f"{f.location()}: [{f.rule}] (baselined) "
                         f"{f.message}")
    summary = (
        f"hblint: {'OK — ' if result.clean else ''}"
        f"{len(result.findings)} finding"
        f"{'' if len(result.findings) == 1 else 's'} "
        f"({len(result.baselined)} baselined, "
        f"{result.suppressed} suppressed) "
        f"across {result.files_scanned} files"
    )
    if result.stale_baseline:
        summary += (f"; {result.stale_baseline} stale baseline "
                    f"entr{'y' if result.stale_baseline == 1 else 'ies'}")
    lines.append(summary)
    return "\n".join(lines)


def render_json(result: LintResult) -> str:
    doc = {
        "version": JSON_VERSION,
        "tool": "hblint",
        "checkers": result.checkers,
        "findings": [f.as_dict() for f in result.findings],
        "baselined": [f.as_dict() for f in result.baselined],
        "summary": {
            "findings": len(result.findings),
            "baselined": len(result.baselined),
            "suppressed": result.suppressed,
            "files_scanned": result.files_scanned,
            "stale_baseline": result.stale_baseline,
            "clean": result.clean,
        },
    }
    return json.dumps(doc, indent=2, sort_keys=True)

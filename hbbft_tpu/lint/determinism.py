"""Checker: consensus-core determinism.

Every honest replica must fold the same inputs into the same Steps and the
same ledger, so code under ``protocols/``, ``parallel/`` and ``crypto/``
must not consult ambient nondeterminism — and ``chaos/`` joins the scope
because a chaos campaign cell must replay byte-identically from its seed
(a shaping decision drawn from wall time or the global RNG would make
every triaged failure unreproducible):

- ``det-wall-clock`` — wall-clock reads (``time.time``, ``time.monotonic``,
  ``datetime.now`` …).  Timing belongs to the drivers (net/, sim/, obs/),
  never to protocol state transitions.
- ``det-unseeded-random`` — module-level ``random.*`` calls (the shared,
  OS-seeded global RNG), ``os.urandom``, ``secrets.*``, ``uuid.uuid4``.
  Seeded ``random.Random(seed)`` instances are the sanctioned source
  (every protocol takes one); key-generation entry points (function name
  matching ``keygen|key_gen|generate``) are exempt — keys are *supposed*
  to be unpredictable.
- ``det-set-iteration`` — iterating a ``set``/``frozenset`` where the
  element order flows into wire encoding, hashing, or message fan-out
  (``encode_message``, ``to_bytes``, ``sha3*``, ``blob``, ``send`` …).
  Set iteration order is salted per process: two replicas running the
  same code can serialize the same logical value differently.  Route
  through ``sorted(...)`` instead.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterable, List, Set, Tuple

from hbbft_tpu.lint.core import Checker, Finding, ModuleSource, register

_WALL_CLOCK = {
    ("time", "time"), ("time", "time_ns"),
    ("time", "monotonic"), ("time", "monotonic_ns"),
    ("time", "perf_counter"), ("time", "perf_counter_ns"),
    ("time", "clock"),
    ("datetime", "now"), ("datetime", "utcnow"), ("datetime", "today"),
}

#: random-module attributes that are fine to *reference* (classes and
#: non-drawing helpers); everything else on the module is the global RNG
_RANDOM_OK = {"Random", "SystemRandom"}

_KEYGEN_RE = re.compile(r"(keygen|key_gen|generate)", re.IGNORECASE)

#: call names whose argument/loop-body ordering is consensus-visible
_ORDER_SINKS = {
    "encode_message", "to_bytes", "blob", "node_id", "u32", "u64",
    "sha3_256", "sha3_256_host", "update", "digest", "pack",
    "send", "send_frame", "push_message", "send_message", "join",
}


class _ImportMap(ast.NodeVisitor):
    """alias → module for plain imports, local name → (module, attr) for
    from-imports — enough to resolve ``t.monotonic()`` after
    ``import time as t`` and ``urandom()`` after ``from os import urandom``.
    """

    def __init__(self):
        self.modules: Dict[str, str] = {}
        self.froms: Dict[str, Tuple[str, str]] = {}

    def visit_Import(self, node: ast.Import) -> None:
        for a in node.names:
            self.modules[a.asname or a.name.split(".")[0]] = a.name

    def visit_ImportFrom(self, node: ast.ImportFrom) -> None:
        for a in node.names:
            if node.module:
                self.froms[a.asname or a.name] = (node.module, a.name)


def _resolve_call(node: ast.Call, imp: _ImportMap):
    """(module, attr) of a call when statically resolvable, else None."""
    f = node.func
    if isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name):
        mod = imp.modules.get(f.value.id)
        if mod is not None:
            return (mod, f.attr)
        # datetime.datetime.now() resolves through the from-import too
        frm = imp.froms.get(f.value.id)
        if frm is not None:
            return (frm[1], f.attr)
    if isinstance(f, ast.Name):
        frm = imp.froms.get(f.id)
        if frm is not None:
            return frm
    return None


def _is_set_expr(node: ast.AST, set_vars: Set[str]) -> bool:
    if isinstance(node, (ast.Set, ast.SetComp)):
        return True
    if isinstance(node, ast.Call) and isinstance(node.func, ast.Name):
        if node.func.id in ("set", "frozenset"):
            return True
    if isinstance(node, ast.Name) and node.id in set_vars:
        return True
    return False


def _call_name(node: ast.Call) -> str:
    if isinstance(node.func, ast.Name):
        return node.func.id
    if isinstance(node.func, ast.Attribute):
        return node.func.attr
    return ""


@register
class DeterminismChecker(Checker):
    name = "determinism"
    # chaos/ is in scope since the campaign runner: shaping decisions
    # and scenario schedules must come from the seeded RNG, or the
    # campaign's byte-identical-replay guarantee is fiction
    # ops/rs.py joined the scope with the backend-switched erasure hot
    # path: its per-backend STATS counters must stay plain ints (no
    # clocks) — all three backends must produce byte-identical parity,
    # and nondeterminism here forks the Merkle commitment
    # parallel/ includes parallel/mesh.py: the sharded epoch wrappers and
    # their per-phase STATS counters are subject to the same rule — a
    # mesh run and a single-device run must stay bit-identical, so the
    # collective accounting is computed statically from shapes, never
    # from clocks or traced values
    # obs/trace.py joined the scope with causal tracing: trace ids and
    # stage records ride the wire (tag 0x95) and must be derivable from
    # the tx bytes alone — a clock or RNG read here would fork the
    # byte-identical critpath reports of identical-seed sim runs
    # net/retrieve.py is clock-FREE by contract: every deadline decision
    # takes `now` from the caller (the runtime's pump), so the retrieval
    # state machine replays deterministically under the simulator — a
    # wall-clock read inside it would break that
    # obs/audit_stream.py joined with the streaming auditor: the
    # incremental core must produce byte-identical verdicts to the batch
    # CLI over the same journal bytes, regardless of feed order or poll
    # cadence — any clock or RNG read would fork that equivalence
    # obs/watch.py is clock-free by the same contract as net/retrieve:
    # Watchtower.tick(now, ...) takes the caller's clock (virtual in sim
    # cells, scripted in tests); only the CLI loop reads wall time,
    # under justified suppressions
    # obs/perf.py holds the performance plane to the same contract:
    # sample(now)/segment_means are pure folds over counter snapshots;
    # the single wall-clock read lives in maybe_sample under a
    # justified suppression
    scope = ("hbbft_tpu/protocols/", "hbbft_tpu/parallel/",
             "hbbft_tpu/crypto/", "hbbft_tpu/chaos/",
             "hbbft_tpu/ops/rs.py", "hbbft_tpu/obs/trace.py",
             "hbbft_tpu/net/retrieve.py", "hbbft_tpu/obs/audit_stream.py",
             "hbbft_tpu/obs/watch.py", "hbbft_tpu/obs/perf.py")
    rules = {
        "det-wall-clock":
            "wall-clock read in consensus-core code (time.time, "
            "time.monotonic, datetime.now, ...)",
        "det-unseeded-random":
            "global/OS-seeded randomness (module-level random.*, "
            "os.urandom, secrets, uuid4) outside key-generation entry "
            "points",
        "det-set-iteration":
            "set/frozenset iteration order flowing into wire encoding, "
            "hashing, or message fan-out",
    }

    def check_module(self, mod: ModuleSource) -> Iterable[Finding]:
        tree = mod.tree
        if tree is None:
            return []
        imp = _ImportMap()
        imp.visit(tree)
        out: List[Finding] = []
        self._visit(mod, tree, imp, func_stack=[], set_vars=set(), out=out)
        return out

    # -- recursive walk (one visit per node, function stack tracked) -------

    def _visit(self, mod, node, imp, func_stack, set_vars, out) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._visit(mod, child, imp, func_stack + [child.name],
                            set(), out)
                continue
            # track names assigned from set-typed expressions in this scope
            if isinstance(child, ast.Assign) and len(child.targets) == 1:
                tgt = child.targets[0]
                if isinstance(tgt, ast.Name):
                    if _is_set_expr(child.value, set_vars):
                        set_vars.add(tgt.id)
                    else:
                        set_vars.discard(tgt.id)
            self._check_node(mod, child, imp, func_stack, set_vars, out)
            self._visit(mod, child, imp, func_stack, set_vars, out)

    def _check_node(self, mod, node, imp, func_stack, set_vars, out) -> None:
        if isinstance(node, ast.Call):
            res = _resolve_call(node, imp)
            if res in _WALL_CLOCK:
                out.append(self.finding(
                    mod, "det-wall-clock", node,
                    f"wall-clock read {res[0]}.{res[1]}() in "
                    f"consensus-core code: replicas must not branch "
                    f"on local time",
                ))
            elif res is not None and self._is_global_random(res):
                if not any(_KEYGEN_RE.search(fn) for fn in func_stack):
                    out.append(self.finding(
                        mod, "det-unseeded-random", node,
                        f"{res[0]}.{res[1]}() draws from global/OS "
                        f"entropy: use a caller-supplied seeded "
                        f"random.Random (or move into a key-generation "
                        f"entry point)",
                    ))
            self._check_set_arg(mod, node, set_vars, out)
        elif isinstance(node, (ast.For, ast.AsyncFor)):
            self._check_set_loop(mod, node, set_vars, out)

    @staticmethod
    def _is_global_random(res: Tuple[str, str]) -> bool:
        mod, attr = res
        if mod == "random" and attr not in _RANDOM_OK:
            return True
        if (mod, attr) == ("os", "urandom"):
            return True
        if mod == "secrets":
            return True
        if (mod, attr) == ("uuid", "uuid4"):
            return True
        return False

    def _check_set_loop(self, mod, node, set_vars, out) -> None:
        if not _is_set_expr(node.iter, set_vars):
            return
        for sub in ast.walk(node):
            if isinstance(sub, ast.Call) and _call_name(sub) in _ORDER_SINKS:
                out.append(self.finding(
                    mod, "det-set-iteration", node,
                    f"loop over a set feeds order-sensitive call "
                    f"{_call_name(sub)}(): iterate sorted(...) so every "
                    f"replica serializes identically",
                ))
                return

    def _check_set_arg(self, mod, node, set_vars, out) -> None:
        if _call_name(node) not in _ORDER_SINKS:
            return
        for arg in node.args:
            direct_set = _is_set_expr(arg, set_vars)
            comp_over_set = isinstance(
                arg, (ast.GeneratorExp, ast.ListComp)
            ) and any(
                _is_set_expr(g.iter, set_vars) for g in arg.generators
            )
            if direct_set or comp_over_set:
                out.append(self.finding(
                    mod, "det-set-iteration", node,
                    f"set iteration order reaches order-sensitive call "
                    f"{_call_name(node)}(): wrap the set in sorted(...)",
                ))
                return

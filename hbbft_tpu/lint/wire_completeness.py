"""Checker: the wire codec covers the whole message surface.

``protocols/wire.py::_lazy_register`` is the single registry every byte on
the wire flows through; an unregistered message type raises only when it
is first *sent*, and an unhashable one breaks the replay-log dedup in
``net/runtime.py`` only when a peer *reconnects* — both far too late.
This checker front-loads the contract:

- ``wire-duplicate-tag`` — two classes registered under one tag byte;
- ``wire-missing-codec`` — a class with an encoder but no decoder for its
  tag (or a decoder tag no class encodes to);
- ``wire-not-frozen`` / ``wire-not-hashable`` — every registered class
  must be a ``@dataclass(frozen=True)`` with a working ``__hash__``
  (``net/runtime.py`` dedups replay-log entries by value; an unhashable
  message turns a peer reconnect into a TypeError);
- ``wire-unregistered`` — an AST sweep over ``protocols/``: any
  ``@dataclass`` whose name looks like a message (``*Msg``, ``*Message``,
  ``*Wrap``) but is not in the registry.  Types that deliberately ride
  *inside* another registered envelope carry a one-line suppression at
  the class definition.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterable, List, Optional, Set, Tuple

from hbbft_tpu.lint.core import Checker, Finding, ModuleSource, Project, register

_MSG_NAME_RE = re.compile(r".*(Msg|Message|Wrap)$")

_WIRE_PATH = "hbbft_tpu/protocols/wire.py"


def _class_anchor(project: Project, cls) -> Tuple[str, int, str]:
    """(path, line, snippet) of a class definition inside the project;
    falls back to wire.py:0 when the defining module is not scanned."""
    mod_name = getattr(cls, "__module__", "") or ""
    rel = mod_name.replace(".", "/") + ".py"
    mod = project.module(rel)
    if mod is not None and mod.tree is not None:
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.ClassDef) and node.name == cls.__name__:
                return rel, node.lineno, mod.line_at(node.lineno)
    return _WIRE_PATH, 0, ""


@register
class WireCompletenessChecker(Checker):
    name = "wire-completeness"
    scope = ("hbbft_tpu/protocols/",)
    rules = {
        "wire-duplicate-tag":
            "two message classes registered under the same wire tag",
        "wire-missing-codec":
            "registered message lacks an encoder/decoder pair",
        "wire-not-frozen":
            "wire-registered message class is not @dataclass(frozen=True)",
        "wire-not-hashable":
            "wire-registered message class has no usable __hash__ "
            "(breaks replay-log dedup in net/runtime.py)",
        "wire-unregistered":
            "message-shaped dataclass in protocols/ is not registered "
            "with the wire codec",
        "wire-import-error":
            "could not import the wire registry to cross-check it",
    }

    # -- per-file AST sweep -------------------------------------------------

    def check_module(self, mod: ModuleSource) -> Iterable[Finding]:
        # the AST sweep needs the registered-name set; done in
        # check_project so the registry is imported exactly once
        return ()

    def ast_unregistered(self, mod: ModuleSource,
                         registered: Set[str]) -> List[Finding]:
        """Message-shaped dataclasses of ``mod`` missing from
        ``registered`` (injectable for fixture tests)."""
        out: List[Finding] = []
        tree = mod.tree
        if tree is None:
            return out
        for node in ast.walk(tree):
            if not isinstance(node, ast.ClassDef):
                continue
            if not _MSG_NAME_RE.match(node.name):
                continue
            if not any(self._is_dataclass_deco(d) for d in
                       node.decorator_list):
                continue
            if node.name in registered:
                continue
            out.append(self.finding(
                mod, "wire-unregistered", node,
                f"dataclass {node.name} looks like a protocol message "
                f"but has no wire registration: add it to "
                f"wire._lazy_register (or suppress here if it only ever "
                f"rides inside another registered envelope)",
            ))
        return out

    @staticmethod
    def _is_dataclass_deco(deco: ast.AST) -> bool:
        if isinstance(deco, ast.Call):
            deco = deco.func
        if isinstance(deco, ast.Name):
            return deco.id == "dataclass"
        if isinstance(deco, ast.Attribute):
            return deco.attr == "dataclass"
        return False

    # -- registry invariants ------------------------------------------------

    def registry_findings(self, msg_tags: Dict[type, Tuple[int, object]],
                          msg_decoders: Dict[int, object],
                          locate) -> List[Finding]:
        """Pure invariant check over a (tags, decoders) registry;
        ``locate(cls) -> (path, line, snippet)`` anchors findings."""
        out: List[Finding] = []

        def f(rule: str, cls: Optional[type], message: str) -> Finding:
            path, line, snippet = (
                locate(cls) if cls is not None else (_WIRE_PATH, 0, "")
            )
            return Finding(checker=self.name, rule=rule, path=path,
                           line=line, message=message, snippet=snippet)

        by_tag: Dict[int, List[type]] = {}
        for cls, (tag, _enc) in msg_tags.items():
            by_tag.setdefault(tag, []).append(cls)
        for tag, classes in sorted(by_tag.items()):
            if len(classes) > 1:
                names = ", ".join(sorted(c.__name__ for c in classes))
                out.append(f(
                    "wire-duplicate-tag", classes[0],
                    f"tag 0x{tag:02x} registered for multiple classes: "
                    f"{names}",
                ))
            if tag not in msg_decoders:
                out.append(f(
                    "wire-missing-codec", classes[0],
                    f"{classes[0].__name__} (tag 0x{tag:02x}) has an "
                    f"encoder but no decoder",
                ))
        for tag in sorted(set(msg_decoders) - set(by_tag)):
            out.append(f(
                "wire-missing-codec", None,
                f"decoder registered for tag 0x{tag:02x} but no class "
                f"encodes to it",
            ))
        for cls in msg_tags:
            params = getattr(cls, "__dataclass_params__", None)
            if params is None or not params.frozen:
                out.append(f(
                    "wire-not-frozen", cls,
                    f"wire-registered {cls.__name__} must be "
                    f"@dataclass(frozen=True): mutable messages break "
                    f"value semantics across the codec and the replay "
                    f"log",
                ))
            if getattr(cls, "__hash__", None) is None:
                out.append(f(
                    "wire-not-hashable", cls,
                    f"wire-registered {cls.__name__} is unhashable "
                    f"(eq without hash): net/runtime.py's replay-log "
                    f"dedup raises TypeError on the first reconnect",
                ))
        return out

    # -- project entry ------------------------------------------------------

    def check_project(self, project: Project) -> Iterable[Finding]:
        try:
            from hbbft_tpu.protocols import wire

            wire.ensure_registered()
            msg_tags = dict(wire._MSG_TAGS)
            msg_decoders = dict(wire._MSG_DECODERS)
        except Exception as exc:  # pragma: no cover - import environment
            return [Finding(
                checker=self.name, rule="wire-import-error",
                path=_WIRE_PATH, line=0,
                message=f"cannot import/inspect the wire registry: "
                        f"{exc!r}",
            )]
        out = self.registry_findings(
            msg_tags, msg_decoders,
            locate=lambda cls: _class_anchor(project, cls),
        )
        registered = {cls.__name__ for cls in msg_tags}
        for mod in project.in_scope(self.scope):
            out.extend(self.ast_unregistered(mod, registered))
        return out

"""Checker: exception paths must be accounted, not swallowed.

PR 3's fault accounting only works if every drop path feeds a counter —
a swallowed exception is an invisible Byzantine symptom.

- ``fault-except-pass`` (repo-wide) — ``except: pass`` and its morally
  identical spellings (``except Exception: pass``, ``except
  (..., Exception): pass``).  If ignoring really is correct, write
  ``contextlib.suppress(...)`` (greppable, reviewable) — or a narrow
  exception type plus an accounting call.
- ``fault-swallowed-drop`` (``net/`` and ``obs/``) — an ``except`` handler
  that neither re-raises nor performs any *accounting*: a counter
  increment (``x += 1``, ``.inc()``, ``.observe()``), a ``record_*``/
  ``*_count``/``*backoff*``/``*fail*``/``*fault*`` call, or a raise.
  Logging alone does not count — logs are not scrapeable, and the whole
  point of the fault counters is that a drop path shows up in
  ``/metrics``.  ``obs/`` is in scope since the flight recorder: a
  journal that silently drops records on disk errors is a black box that
  lies, so its failure paths must count
  ``hbbft_obs_flight_write_failures_total`` (and friends).  ``chaos/``
  is in scope since the campaign runner: shaped-away frames must count
  ``hbbft_chaos_frames_dropped_total`` and a failed cell must land in
  the report's error tally, never vanish.  ``net/statesync.py`` is in
  scope since the membership lifecycle landed: every failed chunk is a
  counted retry (``hbbft_sync_chunk_retries_total``), every donor
  switch a counted failover, and an abandoned transfer must count
  ``hbbft_sync_transfers_abandoned_total`` — a joiner that silently
  gives up is a wedged validator.  ``obs/critpath.py`` rides the
  ``obs/`` scope with the same contract at the analysis layer:
  send/receive pairs that never match, trace stages that never pair
  up, and unalignable processes are *counted* in the report's
  ``unmatched`` section — an attribution tool that silently drops the
  evidence it couldn't attribute would be worse than none.  The
  authenticated handshake extends the contract to identity refusals:
  every hello the acceptor turns away must increment
  ``hbbft_guard_auth_failures_total`` under its reason label
  (``bad_sig`` / ``unknown_key`` / ``no_auth`` / ``malformed`` /
  ``timeout`` / ``session`` / ``half_open``) and journal the
  attacker's endpoint — a spoof attempt that vanishes without a
  counter is an attack rehearsal nobody will see coming.
"""

from __future__ import annotations

import ast
import re
from typing import Iterable, List

from hbbft_tpu.lint.core import Checker, Finding, ModuleSource, register

_BROAD = {"Exception", "BaseException"}

_ACCOUNT_RE = re.compile(
    r"(inc|observe|count|record|fault|fail|backoff|abort|drop|suppress)",
    re.IGNORECASE,
)


def _catches_broad(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:
        return True
    names = []
    for node in [t] + (list(t.elts) if isinstance(t, ast.Tuple) else []):
        if isinstance(node, ast.Name):
            names.append(node.id)
        elif isinstance(node, ast.Attribute):
            names.append(node.attr)
    return any(n in _BROAD for n in names)


def _body_is_pass(handler: ast.ExceptHandler) -> bool:
    return all(isinstance(s, ast.Pass) for s in handler.body)


def _call_name(node: ast.Call) -> str:
    if isinstance(node.func, ast.Name):
        return node.func.id
    if isinstance(node.func, ast.Attribute):
        return node.func.attr
    return ""


def _has_accounting(handler: ast.ExceptHandler) -> bool:
    for node in ast.walk(handler):
        if isinstance(node, ast.Raise):
            return True
        if isinstance(node, ast.AugAssign):
            return True  # self.decode_failures += 1 and friends
        if isinstance(node, ast.Call) and _ACCOUNT_RE.search(
            _call_name(node)
        ):
            return True
    return False


@register
class FaultAccountingChecker(Checker):
    name = "fault-accounting"
    scope = ()  # except-pass is repo-wide; the drop rule self-scopes
    rules = {
        "fault-except-pass":
            "bare/broad `except: pass` — use contextlib.suppress(...) or "
            "a narrow type plus accounting",
        "fault-swallowed-drop":
            "except handler in net/ or obs/ drops input with no "
            "accounting (no raise, no counter increment, no "
            "record_*/backoff call)",
    }

    #: the drop rule only applies here — peer/client input paths, the
    #: flight recorder's journal-durability paths, and the chaos layer
    #: (a shaped/dropped frame the campaign can't account for would
    #: corrupt every liveness number the report emits)
    #: protocols/vid.py joins net/'s drop scope: a swallowed disperse /
    #: vote / cert failure is availability input dropped without the
    #: counted fault the retrievability argument depends on
    DROP_SCOPE = ("hbbft_tpu/net/", "hbbft_tpu/obs/", "hbbft_tpu/chaos/",
                  "hbbft_tpu/protocols/vid.py")

    def check_module(self, mod: ModuleSource) -> Iterable[Finding]:
        tree = mod.tree
        if tree is None:
            return []
        out: List[Finding] = []
        in_drop_scope = any(
            mod.path.startswith(p) for p in self.DROP_SCOPE
        )
        for node in ast.walk(tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if _body_is_pass(node) and _catches_broad(node):
                out.append(self.finding(
                    mod, "fault-except-pass", node,
                    "broad except with a bare pass body swallows every "
                    "error invisibly: use contextlib.suppress(...) or "
                    "narrow the type and account for the drop",
                ))
                continue
            if in_drop_scope and not _has_accounting(node):
                out.append(self.finding(
                    mod, "fault-swallowed-drop", node,
                    "exception path drops input without accounting: "
                    "increment a fault/drop counter (or re-raise) so the "
                    "drop shows in /metrics",
                ))
        return out

"""hblint core: the checker framework (no dependencies beyond stdlib).

The pieces every checker shares:

- :class:`Finding` — one diagnostic, anchored to a file+line, carrying a
  content-based ``fingerprint`` so baselines survive line drift;
- :class:`ModuleSource` — one parsed source file (text, AST, suppression
  table).  Suppression comments::

      # hblint: disable=<rule>[,<rule>...]        (this line only)
      # hblint: disable-file=<rule>[,<rule>...]   (whole file)

  ``all`` suppresses every rule.  Anything after the rule list is a
  free-form justification (and writing one is the convention);
- :class:`Checker` — subclass, set ``name``/``rules``/``scope``, implement
  :meth:`Checker.check_module` (per in-scope file) and/or
  :meth:`Checker.check_project` (once per run, for cross-file rules);
- :func:`run_lint` — walk the scan set, run every registered checker,
  filter findings through suppressions and the checked-in baseline.

The baseline file (``hbbft_tpu/lint/baseline.txt``) grandfathers known,
deliberate findings: one per line, ``<fingerprint> <rule> <path>  #
justification``.  Fingerprints hash the rule + path + anchored source
line (not the line *number*), so unrelated edits to the file do not
invalidate entries; editing the anchored line itself does, on purpose.
"""

from __future__ import annotations

import ast
import hashlib
import os
import re
import subprocess
from dataclasses import dataclass, field
from typing import Callable, Dict, Iterable, List, Optional, Sequence, Set, Tuple

# ---------------------------------------------------------------------------
# findings


@dataclass(frozen=True)
class Finding:
    """One diagnostic: ``rule`` fired in ``path`` at ``line``.

    ``snippet`` is the stripped source line the finding anchors to (empty
    for file-level findings); it feeds the fingerprint so baseline entries
    track content, not line numbers.
    """

    checker: str
    rule: str
    path: str  # repo-relative, posix separators
    line: int  # 1-based; 0 = whole file
    message: str
    snippet: str = ""

    @property
    def fingerprint(self) -> str:
        anchor = self.snippet.strip() or self.message
        raw = f"{self.rule}|{self.path}|{anchor}".encode()
        return hashlib.sha1(raw).hexdigest()[:12]

    def location(self) -> str:
        return f"{self.path}:{self.line}" if self.line else self.path

    def as_dict(self) -> dict:
        return {
            "checker": self.checker,
            "rule": self.rule,
            "path": self.path,
            "line": self.line,
            "message": self.message,
            "fingerprint": self.fingerprint,
        }


# ---------------------------------------------------------------------------
# source model

# the rule list is comma-separated identifiers ONLY: it must stop at the
# first bare word so an unparenthesized justification ("... disable=x all
# timers are diagnostic") cannot leak tokens (like "all") into the list
_SUPPRESS_RE = re.compile(
    r"#\s*hblint:\s*(disable(?:-file)?)\s*=\s*"
    r"([A-Za-z0-9_\-]+(?:\s*,\s*[A-Za-z0-9_\-]+)*)"
)


def _parse_rule_list(raw: str) -> Set[str]:
    return {tok.strip() for tok in raw.split(",") if tok.strip()}


class ModuleSource:
    """One scanned file: text, lazily-parsed AST, suppression table."""

    def __init__(self, root: str, rel_path: str):
        self.root = root
        self.path = rel_path.replace(os.sep, "/")
        self.abs_path = os.path.join(root, rel_path)
        with open(self.abs_path, encoding="utf-8") as fh:
            self.text = fh.read()
        self.lines = self.text.splitlines()
        self._tree: Optional[ast.AST] = None
        self._parse_error: Optional[SyntaxError] = None
        self._line_suppress: Dict[int, Set[str]] = {}
        self._file_suppress: Set[str] = set()
        self._scan_suppressions()

    # -- AST ---------------------------------------------------------------

    @property
    def tree(self) -> Optional[ast.AST]:
        """Parsed AST, or None on a syntax error (see ``parse_error``)."""
        if self._tree is None and self._parse_error is None:
            try:
                self._tree = ast.parse(self.text, filename=self.path)
            except SyntaxError as exc:
                self._parse_error = exc
        return self._tree

    @property
    def parse_error(self) -> Optional[SyntaxError]:
        self.tree
        return self._parse_error

    def line_at(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1]
        return ""

    # -- suppressions ------------------------------------------------------

    def _scan_suppressions(self) -> None:
        for i, line in enumerate(self.lines, start=1):
            if "hblint" not in line:
                continue
            for m in _SUPPRESS_RE.finditer(line):
                rules = _parse_rule_list(m.group(2))
                if m.group(1) == "disable-file":
                    self._file_suppress |= rules
                    continue
                self._line_suppress.setdefault(i, set()).update(rules)
                # a comment-only suppression line also covers the next
                # code line (so the comment can sit ABOVE a long
                # statement instead of trailing past the line width)
                if line.lstrip().startswith("#"):
                    j = i + 1
                    while j <= len(self.lines) and (
                        not self.lines[j - 1].strip()
                        or self.lines[j - 1].lstrip().startswith("#")
                    ):
                        j += 1
                    if j <= len(self.lines):
                        self._line_suppress.setdefault(
                            j, set()).update(rules)

    def is_suppressed(self, rule: str, line: int) -> bool:
        if "all" in self._file_suppress or rule in self._file_suppress:
            return True
        at = self._line_suppress.get(line, ())
        return "all" in at or rule in at


class Project:
    """The whole scan set, handed to project-level checkers."""

    def __init__(self, root: str, modules: Sequence[ModuleSource]):
        self.root = root
        self.modules = list(modules)
        self._by_path = {m.path: m for m in self.modules}

    def module(self, rel_path: str) -> Optional[ModuleSource]:
        return self._by_path.get(rel_path.replace(os.sep, "/"))

    def in_scope(self, prefixes: Sequence[str]) -> List[ModuleSource]:
        if not prefixes:
            return list(self.modules)
        return [
            m for m in self.modules
            if any(m.path.startswith(p) for p in prefixes)
        ]


# ---------------------------------------------------------------------------
# checkers


class Checker:
    """Base class.  Subclasses set:

    - ``name`` — checker id (used in reports and ``--checkers``);
    - ``rules`` — {rule-id: one-line description} (drives ``--list-rules``
      and the README table);
    - ``scope`` — path prefixes (relative to the repo root) the per-file
      pass applies to; ``()`` means every scanned file.
    """

    name: str = "base"
    rules: Dict[str, str] = {}
    scope: Tuple[str, ...] = ()

    def check_module(self, mod: ModuleSource) -> Iterable[Finding]:
        return ()

    def check_project(self, project: Project) -> Iterable[Finding]:
        return ()

    # helper: a Finding anchored to an AST node of ``mod``
    def finding(self, mod: ModuleSource, rule: str, node,
                message: str) -> Finding:
        line = getattr(node, "lineno", 0) if node is not None else 0
        return Finding(
            checker=self.name, rule=rule, path=mod.path, line=line,
            message=message, snippet=mod.line_at(line),
        )


_REGISTRY: List[Callable[[], Checker]] = []


def register(cls):
    """Class decorator: add a checker to the default suite."""
    _REGISTRY.append(cls)
    return cls


def all_checkers() -> List[Checker]:
    """Instantiate the full default suite (imports the checker modules)."""
    from hbbft_tpu.lint import (  # noqa: F401  (registration side effect)
        asyncio_hazard,
        bounded_ingress,
        determinism,
        fault_accounting,
        metric_convention,
        wire_completeness,
    )

    return [cls() for cls in _REGISTRY]


def rule_table() -> Dict[str, Tuple[str, str]]:
    """{rule-id: (checker name, description)} for the default suite."""
    out = {}
    for chk in all_checkers():
        for rule, desc in chk.rules.items():
            out[rule] = (chk.name, desc)
    return out


# ---------------------------------------------------------------------------
# scan set

#: default scan targets, relative to the repo root — the package plus the
#: repo-level scripts; tests/ is deliberately excluded (lint fixtures live
#: there and contain intentional violations)
DEFAULT_PATHS = (
    "hbbft_tpu",
    "examples",
    "bench.py",
    "tools_check_metrics.py",
    "tools_measure_host64.py",
    "__graft_entry__.py",
)

_SKIP_DIRS = {"__pycache__", ".git", ".jax_cache"}


def default_root() -> str:
    """The repo root: the directory containing the ``hbbft_tpu`` package."""
    pkg = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    return os.path.dirname(pkg)


def collect_files(root: str, paths: Sequence[str]) -> List[str]:
    """Expand the scan set into sorted repo-relative ``.py`` paths."""
    out: Set[str] = set()
    for p in paths:
        absp = os.path.join(root, p)
        if os.path.isfile(absp):
            if absp.endswith(".py"):
                out.add(os.path.relpath(absp, root))
        elif os.path.isdir(absp):
            for dirpath, dirnames, filenames in os.walk(absp):
                dirnames[:] = [d for d in dirnames if d not in _SKIP_DIRS]
                for fn in filenames:
                    if fn.endswith(".py"):
                        out.add(os.path.relpath(
                            os.path.join(dirpath, fn), root))
    return sorted(o.replace(os.sep, "/") for o in out)


def changed_files(root: str, gitref: str) -> Set[str]:
    """Repo-relative paths changed vs ``gitref``: working-tree diff PLUS
    untracked files — a brand-new module must not dodge the pre-commit
    path just because it was never ``git add``\\ ed."""
    out: Set[str] = set()
    for cmd in (
        ["git", "diff", "--name-only", gitref, "--"],
        ["git", "ls-files", "--others", "--exclude-standard"],
    ):
        try:
            proc = subprocess.run(
                cmd, cwd=root, capture_output=True, text=True, timeout=30,
            )
        except (OSError, subprocess.TimeoutExpired) as exc:
            raise RuntimeError(f"{' '.join(cmd)} failed: {exc}")
        if proc.returncode != 0:
            raise RuntimeError(
                f"{' '.join(cmd)} failed: {proc.stderr.strip()}"
            )
        out |= {l.strip() for l in proc.stdout.splitlines() if l.strip()}
    return out


# ---------------------------------------------------------------------------
# baseline

DEFAULT_BASELINE = os.path.join(os.path.dirname(__file__), "baseline.txt")


def load_baseline(path: str) -> Dict[str, str]:
    """{fingerprint: rest-of-line} from a baseline file (missing → {})."""
    out: Dict[str, str] = {}
    if not path or not os.path.exists(path):
        return out
    with open(path, encoding="utf-8") as fh:
        for raw in fh:
            line = raw.strip()
            if not line or line.startswith("#"):
                continue
            fp = line.split()[0]
            out[fp] = line
    return out


def render_baseline(findings: Sequence[Finding]) -> str:
    """Serialize findings as a baseline file body (stable order).

    Fingerprints are content-based (rule + path + anchored source line),
    so one entry covers every identical-content occurrence in that file —
    deliberate: grandfathering `async with self._wlock:` once means the
    established pattern, not one blessed line number.
    """
    lines = [
        "# hblint baseline — grandfathered findings; one per line:",
        "#   <fingerprint> <rule> <path>  # justification",
        "# Regenerate with: python -m hbbft_tpu.lint --write-baseline",
        "# (and then EDIT the justifications — they are the point).",
        "# An entry covers all identical-content occurrences in its file.",
    ]
    seen: Set[str] = set()
    for f in sorted(findings, key=lambda f: (f.path, f.rule, f.line)):
        if f.fingerprint in seen:
            continue
        seen.add(f.fingerprint)
        lines.append(
            f"{f.fingerprint} {f.rule} {f.path}  # TODO justify: "
            f"{f.message[:100]}"
        )
    return "\n".join(lines) + "\n"


# ---------------------------------------------------------------------------
# runner


@dataclass
class LintResult:
    findings: List[Finding] = field(default_factory=list)  # actionable
    baselined: List[Finding] = field(default_factory=list)
    suppressed: int = 0
    files_scanned: int = 0
    stale_baseline: int = 0
    checkers: List[str] = field(default_factory=list)

    @property
    def clean(self) -> bool:
        return not self.findings


def run_lint(
    root: Optional[str] = None,
    paths: Optional[Sequence[str]] = None,
    checkers: Optional[Sequence[Checker]] = None,
    baseline_path: Optional[str] = DEFAULT_BASELINE,
    changed_only: Optional[str] = None,
) -> LintResult:
    """Run the suite; returns a :class:`LintResult`.

    ``changed_only``: a git ref — per-file checks are restricted to files
    that differ from it (project-level checks always run: they are
    cross-file, and a changed file can break an invariant anchored in an
    unchanged one).
    """
    root = root or default_root()
    rel_paths = collect_files(root, paths or DEFAULT_PATHS)
    changed: Optional[Set[str]] = None
    if changed_only is not None:
        changed = changed_files(root, changed_only)

    modules = [ModuleSource(root, rp) for rp in rel_paths]
    project = Project(root, modules)
    suite = list(checkers) if checkers is not None else all_checkers()

    raw: List[Finding] = []
    for mod in modules:
        if mod.parse_error is not None:
            raw.append(Finding(
                checker="core", rule="syntax-error", path=mod.path,
                line=mod.parse_error.lineno or 0,
                message=f"file does not parse: {mod.parse_error.msg}",
            ))
            continue
        if changed is not None and mod.path not in changed:
            continue
        for chk in suite:
            if chk.scope and not any(
                mod.path.startswith(p) for p in chk.scope
            ):
                continue
            raw.extend(chk.check_module(mod))
    for chk in suite:
        raw.extend(chk.check_project(project))

    result = LintResult(
        files_scanned=len(modules), checkers=[c.name for c in suite]
    )
    baseline = load_baseline(baseline_path) if baseline_path else {}
    seen_fp: Set[str] = set()
    for f in sorted(raw, key=lambda f: (f.path, f.line, f.rule)):
        mod = project.module(f.path)
        if mod is not None and mod.is_suppressed(f.rule, f.line):
            result.suppressed += 1
            continue
        if f.fingerprint in baseline:
            seen_fp.add(f.fingerprint)
            result.baselined.append(f)
            continue
        result.findings.append(f)
    result.stale_baseline = len(set(baseline) - seen_fp)
    return result

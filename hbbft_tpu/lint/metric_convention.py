"""Checker: the observability metrics contract (ex ``tools_check_metrics``).

The PR-3 static pass, rehosted on the lint framework (the repo-root
``tools_check_metrics.py`` remains as a thin CLI shim with byte-identical
output).  Three invariants over the package + ``bench.py``:

- every registered metric name follows
  ``hbbft_<net|node|phase|sim|obs|chaos|sync|guard|rbc|load|mesh|gw|vid>_<name>``;
- every registered metric name is documented in README.md's Observability
  section;
- every :class:`~hbbft_tpu.fault_log.FaultKind` variant has a
  pre-initialized ``kind`` label on ``hbbft_node_faults_total``.

Problem *messages* are kept identical to the original tool so its tier-1
behavior cannot drift while the plumbing changes underneath.
"""

from __future__ import annotations

import os
import re
from typing import Iterable, List, Optional, Tuple

from hbbft_tpu.lint.core import Checker, Finding, Project, register

NAME_CONVENTION = re.compile(
    r"^hbbft_(net|node|phase|sim|obs|chaos|sync|guard|rbc|load|mesh"
    r"|pump|trace|gw|vid|health|perf|ctrl)"
    r"_[a-z][a-z0-9_]*$"
)

# a registration is a .counter( / .gauge( / .histogram( call whose first
# argument is a string literal starting with hbbft_ (possibly on the next
# line); DEFAULT.counter(...) in sim/trace.py matches the same shape
_REG_RE = re.compile(
    r"\.(?:counter|gauge|histogram)\(\s*[\r\n]?\s*['\"](hbbft_[A-Za-z0-9_]*)['\"]",
    re.MULTILINE,
)


def scan_registrations(root: str) -> List[Tuple[str, str, int]]:
    """(name, repo-relative file, line) for every registration in the
    package + bench.py under ``root``."""
    paths = []
    pkg = os.path.join(root, "hbbft_tpu")
    for dirpath, _dirs, files in os.walk(pkg):
        for fn in files:
            if fn.endswith(".py"):
                paths.append(os.path.join(dirpath, fn))
    bench = os.path.join(root, "bench.py")
    if os.path.exists(bench):
        paths.append(bench)
    out = []
    for path in paths:
        with open(path, encoding="utf-8") as fh:
            src = fh.read()
        rel = os.path.relpath(path, root).replace(os.sep, "/")
        for m in _REG_RE.finditer(src):
            line = src.count("\n", 0, m.start()) + 1
            out.append((m.group(1), rel, line))
    return out


def check_metrics(root: str, check_faults: bool = True):
    """The full contract check.

    Returns ``(problems, n_names, n_fault_labels)`` where ``problems`` is a
    list of ``(message, path, line)`` — messages byte-identical to the
    original ``tools_check_metrics.py`` so the shim's output cannot drift.
    """
    problems: List[Tuple[str, Optional[str], int]] = []
    regs = scan_registrations(root)
    if not regs:
        problems.append((
            "no metric registrations found at all — the "
            "scanner regex is broken", None, 0,
        ))
    readme_path = os.path.join(root, "README.md")
    readme = ""
    if os.path.exists(readme_path):
        with open(readme_path, encoding="utf-8") as fh:
            readme = fh.read()

    seen = {}
    first_at = {}
    for name, path, line in regs:
        seen.setdefault(name, set()).add(path)
        first_at.setdefault(name, (path, line))
    for name in sorted(seen):
        where = ", ".join(sorted(seen[name]))
        path, line = first_at[name]
        if not NAME_CONVENTION.match(name):
            problems.append((
                f"{name} ({where}): violates the naming convention "
                f"hbbft_<net|node|phase|sim|obs|chaos|sync|guard>_<name>",
                path, line,
            ))
        if f"`{name}`" not in readme and name not in readme:
            problems.append((
                f"{name} ({where}): not documented in README.md's "
                f"Observability section", path, line,
            ))

    n_labels = 0
    if check_faults:
        # FaultKind coverage: the runtime pre-initializes one label per
        # variant via obs.metrics.fault_counter — verify against the enum
        from hbbft_tpu.fault_log import FaultKind
        from hbbft_tpu.obs.metrics import Registry, fault_counter

        reg = Registry()
        c = fault_counter(reg)
        labeled = {labels["kind"] for labels, _child in c.series()}
        n_labels = len(labeled)
        for k in FaultKind:
            if k.name not in labeled:
                problems.append((
                    f"FaultKind.{k.name}: no pre-initialized label on "
                    f"hbbft_node_faults_total (obs.metrics.fault_counter)",
                    "hbbft_tpu/obs/metrics.py", 0,
                ))
    return problems, len(seen), n_labels


@register
class MetricConventionChecker(Checker):
    name = "metric-convention"
    scope = ("hbbft_tpu/",)
    rules = {
        "metric-convention":
            "metric naming convention, README documentation, and "
            "FaultKind label coverage (the tools_check_metrics contract)",
    }

    def check_project(self, project: Project) -> Iterable[Finding]:
        problems, _n, _l = check_metrics(project.root)
        out = []
        for message, path, line in problems:
            mod = project.module(path) if path else None
            snippet = mod.line_at(line) if (mod and line) else ""
            out.append(Finding(
                checker=self.name, rule="metric-convention",
                path=path or "hbbft_tpu/obs/metrics.py", line=line,
                message=message, snippet=snippet,
            ))
        return out

"""Checker: network-fed collections must be bounded.

The overload-defense contract (Byzantine overload PR): any dict / list /
set a ``net/`` or ``protocols/`` module GROWS from network-derived input
must carry a cap with a counted eviction — or a justified suppression.
A buffer that only ever appends is a memory-exhaustion lever for a
single Byzantine peer; the per-peer ingress budgets at the transport
only help if every layer above them is bounded too.

- ``bounded-ingress`` (``net/`` and ``protocols/``) — a statement that
  grows a ``self.*`` collection (``.append`` / ``.add`` / ``.extend`` /
  ``.insert``, including through ``.setdefault(...)`` chains) inside a
  function that receives network-derived input (a parameter named like
  ``sender_id`` / ``peer_id`` / ``payload`` / ``message`` / ``conn``),
  where the enclosing CLASS shows no bounding evidence for that
  attribute.

Bounding evidence for attribute ``X`` is any of, anywhere in the class:

- a ``len(self.X…)`` comparison (cap check);
- a removal call on it (``pop`` / ``popleft`` / ``popitem`` / ``clear``
  / ``discard`` / ``remove``) or a ``del self.X[…]`` statement;
- assignment replacing it wholesale (``self.X = …`` outside
  ``__init__`` — swap-and-drain buffers).

Growth whose added element is itself just the sender identity is exempt:
a set/dict keyed by peer id is bounded by peer cardinality, which the
``UnknownSender`` screening already caps.

Heuristic by design: a genuinely bounded-elsewhere site earns a
``# hblint: disable=bounded-ingress (<why>)`` with its justification —
the suppression IS the documentation the rule exists to force.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterable, List, Optional, Set

from hbbft_tpu.lint.core import Checker, Finding, ModuleSource, register

#: parameter names that mark a function as handling network-derived
#: input (the protocols' handle_message surface, transport callbacks,
#: client admission)
_NET_PARAMS = frozenset({
    "sender_id", "sender", "peer_id", "peer", "payload", "data",
    "message", "msg", "frame", "hello", "conn", "tx",
    # the authenticated-handshake surface: everything a dialer hands
    # the acceptor BEFORE it is verified is network-derived input, and
    # anything grown from it pre-verification is a pre-auth memory
    # lever (the half-open budget only caps concurrency, not state)
    "nonce", "session", "sig", "signature", "auth",
})

#: the subset of network parameters that are peer IDENTITIES — only
#: these make a grown element "bounded by peer cardinality" (a message
#: or payload parameter is attacker-controlled content, never exempt)
_SENDER_PARAMS = frozenset({"sender_id", "sender", "peer_id", "peer"})

_GROW_METHODS = frozenset({"append", "add", "extend", "insert"})
_REMOVE_METHODS = frozenset({
    "pop", "popleft", "popitem", "clear", "discard", "remove",
})


def _self_attr_of(node: ast.AST) -> Optional[str]:
    """``self.X`` → ``"X"``; also unwraps one subscript level
    (``self.X[k]``) and ``self.X.setdefault(...)`` chains."""
    if isinstance(node, ast.Subscript):
        node = node.value
    if isinstance(node, ast.Call):
        # self.X.setdefault(...).append(...): the call's own func is
        # Attribute(setdefault) on self.X
        func = node.func
        if isinstance(func, ast.Attribute) and func.attr == "setdefault":
            node = func.value
    if (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return node.attr
    return None


class _ClassEvidence(ast.NodeVisitor):
    """Collect, per class, the attributes with bounding evidence."""

    def __init__(self):
        self.bounded: Set[str] = set()
        self._in_init = False

    def visit_FunctionDef(self, node):
        prev, self._in_init = self._in_init, node.name == "__init__"
        self.generic_visit(node)
        self._in_init = prev

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Call(self, node: ast.Call):
        func = node.func
        if isinstance(func, ast.Attribute):
            if func.attr in _REMOVE_METHODS:
                attr = _self_attr_of(func.value)
                if attr is not None:
                    self.bounded.add(attr)
            elif func.attr == "sort":
                # sort-then-del is the front-chop idiom; the del itself
                # also registers, this just tolerates helper splits
                attr = _self_attr_of(func.value)
                if attr is not None:
                    self.bounded.add(attr)
        if (isinstance(func, ast.Name) and func.id == "len"
                and node.args):
            attr = _self_attr_of(node.args[0])
            if attr is not None:
                self.bounded.add(attr)
        self.generic_visit(node)

    def visit_Delete(self, node: ast.Delete):
        for target in node.targets:
            attr = _self_attr_of(target)
            if attr is not None:
                self.bounded.add(attr)
        self.generic_visit(node)

    def visit_Assign(self, node: ast.Assign):
        if not self._in_init:
            for target in node.targets:
                # wholesale replacement (swap-and-drain) — but NOT a
                # keyed write, which is growth, not bounding
                if (isinstance(target, ast.Attribute)
                        and isinstance(target.value, ast.Name)
                        and target.value.id == "self"):
                    self.bounded.add(target.attr)
                if isinstance(target, ast.Tuple):
                    for elt in target.elts:
                        if (isinstance(elt, ast.Attribute)
                                and isinstance(elt.value, ast.Name)
                                and elt.value.id == "self"):
                            self.bounded.add(elt.attr)
        self.generic_visit(node)


def _function_params(fn) -> Set[str]:
    args = fn.args
    names = [a.arg for a in args.args + args.kwonlyargs
             + args.posonlyargs]
    if args.vararg:
        names.append(args.vararg.arg)
    return set(names)


def _is_sender_valued(call: ast.Call, params: Set[str]) -> bool:
    """Growth adding just the sender identity (bounded by peer
    cardinality) — ``self.X.add(sender_id)``."""
    if len(call.args) != 1:
        return False
    arg = call.args[0]
    return (isinstance(arg, ast.Name)
            and arg.id in (params & _SENDER_PARAMS))


@register
class BoundedIngressChecker(Checker):
    name = "bounded-ingress"
    # obs/audit_stream.py and obs/watch.py joined with the live health
    # plane: both consume unbounded external input (journal bytes,
    # scraped endpoints) in long-running processes, so their state must
    # show the same bounding evidence as the network ingress paths;
    # obs/perf.py samples forever in-process (its ring deques must stay
    # bounded the same way)
    scope = ("hbbft_tpu/net/", "hbbft_tpu/protocols/",
             "hbbft_tpu/obs/audit_stream.py", "hbbft_tpu/obs/watch.py",
             "hbbft_tpu/obs/perf.py")
    rules = {
        "bounded-ingress":
            "a self.* collection grown from network-derived input in "
            "net/ or protocols/ shows no bounding evidence (no len() "
            "cap check, no removal, no wholesale replacement) — add a "
            "cap with a counted eviction or a justified suppression",
    }

    def check_module(self, mod: ModuleSource) -> Iterable[Finding]:
        tree = mod.tree
        if tree is None:
            return []
        out: List[Finding] = []
        for cls in ast.walk(tree):
            if not isinstance(cls, ast.ClassDef):
                continue
            evidence = _ClassEvidence()
            evidence.visit(cls)
            out.extend(self._check_class(mod, cls, evidence.bounded))
        return out

    def _check_class(self, mod: ModuleSource, cls: ast.ClassDef,
                     bounded: Set[str]) -> Iterable[Finding]:
        out: List[Finding] = []
        for fn in ast.walk(cls):
            if not isinstance(fn, (ast.FunctionDef,
                                   ast.AsyncFunctionDef)):
                continue
            params = _function_params(fn)
            net_params = params & _NET_PARAMS
            if not net_params:
                continue
            for call in ast.walk(fn):
                if not isinstance(call, ast.Call):
                    continue
                func = call.func
                if not (isinstance(func, ast.Attribute)
                        and func.attr in _GROW_METHODS):
                    continue
                attr = _self_attr_of(func.value)
                if attr is None or attr in bounded:
                    continue
                if _is_sender_valued(call, net_params):
                    continue
                out.append(self.finding(
                    mod, "bounded-ingress", call,
                    f"self.{attr}.{func.attr}(...) grows from network "
                    f"input ({fn.name}({', '.join(sorted(net_params))}"
                    f")) with no bounding evidence in "
                    f"{cls.name}: cap it with a counted eviction, or "
                    f"suppress with a justification",
                ))
        return out

"""Checker: asyncio hazards in the net/obs layers.

The event loop IS the Step pump: anything that silently drops a coroutine,
loses a task, or blocks the loop stalls consensus for the whole node.

- ``async-unawaited-coroutine`` — a call to an ``async def`` defined in the
  same module, used as a bare expression statement: the coroutine object is
  created and garbage-collected without ever running ("coroutine was never
  awaited" at best, a silently missing side effect at worst).
- ``async-fire-and-forget-task`` — ``asyncio.create_task`` /
  ``ensure_future`` whose result is discarded.  The event loop keeps only
  a weak reference to tasks: a fire-and-forget task can be
  garbage-collected mid-flight and its exceptions are never observed.
  Retain the handle (attribute, list, set) or await it.
- ``async-blocking-call`` — a blocking call inside ``async def``:
  ``time.sleep``, synchronous socket/subprocess/urllib calls, ``open()``,
  and the BLS pairing entry points (``pairing``/``pairing_check`` — a
  multi-ms pure-Python computation).  Each blocks every peer's pump, not
  just the caller's.
- ``async-lock-across-await`` — an ``async with <lock>`` (or ``with
  <lock>``) whose body awaits network I/O (``drain``, ``read*``,
  ``open_connection``, ``wait_for`` around those …).  A peer that stops
  reading wedges the awaiting task *while it holds the lock*, starving
  every other task that needs it — the deadlock shape the transport's
  heartbeat logic documents.  ``net/statesync.py`` is in scope like the
  rest of ``net/``: a snapshot transfer awaiting a stalled donor must
  never hold a lock (the client is written lock-free — sequential
  request/response with per-request deadlines — and this rule keeps it
  that way).
- ``pump-inline-crypto`` — a direct ``pairing*`` / share-verify /
  share-generation call in the scheduler module (``net/scheduler.py``).
  The pump's contract is that ALL threshold crypto flows through the
  protocols' deferred-resolution surface and ``crypto/batch.py``'s
  batched executor path; a direct call in the scheduler bypasses the
  cross-epoch batching (and, on the event-loop side, blocks the loop).
"""

from __future__ import annotations

import ast
from typing import Iterable, List, Optional, Set

from hbbft_tpu.lint.core import Checker, Finding, ModuleSource, register

_TASK_SPAWNERS = {"create_task", "ensure_future"}

#: (module-ish prefix, attr) pairs and bare names that block the loop
_BLOCKING_ATTRS = {
    ("time", "sleep"),
    ("socket", "create_connection"), ("socket", "socket"),
    ("subprocess", "run"), ("subprocess", "call"),
    ("subprocess", "check_call"), ("subprocess", "check_output"),
    ("request", "urlopen"), ("urllib", "urlopen"),
    ("bls", "pairing"), ("bls", "pairing_check"),
}
_BLOCKING_NAMES = {"open", "pairing", "pairing_check", "http_get"}

_NET_IO_ATTRS = {
    "drain", "read", "readline", "readuntil", "readexactly",
    "open_connection", "sendall", "recv", "connect", "accept",
    "wait_closed", "start_server",
}

#: call names that ARE threshold crypto — banned outright in the
#: scheduler module (see the ``pump-inline-crypto`` rule)
_PUMP_CRYPTO_NAMES = {
    "pairing", "pairing_check", "miller_loop",
    "verify", "verify_signature", "verify_signature_share",
    "verify_decryption_share", "batch_verify_sig_shares",
    "batch_verify_dec_shares", "verify_dec_share_sets",
    "verify_ciphertext_batch", "decrypt_share", "decrypt",
    "combine_signatures", "sign", "encrypt",
}

def _is_pump_module(path: str) -> bool:
    """The pump-inline-crypto rule's scope: scheduler modules of the net
    layer (``hbbft_tpu/net/scheduler.py`` and any sibling scheduler)."""
    base = path.rsplit("/", 1)[-1]
    return "/net/" in f"/{path}" and "scheduler" in base


def _lock_like(expr: ast.AST) -> Optional[str]:
    """Name of a lock-ish context expression (``*lock*``/``*sem*``)."""
    name = None
    if isinstance(expr, ast.Name):
        name = expr.id
    elif isinstance(expr, ast.Attribute):
        name = expr.attr
    elif isinstance(expr, ast.Call):
        return _lock_like(expr.func)  # e.g. self._lock() factories
    if name is not None:
        low = name.lower()
        if "lock" in low or "semaphore" in low or low.endswith("sem"):
            return name
    return None


def _awaited_net_io(await_node: ast.Await) -> Optional[str]:
    """The network-I/O call name under an ``await``, unwrapping
    ``asyncio.wait_for(...)``; None if the await is not network I/O."""
    value = await_node.value
    if isinstance(value, ast.Call):
        func = value.func
        if isinstance(func, ast.Attribute) and func.attr == "wait_for":
            if value.args and isinstance(value.args[0], ast.Call):
                value = value.args[0]
                func = value.func
        if isinstance(func, ast.Attribute) and func.attr in _NET_IO_ATTRS:
            return func.attr
        if isinstance(func, ast.Name) and func.id in _NET_IO_ATTRS:
            return func.id
    return None


def _collect_async_defs(tree: ast.AST) -> Set[str]:
    return {
        n.name for n in ast.walk(tree)
        if isinstance(n, ast.AsyncFunctionDef)
    }


@register
class AsyncioHazardChecker(Checker):
    name = "asyncio-hazard"
    scope = ("hbbft_tpu/net/", "hbbft_tpu/obs/")
    rules = {
        "async-unawaited-coroutine":
            "coroutine call used as a bare statement — never awaited, "
            "never runs",
        "async-fire-and-forget-task":
            "create_task/ensure_future result discarded — the loop holds "
            "only a weak ref, the GC can cancel the task mid-flight",
        "async-blocking-call":
            "blocking call (time.sleep, sync I/O, subprocess, BLS "
            "pairing) inside async def — stalls the whole Step pump",
        "async-lock-across-await":
            "lock held across an await of network I/O — a stalled peer "
            "wedges every task contending for the lock",
        "pump-inline-crypto":
            "direct pairing/share-crypto call in the scheduler module — "
            "threshold crypto must flow through the protocols' deferred "
            "resolution and crypto/batch.py's batched executor path",
    }

    def check_module(self, mod: ModuleSource) -> Iterable[Finding]:
        tree = mod.tree
        if tree is None:
            return []
        out: List[Finding] = []
        async_defs = _collect_async_defs(tree)
        pump_module = _is_pump_module(mod.path)
        for node in ast.walk(tree):
            if isinstance(node, ast.Expr) and isinstance(
                node.value, ast.Call
            ):
                self._check_bare_call(mod, node.value, async_defs, out)
            if isinstance(node, ast.AsyncFunctionDef):
                self._check_async_body(mod, node, out)
            if isinstance(node, (ast.AsyncWith, ast.With)):
                self._check_lock_span(mod, node, out)
            if pump_module and isinstance(node, ast.Call):
                self._check_pump_crypto(mod, node, out)
        return out

    # -- direct crypto calls in the scheduler -------------------------------

    def _check_pump_crypto(self, mod, call: ast.Call, out) -> None:
        func = call.func
        name = None
        if isinstance(func, ast.Name):
            name = func.id
        elif isinstance(func, ast.Attribute):
            name = func.attr
        if name in _PUMP_CRYPTO_NAMES:
            out.append(self.finding(
                mod, "pump-inline-crypto", call,
                f"{name}() called directly in the scheduler: route it "
                f"through the protocols' resolve_deferred surface / "
                f"crypto.batch so it joins the per-iteration batched "
                f"call (and never runs on the event loop)",
            ))

    # -- bare expression statements ----------------------------------------

    def _check_bare_call(self, mod, call, async_defs, out) -> None:
        func = call.func
        name = None
        if isinstance(func, ast.Name):
            name = func.id
        elif isinstance(func, ast.Attribute):
            name = func.attr
            # only `self.<m>()` / `asyncio.<m>()` resolve against this
            # module's async defs: `self._writer.close()` must not match
            # our own `async def close`
            if name not in _TASK_SPAWNERS and not (
                isinstance(func.value, ast.Name)
                and func.value.id in ("self", "asyncio")
            ):
                return
        if name in _TASK_SPAWNERS:
            out.append(self.finding(
                mod, "async-fire-and-forget-task", call,
                f"{name}(...) result discarded: retain the Task (the "
                f"event loop keeps only a weak reference) or await it",
            ))
        elif name in async_defs:
            out.append(self.finding(
                mod, "async-unawaited-coroutine", call,
                f"{name}(...) is a coroutine call used as a statement: "
                f"it never runs without an await (or a retained task)",
            ))

    # -- blocking calls inside async defs ----------------------------------

    def _check_async_body(self, mod, fn: ast.AsyncFunctionDef, out) -> None:
        for node in self._walk_same_function(fn):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            hit = None
            if isinstance(func, ast.Attribute):
                base = func.value
                base_name = (
                    base.id if isinstance(base, ast.Name)
                    else base.attr if isinstance(base, ast.Attribute)
                    else None
                )
                if (base_name, func.attr) in _BLOCKING_ATTRS:
                    hit = f"{base_name}.{func.attr}"
            elif isinstance(func, ast.Name) and func.id in _BLOCKING_NAMES:
                hit = func.id
            if hit is not None:
                out.append(self.finding(
                    mod, "async-blocking-call", node,
                    f"blocking call {hit}() inside async def "
                    f"{fn.name}: it stalls the event loop (use the "
                    f"async equivalent or an executor)",
                ))

    @staticmethod
    def _walk_same_function(fn):
        """All nodes of ``fn`` without descending into nested defs."""
        stack = list(ast.iter_child_nodes(fn))
        while stack:
            node = stack.pop()
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            yield node
            stack.extend(ast.iter_child_nodes(node))

    # -- locks held across network awaits ----------------------------------

    def _check_lock_span(self, mod, node, out) -> None:
        lock_name = None
        for item in node.items:
            lock_name = _lock_like(item.context_expr) or lock_name
        if lock_name is None:
            return
        for sub in ast.walk(node):
            if isinstance(sub, ast.Await):
                io_name = _awaited_net_io(sub)
                if io_name is not None:
                    out.append(self.finding(
                        mod, "async-lock-across-await", node,
                        f"{lock_name} held across await {io_name}(): a "
                        f"peer that stops reading parks this task inside "
                        f"the critical section and starves other "
                        f"contenders",
                    ))
                    return

"""CLI: ``python -m hbbft_tpu.lint``.

Exit status: 0 = clean (baselined findings do not fail the run),
1 = actionable findings, 2 = usage/internal error.
"""

from __future__ import annotations

import argparse
import sys
from typing import List, Optional

from hbbft_tpu.lint.core import (
    DEFAULT_BASELINE,
    DEFAULT_PATHS,
    render_baseline,
    rule_table,
    run_lint,
)
from hbbft_tpu.lint.reporters import render_json, render_text


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m hbbft_tpu.lint",
        description="hblint: determinism / asyncio-hazard / "
                    "wire-completeness / fault-accounting / "
                    "metric-convention static analysis",
    )
    ap.add_argument("paths", nargs="*",
                    help=f"files/dirs to scan, relative to the repo root "
                         f"(default: {' '.join(DEFAULT_PATHS)})")
    ap.add_argument("--root", default=None,
                    help="repo root (default: auto-detected from the "
                         "package location)")
    ap.add_argument("--json", action="store_true",
                    help="emit machine-readable JSON instead of text")
    ap.add_argument("--baseline", default=DEFAULT_BASELINE,
                    help="baseline file of grandfathered findings")
    ap.add_argument("--no-baseline", action="store_true",
                    help="ignore the baseline (report everything)")
    ap.add_argument("--write-baseline", action="store_true",
                    help="write all current findings to the baseline "
                         "file and exit 0 (then edit the justifications)")
    ap.add_argument("--changed-only", metavar="GITREF", default=None,
                    help="per-file checks only on files changed vs this "
                         "git ref (project-wide checks always run)")
    ap.add_argument("--show-baselined", action="store_true",
                    help="also list baselined findings in text output")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the rule table and exit")
    args = ap.parse_args(argv)

    if args.list_rules:
        for rule, (checker, desc) in sorted(rule_table().items()):
            print(f"{rule:28s} [{checker}] {desc}")
        return 0

    if args.write_baseline and (args.paths or args.changed_only):
        # a restricted scan sees only a subset of findings; writing it
        # wholesale would silently delete every other grandfathered entry
        print("hblint: error: --write-baseline requires a full scan "
              "(no path arguments, no --changed-only)", file=sys.stderr)
        return 2

    baseline = None if args.no_baseline else args.baseline
    try:
        result = run_lint(
            root=args.root,
            paths=args.paths or None,
            baseline_path=None if args.write_baseline else baseline,
            changed_only=args.changed_only,
        )
    except RuntimeError as exc:
        print(f"hblint: error: {exc}", file=sys.stderr)
        return 2

    if args.write_baseline:
        with open(args.baseline, "w", encoding="utf-8") as fh:
            fh.write(render_baseline(result.findings))
        print(f"hblint: wrote {len(result.findings)} entries to "
              f"{args.baseline} — now edit the justifications")
        return 0

    if args.json:
        print(render_json(result))
    else:
        print(render_text(result, verbose_baseline=args.show_baselined))
    return 0 if result.clean else 1


if __name__ == "__main__":
    sys.exit(main())

"""Static per-run membership and key material.

Mirrors the reference's ``src/netinfo.rs :: NetworkInfo``: one immutable
object, shared by every protocol instance of a node, holding the sorted
validator set, the BFT fault bound f = ⌊(n−1)/3⌋, the threshold public key
set, per-node threshold public key shares, this node's secret key share (only
validators have one), plus plain per-node keypairs used for message-level
signatures (DynamicHoneyBadger votes, SyncKeyGen row encryption).
"""

from __future__ import annotations

from typing import Any, Dict, Hashable, List, Mapping, Optional, Sequence

NodeId = Hashable


class NetworkInfo:
    """Reference: ``src/netinfo.rs :: NetworkInfo``."""

    def __init__(
        self,
        our_id: NodeId,
        public_keys: Mapping[NodeId, Any],
        public_key_set: Any,
        secret_key_share: Optional[Any] = None,
        secret_key: Optional[Any] = None,
    ):
        self._our_id = our_id
        # Deterministic global node ordering: sort by repr-stable key.  The
        # reference uses BTreeMap<N, _> ordering; we sort the ids themselves.
        self._all_ids: List[NodeId] = sorted(public_keys.keys())
        self._public_keys: Dict[NodeId, Any] = dict(public_keys)
        self._public_key_set = public_key_set
        self._secret_key_share = secret_key_share
        self._secret_key = secret_key
        self._index: Dict[NodeId, int] = {n: i for i, n in enumerate(self._all_ids)}
        n = len(self._all_ids)
        self._num_faulty = (n - 1) // 3 if n > 0 else 0

    # -- membership --------------------------------------------------------
    def our_id(self) -> NodeId:
        return self._our_id

    def all_ids(self) -> List[NodeId]:
        return self._all_ids

    def num_nodes(self) -> int:
        return len(self._all_ids)

    def num_faulty(self) -> int:
        """f = ⌊(n−1)/3⌋ — the maximum tolerated Byzantine count."""
        return self._num_faulty

    def num_correct(self) -> int:
        return self.num_nodes() - self.num_faulty()

    def node_index(self, node_id: NodeId) -> Optional[int]:
        return self._index.get(node_id)

    def is_node_validator(self, node_id: NodeId) -> bool:
        return node_id in self._index

    def is_validator(self) -> bool:
        return self._our_id in self._index and self._secret_key_share is not None

    # -- key material ------------------------------------------------------
    def public_key_set(self):
        return self._public_key_set

    def public_key_share(self, node_id: NodeId):
        idx = self.node_index(node_id)
        if idx is None:
            return None
        return self._public_key_set.public_key_share(idx)

    def secret_key_share(self):
        return self._secret_key_share

    def secret_key(self):
        return self._secret_key

    def public_key(self, node_id: NodeId):
        return self._public_keys.get(node_id)

    def public_key_map(self) -> Dict[NodeId, Any]:
        return dict(self._public_keys)

    # -- test helper -------------------------------------------------------
    @staticmethod
    def generate_map(ids: Sequence[NodeId], rng) -> Dict[NodeId, "NetworkInfo"]:
        """Generate a full validator network's key material for tests.

        Reference analog: ``NetworkInfo::generate_map`` (test utility).
        Returns one NetworkInfo per id, all sharing a fresh
        ``SecretKeySet.random(f, rng)`` with threshold f = ⌊(n−1)/3⌋.
        """
        from hbbft_tpu.crypto import tc

        ids = sorted(ids)
        n = len(ids)
        f = (n - 1) // 3
        sk_set = tc.SecretKeySet.random(f, rng)
        pk_set = sk_set.public_keys()
        sec_keys = {nid: tc.SecretKey.random(rng) for nid in ids}
        pub_keys = {nid: sk.public_key() for nid, sk in sec_keys.items()}
        return {
            nid: NetworkInfo(
                our_id=nid,
                public_keys=pub_keys,
                public_key_set=pk_set,
                secret_key_share=sk_set.secret_key_share(i),
                secret_key=sec_keys[nid],
            )
            for i, nid in enumerate(ids)
        }

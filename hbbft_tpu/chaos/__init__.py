"""Chaos engineering for the hbbft-tpu stack (ROADMAP Open item 4).

Two pieces:

- :mod:`hbbft_tpu.chaos.link` — the pluggable link-shaping layer: seeded,
  per-directed-edge :class:`LinkPolicy` decisions (latency/jitter, loss,
  duplication, reorder, bandwidth caps, timed partitions/heals) behind ONE
  shaping hook (:class:`LinkShaper`) consumed by *both* the deterministic
  simulator (``sim/virtual_net.py``) and the real socket transport
  (``net/transport.py``);
- :mod:`hbbft_tpu.chaos.campaign` — the campaign runner: hundreds of
  seeded (scenario × topology × adversary) cells per invocation, every
  cell's flight journals audited by :mod:`hbbft_tpu.obs.audit`, every
  non-clean verdict auto-triaged to its first divergent epoch with the
  seed + scenario spec needed to replay it deterministically.

This package sits inside hblint's ``determinism`` scope: every shaping
decision must come from the seeded RNG (no wall-clock reads, no global
randomness) — the same run replays byte-identically.
"""

from hbbft_tpu.chaos.link import (
    LinkPolicy,
    LinkShaper,
    NetShape,
    PRESETS,
    ShapedLink,
    preset_shape,
)

__all__ = [
    "LinkPolicy", "LinkShaper", "NetShape", "PRESETS", "ShapedLink",
    "preset_shape",
]

"""Chaos campaign runner: seeded scenario sweeps judged by the auditor.

``python -m hbbft_tpu.chaos.campaign`` runs a grid of seeded
(link-shaping policy × topology × adversary) **cells**.  Each simulator
cell is one deterministic VirtualNet run of the full QHB stack with

- a :mod:`hbbft_tpu.chaos.link` preset (scaled to the virtual clock)
  shaping every directed edge,
- one adversary from the zoo (:mod:`hbbft_tpu.sim.adversary`),
- a flight recorder per node (logical clock → byte-deterministic
  journals),

after which the cell's journal set is fed to the forensic auditor
(:mod:`hbbft_tpu.obs.audit`).  Churn cells run a real in-process socket
cluster (:class:`~hbbft_tpu.net.cluster.LocalCluster`) through a
kill/restart storm instead, and audit the incident's journals the same
way.

Every non-clean verdict is **auto-triaged**: the report names the faulty
node(s), the first divergent epoch, and carries the exact
:class:`CellSpec` (seed included) needed to replay the cell — a
simulator cell replays **byte-identically** (``--replay`` checks this by
running the spec twice and comparing merged audit timelines).

Output is ONE JSON report (verdict histogram, liveness/latency per cell,
shaping counters, triage list) suitable for the ``BENCH_CHAOS_rNN.json``
trajectory and ``bench.py --compare`` gating (``unit: clean_fraction``).

This module lives in hblint's ``determinism`` scope: no wall-clock
reads, no unseeded randomness — campaign runs are replayable artifacts,
not weather reports.
"""

from __future__ import annotations

import argparse
import hashlib
import json
import os
import random
import sys
from dataclasses import asdict, dataclass
from typing import Any, Dict, List, Optional, Sequence, Tuple

from hbbft_tpu.chaos.link import PRESETS, preset_shape
from hbbft_tpu.obs import critpath as _critpath
from hbbft_tpu.obs.audit import AuditResult, run_audit
from hbbft_tpu.obs.audit_stream import (
    IncrementalAuditor,
    JournalTailer,
    extract_incidents,
)
from hbbft_tpu.protocols.dynamic_honey_badger import DynamicHoneyBadger
from hbbft_tpu.protocols.honey_badger import EncryptionSchedule
from hbbft_tpu.protocols.queueing_honey_badger import (
    QhbBatch,
    QueueingHoneyBadger,
    TxInput,
)
from hbbft_tpu.sim import NetBuilder
from hbbft_tpu.sim.adversary import (
    CensorshipAdversary,
    CrashAtEpochAdversary,
    EclipseAdversary,
    EquivocatingAdversary,
    FloodAdversary,
    FutureEpochSpamAdversary,
    GarbageStreamAdversary,
    IdentitySpoofAdversary,
    MitmDelayAdversary,
    NullAdversary,
    ReorderingAdversary,
    SpoofReplayAdversary,
    VoteStormAdversary,
)
from hbbft_tpu.sim.trace import CostModel

#: keygen seed shared by every cell — BLS key material is NOT the chaos
#: variable, and regenerating it per cell would dominate the sweep
KEYGEN_SEED = 13

_INFOS: Dict[int, Dict[int, Any]] = {}


def _infos_for(n: int):
    if n not in _INFOS:
        from hbbft_tpu.netinfo import NetworkInfo

        _INFOS[n] = NetworkInfo.generate_map(
            list(range(n)), random.Random(KEYGEN_SEED))
    return _INFOS[n]


# ===========================================================================
# Cell specification — the replay artifact
# ===========================================================================


@dataclass(frozen=True)
class CellSpec:
    """Everything needed to replay one campaign cell deterministically."""

    shape: str = "none"          # chaos.link preset name
    adversary: str = "null"      # zoo name (see ADVERSARIES)
    n: int = 4
    batch_size: int = 4
    txs: int = 8
    seed: int = 0                # drives protocol RNGs, shaping, adversary
    time_scale: float = 1e-3     # preset times × this (virtual seconds)
    crank_limit: int = 40_000
    kind: str = "sim"            # "sim" | "churn" | "socket"
    restarts: int = 2            # churn cells: kill/restart count
    pipeline_depth: int = 1      # socket cells: epochs kept in flight
    vid: bool = False            # socket cells: order VID commitments,
    #                              retrieve payloads lazily (net/retrieve)

    @property
    def name(self) -> str:
        return (f"{self.kind}--{self.shape}--{self.adversary}"
                f"--n{self.n}--s{self.seed}"
                + ("--vid" if self.vid else ""))

    @property
    def faulty(self) -> Tuple[int, ...]:
        """Byzantine node set implied by the adversary (the equivocator
        needs a faulty sender for tamper() to apply to; the flood /
        window-spam adversaries act under the last node's identity).
        Spoof adversaries return (): their victim genuinely sent the
        replayed traffic once and must NOT be pre-blamed — mis-blaming
        the impersonated node is exactly the failure those cells
        exist to catch."""
        if self.adversary in ("equivocate", "flood", "future-spam"):
            return (self.n - 1,)
        return ()

    def as_dict(self) -> Dict[str, Any]:
        return asdict(self)

    @classmethod
    def from_dict(cls, doc: Dict[str, Any]) -> "CellSpec":
        return cls(**{k: doc[k] for k in cls.__dataclass_fields__
                      if k in doc})


#: the adversary zoo, by campaign name.  "flood" and "future-spam" are
#: the overload-defense drills (valid-frame spam amplification and
#: window-edge protocol spam); "spoof-replay" is the identity-theft
#: analog the authenticated transport leaves possible in-sim (replayed
#: duplicates of an HONEST victim's own traffic — the victim must not
#: be blamed).  Their socket siblings ("garbage-stream" / "flood" /
#: the "spoof-*" modes at kind "socket") drive a REAL cluster via
#: raw-socket injectors instead of the simulator hooks.
ADVERSARIES: Tuple[str, ...] = (
    "null", "reorder", "mitm-delay", "censor-ready", "eclipse", "crash",
    "equivocate", "vote-storm", "flood", "future-spam", "spoof-replay",
)

#: per-preset sim time scale: presets are written in real seconds, cells
#: run on the cost model's much faster virtual clock — each preset is
#: scaled so its faults actually bite within a short run (wan latency
#: comparable to an epoch; the partition window opening mid-run)
SIM_SCALES: Dict[str, float] = {
    "none": 1e-3,
    "wan-100ms": 5e-3,
    "lossy-1pct": 1e-3,
    "dup-reorder": 1e-3,
    "partition-10s": 5e-4,
    "bandwidth-64k": 1e-3,
}


def make_adversary(spec: CellSpec):
    """Build the cell's adversary, parameterized from the scenario seed
    (every budget/trigger derives from ``spec.seed`` so cells sweep the
    adversary's strength, not just its schedule)."""
    name, seed, n = spec.adversary, spec.seed, spec.n
    if name == "null":
        return NullAdversary()
    if name == "reorder":
        return ReorderingAdversary(seed=seed)
    if name == "mitm-delay":
        # seeded delay budget (satellite: max_delay=None draws from seed)
        return MitmDelayAdversary(target=0, max_delay=None, seed=seed)
    if name == "censor-ready":
        return CensorshipAdversary(msg_types=("ReadyMsg",), dests=(1,),
                                   seed=seed)
    if name == "eclipse":
        return EclipseAdversary(victim=n - 1,
                                heal_crank=1500 + (seed % 4) * 700)
    if name == "crash":
        return CrashAtEpochAdversary(victim=n - 1,
                                     after_batches=1 + seed % 2)
    if name == "equivocate":
        return EquivocatingAdversary()
    if name == "vote-storm":
        # membership-vote storms: coordinated remove/re-add waves drive
        # REAL DKG rotations mid-run (mid-partition under the
        # partition-10s preset); split waves stall without a winner
        return VoteStormAdversary(seed=seed)
    if name == "flood":
        # max-rate valid-frame spam amplification from the last node
        return FloodAdversary(flooder=n - 1, seed=seed)
    if name == "future-spam":
        # window-edge protocol spam: the receivers' future-epoch
        # budgets and buffer caps must absorb it, counted
        return FutureEpochSpamAdversary(spammer=n - 1, seed=seed)
    if name == "spoof-replay":
        # replayed duplicates of node 0's own genuine traffic: the
        # strongest spoof the authenticated transport leaves possible.
        # Duplicates are protocol no-ops; node 0 stays HONEST (not in
        # spec.faulty) and the cell must audit clean
        return SpoofReplayAdversary(victim=0, seed=seed)
    raise ValueError(f"unknown adversary {name!r} "
                     f"(known: {', '.join(ADVERSARIES)})")


# ===========================================================================
# Simulator cells
# ===========================================================================


def _qhb_stack(infos, nid, spec: CellSpec):
    return QueueingHoneyBadger(
        DynamicHoneyBadger(
            infos[nid], infos[nid].secret_key(),
            rng=random.Random(spec.seed * 1_000 + 100 + nid),
            encryption_schedule=EncryptionSchedule.never(),
        ),
        batch_size=spec.batch_size,
        rng=random.Random(spec.seed * 1_000 + 500 + nid),
    )


def _timeline_digest(res: AuditResult) -> str:
    """Digest of the merged audit timeline — the byte-identity witness
    two replays of one spec must share."""
    h = hashlib.sha3_256()
    for e in res.events:
        h.update(e.line.encode())
        h.update(b"\n")
    return h.hexdigest()[:24]


def _sim_guard_doc(net, correct) -> Dict[str, Any]:
    """Per-cell overload-defense witness for simulator cells: every
    budgeted buffer's RUN-LONG peak depth vs its cap across the correct
    nodes, plus the counted drops/evictions.  Peaks/evictions of epochs
    that CLOSED during the run are preserved (``HoneyBadger`` folds
    them into ``closed_guard`` when it deletes the epoch state) and
    merged with the live instances' high-water marks, so the witness
    covers the whole run, not just whatever was still open at the end.
    The flood cells assert the peaks stay ≤ the caps (+1: peaks record
    the pre-eviction length, falsifiably) while the cluster commits."""
    aba_peak = aba_cap = 0
    aba_evictions = 0
    hb_drops = 0
    subset_drops = 0
    era_drops = 0
    for nid in correct:
        algo = net.nodes[nid].algorithm
        algo = getattr(algo, "algo", algo)            # unwrap SenderQueue
        dhb = getattr(algo, "dhb", algo)
        hb = getattr(dhb, "hb", dhb)
        era_drops += sum(getattr(dhb, "future_era_drops", {}).values())
        hb_drops += sum(getattr(hb, "future_drops", {}).values())
        closed = getattr(hb, "closed_guard", {})
        aba_peak = max(aba_peak, closed.get("aba_future_peak", 0))
        aba_evictions += closed.get("aba_future_evictions", 0)
        subset_drops += closed.get("subset_flood_drops", 0)
        for state in getattr(hb, "epochs", {}).values():
            subset_drops += sum(state.subset.flood_drops.values())
            for prop in state.subset.proposals.values():
                ba = prop.agreement
                aba_cap = max(aba_cap, ba.future_cap_per_sender)
                aba_peak = max(aba_peak, ba.future_peak)
                aba_evictions += sum(ba.future_evictions.values())
    if aba_peak and not aba_cap:
        # every live BA closed before the read: report the default cap
        # so the folded peak still has its bound to compare against
        from hbbft_tpu.protocols.binary_agreement import (
            DEFAULT_MAX_FUTURE_EPOCHS, FUTURE_CAP_PER_EPOCH,
        )

        aba_cap = FUTURE_CAP_PER_EPOCH * (DEFAULT_MAX_FUTURE_EPOCHS + 1)
    return {
        "aba_future_peak": aba_peak,
        "aba_future_cap": aba_cap,
        "aba_future_evictions": aba_evictions,
        "hb_future_drops": hb_drops,
        "subset_flood_drops": subset_drops,
        "future_era_drops": era_drops,
    }


def _cell_critpath(cell_dir: str) -> Optional[Dict[str, Any]]:
    """Per-cell latency attribution: the critical-path summary over the
    cell's journals (obs.critpath) — a shaped link (e.g. ``wan-100ms``)
    must surface as ``wire`` time in the decomposition, not as a
    mysteriously slow protocol phase."""
    dirs = _critpath.find_journal_dirs(cell_dir)
    if not dirs:
        return None
    rep = _critpath.build_report(sorted(dirs), waterfalls=0)
    return {
        "reconstructed_fraction": rep["reconstructed_fraction"],
        "mean_components": rep["mean_components"],
        "p50": rep.get("p50"),
        "dominant": (rep.get("p50") or {}).get("dominant"),
        "unmatched": rep["unmatched"],
    }


#: crank period between streaming-audit polls in simulator cells: the
#: online detector's tick.  The recorder flushes every append, so each
#: poll sees all evidence journaled up to that crank.  Fine-grained on
#: purpose: a quiet unshaped cell can go quiescent inside a few hundred
#: cranks, and the online-detection claim needs polls DURING the run.
#: Cheap by construction — the tailer read is incremental, and the
#: result() derivation only runs while a configured-faulty cell is
#: still undetected (clean cells never derive mid-run).
WATCH_POLL_CRANKS = 200


def _watch_online(tailer: JournalTailer, faulty: frozenset,
                  watch: Dict[str, Any], virtual_s: float,
                  cranks: int) -> None:
    """One online-detection check: did the streaming auditor just raise
    an incident naming a configured-faulty node?  First hit stamps the
    detection time (the cell's virtual clock — the online-detection
    latency the BENCH_OBS family records)."""
    if watch["detected_online"] or not faulty:
        return
    for fi in extract_incidents(tailer.result()):
        if fi["subject"] in faulty:
            watch["detected_online"] = True
            watch["detect_virtual_s"] = round(virtual_s, 6)
            watch["detect_cranks"] = cranks
            watch["first_kind"] = fi["kind"]
            return


def run_cell(spec: CellSpec, cell_dir: str
             ) -> Tuple[Dict[str, Any], AuditResult]:
    """One simulator cell: run, record, audit.  Returns the per-cell
    report dict and the audit result.

    The streaming auditor rides along: a :class:`JournalTailer` polls
    the cell's journals every ``WATCH_POLL_CRANKS`` cranks DURING the
    run (the watchtower's sim-cell stand-in — virtual clock, no
    sockets), so a Byzantine cell's report carries whether the fault
    was flagged online and at what virtual-time latency."""
    infos = _infos_for(spec.n)
    builder = (
        NetBuilder(list(range(spec.n)))
        .adversary(make_adversary(spec))
        .faulty(list(spec.faulty))
        .cost_model(CostModel())
        .flight(cell_dir)
    )
    if spec.shape not in ("", "none"):
        builder.shape(preset_shape(spec.shape, spec.n)
                      .scaled(spec.time_scale), seed=spec.seed)
    net = builder.using_step(lambda nid: _qhb_stack(infos, nid, spec))
    for i in range(spec.txs):
        net.send_input(i % spec.n, TxInput(b"chaos-%04d" % i))
    tailer = JournalTailer([cell_dir], IncrementalAuditor(max_events=0))
    faulty_names = frozenset(str(nid) for nid in spec.faulty)
    watch: Dict[str, Any] = {
        "detected_online": False, "detect_virtual_s": None,
        "detect_cranks": None, "first_kind": None, "incidents": [],
    }
    while net.cranks < spec.crank_limit:
        if net.crank() is None:
            break
        if net.cranks % WATCH_POLL_CRANKS == 0:
            tailer.poll()
            _watch_online(tailer, faulty_names, watch,
                          net.virtual_time, net.cranks)
    net.close_observers()
    # boundary poll: evidence flushed at close still counts, but is NOT
    # online detection (the cell had already ended)
    tailer.finalize()
    watch["incidents"] = sorted(
        {(fi["kind"], fi["subject"])
         for fi in extract_incidents(tailer.result())})
    res, _journals = run_audit([cell_dir])
    correct = [nid for nid in range(spec.n) if nid not in spec.faulty]
    batches = {
        nid: sum(1 for o in net.nodes[nid].outputs
                 if isinstance(o, QhbBatch))
        for nid in correct
    }
    min_b = min(batches.values())
    eras = max(
        (o.era for nid in correct for o in net.nodes[nid].outputs
         if isinstance(o, QhbBatch)), default=0)
    detail = {
        "cell": spec.name,
        "spec": spec.as_dict(),
        "verdict": res.verdict,
        "batches_min": min_b,
        "batches_max": max(batches.values()),
        "eras_rotated": eras,
        "stalled": min_b == 0,
        "cranks": net.cranks,
        "virtual_time_s": round(net.virtual_time, 6),
        "epoch_virtual_s": (round(net.virtual_time / min_b, 6)
                            if min_b else None),
        "shaping": net.shaper.stats() if net.shaper is not None else None,
        "adversary_filtered": net.adversary_filtered,
        "timeline_digest": _timeline_digest(res),
        "guard": _sim_guard_doc(net, correct),
        "overload_attributed_to": [
            o["peer"] for o in res.overload_incidents
        ],
        "watch": watch,
        "critical_path": _cell_critpath(cell_dir),
        "journal": cell_dir,
    }
    return detail, res


def replay_matches(spec: CellSpec, expected_digest: str,
                   scratch_dir: str) -> bool:
    """Re-run ``spec`` into ``scratch_dir``; True iff the merged audit
    timeline is byte-identical to the recorded digest."""
    detail, _res = run_cell(spec, scratch_dir)
    return detail["timeline_digest"] == expected_digest


# ===========================================================================
# Churn cells (socket cluster kill/restart storms)
# ===========================================================================


async def _churn_scenario(spec: CellSpec, cell_dir: str) -> Dict[str, Any]:
    import asyncio

    from hbbft_tpu.net.cluster import (
        ClusterConfig,
        LocalCluster,
        find_free_base_port,
    )

    cfg = ClusterConfig(
        n=spec.n, seed=spec.seed, batch_size=spec.batch_size,
        base_port=find_free_base_port(spec.n),
        heartbeat_s=0.2, dead_after_s=1.5,
        flight_dir=cell_dir,
        chaos=spec.shape if spec.shape != "none" else "",
        chaos_seed=spec.seed,
    )
    cluster = LocalCluster(cfg)
    await cluster.start()
    wave = 0
    try:
        client = await cluster.client(0)

        async def pump(count: int) -> None:
            nonlocal wave
            txs = [b"churn-%02d-%04d" % (wave, i) for i in range(count)]
            wave += 1
            for tx in txs:
                status = await client.submit(tx)
                if status != 0:
                    raise AssertionError(
                        f"churn cell tx rejected with status {status}")
            for tx in txs:
                await client.wait_committed(tx, timeout_s=60)

        await pump(spec.batch_size * 2)
        rng = random.Random(spec.seed * 7 + 3)
        victims = [rng.randrange(1, spec.n) for _ in range(spec.restarts)]
        for victim in victims:
            await cluster.restart_node(victim)
            await pump(spec.batch_size * 2)
        # every node (restarted ones included) must converge on a common
        # chain prefix — a wedged catch-up fails loudly here
        await cluster.wait_epochs(min_batches=2, timeout_s=60)
        prefix = cluster.common_digest_prefix()
        batches = [len(rt.batches) for rt in cluster.runtimes]
        return {
            "batches_min": min(batches),
            "batches_max": max(batches),
            "victims": victims,
            "common_prefix_len": len(prefix),
        }
    finally:
        await cluster.stop()


def run_churn_cell(spec: CellSpec, cell_dir: str
                   ) -> Tuple[Dict[str, Any], AuditResult]:
    import asyncio

    live = asyncio.run(asyncio.wait_for(
        _churn_scenario(spec, cell_dir), 180))
    res, _journals = run_audit([cell_dir])
    detail = {
        "cell": spec.name,
        "spec": spec.as_dict(),
        "verdict": res.verdict,
        "batches_min": live["batches_min"],
        "batches_max": live["batches_max"],
        "stalled": live["batches_min"] == 0,
        "restarts": dict(res.restarts),
        "victims": live["victims"],
        "common_prefix_len": live["common_prefix_len"],
        "journal": cell_dir,
    }
    return detail, res


# ===========================================================================
# Socket cells (WAN-shaped PIPELINED cluster liveness)
# ===========================================================================


#: socket-kind adversaries driven by raw-socket injectors (everything
#: else in the zoo is a simulator adversary).  The flood injectors
#: model a COMPROMISED validator (they hold its real key, so the
#: authenticated handshake completes and the flood drill proceeds);
#: the spoof injectors claim a correct validator's id WITHOUT its key
#: and must be refused at the challenge, zero frames in.
SOCKET_FLOOD_ADVERSARIES = ("garbage-stream", "flood")
SOCKET_SPOOF_ADVERSARIES = ("spoof-nokey", "spoof-wrongkey",
                            "spoof-hijack", "spoof-downgrade")


async def _socket_scenario(spec: CellSpec, cell_dir: str
                           ) -> Dict[str, Any]:
    """A real socket cluster at ``pipeline_depth > 1`` under a chaos
    preset at its REAL timings (wan latency in actual milliseconds):
    traffic must keep committing and the whole incident must audit
    clean — the pipelined liveness point of the chaos trajectory.

    With a flood adversary (``garbage-stream`` / ``flood``), a
    raw-socket injector holding the LAST validator's REAL key (the
    compromised-validator model) floods node 0 while the cell's client
    traffic flows: the cluster must keep committing, every budgeted
    buffer gauge must stay under its cap (sampled live throughout the
    flood), and the guard's counted throttles/disconnects must
    attribute the incident to the claimed identity in the audit.

    With a spoof adversary (``spoof-*``), the injector claims the last
    validator's identity WITHOUT its key: every hello must be refused
    at the challenge (zero accepted, counted under
    ``hbbft_guard_auth_failures_total``), the impersonated validator
    must accrue no budget debt or strikes, and the audit must name the
    ATTACKER's endpoint — never the victim — in its incidents."""
    import asyncio
    import contextlib
    import time

    from hbbft_tpu.net.cluster import (
        ClusterConfig,
        LocalCluster,
        find_free_base_port,
        node_secret_key,
    )

    flooding = spec.adversary in SOCKET_FLOOD_ADVERSARIES
    spoofing = spec.adversary in SOCKET_SPOOF_ADVERSARIES
    cfg = ClusterConfig(
        n=spec.n, seed=spec.seed, batch_size=spec.batch_size,
        base_port=find_free_base_port(spec.n),
        heartbeat_s=0.3, dead_after_s=3.0,
        flight_dir=cell_dir,
        pipeline_depth=spec.pipeline_depth,
        vid=spec.vid,
        chaos=spec.shape if spec.shape != "none" else "",
        chaos_seed=spec.seed,
        # flood cells tighten the ingress budgets so the guard engages
        # within the cell's few-second window (production defaults are
        # sized for sustained heavy traffic, not a short drill)
        ingress_bytes_per_s=256 * 1024 if flooding else 0,
        ingress_burst_bytes=128 * 1024 if flooding else 0,
        ingress_decode_strikes=64 if flooding else 0,
        ingress_throttle_strikes=8 if flooding else 0,
    )
    cluster = LocalCluster(cfg)
    await cluster.start()
    injector = None
    injector_task = None
    gauge_peaks = {"senderq_buffered": 0, "inflight_frames": 0}
    caps = {"senderq_buffered": None, "inflight_frames": None}
    stop_sampling = asyncio.Event()

    async def sample_gauges():
        """Live witness that every budgeted buffer stays ≤ its cap for
        the WHOLE run, not just at the end.  Reads only thread-safe
        surfaces: the SenderQueue's own post-cap high-water mark (an
        int maintained on the pump thread — iterating the live backlog
        lists from this loop would race their mutations) and the
        ingress budget's lock-protected peer table."""
        while not stop_sampling.is_set():
            for rt in cluster.runtimes:
                sq = rt.sq
                # the ASSERTABLE bounds: peaks are recorded pre-chop
                # (+1 transient is legal) and the in-flight cap is
                # enforced at recv-chunk granularity
                caps["senderq_buffered"] = sq.buffered_cap + 1
                caps["inflight_frames"] = (
                    rt.transport.ingress.inflight_hard_bound)
                gauge_peaks["senderq_buffered"] = max(
                    gauge_peaks["senderq_buffered"], sq.buffered_peak)
                for doc in rt.transport.ingress.peer_doc().values():
                    gauge_peaks["inflight_frames"] = max(
                        gauge_peaks["inflight_frames"], doc["inflight"])
            with contextlib.suppress(asyncio.TimeoutError):
                await asyncio.wait_for(stop_sampling.wait(), 0.1)

    sampler = None
    tower = None
    watcher = None
    try:
        if flooding:
            # the flood injector holds the claimed validator's REAL
            # key (compromised-validator model): the authenticated
            # handshake completes and the ingress-budget drill runs
            # exactly as before auth existed
            injector = GarbageStreamAdversary(
                seed=spec.seed,
                valid_frames=(spec.adversary == "flood"),
                secret_key=node_secret_key(cfg, spec.n - 1))
            injector_task = asyncio.ensure_future(injector.run(
                cluster.addrs[0], cfg.cluster_id, identity=spec.n - 1,
                duration_s=20.0))
        elif spoofing:
            mode = spec.adversary[len("spoof-"):]
            # wrongkey/downgrade sign the genuine transcript with a key
            # that is NOT the claimed validator's — deterministically
            # derived, guaranteed outside the cluster's key map
            from hbbft_tpu.crypto import tc
            wrong = (tc.SecretKey.random(
                random.Random(spec.seed * 7919 + 123))
                if mode in ("wrongkey", "downgrade") else None)
            # the downgrade probe claims a NON-current era (the cell
            # never rotates, so any era != 0 drives the stale-era /
            # mismatch verification path the grace window gates)
            injector = IdentitySpoofAdversary(
                seed=spec.seed, mode=mode, secret_key=wrong,
                claim_era=3 if mode == "downgrade" else 0)
            injector_task = asyncio.ensure_future(injector.run(
                cluster.addrs[0], cfg.cluster_id, identity=spec.n - 1,
                duration_s=8.0))
        # the live watchtower: scrape every node's obs endpoint AND tail
        # the cell's journals through the streaming auditor while the
        # scenario runs — online detection on a REAL cluster, wall-clock
        # latency measured from scenario start
        from hbbft_tpu.obs.watch import Watchtower

        tower = Watchtower(
            [cluster.metrics_addrs[nid] for nid in range(spec.n)],
            journal_roots=[cell_dir], scrape_timeout_s=1.0)
        faulty_names = frozenset(str(nid) for nid in spec.faulty)
        watch_doc: Dict[str, Any] = {
            "detected_online": False, "detect_wall_s": None,
            "first_kind": None, "incidents": [],
        }
        loop = asyncio.get_event_loop()
        # hblint: disable=det-wall-clock (live watchtower over a
        # real-time cluster: wall clock IS the measured detection
        # latency, same clock as the cell's liveness measurement)
        watch_t0 = time.monotonic()

        async def watch_loop():
            while not stop_sampling.is_set():
                # hblint: disable=det-wall-clock (same measured clock)
                now = time.monotonic()
                new = await loop.run_in_executor(None, tower.tick, now)
                for inc in new:
                    if (not watch_doc["detected_online"]
                            and inc["subject"] in faulty_names):
                        watch_doc["detected_online"] = True
                        watch_doc["detect_wall_s"] = round(
                            now - watch_t0, 3)
                        watch_doc["first_kind"] = inc["kind"]
                with contextlib.suppress(asyncio.TimeoutError):
                    await asyncio.wait_for(stop_sampling.wait(), 0.5)

        watcher = asyncio.ensure_future(watch_loop())
        sampler = asyncio.ensure_future(sample_gauges())
        client = await cluster.client(
            0, trace_dir=os.path.join(cell_dir, "client-0"))
        txs = [b"sock-%04d" % i for i in range(spec.txs)]
        # hblint: disable=det-wall-clock (socket cells run a REAL-time
        # cluster under real-second chaos presets: wall time here is the
        # measured liveness metric, not replica logic — sim cells stay
        # on the virtual clock)
        t0 = time.monotonic()
        for tx in txs:
            status = await client.submit(tx)
            if status != 0:
                raise AssertionError(
                    f"socket cell tx rejected with status {status}")
        for tx in txs:
            await client.wait_committed(tx, timeout_s=120)
        # hblint: disable=det-wall-clock (same measured-liveness read)
        wall = time.monotonic() - t0
        await cluster.wait_epochs(min_batches=1, timeout_s=60)
        if injector_task is not None:
            if flooding:
                injector.budget_frames = 0  # stop flooding, then join
            else:
                injector.budget_attempts = 0
            await asyncio.wait_for(injector_task, 30.0)
            injector_task = None
        prefix = cluster.common_digest_prefix()
        batches = [len(rt.batches) for rt in cluster.runtimes]
        stop_sampling.set()
        await sampler
        await watcher
        # boundary poll: evidence flushed at teardown still lands in the
        # incident list, but detect_wall_s only ever records ONLINE hits
        tower.tailer.finalize()
        for fi in extract_incidents(tower.tailer.result()):
            tower.incidents.append(
                {"kind": fi["kind"], "severity": fi["severity"],
                 "subject": fi["subject"]})
        watch_doc["incidents"] = sorted(
            {(i["kind"], i["subject"]) for i in tower.incidents})
        out = {
            "batches_min": min(batches),
            "batches_max": max(batches),
            "commit_wall_s": round(wall, 3),
            "common_prefix_len": len(prefix),
            "watch": watch_doc,
        }
        if flooding:
            guard_docs = [rt.transport.ingress.as_dict()
                          for rt in cluster.runtimes]
            out["guard"] = {
                "gauge_peaks": gauge_peaks,
                "gauge_caps": caps,
                "throttles": sum(g["throttles"] for g in guard_docs),
                "disconnects": sum(g["disconnects"]
                                   for g in guard_docs),
                "decode_strikes": sum(g["decode_strikes"]
                                      for g in guard_docs),
                "hello_rejects": sum(g["hello_rejects"]
                                     for g in guard_docs),
                "injector": {
                    "frames_sent": injector.frames_sent,
                    "bytes_sent": injector.bytes_sent,
                    "disconnects_observed": injector.disconnects,
                },
            }
        elif spoofing:
            victim_ingress = cluster.runtimes[0].transport.ingress
            doc = victim_ingress.as_dict()
            auth_refused = sum(doc["auth_failures"].values())
            # the spoof-proof contract, asserted live: zero spoofed
            # hellos accepted, every attempt refused AND counted, and
            # the IMPERSONATED validator's budget record stays
            # strike-free (its genuine peer connection keeps working)
            if injector.hellos_accepted:
                raise AssertionError(
                    f"spoofed hello ACCEPTED "
                    f"({injector.hellos_accepted} of "
                    f"{injector.attempts} attempts)")
            if injector.attempts and not auth_refused:
                raise AssertionError(
                    "spoof attempts were made but no auth failure "
                    "was counted")
            victim_peer = doc["peers"].get(repr(spec.n - 1), {})
            if (victim_peer.get("strikes", 0)
                    or victim_peer.get("decode_fails", 0)):
                raise AssertionError(
                    "spoof attempt charged the IMPERSONATED "
                    f"validator's budget record: {victim_peer}")
            out["guard"] = {
                "auth_failures": doc["auth_failures"],
                "auth_refused": auth_refused,
                "auth_ok": doc["auth_ok"],
                "impersonated_peer_doc": victim_peer,
                "injector": {
                    "mode": injector.mode,
                    "attempts": injector.attempts,
                    "refusals_observed": injector.refusals,
                    "hellos_accepted": injector.hellos_accepted,
                },
            }
        return out
    finally:
        stop_sampling.set()
        if sampler is not None:
            with contextlib.suppress(asyncio.CancelledError, Exception):
                await sampler
        if watcher is not None:
            watcher.cancel()
            with contextlib.suppress(asyncio.CancelledError, Exception):
                await watcher
        if tower is not None:
            tower.close()
        if injector_task is not None:
            injector_task.cancel()
            with contextlib.suppress(asyncio.CancelledError, Exception):
                await injector_task
        await cluster.stop()


def run_socket_cell(spec: CellSpec, cell_dir: str
                    ) -> Tuple[Dict[str, Any], AuditResult]:
    import asyncio

    live = asyncio.run(asyncio.wait_for(
        _socket_scenario(spec, cell_dir), 300))
    res, _journals = run_audit([cell_dir])
    detail = {
        "cell": spec.name,
        "spec": spec.as_dict(),
        "verdict": res.verdict,
        "batches_min": live["batches_min"],
        "batches_max": live["batches_max"],
        "stalled": live["batches_min"] == 0,
        "commit_wall_s": live["commit_wall_s"],
        "common_prefix_len": live["common_prefix_len"],
        "pipeline_depth": spec.pipeline_depth,
        "watch": live.get("watch"),
        "critical_path": _cell_critpath(cell_dir),
        "journal": cell_dir,
    }
    if "guard" in live:
        detail["guard"] = live["guard"]
        detail["overload_attributed_to"] = [
            o["peer"] for o in res.overload_incidents
        ]
    return detail, res


# ===========================================================================
# Grids
# ===========================================================================


def full_grid(seeds: Sequence[int] = (0, 1),
              churn_cells: int = 2) -> List[CellSpec]:
    """The default sweep: every (policy × adversary) pair on the 4-node
    topology per seed, a reduced n=7 slice, plus churn storms — ≥ 100
    cells at the default two seeds."""
    specs: List[CellSpec] = []
    for seed in seeds:
        for shape in PRESETS:
            for adv in ADVERSARIES:
                limit = 40_000
                if adv in ("equivocate", "vote-storm", "flood",
                           "future-spam", "spoof-replay"):
                    # never-draining queues (equivocator re-proposals) /
                    # multi-rotation storms / injected spam waves need
                    # the longer leash
                    limit = 60_000
                specs.append(CellSpec(
                    shape=shape, adversary=adv, n=4, seed=seed,
                    time_scale=SIM_SCALES.get(shape, 1e-3),
                    crank_limit=limit))
        # topology slice: the same stack at n=7 / f=2.  An equivocator's
        # own transactions never commit, so its queue re-proposes forever
        # and the run never drains — the crank bound IS the cell length;
        # 20k cranks is several committed epochs at n=7
        for shape in ("none", "wan-100ms", "lossy-1pct"):
            for adv in ("null", "reorder", "equivocate"):
                limit = 20_000 if adv == "equivocate" else 60_000
                specs.append(CellSpec(
                    shape=shape, adversary=adv, n=7, txs=7, seed=seed,
                    time_scale=SIM_SCALES.get(shape, 1e-3),
                    crank_limit=limit))
    for i in range(churn_cells):
        specs.append(CellSpec(kind="churn", shape="none",
                              adversary="null", n=4, seed=i))
    # WAN-shape cells against the PIPELINED socket cluster (ROADMAP item
    # 1 meets item 4): real transport, real chaos preset timings, epochs
    # kept in flight — the trajectory's second liveness point
    for shape in ("wan-100ms", "dup-reorder", "lossy-1pct"):
        specs.append(CellSpec(kind="socket", shape=shape,
                              adversary="null", n=4, seed=0,
                              pipeline_depth=2))
    # socket flood cells (overload defense, end to end): a raw-socket
    # injector claiming the last validator's identity streams garbage
    # (framing-valid, decode-invalid) or max-rate valid frames at a live
    # node — the cluster must keep committing, every buffer gauge stays
    # under its cap, and the audit attributes the incident to the
    # claimed peer from the journaled guard events
    for adv in SOCKET_FLOOD_ADVERSARIES:
        specs.append(CellSpec(kind="socket", shape="none",
                              adversary=adv, n=4, seed=0,
                              pipeline_depth=2))
    # bandwidth-asymmetry comparison cells (VID tentpole): one straggler
    # at 64 KB/s, classic RBC vs VID commitment ordering on the SAME
    # shape and seed, pipeline_depth=1 so the comparison is apples to
    # apples — classic serializes full payloads on the victim's uplink,
    # VID ships it an O(1/n) shard and must stay live AND audit clean
    # (cert-vs-retrieval corroboration included)
    for vid in (False, True):
        specs.append(CellSpec(kind="socket", shape="bandwidth-asym",
                              adversary="null", n=4, seed=0,
                              pipeline_depth=1, vid=vid))
    # socket identity-spoof cells (authenticated transport, end to
    # end): a raw-socket injector claims a correct validator's id
    # WITHOUT its key, in each refusal mode — every hello must die at
    # the challenge (zero frames into the protocol), the impersonated
    # validator's budget record stays clean, and the audit names the
    # attacker's endpoint
    for adv in SOCKET_SPOOF_ADVERSARIES:
        specs.append(CellSpec(kind="socket", shape="none",
                              adversary=adv, n=4, seed=0,
                              pipeline_depth=2))
    return specs


def smoke_grid() -> List[CellSpec]:
    """The tier-1 smoke: six fast simulator cells spanning every preset,
    all required to commit and audit clean — seconds, not minutes."""
    cells = [
        ("none", "null", 0),
        ("wan-100ms", "null", 0),
        ("lossy-1pct", "reorder", 1),
        ("dup-reorder", "null", 0),
        ("partition-10s", "null", 0),
        ("bandwidth-64k", "mitm-delay", 0),
    ]
    return [
        CellSpec(shape=shape, adversary=adv, seed=seed,
                 time_scale=SIM_SCALES.get(shape, 1e-3))
        for shape, adv, seed in cells
    ]


# ===========================================================================
# Campaign
# ===========================================================================


def _triage(spec: CellSpec, res: AuditResult) -> Dict[str, Any]:
    """Map a non-clean verdict to the facts an operator acts on: who,
    first divergent epoch, and the spec that replays it."""
    faulty: List[str] = []
    kinds: List[str] = []
    first: Optional[Tuple[int, int]] = None
    if res.equivocations:
        faulty = sorted({e["sender"] for e in res.equivocations})
        kinds = sorted({e["kind"] for e in res.equivocations})
        first = res.first_affected_epoch
    if res.first_divergence:
        d = res.first_divergence
        kinds = kinds + ["fork"]
        faulty = faulty or sorted(d.get("per_node", {}))
        first = first or (d["era"], d["epoch"])
    if res.monotonicity_violations and not faulty:
        faulty = sorted({v["node"] for v in res.monotonicity_violations})
        kinds = kinds + ["non-monotone"]
    return {
        "cell": spec.name,
        "verdict": res.verdict,
        "faulty_nodes": faulty,
        "first_divergent_epoch": list(first) if first else None,
        "kinds": kinds,
        "replay": {
            "seed": spec.seed,
            "spec": spec.as_dict(),
            "how": ("python -m hbbft_tpu.chaos.campaign --replay "
                    "'<spec json>'"),
        },
    }


def run_campaign(specs: Sequence[CellSpec], journal_root: str,
                 verify_nonclean: bool = True,
                 progress=None) -> Dict[str, Any]:
    """Run every cell, audit every journal set, build the report."""
    os.makedirs(journal_root, exist_ok=True)
    details: List[Dict[str, Any]] = []
    triage: List[Dict[str, Any]] = []
    verdicts: Dict[str, int] = {}
    frames = {"shaped": 0, "dropped": 0, "delayed": 0, "duplicated": 0,
              "partition_holds": 0}
    errors = 0
    epoch_lat: List[float] = []
    for idx, spec in enumerate(specs):
        cell_dir = os.path.join(journal_root, f"{idx:04d}--{spec.name}")
        try:
            if spec.kind == "churn":
                detail, res = run_churn_cell(spec, cell_dir)
            elif spec.kind == "socket":
                detail, res = run_socket_cell(spec, cell_dir)
            else:
                detail, res = run_cell(spec, cell_dir)
        except Exception as exc:
            errors += 1
            detail = {"cell": spec.name, "spec": spec.as_dict(),
                      "verdict": "error", "error": repr(exc)}
            res = None
        verdict = detail["verdict"]
        verdicts[verdict] = verdicts.get(verdict, 0) + 1
        shaping = detail.get("shaping")
        if shaping:
            for k in frames:
                frames[k] += shaping.get(k, 0)
        if detail.get("epoch_virtual_s") is not None:
            epoch_lat.append(detail["epoch_virtual_s"])
        if res is not None and res.verdict != "clean":
            entry = _triage(spec, res)
            if (verify_nonclean and spec.kind == "sim"
                    and not spec.faulty):
                # a non-clean verdict with NO configured Byzantine node
                # is either a real bug or nondeterminism — prove which:
                # the replay must reproduce byte-identically
                entry["reproduced"] = replay_matches(
                    spec, detail["timeline_digest"],
                    os.path.join(cell_dir, "replay-check"))
            triage.append(entry)
        details.append(detail)
        if progress is not None:
            progress(idx + 1, len(specs), detail)
    cells = len(details)
    clean = verdicts.get("clean", 0)
    epoch_lat.sort()
    report = {
        "metric": "chaos_campaign",
        "value": round(clean / cells, 4) if cells else 0.0,
        "unit": "clean_fraction",
        "cells": cells,
        "policies": sorted({s.shape for s in specs}),
        "adversaries": sorted({s.adversary for s in specs}),
        "topologies": sorted({s.n for s in specs}),
        "seeds": sorted({s.seed for s in specs}),
        "verdicts": verdicts,
        "errors": errors,
        "stalled_cells": sum(1 for d in details if d.get("stalled")),
        "frames": frames,
        "epoch_virtual_s_p50": (
            round(epoch_lat[len(epoch_lat) // 2], 6) if epoch_lat
            else None),
        "triage": triage,
        "cells_detail": details,
    }
    return report


#: incident kinds that constitute an ALARM (fault/fork classes) — the
#: info-class kinds (overload attribution, restart re-proposals) are
#: working-as-designed annotations, not alarms, and never count as a
#: false positive on a clean cell
ALARM_KINDS = frozenset({
    "fork", "self_fork", "sync_mismatch", "vid_mismatch",
    "status_mismatch", "equivocation", "monotonicity",
})


def build_obs_report(report: Dict[str, Any]) -> Dict[str, Any]:
    """Distill the campaign's per-cell watch blocks into the BENCH_OBS
    online-detection record: for every cell with a configured Byzantine
    node, was the fault flagged ONLINE (incident naming the faulty node
    before the cell ended) and at what detection latency; for every
    clean cell, did the live plane stay silent.  Sim cells measure
    latency on the virtual clock, socket cells on the wall clock —
    ``clock`` says which."""
    detection: List[Dict[str, Any]] = []
    false_alarms: List[Dict[str, Any]] = []
    fault_cells = flagged = 0
    lat: List[float] = []
    for d in report["cells_detail"]:
        w = d.get("watch")
        if not w:
            continue
        spec = CellSpec.from_dict(d.get("spec", {}))
        if spec.faulty:
            fault_cells += 1
            detect_s, clock = w.get("detect_virtual_s"), "virtual"
            if detect_s is None and w.get("detect_wall_s") is not None:
                detect_s, clock = w.get("detect_wall_s"), "wall"
            if w.get("detected_online"):
                flagged += 1
                if detect_s is not None:
                    lat.append(detect_s)
            detection.append({
                "cell": d["cell"],
                "adversary": spec.adversary,
                "detected_online": bool(w.get("detected_online")),
                "kind": w.get("first_kind"),
                "detect_s": detect_s,
                "clock": clock,
                "detect_cranks": w.get("detect_cranks"),
            })
        else:
            alarms = sorted(
                tuple(i) for i in w.get("incidents", ())
                if tuple(i)[0] in ALARM_KINDS)
            if alarms and d.get("verdict") == "clean":
                false_alarms.append(
                    {"cell": d["cell"], "incidents": alarms})
    lat.sort()
    return {
        "metric": "chaos_online_detection",
        "value": (round(flagged / fault_cells, 4)
                  if fault_cells else None),
        "unit": "flagged_fraction",
        "fault_cells": fault_cells,
        "flagged_online": flagged,
        "clean_false_alarms": len(false_alarms),
        "false_alarm_cells": false_alarms,
        "detect_p50_s": (round(lat[len(lat) // 2], 6) if lat else None),
        "detect_max_s": (round(lat[-1], 6) if lat else None),
        "detection": detection,
        "clean_fraction": report.get("value"),
    }


# ===========================================================================
# CLI
# ===========================================================================


def _load_spec(arg: str) -> CellSpec:
    if arg.startswith("@"):
        with open(arg[1:], encoding="utf-8") as fh:
            doc = json.load(fh)
    else:
        doc = json.loads(arg)
    # a triage entry's replay block is accepted directly
    if "spec" in doc and isinstance(doc["spec"], dict):
        doc = doc["spec"]
    return CellSpec.from_dict(doc)


def run_replay(spec: CellSpec, journal_root: str,
               keep_journals: bool = False) -> int:
    """Replay one cell twice and verify byte-identity (the triage
    workflow: paste the reported spec, watch the same failure again)."""
    from hbbft_tpu.obs.audit import format_report

    d1, res1 = run_cell(spec, os.path.join(journal_root, "replay-a"))
    d2, _res2 = run_cell(spec, os.path.join(journal_root, "replay-b"))
    identical = d1["timeline_digest"] == d2["timeline_digest"]
    sys.stdout.write(format_report(res1))
    doc = {
        "metric": "chaos_replay",
        "cell": spec.name,
        "verdict": d1["verdict"],
        "timeline_digest": d1["timeline_digest"],
        "byte_identical": identical,
    }
    if keep_journals:
        # only advertise the journal path when it survives this process
        # (no --journal-root → the temp root is deleted on exit)
        doc["journal"] = d1["journal"]
    print(json.dumps(doc))
    return 0 if identical else 3


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m hbbft_tpu.chaos.campaign", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("--grid", choices=("full", "smoke"), default="full")
    ap.add_argument("--seeds", type=int, default=2,
                    help="scenario seeds per (policy × adversary) cell "
                         "in the full grid")
    ap.add_argument("--churn", type=int, default=2,
                    help="kill/restart storm cells over a real socket "
                         "cluster (full grid only)")
    ap.add_argument("--max-cells", type=int, default=0,
                    help="cap the grid (0 = run everything)")
    ap.add_argument("--out", default="",
                    help="write the JSON report here (default: stdout)")
    ap.add_argument("--obs-out", default="",
                    help="also write the BENCH_OBS online-detection "
                         "record (per-cell detection latency) here")
    ap.add_argument("--journal-root", default="",
                    help="keep cell journals under this directory "
                         "(default: a temp dir, deleted after the run)")
    ap.add_argument("--no-verify", action="store_true",
                    help="skip the byte-identity replay of non-clean "
                         "correct-node cells")
    ap.add_argument("--replay", metavar="SPEC",
                    help="replay ONE cell from a JSON CellSpec (inline "
                         "or @file; a triage entry's replay block works "
                         "verbatim) and verify byte-identity")
    args = ap.parse_args(argv)

    import shutil
    import tempfile

    root = args.journal_root or tempfile.mkdtemp(prefix="hbbft-chaos-")
    keep = bool(args.journal_root)
    try:
        if args.replay:
            return run_replay(_load_spec(args.replay), root,
                              keep_journals=keep)
        if args.grid == "smoke":
            specs = smoke_grid()
        else:
            specs = full_grid(seeds=list(range(args.seeds)),
                              churn_cells=args.churn)
        if args.max_cells:
            specs = specs[: args.max_cells]

        def progress(i, total, detail):
            print(f"# [{i}/{total}] {detail['cell']}: "
                  f"{detail['verdict']}"
                  + (f" batches={detail.get('batches_min')}"
                     if "batches_min" in detail else ""),
                  file=sys.stderr, flush=True)

        report = run_campaign(specs, root,
                              verify_nonclean=not args.no_verify,
                              progress=progress)
        if args.obs_out:
            obs_doc = build_obs_report(report)
            with open(args.obs_out, "w", encoding="utf-8") as fh:
                fh.write(json.dumps(obs_doc) + "\n")
            print(f"# online-detection record written to "
                  f"{args.obs_out} (flagged "
                  f"{obs_doc['flagged_online']}/"
                  f"{obs_doc['fault_cells']}, false alarms "
                  f"{obs_doc['clean_false_alarms']})",
                  file=sys.stderr)
        if not keep:
            # journals were a working set; the report is the artifact
            for d in report["cells_detail"]:
                d.pop("journal", None)
        doc = json.dumps(report)
        if args.out:
            with open(args.out, "w", encoding="utf-8") as fh:
                fh.write(doc + "\n")
            print(f"# report written to {args.out}", file=sys.stderr)
        else:
            print(doc)
        return 0
    finally:
        if not keep:
            shutil.rmtree(root, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main())

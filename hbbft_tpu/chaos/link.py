"""Pluggable link shaping: seeded per-directed-edge fault policies.

The ONE place link faults are modeled (Thetacrypt evaluates threshold
services by sweeping exactly these network shapes — PAPERS.md):

- :class:`LinkPolicy` — the trait: given the link clock and a frame's
  size, decide whether the frame is delivered and with which per-copy
  delays.  Every random choice draws from the caller-supplied seeded RNG,
  so a (seed, schedule) pair replays byte-identically;
- :class:`ShapedLink` — the standard policy: latency + jitter, loss,
  duplication, reorder spread, a bandwidth cap (per-edge serialization
  queue), and timed partition windows that either *hold* frames until the
  heal (the transport default — models a healed path redelivering) or
  *drop* them outright;
- :class:`NetShape` — per-edge policy table with a default (edges are
  DIRECTED: ``(src, dst)``);
- :class:`LinkShaper` — the shared shaping hook both drivers consume:
  ``sim/virtual_net.py`` feeds it the virtual clock and enqueues shaped
  deliveries into its held queue; ``net/transport.py`` feeds it a
  monotonic-since-start clock and schedules shaped frames onto the event
  loop.  The shaper owns one seeded RNG and one mutable state dict per
  edge, and accounts every decision (``hbbft_chaos_*`` counters) — a
  dropped frame is never silent.

Time units are the *driver's clock units*: real seconds on the socket
path, virtual (cost-model) seconds in the simulator.  Presets are written
in real seconds; :meth:`NetShape.scaled` rescales a whole shape for the
simulator's much faster virtual clock (the campaign uses ``1e-3``).
"""

from __future__ import annotations

import hashlib
import random
from dataclasses import dataclass, field, replace
from typing import Any, Callable, Dict, Hashable, List, Optional, Tuple

NodeId = Hashable
Edge = Tuple[NodeId, NodeId]


class LinkPolicy:
    """Trait: one directed edge's shaping decision for one frame.

    Subclasses implement :meth:`decide`.  ``needs_size`` tells drivers
    whether the frame's byte size matters (the simulator only encodes a
    payload to measure it when a policy actually needs the number).
    """

    #: does decide() consult ``nbytes``? (bandwidth-capped links do)
    needs_size: bool = False

    def decide(self, now: float, nbytes: int, rng: random.Random,
               state: Dict[str, Any]) -> Tuple[bool, List[float]]:
        """``(deliver, delays)`` for one frame entering the link at
        ``now``: ``deliver`` False drops it; otherwise one copy is
        delivered per entry of ``delays`` (seconds after ``now``; more
        than one entry means duplication).  ``state`` is this edge's
        private mutable dict (e.g. the bandwidth queue tail)."""
        return True, [0.0]

    def scaled(self, k: float) -> "LinkPolicy":
        """This policy with every time constant multiplied by ``k``
        (identity for policies with no time constants)."""
        return self


@dataclass(frozen=True)
class ShapedLink(LinkPolicy):
    """The standard knob set — all times in the driver's clock units.

    ``partitions`` are half-open ``(start, end)`` windows on the link
    clock; during a window, ``partition_mode="hold"`` delivers the frame
    at the heal instant (the transport's at-least-once queue made
    visible), ``"drop"`` loses it outright (the simulator's hard-loss
    shape).
    """

    delay_s: float = 0.0
    jitter_s: float = 0.0
    loss: float = 0.0                 # P(drop) per frame
    dup: float = 0.0                  # P(second copy) per frame
    reorder: float = 0.0              # P(extra delay spread) per frame
    reorder_spread_s: float = 0.0
    bandwidth_bps: float = 0.0        # 0 = unlimited
    partitions: Tuple[Tuple[float, float], ...] = ()
    partition_mode: str = "hold"      # "hold" | "drop"

    @property
    def needs_size(self) -> bool:  # type: ignore[override]
        return self.bandwidth_bps > 0

    def decide(self, now: float, nbytes: int, rng: random.Random,
               state: Dict[str, Any]) -> Tuple[bool, List[float]]:
        for start, end in self.partitions:
            if start <= now < end:
                if self.partition_mode == "drop":
                    return False, []
                state["partition_holds"] = state.get(
                    "partition_holds", 0) + 1
                # delivered at the heal (plus the link's base latency)
                return True, [max(0.0, end - now) + self.delay_s]
        if self.loss > 0 and rng.random() < self.loss:
            return False, []
        d = self.delay_s
        if self.jitter_s > 0:
            d += rng.random() * self.jitter_s
        if self.reorder > 0 and rng.random() < self.reorder:
            d += rng.random() * self.reorder_spread_s
        if self.bandwidth_bps > 0 and nbytes > 0:
            # per-edge serialization queue: a frame transmits after the
            # previous one clears, at 8·nbytes/bps seconds per frame
            clear = max(now, state.get("bw_clear", 0.0))
            clear += 8.0 * nbytes / self.bandwidth_bps
            state["bw_clear"] = clear
            d += clear - now
        delays = [d]
        if self.dup > 0 and rng.random() < self.dup:
            # the copy lands nearby but not byte-simultaneously
            spread = self.jitter_s or self.delay_s or 0.001
            delays.append(d + rng.random() * spread)
        return True, delays

    def scaled(self, k: float) -> "ShapedLink":
        return replace(
            self,
            delay_s=self.delay_s * k,
            jitter_s=self.jitter_s * k,
            reorder_spread_s=self.reorder_spread_s * k,
            # time scaled by k ⇒ a frame's transmission time must scale
            # too: t' = k·8n/bps = 8n/(bps/k)
            bandwidth_bps=(self.bandwidth_bps / k
                           if self.bandwidth_bps > 0 else 0.0),
            partitions=tuple((s * k, e * k) for s, e in self.partitions),
        )


@dataclass
class NetShape:
    """Per-directed-edge policy table with an optional default."""

    default: Optional[LinkPolicy] = None
    edges: Dict[Edge, LinkPolicy] = field(default_factory=dict)

    def policy_for(self, src: NodeId, dst: NodeId) -> Optional[LinkPolicy]:
        return self.edges.get((src, dst), self.default)

    def scaled(self, k: float) -> "NetShape":
        return NetShape(
            default=self.default.scaled(k) if self.default else None,
            edges={e: p.scaled(k) for e, p in self.edges.items()},
        )


# ===========================================================================
# Presets (times in REAL seconds; .scaled(1e-3) for simulator cells)
# ===========================================================================


def _isolate(n: int, victim: int, policy: LinkPolicy,
             base: Optional[LinkPolicy] = None) -> NetShape:
    """``policy`` on every edge crossing the cut {victim} | rest."""
    edges: Dict[Edge, LinkPolicy] = {}
    for other in range(n):
        if other != victim:
            edges[(victim, other)] = policy
            edges[(other, victim)] = policy
    return NetShape(default=base, edges=edges)


def preset_shape(name: str, n: int) -> NetShape:
    """A named link-shaping preset for an ``n``-node cluster.

    The table (README "Chaos campaigns" has the prose version):

    ==============  ========================================================
    name            shape
    ==============  ========================================================
    none            no shaping (the control cell)
    wan-100ms       every link 50 ms ± 10 ms one-way (~100 ms RTT)
    lossy-1pct      every link drops 1% of frames, 5 ms ± 5 ms latency
    dup-reorder     5% duplication, 30% of frames re-spread over 50 ms
    partition-10s   node n−1 partitioned from everyone for t ∈ [2 s, 12 s),
                    frames held and delivered at the heal
    bandwidth-64k   every link capped at 64 kbit/s (serialization queue)
    bandwidth-asym  node n−1's links (both directions) capped at 64 KB/s,
                    every other link unshaped — the DispersedLedger WAN
                    shape: classic RBC collapses to the slow node's
                    uplink, VID keeps ordering at the fast nodes' pace
    ==============  ========================================================

    ``bandwidth-asym`` is deliberately NOT in :data:`PRESETS` (the full
    campaign grid): it exists for the targeted classic-vs-VID comparison
    cells and the ``BENCH_VID`` artifact, not for every adversary sweep.
    """
    if name in ("none", ""):
        return NetShape()
    if name == "wan-100ms":
        return NetShape(default=ShapedLink(delay_s=0.05, jitter_s=0.01))
    if name == "lossy-1pct":
        return NetShape(default=ShapedLink(delay_s=0.005, jitter_s=0.005,
                                           loss=0.01))
    if name == "dup-reorder":
        return NetShape(default=ShapedLink(delay_s=0.01, dup=0.05,
                                           reorder=0.3,
                                           reorder_spread_s=0.05))
    if name == "partition-10s":
        return _isolate(n, n - 1,
                        ShapedLink(delay_s=0.005,
                                   partitions=((2.0, 12.0),)),
                        base=ShapedLink(delay_s=0.005))
    if name == "bandwidth-64k":
        return NetShape(default=ShapedLink(delay_s=0.002,
                                           bandwidth_bps=64_000.0))
    if name == "bandwidth-asym":
        # one straggler at 64 KB/s (= 524288 bit/s) in BOTH directions,
        # everyone else unshaped — the shape under which payload-carrying
        # broadcast (classic RBC) serializes on the victim's uplink while
        # dispersal ships it only an O(1/n) shard
        return _isolate(n, n - 1,
                        ShapedLink(delay_s=0.002,
                                   bandwidth_bps=8.0 * 64 * 1024))
    raise ValueError(
        f"unknown chaos preset {name!r} "
        f"(known: {', '.join(PRESETS)}, bandwidth-asym)")


PRESETS: Tuple[str, ...] = ("none", "wan-100ms", "lossy-1pct",
                            "dup-reorder", "partition-10s",
                            "bandwidth-64k")


# ===========================================================================
# The shared shaping hook
# ===========================================================================


class LinkShaper:
    """Seeded per-edge shaping decisions + accounting for ONE driver.

    Clock-free by design: the driver supplies ``now`` on every call
    (virtual seconds in the simulator, monotonic-since-start seconds on
    the transport), so this module never reads a wall clock — hblint's
    ``determinism`` scope holds.

    Per-edge RNGs derive from ``(seed, src, dst)`` the same way the
    transport's :class:`~hbbft_tpu.net.transport.BackoffPolicy` derives
    its streams, so one edge's draw count never perturbs another's.
    """

    def __init__(self, shape: NetShape, seed: int = 0, registry=None):
        self.shape = shape
        self.seed = seed
        self._rngs: Dict[Edge, random.Random] = {}
        self._state: Dict[Edge, Dict[str, Any]] = {}
        self._bind_metrics(registry)

    def _bind_metrics(self, registry) -> None:
        if registry is None:
            from hbbft_tpu.obs.metrics import Registry

            registry = Registry()
        self.registry = registry
        r = registry
        self._c_shaped = r.counter(
            "hbbft_chaos_frames_shaped_total",
            "frames that passed through a link-shaping policy")
        self._c_dropped = r.counter(
            "hbbft_chaos_frames_dropped_total",
            "frames dropped by link shaping (loss or drop-mode "
            "partitions)")
        self._c_delayed = r.counter(
            "hbbft_chaos_frames_delayed_total",
            "frames delivered late by link shaping")
        self._c_dup = r.counter(
            "hbbft_chaos_frames_duplicated_total",
            "extra frame copies injected by link shaping")
        self._c_partition = r.counter(
            "hbbft_chaos_partition_holds_total",
            "frames held across a partition window until its heal")

    def bind_registry(self, registry) -> None:
        """Re-home the counters onto a node's registry (the transport
        calls this so shaping shows on that node's ``/metrics``)."""
        self._bind_metrics(registry)

    # -- decisions -----------------------------------------------------------

    def policy_for(self, src: NodeId, dst: NodeId) -> Optional[LinkPolicy]:
        return self.shape.policy_for(src, dst)

    def backlog_s(self, src: NodeId, dst: NodeId, now: float) -> float:
        """Seconds of bulk already committed to the ``src → dst`` edge's
        serialization queue (0.0 for unshaped / non-bandwidth edges).
        The transport consults this before pushing more best-effort bulk
        — e.g. VID dispersal shards beyond the cert's ``n − f`` voters —
        at a peer whose link is already the bottleneck."""
        state = self._state.get((src, dst))
        if not state:
            return 0.0
        return max(0.0, state.get("bw_clear", 0.0) - now)

    def rng_for(self, src: NodeId, dst: NodeId) -> random.Random:
        edge = (src, dst)
        rng = self._rngs.get(edge)
        if rng is None:
            digest = hashlib.sha3_256(
                b"hbbft-chaos-link:%d:%s>%s"
                % (self.seed, repr(src).encode(), repr(dst).encode())
            ).digest()
            rng = random.Random(int.from_bytes(digest[:8], "big"))
            self._rngs[edge] = rng
        return rng

    def shape_frame(self, src: NodeId, dst: NodeId, now: float,
                    nbytes: int = 0,
                    size_fn: Optional[Callable[[], int]] = None,
                    ) -> Optional[List[float]]:
        """Per-copy delivery delays for one frame on edge ``src → dst``.

        ``None`` means the edge has no policy (driver fast path — nothing
        counted); ``[]`` means the frame is dropped; otherwise deliver one
        copy per entry, that many units after ``now``.  ``size_fn`` is
        consulted only when the policy needs a size and ``nbytes`` is 0.
        """
        policy = self.shape.policy_for(src, dst)
        if policy is None:
            return None
        if policy.needs_size and nbytes == 0 and size_fn is not None:
            nbytes = size_fn()
        edge = (src, dst)
        state = self._state.setdefault(edge, {})
        holds_before = state.get("partition_holds", 0)
        deliver, delays = policy.decide(now, nbytes,
                                        self.rng_for(src, dst), state)
        self._c_shaped.inc()
        if not deliver:
            self._c_dropped.inc()
            return []
        if state.get("partition_holds", 0) > holds_before:
            self._c_partition.inc()
        if any(d > 0 for d in delays):
            self._c_delayed.inc()
        if len(delays) > 1:
            self._c_dup.inc(len(delays) - 1)
        return delays

    # -- introspection -------------------------------------------------------

    def stats(self) -> Dict[str, int]:
        return {
            "shaped": int(self._c_shaped.value()),
            "dropped": int(self._c_dropped.value()),
            "delayed": int(self._c_delayed.value()),
            "duplicated": int(self._c_dup.value()),
            "partition_holds": int(self._c_partition.value()),
        }

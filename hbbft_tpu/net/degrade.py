"""Guard-driven adaptive degradation + headroom-driven raising: the
bidirectional control ladder.

The overload-defense layer (transport ingress budgets, SenderQueue caps,
mempool shedding) is a set of hard ceilings: each engages only once its
buffer is already full, and each sheds by *eviction* — a cliff edge.
This module adds the graceful slope in front of those cliffs: a bounded
controller that watches the guard layer's own pressure counters and,
while pressure is sustained, shrinks what this node *volunteers* into
the system — its proposed batch size and its mempool admission ceilings
— then restores them once pressure clears.

Since the performance plane (:mod:`hbbft_tpu.obs.perf`) the ladder also
extends *upward* (ROADMAP 5(b)): under sustained benign slack — guard
counters quiet, measured headroom above ``raise_headroom``, and real
demand present (a non-empty mempool; an idle node has nothing to absorb)
for ``raise_windows`` consecutive windows — the controller raises the
proposed batch size and mempool admission toward the measured MB-scale
optimum, one boost level at a time up to ``max_boost``.  The raise arm
is strictly subordinate: ANY abuse pressure instantly restores the exact
bases before the degradation ladder engages, sustained strain (demand
with headroom gone) steps the boost back down, and quiet windows (demand
gone) restore the exact configured bases — the raised state never
survives the load that justified it.

Design constraints:

- **Bounded and monotone-safe.**  The controller moves one level at a
  time through a fixed ladder (``max_level`` deep).  Every lever is a
  pure function of the level and the bases captured at attach time, so
  levels never compound and recovery restores the exact configured
  values.
- **Pressure is read from counters, not events.**  Each tick diffs the
  monotone guard ABUSE counters (decode strikes, strike-ladder
  disconnects) over the window — the controller needs no new plumbing
  into the hot paths and cannot miss events.  Rate-limit and capacity
  counters (ingress throttles, egress stalls, SenderQueue evictions)
  are deliberately NOT sources: they fire under honest open-loop
  saturation, which the mempool's fair-shedding layer owns.
- **Runs on the pump thread.**  :meth:`tick` is called between pump
  iterations (``StepPump`` wakes idle pumps every ``tick_s`` for exactly
  this reason — recovery must proceed while the node is quiet), so the
  batch-size mutation is serialized with the proposer that reads it.
- **Observable, never silent.**  Level transitions are counted
  (``hbbft_guard_degraded_transitions_total``, and
  ``hbbft_ctrl_transitions_total`` for the raise arm), the current state
  is exported as gauges (``hbbft_guard_degraded_level`` / ``_active`` /
  ``_batch_size``, plus ``hbbft_ctrl_boost_level`` /
  ``hbbft_ctrl_headroom``), journaled through the flight pipeline (note
  kind ``degrade`` — distinct from ``guard`` so the forensic auditor's
  overload attribution is not polluted by peerless controller events),
  and surfaced in ``/status``'s ``degraded`` section.
"""

from __future__ import annotations

import logging
import time
from typing import Any, Callable, Dict, List, Optional

logger = logging.getLogger("hbbft_tpu.net")


class DegradationController:
    """Bounded load-shedding ladder driven by guard pressure counters.

    ``sources`` is a list of ``(name, fn)`` pairs where ``fn() -> float``
    reads a monotone counter; the per-window pressure is the summed
    delta across all sources divided by the window length (events/s).
    Pressure at or above ``engage_per_s`` steps the level up;
    ``clear_windows`` consecutive windows below ``clear_per_s`` step it
    back down.  At level ``L`` the batch size and mempool ceilings are
    halved ``L`` times (floored at ``min_batch`` / ``min_capacity``).

    The raise arm (off unless ``max_boost > 0`` and a ``headroom_fn`` is
    wired): at level 0, ``raise_windows`` consecutive clean windows with
    ``demand_fn() > 0`` and ``headroom_fn() >= raise_headroom`` step
    ``boost`` up (levers doubled per boost level, capped at attach-time
    ceilings); ``clear_windows`` windows of strain (demand, no headroom)
    step it down; ``clear_windows`` windows of quiet (no demand) — or a
    single window of guard pressure — restore the exact bases at once.
    ``apply_level`` receives the SIGNED effective level
    (``level - boost``): positive degrades, negative raises, zero is the
    exact configured bases.
    """

    def __init__(
        self,
        *,
        sources: List,
        apply_level: Callable[[int], None],
        registry=None,
        window_s: float = 1.0,
        engage_per_s: float = 8.0,
        clear_per_s: float = 1.0,
        clear_windows: int = 3,
        max_level: int = 3,
        max_boost: int = 0,
        raise_windows: int = 10,
        raise_headroom: float = 0.6,
        headroom_fn: Optional[Callable[[], Optional[float]]] = None,
        demand_fn: Optional[Callable[[], float]] = None,
        on_transition: Optional[Callable[[int, int, str], None]] = None,
        clock: Callable[[], float] = time.monotonic,
    ):
        from hbbft_tpu.obs.metrics import Registry

        if window_s <= 0 or max_level < 1:
            raise ValueError("window_s must be > 0 and max_level >= 1")
        if max_boost < 0 or raise_windows < 1:
            raise ValueError("max_boost must be >= 0 and "
                             "raise_windows >= 1")
        self.sources = list(sources)
        self.apply_level = apply_level
        self.window_s = float(window_s)
        self.engage_per_s = float(engage_per_s)
        self.clear_per_s = float(clear_per_s)
        self.clear_windows = int(clear_windows)
        self.max_level = int(max_level)
        self.max_boost = int(max_boost)
        self.raise_windows = int(raise_windows)
        self.raise_headroom = float(raise_headroom)
        self.headroom_fn = headroom_fn
        self.demand_fn = demand_fn
        self.on_transition = on_transition
        self.clock = clock
        self.level = 0
        self.boost = 0
        self.last_pressure_per_s = 0.0
        self.last_headroom: Optional[float] = None
        self._clean = 0
        self._slack = 0
        self._strain = 0
        self._quiet = 0
        self._t_window = clock()
        self._last: Dict[str, float] = {
            name: float(fn()) for name, fn in self.sources
        }
        r = registry if registry is not None else Registry()
        self._g_level = r.gauge(
            "hbbft_guard_degraded_level",
            "current adaptive-degradation ladder level (0 = full "
            "service; each level halves proposed batch size and "
            "mempool admission ceilings)")
        self._g_active = r.gauge(
            "hbbft_guard_degraded_active",
            "1 while adaptive degradation is engaged (level > 0)")
        self._g_batch = r.gauge(
            "hbbft_guard_degraded_batch_size",
            "the batch size currently proposed under degradation "
            "(equals the configured base at level 0)")
        self._c_transitions = r.counter(
            "hbbft_guard_degraded_transitions_total",
            "adaptive-degradation level changes, by direction",
            labelnames=("direction",), max_label_sets=3)
        for d in ("up", "down"):
            self._c_transitions.labels(direction=d)
        self._g_boost = r.gauge(
            "hbbft_ctrl_boost_level",
            "current raise-arm boost level (0 = configured bases; each "
            "level doubles proposed batch size and mempool admission "
            "toward the attach-time ceilings)")
        self._g_headroom = r.gauge(
            "hbbft_ctrl_headroom",
            "latest headroom scalar the controller consumed from the "
            "perf plane (1 = idle, 0 = saturated; -1 = no sample yet)")
        self._c_ctrl_transitions = r.counter(
            "hbbft_ctrl_transitions_total",
            "raise-arm boost changes, by direction (`raise` under "
            "sustained slack, `lower` under strain, `restore` = exact "
            "bases on quiet or abuse preemption)",
            labelnames=("direction",), max_label_sets=4)
        for d in ("raise", "lower", "restore"):
            self._c_ctrl_transitions.labels(direction=d)
        self._g_level.set(0)
        self._g_active.set(0)
        self._g_boost.set(0)
        self._g_headroom.set(-1)

    # -- the ladder ----------------------------------------------------------

    @staticmethod
    def shrink(base: int, level: int, floor: int) -> int:
        """The lever law: halve ``base`` once per level, floored."""
        return max(int(floor), int(base) >> level)

    @staticmethod
    def grow(base: int, boost: int, ceiling: int) -> int:
        """The raise-arm lever law: double ``base`` once per boost
        level, capped at ``ceiling`` (the measured-optimum ceiling
        captured at attach time)."""
        return min(int(ceiling), int(base) << boost)

    def _pressure(self, dt: float) -> float:
        total = 0.0
        for name, fn in self.sources:
            now = float(fn())
            # a re-bound counter restarting at 0 must not read as a
            # negative delta and mask real pressure
            prev = self._last.get(name, 0.0)
            total += max(0.0, now - prev)
            self._last[name] = now
        return total / dt

    def _set_level(self, level: int, why: str) -> None:
        direction = "up" if level > self.level else "down"
        self.level = level
        self.apply_level(level - self.boost)
        self._g_level.set(level)
        self._g_active.set(1 if level else 0)
        self._c_transitions.labels(direction=direction).inc()
        if self.on_transition is not None:
            self.on_transition(level, self.batch_size(), why)
        logger.warning("degrade: level %d (%s, %s)", level, direction, why)

    def _set_boost(self, boost: int, direction: str, why: str) -> None:
        self.boost = boost
        self.apply_level(self.level - boost)
        self._g_boost.set(boost)
        self._c_ctrl_transitions.labels(direction=direction).inc()
        if self.on_transition is not None:
            self.on_transition(self.level - boost, self.batch_size(), why)
        logger.info("ctrl: boost %d (%s, %s)", boost, direction, why)

    def batch_size(self) -> int:
        """What the attach-time wiring reports as the current batch
        size lever value; overwritten by :func:`attach_runtime`."""
        return 0

    def tick(self) -> None:
        """One controller step (pump thread): no-op until a full window
        has elapsed, then judge the window's pressure."""
        now = self.clock()
        dt = now - self._t_window
        if dt < self.window_s:
            return
        self._t_window = now
        pressure = self._pressure(dt)
        self.last_pressure_per_s = pressure
        if pressure >= self.engage_per_s:
            self._clean = 0
            self._slack = 0
            if self.boost > 0:
                # abuse preempts any raised state BEFORE the degradation
                # ladder engages: one restore straight to the bases
                self._set_boost(0, "restore",
                                f"abuse pressure={pressure:.1f}/s")
            if self.level < self.max_level:
                self._set_level(self.level + 1,
                                f"pressure={pressure:.1f}/s")
        elif pressure <= self.clear_per_s:
            self._clean += 1
            if self.level > 0:
                if self._clean >= self.clear_windows:
                    self._clean = 0
                    self._set_level(
                        self.level - 1,
                        f"clean for {self.clear_windows} windows")
            else:
                self._raise_arm()
        else:
            # between the thresholds: hold the level, restart the
            # clean-window count (hysteresis — no up/down flapping)
            self._clean = 0
            self._slack = 0
            if self.boost > 0:
                # any guard pressure at all forfeits the raised state
                self._set_boost(0, "restore",
                                f"pressure={pressure:.1f}/s")

    def _raise_arm(self) -> None:
        """One clean level-0 window: judge slack / strain / quiet.

        Runs only when the degradation ladder is fully clear; disabled
        entirely (every counter pinned to 0) unless ``max_boost > 0``
        and a headroom source is wired — a controller without a perf
        plane behind it must never infer slack."""
        if self.max_boost <= 0 or self.headroom_fn is None:
            return
        headroom = self.headroom_fn()
        self.last_headroom = headroom
        self._g_headroom.set(-1 if headroom is None else headroom)
        demand = (float(self.demand_fn())
                  if self.demand_fn is not None else 0.0)
        if demand <= 0:
            self._slack = 0
            self._strain = 0
            self._quiet += 1
            if self._quiet >= self.clear_windows and self.boost > 0:
                self._quiet = 0
                self._set_boost(
                    0, "restore",
                    f"quiet for {self.clear_windows} windows")
            return
        self._quiet = 0
        if headroom is not None and headroom >= self.raise_headroom:
            self._strain = 0
            self._slack += 1
            if self._slack >= self.raise_windows \
                    and self.boost < self.max_boost:
                self._slack = 0
                self._set_boost(
                    self.boost + 1, "raise",
                    f"headroom={headroom:.2f} for "
                    f"{self.raise_windows} windows")
        else:
            self._slack = 0
            self._strain += 1
            if self._strain >= self.clear_windows and self.boost > 0:
                self._strain = 0
                self._set_boost(
                    self.boost - 1, "lower",
                    f"strain (headroom="
                    f"{'?' if headroom is None else f'{headroom:.2f}'})")

    def as_dict(self) -> Dict[str, Any]:
        return {
            "level": self.level,
            "boost": self.boost,
            "active": bool(self.level),
            "batch_size": self.batch_size(),
            "base_batch_size": getattr(self, "base_batch_size", None),
            "pressure_per_s": round(self.last_pressure_per_s, 3),
            "engage_per_s": self.engage_per_s,
            "max_level": self.max_level,
            "max_boost": self.max_boost,
            "headroom": self.last_headroom,
        }


def attach_runtime(runtime, *, min_batch: int = 8,
                   min_capacity: int = 64,
                   max_batch: Optional[int] = None,
                   max_capacity: Optional[int] = None,
                   **kwargs) -> Optional[DegradationController]:
    """Wire a :class:`DegradationController` onto a ``NodeRuntime``.

    Captures the configured bases (SenderQueue batch size, mempool
    capacity / pending-byte ceiling), binds the guard pressure sources,
    and returns the controller — or ``None`` when the wrapped protocol
    exposes no batch size to shrink (nothing to degrade).  Levers are
    applied between pump iterations, which serializes them with the
    proposer; the mempool attributes are read under its own lock on the
    admission path, so shrinking them mid-run is safe.

    The raise arm activates only when ``max_boost > 0`` is passed AND
    the runtime carries a perf plane (its measured headroom is the slack
    signal; mempool depth is the demand signal).  ``max_batch`` /
    ``max_capacity`` are the raise ceilings (default 8× the bases — the
    order of magnitude the MB-scale ingest sweeps measured as the
    throughput knee).
    """
    algo = runtime.sq.algo
    base_batch = getattr(algo, "batch_size", None)
    if base_batch is None:
        return None
    base_batch = int(base_batch)
    mp = runtime.mempool
    base_capacity = int(mp.capacity)
    base_pending = int(mp.max_pending_bytes)
    ceil_batch = int(max_batch) if max_batch is not None \
        else base_batch << 3
    ceil_capacity = int(max_capacity) if max_capacity is not None \
        else base_capacity << 3
    ceil_pending = base_pending << 3
    ingress = runtime.transport.ingress

    def apply_level(level: int) -> None:
        if level >= 0:
            algo.batch_size = DegradationController.shrink(
                base_batch, level, min_batch)
            mp.capacity = DegradationController.shrink(
                base_capacity, level, min_capacity)
            mp.max_pending_bytes = DegradationController.shrink(
                base_pending, level, 1)
        else:
            boost = -level
            algo.batch_size = DegradationController.grow(
                base_batch, boost, ceil_batch)
            mp.capacity = DegradationController.grow(
                base_capacity, boost, ceil_capacity)
            mp.max_pending_bytes = DegradationController.grow(
                base_pending, boost, ceil_pending)
        ctl._g_batch.set(algo.batch_size)

    def on_transition(level: int, batch: int, why: str) -> None:
        if runtime.flight is not None:
            # note kind "degrade", NOT "guard": these are peerless
            # controller events and must not enter the auditor's
            # per-peer overload attribution
            runtime.flight.on_note(
                "degrade",
                f"level={level} batch_size={batch} why={why!r}")

    # pressure sources are the guard's ABUSE verdicts only: decode
    # strikes (garbage streams) and strike-ladder disconnects
    # (sustained budget abuse).  Rate-limit and capacity counters —
    # ingress throttles, egress stalls, SenderQueue buffered-cap
    # evictions — all fire under honest saturation (an open-loop
    # loadgen, MB-scale ingestion backing up a lagging peer, a
    # bandwidth-shaped WAN link) and must not shrink service for
    # benign load; the mempool's fair-shedding layer owns that regime.
    sources = [
        ("ingress_disconnects", ingress._c_disconnects.total),
        ("decode_strikes", ingress._c_decode_strikes.total),
    ]
    # slack comes from the perf plane's MEASURED headroom — a runtime
    # without one (perf=None) never raises; demand is mempool depth (an
    # idle node has nothing to absorb, so quiet restores the bases)
    perf = getattr(runtime, "perf", None)
    kwargs.setdefault("headroom_fn",
                      perf.headroom if perf is not None else None)
    kwargs.setdefault("demand_fn", lambda: len(mp))
    ctl = DegradationController(
        sources=sources, apply_level=apply_level,
        registry=runtime.registry, on_transition=on_transition, **kwargs)
    ctl.batch_size = lambda: int(getattr(algo, "batch_size", 0))
    ctl.base_batch_size = base_batch
    ctl._g_batch.set(base_batch)
    return ctl

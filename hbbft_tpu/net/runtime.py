"""NodeRuntime: host a sans-I/O consensus protocol on real sockets.

The runtime owns the event loop the :class:`~hbbft_tpu.traits.Step`
contract demands: it feeds received wire bytes into a
:class:`~hbbft_tpu.protocols.sender_queue.SenderQueue`-wrapped algorithm
(QHB/DHB/HB — anything ``SenderQueue`` can wrap), resolves each outgoing
``Target::All/AllExcept/Node`` against the transport's peer set, and
encodes every message exactly once per payload.

Since the epoch-pipelined scheduler landed, the protocol no longer runs
inside transport callbacks: every event is queued on a
:class:`~hbbft_tpu.net.scheduler.StepPump`, whose worker thread runs the
state machine (threshold crypto included) off the event loop, keeps up to
``pipeline_depth`` epochs in flight, resolves cross-epoch batched share
verifications once per iteration, and coalesces each iteration's
outbound messages into per-peer MSG_BATCH frames.

Catch-up (the ``EpochStarted`` path):

- every connection hello carries the sender's current (era, epoch);
- a hello *above* a peer's recorded key is fed to the SenderQueue as a
  normal ``EpochStarted`` (releasing held-back messages);
- a hello *below* it means the peer restarted: the runtime rewinds the
  SenderQueue via :meth:`SenderQueue.reinit_peer`, handing it the replay
  log of recently-sent (key, message) pairs it retains per peer.  The
  restarted peer then replays the protocol from its announced key, with
  the backlog flowing in epoch order as it announces progress — a node
  restarted from scratch at (0, 0) recovers every batch as long as the
  replay retention covers the history.

Client traffic (``TX``/``STATUS_REQ`` frames) is admitted through a
bounded dedup'd :class:`~hbbft_tpu.net.client.Mempool` — the backpressure
boundary — and committed batches are pushed back to every connected client
as ``TX_COMMIT`` digests, which is what the client's latency measurement
keys on.  A running SHA3 chain over committed batches (``ledger digest``)
makes cross-node batch-identity a one-line comparison.
"""

from __future__ import annotations

import asyncio
import hashlib
import json
import logging
import os
import struct
import time
from collections import OrderedDict
from typing import (
    Any, Callable, Dict, FrozenSet, Hashable, List, Optional, Tuple,
)

from hbbft_tpu.net import framing
from hbbft_tpu.net.client import Mempool, tx_digest
from hbbft_tpu.net.degrade import attach_runtime as _attach_degrade
from hbbft_tpu.net.retrieve import RetrieveService, RetrievedPayload
from hbbft_tpu.net.scheduler import StepPump
from hbbft_tpu.net.statesync import SnapshotStore
from hbbft_tpu.net.transport import ClientConn, EraKeyRing, Transport
from hbbft_tpu.snapshot import capture_join_snapshot
from hbbft_tpu.obs.flight import FlightObserver, FlightRecorder
from hbbft_tpu.obs.http import ObsServer
from hbbft_tpu.obs.metrics import MetricAttr, Registry, fault_counter
from hbbft_tpu.obs.perf import PerfPlane
from hbbft_tpu.obs.spans import SpanTracer
from hbbft_tpu.obs.trace import trace_id
from hbbft_tpu.ops import rs as _rs
from hbbft_tpu.parallel import mesh as _mesh
from hbbft_tpu.protocols import wire
from hbbft_tpu.protocols.dynamic_honey_badger import (
    DhbBatch,
    DynamicHoneyBadger,
)
from hbbft_tpu.protocols.honey_badger import Batch as HbBatch, HoneyBadger
from hbbft_tpu.fault_log import FaultKind
from hbbft_tpu.protocols.queueing_honey_badger import (
    PipelineInput,
    QhbBatch,
    TxInput,
    _de_txs,
)
from hbbft_tpu.protocols.vid import (
    VidCertReady,
    VidDisperse,
    VidQhbBatch,
    VidQueueingHoneyBadger,
    VidRetrieve,
    VidShard,
    payload_digest,
)
from hbbft_tpu.protocols.sender_queue import (
    AlgoMessage,
    EpochStarted,
    SenderQueue,
    _algo_key,
    _algo_window,
    message_key,
)
from hbbft_tpu.traits import Step


class _PumpOutcome:
    """One pump iteration's deferred side effects, applied on the event
    loop after the worker thread returns: coalesced outbound payloads per
    destination (insertion-ordered) and client commit notifications."""

    __slots__ = ("frames", "frames_delayed", "commits", "sheds", "cpu_s")

    def __init__(self):
        self.frames: Dict[NodeId, List[bytes]] = {}
        # payloads held back by class-selective shaping (pump_flush
        # schedules them `aba_out_delay_s` later, out of band so they
        # never head-block the fast classes)
        self.frames_delayed: Dict[NodeId, List[bytes]] = {}
        self.commits: List[Tuple[int, int, List[bytes]]] = []
        # digests of mempool-shed txs: clients are notified (ACK_SHED
        # push) so their commit waits fail fast instead of timing out
        self.sheds: List[bytes] = []
        # CPU seconds this iteration actually burned (thread time, immune
        # to preemption on a contended host) — drives the pump's
        # inline-vs-executor decision
        self.cpu_s: float = 0.0

NodeId = Hashable
EpochKey = Tuple[int, int]
Addr = Tuple[str, int]

logger = logging.getLogger("hbbft_tpu.net")


def pick_shed_peers(
    backlogs: Dict[Any, float],
    threshold_s: float,
    max_shed: int,
    already: FrozenSet[Any] = frozenset(),
) -> FrozenSet[Any]:
    """Which peers a VID proposer may skip when fanning out this root's
    dispersal shards.

    A dispersal beyond the cert's ``n − f`` voters is pure availability
    insurance, so frames bound for a congested link are the one place the
    protocol can legally shed load: the skipped peer retrieves the
    payload lazily after ordering the commitment.  Classic RBC has no
    such slack — every payload frame is on the ordering critical path.

    The root's prior shed set is reused (and extended, within budget) so
    a re-dispersal of the same root never exceeds ``max_shed`` distinct
    peers total; worst links are shed first."""
    shed = set(already)
    for peer, lag in sorted(backlogs.items(), key=lambda kv: (-kv[1],
                                                              repr(kv[0]))):
        if len(shed) >= max_shed:
            break
        if peer in shed or lag < threshold_s:
            continue
        shed.add(peer)
    return frozenset(shed)


class NodeRuntime:
    """One networked consensus node: SenderQueue-wrapped algorithm +
    :class:`Transport` + client admission."""

    def __init__(
        self,
        algo: Any,
        cluster_id: bytes,
        *,
        seed: int = 0,
        mempool: Optional[Mempool] = None,
        make_tx_input: Callable[[bytes], Any] = TxInput,
        replay_retain_epochs: int = 64,
        replay_retain_bytes: int = 0,
        on_batch: Optional[Callable[[Any], None]] = None,
        trace=None,
        cost_model=None,
        registry: Optional[Registry] = None,
        digest_chain_retain: int = 4096,
        flight_dir: Optional[str] = None,
        flight_max_segment_bytes: int = 4 * 2**20,
        flight_max_segments: int = 16,
        flight_retain_batches: int = 0,
        ledger_seed: Optional[Tuple[bytes, int]] = None,
        sync_chunk_bytes: int = 32 * 1024,
        peer_addr_book: Optional[
            Callable[[NodeId], Optional[Addr]]
        ] = None,
        pipeline_depth: int = 1,
        step_delay_s: float = 0.0,
        aba_out_delay_s: float = 0.0,
        aba_out_classes: str = "",
        auth: bool = True,
        auth_grace_s: float = 30.0,
        degrade: bool = True,
        degrade_kwargs: Optional[Dict[str, Any]] = None,
        vid_retrieve_kwargs: Optional[Dict[str, Any]] = None,
        vid_shed_backlog_s: float = 0.25,
        **transport_kwargs,
    ):
        self.sq = algo if isinstance(algo, SenderQueue) else SenderQueue(algo)
        # VID mode (protocols/vid.py + net/retrieve.py): the wrapped
        # algorithm orders constant-size (root, cert) commitments and the
        # runtime owns lazy payload retrieval — fetch k shards, rebuild,
        # re-verify — off the ordering critical path.
        self._vid = isinstance(self.sq.algo, VidQueueingHoneyBadger)
        self._retrieve: Optional[RetrieveService] = None
        # root → (era, epoch) of the committed commitment awaiting its
        # payload; resolved entries are popped in _on_retrieved
        self._vid_pending: Dict[bytes, EpochKey] = {}
        if self._vid:
            self._retrieve = RetrieveService(
                self.sq.our_id(), self.sq.algo.store,
                on_note=self._vid_note,
                **(vid_retrieve_kwargs or {}))
        # Best-effort dispersal shedding: a shard bound for a peer whose
        # shaped link already has ≥ this many seconds of bulk committed
        # is dropped at dispatch (at most f peers per root, so the cert
        # stays reachable from the remaining n − f voters).  0 disables.
        # Keep the threshold SMALL: shards admitted while the backlog sits
        # just under it become a standing serialization queue that every
        # consensus control frame behind them must wait out — the
        # threshold is effectively the straggler's added ordering latency.
        self.vid_shed_backlog_s = float(vid_shed_backlog_s)
        # root → frozen shed set, LRU-capped: a re-dispersal of the same
        # root (excluded proposer re-sampling its queue) reuses the same
        # budget instead of shedding a fresh f peers each time
        self._vid_shed_roots: "OrderedDict[bytes, FrozenSet[Any]]" = (
            OrderedDict())
        self._vid_sheds = 0
        # Epoch-pipelined scheduler (net/scheduler.py): every protocol
        # interaction is queued and processed in batches on the pump's
        # worker thread; with pipeline_depth > 1 the pump keeps that many
        # epochs proposed-into at once.  Depth 1 preserves the sequential
        # one-epoch-at-a-time behavior.
        self.pipeline_depth = max(1, int(pipeline_depth))
        # chaos/scenario knob: sleep this long before every pump
        # iteration — models an overloaded/underprovisioned validator
        # (the bench's coin-exercise run slows one node until its own
        # proposal races the Subset give-up threshold, the honest way to
        # split ABA votes and flip real threshold coins)
        self.step_delay_s = float(step_delay_s)
        # message-class-selective shaping: outbound BINARY-AGREEMENT
        # traffic is held this long while RBC and the rest flow normally —
        # decorrelates ABA progress from RBC delivery, which is what
        # genuinely splits Subset's accept/give-up votes (plain per-link
        # delay cannot: the RBC echo relay re-equalizes deliveries).
        # `aba_out_classes` narrows the hold to specific phases (comma
        # list of span names, e.g. "aba_conf" delays only decisions — the
        # bench's coin-exercise shape — while BVal/Aux propagate freely
        # so neither side of a vote split gets flooded out); empty = all
        # aba_* classes.  First member of the ROADMAP's link-shaping
        # policy zoo.
        self.aba_out_delay_s = float(aba_out_delay_s)
        self.aba_out_classes = frozenset(
            c.strip() for c in aba_out_classes.split(",") if c.strip()
        )
        # tick_s: the degradation controller needs periodic pump wakes
        # to recover on an idle node (see StepPump), VID retrieval
        # retries need the same heartbeat, and the always-on perf plane
        # samples on it (a stalled sampler would freeze /status headroom
        # exactly when an operator looks)
        self.pump = StepPump(self, pipeline_depth=self.pipeline_depth,
                             tick_s=0.25)
        self._out: Optional[_PumpOutcome] = None
        # park threshold-decrypt share verification in the protocols so
        # the pump can resolve ALL in-flight epochs' sets in one merged
        # crypto.batch call per iteration (no-op for unencrypted runs)
        self._enable_deferred_crypto()
        # one registry per node: every layer below (transport, mempool,
        # span tracer, fault tallies) registers onto it, and /metrics
        # exposes it live (see hbbft_tpu.obs)
        self.registry = registry or Registry()
        self.spans = SpanTracer(self.registry, node=self.sq.our_id())
        self._c_decode = self.registry.counter(
            "hbbft_node_decode_failures_total",
            "undecodable or protocol-rejected peer messages")
        self._c_send_fail = self.registry.counter(
            "hbbft_node_send_failures_total",
            "outbound frames dropped (frame cap)")
        self._c_replay_gaps = self.registry.counter(
            "hbbft_node_replay_gaps_total",
            "peer restarts whose gap exceeded replay retention "
            "(the peer cannot catch up from here; remedy: snapshot "
            "state-sync — net/statesync.py)")
        self._c_replay_trunc = self.registry.counter(
            "hbbft_node_replay_truncations_total",
            "replay-log entries truncated by the byte cap "
            "(replay_retain_bytes) before their epoch retention expired")
        self._c_committed = self.registry.counter(
            "hbbft_node_committed_txs_total", "transactions committed")
        self._c_faults = fault_counter(self.registry)
        # hbbft_guard_*: the overload-defense metric family (transport
        # ingress budgets register theirs on the same registry below)
        self._c_sq_evict = self.registry.counter(
            "hbbft_guard_senderq_evictions_total",
            "SenderQueue backlog entries front-chopped at the per-peer "
            "cap (the peer recovers via snapshot state-sync)",
            labelnames=("peer",), max_label_sets=33)
        self._c_proto_drops = self.registry.counter(
            "hbbft_guard_protocol_drops_total",
            "messages dropped by protocol-layer flood budgets "
            "(hb_future = HoneyBadger future-epoch budget, subset = "
            "per-ACS sender budget)",
            labelnames=("kind",), max_label_sets=4)
        for k in ("hb_future", "subset"):
            self._c_proto_drops.labels(kind=k)
        # hbbft_rbc_*: erasure hot-path accounting.  ops/rs.py keeps
        # deterministic plain-int counters (no registry dependency, no
        # clocks — the module is in the determinism lint's scope); each
        # scrape folds the delta since the last sync into real counters.
        # The rs counters are process-global, so in-process multi-node
        # harnesses see the shared total on every node's registry.
        self._c_rbc_calls = self.registry.counter(
            "hbbft_rbc_erasure_calls_total",
            "erasure encode/decode matrix applications by backend",
            labelnames=("backend",), max_label_sets=4)
        self._c_rbc_bytes = self.registry.counter(
            "hbbft_rbc_erasure_bytes_total",
            "payload bytes through the erasure hot path by backend",
            labelnames=("backend",), max_label_sets=4)
        self._rs_stats_last = _rs.stats_snapshot()
        # hbbft_mesh_*: device-mesh collective accounting for the sharded
        # epoch phases (parallel/mesh.py keeps the same deterministic
        # plain-int counters as ops/rs.py; deltas fold here per scrape).
        # Zero on single-device runs — nonzero only when a node runs the
        # mesh-sharded epoch path.
        self._c_mesh_coll = self.registry.counter(
            "hbbft_mesh_collectives_total",
            "mesh-spanning collective launches by sharded epoch phase",
            labelnames=("phase",), max_label_sets=5)
        self._c_mesh_bytes = self.registry.counter(
            "hbbft_mesh_gather_bytes_total",
            "bytes returned by sharded-phase collectives (computed "
            "statically from array shapes, not traced)",
            labelnames=("phase",), max_label_sets=5)
        for ph in ("rbc", "aba", "coin", "decrypt"):
            self._c_mesh_coll.labels(phase=ph)
            self._c_mesh_bytes.labels(phase=ph)
        self._mesh_stats_last = _mesh.stats_snapshot()
        # hbbft_vid_*: dispersal/retrieval accounting.  The protocol and
        # service layers keep deterministic plain-int counters (both are
        # in hblint's determinism scope); scrapes fold the deltas here,
        # same pattern as the rs/mesh counters above.
        self._c_vid = None
        self._vid_stats_last: Dict[str, int] = {}
        if self._vid:
            self._c_vid = self.registry.counter(
                "hbbft_vid_events_total",
                "verifiable-information-dispersal events by kind "
                "(disperse / vote_cast / cert = proposer+voter side; "
                "retrieve / retrieved / retry / failure = requester "
                "side; shard_served / refusal / quota_drop = donor "
                "side; disperse_shed = best-effort dispersals skipped "
                "toward backlogged links; bad_shard / mismatch = "
                "Byzantine evidence; stray_shard / store_eviction = "
                "hygiene)",
                labelnames=("kind",), max_label_sets=16)
            for k in self._vid_stats():
                self._c_vid.labels(kind=k)
        self.registry.register_callback(self._refresh_gauges)
        # `is not None`, not `or`: Mempool defines __len__, so a freshly
        # configured (empty → falsy) instance would be silently replaced
        # by the default, discarding its max_tx_bytes sizing
        self.mempool = mempool if mempool is not None else Mempool()
        self.mempool.bind_registry(self.registry)
        # the oversized-frame drop in _dispatch is a last-resort guard,
        # not a config escape hatch: a proposal of batch_size max-size txs
        # must fit the wire blob cap with margin (TLV + TPKE overhead),
        # or an honest proposer could wedge its own epochs
        # (in VID mode proposals are constant-size commitments — the
        # payload travels as per-node shards of ~1/k its size — so
        # MB-scale batch shapes the classic check forbids are exactly
        # the point; the shard frames stay under the cap by design)
        batch_size = getattr(self.sq.algo, "batch_size", None)
        if batch_size is not None and not self._vid:
            worst = batch_size * (self.mempool.max_tx_bytes + 16)
            if worst > wire.MAX_BLOB_BYTES // 2:
                raise ValueError(
                    f"batch_size {batch_size} × max_tx_bytes "
                    f"{self.mempool.max_tx_bytes} = {worst}B can exceed "
                    f"half the wire blob cap ({wire.MAX_BLOB_BYTES}B): "
                    f"lower one of them (Mempool(max_tx_bytes=…))"
                )
        self.make_tx_input = make_tx_input
        self.replay_retain_epochs = replay_retain_epochs
        # bounded storage: a per-peer byte ceiling on the replay log that
        # truncates EARLIER than the epoch retention when a peer's
        # backlog grows fat (0 = epochs-only).  Truncated entries are
        # counted — a peer whose gap now exceeds what the log covers
        # recovers via snapshot state-sync instead of replay.
        self.replay_retain_bytes = int(replay_retain_bytes)
        self.flight_retain_batches = int(flight_retain_batches)
        self.on_batch = on_batch
        self.batches: List[Any] = []
        self.ledger_digest = b"\x00" * 32
        # era-boundary join snapshots: captured at every completed DKG
        # rotation, served to joiners over SYNC client frames
        self.sync_store = SnapshotStore(self.registry,
                                        chunk_bytes=sync_chunk_bytes)
        self.peer_addr_book = peer_addr_book
        # the digest chain is CHECKPOINTED, not unbounded: only the last
        # `digest_chain_retain` entries stay in memory; `chain_len` (the
        # total) and `ledger_digest` (the head) never truncate, and the
        # flight journal keeps the full per-batch record on disk
        self.digest_chain_retain = max(1, digest_chain_retain)
        self._digest_chain: List[str] = []
        self._digest_chain_offset = 0
        # black-box flight recorder (obs.flight): journals every message,
        # commit, fault, span and lifecycle event for offline forensics
        self.flight: Optional[FlightObserver] = None
        if flight_dir:
            recorder = FlightRecorder(
                flight_dir, node=repr(self.sq.our_id()),
                flavor="runtime", clock=time.time,
                max_segment_bytes=flight_max_segment_bytes,
                max_segments=flight_max_segments,
                registry=self.registry,
            )
            self.flight = FlightObserver(recorder)
            self.spans.sink = self.flight.record_span
        # snapshot state-sync activation: continue the ledger-digest
        # chain from the snapshot's era boundary instead of genesis.
        # The flight journal is seeded with the same position and notes
        # the boundary so the forensic auditor can verify the join
        # against the donors' chains (obs.audit).
        if ledger_seed is not None:
            head, chain_len = ledger_seed
            if len(head) != 32 or chain_len < 0:
                raise ValueError("ledger_seed must be (32-byte head, len)")
            self.ledger_digest = bytes(head)
            self._digest_chain_offset = int(chain_len)
            if self.flight is not None:
                self.flight.seed_chain(self.ledger_digest,
                                       self._digest_chain_offset)
                self.flight.on_note(
                    "statesync",
                    f"index={self._digest_chain_offset} "
                    f"head={self.ledger_digest.hex()}")
        # per-peer replay log of recently sent consensus messages, in send
        # order: the reinit_peer history (see module docstring).  Entries
        # are (key, message, payload) — the companion set dedups on
        # (key, payload) BYTES so reinit re-sends don't duplicate the log
        # (hashing the wire bytes is C-speed; hashing the frozen-dataclass
        # chains recursively was a measurable slice of _dispatch)
        self._replay: Dict[NodeId, List[Tuple[EpochKey, Any, bytes]]] = {}
        self._replay_seen: Dict[NodeId, set] = {}
        self._replay_bytes: Dict[NodeId, int] = {}
        self._clients: set = set()
        # transport authentication (see transport module security
        # model): the per-era keypairs the protocol already carries
        # become the handshake's WHO.  Wired whenever the wrapped stack
        # exposes a NetworkInfo; a bare test harness without one keeps
        # the legacy identification-only handshake, as does auth=False.
        self._cluster_id = bytes(cluster_id)
        self._era_keys: Optional[EraKeyRing] = None
        if auth and self._auth_netinfo() is not None:
            self._era_keys = EraKeyRing(self._era_key_provider,
                                        grace_s=auth_grace_s)
            transport_kwargs.setdefault("auth_sign", self._auth_sign)
            transport_kwargs.setdefault("auth_verify", self._auth_verify)
        self.transport = Transport(
            our_id=self.sq.our_id(),
            cluster_id=cluster_id,
            seed=seed,
            hello_key=self.current_key,
            on_peer_message=self._on_peer_message,
            on_peer_batch=self._on_peer_batch,
            on_peer_hello=self._on_peer_hello,
            on_client_frame=self._on_client_frame,
            on_client_gone=self._on_client_gone,
            trace=trace,
            cost_model=cost_model,
            registry=self.registry,
            peer_resolver=self._resolve_peer,
            **transport_kwargs,
        )
        # overload-defense wiring: the transport meters per-peer ingress
        # (frames admitted here retire in _process_peer_message), the
        # runtime reports decode-garbage strikes back to it, and every
        # guard escalation is journaled through the pump so the forensic
        # auditor can attribute the incident to the offending peer
        self.transport.ingress.track_inflight = True
        self.transport.ingress.on_event = self._on_guard_event
        self.sq.on_evict = self._on_senderq_evict
        # a shed tx was pump-enqueued at admission: pull it back out of
        # the protocol queue too, or every shed would grow the queue
        # past the mempool ceiling (an unproposed shed tx then truly
        # never commits; one already riding an open epoch still lands —
        # proposals cannot be recalled)
        self.mempool.on_shed = self._on_mempool_shed
        self._obs_server: Optional[ObsServer] = None
        self.obs_addr: Optional[Addr] = None
        # Always-on pump segment accounting: the env-gated
        # HBBFT_PUMP_TIMING accumulators' low-overhead production
        # sibling.  Observed once per pump iteration per segment
        # (aggregated within the iteration), so the cost is a handful of
        # perf_counter reads per batch, not per event — and the per-tx
        # critical path's pump-queue component (obs.critpath)
        # cross-checks against a live metric.
        self._h_pump_seg = self.registry.histogram(
            "hbbft_pump_segment_seconds",
            "seconds per pump segment per iteration (msg/input/hello/"
            "startup/guard/shed = event dispatch by kind; deferred = "
            "merged threshold-crypto drain; flush = coalesced egress "
            "writes; queue_wait = the iteration's max inbox wait; "
            "recv = transport frame receive)",
            labelnames=("segment",),
            buckets=(1e-5, 1e-4, 1e-3, 1e-2, 1e-1, 1.0),
            max_label_sets=12)
        self._pump_seg = {
            k: self._h_pump_seg.labels(segment=k)
            for k in ("msg", "input", "hello", "startup", "guard",
                      "shed", "deferred", "flush", "queue_wait", "recv")
        }
        # HBBFT_PUMP_TIMING=1: accumulate per-segment thread time in the
        # pump (perf diagnosis; dumped by run_node on shutdown)
        self._pump_timing: Optional[Dict[str, float]] = (
            {} if os.environ.get("HBBFT_PUMP_TIMING") else None
        )
        self.transport.timing = self._pump_timing
        self.transport.seg_recv = self._pump_seg["recv"].observe
        self._decode_cache: Dict[bytes, Any] = {}
        # HBBFT_PUMP_RECORD=<dir>: journal pump events as JSONL for
        # offline replay profiling (only with timing enabled)
        self._pump_record = None
        rec_dir = os.environ.get("HBBFT_PUMP_RECORD")
        if rec_dir and self._pump_timing is not None:
            os.makedirs(rec_dir, exist_ok=True)
            self._pump_record = open(
                os.path.join(rec_dir,
                             f"events-{self.sq.our_id()!r}.jsonl"), "w")
        # performance plane (obs/perf.py): always-on counter-snapshot
        # profiler + headroom model, sampled on the pump heartbeat
        # (pump_tick) and served at /perf.  Built BEFORE the controller
        # so the degradation ladder's raise arm can consume its measured
        # headroom as the slack signal.
        self.perf = PerfPlane(
            self.registry, self.sq.our_id(),
            pump_cpu_fn=lambda: self.pump.cpu_seconds,
            pump_stats_fn=lambda: (self.pump.iterations,
                                   self.pump.offloaded),
            record=(self.flight.recorder.record_perf
                    if self.flight is not None else None))
        # guard-driven adaptive degradation (net/degrade.py): shrink the
        # proposed batch size and mempool admission under sustained
        # guard pressure, restore when it clears — and, with a perf
        # plane measuring slack, raise toward the configured ceilings
        # under sustained benign headroom.  None when the wrapped
        # protocol exposes no batch size (nothing to degrade) or
        # degrade=False.
        self.degrade = (_attach_degrade(self, **(degrade_kwargs or {}))
                        if degrade else None)
        # live health plane (obs/watch.py): last locally-evaluated
        # health status, updated on the pump heartbeat so ok↔degraded
        # transitions are journaled as HealthIncident records from the
        # one thread allowed to append
        self._health_status = "ok"
        self._health_transitions = 0

    # -- transport authentication --------------------------------------------

    def _auth_netinfo(self):
        """The NetworkInfo carrying this era's keypairs, if the wrapped
        stack has one (DynamicHoneyBadger or plain HoneyBadger)."""
        dhb = self._inner_dhb()
        if dhb is not None:
            return dhb.netinfo
        return getattr(self._inner_hb(), "netinfo", None)

    def _era_key_provider(self) -> Tuple[int, Dict[NodeId, Any]]:
        """EraKeyRing source: the CURRENT era's plain public-key map —
        the same map the dynamic-peer resolver consults for membership."""
        era, _epoch = self.current_key()
        ni = self._auth_netinfo()
        return int(era), (dict(ni.public_key_map())
                          if ni is not None else {})

    def _auth_sign(self, cluster_id: bytes, nonce: bytes,
                   session: bytes) -> Tuple[int, bytes]:
        """Answer a handshake CHALLENGE: sign the transcript with this
        node's current per-era secret key (transport auth callback)."""
        ni = self._auth_netinfo()
        if ni is None:
            raise framing.FrameError(
                "challenged but this node carries no era keypair")
        era, _epoch = self.current_key()
        transcript = framing.auth_transcript(
            cluster_id, nonce, session, self.our_id(),
            framing.ROLE_NODE, int(era))
        return int(era), ni.secret_key().sign(transcript).to_bytes()

    def _auth_verify(self, node_id: NodeId, role: int, era: int,
                     sig_bytes: bytes, nonce: bytes,
                     session: bytes) -> str:
        """Judge an inbound handshake proof (transport auth callback):
        ``ok`` / ``stale`` (previous-era key inside the rotation grace
        window, or an honest-but-behind era claim under a current key)
        / ``bad_sig`` / ``unknown_key``."""
        from hbbft_tpu.crypto import tc

        try:
            sig = tc.Signature.from_bytes(bytes(sig_bytes))
            transcript = framing.auth_transcript(
                self._cluster_id, nonce, session, node_id, role,
                int(era))
        # hblint: disable=fault-swallowed-drop (the verdict return IS
        # the accounting: the transport counts every non-ok verdict
        # under hbbft_guard_auth_failures_total{reason=...} and
        # journals the endpoint)
        except (ValueError, IndexError, framing.FrameError):
            # IndexError: pairing libs raise it on empty/truncated
            # signature blobs rather than ValueError
            return "bad_sig"
        candidates = self._era_keys.lookup(node_id)
        if not candidates:
            return "unknown_key"
        era_matched = False
        for cand_era, key, stale in candidates:
            if cand_era != era:
                continue
            era_matched = True
            if key.verify(sig, transcript):
                return "stale" if stale else "ok"
        if not era_matched:
            # an honest peer behind on rotations signs its own (older)
            # era view; a signature by a CURRENT-map key still proves
            # key possession — admit as stale (counted), or a restarted
            # validator could never reconnect.  A revoked key holder
            # still fails: its key is in no admissible map.
            for _cand_era, key, stale in candidates:
                if not stale and key.verify(sig, transcript):
                    return "stale"
        return "bad_sig"

    def pump_tick(self) -> None:
        """Periodic pump heartbeat (between iterations, serialized with
        pump_process): drives the degradation controller so engage AND
        recovery both proceed whether the node is busy or idle.  VID
        retrieval retries are enqueued as a pump event rather than run
        here — the tick has no _PumpOutcome to absorb Steps into."""
        # sample the perf plane FIRST: the controller's raise arm reads
        # the headroom this tick just measured, not last tick's
        self.perf.maybe_sample()
        if self.degrade is not None:
            self.degrade.tick()
        if self._retrieve is not None and self._retrieve.pending_count():
            deadline = self._retrieve.next_deadline()
            if deadline is not None and time.time() >= deadline:
                self.pump.enqueue("vid_tick")
        self._health_tick()

    def _health_issues(self) -> List[str]:
        """Locally-observable health problems, cheap enough for every
        heartbeat: the degradation controller being engaged, and the
        mempool running at ≥90% of its admission capacity."""
        issues: List[str] = []
        if self.degrade is not None and self.degrade.level:
            issues.append("degrade_active")
        cap = self.mempool.capacity
        if cap and len(self.mempool) * 10 >= cap * 9:
            issues.append("mempool_pressure")
        return issues

    def _health_tick(self) -> None:
        """Journal one HealthIncident per local ok↔degraded transition
        (pump thread — the only thread allowed to append).  Transitions,
        not levels: a sustained degrade writes one record when it
        engages and one when it recovers, never one per heartbeat."""
        issues = self._health_issues()
        status = "degraded" if issues else "ok"
        if status == self._health_status:
            return
        prev, self._health_status = self._health_status, status
        self._health_transitions += 1
        if self.flight is not None:
            me = repr(self.our_id())
            self.flight.recorder.record_incident(
                "local_health",
                "warn" if status == "degraded" else "info", me,
                f"local_health:{me}:{self._health_transitions}",
                f"{prev}->{status}"
                + (f": {','.join(issues)}" if issues else ""))

    def _vid_note(self, kind: str, detail: str) -> None:
        """RetrieveService loudness sink → flight journal (the service's
        methods only ever run on the pump thread, where appends are
        allowed)."""
        if self.flight is not None:
            self.flight.on_note(kind, detail)

    def _shed_for_disperse(
        self, root: bytes, peer_ids: List[NodeId]
    ) -> "FrozenSet[Any]":
        """The (possibly empty) set of peers to skip for this root's
        dispersal frames — see :func:`pick_shed_peers` for the policy.
        Budget is ``f``: with our own vote plus the other ``n − 1 − f``
        peers still served, the ``n − f`` cert threshold stays reachable
        even if every shed peer never sees the shard."""
        f = len(peer_ids) // 3  # n = peers + 1, so f = (n − 1) // 3
        if f <= 0:
            return frozenset()
        roots = self._vid_shed_roots
        already = roots.get(root, frozenset())
        backlogs = {p: self.transport.send_backlog_s(p) for p in peer_ids}
        shed = pick_shed_peers(
            backlogs, self.vid_shed_backlog_s, f, already)
        roots[root] = shed
        roots.move_to_end(root)
        while len(roots) > 64:
            roots.popitem(last=False)
        return shed

    def _vid_stats(self) -> Dict[str, int]:
        """The VID layers' deterministic plain-int counters, keyed by
        the ``hbbft_vid_events_total`` kind label."""
        d = self.sq.algo.disperser
        s = self._retrieve
        return {
            "disperse": d.disperses,
            "vote_cast": d.votes_cast,
            "cert": d.certs,
            "retrieve": s.retrieves,
            "retrieved": s.retrieved,
            "shard_served": s.served,
            "refusal": s.refusals,
            "quota_drop": s.quota_drops,
            "bad_shard": s.shards_bad,
            "mismatch": s.mismatches,
            "retry": s.retries,
            "failure": s.failures,
            "stray_shard": s.stray_shards,
            "store_eviction": self.sq.algo.store.evictions,
            "disperse_shed": self._vid_sheds,
        }

    # -- observability -------------------------------------------------------
    #
    # The pre-registry integer attributes survive as thin counter-backed
    # views (MetricAttr descriptors) so existing call sites — status_doc
    # consumers, tests — keep working; the registry is the single source
    # of truth.

    committed_txs = MetricAttr("_c_committed")
    decode_failures = MetricAttr("_c_decode")
    send_failures = MetricAttr("_c_send_fail")
    replay_gaps = MetricAttr("_c_replay_gaps")

    @property
    def digest_chain(self) -> List[str]:
        """The RETAINED tail of the ledger-digest chain (see
        :attr:`digest_chain_offset` for where it starts)."""
        return self._digest_chain

    @property
    def digest_chain_offset(self) -> int:
        return self._digest_chain_offset

    @property
    def chain_len(self) -> int:
        """Total batches folded into the digest chain (never truncates)."""
        return self._digest_chain_offset + len(self._digest_chain)

    @property
    def faults_observed(self) -> int:
        return int(self._c_faults.total())

    def _refresh_gauges(self) -> None:
        """Derived-state gauges, refreshed on every scrape: consensus
        position, ledger length, connection health, and the replay/catch-up
        surfaces PR 2 only logged — replay-log depth and each peer's
        last-acked (era, epoch) — now scrapeable instead of grep-able."""
        r = self.registry
        for backend, cur in _rs.stats_snapshot().items():
            last = self._rs_stats_last.get(backend, {})
            d_calls = cur["calls"] - last.get("calls", 0)
            d_bytes = cur["bytes"] - last.get("bytes", 0)
            if d_calls > 0:
                self._c_rbc_calls.labels(backend=backend).inc(d_calls)
            if d_bytes > 0:
                self._c_rbc_bytes.labels(backend=backend).inc(d_bytes)
            self._rs_stats_last[backend] = dict(cur)
        for ph, cur in _mesh.stats_snapshot().items():
            last = self._mesh_stats_last.get(ph, {})
            d_coll = cur["collectives"] - last.get("collectives", 0)
            d_bytes = cur["gather_bytes"] - last.get("gather_bytes", 0)
            if d_coll > 0:
                self._c_mesh_coll.labels(phase=ph).inc(d_coll)
            if d_bytes > 0:
                self._c_mesh_bytes.labels(phase=ph).inc(d_bytes)
            self._mesh_stats_last[ph] = dict(cur)
        if self._c_vid is not None:
            cur = self._vid_stats()
            for k, v in cur.items():
                delta = v - self._vid_stats_last.get(k, 0)
                if delta > 0:
                    self._c_vid.labels(kind=k).inc(delta)
            self._vid_stats_last = cur
            r.gauge("hbbft_vid_store_bytes",
                    "bytes held by the bounded LRU shard store").set(
                        self.sq.algo.store.bytes)
            r.gauge("hbbft_vid_pending_retrievals",
                    "committed commitments whose payload retrieval is "
                    "still in flight").set(self._retrieve.pending_count())
        era, epoch = self.current_key()
        r.gauge("hbbft_node_era", "current consensus era").set(era)
        r.gauge("hbbft_node_epoch", "current epoch within the era").set(epoch)
        r.gauge("hbbft_node_batches", "batches committed so far").set(
            len(self.batches))
        r.gauge("hbbft_node_peers_connected",
                "peers with a live outbound connection").set(sum(
                    1 for p in self.transport.peer_ids()
                    if self.transport.connected(p)))
        # pipelining health: how many epochs this node currently keeps
        # open concurrently, and how deep the pump's event backlog is
        hb = self._inner_hb()
        r.gauge("hbbft_node_epochs_in_flight",
                "epochs with live in-flight consensus state "
                "(> 1 means the pipeline is engaged)").set(
                    len(hb.epochs) if hb is not None else 0)
        r.gauge("hbbft_node_pump_backlog",
                "events queued for the step pump").set(self.pump.pending())
        g_replay = r.gauge(
            "hbbft_node_replay_log_entries",
            "retained replay-log messages per peer", labelnames=("peer",))
        # list() snapshots: the pump's worker thread mutates these dicts
        # concurrently with a scrape
        for peer, entries in list(self._replay.items()):
            g_replay.labels(peer=repr(peer)).set(len(entries))
        g_pera = r.gauge(
            "hbbft_node_peer_era",
            "last (era, epoch) each peer announced: era part",
            labelnames=("peer",))
        g_pep = r.gauge(
            "hbbft_node_peer_epoch",
            "last (era, epoch) each peer announced: epoch part",
            labelnames=("peer",))
        for peer, (p_era, p_epoch) in list(self.sq.peer_epochs.items()):
            if peer == self.our_id():
                continue
            g_pera.labels(peer=repr(peer)).set(p_era)
            g_pep.labels(peer=repr(peer)).set(p_epoch)
        # overload-defense gauges: every budgeted buffer's depth, per
        # peer — the "pinned under its cap" witnesses the chaos cells
        # (and operators) assert on
        g_sqb = r.gauge(
            "hbbft_guard_senderq_buffered",
            "SenderQueue backlog entries held for each peer "
            "(capped at buffered_cap; overflow front-chops, counted)",
            labelnames=("peer",), max_label_sets=33)
        for peer, entries in list(self.sq.buffered.items()):
            g_sqb.labels(peer=repr(peer)).set(len(entries))
        g_aba = r.gauge(
            "hbbft_guard_aba_future_buffered",
            "largest per-sender ABA future-epoch buffer across live "
            "agreement instances (capped at future_cap_per_sender)",
            labelnames=("peer",), max_label_sets=33)
        for peer, depth in self._aba_future_depths().items():
            g_aba.labels(peer=repr(peer)).set(depth)

    def _aba_future_depths(self) -> Dict[NodeId, int]:
        """max per-sender future-buffer depth over live BA instances."""
        out: Dict[NodeId, int] = {}
        hb = self._inner_hb()
        if hb is None:
            return out
        try:
            for state in list(hb.epochs.values()):
                for prop in list(state.subset.proposals.values()):
                    per: Dict[NodeId, int] = {}
                    for sender, _msg in list(prop.agreement.future):
                        per[sender] = per.get(sender, 0) + 1
                    for sender, n in per.items():
                        if n > out.get(sender, 0):
                            out[sender] = n
        # hblint: disable=fault-swallowed-drop (nothing is dropped: this
        # is a best-effort gauge sample racing the pump thread's
        # mutations; the next scrape re-reads the live state)
        except RuntimeError:
            pass
        return out

    def _inner_hb(self):
        """The innermost HoneyBadger of the wrapped stack, if any."""
        algo = self.sq.algo
        dhb = getattr(algo, "dhb", algo)
        return getattr(dhb, "hb", dhb if isinstance(dhb, HoneyBadger)
                       else None)

    def _inner_dhb(self):
        """The DynamicHoneyBadger of the wrapped stack, if any."""
        algo = self.sq.algo
        dhb = getattr(algo, "dhb", algo)
        return dhb if isinstance(dhb, DynamicHoneyBadger) else None

    def _resolve_peer(self, node_id: NodeId) -> Optional[Addr]:
        """Transport hook: may an unknown node-role hello join the peer
        set, and at what address?  Membership is consensus state — a
        node the current era's validator map names (e.g. one voted in by
        a DHB rotation) is accepted, everyone else stays rejected.  The
        address comes from the deployment's address book
        (config-derived ports for the shipped cluster tooling)."""
        if self.peer_addr_book is None or node_id == self.our_id():
            return None
        dhb = self._inner_dhb()
        if dhb is None or node_id not in dhb.netinfo.public_key_map():
            return None
        return self.peer_addr_book(node_id)

    async def start_obs(self, host: str = "127.0.0.1",
                        port: int = 0) -> Addr:
        """Serve ``/metrics``, ``/status``, ``/spans``, ``/flight``,
        ``/trace``, ``/health``, ``/perf`` (see obs.http)."""
        self._obs_server = ObsServer(
            self.registry,
            status_fn=self.status_doc,
            spans_fn=self.spans.export_jsonl,
            flight_fn=(self.flight.recorder.tail_jsonl
                       if self.flight is not None else None),
            trace_fn=(self.flight.recorder.trace_jsonl
                      if self.flight is not None else None),
            health_fn=self.health_doc,
            perf_fn=self.perf.perf_doc,
        )
        self.obs_addr = await self._obs_server.start(host, port)
        return self.obs_addr

    # -- lifecycle -----------------------------------------------------------

    def our_id(self) -> NodeId:
        return self.sq.our_id()

    def current_key(self) -> EpochKey:
        return _algo_key(self.sq.algo)

    def _enable_deferred_crypto(self) -> None:
        """Flip the wrapped protocol stack into deferred threshold-decrypt
        verification (see ``HoneyBadger.defer_decrypt``)."""
        algo = self.sq.algo
        dhb = getattr(algo, "dhb", None)
        if dhb is None and isinstance(algo, DynamicHoneyBadger):
            dhb = algo
        if dhb is not None:
            dhb.defer_decrypt_verify = True
            dhb.hb.defer_decrypt = True
        elif isinstance(algo, HoneyBadger):
            algo.defer_decrypt = True

    async def start(self, host: str = "127.0.0.1", port: int = 0) -> Addr:
        addr = await self.transport.listen(host, port)
        if self.pump.task is None:
            self.pump.start()
        return addr

    def connect(self, peer_addrs: Dict[NodeId, Addr]) -> None:
        """Add peers and announce our epoch (SenderQueue startup)."""
        for peer_id, addr in peer_addrs.items():
            if peer_id != self.our_id():
                self.transport.add_peer(peer_id, addr)
        self.pump.enqueue("startup")

    async def stop(self) -> None:
        if self._obs_server is not None:
            await self._obs_server.stop()
        await self.pump.stop()
        await self.transport.stop()
        if self.flight is not None:
            self.flight.close()
        if self._pump_record is not None:
            self._pump_record.close()

    def flight_crash(self, exc: BaseException) -> None:
        """Crash-dump flush: journal the fatal error and force the
        journal to disk before the process dies (the note/flush path is
        what makes a SIGKILL-adjacent crash auditable)."""
        if self.flight is not None:
            self.flight.on_note("crash", repr(exc))
            self.flight.recorder.flush()

    # -- ingress (event-loop side): everything protocol-touching enqueues ----

    def submit_tx(self, tx: bytes) -> int:
        """Local admission (same path as a client TX frame)."""
        t_ingress = time.time()
        status = self.mempool.add(tx, client_id="_local")
        if status == Mempool.ACCEPTED:
            self.pump.enqueue("input", self.make_tx_input(tx),
                              t_ingress, "_local")
        return status

    def _on_peer_message(self, peer_id: NodeId, payload: bytes) -> None:
        self.pump.enqueue("msg", peer_id, payload)

    def _on_peer_batch(self, peer_id: NodeId, items: List[Any]) -> None:
        """Batch-handle fast path (event loop side): one transport chunk
        — every MSG payload and MSG_BATCH sub-message it carried — is
        ONE pump enqueue, so the pump sees one event per chunk instead
        of one per message.  ``items`` are raw payload bytes, or
        ``(payload, decoded_msg_or_None)`` pairs when ingress worker
        threads pre-decode off the loop."""
        self.pump.enqueue("msgs", peer_id, items)

    def _on_guard_event(self, kind: str, peer_id: NodeId,
                        detail: str) -> None:
        """Transport ingress-guard escalations (event loop side): queue
        them through the pump so the journal append — which the pump's
        worker thread owns — stays single-threaded."""
        self.pump.enqueue("guard", kind, peer_id, detail)

    def _on_mempool_shed(self, tx: bytes) -> None:
        self.pump.enqueue("shed", tx)

    def _on_senderq_evict(self, peer_id: NodeId, n: int) -> None:
        """SenderQueue backlog eviction (pump thread): count and
        journal, attributing the overflow to the backlogged peer."""
        self._c_sq_evict.labels(peer=repr(peer_id)).inc(n)
        if self.flight is not None:
            self.flight.on_note(
                "guard", f"kind=senderq_evict peer={peer_id!r} n={n}")

    def _on_peer_hello(self, peer_id: NodeId, hello, direction: str) -> None:
        # ordering with the peer's subsequent messages is preserved by the
        # FIFO inbox (the hello is enqueued before any MSG frame that
        # follows it on the socket)
        self.pump.enqueue("hello", peer_id, hello)

    # -- pump worker (single thread; the only place protocol state mutates) --

    def pump_process(self, events, depth: int) -> _PumpOutcome:
        """One pump iteration: run ``events`` through the protocol, drain
        the cross-epoch deferred crypto, top up the epoch pipeline, prune
        the replay log once.  Runs on the pump's worker thread."""
        if self.step_delay_s > 0:
            time.sleep(self.step_delay_s)
        out = _PumpOutcome()
        self._out = out
        t_cpu = time.thread_time()
        timing = self._pump_timing
        pc = time.perf_counter
        segs: Dict[str, float] = {}
        t_iter = pc()
        # queue_wait: the iteration's max inbox wait — how long the
        # oldest event of this batch sat parked before the pump got to
        # it (events are (kind, args, t_enq) 3-tuples since the
        # scheduler started stamping them; bare 2-tuples from direct
        # pump_process callers still work)
        max_wait = 0.0
        for ev in events:
            if len(ev) > 2 and t_iter - ev[2] > max_wait:
                max_wait = t_iter - ev[2]
        try:
            if timing is not None:
                self._pump_process_timed(events, depth, timing, segs)
            else:
                for ev in events:
                    kind, args = ev[0], ev[1]
                    t0 = pc()
                    if kind == "msg":
                        self._process_peer_message(*args)
                    elif kind == "msgs":
                        self._process_peer_batch(*args)
                    elif kind == "input":
                        self._process_input(*args)
                    elif kind == "hello":
                        self._process_peer_hello(*args)
                    elif kind == "startup":
                        self._absorb(self.sq.startup_step())
                    elif kind == "guard":
                        self._process_guard_event(*args)
                    elif kind == "shed":
                        self._process_shed(args[0])
                    elif kind == "vid_tick":
                        self._absorb(self._retrieve.tick(time.time()))
                    else:  # pragma: no cover - enqueue() callers are local
                        raise ValueError(f"unknown pump event {kind!r}")
                    # batch-handle events ("msgs") are the same dispatch
                    # work as "msg" — fold them into one segment so the
                    # hot path stays visible to the perf plane
                    sk = "msg" if kind == "msgs" else kind
                    segs[sk] = segs.get(sk, 0.0) + (pc() - t0)
                t0 = pc()
                self._drain_deferred()
                if depth > 1:
                    self._absorb(self.sq.handle_input(PipelineInput(depth)))
                    self._drain_deferred()
                segs["deferred"] = segs.get("deferred", 0.0) + (pc() - t0)
            self._prune_replay()
        finally:
            out.cpu_s = time.thread_time() - t_cpu
            self._out = None
        children = self._pump_seg
        for k, v in segs.items():
            child = children.get(k)
            if child is not None:
                child.observe(v)
        if events:
            children["queue_wait"].observe(max_wait)
        return out

    def _pump_process_timed(self, events, depth: int, timing,
                            segs: Dict[str, float]) -> None:
        """``HBBFT_PUMP_TIMING`` variant of the iteration body: same
        semantics, with per-segment thread-time accumulators (decode /
        protocol / spans / dispatch split inside _process_peer_message is
        approximated by timing that call whole).  ``segs`` receives the
        wall-clock per-kind split so the always-on
        ``hbbft_pump_segment_seconds`` histogram stays populated in this
        mode too."""
        rec = self._pump_record
        if rec is not None:
            for ev in events:
                kind, args = ev[0], ev[1]
                if kind == "msg":
                    rec.write('["msg",%d,"%s"]\n'
                              % (args[0], args[1].hex()))
                elif kind == "msgs":
                    # journal a batch as its per-message lines so replay
                    # profiling stays format-compatible
                    for it in args[1]:
                        p = it[0] if type(it) is tuple else it
                        rec.write('["msg",%d,"%s"]\n'
                                  % (args[0], p.hex()))
                elif kind == "input":
                    tx = getattr(args[0], "tx", None)
                    if tx is not None:
                        rec.write('["input","%s"]\n' % tx.hex())
        tt = time.thread_time
        pc = time.perf_counter
        for ev in events:
            kind, args = ev[0], ev[1]
            t0 = tt()
            w0 = pc()
            if kind == "msg":
                self._process_peer_message(*args)
            elif kind == "msgs":
                self._process_peer_batch(*args)
            elif kind == "input":
                self._process_input(*args)
            elif kind == "hello":
                self._process_peer_hello(*args)
            elif kind == "startup":
                self._absorb(self.sq.startup_step())
            elif kind == "guard":
                self._process_guard_event(*args)
            elif kind == "shed":
                self._process_shed(args[0])
            elif kind == "vid_tick":
                self._absorb(self._retrieve.tick(time.time()))
            else:  # pragma: no cover - enqueue() callers are local
                raise ValueError(f"unknown pump event {kind!r}")
            timing[kind] = timing.get(kind, 0.0) + (tt() - t0)
            timing["n_" + kind] = timing.get("n_" + kind, 0.0) + 1
            sk = "msg" if kind == "msgs" else kind
            segs[sk] = segs.get(sk, 0.0) + (pc() - w0)
        t0 = tt()
        w0 = pc()
        self._drain_deferred()
        if depth > 1:
            self._absorb(self.sq.handle_input(PipelineInput(depth)))
            self._drain_deferred()
        timing["deferred"] = timing.get("deferred", 0.0) + (tt() - t0)
        segs["deferred"] = segs.get("deferred", 0.0) + (pc() - w0)

    def _drain_deferred(self) -> None:
        """Resolve every parked threshold-decrypt verification — ONE
        merged MSM/pairing call per round via crypto.batch — looping while
        resolutions cascade into new threshold crossings."""
        guard = 0
        while self.sq.has_deferred():
            self._absorb(self.sq.resolve_deferred())
            guard += 1
            if guard > 64:  # pragma: no cover - each round consumes jobs
                logger.error("deferred-crypto drain did not settle")
                break

    def pump_flush(self, out: _PumpOutcome) -> None:
        """Apply one iteration's side effects on the event loop: coalesced
        MSG/MSG_BATCH frames per peer, then client commit pushes."""
        timing = self._pump_timing
        w0 = time.perf_counter()
        if timing is not None:
            t0 = time.thread_time()
            self._pump_flush_body(out)
            timing["flush"] = (
                timing.get("flush", 0.0) + (time.thread_time() - t0))
        else:
            self._pump_flush_body(out)
        self._pump_seg["flush"].observe(time.perf_counter() - w0)

    def _pump_flush_body(self, out: _PumpOutcome) -> None:
        for dest, payloads in out.frames.items():
            try:
                self.transport.send_payloads(dest, payloads)
            except KeyError:
                # a target that is not a transport peer (e.g. an observer
                # known only to the SenderQueue) has nowhere to go yet
                self.send_failures += 1
                logger.warning("no transport peer for %r: dropped %d "
                               "payloads", dest, len(payloads))
        if out.frames_delayed:
            loop = asyncio.get_running_loop()
            for dest, payloads in out.frames_delayed.items():
                loop.call_later(self.aba_out_delay_s, self._send_shaped,
                                dest, payloads)
        for era, epoch, digests in out.commits:
            self._notify_commit(era, epoch, digests)
        for digest in out.sheds:
            # ACK_SHED push: every client sees it; only the one holding
            # the digest's commit waiters reacts (the others ignore it)
            for conn in list(self._clients):
                conn.send(framing.TX_ACK,
                          bytes([framing.ACK_SHED]) + digest)
                if conn.closed:
                    self._clients.discard(conn)

    def _send_shaped(self, dest: NodeId, payloads: List[bytes]) -> None:
        try:
            self.transport.send_payloads(dest, payloads)
        except KeyError:
            self.send_failures += 1
            logger.warning("no transport peer for %r: dropped %d shaped "
                           "payloads", dest, len(payloads))

    def _process_input(self, inp: Any, t_ingress: Optional[float] = None,
                       client: str = "") -> None:
        """A mempool-admitted input (pump thread): journal its per-tx
        ``ingress`` (event-loop admission time, captured at the mempool
        add) and ``queued`` (now: the pump dequeued it) trace stages —
        the journal append itself stays on the pump thread, the one
        place appends are allowed — then feed the protocol."""
        if self.flight is not None:
            tx = getattr(inp, "tx", None)
            if isinstance(tx, (bytes, bytearray)):
                tid = trace_id(bytes(tx))
                era, epoch = self.current_key()
                self.flight.recorder.record_trace(
                    "ingress", era, epoch, tid, detail=client,
                    t=t_ingress)
                self.flight.recorder.record_trace(
                    "queued", era, epoch, tid, t=time.time())
        self._absorb(self.sq.handle_input(inp))

    def _process_guard_event(self, kind: str, peer_id: NodeId,
                             detail: str) -> None:
        """Journal a transport guard escalation (pump thread — the one
        place journal appends are allowed)."""
        if self.flight is not None:
            self.flight.on_note("guard",
                                f"kind={kind} peer={peer_id!r} {detail}")

    def _process_shed(self, tx: bytes) -> None:
        """A mempool shed (pump thread): drop the tx from the protocol
        queue so the shed frees consensus-side memory too, not just
        mempool bookkeeping — and queue the client push notification
        (written by pump_flush on the event loop).  The notification is
        DEFINITIVE: it is suppressed when the tx was no longer in the
        queue or is riding a not-yet-committed proposal (a proposal
        cannot be recalled, so such a tx may still commit — the client
        must not be told it never will)."""
        tx = bytes(tx)
        queue = getattr(self.sq.algo, "queue", None)
        if queue is None:
            return
        removed = queue.remove_multiple({tx})
        in_flight = getattr(self.sq.algo, "in_flight_txs", None)
        riding = in_flight is not None and tx in in_flight()
        if removed and not riding and self._out is not None:
            self._out.sheds.append(tx_digest(tx))

    def _process_peer_message(self, peer_id: NodeId, payload: bytes) -> None:
        self.transport.ingress.frame_done(peer_id)
        timing = self._pump_timing
        t0 = time.thread_time() if timing is not None else 0.0
        # Decode memo: wire messages are frozen/immutable, and much of an
        # epoch's traffic is byte-identical payloads from different peers
        # (Ready/BVal/Aux/Conf/Term broadcasts carry no sender field), so
        # sharing the decoded object is safe and skips the full TLV walk
        # for ~half the messages.  Bounded: cleared wholesale at the cap,
        # so a Byzantine payload flood costs reruns, not memory.
        cache = self._decode_cache
        msg = cache.get(payload)
        if msg is not None and timing is not None:
            timing["n_dec_hit"] = timing.get("n_dec_hit", 0) + 1
        if msg is None:
            try:
                msg = wire.decode_message(payload)
            except ValueError as exc:
                self.decode_failures += 1
                self.transport.ingress.decode_strike(peer_id)
                logger.warning("undecodable message from %r: %s",
                               peer_id, exc)
                return
            if len(cache) >= 4096:
                cache.clear()
            cache[payload] = msg
        if not isinstance(msg, (AlgoMessage, EpochStarted)):
            # runtime-level VID retrieval traffic rides the same sockets
            # but never enters the SenderQueue: route it to the retrieve
            # service (whose Steps absorb exactly like protocol steps)
            if self._retrieve is not None and isinstance(
                    msg, (VidRetrieve, VidShard)):
                if self.flight is not None:
                    self.flight.on_message(peer_id, msg,
                                           payload=bytes(payload))
                self._process_vid_direct(peer_id, msg)
                return
            self.decode_failures += 1
            self.transport.ingress.decode_strike(peer_id)
            logger.warning("non-sender-queue message %s from %r",
                           type(msg).__name__, peer_id)
            return
        if timing is not None:
            t1 = time.thread_time()
            timing["m_decode"] = timing.get("m_decode", 0.0) + (t1 - t0)
        self.spans.on_message(peer_id, msg)
        if self.flight is not None:
            self.flight.on_message(peer_id, msg, payload=bytes(payload))
        if timing is not None:
            t2 = time.thread_time()
            timing["m_spans"] = timing.get("m_spans", 0.0) + (t2 - t1)
        try:
            step = self.sq.handle_message(peer_id, msg)
        except TypeError as exc:
            # decodable but protocol-unexpected (e.g. AlgoMessage wrapping
            # a bare ReadyMsg): Byzantine input at the network boundary —
            # count it, keep the connection and the loop alive (the
            # guard's strike ladder disconnects a sustained stream)
            self.decode_failures += 1
            self.transport.ingress.decode_strike(peer_id)
            logger.warning("protocol-rejected message from %r: %s",
                           peer_id, exc)
            return
        if timing is not None:
            t3 = time.thread_time()
            timing["m_handle"] = timing.get("m_handle", 0.0) + (t3 - t2)
            self._absorb(step)
            timing["m_absorb"] = (
                timing.get("m_absorb", 0.0) + (time.thread_time() - t3))
            return
        self._absorb(step)

    def _process_peer_batch(self, peer_id: NodeId,
                            items: List[Any]) -> None:
        """One transport chunk's payloads as ONE pump unit: a single
        in-flight retire, one :meth:`SenderQueue.handle_message_batch`
        call merging the per-message Steps, one ``_absorb`` (one
        spans/flight step pass, with ``_dispatch``'s broadcast-encode
        cache shared across the whole batch).  Per-item error handling
        matches :meth:`_process_peer_message` exactly — an undecodable,
        non-sender-queue, or protocol-rejected item strikes THIS peer
        and is skipped, never voiding the rest of the batch — and the
        handle order is the socket order, so ledgers are byte-identical
        with the per-message path."""
        self.transport.ingress.frame_done(peer_id, len(items))
        timing = self._pump_timing
        t0 = time.thread_time() if timing is not None else 0.0
        cache = self._decode_cache
        strike = self.transport.ingress.decode_strike
        msgs: List[Any] = []
        payloads: Dict[int, bytes] = {}
        for item in items:
            if type(item) is tuple:
                # ingress-worker pre-decoded (payload, msg|None) pair
                payload, msg = item
            else:
                payload, msg = item, None
            if msg is None:
                msg = cache.get(payload)
                if msg is None:
                    try:
                        msg = wire.decode_message(payload)
                    except ValueError as exc:
                        self.decode_failures += 1
                        strike(peer_id)
                        logger.warning("undecodable message from %r: %s",
                                       peer_id, exc)
                        continue
                    if len(cache) >= 4096:
                        cache.clear()
                    cache[payload] = msg
                elif timing is not None:
                    timing["n_dec_hit"] = timing.get("n_dec_hit", 0) + 1
            if not isinstance(msg, (AlgoMessage, EpochStarted)):
                if self._retrieve is not None and isinstance(
                        msg, (VidRetrieve, VidShard)):
                    # handled inline: retrieval traffic is ordering-
                    # independent of the consensus messages around it
                    if self.flight is not None:
                        self.flight.on_message(peer_id, msg,
                                               payload=payload)
                    self._process_vid_direct(peer_id, msg)
                    continue
                self.decode_failures += 1
                strike(peer_id)
                logger.warning("non-sender-queue message %s from %r",
                               type(msg).__name__, peer_id)
                continue
            # keep the wire payload beside the message: the flight
            # journal records it verbatim, skipping a re-encode (the
            # decode cache may hand back one msg object for identical
            # payloads — same bytes either way)
            payloads[id(msg)] = payload
            msgs.append(msg)
        if timing is not None:
            t1 = time.thread_time()
            timing["m_decode"] = timing.get("m_decode", 0.0) + (t1 - t0)
        if not msgs:
            return
        spans = self.spans
        flight = self.flight

        def pre(msg):
            spans.on_message(peer_id, msg)
            if flight is not None:
                flight.on_message(peer_id, msg,
                                  payload=payloads.get(id(msg)))

        def on_error(msg, exc):
            # decodable but protocol-unexpected: Byzantine input at the
            # network boundary — count it, keep connection + batch alive
            self.decode_failures += 1
            strike(peer_id)
            logger.warning("protocol-rejected message from %r: %s",
                           peer_id, exc)

        step = self.sq.handle_message_batch(peer_id, msgs, pre=pre,
                                            on_error=on_error)
        if timing is not None:
            t2 = time.thread_time()
            timing["m_handle"] = timing.get("m_handle", 0.0) + (t2 - t1)
            self._absorb(step)
            timing["m_absorb"] = (
                timing.get("m_absorb", 0.0) + (time.thread_time() - t2))
            return
        self._absorb(step)

    def _process_peer_hello(self, peer_id: NodeId, hello) -> None:
        # A hello means a (re)connection: whatever we previously drained
        # into a socket for this peer may have died in TCP buffers, and a
        # below-record key means it restarted outright (possibly from
        # (0, 0)).  At-least-once, uniformly: (re)set its sender-queue
        # record to the announced key and replay the retained log from
        # there — entries below the key are obsolete at the peer, resent
        # duplicates above it are protocol no-ops.  On a clean first
        # connect the log is empty and this degrades to registering the
        # peer and exchanging EpochStarted.
        key = hello.key
        cur = self.sq.peer_epochs.get(peer_id)
        history = [
            (k, m) for k, m, _p in self._replay.get(peer_id, [])
            if k >= key
        ]
        if history or (cur is not None and key < cur):
            logger.info("peer %r reconnected at %r (recorded %r): "
                        "replaying %d retained messages through the "
                        "sender queue", peer_id, key, cur, len(history))
        # retention check: if the oldest retained entry is already beyond
        # the peer's delivery window, nothing we replay is deliverable and
        # the peer can never announce progress — it is wedged, not merely
        # catching up.  Surface that loudly instead of stalling silently
        # (remedy: restart the peer from a snapshot, or raise
        # replay_retain_epochs).
        window = _algo_window(self.sq.algo)
        if history and min(e[0] for e in history) > (key[0],
                                                     key[1] + window):
            self.replay_gaps += 1
            if self.flight is not None:
                self.flight.on_note(
                    "replay_gap",
                    f"peer={peer_id!r} announced={key!r} "
                    f"oldest_retained={min(e[0] for e in history)!r}")
            logger.error(
                "peer %r announced %r but the replay log only reaches "
                "back to %r (> window %d): retention does not cover its "
                "gap; it cannot catch up from here",
                peer_id, key, min(e[0] for e in history), window,
            )
        self._absorb(self.sq.reinit_peer(peer_id, key, history))

    def _absorb(self, step: Step) -> None:
        try:
            for fault in step.fault_log:
                self._c_faults.labels(kind=fault.kind.name).inc()
                name = fault.kind.name
                if name == "FutureEpochFlood":
                    self._c_proto_drops.labels(kind="hb_future").inc()
                elif name == "SubsetMessageFlood":
                    self._c_proto_drops.labels(kind="subset").inc()
            self.spans.on_step(step)
            if self.flight is not None:
                self.flight.on_step(step)
            for out in step.output:
                if isinstance(out, (QhbBatch, DhbBatch, HbBatch,
                                    VidQhbBatch)):
                    self._on_batch(out)
                elif isinstance(out, VidCertReady):
                    # proposer-side audit anchor: every retriever's
                    # vid_retrieved note must corroborate this digest
                    if self.flight is not None:
                        self.flight.on_note(
                            "vid_cert",
                            f"root={out.root.hex()} len={out.total_len} "
                            f"payload_sha3={out.payload_sha3}")
                elif isinstance(out, RetrievedPayload):
                    self._on_retrieved(out)
            self._dispatch(step)
        except Exception as exc:
            # fatal in the consensus path: flush the black box so the
            # journal's last records survive whatever happens next
            self.flight_crash(exc)
            raise

    def _dispatch(self, step: Step) -> None:
        """Accumulate the step's outbound payloads into the current pump
        outcome (coalesced + written once per iteration by pump_flush)."""
        out = self._out
        our = self.our_id()
        peer_ids = self.transport.peer_ids()
        all_ids = peer_ids + [our]
        max_payload = self.transport.max_frame - 1
        # The SenderQueue fans a broadcast into one per-peer AlgoMessage
        # wrapping the SAME inner message object; encoding each copy
        # costs the hot path ~3× the bytes it needs.  Cache by inner-
        # object identity — safe because every message in `step` stays
        # referenced for the duration of this call.
        enc_cache: Dict[int, bytes] = {}
        for tm in step.messages:
            msg = tm.message
            if isinstance(msg, AlgoMessage):
                ckey = id(msg.msg)
                payload = enc_cache.get(ckey)
                if payload is None:
                    payload = enc_cache[ckey] = wire.encode_message(msg)
            else:
                payload = wire.encode_message(msg)
            if len(payload) > max_payload:
                # an oversized frame must not abort the rest of the
                # Step's fan-out (the mempool's max_tx_bytes admission
                # bound makes this unreachable for honest configs)
                self.send_failures += 1
                logger.error("dropping oversized frame (%d bytes > cap)",
                             len(payload))
                continue
            key = (
                message_key(tm.message.msg)
                if isinstance(tm.message, AlgoMessage) else None
            )
            shed: FrozenSet[Any] = frozenset()
            if (self._vid and self.vid_shed_backlog_s > 0
                    and isinstance(msg, AlgoMessage)
                    and type(msg.msg) is VidDisperse):
                shed = self._shed_for_disperse(msg.msg.root, peer_ids)
            frames = out.frames
            if self.aba_out_delay_s > 0 and key is not None:
                from hbbft_tpu.obs.spans import classify

                hit = classify(msg.msg)
                if hit is not None and hit[2].startswith("aba_") and (
                    not self.aba_out_classes
                    or hit[2] in self.aba_out_classes
                ):
                    frames = out.frames_delayed
            for dest in tm.target.resolve(all_ids, our):
                if dest in shed and dest != our:
                    # skip replay registration too: a reconnect replay
                    # pushing the shard would defeat the shed entirely
                    self._vid_sheds += 1
                    if self.flight is not None:
                        self.flight.on_note(
                            "vid_shed",
                            f"root={msg.msg.root.hex()} peer={dest!r}")
                    continue
                frames.setdefault(dest, []).append(payload)
                if key is not None:
                    dedup = (key, payload)
                    seen = self._replay_seen.setdefault(dest, set())
                    if dedup not in seen:
                        seen.add(dedup)
                        self._replay.setdefault(dest, []).append(
                            (key, msg.msg, payload)
                        )
                        self._replay_bytes[dest] = (
                            self._replay_bytes.get(dest, 0) + len(payload)
                        )

    def _prune_replay(self) -> None:
        era, epoch = self.current_key()
        if epoch >= self.replay_retain_epochs:
            floor = (era, epoch - self.replay_retain_epochs)
        else:
            # young era: a naive (era, epoch−retain) floor would discard
            # the ENTIRE previous era the instant a DKG rotation lands,
            # breaking replay for a peer whose outage spans the boundary.
            # Keep the previous era's tail (itself already pruned to its
            # last `retain` epochs while that era was current) until this
            # era is `retain` epochs old.
            floor = (era - 1, 0) if era > 0 else (0, 0)
        cap = self.replay_retain_bytes
        for dest, entries in self._replay.items():
            i = 0
            if entries and entries[0][0] < floor:
                # entries are appended in send order (keys non-decreasing
                # modulo reinit merges), so pruning is a front chop —
                # incremental, not a full list+set rebuild per epoch
                n = len(entries)
                while i < n and entries[i][0] < floor:
                    i += 1
            if cap > 0 and self._replay_bytes.get(dest, 0) > cap:
                # byte ceiling (bounded storage): keep chopping the
                # oldest entries past the epoch floor until the peer's
                # log fits — measured AFTER crediting what the epoch
                # floor is already removing, so the cap never truncates
                # more than it must.  Chopped entries are counted — they
                # were still inside epoch retention, so a peer that
                # needed them must recover via snapshot state-sync.
                floor_bytes = sum(len(p) for _k, _m, p in entries[:i])
                over = self._replay_bytes[dest] - floor_bytes - cap
                j = i
                n = len(entries)
                while j < n and over > 0:
                    over -= len(entries[j][2])
                    j += 1
                if j > i:
                    self._c_replay_trunc.inc(j - i)
                    i = j
            if i:
                seen = self._replay_seen.get(dest)
                if seen is not None:
                    for k, _m, p in entries[:i]:
                        seen.discard((k, p))
                self._replay_bytes[dest] = self._replay_bytes.get(
                    dest, 0) - sum(len(p) for _k, _m, p in entries[:i])
                del entries[:i]

    # -- batches & clients ---------------------------------------------------

    def _on_batch(self, batch: Any) -> None:
        self.batches.append(batch)
        self.ledger_digest = hashlib.sha3_256(
            self.ledger_digest + wire.batch_bytes(batch)
        ).digest()
        self._digest_chain.append(self.ledger_digest.hex())
        if len(self._digest_chain) > self.digest_chain_retain:
            drop = len(self._digest_chain) - self.digest_chain_retain
            del self._digest_chain[:drop]
            self._digest_chain_offset += drop
        change = getattr(batch, "change", None)
        if change is not None and change.state == "complete":
            # a DKG rotation just landed: this instant — the new era's
            # boundary, before any of its epochs complete — is the only
            # moment join_plan() is valid.  Package it with the committed
            # DKG transcript and the chain position as the served join
            # snapshot.
            dhb = self._inner_dhb()
            if dhb is not None:
                try:
                    self.sync_store.publish(capture_join_snapshot(
                        dhb, self.ledger_digest, self.chain_len))
                except ValueError as exc:
                    # a replayed future-era message already completed an
                    # epoch of the new era inside this same step — the
                    # boundary passed before we saw it.  Counted: joiners
                    # must wait for the next rotation.
                    self.sync_store._c_capture_misses.inc()
                    logger.warning("join snapshot not captured at era "
                                   "%d boundary: %s", dhb.era, exc)
        if (self.flight_retain_batches > 0 and self.flight is not None
                and self.chain_len % 16 == 0):
            # bounded storage: drop whole journal segments that lie
            # entirely below the digest-chain checkpoint horizon (the
            # chain head + /status cover the truncated history)
            self.flight.recorder.truncate_checkpoint(
                self.chain_len - self.flight_retain_batches)
        if isinstance(batch, QhbBatch):
            txs = batch.all_txs()
            self._c_committed.inc(len(txs))
            digests = self.mempool.mark_committed(txs)
            # client sockets are event-loop objects: the notification is
            # queued on the outcome and written by pump_flush
            self._out.commits.append((batch.era, batch.epoch, digests))
        elif isinstance(batch, VidQhbBatch):
            self._on_vid_batch(batch)
        if self.on_batch is not None:
            self.on_batch(batch)

    # -- VID resolution (pump thread) ----------------------------------------

    def _process_vid_direct(self, peer_id: NodeId, msg: Any) -> None:
        """Route runtime-level retrieval traffic (pump thread)."""
        now = time.time()
        if isinstance(msg, VidRetrieve):
            self._absorb(self._retrieve.handle_retrieve(peer_id, msg, now))
        else:
            self._absorb(self._retrieve.handle_shard(peer_id, msg, now))

    def _on_vid_batch(self, batch: VidQhbBatch) -> None:
        """An epoch ORDERED in VID mode: commit what resolves locally
        (plain contributions, our own dispersals) right now, open a
        retrieval for every foreign commitment.  ``commit`` is the
        ordering instant; each contribution's ``commit_retrieved``
        lands when its payload does — identical timestamps for the
        locally-resolved part, so the two stages always bracket the
        retrieval gap exactly."""
        now = time.time()
        vqhb = self.sq.algo
        ni = vqhb.dhb.netinfo
        txs: List[bytes] = []
        for _proposer, plain in batch.plain_txs():
            txs.extend(plain)
        for proposer, cert in batch.commitments():
            local = vqhb.disperser.local_payload(cert.root)
            if local is not None:
                txs.extend(_de_txs(local))
                if self.flight is not None:
                    self.flight.on_note(
                        "vid_retrieved",
                        f"root={cert.root.hex()} "
                        f"payload_sha3={payload_digest(local)} "
                        f"shards_bad=0 rounds=0")
                continue
            # _vid_pending first: start() can complete synchronously
            # (our own stored shard suffices when k == 1) and the
            # resulting RetrievedPayload resolves through _on_retrieved
            self._vid_pending[cert.root] = (batch.era, batch.epoch)
            # holders in shard-index order: node i stores shard i, so the
            # retrieve service can target exactly the missing indices
            holders = tuple(sorted(ni.all_ids(), key=ni.node_index))
            self._absorb(self._retrieve.start(
                cert.root, cert.total_len, ni.num_nodes(),
                ni.num_faulty(), proposer, now, now, holders=holders))
        if txs:
            self._c_committed.inc(len(txs))
            digests = self.mempool.mark_committed(txs)
            self._out.commits.append((batch.era, batch.epoch, digests))
            self._vid_traces(batch.era, batch.epoch, txs, now, now)

    def _on_retrieved(self, rp: RetrievedPayload) -> None:
        """A retrieval finished (pump thread): surface the audit note,
        and on success commit the transactions against the ordering
        position recorded at batch time."""
        key = self._vid_pending.pop(rp.root, None)
        if key is None:
            return
        era, epoch = key
        now = time.time()
        sha3 = (payload_digest(rp.payload)
                if rp.payload is not None else "none")
        if self.flight is not None:
            self.flight.on_note(
                "vid_retrieved",
                f"root={rp.root.hex()} payload_sha3={sha3} "
                f"shards_bad={rp.shards_bad} rounds={rp.rounds}")
        if rp.payload is None:
            # mismatch / exhaustion: the service already logged the
            # fault evidence; the contribution resolves to nothing on
            # every correct node identically
            return
        try:
            txs = list(_de_txs(rp.payload))
        except ValueError:
            # a valid codeword of a non-contribution payload: the
            # proposer certified garbage — same fault class as a plain
            # contribution that fails to deserialize
            self._absorb(Step.from_fault(
                rp.proposer, FaultKind.BatchDeserializationFailed))
            return
        self.sq.algo.on_retrieved(txs)
        self._c_committed.inc(len(txs))
        digests = self.mempool.mark_committed(txs)
        self._out.commits.append((era, epoch, digests))
        self._vid_traces(era, epoch, txs, rp.t_ordered, now)

    def _vid_traces(self, era: int, epoch: int, txs: List[bytes],
                    t_ordered: float, t_resolved: float) -> None:
        """Journal the commit / commit_retrieved stage pair: ``commit``
        carries the ordering timestamp, ``commit_retrieved`` the moment
        the payload became readable, so per-tx waterfalls report both
        latencies and their difference is exactly the retrieval gap."""
        if self.flight is None or not txs:
            return
        tids = b"".join(trace_id(bytes(tx)) for tx in txs)
        self.flight.recorder.record_trace("commit", era, epoch, tids,
                                          t=t_ordered)
        self.flight.recorder.record_trace("commit_retrieved", era, epoch,
                                          tids, t=t_resolved)

    def _notify_commit(self, era: int, epoch: int,
                       digests: List[bytes]) -> None:
        if not self._clients or not digests:
            return
        payload = struct.pack(">QQI", era, epoch, len(digests)) + b"".join(
            digests
        )
        for conn in list(self._clients):
            conn.send(framing.TX_COMMIT, payload)
            if conn.closed:
                self._clients.discard(conn)

    def _on_client_frame(self, conn: ClientConn, kind: int,
                         payload: bytes) -> None:
        if kind == framing.SYNC:
            # snapshot state-sync (joiner bootstrap): request → reply on
            # this connection, WITHOUT registering it for commit pushes —
            # a transferring joiner wants chunks, not TX_COMMIT noise
            try:
                msg = wire.decode_message(payload)
            except ValueError as exc:
                from hbbft_tpu.net.statesync import SyncNack

                self.sync_store._c_nacks.inc()
                logger.warning("undecodable sync request: %s", exc)
                conn.send(framing.SYNC,
                          wire.encode_message(SyncNack("bad request")))
                return
            conn.send(framing.SYNC,
                      wire.encode_message(self.sync_store.handle(msg)))
            return
        self._clients.add(conn)
        if kind == framing.TX:
            # admission (bounded, dedup'd, FAIR per client under FULL
            # pressure) and the ack stay on the event loop — backpressure
            # must not wait behind a pump iteration; only the accepted
            # input crosses into the pump
            t_ingress = time.time()
            status = self.mempool.add(payload,
                                      client_id=str(conn.client_id))
            conn.send(framing.TX_ACK, bytes([status]) + tx_digest(payload))
            if status == Mempool.ACCEPTED:
                self.pump.enqueue("input", self.make_tx_input(payload),
                                  t_ingress, str(conn.client_id))
        elif kind == framing.STATUS_REQ:
            # optional u32 payload: digest-chain tail length (0 = just the
            # head/length — the cheap poll loops use this; the full
            # 256-entry default costs ~16 KB of JSON per request)
            tail = 256
            if len(payload) == 4:
                tail = struct.unpack(">I", payload)[0]
            conn.send(framing.STATUS,
                      json.dumps(self.status_doc(chain_tail=tail)).encode())
        else:
            logger.warning("unknown client frame kind %d", kind)

    def _on_client_gone(self, conn: ClientConn) -> None:
        self._clients.discard(conn)

    def status_doc(self, chain_tail: int = 256) -> dict:
        era, epoch = self.current_key()
        local = max(0, len(self._digest_chain) - chain_tail)
        return {
            "node": repr(self.our_id()),
            "era": era,
            "epoch": epoch,
            "batches": len(self.batches),
            "ledger": self.ledger_digest.hex(),
            # chain head + total length: what the forensic auditor
            # cross-checks against a live node without the full journal
            "chain_head": self.ledger_digest.hex(),
            "chain_len": self.chain_len,
            "digest_chain": self._digest_chain[local:],
            "digest_chain_offset": self._digest_chain_offset + local,
            "flight": (self.flight.recorder.stats_doc()
                       if self.flight is not None else None),
            "committed_txs": self.committed_txs,
            "mempool": len(self.mempool),
            "decode_failures": self.decode_failures,
            "send_failures": self.send_failures,
            "replay_gaps": self.replay_gaps,
            "replay_truncations": int(self._c_replay_trunc.total()),
            "replay_log_bytes": sum(self._replay_bytes.values()),
            "sync_snapshot": (
                {
                    "era": self.sync_store.manifest.era,
                    "chain_len": self.sync_store.manifest.chain_len,
                    "image_len": self.sync_store.manifest.image_len,
                }
                if self.sync_store.manifest is not None else None
            ),
            "guard": {
                "ingress": self.transport.ingress.as_dict(),
                "senderq_evictions": int(self._c_sq_evict.total()),
                "senderq_buffered": {
                    repr(p): len(e)
                    for p, e in list(self.sq.buffered.items())
                },
                "protocol_drops": {
                    "hb_future": int(self._c_proto_drops.value(
                        kind="hb_future")),
                    "subset": int(self._c_proto_drops.value(
                        kind="subset")),
                },
                "mempool_sheds": dict(self.mempool.sheds),
            },
            "degraded": (self.degrade.as_dict()
                         if self.degrade is not None else None),
            # the perf plane's compact view: the single headroom scalar
            # plus per-layer utilization (full doc at /perf)
            "perf": self.perf.summary(),
            "headroom": self.perf.headroom(),
            "vid": (
                {
                    "pending_retrievals": self._retrieve.pending_count(),
                    "store_bytes": self.sq.algo.store.bytes,
                    "store_roots": len(self.sq.algo.store),
                    **self._vid_stats(),
                }
                if self._vid else None
            ),
            "faults_observed": self.faults_observed,
            "peers_connected": sum(
                1 for p in self.transport.peer_ids()
                if self.transport.connected(p)
            ),
            "epochs_traced": self.spans.epochs_finalized,
            "pipeline_depth": self.pipeline_depth,
            "epochs_in_flight": (
                len(self._inner_hb().epochs)
                if self._inner_hb() is not None else 0
            ),
            "obs_addr": list(self.obs_addr) if self.obs_addr else None,
            "stats": self.transport.stats.as_dict(),
        }

    def health_doc(self) -> dict:
        """The ``/health`` document: machine-readable status + headroom.

        Shaped for the adaptive-control ladder (ROADMAP 5(b)): every
        lever the controller could pull is reported as used/cap/frac so
        "how much room is left" needs no endpoint-specific knowledge.
        Read-only snapshot — safe from the obs HTTP thread; the
        journaled transition record is the pump heartbeat's job
        (:meth:`_health_tick`)."""
        era, epoch = self.current_key()
        issues = self._health_issues()

        def lever(used: int, cap: int) -> dict:
            return {"used": used, "cap": cap,
                    "frac": round(used / cap, 4) if cap else 0.0}

        mp = self.mempool
        hb = self._inner_hb()
        return {
            "node": repr(self.our_id()),
            "status": "degraded" if issues else "ok",
            "issues": issues,
            "transitions": self._health_transitions,
            "era": era,
            "epoch": epoch,
            "chain_len": self.chain_len,
            "headroom": {
                "mempool": lever(len(mp), mp.capacity),
                "mempool_bytes": lever(mp.pending_bytes,
                                       mp.max_pending_bytes),
                "pipeline": lever(
                    len(hb.epochs) if hb is not None else 0,
                    self.pipeline_depth),
                # the pump drains max_batch events per iteration: a
                # backlog persistently above it means the node is
                # processing-bound, not waiting for traffic
                "pump_backlog": lever(self.pump.pending(),
                                      self.pump.max_batch),
                "vid_pending": (self._retrieve.pending_count()
                                if self._retrieve is not None else 0),
            },
            # the perf plane's measured slack scalar (None before the
            # first complete sampling window) + per-layer utilization —
            # what the controller's raise arm actually consumes
            "perf_headroom": self.perf.headroom(),
            "util": self.perf.utilization(),
            "degrade": (self.degrade.as_dict()
                        if self.degrade is not None else None),
            "guard": {
                "senderq_evictions": int(self._c_sq_evict.total()),
                "mempool_sheds": sum(self.mempool.sheds.values()),
            },
            "peers_connected": sum(
                1 for p in self.transport.peer_ids()
                if self.transport.connected(p)
            ),
            "send_failures": self.send_failures,
        }
